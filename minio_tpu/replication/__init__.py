"""Bucket replication: rules, async replication workers, resync.

The product tier the reference builds in cmd/bucket-replication.go +
internal/bucket/replication: a bucket carries a replication
configuration (rules with prefix filters and delete-marker handling)
and a remote target (another S3 cluster + bucket); writes replicate
asynchronously with a PENDING -> COMPLETED/FAILED status recorded on
the source version, and the scanner re-queues anything left behind.
"""

from minio_tpu.replication.engine import (ReplicationEngine,
                                          ReplicationError,
                                          parse_replication_xml,
                                          REPL_STATUS_KEY)

__all__ = ["ReplicationEngine", "ReplicationError",
           "parse_replication_xml", "REPL_STATUS_KEY"]
