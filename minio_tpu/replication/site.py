"""Site replication: active-active mirroring across clusters.

The analogue of the reference's site replication
(cmd/site-replication.go): a set of peer sites is registered once;
from then on bucket creations/deletions, the complete bucket-metadata
document (policy, versioning, lifecycle, object-lock, tagging, ...),
and object writes/deletes mirror to every peer automatically —
active-active, with replica markers breaking the ping-pong loop
(a change received FROM a site never re-replicates back out).

Scope: buckets + bucket metadata + objects + delete markers + IAM
(users, service accounts, named policies, policy attachments, groups —
the durable identity state; STS temp credentials stay local, reference
cmd/site-replication.go mirrors the same set). SSE-encrypted objects do
not replicate (their keys bind to one cluster, same as bucket
replication v1). Registering sites bootstraps existing buckets, their
metadata, and the IAM document to the peers; existing OBJECTS are not
backfilled (run a batch replicate job per bucket for that).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Optional

from minio_tpu.storage.local import SYS_VOL

SITE_PATH = "config/site-replication.json"

# Header a site-replicated BUCKET operation carries so the receiving
# site applies it without re-replicating (objects reuse the existing
# x-amz-meta-mtpu-replica marker).
H_SITE_REPLICA = "x-mtpu-site-replica"


class SiteError(Exception):
    pass


def load_config(sets) -> Optional[dict]:
    votes: dict[bytes, int] = {}
    for es in sets:
        for d in es.disks:
            try:
                blob = d.read_all(SYS_VOL, SITE_PATH)
                votes[blob] = votes.get(blob, 0) + 1
            except Exception:  # noqa: BLE001 - absent / offline
                continue
    if not votes:
        return None
    try:
        doc = json.loads(max(votes.items(), key=lambda kv: kv[1])[0])
        return doc if isinstance(doc, dict) and doc.get("peers") else None
    except ValueError:
        return None


def hook_iam_changes(server) -> None:
    """Install (once per server) an IAM on_change hook that mirrors the
    identity document to peer sites whenever a replicator is armed.
    Chained AFTER any existing hook (the intra-cluster peer broadcast),
    and a no-op while no site is configured — so arming later via the
    admin API needs no rewiring."""
    iam = getattr(server.credentials, "iam", None)
    if iam is None or getattr(server, "_site_iam_hooked", False):
        return
    server._site_iam_hooked = True
    prev = iam.on_mirror_change

    def changed():
        if prev is not None:
            prev()
        site = server.site
        if site is not None and site.iam is not None:
            site.enqueue("iam", "")

    # The MIRROR hook, not on_change: STS credential mints fire the
    # latter constantly and must not push the document across sites.
    iam.on_mirror_change = changed


class SiteReplicator:
    """Fan-out worker mirroring changes to every peer site."""

    _RETRIES = 3

    def __init__(self, object_layer, sets, config: dict,
                 workers: int = 2, iam=None):
        self.layer = object_layer
        self._sets = list(sets)
        self.iam = iam                 # IAMSys to mirror (None = skip)
        self.config = dict(config)
        self._q: queue.Queue = queue.Queue(maxsize=10_000)
        self._stop = threading.Event()
        self.queued = self.completed = self.failed = 0
        # Items between enqueue and terminal outcome — retries parked
        # on the timer heap are NOT in self._q, so drain must not key
        # off unfinished_tasks.
        self._outstanding = 0
        self._omu = threading.Lock()
        from minio_tpu.replication.engine import RetryTimer
        self._timer = RetryTimer(name="site-repl-timer")
        self._threads = [threading.Thread(target=self._run, daemon=True,
                                          name=f"site-repl-{i}")
                         for i in range(workers)]
        for t in self._threads:
            t.start()

    # -- config ----------------------------------------------------------

    @staticmethod
    def validate(config: dict) -> dict:
        peers = config.get("peers") or []
        if not peers:
            raise SiteError("at least one peer site required")
        names = set()
        for p in peers:
            for f in ("name", "endpoint", "accessKey", "secretKey"):
                if not p.get(f):
                    raise SiteError(f"peer missing {f!r}")
            if p["name"] in names:
                raise SiteError(f"duplicate peer name {p['name']!r}")
            names.add(p["name"])
        return config

    def save(self) -> None:
        blob = json.dumps(self.config, sort_keys=True).encode()
        disks = [d for es in self._sets for d in es.disks]
        ok = 0
        for d in disks:
            try:
                d.write_all(SYS_VOL, SITE_PATH, blob)
                ok += 1
            except Exception:  # noqa: BLE001 - offline drive
                continue
        if ok < len(disks) // 2 + 1:
            raise SiteError("could not persist site config to a quorum")

    def info(self) -> dict:
        peers = []
        for p in self.config.get("peers", []):
            q = dict(p)
            q.pop("secretKey", None)      # never echo credentials
            peers.append(q)
        return {"name": self.config.get("name", ""), "peers": peers,
                "queued": self.queued, "completed": self.completed,
                "failed": self.failed}

    def _clients(self):
        from minio_tpu.s3.client import RemoteS3
        for p in self.config.get("peers", []):
            yield p["name"], RemoteS3(p["endpoint"], p["accessKey"],
                                      p["secretKey"])

    # -- ingestion -------------------------------------------------------

    def enqueue(self, kind: str, bucket: str, key: str = "",
                version_id: str = "") -> None:
        try:
            # The trailing set tracks which peers already received this
            # change — retries only touch the peers that failed
            # (re-delivering to a versioned peer would stack duplicate
            # versions per retry).
            self._q.put_nowait((kind, bucket, key, version_id, 0, set()))
            self.queued += 1
            with self._omu:
                self._outstanding += 1
        except queue.Full:
            self.failed += 1

    def bootstrap(self) -> None:
        """One-time sync at registration: existing buckets and their
        metadata documents reach every peer (objects are not
        backfilled — the reference offers resync separately)."""
        for b in self.layer.list_buckets():
            self.enqueue("bucket-make", b.name)
            self.enqueue("bucket-meta", b.name)
        if self.iam is not None:
            self.enqueue("iam", "")

    def drain(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._omu:
                if self._outstanding == 0:
                    return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        self._stop.set()
        self._timer.stop()
        for t in self._threads:
            t.join(timeout=5)

    # -- delivery --------------------------------------------------------

    def _deliver(self, kind: str, bucket: str, key: str,
                 version_id: str, done: set) -> None:
        """Fan one change out to every peer NOT already in `done`,
        recording successes there — a retry must only touch the peers
        that failed (re-delivering to a versioned peer would stack a
        duplicate version or delete marker per attempt) and must still
        reach peers listed after an earlier failure."""
        failures = []
        for name, client in self._clients():
            if name in done:
                continue
            try:
                self._deliver_one(kind, bucket, key, version_id, name,
                                  client)
                done.add(name)
            except Exception as e:  # noqa: BLE001 - recorded per peer
                failures.append(f"{name}: {e}")
        if failures:
            raise SiteError("; ".join(failures))

    def _deliver_one(self, kind, bucket, key, version_id, name,
                     client) -> None:
        if kind == "bucket-make":
            st, _, _ = client.request(
                "PUT", f"/{bucket}", headers={H_SITE_REPLICA: "true"})
            if st not in (200, 409):   # exists on peer: converged
                raise SiteError(f"mkbucket HTTP {st}")
        elif kind == "bucket-delete":
            st, _, _ = client.request(
                "DELETE", f"/{bucket}", headers={H_SITE_REPLICA: "true"})
            if st not in (204, 404):
                raise SiteError(f"rmbucket HTTP {st}")
        elif kind == "bucket-meta":
            meta = self.layer.get_bucket_meta(bucket)
            st, _, _ = client.request(
                "PUT", "/minio/admin/v3/site-import-bucket-meta",
                query={"bucket": bucket},
                body=json.dumps(meta).encode())
            if st != 200:
                raise SiteError(f"meta import HTTP {st}")
        elif kind == "iam":
            if self.iam is None:
                return
            st, _, _ = client.request(
                "PUT", "/minio/admin/v3/site-import-iam",
                body=json.dumps(self.iam.export_doc()).encode())
            if st != 200:
                raise SiteError(f"iam import HTTP {st}")
        elif kind == "put":
            from minio_tpu.replication.common import push_object
            push_object(self.layer, client, bucket, key, version_id,
                        bucket, skip_sse=True)
        elif kind == "delete":
            # The replica marker rides the delete too — without it the
            # receiving site mirrors the delete back and the pair
            # ping-pongs forever (stacking a new delete marker per
            # bounce on versioned buckets).
            st, _, _ = client.request(
                "DELETE", f"/{bucket}/{key}",
                headers={H_SITE_REPLICA: "true"})
            if st not in (200, 204, 404):
                raise SiteError(f"delete HTTP {st}")

    def _requeue(self, item) -> None:
        try:
            self._q.put_nowait(item)
        except queue.Full:
            self.failed += 1
            with self._omu:
                self._outstanding -= 1

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                kind, bucket, key, vid, attempt, done = \
                    self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._deliver(kind, bucket, key, vid, done)
                self.completed += 1
            except Exception:  # noqa: BLE001 - retry then count failed
                if attempt + 1 < self._RETRIES and not self._stop.is_set():
                    # Backoff rides the shared timer heap, never this
                    # worker: a dead peer must not head-of-line block
                    # deliveries to the live ones.
                    item = (kind, bucket, key, vid, attempt + 1, done)
                    self._timer.call_later(
                        min(0.2 * 2 ** attempt, 5.0),
                        lambda it=item: self._requeue(it))
                    self._q.task_done()
                    continue
                self.failed += 1
            with self._omu:
                self._outstanding -= 1
            self._q.task_done()
