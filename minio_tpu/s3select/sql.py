"""The S3 Select SQL subset: tokenizer + recursive-descent parser +
row evaluator.

Grammar (case-insensitive keywords):

    select   := SELECT projection FROM from_clause [WHERE expr] [LIMIT n]
    projection := '*' | COUNT '(' '*' ')' | item (',' item)*
    item     := column [AS? ident]
    column   := ident ('.' ident)* | S3Object-qualified ref
    expr     := or_expr
    or_expr  := and_expr (OR and_expr)*
    and_expr := not_expr (AND not_expr)*
    not_expr := NOT not_expr | cmp
    cmp      := operand (op operand | IS [NOT] NULL)?
    op       := = | != | <> | < | <= | > | >=
    operand  := literal | column | '(' expr ')'

Values compare numerically when both sides parse as numbers, else as
strings (the reference's dynamic typing for CSV input).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional


class SQLError(Exception):
    pass


_TOKEN_RE = re.compile(r"""
    \s*(?:
        (?P<string>'(?:[^']|'')*')
      | (?P<number>-?\d+(?:\.\d+)?)
      | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
      | (?P<op><=|>=|!=|<>|=|<|>)
      | (?P<punct>[(),.*])
    )""", re.VERBOSE)

_KEYWORDS = {"select", "from", "where", "limit", "and", "or", "not",
             "as", "is", "null", "count", "sum", "avg", "min", "max",
             "cast", "like", "escape"}

_AGG_FUNCS = ("count", "sum", "avg", "min", "max")


def _tokenize(text: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise SQLError(f"bad token at {text[pos:pos + 20]!r}")
        pos = m.end()
        kind = m.lastgroup
        val = m.group(kind)
        if kind == "ident" and val.lower() in _KEYWORDS:
            out.append(("kw", val.lower()))
        elif kind == "string":
            out.append(("string", val[1:-1].replace("''", "'")))
        else:
            out.append((kind, val))
    return out


# -- AST --------------------------------------------------------------------

@dataclasses.dataclass
class Col:
    name: str
    parts: list = dataclasses.field(default_factory=list)

    def eval(self, row: dict):
        return row.get(self.name)


@dataclasses.dataclass
class Lit:
    value: object

    def eval(self, row: dict):
        return self.value


@dataclasses.dataclass
class Cmp:
    op: str
    left: object
    right: object

    def eval(self, row: dict):
        """SQL three-valued logic: a comparison with a NULL/missing
        operand is NULL (None), not False — NOT must not flip it to
        True."""
        a, b = self.left.eval(row), self.right.eval(row)
        if a is None or b is None:
            return None
        fa, fb = _as_number(a), _as_number(b)
        if fa is not None and fb is not None:
            a, b = fa, fb
        else:
            a, b = str(a), str(b)
        return {"=": a == b, "!=": a != b, "<>": a != b, "<": a < b,
                "<=": a <= b, ">": a > b, ">=": a >= b}[self.op]


@dataclasses.dataclass
class IsNull:
    operand: object
    negate: bool

    def eval(self, row: dict) -> bool:
        missing = self.operand.eval(row) is None
        return not missing if self.negate else missing


@dataclasses.dataclass
class Logical:
    op: str
    terms: list

    def eval(self, row: dict):
        vals = [t.eval(row) for t in self.terms]
        if self.op == "and":
            if any(v is False for v in vals):
                return False
            return None if any(v is None for v in vals) else True
        if any(v is True for v in vals):
            return True
        return None if any(v is None for v in vals) else False


@dataclasses.dataclass
class Not:
    term: object

    def eval(self, row: dict):
        v = self.term.eval(row)
        return None if v is None else not v


@dataclasses.dataclass
class Cast:
    """CAST(expr AS type) — the reference's sql.FuncCast family
    (internal/s3select/sql/parser.go:23 territory)."""
    expr: object
    type: str

    def eval(self, row: dict):
        v = self.expr.eval(row)
        if v is None:
            return None
        t = self.type
        try:
            if t in ("int", "integer"):
                return int(float(v))
            if t in ("float", "double", "decimal", "numeric"):
                return float(v)
            if t in ("string", "varchar", "char"):
                return str(v)
            if t in ("bool", "boolean"):
                if isinstance(v, bool):
                    return v
                s = str(v).strip().lower()
                if s in ("true", "1"):
                    return True
                if s in ("false", "0"):
                    return False
                raise ValueError(s)
        except (TypeError, ValueError):
            raise SQLError(
                f"cannot cast {v!r} to {t}") from None
        raise SQLError(f"unsupported CAST type {t!r}")


@dataclasses.dataclass
class Like:
    """operand [NOT] LIKE pattern [ESCAPE c] — SQL wildcard match
    (% = any run, _ = any one char)."""
    operand: object
    pattern: object
    escape: str = ""
    negate: bool = False

    def _regex(self, pat: str):
        esc = self.escape
        out = []
        i = 0
        while i < len(pat):
            c = pat[i]
            if esc and c == esc and i + 1 < len(pat):
                out.append(re.escape(pat[i + 1]))
                i += 2
                continue
            if c == "%":
                out.append(".*")
            elif c == "_":
                out.append(".")
            else:
                out.append(re.escape(c))
            i += 1
        return re.compile("".join(out) + r"\Z", re.DOTALL)

    def eval(self, row: dict):
        v = self.operand.eval(row)
        p = self.pattern.eval(row)
        if v is None or p is None:
            return None
        hit = self._regex(str(p)).match(str(v)) is not None
        return (not hit) if self.negate else hit


@dataclasses.dataclass
class Agg:
    """One aggregate projection item (COUNT/SUM/AVG/MIN/MAX); the
    engine accumulates across rows and emits one result row."""
    func: str
    operand: Optional[object]      # None = '*' (COUNT only)
    alias: str


@dataclasses.dataclass
class Query:
    columns: Optional[list]        # [(expr, alias)] or None for '*'
    aggregates: Optional[list]     # [Agg] — exclusive with columns
    where: Optional[object]
    limit: Optional[int]



def _as_number(v) -> Optional[float]:
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(str(v))
    except (TypeError, ValueError):
        return None


class _Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.pos = 0
        self._cols: list[Col] = []
        self._aliases = {"s3object"}

    def peek(self):
        return self.toks[self.pos] if self.pos < len(self.toks) else ("eof", "")

    def next(self):
        t = self.peek()
        self.pos += 1
        return t

    def expect(self, kind, val=None):
        t = self.next()
        if t[0] != kind or (val is not None and t[1].lower() != val):
            raise SQLError(f"expected {val or kind}, got {t[1]!r}")
        return t

    # -- clauses --------------------------------------------------------

    def parse(self) -> Query:
        self.expect("kw", "select")
        columns, aggregates = self._projection()
        self.expect("kw", "from")
        self._from()
        where = None
        limit = None
        if self.peek() == ("kw", "where"):
            self.next()
            where = self._expr()
        if self.peek() == ("kw", "limit"):
            self.next()
            t = self.expect("number")
            limit = int(float(t[1]))
            if limit < 0 or limit != float(t[1]):
                raise SQLError(f"LIMIT must be a non-negative integer, "
                               f"got {t[1]}")
        if self.peek()[0] != "eof":
            raise SQLError(f"unexpected trailing {self.peek()[1]!r}")
        # Resolve qualified references now that the FROM alias is known:
        # a prefix must be the table (or its alias); anything else (or
        # nested paths) is unsupported, never silently misread.
        for col in self._cols:
            parts = col.parts
            if len(parts) == 1:
                col.name = parts[0]
            elif len(parts) == 2 and parts[0].lower() in self._aliases:
                col.name = parts[1]
            else:
                raise SQLError("unsupported column reference "
                               f"{'.'.join(parts)!r}")
        return Query(columns=columns, aggregates=aggregates,
                     where=where, limit=limit)

    def _projection(self):
        """Returns (columns, aggregates) — exactly one is non-None
        unless '*' (both None). Mixing aggregates with plain columns is
        rejected (no GROUP BY in the S3 Select subset, matching the
        reference)."""
        if self.peek() == ("punct", "*"):
            self.next()
            return None, None
        cols = []
        aggs = []
        idx = 0
        while True:
            idx += 1
            t = self.peek()
            if t[0] == "kw" and t[1] in _AGG_FUNCS:
                func = self.next()[1]
                self.expect("punct", "(")
                if self.peek() == ("punct", "*"):
                    if func != "count":
                        raise SQLError(f"{func.upper()}(*) is not valid")
                    self.next()
                    operand = None
                else:
                    operand = self._value_expr()
                self.expect("punct", ")")
                alias = f"_{idx}"
                if self.peek() == ("kw", "as"):
                    self.next()
                    alias = self.expect("ident")[1]
                aggs.append(Agg(func, operand, alias))
            else:
                expr = self._value_expr()
                alias = expr.name if isinstance(expr, Col) else f"_{idx}"
                if self.peek() == ("kw", "as"):
                    self.next()
                    alias = self.expect("ident")[1]
                elif self.peek()[0] == "ident":
                    alias = self.next()[1]
                cols.append((expr, alias))
            if self.peek() == ("punct", ","):
                self.next()
                continue
            break
        if aggs and cols:
            raise SQLError("cannot mix aggregates with plain columns "
                           "(no GROUP BY)")
        if aggs:
            return None, aggs
        return cols, None

    def _value_expr(self):
        """A projection/operand value: column, literal, or CAST."""
        t = self.peek()
        if t == ("kw", "cast"):
            self.next()
            self.expect("punct", "(")
            inner = self._value_expr()
            self.expect("kw", "as")
            ty = self.next()
            if ty[0] not in ("ident", "kw"):
                raise SQLError(f"expected type name, got {ty[1]!r}")
            self.expect("punct", ")")
            return Cast(inner, ty[1].lower())
        if t[0] == "string":
            self.next()
            return Lit(t[1])
        if t[0] == "number":
            self.next()
            return Lit(float(t[1]))
        if t[0] == "ident":
            return self._column()
        raise SQLError(f"unexpected {t[1]!r}")

    def _from(self):
        # FROM S3Object[.path][ alias] — the alias becomes a valid
        # column qualifier.
        t = self.next()
        if t[0] != "ident" or t[1].lower() not in ("s3object",):
            raise SQLError("FROM must reference S3Object")
        while self.peek() == ("punct", "."):
            self.next()
            self.next()
        if self.peek()[0] == "ident":
            self._aliases.add(self.next()[1].lower())

    def _column(self) -> Col:
        t = self.next()
        if t[0] != "ident":
            raise SQLError(f"expected column, got {t[1]!r}")
        parts = [t[1]]
        while self.peek() == ("punct", "."):
            self.next()
            parts.append(self.expect("ident")[1])
        col = Col(parts[-1], parts)
        self._cols.append(col)
        return col

    # -- expressions ----------------------------------------------------

    def _expr(self):
        return self._or()

    def _or(self):
        terms = [self._and()]
        while self.peek() == ("kw", "or"):
            self.next()
            terms.append(self._and())
        return terms[0] if len(terms) == 1 else Logical("or", terms)

    def _and(self):
        terms = [self._not()]
        while self.peek() == ("kw", "and"):
            self.next()
            terms.append(self._not())
        return terms[0] if len(terms) == 1 else Logical("and", terms)

    def _not(self):
        if self.peek() == ("kw", "not"):
            self.next()
            return Not(self._not())
        return self._cmp()

    def _cmp(self):
        left = self._operand()
        t = self.peek()
        if t == ("kw", "is"):
            self.next()
            negate = False
            if self.peek() == ("kw", "not"):
                self.next()
                negate = True
            self.expect("kw", "null")
            return IsNull(left, negate)
        if t == ("kw", "not") and self.pos + 1 < len(self.toks) and \
                self.toks[self.pos + 1] == ("kw", "like"):
            self.next()
            return self._like(left, negate=True)
        if t == ("kw", "like"):
            return self._like(left, negate=False)
        if t[0] == "op":
            op = self.next()[1]
            right = self._operand()
            return Cmp(op, left, right)
        return left

    def _like(self, left, negate: bool):
        self.expect("kw", "like")
        pattern = self._operand()
        escape = ""
        if self.peek() == ("kw", "escape"):
            self.next()
            e = self.expect("string")[1]
            if len(e) != 1:
                raise SQLError("ESCAPE must be a single character")
            escape = e
        return Like(left, pattern, escape=escape, negate=negate)

    def _operand(self):
        t = self.peek()
        if t == ("punct", "("):
            self.next()
            e = self._expr()
            self.expect("punct", ")")
            return e
        return self._value_expr()


def parse_select(sql: str) -> Query:
    return _Parser(_tokenize(sql)).parse()
