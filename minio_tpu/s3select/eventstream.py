"""AWS event-stream binary framing for Select responses.

Message = prelude(total_len u32BE, headers_len u32BE, prelude_crc u32BE)
          headers payload message_crc(u32BE over everything prior).
Header  = name_len u8, name, type u8 (7 = string), value_len u16BE,
          value. (reference: the aws eventstream codec the SDKs speak;
          internal/s3select/message.go writes the same frames.)
"""

from __future__ import annotations

import struct
import zlib


def _header(name: str, value: str) -> bytes:
    nb, vb = name.encode(), value.encode()
    return bytes([len(nb)]) + nb + b"\x07" + struct.pack(">H", len(vb)) + vb


def encode_message(headers: dict[str, str], payload: bytes = b"") -> bytes:
    hblob = b"".join(_header(k, v) for k, v in headers.items())
    total = 12 + len(hblob) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hblob))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hblob + payload
    return body + struct.pack(">I", zlib.crc32(body))


def records_message(payload: bytes) -> bytes:
    return encode_message({":message-type": "event",
                           ":event-type": "Records",
                           ":content-type": "application/octet-stream"},
                          payload)


def stats_message(scanned: int, processed: int, returned: int) -> bytes:
    xml = (f"<Stats><BytesScanned>{scanned}</BytesScanned>"
           f"<BytesProcessed>{processed}</BytesProcessed>"
           f"<BytesReturned>{returned}</BytesReturned></Stats>").encode()
    return encode_message({":message-type": "event",
                           ":event-type": "Stats",
                           ":content-type": "text/xml"}, xml)


def end_message() -> bytes:
    return encode_message({":message-type": "event",
                           ":event-type": "End"})


def decode_messages(blob: bytes):
    """Parse a concatenated event-stream back into (headers, payload)
    pairs — the test-side decoder."""
    out = []
    pos = 0
    while pos < len(blob):
        total, hlen = struct.unpack_from(">II", blob, pos)
        hdr_start = pos + 12
        headers = {}
        hpos = hdr_start
        while hpos < hdr_start + hlen:
            nlen = blob[hpos]
            name = blob[hpos + 1:hpos + 1 + nlen].decode()
            hpos += 1 + nlen + 1                 # + type byte
            vlen = struct.unpack_from(">H", blob, hpos)[0]
            headers[name] = blob[hpos + 2:hpos + 2 + vlen].decode()
            hpos += 2 + vlen
        payload = blob[hdr_start + hlen:pos + total - 4]
        out.append((headers, payload))
        pos += total
    return out
