"""S3 Select: SQL queries over CSV / JSON objects.

The subset analogue of the reference's internal/s3select/: a
SelectObjectContentRequest (POST ?select&select-type=2) runs a SQL
expression against one object's records and streams matching rows back
in the AWS event-stream envelope. Supported: SELECT column projections
(including *, aliases, and COUNT(*)), FROM S3Object, WHERE with
comparison/AND/OR/NOT/parentheses and IS [NOT] NULL, LIMIT; CSV input
(header or positional _N columns, custom delimiters) and JSON-lines
input; CSV or JSON output.
"""

from minio_tpu.s3select.engine import SelectError, run_select
from minio_tpu.s3select.eventstream import encode_message

__all__ = ["SelectError", "run_select", "encode_message"]
