"""Select execution: input deserialization, query evaluation, output
serialization, event-stream assembly."""

from __future__ import annotations

import csv
import io
import json
import xml.etree.ElementTree as ET

from minio_tpu.s3select import eventstream
from minio_tpu.s3select.sql import SQLError, parse_select

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
_NS = f"{{{XMLNS}}}"


class SelectError(Exception):
    pass


def _strip_ns(root):
    for el in root.iter():
        if isinstance(el.tag, str) and "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def parse_select_request(body: bytes) -> dict:
    """SelectObjectContentRequest XML -> {expression, input, output}."""
    try:
        root = _strip_ns(ET.fromstring(body))
    except ET.ParseError as e:
        raise SelectError(f"malformed request: {e}") from None
    expr = root.findtext("Expression") or ""
    etype = (root.findtext("ExpressionType") or "SQL").upper()
    if etype != "SQL" or not expr:
        raise SelectError("ExpressionType must be SQL with an Expression")
    req = {"expression": expr, "input": {}, "output": {}}
    inp = root.find("InputSerialization")
    if inp is None:
        raise SelectError("missing InputSerialization")
    csv_in = inp.find("CSV")
    json_in = inp.find("JSON")
    if csv_in is not None:
        req["input"] = {
            "format": "csv",
            "header": (csv_in.findtext("FileHeaderInfo") or "NONE").upper(),
            "delimiter": csv_in.findtext("FieldDelimiter") or ",",
            "quote": csv_in.findtext("QuoteCharacter") or '"',
        }
    elif json_in is not None:
        req["input"] = {"format": "json"}
    else:
        raise SelectError("InputSerialization needs CSV or JSON")
    out = root.find("OutputSerialization")
    fmt = "csv" if req["input"]["format"] == "csv" else "json"
    delim = ","
    if out is not None:
        if out.find("JSON") is not None:
            fmt = "json"
        elif out.find("CSV") is not None:
            fmt = "csv"
            delim = out.find("CSV").findtext("FieldDelimiter") or ","
    req["output"] = {"format": fmt, "delimiter": delim}
    return req


def _iter_csv(data: bytes, opts: dict):
    text = io.StringIO(data.decode("utf-8", "replace"))
    reader = csv.reader(text, delimiter=opts.get("delimiter", ","),
                        quotechar=opts.get("quote", '"'))
    header_mode = opts.get("header", "NONE")
    headers = None
    header_pending = header_mode in ("USE", "IGNORE")
    while True:
        try:
            fields = next(reader)
        except StopIteration:
            return
        except csv.Error as e:
            raise SelectError(f"malformed CSV record: {e}") from None
        if not fields:
            continue
        if header_pending:
            # First NON-EMPTY row is the header (blank leading lines
            # must not demote it to data).
            header_pending = False
            if header_mode == "USE":
                headers = fields
            continue
        if headers is not None:
            row = dict(zip(headers, fields))
        else:
            row = {f"_{j + 1}": v for j, v in enumerate(fields)}
        yield row


def _iter_json(data: bytes):
    for line in data.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            raise SelectError("malformed JSON record") from None
        if not isinstance(rec, dict):
            raise SelectError("JSON record is not an object")
        yield rec


def _project(query, row: dict) -> dict:
    if query.columns is None:
        return row
    return {alias: col.eval(row) for col, alias in query.columns}


def _serialize(rows: list, out_opts: dict, field_order) -> bytes:
    if out_opts["format"] == "json":
        return b"".join(json.dumps(r, default=str).encode() + b"\n"
                        for r in rows)
    buf = io.StringIO()
    w = csv.writer(buf, delimiter=out_opts.get("delimiter", ","),
                   lineterminator="\n")
    for r in rows:
        order = field_order or list(r)
        w.writerow(["" if r.get(k) is None else r.get(k) for k in order])
    return buf.getvalue().encode()


def run_select(body: bytes, request_xml: bytes) -> bytes:
    """Execute a Select request against object bytes; returns the full
    event-stream response (Records + Stats + End)."""
    req = parse_select_request(request_xml)
    try:
        query = parse_select(req["expression"])
    except SQLError as e:
        raise SelectError(str(e)) from None

    rows_iter = _iter_csv(body, req["input"]) \
        if req["input"]["format"] == "csv" else _iter_json(body)

    matched = []
    count = 0
    for row in rows_iter:
        # LIMIT bounds OUTPUT records: an aggregate emits one record,
        # so COUNT(*) scans everything regardless of LIMIT.
        if not query.count_star and query.limit is not None \
                and len(matched) >= query.limit:
            break
        if query.where is not None:
            try:
                # Three-valued logic: only TRUE keeps the row (NULL and
                # FALSE both drop it).
                keep = query.where.eval(row) is True
            except Exception:  # noqa: BLE001 - bad row never kills the scan
                keep = False
            if not keep:
                continue
        if query.count_star:
            count += 1
        else:
            matched.append(_project(query, row))

    if query.count_star:
        matched = [{"_1": count}]
    field_order = [alias for _, alias in query.columns] \
        if query.columns else None

    payload = _serialize(matched, req["output"], field_order)
    out = bytearray()
    # Chunk Records frames at ~128 KiB like the reference's writer.
    step = 128 * 1024
    for off in range(0, len(payload), step):
        out += eventstream.records_message(payload[off:off + step])
    out += eventstream.stats_message(len(body), len(body), len(payload))
    out += eventstream.end_message()
    return bytes(out)
