"""Select execution: input deserialization, query evaluation, output
serialization, event-stream assembly."""

from __future__ import annotations

import csv
import io
import json
import xml.etree.ElementTree as ET

from minio_tpu.s3select import eventstream
from minio_tpu.s3select.sql import SQLError, parse_select

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
_NS = f"{{{XMLNS}}}"


class SelectError(Exception):
    pass


def _strip_ns(root):
    for el in root.iter():
        if isinstance(el.tag, str) and "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def parse_select_request(body: bytes) -> dict:
    """SelectObjectContentRequest XML -> {expression, input, output}."""
    try:
        root = _strip_ns(ET.fromstring(body))
    except ET.ParseError as e:
        raise SelectError(f"malformed request: {e}") from None
    expr = root.findtext("Expression") or ""
    etype = (root.findtext("ExpressionType") or "SQL").upper()
    if etype != "SQL" or not expr:
        raise SelectError("ExpressionType must be SQL with an Expression")
    req = {"expression": expr, "input": {}, "output": {}}
    inp = root.find("InputSerialization")
    if inp is None:
        raise SelectError("missing InputSerialization")
    csv_in = inp.find("CSV")
    json_in = inp.find("JSON")
    parquet_in = inp.find("Parquet")
    if csv_in is not None:
        req["input"] = {
            "format": "csv",
            "header": (csv_in.findtext("FileHeaderInfo") or "NONE").upper(),
            "delimiter": csv_in.findtext("FieldDelimiter") or ",",
            "quote": csv_in.findtext("QuoteCharacter") or '"',
        }
    elif json_in is not None:
        req["input"] = {"format": "json"}
    elif parquet_in is not None:
        req["input"] = {"format": "parquet"}
    else:
        raise SelectError("InputSerialization needs CSV, JSON or Parquet")
    out = root.find("OutputSerialization")
    fmt = "csv" if req["input"]["format"] == "csv" else "json"
    delim = ","
    if out is not None:
        if out.find("JSON") is not None:
            fmt = "json"
        elif out.find("CSV") is not None:
            fmt = "csv"
            delim = out.find("CSV").findtext("FieldDelimiter") or ","
    req["output"] = {"format": fmt, "delimiter": delim}
    return req


def _lines(chunks):
    """Byte chunks -> decoded text lines, O(line) memory (UTF-8
    sequences split across chunk boundaries decode correctly via the
    incremental decoder)."""
    import codecs
    dec = codecs.getincrementaldecoder("utf-8")("replace")
    carry = ""
    for c in chunks:
        carry += dec.decode(c)
        while True:
            i = carry.find("\n")
            if i < 0:
                break
            yield carry[:i + 1]
            carry = carry[i + 1:]
    carry += dec.decode(b"", True)
    if carry:
        yield carry


def _iter_csv(chunks, opts: dict):
    # csv.reader over a LINE iterator handles quoted newlines by
    # pulling further lines itself — records stream in O(record).
    reader = csv.reader(_lines(chunks),
                        delimiter=opts.get("delimiter", ","),
                        quotechar=opts.get("quote", '"'))
    header_mode = opts.get("header", "NONE")
    headers = None
    header_pending = header_mode in ("USE", "IGNORE")
    while True:
        try:
            fields = next(reader)
        except StopIteration:
            return
        except csv.Error as e:
            raise SelectError(f"malformed CSV record: {e}") from None
        if not fields:
            continue
        if header_pending:
            # First NON-EMPTY row is the header (blank leading lines
            # must not demote it to data).
            header_pending = False
            if header_mode == "USE":
                headers = fields
            continue
        if headers is not None:
            row = dict(zip(headers, fields))
        else:
            row = {f"_{j + 1}": v for j, v in enumerate(fields)}
        yield row


def _iter_parquet(chunks):
    """Parquet records via pyarrow (reference: internal/s3select/parquet).
    Parquet is a footer-indexed columnar format — the file must
    materialize (no streaming parse exists for it); rows then stream
    out batch by batch."""
    try:
        import pyarrow.parquet as pq
    except ImportError:
        raise SelectError("Parquet input requires pyarrow") from None
    import io as _io
    buf = _io.BytesIO()
    for c in chunks:
        buf.write(c)
    buf.seek(0)
    try:
        pf = pq.ParquetFile(buf)
    except Exception as e:  # noqa: BLE001 - malformed file
        raise SelectError(f"malformed Parquet file: {e}") from None
    try:
        for batch in pf.iter_batches():
            # None survives as None: the WHERE evaluator's three-valued
            # NULL logic and the CSV serializer's empty-cell handling
            # both know what to do with it.
            yield from batch.to_pylist()
    except SelectError:
        raise
    except Exception as e:  # noqa: BLE001 - corrupt pages mid-iterate
        # A valid footer over corrupt data pages fails HERE, not at
        # open — same 400-class mapping as malformed CSV/JSON.
        raise SelectError(f"malformed Parquet data: {e}") from None


def _iter_json(chunks):
    for line in _lines(chunks):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            raise SelectError("malformed JSON record") from None
        if not isinstance(rec, dict):
            raise SelectError("JSON record is not an object")
        yield rec


def _project(query, row: dict) -> dict:
    if query.columns is None:
        return row
    return {alias: col.eval(row) for col, alias in query.columns}


def _serialize(rows: list, out_opts: dict, field_order) -> bytes:
    if out_opts["format"] == "json":
        return b"".join(json.dumps(r, default=str).encode() + b"\n"
                        for r in rows)
    buf = io.StringIO()
    w = csv.writer(buf, delimiter=out_opts.get("delimiter", ","),
                   lineterminator="\n")
    for r in rows:
        order = field_order or list(r)
        w.writerow(["" if r.get(k) is None else r.get(k) for k in order])
    return buf.getvalue().encode()


class _CountingChunks:
    """Wrap a chunk source, tracking bytes consumed (Stats frame)."""

    def __init__(self, source):
        self._source = iter([source]) if isinstance(source, (bytes,
                                                             bytearray)) \
            else iter(source)
        self.total = 0

    def __iter__(self):
        for c in self._source:
            self.total += len(c)
            yield c

    def close(self):
        close = getattr(self._source, "close", None)
        if close is not None:
            close()


def run_select(body, request_xml: bytes) -> bytes:
    """Execute a Select request against object content — bytes or an
    ITERATOR of chunks (records stream in O(record) memory; the
    reference streams the same way, internal/s3select). Returns the
    event-stream response (Records + Stats + End); the response itself
    is the result set, typically far smaller than the input."""
    counter = _CountingChunks(body)
    try:
        # Request parsing INSIDE the try: a malformed request must
        # still close the caller's object stream.
        req = parse_select_request(request_xml)
        try:
            query = parse_select(req["expression"])
        except SQLError as e:
            raise SelectError(str(e)) from None
        fmt_in = req["input"]["format"]
        if fmt_in == "csv":
            rows_iter = _iter_csv(counter, req["input"])
        elif fmt_in == "parquet":
            rows_iter = _iter_parquet(counter)
        else:
            rows_iter = _iter_json(counter)

        field_order = [alias for _, alias in query.columns] \
            if query.columns else None
        if query.aggregates:
            field_order = [a.alias for a in query.aggregates]
        out = bytearray()
        pending: list = []
        pending_bytes = 0
        returned = 0
        emitted = 0
        # Aggregate accumulators: [count, sum, min, max] per item.
        acc = [[0, 0.0, None, None] for _ in (query.aggregates or ())]
        # Flush Records frames at ~128 KiB like the reference's writer.
        step = 128 * 1024

        def flush():
            nonlocal pending, pending_bytes, returned
            if not pending:
                return
            payload = _serialize(pending, req["output"], field_order)
            returned += len(payload)
            for off in range(0, len(payload), step):
                out.extend(eventstream.records_message(
                    payload[off:off + step]))
            pending = []
            pending_bytes = 0

        for row in rows_iter:
            # LIMIT bounds OUTPUT records: aggregates emit one record,
            # so they scan everything regardless of LIMIT.
            if not query.aggregates and query.limit is not None \
                    and emitted >= query.limit:
                break
            if query.where is not None:
                try:
                    # Three-valued logic: only TRUE keeps the row (NULL
                    # and FALSE both drop it).
                    keep = query.where.eval(row) is True
                except Exception:  # noqa: BLE001 - bad row never kills scan
                    keep = False
                if not keep:
                    continue
            if query.aggregates:
                for a, st in zip(query.aggregates, acc):
                    if a.operand is None:          # COUNT(*)
                        st[0] += 1
                        continue
                    try:
                        v = a.operand.eval(row)
                    except Exception:  # noqa: BLE001 - bad cell
                        v = None
                    if v is None or v == "":
                        continue     # NULL / empty cells don't count
                    st[0] += 1
                    from minio_tpu.s3select.sql import _as_number
                    n = _as_number(v)
                    if n is not None:
                        st[1] += n
                        v = n
                    # Mixed numeric/string cells in one column: compare
                    # everything as strings from then on (deterministic,
                    # never a TypeError mid-scan).
                    if st[2] is not None and \
                            isinstance(v, str) != isinstance(st[2], str):
                        v = str(v)
                        st[2], st[3] = str(st[2]), str(st[3])
                    st[2] = v if st[2] is None else min(st[2], v)
                    st[3] = v if st[3] is None else max(st[3], v)
            else:
                pending.append(_project(query, row))
                emitted += 1
                pending_bytes += sum(len(str(v)) for v in row.values())
                if pending_bytes >= step:
                    flush()
        if query.aggregates:
            rec = {}
            for a, st in zip(query.aggregates, acc):
                cnt, total, mn, mx = st
                if a.func == "count":
                    rec[a.alias] = cnt
                elif a.func == "sum":
                    rec[a.alias] = total if cnt else None
                elif a.func == "avg":
                    rec[a.alias] = (total / cnt) if cnt else None
                elif a.func == "min":
                    rec[a.alias] = mn
                elif a.func == "max":
                    rec[a.alias] = mx
            pending = [rec]
        flush()
        out.extend(eventstream.stats_message(counter.total, counter.total,
                                             returned))
        out.extend(eventstream.end_message())
        return bytes(out)
    finally:
        counter.close()
