"""Drive health wrapper (deadlines, circuit breaker) and event
notification (rules, webhook, store-and-forward) — reference:
cmd/xl-storage-disk-id-check.go, internal/event/, internal/store/."""

import http.server
import json
import os
import threading
import time

import pytest

from minio_tpu.events import (EventNotifier, WebhookTarget,
                              parse_notification_xml)
from minio_tpu.events.notify import EventError
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.storage.health import DiskHealthWrapper, wrap_disks
from minio_tpu.storage.local import FaultyDisk, LocalStorage
from minio_tpu.storage.meta import FileNotFoundErr


# ---------------------------------------------------------------------------
# health wrapper
# ---------------------------------------------------------------------------

class _HungDisk:
    """Delegates to a real disk but hangs on demand."""

    def __init__(self, real):
        self._real = real
        self.hang = False
        self.endpoint = "hungdisk"

    def __getattr__(self, name):
        attr = getattr(self._real, name)
        if not callable(attr):
            return attr

        def maybe_hang(*a, **kw):
            if self.hang:
                time.sleep(60)
            return attr(*a, **kw)
        return maybe_hang


@pytest.fixture
def real_disk(tmp_path):
    return LocalStorage(str(tmp_path / "d0"))


def test_wrapper_passthrough_and_latency_stats(real_disk):
    w = DiskHealthWrapper(real_disk)
    w.make_vol_if_missing("vol1")
    w.write_all("vol1", "x", b"hello")
    assert w.read_all("vol1", "x") == b"hello"
    hi = w.health_info()
    assert hi["online"]
    assert hi["ops"]["write_all"]["count"] == 1
    assert hi["ops"]["read_all"]["avg_ms"] >= 0


def test_wrapper_domain_errors_do_not_trip_breaker(real_disk):
    w = DiskHealthWrapper(real_disk, trip_after=2)
    w.make_vol_if_missing("vol1")
    for _ in range(10):
        with pytest.raises(Exception):
            w.read_all("vol1", "missing-file")
    assert w.is_online()


def test_wrapper_timeout_trips_breaker_and_bounds_latency(real_disk):
    hung = _HungDisk(real_disk)
    w = DiskHealthWrapper(hung, op_timeout=0.2, trip_after=2, cooldown=0.3)
    w.make_vol_if_missing("vol1")
    w.write_all("vol1", "y", b"data")
    hung.hang = True
    t0 = time.monotonic()
    for _ in range(2):
        with pytest.raises(FaultyDisk):
            w.read_all("vol1", "y")
    assert time.monotonic() - t0 < 2.0       # bounded, not 60s hangs
    assert not w.is_online()
    # While open: instant failure, no new work submitted.
    t0 = time.monotonic()
    with pytest.raises(FaultyDisk):
        w.read_all("vol1", "y")
    assert time.monotonic() - t0 < 0.05
    # Drive recovers; after cooldown the half-open probe re-admits it.
    hung.hang = False
    time.sleep(0.35)
    assert w.read_all("vol1", "y") == b"data"
    assert w.is_online()


def test_quorum_fanout_latency_bounded_with_hung_drive(tmp_path):
    """PUT/GET stay fast when one wrapped drive hangs (VERDICT item 8)."""
    reals = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    hung = _HungDisk(reals[3])
    disks = wrap_disks([reals[0], reals[1], reals[2], hung],
                       op_timeout=0.3)
    for d in disks:
        d._bulk_timeout = 0.3    # test-speed deadline for create_file too
    es = ErasureSet(disks)
    es.make_bucket("hb")
    es.put_object("hb", "warm", b"w" * 10_000)
    hung.hang = True
    t0 = time.monotonic()
    es.put_object("hb", "obj", b"x" * 10_000)
    put_dt = time.monotonic() - t0
    t0 = time.monotonic()
    _, got = es.get_object("hb", "obj")
    get_dt = time.monotonic() - t0
    assert got == b"x" * 10_000
    assert put_dt < 3.0, put_dt
    assert get_dt < 3.0, get_dt


def test_wrap_disks_skips_offline_placeholders(real_disk):
    from minio_tpu.storage.local import OfflineDisk
    out = wrap_disks([real_disk, OfflineDisk("gone"), None])
    assert isinstance(out[0], DiskHealthWrapper)
    assert type(out[1]).__name__ == "OfflineDisk"
    assert out[2] is None


# ---------------------------------------------------------------------------
# events
# ---------------------------------------------------------------------------

NOTIF_XML = b"""<NotificationConfiguration>
  <QueueConfiguration>
    <Queue>arn:minio:sqs:us-east-1:1:webhook</Queue>
    <Event>s3:ObjectCreated:*</Event>
    <Filter><S3Key>
      <FilterRule><Name>prefix</Name><Value>logs/</Value></FilterRule>
      <FilterRule><Name>suffix</Name><Value>.txt</Value></FilterRule>
    </S3Key></Filter>
  </QueueConfiguration>
</NotificationConfiguration>"""


def test_parse_notification_rules():
    cfg = parse_notification_xml(NOTIF_XML)
    assert len(cfg.rules) == 1
    r = cfg.rules[0]
    assert r.prefix == "logs/" and r.suffix == ".txt"
    assert r.matches("s3:ObjectCreated:Put", "logs/a.txt")
    assert not r.matches("s3:ObjectCreated:Put", "logs/a.bin")
    assert not r.matches("s3:ObjectRemoved:Delete", "logs/a.txt")
    with pytest.raises(EventError):
        parse_notification_xml(b"<NotificationConfiguration>"
                               b"<QueueConfiguration></QueueConfiguration>"
                               b"</NotificationConfiguration>")


class _Hook(http.server.BaseHTTPRequestHandler):
    received: list = []
    fail = False

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if type(self).fail:
            self.send_response(503)
            self.end_headers()
            return
        type(self).received.append(json.loads(body))
        self.send_response(200)
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture
def webhook():
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Hook)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    _Hook.received = []
    _Hook.fail = False
    yield srv
    srv.shutdown()
    srv.server_close()


class _MetaLayer:
    """Object-layer stub exposing bucket metadata only."""

    def __init__(self, doc):
        self._doc = doc

    def get_bucket_meta(self, bucket):
        return {"config:notification": self._doc}


def test_put_fires_webhook(tmp_path, webhook):
    port = webhook.server_address[1]
    n = EventNotifier(_MetaLayer(NOTIF_XML.decode()),
                      str(tmp_path / "evq"),
                      targets=[WebhookTarget(
                          "webhook", f"http://127.0.0.1:{port}/hook")])
    n.notify("s3:ObjectCreated:Put", "b", "logs/app.txt", size=42,
             etag="abc")
    assert n.drain(5)
    n.stop()
    assert len(_Hook.received) == 1
    rec = _Hook.received[0]["Records"][0]
    assert rec["eventName"] == "s3:ObjectCreated:Put"
    assert rec["s3"]["object"]["key"] == "logs/app.txt"
    assert rec["s3"]["object"]["size"] == 42


def test_events_survive_target_downtime(tmp_path, webhook):
    port = webhook.server_address[1]
    store = str(tmp_path / "evq")
    _Hook.fail = True
    n = EventNotifier(_MetaLayer(NOTIF_XML.decode()), store,
                      targets=[WebhookTarget(
                          "webhook", f"http://127.0.0.1:{port}/hook")])
    n.notify("s3:ObjectCreated:Put", "b", "logs/one.txt")
    n.notify("s3:ObjectCreated:Put", "b", "logs/two.txt")
    time.sleep(0.3)
    assert not n.drain(0.5)          # target down: still queued
    n.stop()
    assert len(os.listdir(store)) == 2
    # "Restart": a new notifier picks the persisted queue up and
    # delivers once the target is back.
    _Hook.fail = False
    n2 = EventNotifier(_MetaLayer(NOTIF_XML.decode()), store,
                       targets=[WebhookTarget(
                           "webhook", f"http://127.0.0.1:{port}/hook")])
    assert n2.drain(10)
    n2.stop()
    keys = [r["Records"][0]["s3"]["object"]["key"]
            for r in _Hook.received]
    assert sorted(keys) == ["logs/one.txt", "logs/two.txt"]


def test_non_matching_events_not_queued(tmp_path):
    n = EventNotifier(_MetaLayer(NOTIF_XML.decode()),
                      str(tmp_path / "evq"),
                      targets=[WebhookTarget("webhook", "http://x/")])
    n.notify("s3:ObjectCreated:Put", "b", "other/app.txt")
    n.notify("s3:ObjectRemoved:Delete", "b", "logs/app.txt")
    n.stop()
    assert os.listdir(str(tmp_path / "evq")) == []


def test_metrics_cover_round4_subsystems(tmp_path):
    """The metrics endpoint exposes the round-4 services: metacache
    effectiveness, replication counters, batch job states."""
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.replication import ReplicationEngine
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.local import LocalStorage
    from tests.s3client import S3Client

    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    srv.replicator = ReplicationEngine(srv.object_layer)
    srv.start()
    try:
        cli = S3Client(srv.address)
        assert cli.request("PUT", "/mbkt")[0] == 200
        cli.request("PUT", "/mbkt/o", body=b"x")
        cli.request("GET", "/mbkt")     # prime a listing
        cli.request("GET", "/mbkt")     # ...and hit the cache
        import urllib.request
        with urllib.request.urlopen(
                f"http://{srv.address}/minio/v2/metrics/cluster") as r:
            text = r.read().decode()
        for series in ("minio_tpu_metacache_hits_total",
                       "minio_tpu_metacache_misses_total",
                       "minio_tpu_replication_queued_total",
                       "minio_tpu_http_requests_total",
                       "minio_tpu_drives_online"):
            assert series in text, series
        # The cache hit actually registered.
        hit_line = [ln for ln in text.splitlines()
                    if ln.startswith("minio_tpu_metacache_hits_total")][0]
        assert float(hit_line.split()[-1]) >= 1
    finally:
        srv.stop()
