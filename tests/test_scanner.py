"""Background scanner + heal drivers: usage accounting, missing-shard
repair without client reads, deep bitrot sampling, replaced-drive format
restore, global heal sweep, MRF persistence (reference:
cmd/data-scanner.go, cmd/background-newdisks-heal-ops.go,
cmd/global-heal.go, cmd/mrf.go)."""

import json
import os
import shutil

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.healing import MRF_PATH, MRFQueue
from minio_tpu.object.scanner import (DataUsage, Scanner,
                                      check_drive_formats, heal_set)
from minio_tpu.storage.local import SYS_VOL, LocalStorage
from minio_tpu.topology.format import init_formats


@pytest.fixture
def env(tmp_path):
    roots = [str(tmp_path / f"d{i}") for i in range(6)]
    disks = [LocalStorage(r) for r in roots]
    init_formats(disks, set_size=6)
    es = ErasureSet(disks)
    es.make_bucket("sb")
    return es, roots


def _obj_dir(root, bucket, key):
    return os.path.join(root, bucket, key)


def test_usage_accounting(env):
    es, roots = env
    es.make_bucket("other")
    for i in range(5):
        es.put_object("sb", f"o{i}", b"x" * (1000 + i))
    es.put_object("other", "big", b"y" * 50_000)
    sc = Scanner([es], throttle=0)
    u = sc.scan_cycle()
    assert u.objects == 6
    assert u.buckets["sb"].objects == 5
    assert u.buckets["sb"].size == sum(1000 + i for i in range(5))
    assert u.buckets["other"].size == 50_000
    # Persisted + reloadable.
    sc2 = Scanner([es], throttle=0)
    assert sc2.usage.objects == 6


def test_scanner_repairs_missing_shard_without_client_read(env):
    es, roots = env
    body = os.urandom(300_000)
    es.put_object("sb", "victim", body)
    # Nuke the object entirely from one drive, filesystem-level.
    shutil.rmtree(_obj_dir(roots[2], "sb", "victim"))
    sc = Scanner([es], throttle=0)
    u = sc.scan_cycle()
    assert u.healed >= 1
    assert os.path.isdir(_obj_dir(roots[2], "sb", "victim"))
    _, got = es.get_object("sb", "victim")
    assert got == body


def test_deep_sampling_finds_silent_bitrot(env):
    es, roots = env
    body = os.urandom(1_500_000)   # above inline threshold: real shard file
    es.put_object("sb", "rot", body)
    # Flip bytes inside one drive's shard file: stat size unchanged, so
    # only a deep (bitrot-verifying) heal can see it.
    objdir = _obj_dir(roots[1], "sb", "rot")
    datadir = next(d for d in os.listdir(objdir) if d != "xl.meta")
    part = os.path.join(objdir, datadir, "part.1")
    blob = bytearray(open(part, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(part, "wb").write(bytes(blob))

    sc = Scanner([es], throttle=0, deep_every=1)   # sample everything
    sc.scan_cycle()
    # The corrupt shard was rebuilt: full read passes bitrot everywhere.
    from minio_tpu.object.healing import heal_object
    r = heal_object(es, "sb", "rot", deep=True)
    assert all(s == "ok" for s in r.before), r.before
    _, got = es.get_object("sb", "rot")
    assert got == body


def test_replaced_drive_format_restore_and_repopulate(env):
    es, roots = env
    body = os.urandom(200_000)
    es.put_object("sb", "keep", body)
    old_format = json.loads(open(
        os.path.join(roots[4], SYS_VOL, "format.json")).read())
    # Replace drive 4 with a blank disk (same mount point).
    shutil.rmtree(roots[4])
    es.disks[4] = LocalStorage(roots[4])
    healed = check_drive_formats([es], set_size=6)
    assert healed == 1
    new_format = json.loads(open(
        os.path.join(roots[4], SYS_VOL, "format.json")).read())
    assert new_format["xl"]["this"] == old_format["xl"]["this"]
    assert new_format["id"] == old_format["id"]
    # The scan then repopulates the blank drive's data.
    Scanner([es], throttle=0).scan_cycle()
    assert os.path.isdir(_obj_dir(roots[4], "sb", "keep"))
    _, got = es.get_object("sb", "keep")
    assert got == body


def test_heal_set_sweep(env):
    es, roots = env
    for i in range(4):
        es.put_object("sb", f"s{i}", os.urandom(10_000))
    for i in range(4):
        shutil.rmtree(_obj_dir(roots[0], "sb", f"s{i}"))
    stats = heal_set(es)
    assert stats["objects"] == 4
    assert stats["healed"] == 4
    for i in range(4):
        assert os.path.isdir(_obj_dir(roots[0], "sb", f"s{i}"))


def test_mrf_persists_and_reloads(env):
    es, roots = env
    es.put_object("sb", "mrfobj", b"z" * 5000)
    es.mrf.stop()
    # Freeze the worker so the enqueued entry stays pending (a crash
    # between enqueue and heal), then snapshot.
    q = MRFQueue(es, persist=True)
    q._stop.set()
    q._worker.join(timeout=2)
    q.enqueue("sb", "mrfobj")
    q.save_now()
    blob = es.disks[0].read_all(SYS_VOL, MRF_PATH)
    items = json.loads(blob)
    assert {"b": "sb", "o": "mrfobj", "v": ""} in items
    # A new queue ("restart") loads the pending entry and heals it away.
    q2 = MRFQueue(es, persist=True)
    q2.drain()
    assert q2.healed >= 1
    q2.stop()


def test_scanner_counts_versions_and_delete_markers(env):
    es, roots = env
    from minio_tpu.object.types import DeleteOptions, PutOptions
    es.put_object("sb", "v", b"a" * 100, PutOptions(versioned=True))
    es.put_object("sb", "v", b"b" * 200, PutOptions(versioned=True))
    es.delete_object("sb", "v", DeleteOptions(versioned=True))
    u = Scanner([es], throttle=0).scan_cycle()
    assert u.buckets["sb"].versions == 3
    assert u.buckets["sb"].delete_markers == 1
    assert u.buckets["sb"].size == 300
