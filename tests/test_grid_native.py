"""Native grid data plane (wire v2): raw bulk frames, credit-window
multiplexing on the shared epoll poller, zero-copy sendfile shard
transfer, and the MTPU_GRID_NATIVE kill switch.

Every test runs a REAL GridServer + StorageRPCService in-process, so
`grid.loop.stats()` counters observe both directions (client and
server share the process-wide poller)."""

import os
import threading
import time

import pytest

from minio_tpu.grid import loop, wire
from minio_tpu.grid.client import GridClient
from minio_tpu.grid.server import GridServer
from minio_tpu.grid.wire import GridError
from minio_tpu.storage.local import LocalStorage
from minio_tpu.storage.remote import RemoteStorage, StorageRPCService


@pytest.fixture
def grid_env(tmp_path):
    roots = [str(tmp_path / f"d{i}") for i in range(2)]
    locals_ = [LocalStorage(r) for r in roots]
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({d.root: d for d in locals_}).register_into(srv)
    srv.start()
    yield srv, roots, locals_
    srv.stop()


def _blob(n: int, seed: int = 7) -> bytes:
    # Deterministic non-repeating pattern (cheaper than os.urandom at
    # multi-MB sizes, still catches offset/ordering bugs).
    one = bytes((i * 31 + seed) & 0xFF for i in range(4096))
    reps = n // len(one) + 1
    return (one * reps)[:n]


# ---------------------------------------------------------------------------
# byte identity + sendfile counters (read direction)
# ---------------------------------------------------------------------------

def test_raw_read_byte_identity_and_sendfile_counter(grid_env):
    """Remote read_file over the native plane is byte-identical to the
    local file — including offset/length slices that straddle the
    1 MiB raw-slice boundary — and the send side goes through
    os.sendfile (counter proof of zero Python-level copies)."""
    srv, roots, locals_ = grid_env
    data = _blob(5 * (1 << 20) + 12345)
    locals_[0].make_vol("vol")
    locals_[0].create_file("vol", "shard.bin", data)

    remote = RemoteStorage("127.0.0.1", srv.port, roots[0])
    before = loop.stats()
    assert remote.read_file("vol", "shard.bin") == data
    # Mixed slice shapes: <= 1 MiB explicit lengths take the unary
    # fast path, larger/unknown lengths the raw stream — identity must
    # hold across both routes, including slices straddling the 1 MiB
    # raw-slice boundary.
    for off, ln in [(0, 17), (1 << 20, 1 << 20), ((1 << 20) - 3, 10),
                    (len(data) - 5, -1), (4321, 3 * (1 << 20) + 7),
                    ((1 << 20) - 3, (1 << 20) + 7)]:
        want = data[off:] if ln < 0 else data[off:off + ln]
        assert remote.read_file("vol", "shard.bin", off, ln) == want, \
            (off, ln)
    after = loop.stats()
    assert after["sendfile_transfers"] > before["sendfile_transfers"]
    assert after["sendfile_bytes"] - before["sendfile_bytes"] >= len(data)
    assert after["raw_tx_frames"] > before["raw_tx_frames"]


def test_small_read_unary_fast_path(grid_env):
    """An explicit-length read <= 1 MiB (the GET path's bitrot block
    window shape) rides ONE unary round-trip: byte-identical, and the
    raw-frame/sendfile counters do not move."""
    srv, roots, locals_ = grid_env
    data = _blob(3 * (1 << 20), seed=5)
    locals_[0].make_vol("svol")
    locals_[0].create_file("svol", "shard.bin", data)
    remote = RemoteStorage("127.0.0.1", srv.port, roots[0])
    before = loop.stats()
    for off, ln in [(0, 1 << 20), (123, 4096), ((1 << 20) + 9, 65536),
                    (len(data) - 10, 10)]:
        assert remote.read_file("svol", "shard.bin", off, ln) \
            == data[off:off + ln], (off, ln)
    after = loop.stats()
    assert after["raw_tx_frames"] == before["raw_tx_frames"]
    assert after["sendfile_transfers"] == before["sendfile_transfers"]


def test_raw_read_empty_file(grid_env):
    srv, roots, locals_ = grid_env
    locals_[0].make_vol("vol")
    locals_[0].create_file("vol", "empty.bin", b"")
    remote = RemoteStorage("127.0.0.1", srv.port, roots[0])
    assert remote.read_file("vol", "empty.bin") == b""


# ---------------------------------------------------------------------------
# byte identity (write direction: client-push raw sink)
# ---------------------------------------------------------------------------

def test_raw_write_sink_byte_identity(grid_env):
    """create_file above the unary cutoff rides the flow-controlled
    push-raw sink; the staged+committed file is byte-identical."""
    srv, roots, locals_ = grid_env
    data = _blob(4 * (1 << 20) + 999, seed=11)
    remote = RemoteStorage("127.0.0.1", srv.port, roots[1])
    remote.make_vol_if_missing("wvol")
    before = loop.stats()
    remote.create_file("wvol", "pushed.bin", data)
    after = loop.stats()
    assert locals_[1].read_file("wvol", "pushed.bin", 0, -1) == data
    assert after["raw_tx_bytes"] - before["raw_tx_bytes"] >= len(data)


def test_push_raw_rawfile_sendfile_send_side(grid_env, tmp_path):
    """wire.RawFile push items ship via os.sendfile straight from the
    source fd — offset/length slicing included."""
    srv, roots, locals_ = grid_env
    data = _blob(2 * (1 << 20), seed=3)
    src = tmp_path / "src.bin"
    src.write_bytes(data)
    remote = RemoteStorage("127.0.0.1", srv.port, roots[0])
    remote.make_vol_if_missing("fvol")
    c = GridClient("127.0.0.1", srv.port)
    before = loop.stats()
    c.push_raw("st.write_file_raw",
               {"d": roots[0], "a": ["fvol", "whole.bin"]},
               [wire.RawFile(str(src))])
    c.push_raw("st.write_file_raw",
               {"d": roots[0], "a": ["fvol", "slice.bin"]},
               [wire.RawFile(str(src), offset=4096, length=123456)])
    after = loop.stats()
    assert locals_[0].read_file("fvol", "whole.bin", 0, -1) == data
    assert locals_[0].read_file("fvol", "slice.bin", 0, -1) \
        == data[4096:4096 + 123456]
    assert after["sendfile_transfers"] > before["sendfile_transfers"]
    c.close()


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------

def test_kill_switch_off_byte_identity(tmp_path, monkeypatch):
    """MTPU_GRID_NATIVE=off reverts to the v1 msgpack plane —
    byte-identical results, zero raw/sendfile counter movement."""
    monkeypatch.setenv("MTPU_GRID_NATIVE", "off")
    roots = [str(tmp_path / "d0")]
    local = LocalStorage(roots[0])
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({local.root: local}).register_into(srv)
    srv.start()
    try:
        data = _blob(3 * (1 << 20) + 77, seed=5)
        local.make_vol("vol")
        local.create_file("vol", "v1.bin", data)
        remote = RemoteStorage("127.0.0.1", srv.port, roots[0])
        before = loop.stats()
        assert remote.read_file("vol", "v1.bin") == data
        assert remote.read_file("vol", "v1.bin", 100, 1 << 20) \
            == data[100:100 + (1 << 20)]
        remote.create_file("vol", "v1-w.bin", data)
        assert local.read_file("vol", "v1-w.bin", 0, -1) == data
        after = loop.stats()
        assert after["raw_tx_frames"] == before["raw_tx_frames"]
        assert after["sendfile_transfers"] == before["sendfile_transfers"]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# multiplexing fairness under an undrained bulk stream
# ---------------------------------------------------------------------------

def test_mux_fairness_under_undrained_bulk_stream(grid_env):
    """A bulk raw stream nobody drains stalls the SENDER at its credit
    window; unary traffic on the same connection keeps sub-50ms
    latency instead of queueing behind megabytes of frames."""
    srv, roots, _ = grid_env
    chunk = _blob(256 << 10, seed=9)
    total = 64

    def bulk_stream(payload):
        for _ in range(total):
            yield wire.RawBytes(chunk)

    srv.register_stream("test.bulk", bulk_stream)
    c = GridClient("127.0.0.1", srv.port)
    try:
        it = c.stream("test.bulk", raw=True, timeout=60.0)
        got = next(it)                     # stream is live…
        if isinstance(got, tuple) and got[1] is not None:
            got[1].release()
        # …and now UNDRAINED: the sender must park on credit, not
        # flood the connection.
        lat = []
        for _ in range(30):
            t0 = time.perf_counter()
            assert c.ping(timeout=5.0)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        assert lat[len(lat) // 2] < 0.05, f"median ping {lat[-1]:.3f}s"
        # Drain to completion: every byte arrives intact.
        n = len(chunk)
        for item in it:
            if isinstance(item, tuple):
                view, lease = item
                n += len(view)
                if lease is not None:
                    lease.release()
            else:
                n += len(item)
        assert n == total * len(chunk)
    finally:
        c.close()


# ---------------------------------------------------------------------------
# per-stream failure accounting (breaker regression)
# ---------------------------------------------------------------------------

def test_stream_timeout_on_live_connection_is_not_breaker_fuel(grid_env):
    """A hung stream handler times out ITS caller while pings keep the
    connection provably alive: the error says so, and repeated
    occurrences never open the peer breaker (which would fail every
    healthy stream sharing the socket)."""
    srv, roots, _ = grid_env
    release = threading.Event()

    def hung_stream(payload):
        yield b"first"
        release.wait(30.0)
        yield b"second"

    srv.register_stream("test.hung", hung_stream)
    c = GridClient("127.0.0.1", srv.port, trip_after=2)
    stop = threading.Event()

    def pinger():
        while not stop.is_set():
            c.ping(timeout=2.0)
            stop.wait(0.2)

    t = threading.Thread(target=pinger, daemon=True)
    t.start()
    try:
        for _ in range(3):                 # > trip_after
            it = c.stream("test.hung", timeout=1.0)
            assert next(it) == b"first"
            with pytest.raises(GridError) as ei:
                next(it)
            assert "connection live" in str(ei.value)
            it.close()
        assert c.breaker_state() == "closed"
        assert c._consecutive == 0
        # The shared connection stays usable for everyone else.
        assert c.ping(timeout=2.0)
    finally:
        release.set()
        stop.set()
        t.join(timeout=5)
        c.close()


# ---------------------------------------------------------------------------
# poller plumbing sanity
# ---------------------------------------------------------------------------

def test_poller_stats_shape_and_accounting(grid_env):
    srv, roots, locals_ = grid_env
    st = loop.stats()
    for key in ("native", "conns", "frames", "raw_rx_frames",
                "raw_rx_bytes", "raw_tx_frames", "raw_tx_bytes",
                "sendfile_transfers", "sendfile_bytes",
                "credit_stalls", "conns_dropped"):
        assert key in st, key
    data = _blob(2 << 20, seed=13)
    locals_[0].make_vol("svol")
    locals_[0].create_file("svol", "s.bin", data)
    remote = RemoteStorage("127.0.0.1", srv.port, roots[0])
    before = loop.stats()
    assert remote.read_file("svol", "s.bin") == data
    after = loop.stats()
    assert after["raw_rx_bytes"] - before["raw_rx_bytes"] >= len(data)
    assert after["raw_rx_frames"] > before["raw_rx_frames"]
