"""Bucket quotas (reference: cmd/bucket-quota.go:32 hard-quota
enforcement on every write path) and dangling-object GC (reference:
cmd/erasure-object.go:484 deleteIfDangling on quorum-less reads)."""

import json
import os

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import ObjectNotFound
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

B = "quotabkt"


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("quotadrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    cli = S3Client(server.address)
    assert cli.request("PUT", f"/{B}")[0] == 200
    yield server, cli, es
    server.stop()


def _set_quota(cli, nbytes, qtype="hard"):
    st, _, b = cli.request(
        "PUT", "/minio/admin/v3/set-bucket-quota",
        query={"bucket": B},
        body=json.dumps({"quota": nbytes, "quotatype": qtype}).encode())
    assert st == 200, b


def test_hard_quota_enforced_on_put(env):
    server, cli, _ = env
    _set_quota(cli, 150_000)
    # Under quota: fine.
    assert cli.request("PUT", f"/{B}/a", body=os.urandom(60_000))[0] == 200
    assert cli.request("PUT", f"/{B}/b", body=os.urandom(60_000))[0] == 200
    # This one would cross 150k: rejected with the admin quota code.
    st, _, body = cli.request("PUT", f"/{B}/c", body=os.urandom(60_000))
    assert st == 400 and b"XMinioAdminBucketQuotaExceeded" in body
    assert cli.request("GET", f"/{B}/c")[0] == 404
    # Deleting data frees quota after the usage TTL; simulate by
    # dropping the server's cached figure.
    assert cli.request("DELETE", f"/{B}/a")[0] == 204
    server._quota_usage.clear()
    assert cli.request("PUT", f"/{B}/c", body=os.urandom(60_000))[0] == 200


def test_quota_enforced_on_multipart_parts(env):
    server, cli, _ = env
    _set_quota(cli, 200_000)
    server._quota_usage.clear()
    st, _, body = cli.request("POST", f"/{B}/mp", query={"uploads": ""})
    assert st == 200
    import xml.etree.ElementTree as ET
    root = ET.fromstring(body)
    uid = root.findtext(f"{root.tag.split('}')[0]}}}UploadId")
    st, _, body = cli.request(
        "PUT", f"/{B}/mp", query={"partNumber": "1", "uploadId": uid},
        body=os.urandom(300_000))
    assert st == 400 and b"XMinioAdminBucketQuotaExceeded" in body
    cli.request("DELETE", f"/{B}/mp", query={"uploadId": uid})


def test_quota_get_and_clear(env):
    _, cli, _ = env
    _set_quota(cli, 123_456)
    st, _, body = cli.request("GET", "/minio/admin/v3/get-bucket-quota",
                              query={"bucket": B})
    assert st == 200 and json.loads(body)["quota"] == 123_456
    _set_quota(cli, 0)                   # 0 clears the config
    st, _, body = cli.request("GET", "/minio/admin/v3/get-bucket-quota",
                              query={"bucket": B})
    assert st == 404 and b"XMinioAdminNoSuchQuotaConfiguration" in body


def test_dangling_object_reaped_on_read(tmp_path):
    """A version stack surviving on a minority of drives (failed-write
    leftover) is deleted by the next read instead of haunting the
    namespace (reference: deleteIfDangling)."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("dang")
    es.put_object("dang", "ghost", os.urandom(50_000))
    # Manufacture the dangling state: remove the object from 3 of 4
    # drives (as if the commit only reached one).
    for d in disks[:3]:
        d.delete("dang", "ghost", recursive=True)
    assert any(True for _ in disks[3].walk_dir("dang"))
    with pytest.raises(ObjectNotFound):
        es.get_object("dang", "ghost")
    # The reap runs async under the key's write lock; wait for it.
    import time
    for _ in range(100):
        if not list(disks[3].walk_dir("dang")):
            break
        time.sleep(0.05)
    # The minority leftover is gone from the last drive too.
    assert not list(disks[3].walk_dir("dang"))
    # A second read is a plain 404 (nothing left to reap).
    with pytest.raises(ObjectNotFound):
        es.get_object("dang", "ghost")


def test_transient_errors_do_not_trigger_reaping(tmp_path):
    """IO errors are NOT definitive not-founds: the object must survive
    when a majority of drives is merely unreachable."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("dang")
    body = os.urandom(50_000)
    es.put_object("dang", "keeper", body)

    class Flaky:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            if name in ("read_version",):
                def fail(*a, **k):
                    raise OSError("drive hiccup")
                return fail
            return getattr(self._inner, name)

    real = list(es.disks)
    try:
        for i in range(3):
            es.disks[i] = Flaky(real[i])
        with pytest.raises(Exception):
            es.get_object("dang", "keeper")
    finally:
        es.disks[:] = real
    _, got = es.get_object("dang", "keeper")
    assert got == body
