"""Chaos harness: composable fault injection + concurrent client load.

Builds S3 stacks whose drives misbehave on a programmed schedule —
NaughtyDisk error schedules (storage/naughty.py), sleep-injected hung
drives (the failure mode that trips the health breaker's op deadline
rather than erroring), and killed grid peers — then drives them with
concurrent clients and collects per-request outcomes, so the chaos
tests (tests/test_chaos.py) can assert the degradation INVARIANTS:
in-quorum traffic succeeds, out-of-quorum traffic fails fast with the
right S3 error, shed traffic gets 503 + Retry-After, and nothing
outlives its deadline budget.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.health import wrap_disks
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


class HungDisk:
    """Sleep-injecting drive wrapper: selected ops (all by default)
    block `delay` seconds before passing through — "the drive answers,
    but glacially", which only op deadlines catch, never error
    handling. release() unblocks every in-flight and future sleep so
    teardown is instant."""

    def __init__(self, disk, delay: float, ops: Optional[set] = None):
        self._disk = disk
        self.delay = delay
        self.ops = set(ops) if ops else None
        self._released = threading.Event()
        self.hung_calls = 0
        self._mu = threading.Lock()

    @property
    def wrapped(self):
        return self._disk

    @property
    def endpoint(self):
        return getattr(self._disk, "endpoint", "hung")

    @property
    def root(self):
        return getattr(self._disk, "root", None)

    def release(self) -> None:
        self._released.set()

    def __getattr__(self, name: str):
        attr = getattr(self._disk, name)
        if not callable(attr):
            return attr

        def wrapped(*args, **kwargs):
            if self.ops is None or name in self.ops:
                with self._mu:
                    self.hung_calls += 1
                self._released.wait(self.delay)
            return attr(*args, **kwargs)
        return wrapped


def build_set(tmp_path, n_disks: int = 4,
              chaos: Optional[Callable[[int, object], object]] = None,
              health: bool = True, op_timeout: float = 0.3,
              bulk_timeout: float = 1.0, trip_after: int = 2,
              cooldown: float = 60.0) -> ErasureSet:
    """ErasureSet over local drives, each passed through `chaos(i, disk)`
    (return a wrapper or the disk unchanged), then health-wrapped with
    tight test-scale deadlines. cooldown defaults high so a tripped
    breaker stays open for the rest of the test unless the test wants
    half-open probes."""
    disks: list = [LocalStorage(str(tmp_path / f"d{i}"))
                   for i in range(n_disks)]
    if chaos is not None:
        disks = [chaos(i, d) or d for i, d in enumerate(disks)]
    if health:
        disks = wrap_disks(disks, op_timeout=op_timeout,
                           bulk_timeout=bulk_timeout,
                           trip_after=trip_after, cooldown=cooldown)
    return ErasureSet(disks)


def boot_server(object_layer, admission=None) -> S3Server:
    """S3Server on an ephemeral port; `admission` (an
    AdmissionController) replaces the env-derived default so tests
    control gating without mutating process env."""
    server = S3Server(object_layer, address="127.0.0.1:0")
    if admission is not None:
        server.admission = admission
    server.start()
    return server


@dataclass
class Outcome:
    """One request's fate under load."""
    status: int                    # HTTP status; 0 = transport error
    seconds: float
    headers: dict = field(default_factory=dict)
    error: Optional[Exception] = None


def run_load(address: str, work: Callable[[S3Client], tuple],
             threads: int = 8, per_thread: int = 1,
             timeout: float = 30.0) -> list[Outcome]:
    """Fire `work(client) -> (status, headers, body)` from N concurrent
    threads, `per_thread` times each, all released on one barrier so
    the burst truly lands together. Returns every Outcome."""
    outcomes: list[Outcome] = []
    mu = threading.Lock()
    barrier = threading.Barrier(threads)

    def runner():
        cli = S3Client(address, timeout=timeout)
        barrier.wait()
        for _ in range(per_thread):
            t0 = time.monotonic()
            try:
                status, headers, _ = work(cli)
                out = Outcome(status, time.monotonic() - t0,
                              dict(headers))
            except Exception as e:  # noqa: BLE001 - an outcome, not a bug
                out = Outcome(0, time.monotonic() - t0, {}, e)
            with mu:
                outcomes.append(out)

    ts = [threading.Thread(target=runner, daemon=True)
          for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=timeout + 30)
    return outcomes


def statuses(outcomes: list[Outcome]) -> dict[int, int]:
    hist: dict[int, int] = {}
    for o in outcomes:
        hist[o.status] = hist.get(o.status, 0) + 1
    return hist


class pool_balance:
    """Context manager asserting buffer-pool lease hygiene across a
    chaos scenario: every lease taken during the block is returned
    exactly once — outstanding drains back to the entry level, no leak
    was counted, no double release happened — even when shard writes
    time out or NaughtyDisks kill writers mid-op. `settle` bounds the
    wait for abandoned (deadline-cut) drive workers to finish and
    return their retained references."""

    def __init__(self, settle: float = 5.0):
        self.settle = settle

    def __enter__(self):
        from minio_tpu.io.bufpool import global_pool
        self.pool = global_pool()
        self.before = self.pool.stats()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        deadline = time.monotonic() + self.settle
        while time.monotonic() < deadline:
            if self.pool.stats()["outstanding"] \
                    <= self.before["outstanding"]:
                break
            time.sleep(0.05)
        after = self.pool.stats()
        assert after["outstanding"] <= self.before["outstanding"], (
            f"leases not returned: {after['outstanding']} outstanding "
            f"(was {self.before['outstanding']})")
        assert after["leaks"] == self.before["leaks"], (
            "dropped lease hit the leak net during chaos run")
        assert after["double_releases"] == self.before["double_releases"], \
            "a lease was returned more than once"
        return False
