"""End-to-end S3 API tests: real HTTP + real SigV4 against the full stack
(server -> erasure set -> local drives), the shape of the reference's
TestServer harness (cmd/test-utils_test.go:314)."""

import os
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import Credentials, S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("drives")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


@pytest.fixture(scope="module")
def cli(srv):
    return S3Client(srv.address)


def _mk(cli, name):
    status, _, body = cli.request("PUT", f"/{name}")
    assert status == 200, body


def test_bucket_lifecycle(cli):
    _mk(cli, "lifec")
    status, _, _ = cli.request("HEAD", "/lifec")
    assert status == 200
    status, _, body = cli.request("GET", "/")
    assert status == 200 and b"<Name>lifec</Name>" in body
    status, _, _ = cli.request("DELETE", "/lifec")
    assert status == 204
    status, _, _ = cli.request("HEAD", "/lifec")
    assert status == 404


def test_invalid_bucket_names(cli):
    for bad in ("ab", "UPPER", "has_underscore", "-lead"):
        status, _, body = cli.request("PUT", f"/{bad}")
        assert status == 400, (bad, body)


def test_put_get_head_delete_object(cli):
    _mk(cli, "objops")
    payload = os.urandom(300_000)
    status, h, _ = cli.request("PUT", "/objops/dir/key.bin", body=payload,
                               headers={"content-type": "app/x",
                                        "x-amz-meta-color": "blue"})
    assert status == 200
    etag = h["ETag"]
    status, h, body = cli.request("GET", "/objops/dir/key.bin")
    assert status == 200 and body == payload
    assert h["ETag"] == etag and h["Content-Type"] == "app/x"
    assert h.get("x-amz-meta-color") == "blue"
    status, h, body = cli.request("HEAD", "/objops/dir/key.bin")
    assert status == 200 and body == b""
    assert int(h["Content-Length"]) == len(payload)
    status, _, _ = cli.request("DELETE", "/objops/dir/key.bin")
    assert status == 204
    status, _, _ = cli.request("GET", "/objops/dir/key.bin")
    assert status == 404


def test_ranged_get(cli):
    _mk(cli, "ranged")
    payload = bytes(range(256)) * 5000
    cli.request("PUT", "/ranged/o", body=payload)
    status, h, body = cli.request("GET", "/ranged/o",
                                  headers={"Range": "bytes=1000-1999"})
    assert status == 206 and body == payload[1000:2000]
    assert h["Content-Range"] == f"bytes 1000-1999/{len(payload)}"
    status, _, body = cli.request("GET", "/ranged/o",
                                  headers={"Range": "bytes=-100"})
    assert status == 206 and body == payload[-100:]
    status, _, body = cli.request("GET", "/ranged/o",
                                  headers={"Range": f"bytes={len(payload)}-"})
    assert status == 416


def test_streaming_chunked_put(cli):
    _mk(cli, "chunked")
    payload = os.urandom(200_000)
    status, _, body = cli.request("PUT", "/chunked/stream", body=payload,
                                  chunked=True)
    assert status == 200, body
    status, _, got = cli.request("GET", "/chunked/stream")
    assert got == payload


def test_streaming_put_te_chunked(cli):
    """aws-chunked inside HTTP Transfer-Encoding: chunked (no
    Content-Length) — the SDK's unknown-length streaming shape."""
    _mk(cli, "techunk")
    payload = os.urandom(300_000)
    status, _, body = cli.request("PUT", "/techunk/stream", body=payload,
                                  chunked=True, te_chunked=True)
    assert status == 200, body
    status, _, got = cli.request("GET", "/techunk/stream")
    assert got == payload
    # Keep-alive stays clean after the trailer drain: a second request
    # on a fresh connection round-trips normally.
    status, _, _ = cli.request("HEAD", "/techunk/stream")
    assert status == 200


def test_listing_v1_v2(cli):
    _mk(cli, "listing")
    for k in ("a/1.txt", "a/2.txt", "b/3.txt", "top.txt"):
        cli.request("PUT", f"/listing/{k}", body=b"x")
    status, _, body = cli.request("GET", "/listing",
                                  query={"list-type": "2"})
    root = ET.fromstring(body)
    keys = [e.text for e in root.iter(f"{NS}Key")]
    assert keys == ["a/1.txt", "a/2.txt", "b/3.txt", "top.txt"]
    # delimiter
    status, _, body = cli.request("GET", "/listing",
                                  query={"list-type": "2", "delimiter": "/"})
    root = ET.fromstring(body)
    prefixes = [e.findtext(f"{NS}Prefix") for e in root.iter(f"{NS}CommonPrefixes")]
    keys = [e.text for e in root.iter(f"{NS}Key")]
    assert prefixes == ["a/", "b/"] and keys == ["top.txt"]
    # pagination v2
    status, _, body = cli.request("GET", "/listing",
                                  query={"list-type": "2", "max-keys": "2"})
    root = ET.fromstring(body)
    assert root.findtext(f"{NS}IsTruncated") == "true"
    token = root.findtext(f"{NS}NextContinuationToken")
    status, _, body = cli.request(
        "GET", "/listing", query={"list-type": "2",
                                  "continuation-token": token})
    root = ET.fromstring(body)
    keys = [e.text for e in root.iter(f"{NS}Key")]
    assert keys == ["b/3.txt", "top.txt"]
    # v1
    status, _, body = cli.request("GET", "/listing", query={"prefix": "a/"})
    root = ET.fromstring(body)
    keys = [e.text for e in root.iter(f"{NS}Key")]
    assert keys == ["a/1.txt", "a/2.txt"]


def test_multi_delete(cli):
    _mk(cli, "multidel")
    for k in ("x1", "x2", "x3"):
        cli.request("PUT", f"/multidel/{k}", body=b"d")
    xml = (b'<Delete><Object><Key>x1</Key></Object>'
           b'<Object><Key>x2</Key></Object>'
           b'<Object><Key>missing</Key></Object></Delete>')
    status, _, body = cli.request("POST", "/multidel", query={"delete": ""},
                                  body=xml)
    assert status == 200
    root = ET.fromstring(body)
    deleted = [e.findtext(f"{NS}Key") for e in root.iter(f"{NS}Deleted")]
    assert set(deleted) >= {"x1", "x2"}
    status, _, _ = cli.request("GET", "/multidel/x1")
    assert status == 404
    status, _, _ = cli.request("GET", "/multidel/x3")
    assert status == 200


def test_versioning_flow(cli):
    _mk(cli, "versioned")
    status, _, body = cli.request("GET", "/versioned", query={"versioning": ""})
    assert status == 200 and b"Enabled" not in body
    vcfg = (b'<VersioningConfiguration><Status>Enabled</Status>'
            b'</VersioningConfiguration>')
    status, _, body = cli.request("PUT", "/versioned", query={"versioning": ""},
                                  body=vcfg)
    assert status == 200, body
    status, h1, _ = cli.request("PUT", "/versioned/doc", body=b"v1")
    status, h2, _ = cli.request("PUT", "/versioned/doc", body=b"v2")
    v1, v2 = h1["x-amz-version-id"], h2["x-amz-version-id"]
    assert v1 != v2
    _, _, body = cli.request("GET", "/versioned/doc")
    assert body == b"v2"
    _, _, body = cli.request("GET", "/versioned/doc",
                             query={"versionId": v1})
    assert body == b"v1"
    status, h, _ = cli.request("DELETE", "/versioned/doc")
    assert h.get("x-amz-delete-marker") == "true"
    marker_vid = h["x-amz-version-id"]
    status, _, _ = cli.request("GET", "/versioned/doc")
    assert status == 404  # latest is a delete marker -> NoSuchKey
    status, _, _ = cli.request("GET", "/versioned/doc",
                               query={"versionId": marker_vid})
    assert status == 405  # naming the marker itself -> MethodNotAllowed
    # delete specific old version
    status, _, _ = cli.request("DELETE", "/versioned/doc",
                               query={"versionId": v1})
    assert status == 204


def test_presigned_get(cli, srv):
    _mk(cli, "presign")
    cli.request("PUT", "/presign/o", body=b"presigned!")
    url = cli.presign("GET", "/presign/o")
    import http.client
    conn = http.client.HTTPConnection(srv.address, timeout=10)
    conn.request("GET", url)
    resp = conn.getresponse()
    assert resp.status == 200 and resp.read() == b"presigned!"
    conn.close()


def test_auth_failures(cli, srv):
    bad = S3Client(srv.address, secret_key="wrong-secret")
    status, _, body = bad.request("GET", "/")
    assert status == 403 and b"SignatureDoesNotMatch" in body
    unknown = S3Client(srv.address, access_key="nobody")
    status, _, body = unknown.request("GET", "/")
    assert status == 403 and b"InvalidAccessKeyId" in body
    status, _, body = cli.request("GET", "/", sign=False)
    assert status == 403


def test_object_name_validation(cli):
    _mk(cli, "names")
    status, _, _ = cli.request("PUT", "/names/a/../b", body=b"x")
    assert status == 400


def test_unconfigured_bucket_subresources(cli):
    _mk(cli, "subres")
    for q, code in (("policy", b"NoSuchBucketPolicy"),
                    ("lifecycle", b"NoSuchLifecycleConfiguration"),
                    ("tagging", b"NoSuchTagSet"),
                    ("encryption", b"ServerSideEncryption"),
                    ("replication", b"ReplicationConfiguration"),
                    ("cors", b"NoSuchCORSConfiguration")):
        status, _, body = cli.request("GET", "/subres", query={q: ""})
        assert status == 404 and code in body, (q, body)


def test_delimiter_pagination_terminates(cli):
    _mk(cli, "delpage")
    for k in ("a/1", "a/2", "b/1", "c", "d/9"):
        cli.request("PUT", f"/delpage/{k}", body=b"x")
    got_keys, got_prefixes, token, pages = [], [], None, 0
    while True:
        q = {"list-type": "2", "delimiter": "/", "max-keys": "1"}
        if token:
            q["continuation-token"] = token
        _, _, body = cli.request("GET", "/delpage", query=q)
        root = ET.fromstring(body)
        got_keys += [e.text for e in root.iter(f"{NS}Key")]
        got_prefixes += [e.findtext(f"{NS}Prefix")
                         for e in root.iter(f"{NS}CommonPrefixes")]
        pages += 1
        assert pages < 20, "pagination loop"
        if root.findtext(f"{NS}IsTruncated") != "true":
            break
        token = root.findtext(f"{NS}NextContinuationToken")
    assert got_keys == ["c"]
    assert got_prefixes == ["a/", "b/", "d/"]


def test_lexicographic_order_with_nested_siblings(cli):
    _mk(cli, "lexo")
    # 'data-1' sorts between object 'data' and nested key 'data/x'.
    for k in ("data", "data-1", "data/x"):
        cli.request("PUT", f"/lexo/{k}", body=b"x")
    _, _, body = cli.request("GET", "/lexo", query={"list-type": "2"})
    keys = [e.text for e in ET.fromstring(body).iter(f"{NS}Key")]
    assert keys == ["data", "data-1", "data/x"]
    # pagination across the boundary
    _, _, body = cli.request("GET", "/lexo",
                             query={"list-type": "2", "max-keys": "1"})
    root = ET.fromstring(body)
    token = root.findtext(f"{NS}NextContinuationToken")
    _, _, body = cli.request("GET", "/lexo",
                             query={"list-type": "2",
                                    "continuation-token": token})
    keys = [e.text for e in ET.fromstring(body).iter(f"{NS}Key")]
    assert keys == ["data-1", "data/x"]


def test_bucket_recreate_resets_versioning(cli):
    _mk(cli, "vreset")
    vcfg = (b'<VersioningConfiguration><Status>Enabled</Status>'
            b'</VersioningConfiguration>')
    cli.request("PUT", "/vreset", query={"versioning": ""}, body=vcfg)
    cli.request("DELETE", "/vreset")
    _mk(cli, "vreset")
    _, _, body = cli.request("GET", "/vreset", query={"versioning": ""})
    assert b"Enabled" not in body


def test_trailing_slash_and_empty_segment_rejected(cli):
    _mk(cli, "slashes")
    cli.request("PUT", "/slashes/x", body=b"1")
    status, _, _ = cli.request("PUT", "/slashes/x/", body=b"2")
    assert status == 400
    status, _, _ = cli.request("PUT", "/slashes/a//b", body=b"2")
    assert status == 400
    _, _, body = cli.request("GET", "/slashes/x")
    assert body == b"1"


def test_suffix_range_empty_object(cli):
    _mk(cli, "emptyrng")
    cli.request("PUT", "/emptyrng/e", body=b"")
    status, h, body = cli.request("GET", "/emptyrng/e",
                                  headers={"Range": "bytes=-100"})
    assert status == 200 and body == b"" and "Content-Range" not in h


def test_delimiter_prefix_visible_past_marker(cli):
    _mk(cli, "markerin")
    for k in ("a/1", "a/2", "b"):
        cli.request("PUT", f"/markerin/{k}", body=b"x")
    _, _, body = cli.request("GET", "/markerin",
                             query={"list-type": "2", "delimiter": "/",
                                    "start-after": "a/1"})
    root = ET.fromstring(body)
    prefixes = [e.findtext(f"{NS}Prefix") for e in root.iter(f"{NS}CommonPrefixes")]
    keys = [e.text for e in root.iter(f"{NS}Key")]
    assert prefixes == ["a/"] and keys == ["b"]


def test_listing_does_not_resurrect_deleted(cli, srv):
    _mk(cli, "resur")
    cli.request("PUT", "/resur/gone", body=b"x")
    # Simulate a drive missing the delete: delete only via quorum subset.
    ol = srv.object_layer
    real = ol.disks[0]

    class DeleteFails:
        def __getattr__(self, name):
            if name == "delete_version":
                def boom(*a, **k):
                    raise OSError("drive hiccup")
                return boom
            return getattr(real, name)
    ol.disks[0] = DeleteFails()
    status, _, _ = cli.request("DELETE", "/resur/gone")
    assert status == 204
    ol.disks[0] = real
    _, _, body = cli.request("GET", "/resur", query={"list-type": "2"})
    keys = [e.text for e in ET.fromstring(body).iter(f"{NS}Key")]
    assert "gone" not in keys
