"""S3 additional checksums: header + trailer declaration, verification
before commit, storage, checksum-mode retrieval, GetObjectAttributes
(reference: internal/hash/checksum.go, cmd/object-handlers.go)."""

import base64
import datetime
import hashlib
import hmac
import http.client
import struct
import zlib

import os

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3 import sigv4
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


def _crc32_b64(data: bytes) -> str:
    return base64.b64encode(struct.pack(">I", zlib.crc32(data))).decode()


def _sha256_b64(data: bytes) -> str:
    return base64.b64encode(hashlib.sha256(data).digest()).decode()


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ckdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    server = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv.address)
    assert c.request("PUT", "/ckbkt")[0] == 200
    return c


def test_header_checksum_verified_and_stored(cli):
    body = os.urandom(50_000)
    st, h, b = cli.request("PUT", "/ckbkt/good", body=body, headers={
        "x-amz-checksum-sha256": _sha256_b64(body)})
    assert st == 200, b
    assert h.get("x-amz-checksum-sha256") == _sha256_b64(body)
    # Returned only when the caller asks (AWS checksum-mode semantics).
    st, h, _ = cli.request("HEAD", "/ckbkt/good")
    assert "x-amz-checksum-sha256" not in h
    st, h, _ = cli.request("HEAD", "/ckbkt/good",
                           headers={"x-amz-checksum-mode": "ENABLED"})
    assert h.get("x-amz-checksum-sha256") == _sha256_b64(body)


def test_multiple_checksum_algorithms_rejected(cli):
    """S3 answers InvalidRequest when a request declares more than one
    checksum algorithm (advisor r4: verifying them all diverges from
    conformance-sensitive clients)."""
    body = b"two algos"
    st, _, b = cli.request("PUT", "/ckbkt/two", body=body, headers={
        "x-amz-checksum-crc32": _crc32_b64(body),
        "x-amz-checksum-sha256": _sha256_b64(body)})
    assert st == 400 and b"InvalidRequest" in b
    assert cli.request("GET", "/ckbkt/two")[0] == 404


def test_wrong_checksum_rejected_before_commit(cli):
    body = b"checksummed payload"
    st, _, b = cli.request("PUT", "/ckbkt/bad", body=body, headers={
        "x-amz-checksum-crc32": _crc32_b64(b"different")})
    assert st == 400 and b"XAmzContentChecksumMismatch" in b
    assert cli.request("GET", "/ckbkt/bad")[0] == 404
    # Unsupported algorithms are refused, never silently unverified.
    st, _, b = cli.request("PUT", "/ckbkt/bad", body=body, headers={
        "x-amz-checksum-crc32c": "AAAAAA=="})
    assert st == 501, b


def test_signed_trailer_roundtrip(cli):
    """STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER: signed data chunks,
    signed terminal 0-chunk, and an x-amz-trailer-signature over the
    trailer lines — all verified server-side."""
    body = os.urandom(100_000)
    trailer_val = _crc32_b64(body)
    st, h, b = cli.request(
        "PUT", "/ckbkt/signed-trailer", body=body, chunked=True,
        trailers={"x-amz-checksum-crc32": trailer_val})
    assert st == 200, b
    assert h.get("x-amz-checksum-crc32") == trailer_val
    st, _, got = cli.request("GET", "/ckbkt/signed-trailer")
    assert st == 200 and got == body


def test_signed_trailer_tamper_rejected(cli):
    """A wrong x-amz-trailer-signature fails the upload (advisor r4:
    unauthenticated trailers let an on-path attacker strip or swap the
    declared checksum)."""
    body = os.urandom(80_000)
    st, _, b = cli.request(
        "PUT", "/ckbkt/tampered-trailer", body=body, chunked=True,
        trailers={"x-amz-checksum-crc32": _crc32_b64(body)},
        corrupt_trailer_sig=True)
    assert st == 403 and b"SignatureDoesNotMatch" in b
    assert cli.request("GET", "/ckbkt/tampered-trailer")[0] == 404


def test_trailer_checksum_sdk_shape(srv):
    """The boto3-default upload shape: aws-chunked with an UNSIGNED
    payload trailer carrying x-amz-checksum-crc32."""
    body = os.urandom(150_000)
    trailer_val = _crc32_b64(body)
    chunks = bytearray()
    step = 64 * 1024
    for off in range(0, len(body), step):
        part = body[off:off + step]
        chunks += f"{len(part):x}\r\n".encode() + part + b"\r\n"
    chunks += b"0\r\n"
    chunks += f"x-amz-checksum-crc32:{trailer_val}\r\n\r\n".encode()

    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    scope = f"{amz_date[:8]}/us-east-1/s3/aws4_request"
    path = "/ckbkt/trailered"
    payload_hash = sigv4.STREAMING_UNSIGNED_TRAILER
    headers = {
        "host": srv.address,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "x-amz-decoded-content-length": str(len(body)),
        "x-amz-trailer": "x-amz-checksum-crc32",
        "content-encoding": "aws-chunked",
        "content-length": str(len(chunks)),
    }
    signed = sorted(headers)
    canon = sigv4.canonical_request("PUT", path, {}, headers, signed,
                                   payload_hash)
    sts = sigv4.string_to_sign(amz_date, scope, canon)
    key = sigv4.signing_key("minioadmin", amz_date[:8], "us-east-1")
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"{sigv4.ALGORITHM} Credential=minioadmin/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")

    conn = http.client.HTTPConnection(*srv.address.rsplit(":", 1),
                                      timeout=30)
    try:
        conn.request("PUT", path, body=bytes(chunks), headers=headers)
        r = conn.getresponse()
        resp = r.read()
        assert r.status == 200, resp
        assert r.headers.get("x-amz-checksum-crc32") == trailer_val
    finally:
        conn.close()
    cli = S3Client(srv.address)
    st, h, got = cli.request("GET", "/ckbkt/trailered",
                             headers={"x-amz-checksum-mode": "ENABLED"})
    assert st == 200 and got == body
    assert h.get("x-amz-checksum-crc32") == trailer_val


def test_get_object_attributes(cli):
    body = os.urandom(30_000)
    st, h, _ = cli.request("PUT", "/ckbkt/attrs", body=body, headers={
        "x-amz-checksum-sha256": _sha256_b64(body)})
    etag = h["ETag"].strip('"')
    st, _, xml = cli.request(
        "GET", "/ckbkt/attrs", query={"attributes": ""},
        headers={"x-amz-object-attributes":
                 "ETag,Checksum,ObjectSize,StorageClass"})
    assert st == 200, xml
    assert f"<ETag>{etag}</ETag>".encode() in xml
    assert f"<ObjectSize>{len(body)}</ObjectSize>".encode() in xml
    assert b"STANDARD" in xml
    assert _sha256_b64(body).encode() in xml
    # Missing the attribute list is a 400, not an empty answer.
    st, _, _ = cli.request("GET", "/ckbkt/attrs", query={"attributes": ""})
    assert st == 400


def test_zero_byte_trailer_upload(srv):
    """Regression: an EMPTY body with a checksum trailer (what modern
    SDKs send for zero-byte objects) must verify and commit — the
    trailer parse must run even though the payload never streams."""
    trailer_val = _crc32_b64(b"")
    chunks = b"0\r\n" + \
        f"x-amz-checksum-crc32:{trailer_val}\r\n\r\n".encode()
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    scope = f"{amz_date[:8]}/us-east-1/s3/aws4_request"
    path = "/ckbkt/empty"
    payload_hash = sigv4.STREAMING_UNSIGNED_TRAILER
    headers = {
        "host": srv.address, "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "x-amz-decoded-content-length": "0",
        "x-amz-trailer": "x-amz-checksum-crc32",
        "content-encoding": "aws-chunked",
        "content-length": str(len(chunks)),
    }
    signed = sorted(headers)
    canon = sigv4.canonical_request("PUT", path, {}, headers, signed,
                                   payload_hash)
    sts = sigv4.string_to_sign(amz_date, scope, canon)
    key = sigv4.signing_key("minioadmin", amz_date[:8], "us-east-1")
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"{sigv4.ALGORITHM} Credential=minioadmin/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    conn = http.client.HTTPConnection(*srv.address.rsplit(":", 1),
                                      timeout=30)
    try:
        conn.request("PUT", path, body=chunks, headers=headers)
        r = conn.getresponse()
        resp = r.read()
        assert r.status == 200, resp
    finally:
        conn.close()
    cli = S3Client(srv.address)
    st, h, got = cli.request("GET", "/ckbkt/empty",
                             headers={"x-amz-checksum-mode": "ENABLED"})
    assert st == 200 and got == b""
    assert h.get("x-amz-checksum-crc32") == trailer_val


def test_upload_part_checksum_verified(cli):
    st, _, body = cli.request("POST", "/ckbkt/mpc", query={"uploads": ""})
    assert st == 200
    uid = body.split(b"<UploadId>")[1].split(b"</UploadId>")[0].decode()
    part = os.urandom(100_000)
    st, h, b = cli.request("PUT", "/ckbkt/mpc",
                           query={"partNumber": "1", "uploadId": uid},
                           body=part,
                           headers={"x-amz-checksum-crc32":
                                    _crc32_b64(part)})
    assert st == 200, b
    assert h.get("x-amz-checksum-crc32") == _crc32_b64(part)
    st, _, b = cli.request("PUT", "/ckbkt/mpc",
                           query={"partNumber": "2", "uploadId": uid},
                           body=part,
                           headers={"x-amz-checksum-crc32":
                                    _crc32_b64(b"corrupt")})
    assert st == 400 and b"XAmzContentChecksumMismatch" in b
