"""Metacache: shared listing walk streams — one background walk per
(bucket, prefix) serves every page and every concurrent listing, with
generation invalidation on writes (reference: cmd/metacache.go,
cmd/metacache-set.go:700)."""

import os
import threading

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import DeleteOptions, ObjectNotFound
from minio_tpu.storage.local import LocalStorage


@pytest.fixture
def es(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    s = ErasureSet(disks)
    s.make_bucket("mcb")
    return s


def test_repeat_listing_hits_cache(es):
    for i in range(5):
        es.put_object("mcb", f"k{i}", b"x")
    first = es.list_objects("mcb", prefix="k")
    assert es.metacache.hits == 0
    again = es.list_objects("mcb", prefix="k")
    assert es.metacache.hits == 1
    assert [o.name for o in again.objects] == \
        [o.name for o in first.objects]
    # DIFFERENT page parameters of the same prefix share the walk too
    # (the whole point of walk streams vs page caching).
    es.list_objects("mcb", prefix="k", max_keys=2)
    assert es.metacache.hits == 2


def test_writes_invalidate_immediately(es):
    es.put_object("mcb", "a", b"1")
    assert [o.name for o in es.list_objects("mcb").objects] == ["a"]
    # A PUT after the cached page must be visible on the very next
    # listing — no TTL windows for same-process writes.
    es.put_object("mcb", "b", b"2")
    assert [o.name for o in es.list_objects("mcb").objects] == ["a", "b"]
    es.delete_object("mcb", "a", DeleteOptions())
    assert [o.name for o in es.list_objects("mcb").objects] == ["b"]
    # Metadata updates (tags show in some listings) invalidate too.
    es.list_objects("mcb")
    es.update_object_tags("mcb", "b", "", "team=x")
    hits_before = es.metacache.hits
    es.list_objects("mcb")
    assert es.metacache.hits == hits_before  # miss: page recomputed


def test_multipart_and_bucket_delete_invalidate(es, tmp_path):
    uid = es.new_multipart_upload("mcb", "mp")
    es.list_objects("mcb")                       # prime the cache
    e1 = es.put_object_part("mcb", "mp", uid, 1, os.urandom(1000)).etag
    es.complete_multipart_upload("mcb", "mp", uid, [(1, e1)])
    assert "mp" in [o.name for o in es.list_objects("mcb").objects]
    es.delete_object("mcb", "mp", DeleteOptions())
    es.delete_bucket("mcb")
    with pytest.raises(Exception):
        es.list_objects("mcb")


def _counting(disks):
    """Wrap drives so walk invocations (either primitive) are counted."""
    counter = {"walks": 0}

    class W:
        def __init__(self, inner):
            self._inner = inner

        def walk_dir(self, *a, **k):
            counter["walks"] += 1
            return self._inner.walk_dir(*a, **k)

        def walk_scan(self, *a, **k):
            counter["walks"] += 1
            return self._inner.walk_scan(*a, **k)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    return [W(d) for d in disks], counter


def test_large_bucket_pages_without_rewalking(tmp_path):
    """A multi-page listing of a big bucket drives ONE walk of the
    drives, not one per page (reference: metacache streams shared
    across pages, cmd/metacache-set.go:700)."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("big")
    for i in range(0, 5000, 100):
        # Seed sparse then fill with cheap empty objects for speed.
        pass
    for i in range(2000):
        es.put_object("big", f"o{i:05d}", b"")
    wrapped, counter = _counting(es.disks)
    es.disks[:] = wrapped
    names = []
    marker = ""
    pages = 0
    while True:
        page = es.list_objects("big", marker=marker, max_keys=100)
        names.extend(o.name for o in page.objects)
        pages += 1
        if not page.is_truncated:
            break
        marker = page.next_marker
    assert pages >= 20
    assert names == [f"o{i:05d}" for i in range(2000)]
    # One walk = one walk_dir per walked drive (majority of 4 = 3).
    assert counter["walks"] <= 3, counter


def test_concurrent_listings_share_one_walk(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("cc")
    for i in range(500):
        es.put_object("cc", f"k{i:04d}", b"")
    wrapped, counter = _counting(es.disks)
    es.disks[:] = wrapped
    results = [None] * 6
    def worker(i):
        results[i] = [o.name for o in
                      es.list_objects("cc", max_keys=1000).objects]
    ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20)
    want = [f"k{i:04d}" for i in range(500)]
    assert all(r == want for r in results)
    assert counter["walks"] <= 3, counter


def test_peer_bump_invalidates_other_nodes_walk(tmp_path):
    """Two 'nodes' over the same drives: after node A writes, node B's
    very next listing reflects it — A's metacache bump rides the peer
    hook to B (no TTL window). The hook here is wired directly; in
    production it is the grid KIND_LISTING broadcast."""
    mk = lambda: ErasureSet(  # noqa: E731
        [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)])
    a, b = mk(), mk()
    a.make_bucket("xn")
    a.put_object("xn", "one", b"1")
    # B warms a walk stream.
    assert [o.name for o in b.list_objects("xn").objects] == ["one"]
    # Wire A's bump broadcast to B (leading-edge coalesced).
    a.metacache.on_bump = lambda bucket: b.metacache.bump(
        bucket, broadcast=False)
    a.put_object("xn", "two", b"2")
    assert [o.name for o in b.list_objects("xn").objects] == \
        ["one", "two"]
    # A rapid follow-up mutation coalesces into a guaranteed TRAILING
    # broadcast (<= the 100 ms window), so B converges promptly even
    # mid-burst.
    import time
    a.delete_object("xn", "one", DeleteOptions())
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline:
        if [o.name for o in b.list_objects("xn").objects] == ["two"]:
            break
        time.sleep(0.02)
    assert [o.name for o in b.list_objects("xn").objects] == ["two"]


def test_continuation_past_truncation_cap(tmp_path, monkeypatch):
    """Pagination must keep progressing past a stream's in-memory cap:
    pages beyond it ride start-floored continuation walks, and every
    key surfaces exactly once."""
    from minio_tpu.object import metacache
    monkeypatch.setattr(metacache, "_MAX_ENTRIES", 120)
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    try:
        es.make_bucket("cap")
        for i in range(300):
            es.put_object("cap", f"o{i:05d}", b"")
        names, marker, pages = [], "", 0
        while True:
            page = es.list_objects("cap", marker=marker, max_keys=50)
            names.extend(o.name for o in page.objects)
            pages += 1
            assert pages < 50
            if not page.is_truncated:
                break
            marker = page.next_marker
        assert names == [f"o{i:05d}" for i in range(300)]
    finally:
        es.close()


def _shm_root(tmp_path, need_bytes):
    """A namespace root on /dev/shm (high-cardinality fixtures measure
    syscalls, and overlay /tmp mounts are pathologically slow), or None
    to skip."""
    import tempfile
    try:
        st = os.statvfs("/dev/shm")
        if st.f_bavail * st.f_frsize < need_bytes:
            return None
    except OSError:
        return None
    return tempfile.mkdtemp(prefix="mtpu-nstest-", dir="/dev/shm")


def test_persisted_seek_and_warm_start_50k(monkeypatch, tmp_path):
    """High-cardinality warm start: a fresh process's first listing —
    and a deep continuation page — load persisted segments (seeking
    past the marker's segment) instead of re-walking 50k keys."""
    import shutil
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from scripts.namespace_gen import attach, generate

    from minio_tpu.object import metacache
    root = _shm_root(tmp_path, 2 << 30)
    if root is None:
        pytest.skip("no /dev/shm capacity for the 50k fixture")
    monkeypatch.setattr(metacache, "_PERSIST_TTL", 600.0)
    try:
        # workers=1: forking under a JAX-loaded pytest process risks
        # deadlock (os.fork + threads); serial fabrication is ~12 s.
        generate(root, 50_000, drives=1, profile="flat", workers=1)
        es = attach(root, 1)
        marker = ""
        while True:
            page = es.list_objects("ns", prefix="flat/", marker=marker,
                                   max_keys=1000)
            if not page.is_truncated:
                break
            marker = page.next_marker
        es.close()

        # Fresh process, first page: served from persisted segments.
        es2 = attach(root, 1)
        wrapped, counter = _counting(es2.disks)
        es2.disks[:] = wrapped
        page = es2.list_objects("ns", prefix="flat/", max_keys=1000)
        assert [o.name for o in page.objects] == \
            [f"flat/o{i:08d}" for i in range(1000)]
        assert counter["walks"] == 0, counter
        assert es2.metacache.persisted_loads == 1
        es2.close()

        # Fresh process, DEEP continuation page: the segment index
        # seeks — only the tail segments load, still zero drive walks.
        es3 = attach(root, 1)
        wrapped, counter = _counting(es3.disks)
        es3.disks[:] = wrapped
        deep_marker = f"flat/o{40_000 - 1:08d}"
        page = es3.list_objects("ns", prefix="flat/",
                                marker=deep_marker, max_keys=1000)
        assert [o.name for o in page.objects] == \
            [f"flat/o{i:08d}" for i in range(40_000, 41_000)]
        assert counter["walks"] == 0, counter
        assert es3.metacache.persisted_loads == 1
        w = next(iter(es3.metacache._walks.values()))
        assert w.persisted_from > 0, "seek should skip whole segments"
        es3.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)


def test_truncated_walk_compacts_in_place(tmp_path, monkeypatch):
    """A truncated persisted run + its continuation walks compact into
    ONE segment run: a fresh process then serves the whole range from
    segments, past the original cap."""
    from minio_tpu.object import metacache
    monkeypatch.setattr(metacache, "_MAX_ENTRIES", 100)
    monkeypatch.setattr(metacache, "_SEG", 40)
    monkeypatch.setattr(metacache, "_PERSIST_TTL", 600.0)
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    try:
        es.make_bucket("cp")
        for i in range(250):
            es.put_object("cp", f"o{i:05d}", b"")
        # Reset generation so walks persist under gen 0 semantics.
        es.metacache._gen.clear()
        names, marker = [], ""
        while True:
            page = es.list_objects("cp", marker=marker, max_keys=50)
            names.extend(o.name for o in page.objects)
            if not page.is_truncated:
                break
            marker = page.next_marker
        assert names == [f"o{i:05d}" for i in range(250)]
        # Wait for the persisted run to be COMPLETE, not merely for the
        # first compaction: continuation walks compact in COMPLETION
        # order, and one floored past the base's current end waits out
        # a bounded gap-retry until the earlier continuation bridges it
        # (WalkStream._compact_onto) — so full convergence is async.
        import json as _json
        import time as _t
        base = metacache.WalkStream._dir("cp", "")
        deadline = _t.monotonic() + 20
        head = {}
        while _t.monotonic() < deadline:
            try:
                head = _json.loads(
                    disks[0].read_all(".mtpu.sys", f"{base}/head"))
                if head.get("count") == 250 and not head.get("truncated"):
                    break
            except Exception:  # noqa: BLE001 - base not persisted yet
                pass
            _t.sleep(0.05)
        assert head.get("count") == 250 and not head.get("truncated"), head
        assert es.metacache.compactions >= 1
    finally:
        es.close()

    # Fresh process: the compacted run serves EVERYTHING, no walks.
    es2 = ErasureSet([LocalStorage(str(tmp_path / f"d{i}"))
                      for i in range(4)])
    try:
        wrapped, counter = _counting(es2.disks)
        es2.disks[:] = wrapped
        names, marker = [], ""
        while True:
            page = es2.list_objects("cp", marker=marker, max_keys=50)
            names.extend(o.name for o in page.objects)
            if not page.is_truncated:
                break
            marker = page.next_marker
        assert names == [f"o{i:05d}" for i in range(250)]
        assert counter["walks"] == 0, counter
        assert es2.metacache.persisted_loads >= 1
    finally:
        es2.close()


@pytest.mark.slow
def test_meta_10m_sweep(tmp_path):
    """Full-cardinality sweep (10M objects by default; scale with
    MTPU_SLOW_NS_OBJECTS): fabricate the namespace on /dev/shm, then
    prove listing and HEAD correctness at depth — first pages at cold
    prefixes, deep continuation, HEAD sampling."""
    import shutil
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from scripts.namespace_gen import attach, generate, key_at

    objects = int(os.environ.get("MTPU_SLOW_NS_OBJECTS", "10000000"))
    root = os.environ.get("MTPU_META_NS_ROOT", "")
    built = False
    if not root:
        root = _shm_root(tmp_path, objects * 6144 + (1 << 30))
        if root is None:
            pytest.skip("no /dev/shm capacity for the slow sweep")
        generate(root, objects, drives=1)
        built = True
    es = attach(root, 1)
    try:
        for pfx in ("kv/a0/", "kv/ff/", "deep/0/1/"):
            es.metacache.bump("ns")
            page = es.list_objects("ns", prefix=pfx, max_keys=1000)
            assert page.objects
            got = [o.name for o in page.objects]
            assert got == sorted(got)
            assert all(o.name.startswith(pfx) for o in page.objects)
        # HEAD sample across the namespace.
        stride = max(1, objects // 500)
        for i in range(0, objects, stride):
            info = es.get_object_info("ns", key_at(i, objects))
            assert info.size == 128
    finally:
        es.close()
        if built:
            shutil.rmtree(root, ignore_errors=True)


def test_persisted_walk_warm_starts_fresh_process(tmp_path):
    """A restarted process's first listing of a quiet bucket loads the
    previous run's persisted walk blocks instead of re-walking."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("pp")
    for i in range(50):
        es.put_object("pp", f"k{i:03d}", b"")
    es.list_objects("pp")                       # walk + persist
    # wait for the background persist
    import time
    for _ in range(100):
        try:
            disks[0].read_all(".mtpu.sys", "listcache/" +
                              __import__("minio_tpu.object.metacache",
                                         fromlist=["_safe"])
                              ._safe("pp") + "/" +
                              __import__("minio_tpu.object.metacache",
                                         fromlist=["_safe"])._safe("") +
                              "/head")
            break
        except Exception:
            time.sleep(0.05)
    # "Restart": a new set object over the same drives.
    es2 = ErasureSet([LocalStorage(str(tmp_path / f"d{i}"))
                      for i in range(4)])
    wrapped, counter = _counting(es2.disks)
    es2.disks[:] = wrapped
    names = [o.name for o in es2.list_objects("pp", max_keys=1000).objects]
    assert names == [f"k{i:03d}" for i in range(50)]
    assert counter["walks"] == 0, counter        # served from blocks
