"""Metacache: listing pages served from cache on quiet buckets, every
write invalidating instantly (reference: cmd/metacache.go, scoped to a
generation-stamped page cache)."""

import os

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import DeleteOptions, ObjectNotFound
from minio_tpu.storage.local import LocalStorage


@pytest.fixture
def es(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    s = ErasureSet(disks)
    s.make_bucket("mcb")
    return s


def test_repeat_listing_hits_cache(es):
    for i in range(5):
        es.put_object("mcb", f"k{i}", b"x")
    first = es.list_objects("mcb", prefix="k")
    assert es.metacache.hits == 0
    again = es.list_objects("mcb", prefix="k")
    assert es.metacache.hits == 1
    assert [o.name for o in again.objects] == \
        [o.name for o in first.objects]
    # Different parameters are different pages.
    es.list_objects("mcb", prefix="k", max_keys=2)
    assert es.metacache.hits == 1


def test_writes_invalidate_immediately(es):
    es.put_object("mcb", "a", b"1")
    assert [o.name for o in es.list_objects("mcb").objects] == ["a"]
    # A PUT after the cached page must be visible on the very next
    # listing — no TTL windows for same-process writes.
    es.put_object("mcb", "b", b"2")
    assert [o.name for o in es.list_objects("mcb").objects] == ["a", "b"]
    es.delete_object("mcb", "a", DeleteOptions())
    assert [o.name for o in es.list_objects("mcb").objects] == ["b"]
    # Metadata updates (tags show in some listings) invalidate too.
    es.list_objects("mcb")
    es.update_object_tags("mcb", "b", "", "team=x")
    hits_before = es.metacache.hits
    es.list_objects("mcb")
    assert es.metacache.hits == hits_before  # miss: page recomputed


def test_multipart_and_bucket_delete_invalidate(es, tmp_path):
    uid = es.new_multipart_upload("mcb", "mp")
    es.list_objects("mcb")                       # prime the cache
    e1 = es.put_object_part("mcb", "mp", uid, 1, os.urandom(1000)).etag
    es.complete_multipart_upload("mcb", "mp", uid, [(1, e1)])
    assert "mp" in [o.name for o in es.list_objects("mcb").objects]
    es.delete_object("mcb", "mp", DeleteOptions())
    es.delete_bucket("mcb")
    with pytest.raises(Exception):
        es.list_objects("mcb")
