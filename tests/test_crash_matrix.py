"""Crash-point matrix: power-cut consistency of every commit path.

For each op (PUT single-part / inline / multipart, DELETE, heal
commit) the harness sweeps the shared CrashClock over every mutation
sub-step the op performs across all drives (storage/crashdisk.CrashDisk
— the node loses power at sub-step N, the in-flight write is dropped or
torn, every later call fails). After each cut the drives are
"remounted": fresh LocalStorage instances, the mount-time recovery
sweep (storage/local.recovery_sweep), then the invariant is asserted:

  * the object reads back as either the COMPLETE old or the COMPLETE
    new version — never torn bytes, never a quorum hole;
  * when the op RETURNED success before the cut (quorum committed),
    the new version is what reads back — an acknowledged write
    survives (drop/tear modes; lose_entry models a non-journaling fs
    without directory fsync, where MTPU_FS_OSYNC is required for that
    guarantee, so it asserts consistency only);
  * healing converges: after the swept repairs + a heal pass the
    answer is unchanged, and no staging/tmp garbage survives.

The full matrix is `slow` (scripts/verify.sh runs it under
MTPU_CRASH_SWEEP=1); a cheap smoke subset stays in tier-1.
"""

from __future__ import annotations

import os
import shutil

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import ObjectNotFound, PutOptions
from minio_tpu.storage.crashdisk import CrashClock, CrashDisk
from minio_tpu.storage.local import SYS_VOL, LocalStorage, recovery_sweep

N = 4
BKT = "bkt"
KEY = "obj"

OLD = os.urandom(300 * 1024 + 17)        # single-part, non-inline
NEW = os.urandom(310 * 1024 + 5)
OLD_INLINE = os.urandom(9_000)           # inlines into xl.meta
NEW_INLINE = os.urandom(9_100)


def _mkset(root, wrap=None):
    disks = [LocalStorage(str(root / f"d{i}")) for i in range(N)]
    if wrap is not None:
        disks = [wrap(d) for d in disks]
    return ErasureSet(disks)


def _get(es, key=KEY):
    try:
        _, data = es.get_object(BKT, key)
        return data
    except ObjectNotFound:
        return None


def crash_sweep(tmp_path, mode, setup, op, check, max_points=400):
    """Walk crash points 1..completion of `op`; assert `check` after
    every cut, pre- and post-heal. Returns the op's sub-step count."""
    n = 1
    while n <= max_points:
        root = tmp_path / f"{mode}-{n}"
        es = _mkset(root)
        es.make_bucket(BKT)
        ctx = setup(es) or {}
        es.close()

        clock = CrashClock(crash_at=n)
        es2 = _mkset(root, wrap=lambda d: CrashDisk(d, clock, mode))
        completed, err = False, None
        try:
            op(es2, ctx)
            completed = True
        except Exception as e:  # noqa: BLE001 - PowerCut/quorum faults
            err = e
        es2.close()
        if not clock.fired:
            assert completed, f"op failed without a crash: {err!r}"

        # "Reboot": remount fresh drives, run the recovery sweep.
        heal: list = []
        for i in range(N):
            rep = recovery_sweep(LocalStorage(str(root / f"d{i}")),
                                 min_age=0)
            heal.extend(rep["heal"])
        es3 = _mkset(root)
        try:
            # Group-commit WALs never survive recovery: replayed (and
            # removed) by the sweep, so remount starts clean.
            for i in range(N):
                gdir = root / f"d{i}" / SYS_VOL / "gcommit"
                leftover = [n for n in
                            (os.listdir(gdir) if gdir.is_dir() else [])
                            if os.path.getsize(gdir / n) > 0]
                assert leftover == [], \
                    f"live WAL frames survived recovery in d{i}"
            check(es3, ctx, completed)
            # Convergence: repair what the sweep reported plus the key
            # itself (the MRF would), then the answer must not move.
            for vol, path in set(heal) | {(BKT, KEY)}:
                try:
                    es3.heal_object(vol, path)
                except Exception:  # noqa: BLE001 - not-found etc.
                    pass
            check(es3, ctx, completed)
            # Degraded reads enqueue MRF repairs whose staged writes
            # pass through tmp/: quiesce before asserting emptiness.
            if es3._mrf is not None:
                es3._mrf.drain(15)
                es3._mrf.stop()
            for i in range(N):
                for sub in ("tmp", "staging"):
                    p = root / f"d{i}" / SYS_VOL / sub
                    assert not os.path.isdir(p) or os.listdir(p) == [], \
                        f"crash garbage survived the sweep in d{i}/{sub}"
        finally:
            es3.close()
        shutil.rmtree(root, ignore_errors=True)
        if not clock.fired:
            return n - 1
        n += 1
    raise AssertionError(f"op never completed within {max_points} points")


# -- the ops ----------------------------------------------------------------

def _setup_none(es):
    return {}


def _setup_old(es):
    es.put_object(BKT, KEY, OLD)
    return {"old": OLD}


def _setup_old_inline(es):
    es.put_object(BKT, KEY, OLD_INLINE)
    return {"old": OLD_INLINE}


def _setup_heal(es):
    es.put_object(BKT, KEY, OLD)
    root = getattr(es.disks[1], "root")
    shutil.rmtree(os.path.join(root, BKT, KEY))
    return {"old": OLD}


def _op_put(new):
    def op(es, ctx):
        es.put_object(BKT, KEY, new)
    return op


def _op_multipart(es, ctx):
    uid = es.new_multipart_upload(BKT, KEY, PutOptions())
    part = es.put_object_part(BKT, KEY, uid, 1, NEW)
    es.complete_multipart_upload(BKT, KEY, uid, [(1, part.etag)])


def _op_delete(es, ctx):
    es.delete_object(BKT, KEY)


def _op_heal(es, ctx):
    es.heal_object(BKT, KEY)


def _check_versions(new, durable=True, deletable=False):
    def check(es, ctx, completed):
        got = _get(es)
        allowed = {id(x): x for x in (ctx.get("old"), new) if x is not None}
        if completed and durable and new is not None:
            assert got == new, "acknowledged write did not survive"
        elif completed and durable and deletable:
            assert got is None, "acknowledged delete resurrected"
        else:
            ok = got is None if (ctx.get("old") is None or deletable) \
                else False
            assert ok or any(got == x for x in allowed.values()), \
                "torn read: neither the old nor the new version"
    return check


# -- tier-1 smoke (cheap subset) --------------------------------------------

def test_crash_smoke_inline_overwrite(tmp_path):
    steps = crash_sweep(tmp_path, "drop", _setup_old_inline,
                        _op_put(NEW_INLINE), _check_versions(NEW_INLINE))
    assert steps >= N    # every drive's journal commit was walked


def test_crash_smoke_delete(tmp_path):
    steps = crash_sweep(
        tmp_path, "drop", _setup_old, _op_delete,
        _check_versions(None, deletable=True))
    assert steps >= N


# -- the full matrix (slow; MTPU_CRASH_SWEEP=1 stage of verify.sh) ----------

@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_put_fresh(tmp_path, mode):
    crash_sweep(tmp_path, mode, _setup_none, _op_put(NEW),
                _check_versions(NEW))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_put_overwrite(tmp_path, mode):
    crash_sweep(tmp_path, mode, _setup_old, _op_put(NEW),
                _check_versions(NEW))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_put_inline(tmp_path, mode):
    crash_sweep(tmp_path, mode, _setup_old_inline, _op_put(NEW_INLINE),
                _check_versions(NEW_INLINE))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_multipart(tmp_path, mode):
    crash_sweep(tmp_path, mode, _setup_old, _op_multipart,
                _check_versions(NEW))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_delete(tmp_path, mode):
    crash_sweep(tmp_path, mode, _setup_old, _op_delete,
                _check_versions(None, deletable=True))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_heal_commit(tmp_path, mode):
    # Healing must never make things worse: the old version is the only
    # acceptable answer at every crash point of the heal's own commit.
    def check(es, ctx, completed):
        assert _get(es) == ctx["old"], "heal commit tore the object"
    crash_sweep(tmp_path, mode, _setup_heal, _op_heal, check)


# -- group-commit sub-steps (storage/group_commit lanes) --------------------
# The batched commit has its own composite sub-steps: per-member data
# moves, the multi-object WAL append, each destination's journal
# rename, and the checkpoint's sync. Two shapes sweep them:
#   * the LANE shape — a real put_object forced through the group path
#     (commit_fanout -> dispatcher -> engine -> CrashDisk.commit_group);
#   * the MULTI-OBJECT shape — one commit_group batch per drive
#     carrying an overwrite of KEY plus a fresh KEY2, so cuts land
#     before/inside/after the batched rename SEQUENCE and on the torn
#     multi-object WAL frame.

KEY2 = "obj2"


def _op_group_put(new):
    def op(es, ctx):
        assert es.group_commit is not None, "group lanes not wired"
        es.group_commit.worth_batching = lambda: True
        es.put_object(BKT, KEY, new)
    return op


def _donor_fis(es, key, data):
    """Per-drive FileInfos (with each drive's own framed inline shard)
    for `data`, fabricated by a real PUT of a donor key then retargeted
    — exactly the version maps a group batch would commit."""
    import dataclasses
    es.put_object(BKT, key, data)
    fis = []
    for d in es.disks:
        fi = d.read_version(BKT, key, read_data=True)
        fis.append(dataclasses.replace(fi))
    return fis


def _setup_group_multi(es):
    es.put_object(BKT, KEY, OLD_INLINE)
    new_fis = _donor_fis(es, "donor-a", NEW_INLINE)
    k2_fis = _donor_fis(es, "donor-b", NEW_INLINE)
    # The donors themselves are deleted so the namespace holds only the
    # keys the invariant checks.
    es.delete_object(BKT, "donor-a")
    es.delete_object(BKT, "donor-b")
    return {"old": OLD_INLINE, "new_fis": new_fis, "k2_fis": k2_fis}


def _op_group_multi(es, ctx):
    """One multi-object commit_group batch per drive: overwrite KEY +
    fresh KEY2 — the exact batch shape the lanes dispatch."""
    import dataclasses

    from minio_tpu.storage.group_commit import GroupOp
    for i, d in enumerate(es.disks):
        fi_new = dataclasses.replace(ctx["new_fis"][i])
        fi_new.name = KEY
        fi_k2 = dataclasses.replace(ctx["k2_fis"][i])
        fi_k2.name = KEY2
        res = d.commit_group([GroupOp.write_meta(BKT, KEY, fi_new),
                              GroupOp.write_meta(BKT, KEY2, fi_k2)])
        for e in res:
            if e is not None:
                raise e


def _check_group_multi(es, ctx, completed):
    got = _get(es)
    if completed:
        assert got == NEW_INLINE, "acknowledged batch overwrite lost"
        assert _get(es, KEY2) == NEW_INLINE, \
            "acknowledged batch fresh key lost"
    else:
        assert got in (ctx["old"], NEW_INLINE), \
            "torn read: neither old nor new after batched commit cut"
        assert _get(es, KEY2) in (None, NEW_INLINE), \
            "torn fresh key after batched commit cut"


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_group_put_inline(tmp_path, mode):
    # A real PUT through the lanes: power cut before/inside/after the
    # WAL append and the journal writes on every drive.
    crash_sweep(tmp_path, mode, _setup_old_inline,
                _op_group_put(NEW_INLINE), _check_versions(NEW_INLINE))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_group_multi_object(tmp_path, mode):
    # Multi-object batches: cuts land before/inside/after the batched
    # rename sequence and on a torn multi-object WAL frame (tear).
    crash_sweep(tmp_path, mode, _setup_group_multi, _op_group_multi,
                _check_group_multi)


@pytest.mark.slow
def test_crash_matrix_group_lose_entry_partial_batch(tmp_path):
    # Non-journaling fs without dir fsync: a partial batch may lose
    # renames AND the WAL file's own dir entry — consistency
    # (old-or-new per object) must hold; durability is the documented
    # MTPU_FS_OSYNC exception, so it is NOT asserted.
    def check(es, ctx, completed):
        assert _get(es) in (ctx["old"], NEW_INLINE)
        assert _get(es, KEY2) in (None, NEW_INLINE)
    crash_sweep(tmp_path, "lose_entry", _setup_group_multi,
                _op_group_multi, check)


# -- tier-1 smoke for the group path ----------------------------------------

def test_crash_smoke_group_commit(tmp_path):
    steps = crash_sweep(tmp_path, "drop", _setup_group_multi,
                        _op_group_multi, _check_group_multi,
                        max_points=200)
    # Each drive's batch: WAL append + 2 journal renames = 3 sub-steps.
    assert steps >= 3 * N


# -- pool migration (object/decom.migrate_key) ------------------------------
# The elastic-fleet transfer primitive: snapshot the source stack,
# restore every version into the destination pool, bump the coherence
# generation, then verify + delete the source copies under the key
# lock. The sweep cuts power at EVERY durable sub-step of that chain
# (snapshot reads don't tick; restore writes, journal commits and the
# source deletes all do) and asserts the object is never lost, never
# torn, and never doubly-visible — then that re-running the migration
# (the checkpointed resume path) converges: source empty, destination
# complete, byte-identical.

MIG_DEP = "00000000-0000-0000-0000-000000000e1a"
MIG_V1 = os.urandom(11_000)


def _mk_layer(root, wrap=None):
    """Two-pool ServerPools (src=pool0, dst=pool1) over one shared
    clock; a fixed deployment id keeps key->set routing stable across
    remounts."""
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    pools = []
    for p in ("src", "dst"):
        disks = [LocalStorage(str(root / p / f"d{i}")) for i in range(N)]
        if wrap is not None:
            disks = [wrap(d) for d in disks]
        pools.append(ErasureSets([ErasureSet(disks)],
                                 deployment_id=MIG_DEP))
    return ServerPools(pools)


def migrate_sweep(tmp_path, mode, versioned=False, max_points=400):
    """Crash sweep over migrate_key. Invariants at every cut, before
    and after healing: the key reads back byte-identical (the source
    pool is marked draining — persisted decom state survives the crash
    in the real flow — so reads resolve destination-first, and the
    destination holds the full stack before any source delete runs);
    listings show each (key, version) exactly once; the resumed
    migration converges to source-empty with nothing lost.

    lose_entry (non-journaling fs, no directory fsync — the documented
    MTPU_FS_OSYNC exception) keeps the torn/doubly-visible asserts but
    not the durability one: the destination commit's directory entry
    can be voided by the cut while later source deletes survive, so
    the key may legitimately read back absent."""
    from minio_tpu.object import decom
    strict = mode != "lose_entry"
    n = 1
    while n <= max_points:
        root = tmp_path / f"mig-{mode}-{n}"
        lay = _mk_layer(root)
        lay.make_bucket(BKT)
        if versioned:
            lay.pools[0].put_object(BKT, KEY, MIG_V1,
                                    PutOptions(versioned=True))
            lay.pools[0].put_object(BKT, KEY, OLD,
                                    PutOptions(versioned=True))
        else:
            lay.pools[0].put_object(BKT, KEY, OLD)
        lay.close()

        clock = CrashClock(crash_at=n)
        lay2 = _mk_layer(root, wrap=lambda d: CrashDisk(d, clock, mode))
        lay2.decommissioning.add(0)
        completed, err = False, None
        try:
            decom.migrate_key(lay2, 0, BKT, KEY, lambda: 1)
            completed = True
        except Exception as e:  # noqa: BLE001 - PowerCut/quorum faults
            err = e
        lay2.close()
        if not clock.fired:
            assert completed, f"migrate failed without a crash: {err!r}"
        where = f"cut@{n} in {clock.fired_op or 'n/a'}"

        # "Reboot": remount both pools fresh + recovery sweep.
        for p in ("src", "dst"):
            for i in range(N):
                recovery_sweep(LocalStorage(str(root / p / f"d{i}")),
                               min_age=0)
        lay3 = _mk_layer(root)
        lay3.decommissioning.add(0)
        try:
            nvers = 2 if versioned else 1

            def check():
                try:
                    _, got = lay3.get_object(BKT, KEY)
                except ObjectNotFound:
                    got = None
                if got is not None:
                    assert got == OLD, f"{where}: object torn"
                else:
                    assert not strict, f"{where}: object lost"
                page = lay3.list_objects(BKT, max_keys=10,
                                         include_versions=True)
                vkeys = [(o.name, o.version_id) for o in page.objects]
                assert len(vkeys) == len(set(vkeys)), \
                    f"{where}: doubly visible: {vkeys}"
                if strict:
                    assert len(vkeys) == nvers, f"{where}: {vkeys}"
                if versioned and strict:
                    from minio_tpu.object.types import GetOptions
                    oldest = min(page.objects, key=lambda o: o.mod_time)
                    _, v1 = lay3.get_object(
                        BKT, KEY, GetOptions(version_id=oldest.version_id))
                    assert v1 == MIG_V1, f"{where}: old version torn"

            check()
            for pool in lay3.pools:
                try:
                    pool.heal_object(BKT, KEY)
                except Exception:  # noqa: BLE001 - pool without the key
                    pass
            check()
            # The checkpointed resume: re-running the idempotent
            # migrate must converge (source empty, nothing lost).
            decom.migrate_key(lay3, 0, BKT, KEY, lambda: 1)
            check()
            src_page = lay3.pools[0].list_objects(
                BKT, max_keys=10, include_versions=True)
            assert not src_page.objects, \
                f"{where}: source copies survived the resumed migrate"
            for pool in lay3.pools:
                pool.drain_mrf(15)
            for p in ("src", "dst"):
                for i in range(N):
                    for sub in ("tmp", "staging"):
                        pth = root / p / f"d{i}" / SYS_VOL / sub
                        assert not os.path.isdir(pth) or \
                            os.listdir(pth) == [], \
                            f"{where}: crash garbage in {p}/d{i}/{sub}"
        finally:
            lay3.close()
        shutil.rmtree(root, ignore_errors=True)
        if not clock.fired:
            return n - 1
        n += 1
    raise AssertionError(f"migrate never completed in {max_points} points")


def test_crash_smoke_migrate_key(tmp_path):
    steps = migrate_sweep(tmp_path, "drop")
    # At minimum: per-dst-drive restore commit + per-src-drive delete.
    assert steps >= 2 * N


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_migrate_key(tmp_path, mode):
    migrate_sweep(tmp_path, mode)


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["drop", "tear"])
def test_crash_matrix_migrate_key_versioned(tmp_path, mode):
    migrate_sweep(tmp_path, mode, versioned=True)


@pytest.mark.slow
def test_crash_matrix_migrate_key_lose_entry(tmp_path):
    migrate_sweep(tmp_path, "lose_entry")


@pytest.mark.slow
def test_crash_matrix_lost_dir_entries(tmp_path):
    # Non-journaling fs without dir fsync (MTPU_FS_OSYNC off): the last
    # un-synced rename may vanish. Consistency (old-or-new) must hold;
    # durability of a quorum-acked write legitimately needs FS_OSYNC,
    # so it is NOT asserted here.
    crash_sweep(tmp_path, "lose_entry", _setup_old, _op_put(NEW),
                _check_versions(NEW, durable=False))
    crash_sweep(tmp_path, "lose_entry", _setup_old_inline,
                _op_put(NEW_INLINE),
                _check_versions(NEW_INLINE, durable=False))
