"""Bucket-policy evaluation: Condition operators, Principal matching,
anonymous access, and deny-wins merge with IAM identities (reference:
cmd/auth-handler.go:433-449,758, internal/policy/condition/)."""

import http.client
import json

import pytest

from minio_tpu.iam import IAMSys, Policy, evaluate
from minio_tpu.iam.policy import PolicyError, decide
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import Credentials, S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


# ---------------------------------------------------------------------------
# engine: conditions, principals, tri-state decide
# ---------------------------------------------------------------------------

def _pol(effect, actions, resources, condition=None, principal=None):
    s = {"Effect": effect, "Action": actions, "Resource": resources}
    if condition:
        s["Condition"] = condition
    if principal is not None:
        s["Principal"] = principal
    return Policy.from_json({"Statement": [s]})


def test_condition_string_equals_and_like():
    p = _pol("Allow", ["s3:ListBucket"], ["data"],
             condition={"StringEquals": {"s3:prefix": ["app/"]}})
    assert evaluate([p], "s3:ListBucket", "data", {"s3:prefix": "app/"})
    assert not evaluate([p], "s3:ListBucket", "data", {"s3:prefix": "x/"})
    # Absent key fails a positive operator.
    assert not evaluate([p], "s3:ListBucket", "data", {})
    like = _pol("Allow", ["s3:ListBucket"], ["data"],
                condition={"StringLike": {"s3:prefix": ["app/*"]}})
    assert evaluate([like], "s3:ListBucket", "data",
                    {"s3:prefix": "app/sub/"})


def test_condition_negated_absent_key_passes():
    p = _pol("Allow", ["s3:GetObject"], ["data/*"],
             condition={"StringNotEquals": {"aws:referer": ["evil.example"]}})
    assert evaluate([p], "s3:GetObject", "data/k", {})          # absent -> met
    assert evaluate([p], "s3:GetObject", "data/k",
                    {"aws:Referer": "ok.example"})
    assert not evaluate([p], "s3:GetObject", "data/k",
                        {"aws:Referer": "evil.example"})


def test_condition_ip_address():
    p = _pol("Allow", ["s3:GetObject"], ["data/*"],
             condition={"IpAddress": {"aws:SourceIp": ["10.0.0.0/8"]}})
    assert evaluate([p], "s3:GetObject", "data/k",
                    {"aws:SourceIp": "10.1.2.3"})
    assert not evaluate([p], "s3:GetObject", "data/k",
                        {"aws:SourceIp": "192.168.1.1"})
    n = _pol("Allow", ["s3:GetObject"], ["data/*"],
             condition={"NotIpAddress": {"aws:SourceIp": ["10.0.0.0/8"]}})
    assert not evaluate([n], "s3:GetObject", "data/k",
                        {"aws:SourceIp": "10.1.2.3"})
    assert evaluate([n], "s3:GetObject", "data/k",
                    {"aws:SourceIp": "192.168.1.1"})


def test_condition_bool_and_numeric():
    p = _pol("Deny", ["s3:*"], ["*"],
             condition={"Bool": {"aws:SecureTransport": "false"}})
    assert decide([p], "s3:GetObject", "b/k",
                  {"aws:SecureTransport": "false"}) == "Deny"
    assert decide([p], "s3:GetObject", "b/k",
                  {"aws:SecureTransport": "true"}) is None
    q = _pol("Allow", ["s3:ListBucket"], ["b"],
             condition={"NumericLessThanEquals": {"s3:max-keys": "100"}})
    assert evaluate([q], "s3:ListBucket", "b", {"s3:max-keys": "50"})
    assert not evaluate([q], "s3:ListBucket", "b", {"s3:max-keys": "500"})


def test_unknown_condition_operator_rejected():
    with pytest.raises(PolicyError):
        _pol("Allow", ["s3:*"], ["*"],
             condition={"DateLessThanIfExists": {"aws:CurrentTime": "x"}})


def test_principal_matching():
    anyone = _pol("Allow", ["s3:GetObject"], ["pub/*"], principal="*")
    assert evaluate([anyone], "s3:GetObject", "pub/k", access_key=None)
    assert evaluate([anyone], "s3:GetObject", "pub/k", access_key="alice")
    named = _pol("Allow", ["s3:GetObject"], ["pub/*"],
                 principal={"AWS": ["arn:aws:iam:::user/alice"]})
    assert evaluate([named], "s3:GetObject", "pub/k", access_key="alice")
    assert not evaluate([named], "s3:GetObject", "pub/k", access_key="bob")
    assert not evaluate([named], "s3:GetObject", "pub/k", access_key=None)


def test_decide_tri_state():
    allow = _pol("Allow", ["s3:GetObject"], ["b/*"], principal="*")
    deny = _pol("Deny", ["s3:GetObject"], ["b/secret/*"], principal="*")
    assert decide([allow, deny], "s3:GetObject", "b/k") == "Allow"
    assert decide([allow, deny], "s3:GetObject", "b/secret/k") == "Deny"
    assert decide([allow, deny], "s3:PutObject", "b/k") is None


# ---------------------------------------------------------------------------
# end-to-end over HTTP
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("bpdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    creds = Credentials("minioadmin", "minioadmin")
    creds.iam = IAMSys([es], "minioadmin", "minioadmin")
    server = S3Server(es, address="127.0.0.1:0", credentials=creds)
    server.start()
    yield server
    server.stop()


def _anon(address, method, path, body=None, headers=None):
    host, _, port = address.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=10)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _put_policy(root, bucket, doc):
    return root.request("PUT", f"/{bucket}", query={"policy": ""},
                        body=json.dumps(doc).encode())


def test_anonymous_denied_without_policy(srv):
    root = S3Client(srv.address)
    assert root.request("PUT", "/pubbkt")[0] == 200
    assert root.request("PUT", "/pubbkt/obj", body=b"hello")[0] == 200
    st, _ = _anon(srv.address, "GET", "/pubbkt/obj")
    assert st == 403


def test_public_read_policy_allows_anonymous_get_not_put(srv):
    root = S3Client(srv.address)
    st, _, b = _put_policy(root, "pubbkt", {"Statement": [
        {"Effect": "Allow", "Principal": "*", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::pubbkt/*"]}]})
    assert st == 200, b
    st, body = _anon(srv.address, "GET", "/pubbkt/obj")
    assert st == 200 and body == b"hello"
    # GetObject grant does not cover PUT, listing, or deletion.
    st, _ = _anon(srv.address, "PUT", "/pubbkt/obj2", body=b"x",
                  headers={"Content-Length": "1"})
    assert st == 403
    st, _ = _anon(srv.address, "GET", "/pubbkt")
    assert st == 403
    st, _ = _anon(srv.address, "DELETE", "/pubbkt/obj")
    assert st == 403
    # Admin API never opens anonymously.
    st, _ = _anon(srv.address, "GET", "/minio/admin/v3/list-users")
    assert st == 403


def test_anonymous_put_with_policy_roundtrips(srv):
    root = S3Client(srv.address)
    assert root.request("PUT", "/dropbox")[0] == 200
    st, _, b = _put_policy(root, "dropbox", {"Statement": [
        {"Effect": "Allow", "Principal": "*",
         "Action": ["s3:PutObject", "s3:GetObject"],
         "Resource": ["arn:aws:s3:::dropbox/*"]}]})
    assert st == 200, b
    payload = b"anonymous body bytes"
    st, _ = _anon(srv.address, "PUT", "/dropbox/up.txt", body=payload,
                  headers={"Content-Length": str(len(payload))})
    assert st == 200
    st, body = _anon(srv.address, "GET", "/dropbox/up.txt")
    assert st == 200 and body == payload


def test_bucket_policy_deny_overrides_iam_allow(srv):
    root = S3Client(srv.address)
    st, _, b = root.request("PUT", "/minio/admin/v3/add-user",
                            query={"accessKey": "powerful"},
                            body=json.dumps(
                                {"secretKey": "powerfulsecret"}).encode())
    assert st == 200, b
    st, _, b = root.request("PUT", "/minio/admin/v3/set-user-or-group-policy",
                            query={"userOrGroup": "powerful",
                                   "policyName": "readwrite"})
    assert st == 200, b
    assert root.request("PUT", "/denybkt")[0] == 200
    assert root.request("PUT", "/denybkt/obj", body=b"d")[0] == 200
    st, _, b = _put_policy(root, "denybkt", {"Statement": [
        {"Effect": "Deny", "Principal": "*", "Action": ["s3:DeleteObject"],
         "Resource": ["arn:aws:s3:::denybkt/*"]}]})
    assert st == 200, b
    user = S3Client(srv.address, access_key="powerful",
                    secret_key="powerfulsecret")
    # IAM readwrite allows everything, but the bucket policy's explicit
    # Deny wins for deletes; reads stay allowed.
    assert user.request("GET", "/denybkt/obj")[0] == 200
    assert user.request("DELETE", "/denybkt/obj")[0] == 403
    # Root bypasses policy (owner short-circuit).
    assert root.request("DELETE", "/denybkt/obj")[0] == 204


def test_bucket_policy_grants_signed_user_without_iam_policy(srv):
    root = S3Client(srv.address)
    st, _, b = root.request("PUT", "/minio/admin/v3/add-user",
                            query={"accessKey": "npuser"},
                            body=json.dumps(
                                {"secretKey": "npusersecret"}).encode())
    assert st == 200, b
    assert root.request("PUT", "/grantbkt")[0] == 200
    assert root.request("PUT", "/grantbkt/obj", body=b"g")[0] == 200
    user = S3Client(srv.address, access_key="npuser",
                    secret_key="npusersecret")
    assert user.request("GET", "/grantbkt/obj")[0] == 403
    st, _, b = _put_policy(root, "grantbkt", {"Statement": [
        {"Effect": "Allow", "Principal": {"AWS": ["npuser"]},
         "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::grantbkt/*"]}]})
    assert st == 200, b
    st, _, got = user.request("GET", "/grantbkt/obj")
    assert st == 200 and got == b"g"
    # The grant names npuser only; anonymous stays shut out.
    st, _ = _anon(srv.address, "GET", "/grantbkt/obj")
    assert st == 403


def test_source_ip_condition_enforced(srv):
    root = S3Client(srv.address)
    assert root.request("PUT", "/ipbkt")[0] == 200
    assert root.request("PUT", "/ipbkt/obj", body=b"i")[0] == 200
    st, _, b = _put_policy(root, "ipbkt", {"Statement": [
        {"Effect": "Allow", "Principal": "*", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::ipbkt/*"],
         "Condition": {"IpAddress": {"aws:SourceIp": ["127.0.0.0/8"]}}}]})
    assert st == 200, b
    st, _ = _anon(srv.address, "GET", "/ipbkt/obj")
    assert st == 200
    st, _, b = _put_policy(root, "ipbkt", {"Statement": [
        {"Effect": "Allow", "Principal": "*", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::ipbkt/*"],
         "Condition": {"IpAddress": {"aws:SourceIp": ["10.0.0.0/8"]}}}]})
    assert st == 200, b
    st, _ = _anon(srv.address, "GET", "/ipbkt/obj")
    assert st == 403


def test_unsupported_condition_rejected_at_put(srv):
    root = S3Client(srv.address)
    assert root.request("PUT", "/condbkt")[0] == 200
    st, _, body = _put_policy(root, "condbkt", {"Statement": [
        {"Effect": "Allow", "Principal": "*", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::condbkt/*"],
         "Condition": {"DateGreaterThan": {"aws:CurrentTime": "x"}}}]})
    assert st == 400 and b"MalformedPolicy" in body


def test_malformed_docs_rejected_at_put(srv):
    root = S3Client(srv.address)
    assert root.request("PUT", "/rejbkt")[0] == 200
    # Identity-policy shape (no Principal) must not be storable as a
    # bucket policy — it would otherwise match nobody (or, worse in the
    # old code, everybody).
    st, _, body = _put_policy(root, "rejbkt", {"Statement": [
        {"Effect": "Allow", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::rejbkt/*"]}]})
    assert st == 400 and b"MalformedPolicy" in body
    # NotPrincipal would invert to an over-grant if ignored: reject.
    st, _, body = _put_policy(root, "rejbkt", {"Statement": [
        {"Effect": "Allow", "NotPrincipal": {"AWS": "mallory"},
         "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::rejbkt/*"]}]})
    assert st == 400 and b"MalformedPolicy" in body
    # Unparseable CIDR would silently disarm the condition: reject.
    st, _, body = _put_policy(root, "rejbkt", {"Statement": [
        {"Effect": "Deny", "Principal": "*", "Action": ["s3:*"],
         "Resource": ["arn:aws:s3:::rejbkt/*"],
         "Condition": {"IpAddress": {"aws:SourceIp": ["10.0.0.0/8x"]}}}]})
    assert st == 400 and b"MalformedPolicy" in body


def test_uncompilable_stored_policy_fails_closed(srv):
    """A policy document that reaches the metadata store without passing
    validation (legacy format, corruption) must deny all non-owner
    access, not silently drop its statements."""
    root = S3Client(srv.address)
    assert root.request("PUT", "/corruptbkt")[0] == 200
    assert root.request("PUT", "/corruptbkt/obj", body=b"c")[0] == 200
    ol = srv.object_layer
    meta = ol.get_bucket_meta("corruptbkt")
    meta["config:policy"] = json.dumps({"Statement": [
        {"Effect": "Allow", "Principal": "*", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::corruptbkt/*"],
         "Condition": {"FutureOperator": {"x": "y"}}}]})
    ol.set_bucket_meta("corruptbkt", meta)
    user = S3Client(srv.address, access_key="powerful",
                    secret_key="powerfulsecret")   # readwrite IAM user
    assert user.request("GET", "/corruptbkt/obj")[0] == 403
    st, _ = _anon(srv.address, "GET", "/corruptbkt/obj")
    assert st == 403
    # Owner still passes (root short-circuit).
    assert root.request("GET", "/corruptbkt/obj")[0] == 200


def test_anonymous_post_policy_upload(srv):
    """Browser-form POST with no credentials rides the bucket policy
    (reference: cmd/post-policy.go anonymous path)."""
    root = S3Client(srv.address)
    assert root.request("PUT", "/formbkt")[0] == 200
    body = (b"--BOUND\r\n"
            b'Content-Disposition: form-data; name="key"\r\n\r\n'
            b"form.txt\r\n"
            b"--BOUND\r\n"
            b'Content-Disposition: form-data; name="file"; '
            b'filename="f.txt"\r\n'
            b"Content-Type: text/plain\r\n\r\n"
            b"form upload bytes\r\n"
            b"--BOUND--\r\n")
    hdrs = {"Content-Type": "multipart/form-data; boundary=BOUND",
            "Content-Length": str(len(body))}
    st, _ = _anon(srv.address, "POST", "/formbkt", body=body, headers=hdrs)
    assert st == 403
    st, _, b = _put_policy(root, "formbkt", {"Statement": [
        {"Effect": "Allow", "Principal": "*",
         "Action": ["s3:PutObject", "s3:GetObject"],
         "Resource": ["arn:aws:s3:::formbkt/*"]}]})
    assert st == 200, b
    st, _ = _anon(srv.address, "POST", "/formbkt", body=body, headers=hdrs)
    assert st in (200, 204)
    st, got = _anon(srv.address, "GET", "/formbkt/form.txt")
    assert st == 200 and got == b"form upload bytes"
