"""Breaker x NaughtyDisk interplay: what counts as breaker fuel.

The health wrapper's circuit breaker must trip on INFRASTRUCTURE
faults only (I/O errors, op timeouts), never on domain answers
(missing files/volumes — the drive working correctly), never on the
request's own deadline budget running out, and must re-admit a
recovered drive through the half-open probe after cooldown.
"""

import time

import pytest

from minio_tpu.storage.health import DiskHealthWrapper
from minio_tpu.storage.local import FaultyDisk, LocalStorage, VolumeNotFound
from minio_tpu.storage.meta import FileNotFoundErr
from minio_tpu.storage.naughty import NaughtyDisk
from minio_tpu.utils import deadline as deadline_mod


def _wrapped(tmp_path, naughty_kwargs=None, **health_kwargs):
    disk = LocalStorage(str(tmp_path / "d"))
    naughty = NaughtyDisk(disk, **(naughty_kwargs or {}))
    kwargs = dict(op_timeout=0.5, trip_after=3, cooldown=60.0)
    kwargs.update(health_kwargs)
    return naughty, DiskHealthWrapper(naughty, **kwargs)


def test_infra_errors_trip_breaker_and_fail_fast(tmp_path):
    naughty, hd = _wrapped(tmp_path,
                           {"default_err": OSError("injected io")})
    for _ in range(3):
        with pytest.raises(OSError):
            hd.list_vols()
    assert not hd.is_online()
    # Breaker open: calls fail fast WITHOUT reaching the drive.
    before = naughty.call_count
    t0 = time.monotonic()
    with pytest.raises(FaultyDisk):
        hd.list_vols()
    assert time.monotonic() - t0 < 0.1
    assert naughty.call_count == before


def test_domain_errors_are_never_fuel(tmp_path):
    """Missing files/volumes are the storage layer working CORRECTLY;
    even trip_after consecutive ones leave the breaker closed."""
    naughty, hd = _wrapped(
        tmp_path,
        {"fail_ops": {"read_version": FileNotFoundErr("gone"),
                      "stat_vol": VolumeNotFound("nope")}},
        trip_after=2)
    for _ in range(5):
        with pytest.raises(FileNotFoundErr):
            hd.read_version("b", "o")
        with pytest.raises(VolumeNotFound):
            hd.stat_vol("b")
    assert hd.is_online()
    assert hd._consecutive == 0


def test_domain_error_resets_consecutive_infra_count(tmp_path):
    """fault, domain-answer, fault must NOT trip a trip_after=2
    breaker: the domain answer proves the drive is alive in between."""
    naughty, hd = _wrapped(tmp_path, trip_after=2)
    naughty.fail_ops["list_vols"] = OSError("io")
    with pytest.raises(OSError):
        hd.list_vols()
    with pytest.raises(VolumeNotFound):
        hd.stat_vol("missing-vol")       # real answer from the drive
    with pytest.raises(OSError):
        hd.list_vols()
    assert hd.is_online()


def test_half_open_probe_readmits_after_cooldown(tmp_path):
    naughty, hd = _wrapped(tmp_path,
                           {"default_err": OSError("injected io")},
                           trip_after=2, cooldown=0.1)
    for _ in range(2):
        with pytest.raises(OSError):
            hd.list_vols()
    assert not hd.is_online()
    # Drive recovers; before cooldown the breaker still fails fast.
    naughty.default_err = None
    with pytest.raises(FaultyDisk):
        hd.list_vols()
    time.sleep(0.15)
    # Half-open probe passes through and closes the breaker.
    assert hd.list_vols() == []
    assert hd.is_online()
    assert hd.list_vols() == []


def test_failed_probe_restarts_cooldown(tmp_path):
    naughty, hd = _wrapped(tmp_path,
                           {"default_err": OSError("injected io")},
                           trip_after=2, cooldown=0.15)
    for _ in range(2):
        with pytest.raises(OSError):
            hd.list_vols()
    time.sleep(0.2)
    with pytest.raises(OSError):     # the probe itself fails
        hd.list_vols()
    # Immediately after the failed probe: open again, fail fast.
    with pytest.raises(FaultyDisk):
        hd.list_vols()
    # After another full cooldown a fresh probe succeeds.
    naughty.default_err = None
    time.sleep(0.2)
    assert hd.list_vols() == []
    assert hd.is_online()


def test_deadline_cut_probe_does_not_wedge_half_open():
    """A half-open probe cut short by the REQUEST deadline proves
    nothing: the probe slot must be released so a later (budgeted)
    caller can still re-admit the recovered drive. Also: an already-
    expired budget must fail BEFORE consuming the probe slot."""
    class Flaky:
        endpoint = "flaky"
        mode = "fail"

        def list_vols(self):
            if self.mode == "fail":
                raise OSError("io")
            if self.mode == "slow":
                time.sleep(0.3)
            return []

    disk = Flaky()
    hd = DiskHealthWrapper(disk, op_timeout=5.0, trip_after=2,
                           cooldown=0.1)
    for _ in range(2):
        with pytest.raises(OSError):
            hd.list_vols()
    assert not hd.is_online()
    disk.mode = "slow"               # recovered, but not instant
    time.sleep(0.15)
    # Expired budget: rejected before the probe slot is consumed.
    with deadline_mod.bind(deadline_mod.Deadline(0.0)):
        with pytest.raises(deadline_mod.DeadlineExceeded):
            hd.list_vols()
    # Probe cut mid-op by a short budget: aborted, inconclusive.
    with deadline_mod.bind(deadline_mod.Deadline(0.05)):
        with pytest.raises(deadline_mod.DeadlineExceeded):
            hd.list_vols()
    assert not hd.is_online()        # still open, but not wedged:
    disk.mode = "ok"
    assert hd.list_vols() == []      # a healthy caller's probe closes it
    assert hd.is_online()


def test_clamped_expiry_streak_still_trips_dead_drive():
    """A request budget permanently shorter than the op timeout must
    not starve the breaker: repeated GENEROUS-window (>= 1 s) clamped
    expiries on the same drive are evidence enough to trip, while
    tiny-window expiries never count."""
    class Dead:
        endpoint = "dead"

        def list_vols(self):
            time.sleep(30)

    hd = DiskHealthWrapper(Dead(), op_timeout=10.0, trip_after=2,
                           cooldown=300.0)
    # Tiny windows prove nothing, however many.
    for _ in range(4):
        with deadline_mod.bind(deadline_mod.Deadline(0.05)):
            with pytest.raises(deadline_mod.DeadlineExceeded):
                hd.list_vols()
    assert hd.is_online()
    # Whole-second windows of silence, trip_after in a row: trip.
    for _ in range(2):
        with deadline_mod.bind(deadline_mod.Deadline(1.1)):
            with pytest.raises(deadline_mod.DeadlineExceeded):
                hd.list_vols()
    assert not hd.is_online()
    # And the open breaker now fails fast, budget or no budget.
    t0 = time.monotonic()
    with pytest.raises(FaultyDisk):
        hd.list_vols()
    assert time.monotonic() - t0 < 0.5


def test_request_deadline_exhaustion_is_not_fuel(tmp_path):
    """An op cut short by the REQUEST's deadline budget (clamped below
    the drive's own op timeout) raises DeadlineExceeded and never
    counts against the drive."""
    class Slow:
        endpoint = "slow"

        def list_vols(self):
            time.sleep(0.3)
            return []

    hd = DiskHealthWrapper(Slow(), op_timeout=5.0, trip_after=1,
                           cooldown=60.0)
    with deadline_mod.bind(deadline_mod.Deadline(0.05)):
        with pytest.raises(deadline_mod.DeadlineExceeded):
            hd.list_vols()
    assert hd.is_online()            # trip_after=1, yet still closed
    with deadline_mod.bind(deadline_mod.Deadline(0.0)):
        with pytest.raises(deadline_mod.DeadlineExceeded):
            hd.list_vols()
    assert hd.is_online()
    # Without a deadline the same op completes and records success.
    assert hd.list_vols() == []
    assert hd.health_info()["ops"]["list_vols"]["count"] >= 1
