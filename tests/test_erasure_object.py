"""ErasureSet object CRUD: round-trips, quorum, bitrot recovery, versioning.

The in-process harness mirrors the reference's ObjectLayer test pattern
(cmd/test-utils_test.go prepareErasure): a real erasure set over N
tempdir drives in one process.
"""

import os
import shutil

import numpy as np
import pytest

from minio_tpu.object.erasure_object import (BLOCK_SIZE, ErasureSet,
                                             hash_order)
from minio_tpu.object.types import (BucketExists, BucketNotFound,
                                    DeleteOptions, GetOptions,
                                    MethodNotAllowed, ObjectNotFound,
                                    PutOptions, ReadQuorumError,
                                    VersionNotFound, WriteQuorumError)
from minio_tpu.storage.local import LocalStorage


def make_set(tmp_path, n=4, parity=None):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(n)]
    return ErasureSet(disks, parity=parity)


@pytest.fixture
def es(tmp_path):
    s = make_set(tmp_path, 4)
    s.make_bucket("bkt")
    return s


def test_bucket_lifecycle(tmp_path):
    es = make_set(tmp_path, 4)
    es.make_bucket("b1")
    with pytest.raises(BucketExists):
        es.make_bucket("b1")
    assert [b.name for b in es.list_buckets()] == ["b1"]
    es.delete_bucket("b1")
    with pytest.raises(BucketNotFound):
        es.get_bucket_info("b1")


@pytest.mark.parametrize("size", [0, 1, 100, 128 << 10, 1 << 20,
                                  (1 << 20) + 1, 3 * (1 << 20) + 12345])
def test_put_get_roundtrip(es, size):
    rng = np.random.default_rng(size + 1)
    data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    info = es.put_object("bkt", "obj", data, PutOptions(content_type="x/y"))
    assert info.size == size
    got_info, payload = es.get_object("bkt", "obj")
    assert payload == data
    assert got_info.etag == info.etag
    assert got_info.content_type == "x/y"


def test_range_get(es):
    data = bytes(range(256)) * 8192  # 2 MiB
    es.put_object("bkt", "obj", data)
    _, part = es.get_object("bkt", "obj", GetOptions(offset=100, length=1000))
    assert part == data[100:1100]
    _, tail = es.get_object("bkt", "obj",
                            GetOptions(offset=len(data) - 5, length=5))
    assert tail == data[-5:]


def test_get_missing_raises(es):
    with pytest.raises(ObjectNotFound):
        es.get_object("bkt", "nope")
    with pytest.raises(BucketNotFound):
        es.get_object("nobkt", "x")


def test_overwrite_null_version(es):
    es.put_object("bkt", "o", b"first")
    es.put_object("bkt", "o", b"second")
    _, payload = es.get_object("bkt", "o")
    assert payload == b"second"
    assert len(es.list_versions_all("bkt", "o")) == 1


def test_delete_object(es):
    es.put_object("bkt", "o", b"x")
    es.delete_object("bkt", "o")
    with pytest.raises(ObjectNotFound):
        es.get_object_info("bkt", "o")
    # idempotent-ish: deleting a missing object does not raise quorum errors
    es.delete_object("bkt", "o")


def test_versioned_put_and_delete_marker(es):
    i1 = es.put_object("bkt", "o", b"v1", PutOptions(versioned=True))
    i2 = es.put_object("bkt", "o", b"v2", PutOptions(versioned=True))
    assert i1.version_id and i2.version_id and i1.version_id != i2.version_id
    _, latest = es.get_object("bkt", "o")
    assert latest == b"v2"
    _, old = es.get_object("bkt", "o", GetOptions(version_id=i1.version_id))
    assert old == b"v1"

    deleted = es.delete_object("bkt", "o", DeleteOptions(versioned=True))
    assert deleted.delete_marker
    with pytest.raises(ObjectNotFound):
        es.get_object("bkt", "o")  # latest is a marker -> NoSuchKey
    with pytest.raises(MethodNotAllowed):
        es.get_object("bkt", "o", GetOptions(
            version_id=deleted.delete_marker_version_id))
    # specific versions still readable
    _, old = es.get_object("bkt", "o", GetOptions(version_id=i2.version_id))
    assert old == b"v2"
    # delete the marker -> object visible again
    es.delete_object("bkt", "o",
                     DeleteOptions(version_id=deleted.delete_marker_version_id))
    _, latest = es.get_object("bkt", "o")
    assert latest == b"v2"
    with pytest.raises(VersionNotFound):
        es.get_object("bkt", "o", GetOptions(version_id="00000000-0000-0000-0000-000000000000"))


def test_bitrot_corruption_recovered(es, tmp_path):
    data = np.random.default_rng(7).integers(
        0, 256, size=2 * (1 << 20), dtype=np.uint8).tobytes()
    es.put_object("bkt", "obj", data)
    # Corrupt the shard file on one drive.
    corrupted = 0
    root = tmp_path / "d1" / "bkt" / "obj"
    for dirpath, _, files in os.walk(root):
        for f in files:
            if f.startswith("part.") and not corrupted:
                p = os.path.join(dirpath, f)
                blob = bytearray(open(p, "rb").read())
                blob[100] ^= 0xFF
                open(p, "wb").write(bytes(blob))
                corrupted += 1
    assert corrupted == 1
    _, payload = es.get_object("bkt", "obj")
    assert payload == data


def test_indivisible_block_k3(tmp_path):
    # k=3 does not divide the 1 MiB block: per-block zero padding path.
    es6 = make_set(tmp_path, 6)  # EC 3+3
    es6.make_bucket("b")
    data = os.urandom(2 * (1 << 20) + 777)
    es6.put_object("b", "o", data)
    _, got = es6.get_object("b", "o")
    assert got == data
    _, part = es6.get_object("b", "o", GetOptions(offset=(1 << 20) - 3, length=10))
    assert part == data[(1 << 20) - 3:(1 << 20) + 7]


def test_one_disk_lost_still_reads(es, tmp_path):
    data = b"hello erasure world" * 100000
    es.put_object("bkt", "obj", data)
    shutil.rmtree(tmp_path / "d2")
    os.makedirs(tmp_path / "d2")
    _, payload = es.get_object("bkt", "obj")
    assert payload == data


def test_too_many_disks_lost_read_quorum(es, tmp_path):
    data = os.urandom(1 << 20)
    es.put_object("bkt", "obj", data)  # EC 2+2 on 4 drives
    for i in (1, 2, 3):
        shutil.rmtree(tmp_path / f"d{i}")
        os.makedirs(tmp_path / f"d{i}")
    with pytest.raises((ReadQuorumError, ObjectNotFound)):
        es.get_object("bkt", "obj")


def test_write_quorum_failure(tmp_path):
    es = make_set(tmp_path, 4)
    es.make_bucket("bkt")
    # Make 3 of 4 drives unwritable by replacing them with a broken stub.
    class Broken:
        def __getattr__(self, name):
            def fail(*a, **k):
                raise OSError("dead drive")
            return fail
    es.disks[1] = es.disks[2] = es.disks[3] = Broken()
    with pytest.raises(WriteQuorumError):
        es.put_object("bkt", "obj", b"payload")


def test_hash_order_deterministic_permutation():
    import zlib
    d = hash_order("bkt/obj", 12)
    assert sorted(d) == list(range(1, 13))
    assert d == hash_order("bkt/obj", 12)
    assert d[0] == 1 + zlib.crc32(b"bkt/obj") % 12  # keyed rotation start


def test_inline_small_objects_have_no_part_files(es, tmp_path):
    es.put_object("bkt", "small", b"tiny payload")
    for i in range(4):
        objdir = tmp_path / f"d{i}" / "bkt" / "small"
        assert (objdir / "xl.meta").exists()
        entries = [e for e in os.listdir(objdir) if e != "xl.meta"]
        assert entries == []


def test_large_object_has_part_files(es, tmp_path):
    es.put_object("bkt", "big", os.urandom(2 << 20))
    found = 0
    for i in range(4):
        objdir = tmp_path / f"d{i}" / "bkt" / "big"
        for dirpath, _, files in os.walk(objdir):
            found += sum(1 for f in files if f.startswith("part."))
    assert found == 4


def test_overwrite_reclaims_old_data_dir(es, tmp_path):
    es.put_object("bkt", "o", os.urandom(1 << 20))
    es.put_object("bkt", "o", os.urandom(1 << 20))
    # exactly one data dir (uuid) per drive after overwrite
    for i in range(4):
        objdir = tmp_path / f"d{i}" / "bkt" / "o"
        dirs = [e for e in os.listdir(objdir) if (objdir / e).is_dir()]
        assert len(dirs) == 1


def test_failed_put_cleans_staging(tmp_path):
    es = make_set(tmp_path, 4)
    es.make_bucket("bkt")
    # rename_data fails on 3 drives after staging succeeded.
    class RenameFails:
        def __init__(self, inner):
            self._inner = inner
        def __getattr__(self, name):
            if name == "rename_data":
                def boom(*a, **k):
                    raise OSError("commit failed")
                return boom
            return getattr(self._inner, name)
    for i in (1, 2, 3):
        es.disks[i] = RenameFails(es.disks[i])
    with pytest.raises(WriteQuorumError):
        es.put_object("bkt", "o", os.urandom(1 << 20))
    for i in range(4):
        staging = tmp_path / f"d{i}" / ".mtpu.sys" / "staging"
        leftovers = list(staging.glob("*")) if staging.exists() else []
        assert leftovers == []
