"""Admin profiling (reference: cmd/admin-handlers.go:1021): start a
CPU profile, run load, download the per-node bundle."""

import io
import marshal
import os
import zipfile

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.profiling import Profiler, bundle, make_profile_handler
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


def test_profile_start_load_download(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    srv.start()
    try:
        cli = S3Client(srv.address)
        assert cli.request("PUT", "/profbkt")[0] == 200
        st, _, b = cli.request("POST", "/minio/admin/v3/start-profiling")
        assert st == 200, b
        # Double start is refused.
        assert cli.request("POST",
                           "/minio/admin/v3/start-profiling")[0] == 400
        for i in range(5):
            cli.request("PUT", f"/profbkt/o{i}", body=os.urandom(20_000))
        st, h, body = cli.request("GET",
                                  "/minio/admin/v3/download-profiling")
        assert st == 200
        assert h.get("Content-Type") == "application/zip"
        z = zipfile.ZipFile(io.BytesIO(body))
        names = z.namelist()
        assert "local/profile.txt" in names
        assert "local/profile.pstats" in names
        text = z.read("local/profile.txt").decode()
        # The profile saw the PUT handler run.
        assert "put_object" in text
        stats = marshal.loads(z.read("local/profile.pstats"))
        assert stats                        # loadable pstats table
        # Download without a running profile is a clean 400.
        assert cli.request("GET",
                           "/minio/admin/v3/download-profiling")[0] == 400
    finally:
        srv.stop()


def test_peer_profile_handler_roundtrip():
    p = Profiler()
    h = make_profile_handler(p)
    assert h({"action": "start"})["ok"]
    sum(i * i for i in range(50_000))      # some work to profile
    rec = h({"action": "stop"})
    assert rec["ok"] and rec["text"]
    import base64
    assert marshal.loads(base64.b64decode(rec["stats_b64"]))
    assert not h({"action": "stop"})["ok"]  # nothing running now
    blob = bundle({"n1": {"stats": b"x", "text": "t"}})
    assert zipfile.ZipFile(io.BytesIO(blob)).namelist() == \
        ["n1/profile.pstats", "n1/profile.txt"]
