"""Healing + MRF: shard reconstruction onto bad drives, metadata heal,
degraded-read auto-repair (reference patterns: cmd/erasure-healing.go,
cmd/mrf.go, naughty-disk fault injection)."""

import os
import shutil

import numpy as np
import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.healing import (DRIVE_STATE_CORRUPT, DRIVE_STATE_MISSING,
                                      DRIVE_STATE_OK, heal_object)
from minio_tpu.object.types import GetOptions, PutOptions, ReadQuorumError
from minio_tpu.storage.local import LocalStorage


def make_set(tmp_path, n=4):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(n)]
    es = ErasureSet(disks)
    es.make_bucket("bkt")
    return es


def _wipe_drive(tmp_path, i):
    shutil.rmtree(tmp_path / f"d{i}")
    os.makedirs(tmp_path / f"d{i}" / ".mtpu.sys" / "tmp")
    os.makedirs(tmp_path / f"d{i}" / "bkt")


def test_heal_missing_shard(tmp_path):
    es = make_set(tmp_path)
    data = os.urandom(2 * (1 << 20) + 5)
    es.put_object("bkt", "obj", data)
    _wipe_drive(tmp_path, 1)
    res = es.heal_object("bkt", "obj")
    assert res.before[1] == DRIVE_STATE_MISSING
    assert res.after[1] == DRIVE_STATE_OK and res.healed == 1
    # The healed drive alone + any one other can now serve reads (k=2).
    _wipe_drive(tmp_path, 0)
    _wipe_drive(tmp_path, 2)
    _, got = es.get_object("bkt", "obj")
    assert got == data


def test_heal_corrupt_shard(tmp_path):
    es = make_set(tmp_path)
    data = os.urandom(1 << 20)
    es.put_object("bkt", "obj", data)
    # Corrupt drive 2's shard bytes.
    root = tmp_path / "d2" / "bkt" / "obj"
    for dirpath, _, files in os.walk(root):
        for f in files:
            if f.startswith("part."):
                p = os.path.join(dirpath, f)
                blob = bytearray(open(p, "rb").read())
                blob[50] ^= 1
                open(p, "wb").write(bytes(blob))
    # Non-deep (stat-only) classification cannot see an in-place bit
    # flip: the file exists at the right size.
    res_shallow = es.heal_object("bkt", "obj")
    assert res_shallow.before[2] == DRIVE_STATE_OK
    # Deep mode reads and bitrot-verifies every block and repairs it.
    res = es.heal_object("bkt", "obj", deep=True)
    assert res.before[2] == DRIVE_STATE_CORRUPT
    assert res.after[2] == DRIVE_STATE_OK
    res2 = es.heal_object("bkt", "obj", deep=True)
    assert res2.before == [DRIVE_STATE_OK] * 4 and res2.healed == 0


def test_heal_inline_object(tmp_path):
    es = make_set(tmp_path)
    es.put_object("bkt", "small", b"tiny")
    _wipe_drive(tmp_path, 3)
    res = es.heal_object("bkt", "small")
    assert res.after[3] == DRIVE_STATE_OK
    _wipe_drive(tmp_path, 0)
    _wipe_drive(tmp_path, 1)
    _, got = es.get_object("bkt", "small")
    assert got == b"tiny"


def test_heal_delete_marker(tmp_path):
    es = make_set(tmp_path)
    from minio_tpu.object.types import DeleteOptions
    es.put_object("bkt", "o", b"x", PutOptions(versioned=True))
    es.delete_object("bkt", "o", DeleteOptions(versioned=True))
    _wipe_drive(tmp_path, 1)
    # Heal the whole object path: both versions' metadata return.
    res = es.heal_object("bkt", "o")
    assert res.healed == 1
    fis = es.disks[1].list_versions("bkt", "o")
    assert fis[0].deleted  # marker replicated back


def test_heal_bucket(tmp_path):
    es = make_set(tmp_path)
    shutil.rmtree(tmp_path / "d0" / "bkt")
    out = es.heal_bucket("bkt")
    assert out["missing"] == 1 and out["healed"] == 1
    assert es.disks[0].stat_vol("bkt").name == "bkt"


def test_heal_insufficient_shards_offline_raises(tmp_path):
    # OFFLINE drives (transient errors, not ENOENT) must raise, never
    # purge: the data may come back when the drives do.
    es = make_set(tmp_path)
    es.put_object("bkt", "obj", os.urandom(1 << 20))

    class Offline:
        def __getattr__(self, name):
            def fail(*a, **k):
                raise OSError("drive offline")
            return fail
    for i in (0, 1, 2):
        es.disks[i] = Offline()
    with pytest.raises(ReadQuorumError):
        es.heal_object("bkt", "obj")


def test_heal_unrecoverable_purges_dangling(tmp_path):
    # Genuinely-vanished shards beyond parity: the surviving below-quorum
    # copy is dangling and gets purged (reference: deleteIfDangling).
    es = make_set(tmp_path)
    es.put_object("bkt", "obj", os.urandom(1 << 20))
    for i in (0, 1, 2):
        _wipe_drive(tmp_path, i)
    res = es.heal_object("bkt", "obj")
    assert res.healed == 1  # the stale survivor purged
    with pytest.raises(Exception):
        es.disks[3].read_version("bkt", "obj")


def test_degraded_read_triggers_mrf_heal(tmp_path):
    es = make_set(tmp_path)
    data = os.urandom(1 << 20)
    es.put_object("bkt", "obj", data)
    _wipe_drive(tmp_path, 1)
    _, got = es.get_object("bkt", "obj")   # served via reconstruction
    assert got == data
    es.mrf.drain()
    # MRF healed the wiped drive in the background.
    fi = es.disks[1].read_version("bkt", "obj")
    assert fi.size == len(data)


def test_partial_write_triggers_mrf_heal(tmp_path):
    es = make_set(tmp_path)

    real = es.disks[3]
    fails = {"n": 0}

    class FailOnce:
        def __getattr__(self, name):
            if name == "rename_data" and fails["n"] == 0:
                def boom(*a, **k):
                    fails["n"] += 1
                    raise OSError("transient")
                return boom
            return getattr(real, name)

    es.disks[3] = FailOnce()
    data = os.urandom(1 << 20)
    es.put_object("bkt", "obj", data)  # 3/4 writes, quorum ok
    es.disks[3] = real
    es.mrf.drain()
    fi = real.read_version("bkt", "obj")
    assert fi.size == len(data)


def test_heal_multipart_object(tmp_path):
    from minio_tpu.object import multipart as mp
    es = make_set(tmp_path)
    uid = es.new_multipart_upload("bkt", "multi")
    p1 = os.urandom(mp.MIN_PART_SIZE)
    p2 = os.urandom(123_456)
    e1 = es.put_object_part("bkt", "multi", uid, 1, p1)
    e2 = es.put_object_part("bkt", "multi", uid, 2, p2)
    es.complete_multipart_upload("bkt", "multi", uid,
                                 [(1, e1.etag), (2, e2.etag)])
    _wipe_drive(tmp_path, 2)
    res = es.heal_object("bkt", "multi")
    assert res.healed == 1 and res.after[2] == DRIVE_STATE_OK
    _wipe_drive(tmp_path, 0)
    _wipe_drive(tmp_path, 3)
    _, got = es.get_object("bkt", "multi")
    assert got == p1 + p2


def test_heal_purges_stale_version_after_missed_delete(tmp_path):
    es = make_set(tmp_path)
    es.put_object("bkt", "zombie", b"old data")
    real = es.disks[0]

    class DeleteFails:
        def __getattr__(self, name):
            if name == "delete_version":
                def boom(*a, **k):
                    raise OSError("hiccup")
                return boom
            return getattr(real, name)

    es.disks[0] = DeleteFails()
    es.delete_object("bkt", "zombie")
    es.disks[0] = real
    # Drive 0 still holds the stale copy; heal must purge it.
    res = es.heal_object("bkt", "zombie")
    assert res.healed == 1
    with pytest.raises(Exception):
        real.read_version("bkt", "zombie")
