"""Device HighwayHash + fused encode/bitrot framing must be byte-identical
to the host bitrot layer (and therefore to the reference's golden digests,
cmd/bitrot.go:225-230).

The Pallas kernels run in interpret mode off-TPU, so shapes here stay
small; bench.py and the TPU-gated tests exercise the compiled kernels on
real hardware.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from minio_tpu.erasure.codec import Erasure
from minio_tpu.ops import gf256
from minio_tpu.ops.hh_device import (_hash_words_pallas, _init_smem_np,
                                     _init_state_np, _pick_pchunk,
                                     hash_blocks_device, hash_blocks_pallas,
                                     make_encode_framer)
from minio_tpu.storage import bitrot
from minio_tpu.utils.highwayhash import MAGIC_KEY, highwayhash256_many

_ON_TPU = jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# XLA (portable) path
# ---------------------------------------------------------------------------

_XLA_LENGTHS = [0, 1, 3, 17, 31, 32, 33, 63, 64, 100, 1024, 4096] if _ON_TPU \
    else [0, 17, 31, 32, 100, 1024]   # each length = one ~3s CPU compile


@pytest.mark.parametrize("length", _XLA_LENGTHS)
def test_xla_hash_matches_host(length):
    rng = np.random.default_rng(length)
    blocks = rng.integers(0, 256, size=(5, length), dtype=np.uint8)
    want = highwayhash256_many(MAGIC_KEY, blocks)
    got = hash_blocks_device(MAGIC_KEY, blocks, mode="xla")
    assert np.array_equal(want, got)


def test_xla_hash_arbitrary_key():
    key = bytes(range(32))
    rng = np.random.default_rng(7)
    blocks = rng.integers(0, 256, size=(3, 333), dtype=np.uint8)
    want = highwayhash256_many(key, blocks)
    got = hash_blocks_device(key, blocks, mode="xla")
    assert np.array_equal(want, got)


# ---------------------------------------------------------------------------
# Pallas kernel (interpret off-TPU, compiled on TPU)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("s,length", [(7, 256), (130, 512), (1024, 2048)])
def test_pallas_hash_matches_host(s, length):
    if not _ON_TPU and (s, length) != (7, 256):
        pytest.skip("interpret mode: reduced sweep off-TPU")
    rng = np.random.default_rng(s)
    blocks = rng.integers(0, 256, size=(s, length), dtype=np.uint8)
    want = highwayhash256_many(MAGIC_KEY, blocks)
    got = np.asarray(hash_blocks_pallas(
        blocks, jnp.asarray(_init_smem_np(MAGIC_KEY)), interpret=not _ON_TPU))
    assert np.array_equal(want, got)


def _hash_words(words, pchunk):
    """Run the natural-layout kernel (interpret off-TPU) -> [S, 32] u8."""
    out = _hash_words_pallas(jnp.asarray(words),
                             jnp.asarray(_init_smem_np(MAGIC_KEY)),
                             pchunk=pchunk, interpret=not _ON_TPU)
    return np.asarray(jax.lax.bitcast_convert_type(out, jnp.uint8)) \
        .reshape(out.shape[0], 32)


@pytest.mark.parametrize("shape,pchunk", [
    ((130, 512 // 4), 16),        # 2-D fast path, stream padding
    ((10, 8, 4096 // 4), 16),     # 3-D fast path (no reshape), padding
    ((5, 4, 1024 // 4), 16),      # 3-D, X=4 (parity-shaped), padding
])
def test_hh_kernel_nt_matches_host(shape, pchunk):
    """The transpose-fused natural-layout kernel (_hh_kernel_nt), both
    2-D and 3-D block-spec variants, byte-identical to the host hash in
    interpret mode — a TPU-only regression here must fail off-TPU too."""
    rng = np.random.default_rng(sum(shape))
    words = rng.integers(0, 2 ** 32, size=shape, dtype=np.uint32)
    blocks = words.reshape(-1, shape[-1]).view(np.uint8)
    want = highwayhash256_many(MAGIC_KEY, blocks)
    got = _hash_words(words, pchunk)
    assert np.array_equal(want, got)


# ---------------------------------------------------------------------------
# Fused framer vs the host bitrot layer
# ---------------------------------------------------------------------------

def _host_framed(data, k, m):
    """Reference framing: host encode + frame_shards_batch per block."""
    n = k + m
    b, _, l = data.shape
    e = Erasure(k, m, k * l)
    files = [bytearray() for _ in range(n)]
    for bi in range(b):
        shards = e.encode_data(data[bi].reshape(-1).tobytes())
        for i in range(n):
            blk = np.asarray(shards[i])
            files[i] += bitrot.hash_block(bitrot.DEFAULT_ALGORITHM, blk)
            files[i] += blk.tobytes()
    return [bytes(f) for f in files]


_FRAMER_CONFIGS = [(4, 2, 3, 512), (8, 4, 2, 1024)] if _ON_TPU \
    else [(4, 2, 3, 512)]


def _join_pieces(row) -> bytes:
    """row = per-block (digest, block) piece tuples -> the framed file."""
    return b"".join(bytes(p) for pieces in row for p in pieces)


@pytest.mark.parametrize("k,m,b,l", _FRAMER_CONFIGS)
def test_framer_matches_host_bitrot(k, m, b, l):
    rng = np.random.default_rng(k * m)
    data = rng.integers(0, 256, size=(b, k, l), dtype=np.uint8)
    framer = make_encode_framer(gf256.parity_matrix(k, m))
    rows = framer(data)
    want = _host_framed(data, k, m)
    assert len(rows) == k + m
    for i in range(k + m):
        assert len(rows[i]) == b
        assert _join_pieces(rows[i]) == want[i], f"drive {i} differs"


@pytest.mark.skipif(not _ON_TPU, reason="compiled u32 pipeline needs TPU")
def test_framer_u32_pipeline_on_tpu():
    """The full u32 Pallas pipeline (encode32 + hash) on real hardware,
    eligible shape, including stream padding."""
    k, m = 8, 4
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, size=(10, k, 4096), dtype=np.uint8)
    framer = make_encode_framer(gf256.parity_matrix(k, m))
    rows = framer(data)
    want = _host_framed(data, k, m)
    for i in range(k + m):
        assert _join_pieces(rows[i]) == want[i], f"drive {i} differs"


def test_framed_digests_device_matches_host():
    """Read-path device digests of framed shard windows == host hashes
    (interpret off-TPU). Frame layout: `digest || block` per row."""
    from minio_tpu.ops.hh_device import framed_digests_device
    shard_size = 1024
    rng = np.random.default_rng(21)
    blobs, want = [], []
    for nb in (3, 5):
        blocks = rng.integers(0, 256, size=(nb, shard_size), dtype=np.uint8)
        digs = highwayhash256_many(MAGIC_KEY, blocks)
        framed = np.concatenate([digs, blocks], axis=1)   # [nb, 32+ss]
        blobs.append(np.ascontiguousarray(framed).view(np.uint32))
        want.append(digs)
    got = framed_digests_device(blobs, interpret=not _ON_TPU)
    assert np.array_equal(got, np.concatenate(want, axis=0))


def test_framed_digests_device_chunked(monkeypatch):
    """The whole-chunk dispatch path and its output-offset bookkeeping
    (framed_digests_device splits blobs into _FRAMED_CHUNK-row device
    calls + one padded remainder): shrink the chunk constants so tiny
    interpret-mode shapes exercise chunk slicing, multi-chunk blobs, and
    chunk/remainder mixing."""
    from minio_tpu.ops import hh_device
    monkeypatch.setattr(hh_device, "_FRAMED_CHUNK", 4)
    monkeypatch.setattr(hh_device, "_FRAMED_PAD", 2)
    shard_size = 1024
    rng = np.random.default_rng(33)
    blobs, want = [], []
    for nb in (9, 4, 3):    # 2 chunks + rem 1; 1 chunk exactly; rem only
        blocks = rng.integers(0, 256, size=(nb, shard_size), dtype=np.uint8)
        digs = highwayhash256_many(MAGIC_KEY, blocks)
        framed = np.ascontiguousarray(
            np.concatenate([digs, blocks], axis=1))
        blobs.append(framed.view(np.uint32))
        want.append(digs)
    got = hh_device.framed_digests_device(blobs, interpret=not _ON_TPU)
    assert np.array_equal(got, np.concatenate(want, axis=0))
