"""IAM: policy evaluation, the store, and end-to-end enforcement through
the S3 API (reference: cmd/iam.go, internal/policy)."""

import json

import pytest

from minio_tpu.iam import IAMError, IAMSys, Policy, canned_policies, evaluate
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import Credentials, S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


# ---------------------------------------------------------------------------
# policy engine
# ---------------------------------------------------------------------------

def _pol(effect, actions, resources):
    return Policy.from_json({"Statement": [{
        "Effect": effect, "Action": actions, "Resource": resources}]})


def test_explicit_deny_wins():
    allow = _pol("Allow", ["s3:*"], ["*"])
    deny = _pol("Deny", ["s3:DeleteObject"], ["arn:aws:s3:::secure/*"])
    assert evaluate([allow, deny], "s3:GetObject", "secure/x")
    assert not evaluate([allow, deny], "s3:DeleteObject", "secure/x")
    assert evaluate([allow, deny], "s3:DeleteObject", "other/x")


def test_default_deny_and_wildcards():
    p = _pol("Allow", ["s3:Get*"], ["arn:aws:s3:::data/*"])
    assert evaluate([p], "s3:GetObject", "data/a/b")
    assert not evaluate([p], "s3:PutObject", "data/a")
    assert not evaluate([p], "s3:GetObject", "other/a")
    assert not evaluate([], "s3:GetObject", "data/a")


def test_canned_policies_shape():
    c = canned_policies()
    assert evaluate([c["readonly"]], "s3:GetObject", "b/k")
    assert not evaluate([c["readonly"]], "s3:PutObject", "b/k")
    assert evaluate([c["readwrite"]], "s3:DeleteObject", "b/k")
    assert not evaluate([c["writeonly"]], "s3:GetObject", "b/k")
    assert evaluate([c["writeonly"]], "s3:PutObject", "b/k")


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------

@pytest.fixture
def es(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    return ErasureSet(disks)


def test_store_users_and_persistence(es):
    iam = IAMSys([es], "root", "rootsecret")
    iam.add_user("alice", "alicesecret")
    iam.attach_policy("alice", ["readonly"])
    assert iam.secret_for("alice") == "alicesecret"
    assert iam.secret_for("root") == "rootsecret"
    assert iam.secret_for("nobody") is None
    # New instance reloads from the drives.
    iam2 = IAMSys([es], "root", "rootsecret")
    assert iam2.secret_for("alice") == "alicesecret"
    assert iam2.is_allowed("alice", "s3:GetObject", "b/k")
    assert not iam2.is_allowed("alice", "s3:PutObject", "b/k")
    assert iam2.is_allowed("root", "s3:PutObject", "b/k")


def test_store_service_accounts(es):
    iam = IAMSys([es], "root", "rootsecret")
    iam.add_user("bob", "bobsecret1")
    iam.attach_policy("bob", ["readwrite"])
    # Inherits parent policy.
    iam.add_service_account("bob", "svc1", "svcsecret1")
    assert iam.is_allowed("svc1", "s3:PutObject", "b/k")
    # Embedded policy overrides parent.
    iam.add_service_account("bob", "svc2", "svcsecret2", policy={
        "Statement": [{"Effect": "Allow", "Action": ["s3:GetObject"],
                       "Resource": ["arn:aws:s3:::b/*"]}]})
    assert iam.is_allowed("svc2", "s3:GetObject", "b/k")
    assert not iam.is_allowed("svc2", "s3:PutObject", "b/k")


def test_store_disabled_user_and_errors(es):
    iam = IAMSys([es], "root", "rootsecret")
    iam.add_user("carol", "carolsecret")
    iam.set_user_status("carol", False)
    assert iam.secret_for("carol") is None
    with pytest.raises(IAMError):
        iam.add_user("root", "x" * 10)
    with pytest.raises(IAMError):
        iam.attach_policy("carol", ["nonexistent"])


# ---------------------------------------------------------------------------
# end-to-end enforcement over HTTP
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("iamdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    creds = Credentials("minioadmin", "minioadmin")
    creds.iam = IAMSys([es], "minioadmin", "minioadmin")
    server = S3Server(es, address="127.0.0.1:0", credentials=creds)
    server.start()
    yield server
    server.stop()


def test_e2e_readonly_key_gets_but_cannot_put(srv):
    root = S3Client(srv.address)
    assert root.request("PUT", "/iambkt")[0] == 200
    assert root.request("PUT", "/iambkt/obj", body=b"data")[0] == 200

    # Provision a read-only user through the admin API.
    st, _, b = root.request("PUT", "/minio/admin/v3/add-user",
                            query={"accessKey": "reader"},
                            body=json.dumps({"secretKey": "readersecret"}
                                            ).encode())
    assert st == 200, b
    st, _, b = root.request("PUT", "/minio/admin/v3/set-user-or-group-policy",
                            query={"userOrGroup": "reader",
                                   "policyName": "readonly"})
    assert st == 200, b

    reader = S3Client(srv.address, access_key="reader",
                      secret_key="readersecret")
    st, _, got = reader.request("GET", "/iambkt/obj")
    assert st == 200 and got == b"data"
    st, _, body = reader.request("PUT", "/iambkt/obj2", body=b"nope")
    assert st == 403, body
    st, _, _ = reader.request("DELETE", "/iambkt/obj")
    assert st == 403
    # Admin endpoints are closed to non-root identities.
    st, _, _ = reader.request("GET", "/minio/admin/v3/list-users")
    assert st == 403


def test_e2e_unknown_key_rejected(srv):
    ghost = S3Client(srv.address, access_key="ghost", secret_key="ghosts3cr3t")
    st, _, _ = ghost.request("GET", "/iambkt/obj")
    assert st == 403


def test_e2e_custom_policy_scoped_to_prefix(srv):
    root = S3Client(srv.address)
    pol = {"Statement": [
        {"Effect": "Allow", "Action": ["s3:GetObject", "s3:PutObject"],
         "Resource": ["arn:aws:s3:::iambkt/app/*"]},
        {"Effect": "Allow", "Action": ["s3:ListBucket"],
         "Resource": ["arn:aws:s3:::iambkt"]}]}
    st, _, b = root.request("PUT", "/minio/admin/v3/add-canned-policy",
                            query={"name": "app-rw"},
                            body=json.dumps(pol).encode())
    assert st == 200, b
    st, _, b = root.request("PUT", "/minio/admin/v3/add-user",
                            query={"accessKey": "appuser"},
                            body=json.dumps({"secretKey": "appsecret1"}
                                            ).encode())
    assert st == 200, b
    st, _, b = root.request("PUT", "/minio/admin/v3/set-user-or-group-policy",
                            query={"userOrGroup": "appuser",
                                   "policyName": "app-rw"})
    assert st == 200, b
    app = S3Client(srv.address, access_key="appuser", secret_key="appsecret1")
    assert app.request("PUT", "/iambkt/app/one", body=b"1")[0] == 200
    assert app.request("GET", "/iambkt/app/one")[0] == 200
    assert app.request("PUT", "/iambkt/other/one", body=b"1")[0] == 403
    assert app.request("GET", "/iambkt", query={"prefix": "app/"})[0] == 200


def test_e2e_service_account(srv):
    root = S3Client(srv.address)
    st, _, b = root.request("PUT", "/minio/admin/v3/add-service-account",
                            body=json.dumps({
                                "parent": "minioadmin",
                                "accessKey": "svcroot",
                                "secretKey": "svcrootsec"}).encode())
    assert st == 200, b
    # Root-parented service account with no embedded policy: full access
    # is NOT implied — it has no attached policies (least surprise).
    svc = S3Client(srv.address, access_key="svcroot", secret_key="svcrootsec")
    st, _, _ = svc.request("GET", "/iambkt/obj")
    assert st == 403
