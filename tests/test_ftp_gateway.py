"""FTP gateway over the object layer, driven by the STDLIB ftplib
client — a real external FTP implementation, not a hand-rolled peer
(reference: cmd/ftp-server.go)."""

import ftplib
import io
import os

import pytest

from minio_tpu.crypto.kms import AESGCM as _AESGCM

requires_crypto = pytest.mark.skipif(
    _AESGCM is None,
    reason="SSE needs the optional 'cryptography' wheel")

from minio_tpu.gateway import FTPGateway
from minio_tpu.iam import IAMSys
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import Credentials
from minio_tpu.storage.local import LocalStorage


@pytest.fixture(scope="module")
def gw(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ftpdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    creds = Credentials("minioadmin", "minioadmin")
    creds.iam = IAMSys([es], "minioadmin", "minioadmin")
    creds.iam.add_user("reader", "readersecret")
    creds.iam.attach_policy("reader", ["readonly"])
    from minio_tpu.crypto.kms import KMS
    kms = KMS({"testkey": b"\x07" * 32}, "testkey")
    g = FTPGateway(es, creds, address="127.0.0.1:0", kms=kms)
    g.start()
    yield g
    g.stop()
    es.close()


def _client(gw, user="minioadmin", pw="minioadmin"):
    host, _, port = gw.address.rpartition(":")
    c = ftplib.FTP()
    c.connect(host, int(port), timeout=15)
    c.login(user, pw)
    return c

def test_login_and_bad_credentials(gw):
    c = _client(gw)
    assert "UNIX" in c.sendcmd("SYST")
    c.quit()
    with pytest.raises(ftplib.error_perm):
        _client(gw, pw="wrong")


def test_full_file_lifecycle(gw):
    c = _client(gw)
    c.mkd("/ftpbkt")
    assert "ftpbkt" in c.nlst("/")
    body = os.urandom(300_000)
    c.storbinary("STOR /ftpbkt/dir/file.bin", io.BytesIO(body))
    # Listing with directories (common prefixes) and files.
    assert c.nlst("/ftpbkt") == ["dir"]
    c.cwd("/ftpbkt/dir")
    assert c.pwd() == "/ftpbkt/dir"
    assert c.nlst() == ["file.bin"]
    assert c.size("/ftpbkt/dir/file.bin") == len(body)
    out = io.BytesIO()
    c.retrbinary("RETR /ftpbkt/dir/file.bin", out.write)
    assert out.getvalue() == body
    # LIST format parses as a directory listing.
    lines = []
    c.retrlines("LIST /ftpbkt/dir", lines.append)
    assert any("file.bin" in ln for ln in lines)
    c.delete("/ftpbkt/dir/file.bin")
    with pytest.raises(ftplib.error_perm):
        c.size("/ftpbkt/dir/file.bin")
    c.rmd("/ftpbkt")
    assert "ftpbkt" not in c.nlst("/")
    c.quit()


def test_iam_enforced_over_ftp(gw):
    root = _client(gw)
    root.mkd("/ftpauth")
    root.storbinary("STOR /ftpauth/doc", io.BytesIO(b"ftp data"))
    reader = _client(gw, user="reader", pw="readersecret")
    out = io.BytesIO()
    reader.retrbinary("RETR /ftpauth/doc", out.write)
    assert out.getvalue() == b"ftp data"
    # readonly: no writes, no deletes, no bucket removal.
    with pytest.raises(ftplib.error_perm):
        reader.storbinary("STOR /ftpauth/nope", io.BytesIO(b"x"))
    with pytest.raises(ftplib.error_perm):
        reader.delete("/ftpauth/doc")
    with pytest.raises(ftplib.error_perm):
        reader.rmd("/ftpauth")
    reader.quit()
    root.quit()


def test_path_escape_confined_to_namespace(gw):
    """`..` segments normalize WITHIN the virtual root: /../etc/passwd
    names bucket 'etc', key 'passwd' — never the host filesystem — and
    a missing bucket answers 550."""
    c = _client(gw)
    with pytest.raises(ftplib.error_perm):
        c.size("/../etc/passwd")
    # CWD above the root clamps to the root.
    c.cwd("/")
    c.sendcmd("CDUP")
    assert c.pwd() == "/"
    c.quit()


@requires_crypto
def test_stor_honors_bucket_default_sse(gw):
    """A bucket whose default-encryption config demands SSE must not
    store FTP uploads as plaintext — and RETR must decrypt, so both
    directions ride the shared transform seam (advisor r4 medium)."""
    from minio_tpu.object.types import GetOptions
    c = _client(gw)
    c.mkd("/ftpsse")
    ol = gw.object_layer
    meta = ol.get_bucket_meta("ftpsse")
    meta["config:encryption"] = "AES256"
    ol.set_bucket_meta("ftpsse", meta)
    body = os.urandom(200_000)
    c.storbinary("STOR /ftpsse/secret.bin", io.BytesIO(body))
    info = ol.get_object_info("ftpsse", "secret.bin", GetOptions())
    assert info.internal_metadata.get("x-internal-sse-alg") == "SSE-S3"
    assert info.size == len(body)           # logical size
    _, stored = ol.get_object("ftpsse", "secret.bin", GetOptions())
    assert stored != body                   # at rest: DARE ciphertext
    assert c.size("/ftpsse/secret.bin") == len(body)
    out = io.BytesIO()
    c.retrbinary("RETR /ftpsse/secret.bin", out.write)
    assert out.getvalue() == body           # on the wire: plaintext
    c.quit()


def test_retr_decompresses(gw):
    """RETR of a transparently-compressed object sends logical bytes,
    not the stored zlib blocks."""
    from minio_tpu.crypto import compress as comp
    from minio_tpu.object.types import PutOptions
    c = _client(gw)
    c.mkd("/ftpcomp")
    body = b"compress me " * 20_000
    stored, meta = comp.compress(body)
    opts = PutOptions()
    opts.internal_metadata.update(meta)
    gw.object_layer.put_object("ftpcomp", "blob", stored, opts)
    assert c.size("/ftpcomp/blob") == len(body)
    out = io.BytesIO()
    c.retrbinary("RETR /ftpcomp/blob", out.write)
    assert out.getvalue() == body
    c.quit()


@requires_crypto
def test_retr_sse_c_refused(gw):
    """SSE-C objects need a client-held key FTP cannot carry: RETR
    answers 550 instead of leaking ciphertext."""
    from minio_tpu.crypto import EncryptingPayload, encrypt_stream_size
    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.object.types import PutOptions
    from minio_tpu.utils.streams import Payload
    c = _client(gw)
    c.mkd("/ftpssec")
    body = os.urandom(50_000)
    customer_key = b"\x21" * 32
    import base64
    import hashlib
    md5 = base64.b64encode(hashlib.md5(customer_key).digest()).decode()
    data_key, nonce, imeta = sse_mod.encrypt_metadata(
        "ftpssec", "locked", len(body), gw.kms, (customer_key, md5))
    opts = PutOptions()
    opts.internal_metadata.update(imeta)
    enc = Payload(EncryptingPayload(Payload.wrap(body), data_key, nonce),
                  encrypt_stream_size(len(body)))
    gw.object_layer.put_object("ftpssec", "locked", enc, opts)
    with pytest.raises(ftplib.error_perm):
        c.retrbinary("RETR /ftpssec/locked", lambda b: None)
    c.quit()


def test_user_switch_deauthenticates(gw):
    """Regression: USER after login must drop authentication — a
    reader could otherwise become root by naming it without PASS."""
    c = _client(gw, user="reader", pw="readersecret")
    c.sendcmd("USER minioadmin")          # 331, not logged in
    with pytest.raises(ftplib.error_perm):
        c.mkd("/escalated")               # 530 until PASS succeeds
    c.quit()
