"""FTP gateway over the object layer, driven by the STDLIB ftplib
client — a real external FTP implementation, not a hand-rolled peer
(reference: cmd/ftp-server.go)."""

import ftplib
import io
import os

import pytest

from minio_tpu.gateway import FTPGateway
from minio_tpu.iam import IAMSys
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import Credentials
from minio_tpu.storage.local import LocalStorage


@pytest.fixture(scope="module")
def gw(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ftpdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    creds = Credentials("minioadmin", "minioadmin")
    creds.iam = IAMSys([es], "minioadmin", "minioadmin")
    creds.iam.add_user("reader", "readersecret")
    creds.iam.attach_policy("reader", ["readonly"])
    g = FTPGateway(es, creds, address="127.0.0.1:0")
    g.start()
    yield g
    g.stop()
    es.close()


def _client(gw, user="minioadmin", pw="minioadmin"):
    host, _, port = gw.address.rpartition(":")
    c = ftplib.FTP()
    c.connect(host, int(port), timeout=15)
    c.login(user, pw)
    return c

def test_login_and_bad_credentials(gw):
    c = _client(gw)
    assert "UNIX" in c.sendcmd("SYST")
    c.quit()
    with pytest.raises(ftplib.error_perm):
        _client(gw, pw="wrong")


def test_full_file_lifecycle(gw):
    c = _client(gw)
    c.mkd("/ftpbkt")
    assert "ftpbkt" in c.nlst("/")
    body = os.urandom(300_000)
    c.storbinary("STOR /ftpbkt/dir/file.bin", io.BytesIO(body))
    # Listing with directories (common prefixes) and files.
    assert c.nlst("/ftpbkt") == ["dir"]
    c.cwd("/ftpbkt/dir")
    assert c.pwd() == "/ftpbkt/dir"
    assert c.nlst() == ["file.bin"]
    assert c.size("/ftpbkt/dir/file.bin") == len(body)
    out = io.BytesIO()
    c.retrbinary("RETR /ftpbkt/dir/file.bin", out.write)
    assert out.getvalue() == body
    # LIST format parses as a directory listing.
    lines = []
    c.retrlines("LIST /ftpbkt/dir", lines.append)
    assert any("file.bin" in ln for ln in lines)
    c.delete("/ftpbkt/dir/file.bin")
    with pytest.raises(ftplib.error_perm):
        c.size("/ftpbkt/dir/file.bin")
    c.rmd("/ftpbkt")
    assert "ftpbkt" not in c.nlst("/")
    c.quit()


def test_iam_enforced_over_ftp(gw):
    root = _client(gw)
    root.mkd("/ftpauth")
    root.storbinary("STOR /ftpauth/doc", io.BytesIO(b"ftp data"))
    reader = _client(gw, user="reader", pw="readersecret")
    out = io.BytesIO()
    reader.retrbinary("RETR /ftpauth/doc", out.write)
    assert out.getvalue() == b"ftp data"
    # readonly: no writes, no deletes, no bucket removal.
    with pytest.raises(ftplib.error_perm):
        reader.storbinary("STOR /ftpauth/nope", io.BytesIO(b"x"))
    with pytest.raises(ftplib.error_perm):
        reader.delete("/ftpauth/doc")
    with pytest.raises(ftplib.error_perm):
        reader.rmd("/ftpauth")
    reader.quit()
    root.quit()


def test_path_escape_confined_to_namespace(gw):
    """`..` segments normalize WITHIN the virtual root: /../etc/passwd
    names bucket 'etc', key 'passwd' — never the host filesystem — and
    a missing bucket answers 550."""
    c = _client(gw)
    with pytest.raises(ftplib.error_perm):
        c.size("/../etc/passwd")
    # CWD above the root clamps to the root.
    c.cwd("/")
    c.sendcmd("CDUP")
    assert c.pwd() == "/"
    c.quit()


def test_user_switch_deauthenticates(gw):
    """Regression: USER after login must drop authentication — a
    reader could otherwise become root by naming it without PASS."""
    c = _client(gw, user="reader", pw="readersecret")
    c.sendcmd("USER minioadmin")          # 331, not logged in
    with pytest.raises(ftplib.error_perm):
        c.mkd("/escalated")               # 530 until PASS succeeds
    c.quit()
