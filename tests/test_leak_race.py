"""Leak detection and race harness (reference: cmd/leak-detect_test.go
snapshots goroutines around tests; Go's -race runs the whole suite).

Python has no data-race sanitizer, so the harness takes the other
road: drive the hot paths from many threads at once and assert the
INVARIANTS that races would break (torn reads, resurrected deletes,
lost versions), and verify that a full server lifecycle returns the
process to its baseline thread and file-descriptor footprint."""

import os
import threading
import time

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import (DeleteOptions, GetOptions,
                                    MethodNotAllowed, ObjectNotFound,
                                    PutOptions)
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


def _live_threads() -> set:
    return {t.ident for t in threading.enumerate() if t.is_alive()}


def _open_fds() -> int:
    return len(os.listdir("/proc/self/fd"))


def test_server_lifecycle_leaks_nothing(tmp_path):
    """Boot → serve → stop returns to the baseline thread set and FD
    count (the leak-detect analogue: anything structurally leaked per
    lifecycle compounds in a long-lived test suite or sidecar)."""
    # Warm imports/caches so one-time allocations don't count as leaks.
    disks0 = [LocalStorage(str(tmp_path / "warm" / f"d{i}"))
              for i in range(4)]
    warm = S3Server(ErasureSet(disks0), address="127.0.0.1:0")
    warm.start()
    S3Client(warm.address).request("GET", "/")
    warm.stop()
    time.sleep(0.3)

    before_threads = _live_threads()
    before_fds = _open_fds()
    for cycle in range(3):
        disks = [LocalStorage(str(tmp_path / f"c{cycle}" / f"d{i}"))
                 for i in range(4)]
        srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
        srv.start()
        cli = S3Client(srv.address)
        assert cli.request("PUT", "/leakbkt")[0] == 200
        for i in range(5):
            assert cli.request("PUT", f"/leakbkt/o{i}",
                               body=os.urandom(10_000))[0] == 200
            assert cli.request("GET", f"/leakbkt/o{i}")[0] == 200
        srv.stop()
    deadline = time.time() + 10
    while time.time() < deadline:
        leaked = _live_threads() - before_threads
        if not leaked:
            break
        time.sleep(0.2)
    # Worker pools (erasure fan-out executors) are per-set and die with
    # their references only at GC; allow a small bounded residue but no
    # per-cycle growth.
    leaked = _live_threads() - before_threads
    assert len(leaked) <= 4, (
        f"{len(leaked)} threads leaked across 3 server lifecycles")
    fd_growth = _open_fds() - before_fds
    assert fd_growth <= 8, f"{fd_growth} fds leaked"


def test_request_path_fd_stability(tmp_path):
    """N PUT/GET/DELETE cycles over one server hold the FD count flat —
    a leaked shard file handle or socket per request would climb."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    srv.start()
    try:
        cli = S3Client(srv.address)
        assert cli.request("PUT", "/fdb")[0] == 200
        # Warm one full cycle first.
        cli.request("PUT", "/fdb/w", body=b"warm")
        cli.request("GET", "/fdb/w")
        cli.request("DELETE", "/fdb/w")
        base = _open_fds()
        for i in range(30):
            assert cli.request("PUT", "/fdb/k", body=os.urandom(5000))[0] \
                == 200
            st, _, _ = cli.request("GET", "/fdb/k")
            assert st == 200
            # Ranged read exercises the streaming open/close path.
            st, _, _ = cli.request("GET", "/fdb/k",
                                   headers={"Range": "bytes=100-199"})
            assert st == 206
            assert cli.request("DELETE", "/fdb/k")[0] == 204
        assert _open_fds() - base <= 6, "fd growth on the request path"
    finally:
        srv.stop()


def test_single_key_race_harness(tmp_path):
    """Many writers/readers/deleters on ONE key: every GET must return
    a complete value some PUT wrote (torn or mixed reads = race), and
    the final state must be one committed version or a clean miss."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("raceb")
    bodies = [bytes([i]) * 20_000 for i in range(8)]
    stop = threading.Event()
    violations: list = []

    def writer(i):
        while not stop.is_set():
            try:
                es.put_object("raceb", "hot", bodies[i])
            except Exception as e:  # noqa: BLE001 - recorded
                violations.append(f"put: {e}")

    def reader():
        while not stop.is_set():
            try:
                _, got = es.get_object("raceb", "hot")
                if not (got in bodies):
                    violations.append(f"torn read: len={len(got)} "
                                      f"first={got[:1]!r} uniq="
                                      f"{len(set(got))}")
            except (ObjectNotFound, MethodNotAllowed):
                pass
            except Exception as e:  # noqa: BLE001 - recorded
                violations.append(f"get: {e}")

    def deleter():
        while not stop.is_set():
            try:
                es.delete_object("raceb", "hot", DeleteOptions())
            except (ObjectNotFound, MethodNotAllowed):
                pass
            except Exception as e:  # noqa: BLE001 - recorded
                violations.append(f"del: {e}")
            time.sleep(0.01)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(4)]
    threads += [threading.Thread(target=deleter)]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not violations, violations[:5]
    # Final state: a clean read of a full body, or a clean miss.
    try:
        _, got = es.get_object("raceb", "hot")
        assert got in bodies
    except ObjectNotFound:
        pass


def test_lease_returned_once_on_naughty_shard_writes(tmp_path):
    """Pool invariant under injected faults: a NaughtyDisk failing
    every create_file still sees its per-drive lease reference
    returned exactly once (pool drains to baseline, no leaks, no
    double releases), and the PUT itself succeeds on quorum."""
    from minio_tpu.storage.naughty import NaughtyDisk
    from tests.chaos import pool_balance
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    disks[0] = NaughtyDisk(disks[0],
                           fail_ops={"create_file": OSError("boom")})
    es = ErasureSet(disks)
    es.make_bucket("leaseb")
    # > SMALL_FILE_THRESHOLD per shard so the non-inline (leased
    # memoryview) path runs: 2 MiB at EC 2+2 -> 1 MiB shards.
    body = os.urandom(2 << 20)
    with pool_balance():
        for i in range(4):
            es.put_object("leaseb", f"o{i}", body)
        _, got = es.get_object("leaseb", "o0")
        assert got == body
    es.close()


def test_lease_returned_once_on_timed_out_shard_writes(tmp_path):
    """A health-wrapped drive whose create_file exceeds its deadline
    abandons the op mid-write: the abandoned worker must hold the
    window buffer until it truly finishes and then return it exactly
    once — never recycle-under-writer, never leak."""
    from tests.chaos import HungDisk, build_set, pool_balance
    hung: list = []

    def chaos(i, disk):
        if i == 0:
            h = HungDisk(disk, delay=1.2, ops={"create_file"})
            hung.append(h)
            return h
        return disk

    es = build_set(tmp_path, n_disks=4, chaos=chaos,
                   op_timeout=0.25, bulk_timeout=0.25, trip_after=100)
    es.make_bucket("hungb")
    body = os.urandom(2 << 20)
    with pool_balance(settle=8.0):
        for i in range(2):
            es.put_object("hungb", f"o{i}", body)   # d0 times out
        _, got = es.get_object("hungb", "o0")
        assert got == body
        for h in hung:
            h.release()
    es.close()


def test_lease_returned_once_streaming_writer_death(tmp_path):
    """Streaming PUT with one writer dying mid-stream: the dead
    writer's drain loop must return every window reference it
    swallows; the stream commits on the surviving quorum."""
    from minio_tpu.storage.naughty import NaughtyDisk
    from tests.chaos import pool_balance
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    disks[1] = NaughtyDisk(disks[1],
                           fail_ops={"create_file": OSError("mid-stream")})
    es = ErasureSet(disks)
    es.make_bucket("streamb")
    from minio_tpu.object import erasure_object as eo
    body = os.urandom(eo.STREAM_THRESHOLD + (1 << 20))
    with pool_balance(settle=8.0):
        es.put_object("streamb", "big", body)
        _, got = es.get_object("streamb", "big")
        assert got == body
    es.close()


def test_bucket_meta_write_race(tmp_path):
    """Concurrent metadata writers must never corrupt the quorum doc:
    the final document parses and holds one writer's complete value."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("metab")
    errs: list = []

    def toggler(i):
        for _ in range(30):
            try:
                meta = es.get_bucket_meta("metab")
                meta[f"config:w{i}"] = f"v{i}"
                es.set_bucket_meta("metab", meta)
            except Exception as e:  # noqa: BLE001 - recorded
                errs.append(str(e))

    threads = [threading.Thread(target=toggler, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs[:3]
    es.invalidate_bucket_meta("metab")
    meta = es.get_bucket_meta("metab")
    assert isinstance(meta, dict) and meta   # parses, non-empty
    for k, v in meta.items():
        if k.startswith("config:w"):
            assert v == "v" + k[len("config:w"):]
