"""Tracing/audit subsystem and boot-time robustness: staged-dir sweep,
listing walk rotation (reference: TraceHandler + pubsub, audit targets,
boot tmp sweep, metacache askDisks rotation)."""

import http.client
import http.server
import json
import os
import threading
import time

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.s3.trace import AuditLogger, TraceBroadcaster, make_entry
from minio_tpu.storage.local import SYS_VOL, LocalStorage, sweep_stale_tmp
from tests.s3client import S3Client


# ---------------------------------------------------------------------------
# trace broadcaster + audit
# ---------------------------------------------------------------------------

def test_broadcaster_pubsub_and_slow_subscriber():
    b = TraceBroadcaster()
    assert not b.active
    q = b.subscribe()
    assert b.active
    for i in range(1500):       # over queue depth: oldest drop
        b.publish({"i": i})
    got = []
    while not q.empty():
        got.append(q.get()["i"])
    assert len(got) == 1000
    assert got[-1] == 1499      # newest survived
    b.unsubscribe(q)
    assert not b.active


class _AuditHook(http.server.BaseHTTPRequestHandler):
    received: list = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).received.append(json.loads(body))
        self.send_response(200)
        self.end_headers()

    def log_message(self, *a):
        pass


def test_audit_logger_delivers():
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _AuditHook)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    _AuditHook.received = []
    log = AuditLogger(f"http://127.0.0.1:{srv.server_address[1]}/audit")
    log.submit(make_entry("PUT:object", "PUT", "/b/k", "b", "k", 200,
                          0.01, "127.0.0.1", "minioadmin"))
    for _ in range(100):
        if log.sent:
            break
        time.sleep(0.05)
    log.stop()
    srv.shutdown()
    srv.server_close()
    assert len(_AuditHook.received) == 1
    rec = _AuditHook.received[0]
    assert rec["api"] == "PUT:object" and rec["statusCode"] == 200
    assert rec["accessKey"] == "minioadmin"


# ---------------------------------------------------------------------------
# trace over the admin API
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("trdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


def test_admin_trace_streams_requests(srv):
    cli = S3Client(srv.address)

    entries = []

    def consume():
        # A raw signed GET with count=3, reading the chunked stream.
        import datetime
        import hashlib
        import hmac as hmac_mod
        from minio_tpu.s3 import sigv4
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = amz_date[:8]
        scope = f"{date}/us-east-1/s3/aws4_request"
        payload_hash = hashlib.sha256(b"").hexdigest()
        hdrs = {"host": srv.address, "x-amz-date": amz_date,
                "x-amz-content-sha256": payload_hash}
        signed = sorted(hdrs)
        canon = sigv4.canonical_request(
            "GET", "/minio/admin/v3/trace", {"count": ["4"]}, hdrs,
            signed, payload_hash)
        sts = sigv4.string_to_sign(amz_date, scope, canon)
        skey = sigv4.signing_key("minioadmin", date, "us-east-1")
        sig = hmac_mod.new(skey, sts.encode(), hashlib.sha256).hexdigest()
        conn = http.client.HTTPConnection(srv.address, timeout=20)
        conn.request("GET", "/minio/admin/v3/trace?count=4", headers={
            **hdrs,
            "Authorization": f"{sigv4.ALGORITHM} "
            f"Credential=minioadmin/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"})
        resp = conn.getresponse()
        body = resp.read()          # http.client de-chunks
        conn.close()
        for line in body.splitlines():
            if line.strip():
                entries.append(json.loads(line))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)                 # subscriber attached
    # EVERY traced request happens after subscription — a publish from
    # an earlier request could otherwise land late and interleave.
    assert cli.request("PUT", "/trb")[0] == 200
    cli.request("PUT", "/trb/one", body=b"1")
    cli.request("GET", "/trb/one")
    cli.request("DELETE", "/trb/one")
    t.join(timeout=15)
    assert len(entries) == 4, entries
    apis = [e["api"] for e in entries]
    assert apis == ["PUT:bucket", "PUT:object", "GET:object",
                    "DELETE:object"]
    assert all(e["accessKey"] == "minioadmin" for e in entries)
    assert entries[1]["bucket"] == "trb"


# ---------------------------------------------------------------------------
# robustness
# ---------------------------------------------------------------------------

def test_sweep_stale_tmp(tmp_path):
    d = LocalStorage(str(tmp_path / "d0"))
    os.makedirs(os.path.join(d.root, SYS_VOL, "tmp", "crashed-uuid"))
    os.makedirs(os.path.join(d.root, SYS_VOL, "staging", "stale-put",
                             "datadir"))
    open(os.path.join(d.root, SYS_VOL, "staging", "stale-put", "datadir",
                      "part.1"), "wb").write(b"junk")
    removed = sweep_stale_tmp(d)
    assert removed == 2
    assert os.listdir(os.path.join(d.root, SYS_VOL, "tmp")) == []
    assert os.listdir(os.path.join(d.root, SYS_VOL, "staging")) == []


def test_listing_walk_rotates(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("rb")
    for i in range(3):
        es.put_object("rb", f"o{i}", b"x")
    first = es._walk_rotor if hasattr(es, "_walk_rotor") else 0
    es.list_objects("rb")
    second = es._walk_rotor
    es.list_objects("rb")
    third = es._walk_rotor
    assert second != first or third != second   # rotor advances
    # Listings stay correct across rotations.
    for _ in range(4):
        info = es.list_objects("rb")
        assert [o.name for o in info.objects] == ["o0", "o1", "o2"]
