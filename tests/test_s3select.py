"""S3 Select: SQL parsing/evaluation, event-stream framing, and the
SelectObjectContent API end to end (reference: internal/s3select/)."""

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.s3select.engine import run_select
from minio_tpu.s3select.eventstream import decode_messages
from minio_tpu.s3select.sql import SQLError, parse_select
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

CSV_DATA = (b"name,dept,salary\n"
            b"ada,eng,120\n"
            b"bob,sales,90\n"
            b"cara,eng,130\n"
            b"dan,ops,85\n")

JSON_DATA = (b'{"name": "ada", "dept": "eng", "salary": 120}\n'
             b'{"name": "bob", "dept": "sales", "salary": 90}\n'
             b'{"name": "cara", "dept": "eng", "salary": 130}\n')


def _csv_req(sql, header="USE", out="CSV"):
    return (f"<SelectObjectContentRequest>"
            f"<Expression>{sql}</Expression>"
            f"<ExpressionType>SQL</ExpressionType>"
            f"<InputSerialization><CSV>"
            f"<FileHeaderInfo>{header}</FileHeaderInfo></CSV>"
            f"</InputSerialization>"
            f"<OutputSerialization><{out}/></OutputSerialization>"
            f"</SelectObjectContentRequest>").encode()


def _json_req(sql):
    return (f"<SelectObjectContentRequest>"
            f"<Expression>{sql}</Expression>"
            f"<ExpressionType>SQL</ExpressionType>"
            f"<InputSerialization><JSON><Type>LINES</Type></JSON>"
            f"</InputSerialization>"
            f"<OutputSerialization><JSON/></OutputSerialization>"
            f"</SelectObjectContentRequest>").encode()


def _records(stream: bytes) -> bytes:
    out = b""
    saw_end = False
    for headers, payload in decode_messages(stream):
        if headers.get(":event-type") == "Records":
            out += payload
        if headers.get(":event-type") == "End":
            saw_end = True
    assert saw_end, "missing End event"
    return out


# ---------------------------------------------------------------------------
# SQL subset
# ---------------------------------------------------------------------------

def test_parse_variants():
    q = parse_select("SELECT * FROM S3Object")
    assert q.columns is None and q.where is None
    q = parse_select("select s.name, s.salary as pay from S3Object s "
                     "where s.dept = 'eng' and s.salary > 100 limit 5")
    assert [a for _, a in q.columns] == ["name", "pay"]
    assert q.limit == 5
    q = parse_select("SELECT COUNT(*) FROM S3Object WHERE salary >= 90")
    assert q.aggregates and q.aggregates[0].func == "count" \
        and q.aggregates[0].operand is None
    with pytest.raises(SQLError):
        parse_select("SELECT * FROM other_table")
    with pytest.raises(SQLError):
        parse_select("DROP TABLE S3Object")


def test_where_evaluation_semantics():
    q = parse_select("SELECT * FROM S3Object WHERE "
                     "(dept = 'eng' OR dept = 'ops') AND NOT salary < 100")
    assert q.where.eval({"dept": "eng", "salary": "130"})
    assert not q.where.eval({"dept": "eng", "salary": "90"})
    assert not q.where.eval({"dept": "sales", "salary": "130"})
    q = parse_select("SELECT * FROM S3Object WHERE x IS NULL")
    assert q.where.eval({"y": 1})
    assert not q.where.eval({"x": "v"})


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def test_csv_select_projection_and_where():
    stream = run_select(CSV_DATA, _csv_req(
        "SELECT name, salary FROM S3Object WHERE dept = 'eng'"))
    assert _records(stream) == b"ada,120\ncara,130\n"


def test_csv_positional_columns_without_header():
    body = b"1,alpha\n2,beta\n3,gamma\n"
    stream = run_select(body, _csv_req(
        "SELECT _2 FROM S3Object WHERE _1 > 1", header="NONE"))
    assert _records(stream) == b"beta\ngamma\n"


def test_count_star():
    stream = run_select(CSV_DATA, _csv_req(
        "SELECT COUNT(*) FROM S3Object WHERE salary >= 90"))
    assert _records(stream) == b"3\n"


def test_json_input_output():
    stream = run_select(JSON_DATA, _json_req(
        "SELECT name FROM S3Object WHERE salary > 100"))
    assert _records(stream) == b'{"name": "ada"}\n{"name": "cara"}\n'


def test_limit_and_stats_events():
    stream = run_select(CSV_DATA, _csv_req(
        "SELECT name FROM S3Object LIMIT 2"))
    msgs = decode_messages(stream)
    kinds = [h.get(":event-type") for h, _ in msgs]
    assert kinds[-2:] == ["Stats", "End"]
    assert _records(stream) == b"ada\nbob\n"


# ---------------------------------------------------------------------------
# API end to end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("seldrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


def test_select_over_http(srv):
    cli = S3Client(srv.address)
    assert cli.request("PUT", "/selb")[0] == 200
    assert cli.request("PUT", "/selb/people.csv", body=CSV_DATA)[0] == 200
    st, _, body = cli.request(
        "POST", "/selb/people.csv",
        query={"select": "", "select-type": "2"},
        body=_csv_req("SELECT name FROM S3Object WHERE dept = 'eng'"))
    assert st == 200, body
    assert _records(body) == b"ada\ncara\n"
    # Bad SQL surfaces as a 400, not a stream.
    st, _, body = cli.request(
        "POST", "/selb/people.csv",
        query={"select": "", "select-type": "2"},
        body=_csv_req("SELECT FROM S3Object"))
    assert st == 400


def test_select_streams_input_and_limit_short_circuits():
    """run_select consumes a chunk ITERATOR record by record: a LIMIT
    query over a huge streamed input stops reading shortly after the
    limit instead of draining (and buffering) the whole stream."""
    consumed = [0]

    def gen():
        yield b"name,dept,salary\n"
        for i in range(1_000_000):
            consumed[0] += 1
            yield f"user{i},eng,{i}\n".encode()

    resp = run_select(gen(),
                      _csv_req("SELECT name FROM s3object LIMIT 5"))
    rows = _records(resp).decode().strip().splitlines()
    assert rows == [f"user{i}" for i in range(5)]
    assert consumed[0] < 10_000, consumed[0]


def test_select_streaming_matches_buffered():
    """Chunked input (split at awkward byte boundaries, mid-UTF-8)
    produces byte-identical output to whole-buffer input."""
    data = ("name,note\n" +
            "".join(f"u{i},café-{i}\n" for i in range(200))).encode()
    req = _csv_req("SELECT note FROM s3object WHERE name = 'u42'")
    whole = run_select(data, req)

    def chunks():
        for off in range(0, len(data), 7):   # splits UTF-8 pairs
            yield data[off:off + 7]

    assert run_select(chunks(), req) == whole


def test_select_parquet():
    pa = pytest.importorskip("pyarrow")
    import io as _io
    import pyarrow.parquet as pq
    table = pa.table({"name": ["ada", "bob", "cara", None],
                      "dept": ["eng", "sales", "eng", "eng"],
                      "salary": [120, 90, 130, 50]})
    buf = _io.BytesIO()
    pq.write_table(table, buf)
    req = (b"<SelectObjectContentRequest>"
           b"<Expression>SELECT name FROM s3object WHERE dept = 'eng' "
           b"AND salary &gt; 100</Expression>"
           b"<ExpressionType>SQL</ExpressionType>"
           b"<InputSerialization><Parquet/></InputSerialization>"
           b"<OutputSerialization><CSV/></OutputSerialization>"
           b"</SelectObjectContentRequest>")
    resp = run_select(buf.getvalue(), req)
    rows = _records(resp).decode().strip().splitlines()
    assert rows == ["ada", "cara"]


def _req(sql, in_fmt="csv", header="USE", out_fmt="json"):
    serial = ('<CSV><FileHeaderInfo>%s</FileHeaderInfo></CSV>' % header
              if in_fmt == "csv" else "<JSON><Type>LINES</Type></JSON>")
    return (f'<SelectObjectContentRequest>'
            f'<Expression>{sql}</Expression>'
            f'<ExpressionType>SQL</ExpressionType>'
            f'<InputSerialization>{serial}</InputSerialization>'
            f'<OutputSerialization><JSON/></OutputSerialization>'
            f'</SelectObjectContentRequest>').encode()


CSV_NUM = b"name,cost,qty\nalpha,10,2\nbeta,4.5,8\nalpine,2,5\ngamma,,1\n"


def test_select_aggregates():
    import json as _json
    from minio_tpu.s3select import run_select
    resp = run_select(CSV_NUM, _req(
        "SELECT SUM(cost) AS total, AVG(qty) AS avgq, MIN(cost) AS lo, "
        "MAX(cost) AS hi, COUNT(cost) AS n FROM S3Object"))
    rec = _json.loads(_records(resp))
    assert rec["total"] == 16.5
    assert rec["avgq"] == 4.0
    assert rec["lo"] == 2 and rec["hi"] == 10
    assert rec["n"] == 3          # the empty cost cell doesn't count


def test_select_aggregate_with_where():
    import json as _json
    from minio_tpu.s3select import run_select
    resp = run_select(CSV_NUM, _req(
        "SELECT COUNT(*) FROM S3Object WHERE CAST(qty AS INT) >= 5"))
    rec = _json.loads(_records(resp))
    assert rec["_1"] == 2


def test_select_like_and_cast_projection():
    import json as _json
    from minio_tpu.s3select import run_select
    resp = run_select(CSV_NUM, _req(
        "SELECT name, CAST(qty AS INT) AS q FROM S3Object "
        "WHERE name LIKE 'al%'"))
    rows = [_json.loads(ln) for ln in _records(resp).splitlines()]
    assert rows == [{"name": "alpha", "q": 2}, {"name": "alpine", "q": 5}]
    # NOT LIKE + single-char wildcard + ESCAPE
    resp = run_select(CSV_NUM, _req(
        "SELECT name FROM S3Object WHERE name NOT LIKE '_l%'"))
    rows = [_json.loads(ln) for ln in _records(resp).splitlines()]
    assert [r["name"] for r in rows] == ["beta", "gamma"]


def test_select_mixing_agg_and_columns_rejected():
    from minio_tpu.s3select import SelectError, run_select
    with pytest.raises(SelectError):
        run_select(CSV_NUM, _req("SELECT name, SUM(cost) FROM S3Object"))
