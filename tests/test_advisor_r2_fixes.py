"""Regression tests for the round-2 advisor findings.

Covers: dsync read/write quorum overlap for odd locker counts
(reference internal/dsync/drwmutex.go:218-234), grid client pending-map
isolation across reconnects, walk_dir blob-cache boundedness, and the
TTL sweep of abandoned chunked-upload transfers.
"""

import os
import threading
import time

from minio_tpu.grid.dsync import DRWMutex, LockServer, LocalLocker
from minio_tpu.storage.local import LocalStorage
from minio_tpu.storage.remote import StorageRPCService


# ---------------------------------------------------------------------------
# dsync quorum math
# ---------------------------------------------------------------------------

def test_read_write_quorums_always_overlap():
    for n in range(1, 17):
        m = DRWMutex([object()] * n, "r")
        rq = m._quorum(write=False)
        wq = m._quorum(write=True)
        assert wq == n // 2 + 1
        assert rq + wq > n, f"n={n}: disjoint read+write quorums possible"
        assert 1 <= rq <= n


def test_reader_writer_exclusion_with_one_amnesiac_locker():
    # n=3: one locker restarts (loses its table). A writer holding a
    # quorum on the two live lockers must still block a new reader —
    # with the old read quorum of 1, the reader could win on the fresh
    # locker alone.
    servers = [LockServer() for _ in range(3)]
    lockers = [LocalLocker(s) for s in servers]
    w = DRWMutex(lockers, "res")
    assert w.lock(write=True, timeout=1.0)
    # Locker 0 "restarts": its lock table is wiped.
    servers[0]._res.clear()
    r = DRWMutex(lockers, "res")
    assert not r.lock(write=False, timeout=0.3)
    w.unlock()
    assert r.lock(write=False, timeout=1.0)
    r.unlock()


# ---------------------------------------------------------------------------
# grid client: old socket death must not kill new socket's calls
# ---------------------------------------------------------------------------

def test_drop_conn_only_fails_own_sockets_calls():
    import queue as queue_mod

    from minio_tpu.grid.client import GridClient, _SENTINEL_ERR

    c = GridClient("127.0.0.1", 1)  # never actually connected

    class FakeSock:
        def close(self):
            pass

    old_s, new_s = FakeSock(), FakeSock()
    q_old: "queue_mod.Queue[dict]" = queue_mod.Queue()
    q_new: "queue_mod.Queue[dict]" = queue_mod.Queue()
    with c._mu:
        c._sock = new_s
        c._pending[1] = (old_s, q_old)
        c._pending[2] = (new_s, q_new)
    c._drop_conn(old_s)
    # Old socket's call failed with the sentinel...
    msg = q_old.get_nowait()
    assert msg["e"] == _SENTINEL_ERR
    # ...but the new socket's call is untouched and still registered.
    assert q_new.empty()
    assert 2 in c._pending and 1 not in c._pending
    assert c._sock is new_s


# ---------------------------------------------------------------------------
# walk_dir blob cache stays bounded
# ---------------------------------------------------------------------------

def test_walk_dir_emit_keeps_single_cache_entry(tmp_path):
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.object.types import PutOptions

    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("b")
    for i in range(8):
        es.put_object("b", f"k{i}", b"y" * 128, PutOptions())

    d = disks[0]
    gen = d.walk_dir("b")
    drained = 0
    for _ in gen:
        drained += 1
        # Inspect the running generator's frame: the journal cache is a
        # single slot, never an unbounded map.
        cache = gen.gi_frame.f_locals.get("last_blob")
        if cache is not None:
            assert len(cache) == 2
    assert drained == 8


# ---------------------------------------------------------------------------
# chunked-upload transfer TTL sweep
# ---------------------------------------------------------------------------

def test_stale_transfer_swept(tmp_path):
    d = LocalStorage(str(tmp_path / "d0"))
    svc = StorageRPCService({d.root: d}, xfer_idle_ttl=0.05)
    d.make_vol("v")
    xfer = svc._create_begin({"d": d.root, "a": ["v", "obj/part.1"]})
    st = svc._xfers[xfer]
    tmp_file = st["tmp"]
    assert os.path.exists(tmp_file)
    time.sleep(0.1)
    # A new begin triggers the sweep of the stale one.
    xfer2 = svc._create_begin({"d": d.root, "a": ["v", "obj/part.2"]})
    assert xfer not in svc._xfers
    assert not os.path.exists(tmp_file)
    assert xfer2 in svc._xfers
    # Active transfers are never swept while being written.
    svc._create_chunk({"a": [xfer2, b"data"]})
    svc._sweep_stale_xfers()
    assert xfer2 in svc._xfers
    svc._create_commit({"a": [xfer2]})
    assert d.read_file("v", "obj/part.2") == b"data"
