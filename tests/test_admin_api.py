"""Admin API, Prometheus metrics, health probes (reference:
cmd/admin-handlers.go, cmd/metrics-v3.go, cmd/healthcheck-handler.go)."""

import http.client
import json
import os
import shutil
import time

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.scanner import Scanner
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("admdrv")
    roots = [str(tmp / f"d{i}") for i in range(4)]
    disks = [LocalStorage(r) for r in roots]
    es = ErasureSet(disks)
    es.scanner = Scanner([es], throttle=0)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server, es, roots
    server.stop()


@pytest.fixture(scope="module")
def cli(env):
    return S3Client(env[0].address)


def _raw_get(addr, path):
    conn = http.client.HTTPConnection(addr, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def test_health_probes_unauthenticated(env):
    srv, es, roots = env
    st, _ = _raw_get(srv.address, "/minio/health/live")
    assert st == 200
    st, _ = _raw_get(srv.address, "/minio/health/ready")
    assert st == 200


def test_metrics_endpoint(env, cli):
    srv, es, roots = env
    cli.request("PUT", "/metb")
    cli.request("PUT", "/metb/obj", body=b"x" * 1000)
    es.scanner.scan_cycle()
    st, body = _raw_get(srv.address, "/minio/v2/metrics/cluster")
    assert st == 200
    text = body.decode()
    assert "minio_tpu_http_requests_total" in text
    assert 'api="PUT:object"' in text
    assert "minio_tpu_cluster_objects_total 1" in text
    assert "minio_tpu_drives_online 4" in text
    assert "minio_tpu_capacity_raw_total_bytes" in text


def test_admin_info(env, cli):
    srv, es, roots = env
    st, _, body = cli.request("GET", "/minio/admin/v3/info")
    assert st == 200
    info = json.loads(body)
    assert info["sets"] == 1
    assert info["drives_online"] == 4
    assert len(info["drives"]) == 4
    assert all(d["state"] == "ok" for d in info["drives"])
    assert info["usage"]["objects"] >= 1


def test_admin_heal_trigger(env, cli):
    srv, es, roots = env
    cli.request("PUT", "/healb")
    body = os.urandom(50_000)
    cli.request("PUT", "/healb/fixme", body=body)
    shutil.rmtree(os.path.join(roots[1], "healb", "fixme"))
    st, _, resp = cli.request("POST", "/minio/admin/v3/heal")
    assert st == 200
    assert json.loads(resp)["state"] in ("running", "done")
    for _ in range(50):
        st, _, resp = cli.request("GET", "/minio/admin/v3/heal")
        status = json.loads(resp)
        if status["state"] == "done":
            break
        time.sleep(0.1)
    assert status["state"] == "done", status
    assert status["healed"] >= 1
    assert os.path.isdir(os.path.join(roots[1], "healb", "fixme"))
    st, _, got = cli.request("GET", "/healb/fixme")
    assert got == body


def test_admin_endpoints_require_root(env):
    srv, es, roots = env
    anon = S3Client(srv.address, access_key="nobody", secret_key="xxxxxxxx")
    st, _, _ = anon.request("GET", "/minio/admin/v3/info")
    assert st == 403


def test_readiness_fails_below_quorum(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    try:
        st, _ = _raw_get(server.address, "/minio/health/ready")
        assert st == 200

        class Dead:
            def __getattr__(self, name):
                def fail(*a, **k):
                    raise OSError("dead")
                return fail
        es.disks[0] = Dead()
        es.disks[1] = Dead()
        es.disks[2] = Dead()
        st, _ = _raw_get(server.address, "/minio/health/ready")
        assert st == 503
    finally:
        server.stop()


def test_admin_speedtest(env, cli):
    srv, es, roots = env
    st, _, body = cli.request("POST", "/minio/admin/v3/speedtest",
                              query={"size": str(256 * 1024),
                                     "count": "4"})
    assert st == 200, body
    r = json.loads(body)
    assert r["objects"] == 4 and r["object_size"] == 256 * 1024
    assert r["put_mibps"] > 0 and r["get_mibps"] > 0
    # Synthetic bucket cleaned up.
    st, _, body = cli.request("GET", "/")
    assert b"speedtest" not in body
