"""Read-path acceleration: fused native GET kernel golden tests,
native/numpy/streaming byte-identity under a range sweep, quorum-
fileinfo cache coherence (overwrite/delete/heal, zero-drive-call
repeat GETs), and pooled-lease hygiene of the streaming reader.

The invariants here are the PR's acceptance gates: the three GET paths
must be byte-identical for ANY range over ANY layout (single-part,
multipart, inline), and a cached repeat GET must issue zero
read_version drive calls while never surviving a mutation.
"""

from __future__ import annotations

import ctypes
import gc
import shutil
import tempfile

import numpy as np
import pytest

from minio_tpu import native
from minio_tpu.object.erasure_object import BLOCK_SIZE, ErasureSet
from minio_tpu.object.types import GetOptions, ObjectNotFound, PutOptions
from minio_tpu.storage import bitrot
from minio_tpu.storage.local import LocalStorage
from minio_tpu.utils.highwayhash import MAGIC_KEY

RNG = np.random.default_rng(20260803)


# ---------------------------------------------------------------------------
# mtpu_get_frame golden tests
# ---------------------------------------------------------------------------

def _frame_shard_rows(rows):
    """On-disk framing of one shard's block rows: digest || block."""
    out = bytearray()
    for block in rows:
        out += bitrot.hash_block(bitrot.HIGHWAYHASH256S, block)
        out += bytes(block)
    return bytes(out)


def _numpy_reference(shards_rows, k, nb, take_full, take_last):
    ref = bytearray()
    for b in range(nb):
        take = take_last if b == nb - 1 else take_full
        chunk = b"".join(bytes(shards_rows[j][b]) for j in range(k))
        ref += chunk[:take]
    return bytes(ref)


@pytest.mark.parametrize("k,S,nb,slast,take_last", [
    (8, 1 << 17, 3, 1 << 17, BLOCK_SIZE),      # aligned full blocks
    (8, 1 << 17, 3, 7, 8 * 7),                 # ragged object tail
    (8, 1 << 17, 1, 5, 40),                    # single ragged block
    (3, 349526, 2, 349524, BLOCK_SIZE - 2),    # k does not divide BLOCK
    (2, 1 << 19, 2, 11, 22),                   # tiny tail, k=2
])
def test_get_frame_golden(k, S, nb, slast, take_last):
    lib = native.load()
    if lib is None:
        pytest.skip("native library unavailable")
    shards_rows, blobs = [], []
    for _ in range(k):
        rows = [RNG.integers(0, 256,
                             size=(slast if b == nb - 1 else S),
                             dtype=np.uint8)
                for b in range(nb)]
        shards_rows.append(rows)
        blobs.append(_frame_shard_rows(rows))
    ref = _numpy_reference(shards_rows, k, nb, BLOCK_SIZE, take_last)

    u8p = ctypes.POINTER(ctypes.c_uint8)
    keep = [ctypes.c_char_p(b) for b in blobs]
    ptrs = (u8p * k)(*[ctypes.cast(c, u8p) for c in keep])
    out = (ctypes.c_uint8 * len(ref))()
    rc = lib.mtpu_get_frame(native._u8(MAGIC_KEY), ptrs, k, S, nb,
                            slast, BLOCK_SIZE, take_last, out)
    assert rc == 0
    assert bytes(out) == ref


def test_get_frame_reports_corrupt_shards():
    lib = native.load()
    if lib is None:
        pytest.skip("native library unavailable")
    k, S, nb = 4, 1 << 16, 2
    shards_rows, blobs = [], []
    for _ in range(k):
        rows = [RNG.integers(0, 256, size=S, dtype=np.uint8)
                for _ in range(nb)]
        shards_rows.append(rows)
        blobs.append(bytearray(_frame_shard_rows(rows)))
    # Flip one data byte in shard 1 and one in shard 3.
    blobs[1][32 + 100] ^= 0xFF
    blobs[3][(32 + S) + 32 + 5] ^= 0x01
    u8p = ctypes.POINTER(ctypes.c_uint8)
    keep = [ctypes.c_char_p(bytes(b)) for b in blobs]
    ptrs = (u8p * k)(*[ctypes.cast(c, u8p) for c in keep])
    out = (ctypes.c_uint8 * (nb * BLOCK_SIZE))()
    rc = lib.mtpu_get_frame(native._u8(MAGIC_KEY), ptrs, k, S, nb, S,
                            BLOCK_SIZE, BLOCK_SIZE, out)
    assert rc == (1 << 1) | (1 << 3)


# ---------------------------------------------------------------------------
# object-layer fixtures
# ---------------------------------------------------------------------------

class CountingDisk:
    """Delegating wrapper that counts read_version calls."""

    def __init__(self, inner):
        self._inner = inner
        self.read_version_calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def read_version(self, *a, **kw):
        self.read_version_calls += 1
        return self._inner.read_version(*a, **kw)


@pytest.fixture()
def es6():
    root = tempfile.mkdtemp(prefix="getpath-")
    disks = [CountingDisk(LocalStorage(f"{root}/d{i}")) for i in range(6)]
    for d in disks:
        d.make_vol("b")
    es = ErasureSet(disks, parity=2)
    yield es, disks
    es.close()
    shutil.rmtree(root, ignore_errors=True)


def _sweep_ranges(size: int):
    """Offsets/lengths hugging block (and part) boundaries + random."""
    interesting = {0, 1, BLOCK_SIZE - 1, BLOCK_SIZE, BLOCK_SIZE + 1,
                   2 * BLOCK_SIZE - 1, 2 * BLOCK_SIZE, size - 1}
    pairs = [(0, size), (7, size - 8)]
    for off in sorted(o for o in interesting if 0 <= o < size):
        for ln in (1, BLOCK_SIZE + 3):
            if 0 < ln <= size - off:
                pairs.append((off, ln))
    for _ in range(3):
        off = int(RNG.integers(0, size))
        ln = int(RNG.integers(1, size - off + 1))
        pairs.append((off, ln))
    return pairs


def _read_three_ways(es, bucket, key, off, ln, monkeypatch_ctx):
    got_native = es.get_object(bucket, key,
                               GetOptions(offset=off, length=ln))[1]
    _, chunks = es.get_object_stream(bucket, key,
                                     GetOptions(offset=off, length=ln))
    got_stream = b"".join(bytes(c) for c in chunks)
    with monkeypatch_ctx() as m:
        m.setattr("minio_tpu.native.load", lambda: None)
        got_numpy = es.get_object(bucket, key,
                                  GetOptions(offset=off, length=ln))[1]
    return got_native, got_numpy, got_stream


def test_range_sweep_single_part(es6, monkeypatch):
    es, _ = es6
    size = 2 * BLOCK_SIZE + 34567
    body = RNG.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    es.put_object("b", "o", body)
    for off, ln in _sweep_ranges(size):
        got_native, got_numpy, got_stream = _read_three_ways(
            es, "b", "o", off, ln, monkeypatch.context)
        want = body[off:off + ln]
        assert got_native == want, (off, ln, "native")
        assert got_numpy == want, (off, ln, "numpy")
        assert got_stream == want, (off, ln, "stream")
    assert es.get_kernel["native"] > 0


def test_range_sweep_multipart(es6, monkeypatch):
    es, _ = es6
    p1 = RNG.integers(0, 256, size=5 * (1 << 20) + 17,
                      dtype=np.uint8).tobytes()
    p2 = RNG.integers(0, 256, size=(1 << 20) + 999,
                      dtype=np.uint8).tobytes()
    uid = es.new_multipart_upload("b", "mp")
    e1 = es.put_object_part("b", "mp", uid, 1, p1).etag
    e2 = es.put_object_part("b", "mp", uid, 2, p2).etag
    es.complete_multipart_upload("b", "mp", uid, [(1, e1), (2, e2)])
    body = p1 + p2
    size = len(body)
    # Ranges straddling the part boundary + the generic sweep points.
    pairs = _sweep_ranges(size)[:8]
    pairs += [(len(p1) - 5, 10), (len(p1) - 1, 1), (len(p1), 1),
              (len(p1) - BLOCK_SIZE, 2 * BLOCK_SIZE)]
    for off, ln in pairs:
        if not (0 <= off < size and 0 < ln <= size - off):
            continue
        got_native, got_numpy, got_stream = _read_three_ways(
            es, "b", "mp", off, ln, monkeypatch.context)
        want = body[off:off + ln]
        assert got_native == want, (off, ln, "native")
        assert got_numpy == want, (off, ln, "numpy")
        assert got_stream == want, (off, ln, "stream")


def test_range_sweep_inline(es6, monkeypatch):
    es, _ = es6
    body = RNG.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    es.put_object("b", "tiny", body)
    for off, ln in [(0, 100_000), (0, 1), (99_999, 1), (12345, 4567)]:
        got_native, got_numpy, got_stream = _read_three_ways(
            es, "b", "tiny", off, ln, monkeypatch.context)
        want = body[off:off + ln]
        assert got_native == want == got_numpy == got_stream, (off, ln)


# ---------------------------------------------------------------------------
# fileinfo cache coherence
# ---------------------------------------------------------------------------

def test_repeat_get_zero_drive_metadata_calls(es6):
    es, disks = es6
    body = RNG.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    es.put_object("b", "hot", body)
    _, got = es.get_object("b", "hot")          # cold: pays the fan-out
    assert got == body
    before = sum(d.read_version_calls for d in disks)
    for _ in range(3):
        _, got = es.get_object("b", "hot")      # hot: memory hit
        assert got == body
    es.get_object_info("b", "hot")
    assert sum(d.read_version_calls for d in disks) == before, \
        "repeat GET of a cached object must issue zero read_version calls"
    st = es.fi_cache.stats()
    assert st["hits"] >= 3                  # repeat GETs: data class
    assert st["stat_hits"] >= 1             # the HEAD: stat class


def test_cache_invalidation_overwrite_delete(es6):
    es, _ = es6
    es.put_object("b", "k", b"v1" * 50000)
    assert es.get_object("b", "k")[1] == b"v1" * 50000
    es.put_object("b", "k", b"v2" * 70000)      # overwrite -> bump
    assert es.get_object("b", "k")[1] == b"v2" * 70000
    es.delete_object("b", "k")
    with pytest.raises(ObjectNotFound):
        es.get_object("b", "k")


def test_cache_strips_parity_inline_blobs(es6):
    """Cached entries keep only the k DATA shards' inline payloads
    (the serve fast path); parity holders are stripped to the empty
    not-loaded sentinel, and a read that needs them (cached data blob
    failing digest verification) re-resolves them from the drives and
    still returns correct bytes."""
    import dataclasses

    es, disks = es6
    body = RNG.integers(0, 256, size=90_000, dtype=np.uint8).tobytes()
    es.put_object("b", "striped", body)
    assert es.get_object("b", "striped")[1] == body     # populates cache
    key = ("b", "striped", "")
    entry = es.fi_cache._map[key]
    k = entry["fi"].erasure.data_blocks
    data = [f for f in entry["fis"] if f is not None
            and f.erasure.index <= k]
    parity = [f for f in entry["fis"] if f is not None
              and f.erasure.index > k]
    assert parity and all(f.inline_data == b"" for f in parity), \
        "parity holders must carry only the not-loaded sentinel"
    assert all(f.inline_data for f in data), \
        "data holders must keep their inline payloads resident"
    assert entry["bytes"] == sum(len(f.inline_data) for f in data)
    # Hot path still serves byte-identical from the stripped entry.
    before = sum(d.read_version_calls for d in disks)
    assert es.get_object("b", "striped")[1] == body
    assert sum(d.read_version_calls for d in disks) == before
    # Corrupt ONE cached data blob: digest verification demotes that
    # shard, and the reconstruct path must re-read parity journals
    # from the drives (they are not in the cache) and still rebuild.
    victim = next(i for i, f in enumerate(entry["fis"])
                  if f is not None and f.erasure.index <= k
                  and f.inline_data)
    f = entry["fis"][victim]
    bad = bytearray(f.inline_data)
    bad[len(bad) // 2] ^= 0xFF
    entry["fis"][victim] = dataclasses.replace(f, inline_data=bytes(bad))
    assert es.get_object("b", "striped")[1] == body, \
        "reconstruct around a corrupt cached shard must re-resolve " \
        "stripped parity from the drives"


def test_cache_invalidation_heal(es6):
    es, disks = es6
    body = RNG.integers(0, 256, size=(1 << 20) + 5,
                        dtype=np.uint8).tobytes()
    es.put_object("b", "healme", body)
    assert es.get_object("b", "healme")[1] == body      # cached
    # Destroy one drive's whole copy behind the cache's back.
    disks[2].delete("b", "healme", recursive=True)
    inv0 = es.fi_cache.stats()["invalidations"]
    res = es.heal_object("b", "healme")
    assert res.healed >= 1
    assert es.fi_cache.stats()["invalidations"] > inv0, \
        "a heal that rewrote drive state must invalidate cached fileinfo"
    # Re-read resolves fresh metadata and the healed drive serves again.
    assert es.get_object("b", "healme")[1] == body


def test_cache_disabled_by_env(monkeypatch, tmp_path):
    monkeypatch.setenv("MTPU_FILEINFO_CACHE", "0")
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    for d in disks:
        d.make_vol("b")
    es = ErasureSet(disks, parity=1)
    try:
        es.put_object("b", "o", b"z" * 300_000)
        es.get_object("b", "o")
        es.get_object("b", "o")
        st = es.fi_cache.stats()
        assert not st["enabled"] and st["hits"] == 0 and st["entries"] == 0
    finally:
        es.close()


def test_versioned_get_cached_per_version(es6):
    es, disks = es6
    v1 = es.put_object("b", "ver", b"a" * 200_000,
                       PutOptions(versioned=True)).version_id
    v2 = es.put_object("b", "ver", b"b" * 200_000,
                       PutOptions(versioned=True)).version_id
    assert es.get_object("b", "ver", GetOptions(version_id=v1))[1] \
        == b"a" * 200_000
    assert es.get_object("b", "ver", GetOptions(version_id=v2))[1] \
        == b"b" * 200_000
    assert es.get_object("b", "ver")[1] == b"b" * 200_000  # prime latest
    before = sum(d.read_version_calls for d in disks)
    assert es.get_object("b", "ver", GetOptions(version_id=v1))[1] \
        == b"a" * 200_000
    assert es.get_object("b", "ver")[1] == b"b" * 200_000
    assert sum(d.read_version_calls for d in disks) == before


# ---------------------------------------------------------------------------
# degraded reads through the new paths
# ---------------------------------------------------------------------------

def test_bitrot_demotes_to_reconstruct_and_heals(es6):
    es, disks = es6
    body = RNG.integers(0, 256, size=(2 << 20) + 777,
                        dtype=np.uint8).tobytes()
    es.put_object("b", "rot", body)
    # Corrupt one data byte of one shard file on disk.
    fi = disks[0].read_version("b", "rot")
    import os
    target = None
    for d in disks:
        p = os.path.join(d.root, "b", "rot", fi.data_dir, "part.1")
        if os.path.exists(p):
            target = p
            break
    assert target is not None
    with open(target, "r+b") as f:
        f.seek(40)
        c = f.read(1)
        f.seek(40)
        f.write(bytes([c[0] ^ 0xFF]))
    demoted0 = es.get_kernel["demoted"]
    _, got = es.get_object("b", "rot")
    assert got == body, "degraded read must reconstruct byte-identically"
    assert es.get_kernel["demoted"] > demoted0


def test_missing_shard_reconstructs_via_numpy_path(es6):
    es, disks = es6
    body = RNG.integers(0, 256, size=(1 << 20) + 13,
                        dtype=np.uint8).tobytes()
    es.put_object("b", "gone", body)
    fi = disks[0].read_version("b", "gone")
    import os
    removed = 0
    for d in disks:
        p = os.path.join(d.root, "b", "gone", fi.data_dir, "part.1")
        if os.path.exists(p) and removed < 2:
            os.unlink(p)
            removed += 1
    assert removed == 2
    _, got = es.get_object("b", "gone")
    assert got == body


# ---------------------------------------------------------------------------
# cross-process coherence: 2 pre-forked workers
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fi_worker_server(tmp_path_factory):
    """A 2-worker pre-forked fleet on shared drives (subprocess — the
    pytest process has JAX loaded and fork-after-JAX is unsafe)."""
    import os
    import signal
    import socket
    import subprocess
    import sys
    import time
    root = tmp_path_factory.mktemp("fiworkers")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "minio_tpu.server",
         "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
         f"{root}/d{{1...4}}"],
        env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    from tests.s3client import S3Client
    address = f"127.0.0.1:{port}"
    deadline = time.time() + 90
    ready = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        try:
            st, _, _ = S3Client(address).request(
                "GET", "/minio/health/live", sign=False)
            if st == 200:
                ready = True
                break
        except OSError:
            time.sleep(0.4)
    if not ready:
        out = proc.stdout.read().decode(errors="replace") \
            if proc.stdout else ""
        proc.kill()
        pytest.skip(f"worker fleet failed to boot: {out[-800:]}")
    yield address
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=25)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_workers_fileinfo_cache_cross_invalidation(fi_worker_server):
    """Overwrites and deletes from ANY pre-forked worker invalidate
    every sibling's fileinfo cache: prime both workers' caches with
    repeat GETs on fresh connections (the kernel spreads them), then
    overwrite and assert NO connection anywhere serves stale bytes."""
    from tests.s3client import S3Client
    addr = fi_worker_server
    assert S3Client(addr).request("PUT", "/fib")[0] == 200
    body1 = b"one" * 123_457
    body2 = b"two" * 150_001
    assert S3Client(addr).request("PUT", "/fib/k", body=body1)[0] == 200
    for _ in range(8):       # fresh connections: both workers cache it
        st, _, got = S3Client(addr).request("GET", "/fib/k")
        assert st == 200 and got == body1
    assert S3Client(addr).request("PUT", "/fib/k", body=body2)[0] == 200
    for _ in range(8):
        st, _, got = S3Client(addr).request("GET", "/fib/k")
        assert st == 200 and got == body2, \
            "stale fileinfo served across workers after overwrite"
    assert S3Client(addr).request("DELETE", "/fib/k")[0] == 204
    for _ in range(6):
        st, _, _ = S3Client(addr).request("GET", "/fib/k")
        assert st == 404, "deleted object still served from a cache"


# ---------------------------------------------------------------------------
# pooled-lease hygiene of the streaming reader
# ---------------------------------------------------------------------------

def test_stream_close_returns_pooled_leases(es6):
    from minio_tpu.io.bufpool import global_pool
    es, _ = es6
    size = 40 << 20                    # > GET_WINDOW_BYTES: multi-window
    body = RNG.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    es.put_object("b", "big", body)
    gc.collect()
    pool = global_pool()
    out0 = pool.stats()["outstanding"]
    leaks0 = pool.stats()["leaks"]
    _, chunks = es.get_object_stream("b", "big")
    first = bytes(next(chunks))
    assert body.startswith(first) and len(first) > 0
    chunks.close()                      # mid-stream abandon
    gc.collect()
    st = pool.stats()
    assert st["outstanding"] == out0, "stream close leaked pooled leases"
    assert st["leaks"] == leaks0
    # And a full consume is byte-identical + clean.
    _, chunks = es.get_object_stream("b", "big")
    acc = bytearray()
    for c in chunks:
        acc += c
    assert bytes(acc) == body
    gc.collect()
    assert pool.stats()["outstanding"] == out0
