"""Device-resident read path (the decode mirror of the cross-request
stripe batching): the mesh de-framer's batched-vs-solo verdict/byte
identity across ragged member mixes and every padding bucket, degraded
reads with 1..m missing shards riding the batched device reconstruct,
bitrot-demote-then-device-reconstruct, deadline-cull isolation on the
get route, per-route MTPU_BATCH_FORCE parsing, mixed-geometry batch
isolation (heal verifies of different EC configs through one
verifier), and real shard_map byte-identity on a virtual 8-device mesh
in a subprocess."""

import os
import shutil
import subprocess
import sys
import threading

import numpy as np
import pytest

from minio_tpu.io.bufpool import BufferPool
from minio_tpu.object.erasure_object import (_get_concat, _get_split,
                                             _host_deframe)
from minio_tpu.ops.batcher import (_BUCKETS, StripeBatcher,
                                   batch_force_mode)
from minio_tpu.ops.hh_device import make_deframer
from minio_tpu.storage import bitrot
from minio_tpu.utils.deadline import Deadline, DeadlineExceeded

K, M, SHARD = 8, 4, 4096
FRAME = 32 + SHARD


def _mk_framed(b, seed, k=K, shard=SHARD, corrupt=()):
    """[b, k, 32+shard] of valid on-disk frames; (bi, si) entries in
    `corrupt` get a flipped payload byte after hashing."""
    rng = np.random.default_rng(seed)
    blocks = rng.integers(0, 256, size=(b, k, shard), dtype=np.uint8)
    digs = bitrot.hash_blocks_many(
        bitrot.DEFAULT_ALGORITHM, blocks.reshape(b * k, shard)) \
        .reshape(b, k, 32)
    framed = np.concatenate([digs, blocks], axis=2)
    for bi, si in corrupt:
        framed[bi, si, 32 + (seed % shard)] ^= 0xFF
    return np.ascontiguousarray(framed)


class _RecordingDeframer:
    """Wraps the real single-chip de-framer, recording batch shapes."""

    def __init__(self, k=K):
        self.inner = make_deframer(k)
        self.batches = []
        self.mesh_devices = 1

    def __call__(self, stacked):
        self.batches.append(stacked.shape)
        return self.inner(stacked)


def _get_batcher(dev, pool=None, **kw):
    kw.setdefault("min_device_blocks", 8)
    sb = StripeBatcher(dev, _host_deframe, probe_fn=lambda: True,
                       pool=pool, route="get", split_fn=_get_split,
                       concat_fn=_get_concat, **kw)
    sb.force(True)
    return sb


def _coalesce(sb, windows, timeout=60):
    results = [None] * len(windows)
    errors = [None] * len(windows)

    def worker(i):
        try:
            results[i] = sb.frame(windows[i])
        except BaseException as e:  # noqa: BLE001 - asserted by tests
            errors[i] = e

    with sb._mu:
        sb._inflight += 1
    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(windows))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)
    finally:
        with sb._mu:
            sb._inflight -= 1
    return results, errors


def test_get_route_batched_vs_solo_identity_ragged_members():
    """Coalesced framed windows of UNEVEN sizes demultiplex to exactly
    the per-member verdicts and payload the host de-framer computes
    solo — including corrupt blocks flagged in the right member, and
    payload served as views of the member's OWN window."""
    dev = _RecordingDeframer()
    pool = BufferPool(max_per_class=4)
    sb = _get_batcher(dev, pool=pool, max_wait_s=0.1)
    sizes = [1, 2, 3, 5, 7]
    corrupt = {2: ((1, 4),), 4: ((0, 0), (6, 7))}
    windows = [_mk_framed(b, i, corrupt=corrupt.get(i, ()))
               for i, b in enumerate(sizes)]
    results, errors = _coalesce(sb, windows)
    assert all(e is None for e in errors)
    for i, w in enumerate(windows):
        ok, data = results[i]
        want_ok, want_data = _host_deframe(w)
        assert np.array_equal(ok, want_ok), i
        assert np.array_equal(data, want_data), i
        assert np.shares_memory(data, w)
        for bi, si in corrupt.get(i, ()):
            assert not ok[bi, si]
    assert dev.batches and all(s[0] in _BUCKETS for s in dev.batches)
    st = sb.stats()
    assert st["route"] == "get"
    assert st["dispatches"]["device"] >= 1
    assert pool.stats()["outstanding"] == 0


@pytest.mark.parametrize("bucket", _BUCKETS[:4])
def test_get_route_padding_buckets(bucket):
    """Solo device-sized framed windows at full and one-under bucket
    sizes verify identically to the host de-framer (zero-pad rows of a
    recycled staging buffer must never leak into verdicts)."""
    dev = _RecordingDeframer()
    pool = BufferPool(max_per_class=2)
    sb = _get_batcher(dev, pool=pool, min_device_blocks=4)
    for b in (bucket, bucket - 1):
        w = _mk_framed(b, b, corrupt=((b - 1, 3),))
        ok, data = sb.frame(w)
        want_ok, want_data = _host_deframe(w)
        assert np.array_equal(ok, want_ok)
        assert np.array_equal(data, want_data)
    assert [s[0] for s in dev.batches] == [bucket, bucket]
    assert pool.stats()["outstanding"] == 0


def test_get_route_deadline_cull_isolation():
    """A get-route member whose budget is spent by dispatch time fails
    alone with DeadlineExceeded; batch-mates still get correct
    verdicts."""
    from minio_tpu.ops.batcher import _Pending
    dev = _RecordingDeframer()
    sb = _get_batcher(dev)
    good = [_mk_framed(4, 1), _mk_framed(4, 2)]
    pgood = [_Pending(w, None) for w in good]
    pdead = _Pending(_mk_framed(4, 3), Deadline(-1.0))
    sb._run_batch([pgood[0], pdead, pgood[1]])
    assert isinstance(pdead.exc, DeadlineExceeded)
    assert pdead.event.is_set() and pdead.rows is None
    for i, p in enumerate(pgood):
        assert p.exc is None and p.event.is_set()
        ok, data = p.rows
        want_ok, want_data = _host_deframe(good[i])
        assert np.array_equal(ok, want_ok)
        assert np.array_equal(data, want_data)
    assert sb.stats()["deadline_failures"] == 1


def test_mixed_member_geometries_never_share_a_batch():
    """One verify batcher carries members of DIFFERENT trailing shapes
    (heal verifies of objects with different EC configs): the
    dispatcher drains same-shape runs per batch, so verdicts stay
    correct and no staging buffer mixes geometries."""
    dev = _RecordingDeframer(k=1)
    sb = _get_batcher(dev, max_wait_s=0.1)
    small = [_mk_framed(3, i, k=1, shard=1024) for i in range(3)]
    big = [_mk_framed(3, 10 + i, k=1, shard=4096) for i in range(3)]
    windows = [w for pair in zip(small, big) for w in pair]
    results, errors = _coalesce(sb, windows)
    assert all(e is None for e in errors)
    for i, w in enumerate(windows):
        ok, data = results[i]
        want_ok, want_data = _host_deframe(w)
        assert np.array_equal(ok, want_ok)
        assert np.array_equal(data, want_data)
    for shape in dev.batches:
        assert shape[2] in (32 + 1024, 32 + 4096)


def test_batch_force_mode_per_route(monkeypatch):
    monkeypatch.setenv("MTPU_BATCH_FORCE", "device")
    assert batch_force_mode("put") == "device"
    assert batch_force_mode("get") == "device"
    monkeypatch.setenv("MTPU_BATCH_FORCE", "put=device,get=host")
    assert batch_force_mode("put") == "device"
    assert batch_force_mode("get") == "host"
    assert batch_force_mode("reconstruct") == "auto"
    monkeypatch.setenv("MTPU_BATCH_FORCE", "reconstruct=device")
    assert batch_force_mode("put") == "auto"
    assert batch_force_mode("reconstruct") == "device"
    monkeypatch.setenv("MTPU_BATCH_FORCE", "get=bogus")
    assert batch_force_mode("get") == "auto"


def test_route_split_metrics_render():
    """Batcher occupancy splits by route in Prometheus text, and the
    decode-route kernel-lane service histogram is exported."""
    dev = _RecordingDeframer()
    sb = _get_batcher(dev, min_device_blocks=4)
    sb.frame(_mk_framed(8, 0))
    from minio_tpu.s3.metrics import Metrics
    text = Metrics().render()
    assert 'minio_tpu_batcher_dispatches_total{route="get",' in text
    assert 'minio_tpu_batcher_dispatches_total{route="put",' in text
    assert 'minio_tpu_batcher_fill_ratio{route="reconstruct"}' in text
    assert "minio_tpu_kernel_lane_decode_service_seconds_bucket" in text


# ---------------------------------------------------------------------------
# End-to-end through the object layer (device routes forced off-TPU)
# ---------------------------------------------------------------------------

@pytest.fixture
def forced_decode(monkeypatch, tmp_path):
    """12-drive EC 8+4 set with the decode routes pinned to the device
    (XLA-CPU here — the reproducibility knob reaches the real batched
    route on any host; calibration pins reset on teardown)."""
    monkeypatch.setenv("MTPU_BATCH_FORCE", "get=device,reconstruct=device")
    from minio_tpu.object.erasure_object import (ErasureSet,
                                                 _get_batcher_for)
    from minio_tpu.ops.rs_device import DeviceBackend
    from minio_tpu.storage.local import LocalStorage
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(12)]
    for d in disks:
        d.make_vol("b")
    es = ErasureSet(disks, parity=M, backend=DeviceBackend("auto"))
    for sb in (_get_batcher_for(8, 4), _get_batcher_for(1, 0)):
        sb.reset_calibration()          # re-pin cached instances
    yield es, tmp_path
    es.close()
    monkeypatch.delenv("MTPU_BATCH_FORCE", raising=False)
    for sb in (_get_batcher_for(8, 4), _get_batcher_for(1, 0)):
        sb.reset_calibration()


def test_device_get_window_and_degraded_reads_1_to_m(forced_decode):
    """A device-window-sized GET rides the batched de-framer
    (get_kernel["device"]), and degraded reads with 1..m shards
    missing reconstruct through the device route byte-identically."""
    es, root = forced_decode
    from minio_tpu.object.erasure_object import _get_batcher_for
    from minio_tpu.ops import batcher as batcher_mod
    rng = np.random.default_rng(21)
    body = rng.integers(0, 256, size=9 << 20, dtype=np.uint8).tobytes()
    es.put_object("b", "o", body)
    before = _get_batcher_for(8, 4).stats()["dispatches"]["device"]
    _, got = es.get_object("b", "o")
    assert got == body
    assert es.get_kernel["device"] >= 1
    assert _get_batcher_for(8, 4).stats()["dispatches"]["device"] \
        == before + 1
    # Degraded: knock out 1..m drives' copies; every read must
    # reconstruct byte-identically via the device reconstruct route.
    es.fi_cache.enabled = False
    for n_missing in range(1, M + 1):
        for i in range(n_missing):
            shutil.rmtree(str(root / f"d{i}" / "b" / "o"),
                          ignore_errors=True)
        es.metacache.bump("b")
        _, got = es.get_object("b", "o")
        assert got == body, f"{n_missing} missing"
    recs = [s for s in batcher_mod._REGISTRY
            if s.route == "reconstruct"]
    assert sum(s.stats()["dispatches"]["device"] for s in recs) >= 1


def test_bitrot_demote_then_device_reconstruct(forced_decode):
    """A corrupt shard flagged by the DEVICE verify demotes to the
    reconstruct path, which rebuilds on the device route and serves
    the original bytes."""
    es, root = forced_decode
    import glob
    from minio_tpu.object.erasure_object import hash_order
    rng = np.random.default_rng(22)
    body = rng.integers(0, 256, size=9 << 20, dtype=np.uint8).tobytes()
    es.put_object("b", "rot", body)
    es.fi_cache.enabled = False
    # Corrupt a DATA shard's holder (shard index 0): parity holders are
    # only read after a demotion, so the device verify must see this.
    dist = hash_order("b/rot", 12)
    disk = dist.index(1)
    files = glob.glob(str(root / f"d{disk}" / "b" / "rot" / "*"
                          / "part.1"))
    assert files
    with open(files[0], "r+b") as f:
        f.seek(2000)
        f.write(b"\x5a\xa5\x5a\xa5")
    _, got = es.get_object("b", "rot")
    assert got == body
    assert es.get_kernel["demoted"] >= 1


def test_heal_deep_verify_rides_verify_batcher(forced_decode):
    """Deep heal's bitrot verification routes through the k=1 verify
    batcher (one member per drive shard file) and still detects and
    repairs corruption."""
    es, root = forced_decode
    import glob
    from minio_tpu.object.erasure_object import _get_batcher_for
    rng = np.random.default_rng(23)
    body = rng.integers(0, 256, size=9 << 20, dtype=np.uint8).tobytes()
    es.put_object("b", "hv", body)
    sb = _get_batcher_for(1, 0)
    before = sb.stats()["dispatches"]["device"]
    r = es.heal_object("b", "hv", deep=True)
    assert r.healed == 0
    assert sb.stats()["dispatches"]["device"] > before
    files = glob.glob(str(root / "d5" / "b" / "hv" / "*" / "part.1"))
    with open(files[0], "r+b") as f:
        f.seek(500)
        f.write(b"\xde\xad\xbe\xef")
    r = es.heal_object("b", "hv", deep=True)
    assert r.healed == 1
    es.fi_cache.enabled = False
    es.metacache.bump("b")
    _, got = es.get_object("b", "hv")
    assert got == body


_MESH_BODY = r"""
import numpy as np
import jax
from minio_tpu.object.erasure_object import _host_deframe, _host_apply_rows
from minio_tpu.ops import gf256
from minio_tpu.ops.hh_device import make_mesh_deframer
from minio_tpu.ops.rs_device import make_mesh_matrix
from minio_tpu.storage import bitrot

K, M, SHARD = 8, 4, 256
assert len(jax.devices()) == 8, jax.devices()

def mk(b, seed, corrupt=()):
    rng = np.random.default_rng(seed)
    blocks = rng.integers(0, 256, size=(b, K, SHARD), dtype=np.uint8)
    digs = bitrot.hash_blocks_many(
        bitrot.DEFAULT_ALGORITHM, blocks.reshape(b * K, SHARD)) \
        .reshape(b, K, 32)
    framed = np.concatenate([digs, blocks], axis=2)
    for bi, si in corrupt:
        framed[bi, si, 40] ^= 0xFF
    return np.ascontiguousarray(framed)

deframer = make_mesh_deframer(K)
assert deframer.mesh_devices == 8, deframer.mesh_devices
for b in (8, 16):
    w = mk(b, b, corrupt=((b - 1, 2), (0, 7)))
    ok = deframer(w)
    want_ok, _ = _host_deframe(w)
    assert np.array_equal(ok, want_ok), b

# Batched reconstruct on the mesh: decode rows for 3 missing data
# shards applied across the stripe axis, byte-identical to the host
# codec.
missing = (1, 3, 5)
avail = tuple(i for i in range(K + M) if i not in missing)[:K]
dec = gf256.decode_matrix(K, M, avail)
rows = np.ascontiguousarray(dec[list(missing), :])
mm = make_mesh_matrix(rows)
assert mm.mesh_devices == 8, mm.mesh_devices
rng = np.random.default_rng(9)
surv = rng.integers(0, 256, size=(16, K, SHARD), dtype=np.uint8)
out = mm(surv)
want = _host_apply_rows(rows, surv)
assert np.array_equal(out, want)

# Through the batcher: concurrent get-route members coalesce into
# mesh-divisible buckets and stay verdict-identical.
import threading
from minio_tpu.object.erasure_object import _get_concat, _get_split
from minio_tpu.ops.batcher import StripeBatcher
sb = StripeBatcher(deframer, _host_deframe, probe_fn=lambda: True,
                   min_device_blocks=8, route="get",
                   split_fn=_get_split, concat_fn=_get_concat)
sb.force(True)
windows = [mk(3, 50 + i, corrupt=(((i, i % K),) if i < 3 else ()))
           for i in range(4)]
results = [None] * 4
with sb._mu:
    sb._inflight += 1
ts = [threading.Thread(target=lambda i=i: results.__setitem__(
    i, sb.frame(windows[i]))) for i in range(4)]
[t.start() for t in ts]
[t.join(timeout=120) for t in ts]
with sb._mu:
    sb._inflight -= 1
for i in range(4):
    ok, data = results[i]
    want_ok, want_data = _host_deframe(windows[i])
    assert np.array_equal(ok, want_ok), i
    assert np.array_equal(data, want_data), i
assert sb.stats()["dispatches"]["device"] >= 1
print("MESH_DECODE_OK")
"""


def test_decode_byte_identity_on_virtual_8_device_mesh():
    """The sharded de-framer and reconstruct dispatches on a real
    8-device mesh (virtual CPU devices in a fresh subprocess) produce
    verdicts/bytes identical to the host path, solo and through the
    get-route batcher."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("MTPU_MESH_DEVICES", None)
    env.pop("MTPU_BATCH_FORCE", None)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", "")
        + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c", _MESH_BODY], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, timeout=420)
    assert proc.returncode == 0, proc.stderr.decode()[-4000:]
    assert b"MESH_DECODE_OK" in proc.stdout
