"""N-node-in-one-container cluster harness.

Spawns N REAL `minio_tpu.server` processes on loopback — real grid
mesh, real dsync lock quorums, real storage RPC — over simulated
(directory) drives, sized so 4-8 node clusters fit tier-1 boxes. The
node-level extension of the drive-level chaos harness (tests/chaos.py):

    kill(i)            SIGKILL the node process (crash, not shutdown)
    restart(i)         respawn it on the same endpoints/drives
    partition(i)       blackhole the node's grid plane (every grid
                       connect/send/accept fails) via its chaos file
    drop(i)            silently swallow inbound grid requests (the
                       asymmetric black hole — callers time out)
    delay(i, s)        add `s` seconds to every grid frame (jitter)
    hang_drives(i, s)  every storage RPC served by the node sleeps `s`
                       (a hung REMOTE drive)
    rejoin(i)          clear the node's chaos file

Chaos rides MTPU_GRID_CHAOS (grid/chaos.py): each node polls its own
JSON file, so a LIVE spawned process is reconfigured from the test
without signals or restarts. scripts/cluster_up.py drives the same
class interactively.
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Grid port = S3 port + this (minio_tpu/server.py GRID_PORT_OFFSET).
GRID_OFFSET = 1000


def _bindable(port: int) -> bool:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))
        return True
    except OSError:
        return False
    finally:
        s.close()


def free_ports(n: int, lo: int = 9600, hi: int = 28000) -> list[int]:
    """`n` consecutive S3 ports whose grid twins (port+1000) are also
    free. Random base per attempt so concurrent/serial test clusters
    do not collide on TIME_WAIT leftovers."""
    for _ in range(200):
        base = random.randrange(lo, hi)
        ports = [base + i for i in range(n)]
        if all(_bindable(p) and _bindable(p + GRID_OFFSET) for p in ports):
            return ports
    raise RuntimeError("no free port range for cluster")


class Cluster:
    """N server processes sharing one erasure layout on loopback."""

    def __init__(self, root, nodes: int = 4, drives_per_node: int = 2,
                 ports: Optional[list[int]] = None, parity: Optional[int]
                 = None, set_size: Optional[int] = None,
                 scanner_interval: float = 0.0, boot_timeout: float = 60.0,
                 env: Optional[dict] = None, extra: tuple = (),
                 pools: Optional[list] = None, workers: int = 1):
        """`pools` opts into a MULTI-POOL topology (rebalance/decom
        tests): a list of pool specs, each an int (drives per node, on
        every node), or (node_list, drives_per_node) for a pool hosted
        by a subset of the nodes — e.g. `pools=[2, ([3], 2)]` is one
        2-drives-per-node pool across all nodes plus a second pool
        living entirely on node 3 (the drain-and-remove shape). Each
        pool is passed to every server as ONE comma-separated CLI arg
        (topology/ellipses.parse_pools comma form). Default (None):
        the original single-pool flat layout."""
        self.root = str(root)
        self.n = nodes
        self.drives_per_node = drives_per_node
        self.ports = ports or free_ports(nodes)
        self.procs: dict[int, Optional[subprocess.Popen]] = {}
        self._gen = {i: 0 for i in range(nodes)}   # log file generation
        self.extra = tuple(extra)
        if parity is not None:
            self.extra += ("--parity", str(parity))
        if set_size is not None:
            self.extra += ("--set-size", str(set_size))
        self.extra += ("--scanner-interval", str(scanner_interval),
                       "--boot-timeout", str(boot_timeout))
        # N x M topology: workers > 1 pre-forks that many SO_REUSEPORT
        # workers per node (io/workers.py) — worker 0 owns the node's
        # grid plane. Default 1 keeps every node a single process,
        # regardless of what MTPU_HTTP_WORKERS says in the test env.
        self.workers = max(1, int(workers))
        self.env = dict(env or {})
        self.env.setdefault("MTPU_HTTP_WORKERS", str(self.workers))
        self.endpoints: list[str] = []
        self.pool_args: list[str] = []
        if pools is None:
            for i in range(nodes):
                for d in range(drives_per_node):
                    path = os.path.join(self.root, f"n{i}", f"d{d}")
                    os.makedirs(path, exist_ok=True)
                    self.endpoints.append(
                        f"http://127.0.0.1:{self.ports[i]}{path}")
        else:
            self.pool_specs = []
            for pi, spec in enumerate(pools):
                if isinstance(spec, int):
                    spec = (list(range(nodes)), spec)
                node_list, drives = list(spec[0]), int(spec[1])
                self.pool_specs.append((node_list, drives))
                eps = []
                for i in node_list:
                    for d in range(drives):
                        path = os.path.join(self.root, f"n{i}",
                                            f"p{pi}d{d}")
                        os.makedirs(path, exist_ok=True)
                        eps.append(
                            f"http://127.0.0.1:{self.ports[i]}{path}")
                # A single-endpoint pool keeps a trailing comma so the
                # arg still parses as its OWN pool, not a plain arg
                # merged with others.
                self.pool_args.append(
                    ",".join(eps) + ("," if len(eps) == 1 else ""))
                self.endpoints.extend(eps)

    # -- lifecycle -----------------------------------------------------

    def chaos_path(self, i: int) -> str:
        return os.path.join(self.root, f"chaos-n{i}.json")

    def log_path(self, i: int) -> str:
        return os.path.join(self.root, f"node{i}.log.{self._gen[i]}")

    def address(self, i: int) -> str:
        return f"127.0.0.1:{self.ports[i]}"

    def spawn(self, i: int) -> None:
        self._gen[i] += 1
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO_ROOT,
                   MTPU_GRID_CHAOS=self.chaos_path(i),
                   # Fast re-arm + fast breaker recovery at test scale;
                   # callers override via env=.
                   MTPU_GRID_SYNC_S="0.5",
                   MTPU_GRID_COOLDOWN="0.25",
                   **self.env)
        cmd = [sys.executable, "-m", "minio_tpu.server",
               "--address", self.address(i), "--ec-backend", "host",
               *self.extra, *(self.pool_args or self.endpoints)]
        log = open(self.log_path(i), "wb")
        # Own session per node so kill() can nuke the WHOLE node — in
        # worker mode (workers > 1) the Popen pid is only the
        # supervising parent; SIGKILLing it alone would orphan the
        # pre-forked workers, which keep serving on the node's ports.
        self.procs[i] = subprocess.Popen(cmd, stdout=log,
                                         stderr=subprocess.STDOUT, env=env,
                                         cwd=REPO_ROOT,
                                         start_new_session=True)

    def start(self, wait: bool = True) -> "Cluster":
        for i in range(self.n):
            self.spawn(i)
        if wait:
            self.wait_ready()
        return self

    def wait_ready(self, idx: Optional[int] = None,
                   timeout: float = 120.0) -> None:
        nodes = [idx] if idx is not None else list(range(self.n))
        deadline = time.time() + timeout
        for i in nodes:
            path = self.log_path(i)
            while True:
                blob = b""
                if os.path.exists(path):
                    with open(path, "rb") as fh:
                        blob = fh.read()
                if b"serving S3" in blob:
                    break
                p = self.procs.get(i)
                if p is not None and p.poll() is not None:
                    raise RuntimeError(
                        f"node {i} exited rc={p.returncode}:\n"
                        f"{blob.decode(errors='replace')[-2000:]}")
                if time.time() > deadline:
                    raise TimeoutError(
                        f"node {i} not ready:\n"
                        f"{blob.decode(errors='replace')[-2000:]}")
                time.sleep(0.25)

    def alive(self, i: int) -> bool:
        p = self.procs.get(i)
        return p is not None and p.poll() is None

    def kill(self, i: int) -> None:
        """SIGKILL — a crash, not a drain: held dsync locks leak until
        their TTL, staged writes stay torn, no clean-shutdown stamp.
        Kills the node's whole process GROUP (worker mode forks)."""
        p = self.procs.get(i)
        if p is None:
            return
        try:
            self._signal_group(p, signal.SIGKILL)
            p.wait(timeout=10)
        except OSError:
            pass
        self.procs[i] = None

    @staticmethod
    def _signal_group(p: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(p.pid, sig)
        except (OSError, ProcessLookupError):
            try:
                p.send_signal(sig)
            except OSError:
                pass

    def worker_pids(self, i: int) -> list[int]:
        """Pids of node i's pre-forked worker children (empty in
        single-process mode). /proc walk: children of the Popen pid."""
        p = self.procs.get(i)
        if p is None:
            return []
        try:
            with open(f"/proc/{p.pid}/task/{p.pid}/children") as fh:
                return [int(x) for x in fh.read().split()]
        except OSError:
            return []

    def restart(self, i: int, wait: bool = True) -> None:
        if self.alive(i):
            self.kill(i)
        self.rejoin(i)
        self.spawn(i)
        if wait:
            self.wait_ready(i)

    # -- chaos ---------------------------------------------------------

    def _write_chaos(self, i: int, cfg: dict) -> None:
        tmp = self.chaos_path(i) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(cfg, fh)
        os.replace(tmp, self.chaos_path(i))

    def partition(self, i: int) -> None:
        self._write_chaos(i, {"mode": "blackhole"})

    def drop(self, i: int) -> None:
        self._write_chaos(i, {"mode": "drop"})

    def delay(self, i: int, seconds: float) -> None:
        self._write_chaos(i, {"mode": "delay", "seconds": seconds})

    def hang_drives(self, i: int, seconds: float) -> None:
        self._write_chaos(i, {"drive_delay": seconds})

    def rejoin(self, i: int) -> None:
        try:
            os.unlink(self.chaos_path(i))
        except OSError:
            pass

    # -- clients -------------------------------------------------------

    def client(self, i: int, **kw):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from s3client import S3Client
        return S3Client(self.address(i), **kw)

    def drive_dir(self, i: int, d: int) -> str:
        return os.path.join(self.root, f"n{i}", f"d{d}")

    def pool_drive_dir(self, i: int, pool: int, d: int) -> str:
        """Drive dir in the multi-pool layout (`pools=` ctor arg)."""
        return os.path.join(self.root, f"n{i}", f"p{pool}d{d}")

    # -- teardown ------------------------------------------------------

    def stop(self) -> None:
        for i in list(self.procs):
            p = self.procs.get(i)
            if p is not None:
                self._signal_group(p, signal.SIGKILL)
        for i in list(self.procs):
            p = self.procs.get(i)
            if p is not None:
                try:
                    p.wait(timeout=10)
                except Exception:  # noqa: BLE001 - teardown best effort
                    pass
        self.procs.clear()

    def __enter__(self) -> "Cluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def logs(self, i: int) -> str:
        out = []
        for g in range(1, self._gen[i] + 1):
            path = os.path.join(self.root, f"node{i}.log.{g}")
            if os.path.exists(path):
                with open(path, "rb") as fh:
                    out.append(fh.read().decode(errors="replace"))
        return "\n".join(out)
