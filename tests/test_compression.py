"""Transparent compression: block scheme, ranged reads, API behavior
(reference: cmd/object-api-utils.go compression + seekable index)."""

import os

import pytest

from minio_tpu.crypto import compress as comp
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


# ---------------------------------------------------------------------------
# scheme
# ---------------------------------------------------------------------------

def test_compress_roundtrip_and_index():
    data = (b"line of text %08d\n" * 150_000) % tuple(range(150_000))
    assert len(data) > 2 * comp.BLOCK       # spans 3+ blocks
    result = comp.compress(data)
    assert result is not None
    stored, meta = result
    assert len(stored) < len(data)
    assert comp.decompress_range(stored, meta, 0, len(data)) == data
    # Block-crossing range.
    lo, ln = comp.BLOCK - 100, 300
    assert comp.decompress_range(stored, meta, lo, ln) == data[lo:lo + ln]
    # Partial fetch via stored_range + stored_base.
    slo, sln = comp.stored_range(meta, lo, ln)
    assert comp.decompress_range(stored[slo:slo + sln], meta, lo, ln,
                                 stored_base=slo) == data[lo:lo + ln]


def test_incompressible_returns_none():
    assert comp.compress(os.urandom(100_000)) is None


def test_eligibility():
    assert comp.eligible("logs/app.log", "")
    assert comp.eligible("data.bin", "text/plain")
    assert not comp.eligible("photo.jpg", "image/jpeg")


def test_corrupt_index_or_block_detected():
    data = b"compressible " * 10_000
    stored, meta = comp.compress(data)
    bad = dict(meta)
    bad[comp.META_INDEX] = "!!!!"
    with pytest.raises(comp.CompressionError):
        comp.decompress_range(stored, bad, 0, len(data))
    mangled = bytearray(stored)
    mangled[10] ^= 0xFF
    with pytest.raises(comp.CompressionError):
        comp.decompress_range(bytes(mangled), meta, 0, len(data))


# ---------------------------------------------------------------------------
# API end to end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("compdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.compression = True
    server.start()
    yield server, es
    server.stop()


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv[0].address)
    assert c.request("PUT", "/compb")[0] == 200
    return c


def test_compressed_put_get_roundtrip(cli, srv):
    body = (b"a log line that compresses nicely %08d\n" * 60_000) \
        % tuple(range(60_000))
    st, _, _ = cli.request("PUT", "/compb/app.log", body=body)
    assert st == 200
    st, hh, got = cli.request("GET", "/compb/app.log")
    assert st == 200 and got == body
    assert hh.get("Content-Length") == str(len(body))
    # On-disk footprint is the compressed stream (visible via the
    # object layer's raw size).
    es = srv[1]
    from minio_tpu.object.types import GetOptions
    fi, _, _ = es._get_object_fileinfo("compb", "app.log")
    assert fi.size < len(body)


def test_compressed_ranged_get(cli):
    body = (b"0123456789abcdef" * 150_000)       # 2.4 MB, 3 blocks
    assert cli.request("PUT", "/compb/span.txt", body=body)[0] == 200
    lo, hi = comp.BLOCK - 50, comp.BLOCK + 70
    st, hh, got = cli.request("GET", "/compb/span.txt",
                              headers={"Range": f"bytes={lo}-{hi}"})
    assert st == 206
    assert got == body[lo:hi + 1]
    assert hh["Content-Range"] == f"bytes {lo}-{hi}/{len(body)}"


def test_incompressible_and_ineligible_stored_plain(cli, srv):
    es = srv[1]
    rnd = os.urandom(50_000)
    assert cli.request("PUT", "/compb/noise.log", body=rnd)[0] == 200
    _, _, got = cli.request("GET", "/compb/noise.log")
    assert got == rnd
    fi, _, _ = es._get_object_fileinfo("compb", "noise.log")
    assert "x-internal-comp" not in fi.metadata
    text = b"text " * 10_000
    assert cli.request("PUT", "/compb/img.jpg", body=text)[0] == 200
    fi, _, _ = es._get_object_fileinfo("compb", "img.jpg")
    assert "x-internal-comp" not in fi.metadata


def test_copy_of_compressed_source(cli):
    body = b"copyable text " * 20_000
    assert cli.request("PUT", "/compb/src.txt", body=body)[0] == 200
    st, _, b = cli.request("PUT", "/compb/dst.txt", headers={
        "x-amz-copy-source": "/compb/src.txt"})
    assert st == 200, b
    _, _, got = cli.request("GET", "/compb/dst.txt")
    assert got == body


def test_select_over_compressed_object(cli):
    csvd = b"name,n\n" + b"".join(b"row%d,%d\n" % (i, i)
                                  for i in range(5000))
    assert cli.request("PUT", "/compb/rows.csv", body=csvd)[0] == 200
    req = (b"<SelectObjectContentRequest>"
           b"<Expression>SELECT name FROM S3Object WHERE n = 4999"
           b"</Expression><ExpressionType>SQL</ExpressionType>"
           b"<InputSerialization><CSV><FileHeaderInfo>USE"
           b"</FileHeaderInfo></CSV></InputSerialization>"
           b"<OutputSerialization><CSV/></OutputSerialization>"
           b"</SelectObjectContentRequest>")
    st, _, resp = cli.request("POST", "/compb/rows.csv",
                              query={"select": "", "select-type": "2"},
                              body=req)
    assert st == 200
    from minio_tpu.s3select.eventstream import decode_messages
    recs = b"".join(p for h, p in decode_messages(resp)
                    if h.get(":event-type") == "Records")
    assert recs == b"row4999\n"
