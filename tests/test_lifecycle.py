"""ILM: lifecycle parsing, evaluation, and scanner-driven expiry with an
accelerated clock (reference: internal/bucket/lifecycle,
cmd/bucket-lifecycle.go)."""

import time

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.lifecycle import (LifecycleError, evaluate,
                                        make_scanner_hook, parse_lifecycle)
from minio_tpu.object.scanner import Scanner
from minio_tpu.object.types import DeleteOptions, ObjectNotFound, PutOptions
from minio_tpu.storage.local import LocalStorage

LC_1DAY = b"""<LifecycleConfiguration>
  <Rule><ID>expire-1d</ID><Status>Enabled</Status>
    <Filter><Prefix>tmp/</Prefix></Filter>
    <Expiration><Days>1</Days></Expiration>
  </Rule>
</LifecycleConfiguration>"""

LC_NONCURRENT = b"""<LifecycleConfiguration>
  <Rule><ID>nc</ID><Status>Enabled</Status>
    <NoncurrentVersionExpiration><NoncurrentDays>2</NoncurrentDays>
    </NoncurrentVersionExpiration>
    <Expiration><ExpiredObjectDeleteMarker>true</ExpiredObjectDeleteMarker>
    </Expiration>
  </Rule>
</LifecycleConfiguration>"""


def test_parse_rules():
    rules = parse_lifecycle(LC_1DAY)
    assert len(rules) == 1
    assert rules[0].rule_id == "expire-1d"
    assert rules[0].prefix == "tmp/"
    assert rules[0].expiration_days == 1
    rules = parse_lifecycle(LC_NONCURRENT)
    assert rules[0].noncurrent_days == 2
    assert rules[0].expire_delete_marker


def test_parse_rejects_garbage():
    with pytest.raises(LifecycleError):
        parse_lifecycle(b"<not-lifecycle/>")
    with pytest.raises(LifecycleError):
        parse_lifecycle(b"<LifecycleConfiguration><Rule><Expiration>"
                        b"<Days>0</Days></Expiration></Rule>"
                        b"</LifecycleConfiguration>")


class _V:
    def __init__(self, mod_time_s, deleted=False, vid=""):
        self.mod_time = int(mod_time_s * 1e9)
        self.deleted = deleted
        self.version_id = vid


def test_evaluate_expiration_days():
    rules = parse_lifecycle(LC_1DAY)
    now = time.time()
    fresh = [_V(now - 3600)]
    old = [_V(now - 2 * 86400)]
    assert evaluate(rules, "tmp/x", fresh, now=now) == []
    acts = evaluate(rules, "tmp/x", old, now=now)
    assert [a.kind for a in acts] == ["expire_latest"]
    # Prefix filter respected.
    assert evaluate(rules, "keep/x", old, now=now) == []


def test_evaluate_noncurrent_and_marker():
    rules = parse_lifecycle(LC_NONCURRENT)
    now = time.time()
    stack = [_V(now - 3 * 86400, deleted=True, vid="m1"),
             _V(now - 4 * 86400, vid="v2"),
             _V(now - 9 * 86400, vid="v1")]
    acts = evaluate(rules, "any", stack, now=now)
    kinds = {(a.kind, a.version_id) for a in acts}
    # v2 became noncurrent 3d ago (when m1 superseded it) -> expire;
    # v1 became noncurrent 4d ago -> expire. Marker is not lone -> kept.
    assert ("delete_version", "v2") in kinds
    assert ("delete_version", "v1") in kinds
    assert not any(k == "drop_marker" for k, _ in kinds)
    # Lone marker cleans up.
    acts = evaluate(rules, "any", [_V(now - 3 * 86400, deleted=True,
                                      vid="m1")], now=now)
    assert [(a.kind, a.version_id) for a in acts] == [("drop_marker", "m1")]


@pytest.fixture
def es(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    s = ErasureSet(disks)
    s.make_bucket("ilmb")
    return s


def test_scanner_expires_objects_accelerated_clock(es):
    meta = es.get_bucket_meta("ilmb")
    meta["config:lifecycle"] = LC_1DAY.decode()
    es.set_bucket_meta("ilmb", meta)
    es.put_object("ilmb", "tmp/doomed", b"bye")
    es.put_object("ilmb", "tmp/alive", b"hi")
    es.put_object("ilmb", "keep/safe", b"safe")

    # Clock two days in the future: tmp/* is past its 1-day expiry.
    future = time.time() + 2 * 86400
    sc = Scanner([es], throttle=0)
    sc.on_object.append(make_scanner_hook(now_fn=lambda: future))
    sc.scan_cycle()

    with pytest.raises(ObjectNotFound):
        es.get_object("ilmb", "tmp/doomed")
    with pytest.raises(ObjectNotFound):
        es.get_object("ilmb", "tmp/alive")
    _, got = es.get_object("ilmb", "keep/safe")
    assert got == b"safe"


def test_scanner_expiry_versioned_leaves_marker(es):
    meta = es.get_bucket_meta("ilmb")
    meta["config:lifecycle"] = LC_1DAY.decode()
    meta["versioning"] = True
    es.set_bucket_meta("ilmb", meta)
    es.put_object("ilmb", "tmp/vdoc", b"v1", PutOptions(versioned=True))

    future = time.time() + 2 * 86400
    sc = Scanner([es], throttle=0)
    sc.on_object.append(make_scanner_hook(now_fn=lambda: future))
    sc.scan_cycle()

    # Latest is now a delete marker; the data version survives beneath.
    with pytest.raises(ObjectNotFound):
        es.get_object("ilmb", "tmp/vdoc")
    versions = es.list_versions_all("ilmb", "tmp/vdoc")
    assert any(v.deleted for v in versions)
    assert any(not v.deleted for v in versions)
