"""Batch jobs: bulk replicate/expire with filters, checkpointed resume
(reference: cmd/batch-handlers.go:1879)."""

import datetime
import json
import os
import time

import pytest

from minio_tpu.crypto.kms import AESGCM as _AESGCM

requires_crypto = pytest.mark.skipif(
    _AESGCM is None,
    reason="SSE needs the optional 'cryptography' wheel")

from minio_tpu.object.batch import BatchError, BatchJobs, validate_job
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import PutOptions
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


@pytest.fixture
def es(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    s = ErasureSet(disks)
    s.make_bucket("srcb")
    s.make_bucket("dstb")
    return s


def test_validate_job():
    with pytest.raises(BatchError):
        validate_job({"type": "wipe"})
    with pytest.raises(BatchError):
        validate_job({"type": "replicate", "source": {}})
    with pytest.raises(BatchError):
        validate_job({"type": "replicate", "source": {"bucket": "a"},
                      "target": {"bucket": "a"}})
    with pytest.raises(BatchError):
        validate_job({"type": "replicate", "source": {"bucket": "a"},
                      "target": {"bucket": "b", "endpoint": "h:1"}})
    with pytest.raises(BatchError):
        validate_job({"type": "expire", "source": {"bucket": "a"},
                      "filters": {"createdBefore": "not-a-date"}})
    validate_job({"type": "expire", "source": {"bucket": "a"}})


def test_replicate_job_with_filters(es):
    for i in range(6):
        es.put_object("srcb", f"app/k{i}", f"body{i}".encode(),
                      PutOptions(tags="team=eng" if i % 2 == 0 else
                                 "team=ops",
                                 user_metadata={"n": str(i)}))
    es.put_object("srcb", "other/x", b"skip me")
    mgr = BatchJobs(es, [es])
    jid = mgr.start({"type": "replicate",
                     "source": {"bucket": "srcb", "prefix": "app/"},
                     "target": {"bucket": "dstb", "prefix": "copied/"},
                     "filters": {"tags": {"team": "eng"}}})
    assert mgr.wait(jid, 60)
    st = mgr.status(jid)
    assert st["status"] == "complete", st
    assert st["processed"] == 3 and st["failed"] == 0
    for i in (0, 2, 4):
        info, got = es.get_object("dstb", f"copied/app/k{i}")
        assert got == f"body{i}".encode()
        assert info.user_metadata.get("n") == str(i)
        assert "team=eng" in info.user_tags
    from minio_tpu.object.types import ObjectNotFound
    with pytest.raises(ObjectNotFound):
        es.get_object("dstb", "copied/app/k1")
    with pytest.raises(ObjectNotFound):
        es.get_object("dstb", "copied/other/x")


def test_expire_job_created_before(es):
    old = time.time_ns() - 10 * 86400 * 10**9
    es.put_object("srcb", "old/doomed", b"x", PutOptions(mod_time=old))
    es.put_object("srcb", "old/fresh", b"y")
    cutoff = datetime.datetime.fromtimestamp(
        time.time() - 86400, tz=datetime.timezone.utc).isoformat()
    mgr = BatchJobs(es, [es])
    jid = mgr.start({"type": "expire",
                     "source": {"bucket": "srcb", "prefix": "old/"},
                     "filters": {"createdBefore": cutoff}})
    assert mgr.wait(jid, 60)
    st = mgr.status(jid)
    assert st["status"] == "complete" and st["processed"] == 1, st
    from minio_tpu.object.types import ObjectNotFound
    with pytest.raises(ObjectNotFound):
        es.get_object("srcb", "old/doomed")
    _, got = es.get_object("srcb", "old/fresh")
    assert got == b"y"


def test_job_cancel_and_resume(es):
    for i in range(40):
        es.put_object("srcb", f"bulk/{i:03d}", os.urandom(2000))
    mgr = BatchJobs(es, [es], checkpoint_every=4)
    jid = mgr.start({"type": "replicate",
                     "source": {"bucket": "srcb", "prefix": "bulk/"},
                     "target": {"bucket": "dstb"}})
    # Cancel partway (poll the persisted state, not the thread).
    deadline = time.time() + 30
    while time.time() < deadline:
        st = mgr.status(jid)
        if st and st["processed"] >= 8:
            break
        time.sleep(0.01)
    mgr.cancel(jid)
    mgr.wait(jid, 30)
    st = mgr.status(jid)
    assert st["status"] == "cancelled"
    # "Restart": new manager resumes running jobs only — cancelled
    # jobs stay cancelled.
    mgr2 = BatchJobs(es, [es])
    assert mgr2.resume_all() == 0
    # Flip it back to running (simulating a crash instead of cancel)
    # and resume: completes idempotently.
    full = mgr2._load(jid)
    full["status"] = "running"
    mgr2._save(full)
    assert mgr2.resume_all() == 1
    assert mgr2.wait(jid, 60)
    st = mgr2.status(jid)
    assert st["status"] == "complete", st
    for i in range(40):
        es.get_object("dstb", f"bulk/{i:03d}")


def test_remote_replicate_and_admin_api(tmp_path):
    """End-to-end over HTTP: a batch job copies to ANOTHER live server,
    driven entirely through the admin API."""
    from minio_tpu.s3.server import S3Server
    src_disks = [LocalStorage(str(tmp_path / "src" / f"d{i}"))
                 for i in range(4)]
    dst_disks = [LocalStorage(str(tmp_path / "dst" / f"d{i}"))
                 for i in range(4)]
    src_srv = S3Server(ErasureSet(src_disks), address="127.0.0.1:0")
    dst_srv = S3Server(ErasureSet(dst_disks), address="127.0.0.1:0")
    src_srv.start()
    dst_srv.start()
    try:
        src_cli = S3Client(src_srv.address)
        dst_cli = S3Client(dst_srv.address)
        assert src_cli.request("PUT", "/jobsrc")[0] == 200
        assert dst_cli.request("PUT", "/jobdst")[0] == 200
        bodies = {f"d/{i}": os.urandom(5000) for i in range(5)}
        for k, b in bodies.items():
            assert src_cli.request("PUT", f"/jobsrc/{k}", body=b)[0] == 200
        spec = {"type": "replicate",
                "source": {"bucket": "jobsrc", "prefix": "d/"},
                "target": {"bucket": "jobdst",
                           "endpoint": dst_srv.address,
                           "accessKey": "minioadmin",
                           "secretKey": "minioadmin"}}
        st, _, b = src_cli.request("POST",
                                   "/minio/admin/v3/start-batch-job",
                                   body=json.dumps(spec).encode())
        assert st == 200, b
        jid = json.loads(b)["id"]
        deadline = time.time() + 60
        while time.time() < deadline:
            st, _, b = src_cli.request(
                "GET", "/minio/admin/v3/batch-job-status",
                query={"id": jid})
            doc = json.loads(b)
            if doc.get("status") in ("complete", "failed"):
                break
            time.sleep(0.3)
        assert doc["status"] == "complete", doc
        # Credentials never echo in status/list responses.
        assert "secretKey" not in json.dumps(doc)
        st, _, b = src_cli.request("GET",
                                   "/minio/admin/v3/list-batch-jobs")
        assert st == 200 and jid.encode() in b
        assert b"secretKey" not in b
        for k, body in bodies.items():
            st, _, got = dst_cli.request("GET", f"/jobdst/{k}")
            assert st == 200 and got == body
    finally:
        src_srv.stop()
        dst_srv.stop()


@requires_crypto
def test_batch_keyrotate_reseals_sse_objects(tmp_path):
    """keyrotate (reference: cmd/batch-rotate.go): SSE-S3 objects'
    sealed data keys re-seal under a new named key in place — data
    never moves, old-master compromise stops mattering."""
    import base64 as _b64
    import hashlib as _hash
    import json as _json

    from minio_tpu.crypto import (EncryptingPayload, encrypt_stream_size,
                                  sse as sse_mod)
    from minio_tpu.crypto.kms import KMS
    from minio_tpu.object.batch import BatchJobs
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.object.types import GetOptions, PutOptions
    from minio_tpu.storage.local import LocalStorage
    from minio_tpu.utils.streams import Payload

    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("rotb")
    kms = KMS({"old": b"\x01" * 32, "new": b"\x02" * 32}, "old")
    bodies = {}
    for i in range(3):
        body = os.urandom(40_000)
        bodies[f"s{i}"] = body
        data_key, nonce, imeta = sse_mod.encrypt_metadata(
            "rotb", f"s{i}", len(body), kms, None)
        opts = PutOptions()
        opts.internal_metadata.update(imeta)
        enc = Payload(EncryptingPayload(Payload.wrap(body), data_key,
                                        nonce),
                      encrypt_stream_size(len(body)))
        es.put_object("rotb", f"s{i}", enc, opts)
    es.put_object("rotb", "plain", b"not encrypted")
    # A versioned stack: BOTH versions must rotate (an Enabled-era
    # version left under the old master would die with it).
    ver_keys = []
    for txt in (b"v-one", b"v-two"):
        dk, nonce, imeta = sse_mod.encrypt_metadata(
            "rotb", "vstack", len(txt), kms, None)
        opts = PutOptions(versioned=True)
        opts.internal_metadata.update(imeta)
        enc = Payload(EncryptingPayload(Payload.wrap(txt), dk, nonce),
                      encrypt_stream_size(len(txt)))
        info = es.put_object("rotb", "vstack", enc, opts)
        ver_keys.append(info.version_id)

    jobs = BatchJobs(es, [es])
    jobs.kms = kms
    jid = jobs.start({"type": "keyrotate",
                       "source": {"bucket": "rotb"},
                       "encryption": {"keyId": "new"}})
    assert jobs.wait(jid, 30)
    st = jobs.status(jid)
    assert st["status"] == "complete", st
    # Every SSE object's sealed blob now names the new key and unseals
    # under it — even with the old master gone.
    kms_new_only = KMS({"new": b"\x02" * 32}, "new")
    for name, body in bodies.items():
        info = es.get_object_info("rotb", name, GetOptions())
        sealed = info.internal_metadata[sse_mod.META_KEY]
        assert _json.loads(sealed)["kid"] == "new"
        data_key = kms_new_only.unseal(sealed,
                                       {"bucket": "rotb", "object": name})
        # The rotated key still decrypts the stored bytes.
        from minio_tpu.crypto.dare import decrypt_packages
        nonce = _b64.b64decode(info.internal_metadata[sse_mod.META_NONCE])
        _, stored = es.get_object("rotb", name, GetOptions())
        plain = b"".join(decrypt_packages(iter([stored]), data_key,
                                          nonce, 0, 0, len(body)))
        assert plain == body
    # The plaintext object was skipped untouched.
    _, got = es.get_object("rotb", "plain", GetOptions())
    assert got == b"not encrypted"
    # Every VERSION of the stack now seals under the new key.
    for vid in ver_keys:
        info = es.get_object_info("rotb", "vstack",
                                  GetOptions(version_id=vid))
        sealed = info.internal_metadata[sse_mod.META_KEY]
        assert _json.loads(sealed)["kid"] == "new", vid
