import numpy as np
import pytest

from minio_tpu.erasure.codec import (Erasure, ReconstructError, ShardSizeError,
                                     ceil_frac)
from minio_tpu.erasure.selftest import erasure_self_test, BLOCK_SIZE_V2


def test_golden_selftest_host():
    erasure_self_test()  # raises on any byte mismatch vs reference


def test_shard_size_math():
    e = Erasure(8, 4, BLOCK_SIZE_V2)
    assert e.shard_size() == ceil_frac(BLOCK_SIZE_V2, 8) == 131072
    assert e.shard_file_size(0) == 0
    assert e.shard_file_size(-1) == -1
    assert e.shard_file_size(BLOCK_SIZE_V2) == 131072
    assert e.shard_file_size(BLOCK_SIZE_V2 + 1) == 131072 + 1
    # offsets clamp at shard file size
    assert e.shard_file_offset(0, BLOCK_SIZE_V2, BLOCK_SIZE_V2) == 131072


@pytest.mark.parametrize("k,m", [(2, 2), (4, 2), (8, 4), (5, 3)])
def test_encode_reconstruct_roundtrip(k, m):
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, size=1 << 16, dtype=np.uint8).tobytes()
    e = Erasure(k, m, BLOCK_SIZE_V2)
    shards = e.encode_data(data)
    assert len(shards) == k + m
    # Drop m arbitrary shards (mix of data+parity), reconstruct data.
    victims = list(range(1, 1 + m))
    saved = [shards[v].copy() for v in victims]
    for v in victims:
        shards[v] = np.zeros(0, dtype=np.uint8)
    e.decode_data_blocks(shards)
    for v, s in zip(victims, saved):
        if v < k:
            assert np.array_equal(shards[v], s)
    assert e.join(shards, len(data)) == data


def test_reconstruct_all_parity():
    k, m = 4, 2
    e = Erasure(k, m, BLOCK_SIZE_V2)
    data = bytes(range(256)) * 17
    shards = e.encode_data(data)
    want = [s.copy() for s in shards]
    shards[0] = np.zeros(0, dtype=np.uint8)
    shards[5] = np.zeros(0, dtype=np.uint8)
    e.decode_data_and_parity_blocks(shards)
    for a, b in zip(shards, want):
        assert np.array_equal(a, b)


def test_too_few_shards_raises():
    k, m = 4, 2
    e = Erasure(k, m, BLOCK_SIZE_V2)
    shards = e.encode_data(b"x" * 1024)
    for i in range(3):
        shards[i] = np.zeros(0, dtype=np.uint8)
    with pytest.raises(ReconstructError):
        e.decode_data_blocks(shards)


def test_empty_input():
    e = Erasure(4, 2, BLOCK_SIZE_V2)
    shards = e.encode_data(b"")
    assert len(shards) == 6 and all(s.size == 0 for s in shards)
    # Decoding all-empty raises (total loss is indistinguishable from a
    # 0-byte payload at this layer; read paths skip decode for length 0,
    # mirroring the reference where ReconstructData errors here).
    with pytest.raises(ReconstructError):
        e.decode_data_blocks(shards)


def test_all_shards_missing_raises():
    # Total loss must surface as ReconstructError, never silent success.
    e = Erasure(4, 2, 1 << 20)
    shards = [None] * 6
    with pytest.raises(ReconstructError):
        e.decode_data_blocks(shards)


def test_truncated_shard_raises_shard_size_error():
    e = Erasure(4, 2, 1 << 20)
    shards = e.encode_data(bytes(range(100)))
    shards[0] = None
    shards[1] = shards[1][:-3]  # truncated survivor
    with pytest.raises(ShardSizeError):
        e.decode_data_blocks(shards)
