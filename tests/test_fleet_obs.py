"""Fleet-wide observability: cross-node trace propagation over the
grid, disarmed-path wire conformance, chaos fault annotation, the
continuous SLO engine, and the metrics label-cardinality guard.

The in-process half runs a REAL GridServer/GridClient pair so the
armed and disarmed wire formats are tested against the actual frames;
the cluster half spawns the 3-node harness (tests/cluster.py) and
drives partition/kill chaos against an armed distributed GET.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac as hmac_mod
import http.client
import importlib.util
import json
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from minio_tpu.grid import wire  # noqa: E402
from minio_tpu.grid.client import GridClient  # noqa: E402
from minio_tpu.grid.server import GridServer  # noqa: E402
from minio_tpu.grid.wire import GridError, RemoteCallError  # noqa: E402
from minio_tpu.s3 import sigv4  # noqa: E402
from minio_tpu.s3.metrics import Metrics  # noqa: E402
from minio_tpu.utils import tracing  # noqa: E402
from minio_tpu.utils.slo import SLOEngine  # noqa: E402
from tests.cluster import Cluster  # noqa: E402


# ---------------------------------------------------------------------------
# in-process grid pair
# ---------------------------------------------------------------------------

@pytest.fixture
def grid_pair():
    srv = GridServer(0, host="127.0.0.1")
    hold = threading.Event()

    def spanny(p):
        with tracing.span("storage", "disk.read_file", {"vol": "v"}) \
                if tracing.ACTIVE else tracing.NOOP:
            time.sleep(0.002)
        return "ok"

    def boom(p):
        with tracing.span("storage", "disk.delete_file", {}) \
                if tracing.ACTIVE else tracing.NOOP:
            pass
        raise ValueError("nope")

    def slow(p):
        hold.wait(timeout=10)
        return "done"

    def walk(p):
        for i in range(3):
            with tracing.span("storage", "disk.walk", {"page": i}) \
                    if tracing.ACTIVE else tracing.NOOP:
                pass
            yield i

    srv.register("echo", lambda p: p)
    srv.register("spanny", spanny)
    srv.register("boom", boom)
    srv.register("slow", slow)
    srv.register_stream("walk", walk)
    srv.start()
    cli = GridClient("127.0.0.1", srv.port, connect_timeout=2.0)
    yield srv, cli, hold
    hold.set()
    try:
        srv.stop()
    except Exception:  # noqa: BLE001 - some tests stop it themselves
        pass


class _Armed:
    """Arm span collection for one test and bind a fresh context."""

    def __enter__(self):
        self.tok = object()
        tracing.arm(self.tok)
        self.ctx = tracing.TraceContext()
        self.bind = tracing.bind(self.ctx, 0)
        self.bind.__enter__()
        return self.ctx

    def __exit__(self, *exc):
        self.bind.__exit__(*exc)
        tracing.disarm(self.tok)


def _by_name(ctx, name):
    return [s for s in ctx.spans if s["name"] == name]


def test_armed_unary_stitches_remote_subtree(grid_pair):
    srv, cli, _ = grid_pair
    with _Armed() as ctx:
        assert cli.call("spanny", {"x": 1}, timeout=5.0) == "ok"
    call = _by_name(ctx, "grid.spanny")
    wires = _by_name(ctx, "wire")
    remote = _by_name(ctx, "disk.read_file")
    assert len(call) == len(wires) == len(remote) == 1
    # Tree: grid.spanny <- wire <- disk.read_file, ids remapped into
    # the caller's sequence (all distinct).
    assert wires[0]["parent"] == call[0]["span"]
    assert remote[0]["parent"] == wires[0]["span"]
    ids = {s["span"] for s in ctx.spans}
    assert len(ids) == len(ctx.spans)
    # The wire span carries the full timing split.
    tags = wires[0]["tags"]
    for k in ("peer", "serialize_ms", "peer_queue_ms",
              "peer_service_ms", "transit_ms"):
        assert k in tags, tags
    assert tags["peer_service_ms"] >= 2.0   # the handler slept 2 ms
    assert "fault" not in tags


def test_armed_stream_stitches_remote_subtree(grid_pair):
    srv, cli, _ = grid_pair
    with _Armed() as ctx:
        got = list(cli.stream("walk", {}, timeout=5.0))
    assert got == [0, 1, 2]
    call = _by_name(ctx, "grid.walk")
    wires = _by_name(ctx, "wire")
    remote = _by_name(ctx, "disk.walk")
    assert len(call) == len(wires) == 1 and len(remote) == 3
    assert call[0]["tags"]["chunks"] == 3
    assert wires[0]["parent"] == call[0]["span"]
    assert all(s["parent"] == wires[0]["span"] for s in remote)
    assert "peer_service_ms" in wires[0]["tags"]


def test_remote_error_still_ships_subtree(grid_pair):
    """A handler that RAISES still answered: its spans ship back on the
    T_ERR frame and stitch (the fault is the handler's, not the
    transport's)."""
    srv, cli, _ = grid_pair
    with _Armed() as ctx:
        with pytest.raises(RemoteCallError):
            cli.call("boom", {}, timeout=5.0)
    wires = _by_name(ctx, "wire")
    assert len(wires) == 1 and "fault" not in wires[0]["tags"]
    assert len(_by_name(ctx, "disk.delete_file")) == 1


def test_disarmed_grid_wire_carries_zero_trace_bytes(grid_pair,
                                                     monkeypatch):
    """Disarmed-path conformance: no `tc` on requests, no `ts` on
    replies — the propagation machinery must be invisible on the wire
    unless the caller armed the request."""
    srv, cli, _ = grid_pair
    assert not tracing.ACTIVE
    frames = []
    real_pack = wire.pack_frame

    def spy(msg):
        frames.append(dict(msg))
        return real_pack(msg)

    monkeypatch.setattr(wire, "pack_frame", spy)
    assert cli.call("echo", {"a": 1}, timeout=5.0) == {"a": 1}
    assert list(cli.stream("walk", {}, timeout=5.0)) == [0, 1, 2]
    reqs = [f for f in frames if f["t"] in (wire.T_REQ, wire.T_SREQ)]
    resps = [f for f in frames if f["t"] in (wire.T_RESP, wire.T_ERR,
                                             wire.T_EOF)]
    assert reqs and resps
    assert all("tc" not in f and "_rx" not in f for f in reqs), reqs
    assert all("ts" not in f for f in resps), resps


def test_peer_killed_mid_armed_call_annotates_fault(grid_pair):
    """Transport death mid-armed-call: the caller's tree still
    completes — the wire span carries the fault, nothing stitches, no
    arm token leaks — and the now-open breaker fast-fails the next
    call with the same annotation (a stale reply can never stitch:
    its mux entry is gone)."""
    srv, cli, hold = grid_pair
    with _Armed() as ctx:
        killer = threading.Timer(0.3, srv.stop)
        killer.start()
        with pytest.raises((GridError, Exception)):
            cli.call("slow", {}, timeout=5.0)
        killer.join()
        hold.set()
        wires = _by_name(ctx, "wire")
        assert len(wires) == 1
        assert wires[0]["tags"]["fault"] in (
            "conn_lost", "GridError", "DeadlineExceeded")
        # Transport fault => no remote subtree: exactly the grid call
        # span and its wire span.
        assert {s["name"] for s in ctx.spans} == {"grid.slow", "wire"}
        before = len(ctx.spans)
        # Breaker (or dead socket) path: fails fast, still annotated.
        with pytest.raises(GridError):
            cli.call("echo", {}, timeout=1.0)
        wires = _by_name(ctx, "wire")
        assert len(wires) == 2 and "fault" in wires[1]["tags"]
        assert len(ctx.spans) == before + 2    # call + wire, no stitch
    # No leaked arm tokens: the module gate is back to one attr check.
    assert not tracing.ACTIVE
    with tracing._arm_mu:
        assert not tracing._arm_sources


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

def _eng(spec, now, **kw):
    return SLOEngine(objectives=[spec], eval_s=5.0, now=now, **kw)


def test_slo_budget_arithmetic():
    t = [1000.0]
    eng = _eng({"name": "o", "match": ["GET:object"], "p99_ms": 0,
                "error_budget": 0.02, "window_s": 60},
               now=lambda: t[0])
    for _ in range(98):
        eng.observe("GET:object", 200)
    for _ in range(2):
        eng.observe("GET:object", 500)
    eng.observe("PUT:object", 500)          # no match: must not count
    o = eng.evaluate()[0]
    assert o["requests"] == 100 and o["errors"] == 2
    # Exactly at budget: burn 1.0, nothing left, warn (not yet burn).
    assert o["burn_rate"] == pytest.approx(1.0)
    assert o["budget_remaining"] == pytest.approx(0.0)
    assert o["verdict"] == "warn"
    eng.observe("GET:object", 500)
    o = eng.evaluate()[0]
    assert o["burn_rate"] > 1.0 and o["verdict"] == "burn"
    assert o["budget_remaining"] == 0.0


def test_slo_shed_rate_and_warn_thresholds():
    t = [2000.0]
    eng = _eng({"name": "o", "match": ["GET:*"], "p99_ms": 0,
                "error_budget": 0.5, "shed_ceiling": 0.10,
                "window_s": 60}, now=lambda: t[0])
    for _ in range(93):
        eng.observe("GET:object", 200)
    for _ in range(7):
        eng.observe("GET:object", 503)      # shed = error too
    o = eng.evaluate()[0]
    assert o["sheds"] == 7 and o["errors"] == 7
    assert o["shed_rate"] == pytest.approx(0.07)
    # 7% shed: above half the 10% ceiling -> warn, not burn.
    assert o["verdict"] == "warn"
    for _ in range(5):
        eng.observe("GET:object", 503)
    o = eng.evaluate()[0]
    assert o["shed_rate"] > 0.10 and o["verdict"] == "burn"


def test_slo_window_rollover():
    t = [5000.0]
    eng = _eng({"name": "o", "match": ["GET:object"], "p99_ms": 0,
                "error_budget": 0.5, "window_s": 10},
               now=lambda: t[0])
    eng.observe("GET:object", 500)
    assert eng.evaluate()[0]["requests"] == 1
    t[0] += 11.0                            # window slid past the slot
    o = eng.evaluate()[0]
    assert o["requests"] == 0 and o["burn_rate"] == 0.0
    assert o["verdict"] == "pass"
    t[0] = 5010.0                           # same modular slot, reused
    eng.observe("GET:object", 200)
    o = eng.evaluate()[0]
    # Lazy slot reset: the old error must not survive slot reuse.
    assert o["requests"] == 1 and o["errors"] == 0


def test_slo_p99_from_live_rolling_windows():
    t = [3000.0]
    m = Metrics()
    for _ in range(50):
        m.record("GET:object", 200, 0.400)
    eng = _eng({"name": "o", "match": ["GET:object"], "p99_ms": 100,
                "error_budget": 0.5, "window_s": 60},
               now=lambda: t[0])
    eng.observe("GET:object", 200)
    o = eng.evaluate(metrics=m)[0]
    assert o["p99_s"] >= 0.4 and o["p99_ceiling_s"] == 0.1
    assert o["verdict"] == "burn"           # latency ceiling blown
    relaxed = _eng({"name": "o", "match": ["GET:object"],
                    "p99_ms": 5000, "error_budget": 0.5,
                    "window_s": 60}, now=lambda: t[0])
    relaxed.observe("GET:object", 200)
    assert relaxed.evaluate(metrics=m)[0]["verdict"] == "pass"


def test_slo_from_env_and_snapshot(monkeypatch):
    monkeypatch.setenv("MTPU_SLO", "off")
    assert SLOEngine.from_env() is None
    monkeypatch.setenv("MTPU_SLO", json.dumps(
        [{"name": "mine", "match": ["GET:object"], "error_budget": 0.1}]))
    eng = SLOEngine.from_env()
    assert [o.name for o in eng.objectives] == ["mine"]
    monkeypatch.setenv("MTPU_SLO", "{not json")
    eng = SLOEngine.from_env()              # malformed -> defaults
    assert {o.name for o in eng.objectives} == {
        "get-availability", "put-availability"}
    snap = eng.snapshot()
    assert snap["verdict"] == "pass"
    assert len(snap["objectives"]) == 2
    for o in snap["objectives"]:
        assert set(o) >= {"burn_rate", "budget_remaining", "verdict",
                          "requests", "p99_s"}


# ---------------------------------------------------------------------------
# metrics label-cardinality guard (scripts/metrics_lint.py)
# ---------------------------------------------------------------------------

def _lint_mod():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "metrics_lint.py")
    spec = importlib.util.spec_from_file_location("metrics_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cardinality_guard_flags_explosions():
    ml = _lint_mod()
    text = "\n".join(
        f'minio_tpu_bad_total{{key="{i}"}} 1' for i in range(70))
    probs = ml.check_exposition(text, cap=64)
    assert len(probs) == 1 and "minio_tpu_bad_total" in probs[0]
    # Allowlisted per-drive family at the same cardinality passes.
    text = "\n".join(
        f'minio_tpu_drive_queue_depth{{drive="{i}"}} 1'
        for i in range(70))
    assert ml.check_exposition(text, cap=64) == []
    # Histogram `le` is a bucket boundary, not a cardinality dimension.
    text = "\n".join(
        f'minio_tpu_h_seconds_bucket{{api="GET",le="{i / 10}"}} 1'
        for i in range(200))
    assert ml.check_exposition(text, cap=64) == []


def test_cardinality_guard_runs_on_synthetic_fleet():
    ml = _lint_mod()
    text = ml._synthetic_fleet_exposition()
    assert "minio_tpu_cluster_node_up{" in text
    assert "minio_tpu_slo_burn_rate{" in text
    assert ml.check_exposition(text) == []


# ---------------------------------------------------------------------------
# cluster chaos: armed distributed GET with a dying peer
# ---------------------------------------------------------------------------

def _stream_trace(address, query: dict, out: list):
    """Signed GET of /minio/admin/v3/trace, de-chunked, JSON lines
    appended to `out` (same shape as tests/test_trace_deep.py)."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"
    payload_hash = hashlib.sha256(b"").hexdigest()
    hdrs = {"host": address, "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash}
    signed = sorted(hdrs)
    q = {k: [v] for k, v in query.items()}
    canon = sigv4.canonical_request("GET", "/minio/admin/v3/trace", q,
                                    hdrs, signed, payload_hash)
    sts = sigv4.string_to_sign(amz_date, scope, canon)
    skey = sigv4.signing_key("minioadmin", date, "us-east-1")
    sig = hmac_mod.new(skey, sts.encode(), hashlib.sha256).hexdigest()
    qs = "&".join(f"{k}={v}" for k, v in sorted(query.items()))
    conn = http.client.HTTPConnection(address, timeout=60)
    conn.request("GET", f"/minio/admin/v3/trace?{qs}", headers={
        **hdrs,
        "Authorization": f"{sigv4.ALGORITHM} "
        f"Credential=minioadmin/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}"})
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    for line in body.splitlines():
        if line.strip():
            out.append(json.loads(line))


def _collect_trace(cluster, i, count, fn):
    """Subscribe types=all on node i, run `fn` once armed, pad with
    health requests until the count limit closes the stream."""
    entries: list = []
    t = threading.Thread(target=_stream_trace,
                         args=(cluster.address(i),
                               {"types": "all", "count": str(count)},
                               entries),
                         daemon=True)
    t.start()
    time.sleep(0.8)                 # subscription armed
    fn()
    cli = cluster.client(i)
    for _ in range(150):
        cli.request("GET", "/minio/health/live", sign=False)
        if not t.is_alive():
            break
        time.sleep(0.05)
    t.join(timeout=30)
    return entries


@pytest.mark.slow
def test_cluster_armed_get_chaos_fault_annotation(tmp_path):
    with Cluster(tmp_path, nodes=3, drives_per_node=2,
                 parity=2) as cluster:
        cli = cluster.client(0)
        assert cli.request("PUT", "/obs")[0] == 200
        body = os.urandom(200_000)
        assert cli.request("PUT", "/obs/o", body=body)[0] == 200

        # Healthy armed GET first: ONE stitched tree with remote
        # disk.* spans labeled by their origin node.
        ok: dict = {}

        def healthy():
            st, _, got = cli.request("GET", "/obs/o")
            ok["st"], ok["match"] = st, got == body

        entries = _collect_trace(cluster, 0, 120, healthy)
        assert ok == {"st": 200, "match": True}
        gets = [e for e in entries if e.get("trace_type") == "s3"
                and e.get("api") == "GET:object"]
        assert gets, [e.get("api") for e in entries][:20]
        tid = gets[0]["trace"]
        tree = [e for e in entries if e.get("trace") == tid]
        wires = [e for e in tree if e.get("api") == "wire"]
        remote = [e for e in tree if str(e.get("api", "")
                                         ).startswith("disk.")
                  and e.get("node") != gets[0].get("node")]
        assert wires, "armed distributed GET produced no wire spans"
        assert remote, "no remote disk.* spans stitched into the tree"
        wire_ids = {e["span"] for e in wires}
        assert any(e["parent"] in wire_ids for e in remote)

        # Partition a peer mid-armed-traffic: the tree still
        # completes, with the transport fault on a wire span.
        cluster.partition(1)
        time.sleep(1.2)             # chaos file poll on node 1

        def faulted():
            st, _, got = cli.request("GET", "/obs/o")
            ok["st2"], ok["match2"] = st, got == body

        entries = _collect_trace(cluster, 0, 120, faulted)
        assert ok["st2"] == 200 and ok["match2"]    # parity covers it
        faults = [e for e in entries if e.get("api") == "wire"
                  and "fault" in (e.get("tags") or {})]
        assert faults, "partitioned peer produced no fault-annotated " \
            "wire span"

        # SIGKILL variant: same contract when the peer process dies.
        cluster.rejoin(1)
        time.sleep(1.5)             # node 1 chaos poll clears
        cluster.kill(2)

        def killed():
            # Quorum needs node 1 back: retry while its breaker on
            # node 0 recovers from the partition phase.
            deadline = time.time() + 20
            while True:
                st, _, got = cli.request("GET", "/obs/o")
                if st == 200 or time.time() > deadline:
                    break
                time.sleep(0.5)
            ok["st3"], ok["match3"] = st, got == body

        entries = _collect_trace(cluster, 0, 120, killed)
        assert ok["st3"] == 200 and ok["match3"]
        faults = [e for e in entries if e.get("api") == "wire"
                  and "fault" in (e.get("tags") or {})]
        assert faults, "killed peer produced no fault-annotated " \
            "wire span"
