"""Object lock: WORM retention, legal hold, governance bypass
(reference: internal/bucket/object/lock, cmd/object-handlers.go:2705,
2862, cmd/bucket-object-lock.go)."""

import datetime
import json
import time

import pytest

from minio_tpu.iam import IAMSys
from minio_tpu.object import objectlock as olock
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import Credentials, S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


def _until(seconds: float) -> str:
    return datetime.datetime.fromtimestamp(
        time.time() + seconds, tz=datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def _retention_body(mode: str, until: str) -> bytes:
    return (f"<Retention><Mode>{mode}</Mode>"
            f"<RetainUntilDate>{until}</RetainUntilDate>"
            f"</Retention>").encode()


# ---------------------------------------------------------------------------
# module-level semantics
# ---------------------------------------------------------------------------

def test_lock_config_xml_round_trip():
    cfg = olock.parse_lock_config_xml(
        b"<ObjectLockConfiguration>"
        b"<ObjectLockEnabled>Enabled</ObjectLockEnabled>"
        b"<Rule><DefaultRetention><Mode>GOVERNANCE</Mode><Days>7</Days>"
        b"</DefaultRetention></Rule></ObjectLockConfiguration>")
    assert cfg == {"enabled": True, "mode": "GOVERNANCE", "days": 7}
    again = olock.parse_lock_config_xml(olock.lock_config_xml(cfg))
    assert again == cfg
    with pytest.raises(olock.ObjectLockError):
        olock.parse_lock_config_xml(
            b"<ObjectLockConfiguration><ObjectLockEnabled>Enabled"
            b"</ObjectLockEnabled><Rule><DefaultRetention>"
            b"<Mode>GOVERNANCE</Mode><Days>1</Days><Years>1</Years>"
            b"</DefaultRetention></Rule></ObjectLockConfiguration>")


def test_check_version_deletable_semantics():
    now = time.time_ns()
    future = _until(3600)
    past = _until(-3600)
    # Active COMPLIANCE: never deletable, bypass irrelevant.
    m = {olock.META_MODE: "COMPLIANCE", olock.META_UNTIL: future}
    assert olock.check_version_deletable(m, now, False) == "AccessDenied"
    assert olock.check_version_deletable(m, now, True) == "AccessDenied"
    # Expired retention: deletable.
    m = {olock.META_MODE: "COMPLIANCE", olock.META_UNTIL: past}
    assert olock.check_version_deletable(m, now, False) is None
    # GOVERNANCE: bypass unlocks.
    m = {olock.META_MODE: "GOVERNANCE", olock.META_UNTIL: future}
    assert olock.check_version_deletable(m, now, False) == "AccessDenied"
    assert olock.check_version_deletable(m, now, True) is None
    # Legal hold blocks regardless of retention/bypass.
    m = {olock.META_HOLD: "ON"}
    assert olock.check_version_deletable(m, now, True) == "AccessDenied"
    # Corrupt stored date fails CLOSED (retained forever).
    m = {olock.META_MODE: "COMPLIANCE", olock.META_UNTIL: "garbage"}
    assert olock.check_version_deletable(m, now, True) == "AccessDenied"


def test_check_retention_change_semantics():
    now = time.time_ns()
    future = _until(3600)
    later = _until(7200)
    # COMPLIANCE may only extend.
    m = {olock.META_MODE: "COMPLIANCE", olock.META_UNTIL: future}
    assert olock.check_retention_change(m, "COMPLIANCE", later, now,
                                        False) is None
    assert olock.check_retention_change(m, "COMPLIANCE", _until(10), now,
                                        True) == "AccessDenied"
    assert olock.check_retention_change(m, "GOVERNANCE", later, now,
                                        True) == "AccessDenied"
    # GOVERNANCE: extend freely; shorten/clear needs bypass.
    m = {olock.META_MODE: "GOVERNANCE", olock.META_UNTIL: future}
    assert olock.check_retention_change(m, "GOVERNANCE", later, now,
                                        False) is None
    assert olock.check_retention_change(m, "", "", now,
                                        False) == "AccessDenied"
    assert olock.check_retention_change(m, "", "", now, True) is None


# ---------------------------------------------------------------------------
# end-to-end over HTTP
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("lockdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    creds = Credentials("minioadmin", "minioadmin")
    creds.iam = IAMSys([es], "minioadmin", "minioadmin")
    server = S3Server(es, address="127.0.0.1:0", credentials=creds)
    server.start()
    yield server
    server.stop()


@pytest.fixture(scope="module")
def root(srv):
    return S3Client(srv.address)


def test_lock_bucket_creation_and_config(srv, root):
    st, _, b = root.request("PUT", "/wormbkt", headers={
        "x-amz-bucket-object-lock-enabled": "true"})
    assert st == 200, b
    # Born versioned, with a lock config.
    st, _, b = root.request("GET", "/wormbkt", query={"versioning": ""})
    assert st == 200 and b"Enabled" in b
    st, _, b = root.request("GET", "/wormbkt", query={"object-lock": ""})
    assert st == 200 and b"ObjectLockEnabled" in b
    # Versioning can never be suspended on a locked bucket.
    st, _, b = root.request(
        "PUT", "/wormbkt", query={"versioning": ""},
        body=b"<VersioningConfiguration><Status>Suspended</Status>"
             b"</VersioningConfiguration>")
    assert st == 409, b
    # A plain bucket has no lock config.
    assert root.request("PUT", "/plainbkt")[0] == 200
    st, _, b = root.request("GET", "/plainbkt", query={"object-lock": ""})
    assert st == 404 and b"ObjectLockConfigurationNotFoundError" in b
    # Lock headers on a lock-less bucket are refused.
    st, _, b = root.request("PUT", "/plainbkt/obj", body=b"x", headers={
        "x-amz-object-lock-mode": "GOVERNANCE",
        "x-amz-object-lock-retain-until-date": _until(3600)})
    assert st == 400, b


def test_retention_protects_version_until_expiry(srv, root):
    until = _until(2.0)
    st, hdrs, b = root.request("PUT", "/wormbkt/prot", body=b"keep me",
                               headers={
                                   "x-amz-object-lock-mode": "COMPLIANCE",
                                   "x-amz-object-lock-retain-until-date":
                                       until})
    assert st == 200, b
    vid = hdrs.get("x-amz-version-id", "")
    assert vid
    # HEAD surfaces the lock state.
    st, hdrs2, _ = root.request("HEAD", "/wormbkt/prot")
    assert hdrs2.get("x-amz-object-lock-mode") == "COMPLIANCE"
    # GET ?retention returns the document.
    st, _, b = root.request("GET", "/wormbkt/prot", query={"retention": ""})
    assert st == 200 and b"COMPLIANCE" in b
    # Destroying the version is refused (root holds every permission —
    # COMPLIANCE has no bypass).
    st, _, b = root.request("DELETE", "/wormbkt/prot",
                            query={"versionId": vid})
    assert st == 403, b
    st, _, b = root.request(
        "DELETE", "/wormbkt/prot", query={"versionId": vid},
        headers={"x-amz-bypass-governance-retention": "true"})
    assert st == 403, b
    # Batch delete refuses it too (per-key error, HTTP 200).
    st, _, b = root.request(
        "POST", "/wormbkt", query={"delete": ""},
        body=(f"<Delete><Object><Key>prot</Key><VersionId>{vid}"
              f"</VersionId></Object></Delete>").encode())
    assert st == 200 and b"AccessDenied" in b
    # Versionless delete only stacks a marker: allowed.
    st, _, b = root.request("DELETE", "/wormbkt/prot")
    assert st == 204, b
    # COMPLIANCE retention cannot be shortened...
    st, _, b = root.request("PUT", "/wormbkt/prot",
                            query={"retention": "", "versionId": vid},
                            body=_retention_body("COMPLIANCE", _until(0.5)))
    assert st == 403, b
    # ...but can be extended. (Extend only slightly so the test ends.)
    st, _, b = root.request("PUT", "/wormbkt/prot",
                            query={"retention": "", "versionId": vid},
                            body=_retention_body("COMPLIANCE", _until(2.5)))
    assert st == 200, b
    # After expiry the version deletes fine.
    time.sleep(2.6)
    st, _, b = root.request("DELETE", "/wormbkt/prot",
                            query={"versionId": vid})
    assert st == 204, b


def test_governance_bypass_with_permission(srv, root):
    until = _until(3600)
    st, hdrs, b = root.request("PUT", "/wormbkt/gov", body=b"governed",
                               headers={
                                   "x-amz-object-lock-mode": "GOVERNANCE",
                                   "x-amz-object-lock-retain-until-date":
                                       until})
    assert st == 200, b
    vid = hdrs.get("x-amz-version-id", "")
    # Without the bypass header: refused, even for root.
    st, _, b = root.request("DELETE", "/wormbkt/gov",
                            query={"versionId": vid})
    assert st == 403, b
    # An IAM user WITHOUT BypassGovernanceRetention cannot bypass.
    st, _, b = root.request("PUT", "/minio/admin/v3/add-user",
                            query={"accessKey": "clerk"},
                            body=json.dumps(
                                {"secretKey": "clerksecret"}).encode())
    assert st == 200, b
    pol = {"Statement": [{"Effect": "Allow",
                          "Action": ["s3:GetObject", "s3:PutObject",
                                     "s3:DeleteObject"],
                          "Resource": ["arn:aws:s3:::wormbkt/*"]}]}
    st, _, b = root.request("PUT", "/minio/admin/v3/add-canned-policy",
                            query={"name": "clerk-pol"},
                            body=json.dumps(pol).encode())
    assert st == 200, b
    st, _, b = root.request("PUT",
                            "/minio/admin/v3/set-user-or-group-policy",
                            query={"userOrGroup": "clerk",
                                   "policyName": "clerk-pol"})
    assert st == 200, b
    clerk = S3Client(srv.address, access_key="clerk",
                     secret_key="clerksecret")
    st, _, b = clerk.request(
        "DELETE", "/wormbkt/gov", query={"versionId": vid},
        headers={"x-amz-bypass-governance-retention": "true"})
    assert st == 403, b
    # Root + bypass header: allowed (GOVERNANCE, unlike COMPLIANCE).
    st, _, b = root.request(
        "DELETE", "/wormbkt/gov", query={"versionId": vid},
        headers={"x-amz-bypass-governance-retention": "true"})
    assert st == 204, b


def test_legal_hold_independent_of_retention(srv, root):
    st, hdrs, b = root.request("PUT", "/wormbkt/held", body=b"held")
    assert st == 200, b
    vid = hdrs.get("x-amz-version-id", "")
    st, _, b = root.request("PUT", "/wormbkt/held",
                            query={"legal-hold": "", "versionId": vid},
                            body=b"<LegalHold><Status>ON</Status>"
                                 b"</LegalHold>")
    assert st == 200, b
    st, _, b = root.request("GET", "/wormbkt/held",
                            query={"legal-hold": "", "versionId": vid})
    assert st == 200 and b"<Status>ON</Status>" in b
    # Held version cannot be destroyed, bypass or not.
    st, _, b = root.request(
        "DELETE", "/wormbkt/held", query={"versionId": vid},
        headers={"x-amz-bypass-governance-retention": "true"})
    assert st == 403, b
    # Lift the hold: deletable.
    st, _, b = root.request("PUT", "/wormbkt/held",
                            query={"legal-hold": "", "versionId": vid},
                            body=b"<LegalHold><Status>OFF</Status>"
                                 b"</LegalHold>")
    assert st == 200, b
    st, _, b = root.request("DELETE", "/wormbkt/held",
                            query={"versionId": vid})
    assert st == 204, b


def test_lifecycle_scanner_never_destroys_locked_versions(tmp_path):
    """The scanner's ILM deletes honor WORM: a noncurrent version under
    retention survives an accelerated-clock expiry sweep (reference:
    lifecycle evaluation consults object-lock state)."""
    from minio_tpu.object.lifecycle import make_scanner_hook
    from minio_tpu.object.scanner import Scanner
    from minio_tpu.object.types import PutOptions

    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("wormilm")
    lc = (b'<LifecycleConfiguration><Rule><ID>nc</ID>'
          b'<Status>Enabled</Status><Filter><Prefix></Prefix></Filter>'
          b'<NoncurrentVersionExpiration><NoncurrentDays>1'
          b'</NoncurrentDays></NoncurrentVersionExpiration>'
          b'</Rule></LifecycleConfiguration>')
    meta = es.get_bucket_meta("wormilm")
    meta["config:lifecycle"] = lc.decode()
    meta["versioning"] = True
    meta[olock.BUCKET_META_KEY] = {"enabled": True}
    es.set_bucket_meta("wormilm", meta)

    locked_opts = PutOptions(versioned=True)
    locked_opts.internal_metadata[olock.META_MODE] = "COMPLIANCE"
    locked_opts.internal_metadata[olock.META_UNTIL] = _until(3600)
    es.put_object("wormilm", "doc", b"locked-old", locked_opts)
    es.put_object("wormilm", "doc", b"plain-old",
                  PutOptions(versioned=True))
    es.put_object("wormilm", "doc", b"latest", PutOptions(versioned=True))
    assert len(es.list_versions_all("wormilm", "doc")) == 3

    future = time.time() + 3 * 86400
    sc = Scanner([es], throttle=0)
    sc.on_object.append(make_scanner_hook(now_fn=lambda: future))
    sc.scan_cycle()

    remaining = [v for v in es.list_versions_all("wormilm", "doc")]
    # The unprotected noncurrent version expired; the COMPLIANCE one
    # and the latest survive.
    metas = [v.metadata.get(olock.META_MODE) for v in remaining]
    assert len(remaining) == 2, remaining
    assert "COMPLIANCE" in metas


def test_default_retention_applies_to_puts(srv, root):
    st, _, b = root.request("PUT", "/defbkt", headers={
        "x-amz-bucket-object-lock-enabled": "true"})
    assert st == 200, b
    st, _, b = root.request(
        "PUT", "/defbkt", query={"object-lock": ""},
        body=b"<ObjectLockConfiguration>"
             b"<ObjectLockEnabled>Enabled</ObjectLockEnabled>"
             b"<Rule><DefaultRetention><Mode>GOVERNANCE</Mode>"
             b"<Days>1</Days></DefaultRetention></Rule>"
             b"</ObjectLockConfiguration>")
    assert st == 200, b
    st, hdrs, b = root.request("PUT", "/defbkt/auto", body=b"auto-locked")
    assert st == 200, b
    vid = hdrs.get("x-amz-version-id", "")
    st, hdrs2, _ = root.request("HEAD", "/defbkt/auto")
    assert hdrs2.get("x-amz-object-lock-mode") == "GOVERNANCE"
    assert hdrs2.get("x-amz-object-lock-retain-until-date")
    st, _, b = root.request("DELETE", "/defbkt/auto",
                            query={"versionId": vid})
    assert st == 403, b
