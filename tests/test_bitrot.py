"""Bitrot hashing: golden self-test, magic-key oracle, vectorized lockstep."""

import hashlib

import numpy as np
import pytest

from minio_tpu.storage import bitrot
from minio_tpu.utils.highwayhash import (MAGIC_KEY, highwayhash256,
                                         highwayhash256_many)


def test_reference_golden_selftest():
    # Byte-identical to cmd/bitrot.go:224-255 or we'd corrupt data.
    bitrot.bitrot_self_test()


def test_magic_key_is_hh256_of_pi_decimals():
    # The reference derives its bitrot key as HH-256 of the first 100
    # decimals of pi under a zero key (cmd/bitrot.go:36-37). This exercises
    # the remainder (non-multiple-of-32) path: 100 = 3 packets + 4 bytes.
    pi100 = ("14159265358979323846264338327950288419716939937510"
             "58209749445923078164062862089986280348253421170679")
    assert highwayhash256(b"\x00" * 32, pi100.encode()) == MAGIC_KEY


@pytest.mark.parametrize("length", [0, 1, 3, 4, 15, 16, 17, 31, 32, 33,
                                    63, 64, 100, 1000, 4097])
def test_many_matches_single(length):
    rng = np.random.default_rng(length)
    blocks = rng.integers(0, 256, size=(5, length), dtype=np.uint8)
    got = highwayhash256_many(MAGIC_KEY, blocks)
    for i in range(5):
        assert got[i].tobytes() == highwayhash256(MAGIC_KEY, blocks[i].tobytes())


@pytest.mark.parametrize("algo", [bitrot.SHA256, bitrot.BLAKE2B512,
                                  bitrot.HIGHWAYHASH256, bitrot.HIGHWAYHASH256S])
def test_hash_blocks_many_all_algorithms(algo):
    rng = np.random.default_rng(9)
    blocks = rng.integers(0, 256, size=(3, 333), dtype=np.uint8)
    got = bitrot.hash_blocks_many(algo, blocks)
    assert got.shape == (3, bitrot.digest_size(algo))
    for i in range(3):
        assert got[i].tobytes() == bitrot.hash_block(algo, blocks[i].tobytes())


def test_non_highway_algorithms_are_stdlib():
    data = b"minio-tpu bitrot"
    assert bitrot.hash_block(bitrot.SHA256, data) == hashlib.sha256(data).digest()
    assert bitrot.hash_block(bitrot.BLAKE2B512, data) == \
        hashlib.blake2b(data, digest_size=64).digest()


# ---------------------------------------------------------------------------
# Batched verified reads (the GET/heal read path)
# ---------------------------------------------------------------------------

def _framed(shard: np.ndarray, shard_size: int) -> bytes:
    return bitrot.frame_shard(shard, shard_size)


@pytest.mark.parametrize("data_size,shard_size", [
    (4 * 512, 512),          # exact blocks
    (4 * 512 + 100, 512),    # ragged tail
    (100, 512),              # single short block
    (0, 512),                # empty
])
def test_read_framed_blocks_many_roundtrip(data_size, shard_size):
    rng = np.random.default_rng(data_size)
    shards = [rng.integers(0, 256, size=data_size, dtype=np.uint8)
              for _ in range(5)]
    blobs = [_framed(s, shard_size) for s in shards]
    blobs[2] = None                       # missing shard passes through
    out = bitrot.read_framed_blocks_many(blobs, shard_size, data_size)
    assert out[2] is None
    for i in (0, 1, 3, 4):
        assert out[i] is not None
        assert np.array_equal(out[i], shards[i])


def test_read_framed_blocks_many_detects_corruption():
    shard_size, data_size = 512, 4 * 512 + 77
    rng = np.random.default_rng(7)
    shards = [rng.integers(0, 256, size=data_size, dtype=np.uint8)
              for _ in range(4)]
    blobs = [bytearray(_framed(s, shard_size)) for s in shards]
    blobs[1][700] ^= 0xFF                 # corrupt a full-block byte
    blobs[3][-1] ^= 0xFF                  # corrupt the ragged tail
    out = bitrot.read_framed_blocks_many(
        [bytes(b) for b in blobs], shard_size, data_size)
    assert out[1] is None and out[3] is None
    assert np.array_equal(out[0], shards[0])
    assert np.array_equal(out[2], shards[2])


def test_read_framed_blocks_many_rejects_wrong_size():
    shard_size, data_size = 512, 3 * 512
    rng = np.random.default_rng(3)
    s = rng.integers(0, 256, size=data_size, dtype=np.uint8)
    blob = _framed(s, shard_size)
    out = bitrot.read_framed_blocks_many(
        [blob[:-1], blob + b"x", blob], shard_size, data_size)
    assert out[0] is None and out[1] is None
    assert np.array_equal(out[2], s)


def test_read_framed_blocks_many_matches_reader():
    """Batch output byte-identical to the per-block FramedShardReader."""
    shard_size, data_size = 256, 5 * 256 + 13
    rng = np.random.default_rng(11)
    s = rng.integers(0, 256, size=data_size, dtype=np.uint8)
    blob = _framed(s, shard_size)
    batch, = bitrot.read_framed_blocks_many([blob], shard_size, data_size)
    r = bitrot.FramedShardReader(blob, shard_size, data_size)
    blocks = [r.block(i) for i in range(6)]
    assert np.array_equal(batch, np.concatenate(blocks))
