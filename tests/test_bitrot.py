"""Bitrot hashing: golden self-test, magic-key oracle, vectorized lockstep."""

import hashlib

import numpy as np
import pytest

from minio_tpu.storage import bitrot
from minio_tpu.utils.highwayhash import (MAGIC_KEY, highwayhash256,
                                         highwayhash256_many)


def test_reference_golden_selftest():
    # Byte-identical to cmd/bitrot.go:224-255 or we'd corrupt data.
    bitrot.bitrot_self_test()


def test_magic_key_is_hh256_of_pi_decimals():
    # The reference derives its bitrot key as HH-256 of the first 100
    # decimals of pi under a zero key (cmd/bitrot.go:36-37). This exercises
    # the remainder (non-multiple-of-32) path: 100 = 3 packets + 4 bytes.
    pi100 = ("14159265358979323846264338327950288419716939937510"
             "58209749445923078164062862089986280348253421170679")
    assert highwayhash256(b"\x00" * 32, pi100.encode()) == MAGIC_KEY


@pytest.mark.parametrize("length", [0, 1, 3, 4, 15, 16, 17, 31, 32, 33,
                                    63, 64, 100, 1000, 4097])
def test_many_matches_single(length):
    rng = np.random.default_rng(length)
    blocks = rng.integers(0, 256, size=(5, length), dtype=np.uint8)
    got = highwayhash256_many(MAGIC_KEY, blocks)
    for i in range(5):
        assert got[i].tobytes() == highwayhash256(MAGIC_KEY, blocks[i].tobytes())


@pytest.mark.parametrize("algo", [bitrot.SHA256, bitrot.BLAKE2B512,
                                  bitrot.HIGHWAYHASH256, bitrot.HIGHWAYHASH256S])
def test_hash_blocks_many_all_algorithms(algo):
    rng = np.random.default_rng(9)
    blocks = rng.integers(0, 256, size=(3, 333), dtype=np.uint8)
    got = bitrot.hash_blocks_many(algo, blocks)
    assert got.shape == (3, bitrot.digest_size(algo))
    for i in range(3):
        assert got[i].tobytes() == bitrot.hash_block(algo, blocks[i].tobytes())


def test_non_highway_algorithms_are_stdlib():
    data = b"minio-tpu bitrot"
    assert bitrot.hash_block(bitrot.SHA256, data) == hashlib.sha256(data).digest()
    assert bitrot.hash_block(bitrot.BLAKE2B512, data) == \
        hashlib.blake2b(data, digest_size=64).digest()
