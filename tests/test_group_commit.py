"""Group-commit write plane (storage/group_commit + commit_group).

Covers the ISSUE-chartered suite: batched-vs-solo journal byte-identity
across member mixes, same-object merge ordering, member-failure
isolation, deadline-cull without poisoning, WAL replay semantics, the
no-op short-circuit, the coalesced-bump funnel, and — in a subprocess
fleet — 2-pre-forked-worker coherence of the coalesced invalidation.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.storage import group_commit as gc_mod
from minio_tpu.storage.group_commit import GroupCommit, GroupOp, replay_wals
from minio_tpu.storage.local import SYS_VOL, LocalStorage
from minio_tpu.storage.meta import (ErasureInfo, FileInfo, ObjectPartInfo,
                                    XLMeta, now_ns)

BKT = "b"


def mkdisk(tmp_path, name="d0"):
    d = LocalStorage(str(tmp_path / name))
    os.makedirs(os.path.join(d.root, BKT), exist_ok=True)
    return d


def mkfi(key, mod_time=None, vid="", data=b"x" * 64, deleted=False,
         ddir=""):
    return FileInfo(
        volume=BKT, name=key, version_id=vid, deleted=deleted,
        data_dir=ddir, mod_time=mod_time or now_ns(), size=len(data),
        metadata={"etag": "e"},
        parts=[ObjectPartInfo(number=1, size=len(data),
                              actual_size=len(data))],
        erasure=ErasureInfo(data_blocks=2, parity_blocks=1,
                            block_size=1 << 20, index=1,
                            distribution=(1, 2, 3)),
        inline_data=None if deleted else data)


def read_xl(d, key):
    with open(os.path.join(d.root, BKT, key, "xl.meta"), "rb") as f:
        return f.read()


# ---------------------------------------------------------------------------
# commit_group protocol
# ---------------------------------------------------------------------------

def test_batched_vs_solo_byte_identity(tmp_path):
    """One batch over a mix of fresh keys, overwrites, delete markers
    and same-object sequences produces journals byte-identical to the
    same ops applied solo in the same order."""
    da, db = mkdisk(tmp_path, "da"), mkdisk(tmp_path, "db")
    t0 = now_ns()
    fis = [
        ("k1", mkfi("k1", t0)),
        ("k2", mkfi("k2", t0 + 1)),
        ("k1", mkfi("k1", t0 + 2)),                    # null overwrite
        ("k3", mkfi("k3", t0 + 3, vid="11111111-0000-0000-0000-"
                                      "000000000001")),
        ("k3", mkfi("k3", t0 + 4, vid="11111111-0000-0000-0000-"
                                      "000000000002")),
        ("k2", mkfi("k2", t0 + 5, deleted=True)),      # delete marker
    ]
    # Pre-existing journal for k4 so the overwrite path is covered too.
    for d in (da, db):
        d.write_metadata(BKT, "k4", mkfi("k4", t0 - 5))
    fis.append(("k4", mkfi("k4", t0 + 6)))

    res = da.commit_group([GroupOp.write_meta(BKT, k, fi)
                           for k, fi in fis])
    assert res == [None] * len(fis)
    for k, fi in fis:
        db.write_metadata(BKT, k, fi)
    for k in ("k1", "k2", "k3", "k4"):
        assert read_xl(da, k) == read_xl(db, k), f"journal differs: {k}"


def test_same_object_merge_ordering(tmp_path):
    """Same-object members merge in arrival order into ONE journal
    rewrite: the last null-version member wins the null slot, and the
    commit writes the object's journal exactly once."""
    d = mkdisk(tmp_path)
    t0 = now_ns()
    ops = [GroupOp.write_meta(BKT, "hot", mkfi("hot", t0 + i,
                                               data=bytes([i]) * 32))
           for i in range(5)]
    info = {}
    assert d.commit_group(ops, _info=info) == [None] * 5
    assert info["objects"] == 1
    assert info["merged"] == 4
    xl = XLMeta.load(read_xl(d, "hot"))
    assert len(xl.versions) == 1
    fi = xl.to_fileinfo(BKT, "hot", read_data=True)
    assert fi.inline_data == bytes([4]) * 32   # arrival order: last wins


def test_member_failure_isolation(tmp_path):
    """A rename_data member whose staging is missing fails ALONE;
    batch-mates commit normally."""
    d = mkdisk(tmp_path)
    good = GroupOp.write_meta(BKT, "ok1", mkfi("ok1"))
    bad = GroupOp.rename("nosuchvol", "missing",
                         mkfi("broken", ddir="0" * 8), BKT, "broken")
    good2 = GroupOp.write_meta(BKT, "ok2", mkfi("ok2"))
    res = d.commit_group([good, bad, good2])
    assert res[0] is None and res[2] is None
    assert isinstance(res[1], Exception)
    assert XLMeta.load(read_xl(d, "ok1")).versions
    assert XLMeta.load(read_xl(d, "ok2")).versions
    assert not os.path.exists(os.path.join(d.root, BKT, "broken",
                                           "xl.meta"))


def test_rename_data_members_batch(tmp_path):
    """rename_data members move their staged data dirs in and the
    journal claims them — equivalent to solo rename_data."""
    da, db = mkdisk(tmp_path, "da"), mkdisk(tmp_path, "db")
    t0 = now_ns()
    ops = []
    for d in (da, db):
        os.makedirs(os.path.join(d.root, SYS_VOL, "stage", "dd1"))
        with open(os.path.join(d.root, SYS_VOL, "stage", "dd1",
                               "part.1"), "wb") as f:
            f.write(b"shard")
    fi_a = mkfi("rk", t0, ddir="dd1", data=b"")
    fi_a.inline_data = None
    fi_b = mkfi("rk", t0, ddir="dd1", data=b"")
    fi_b.inline_data = None
    res = da.commit_group([GroupOp.rename(SYS_VOL, "stage", fi_a,
                                          BKT, "rk")])
    assert res == [None]
    db.rename_data(SYS_VOL, "stage", fi_b, BKT, "rk")
    assert read_xl(da, "rk") == read_xl(db, "rk")
    assert os.path.isfile(os.path.join(da.root, BKT, "rk", "dd1",
                                       "part.1"))
    # Staging cleaned on both paths.
    assert not os.path.exists(os.path.join(da.root, SYS_VOL, "stage"))


def test_noop_short_circuit_solo_and_batched(tmp_path):
    """A byte-identical version re-add skips the journal rewrite on
    both the solo and the batched path (the hot-key
    overwrite-with-same-content fix)."""
    d = mkdisk(tmp_path)
    fi = mkfi("nk", now_ns())
    d.write_metadata(BKT, "nk", fi)
    p = os.path.join(d.root, BKT, "nk", "xl.meta")
    st0 = os.stat(p)
    d.write_metadata(BKT, "nk", fi)          # solo no-op
    assert os.stat(p).st_mtime_ns == st0.st_mtime_ns
    info = {}
    res = d.commit_group([GroupOp.write_meta(BKT, "nk", fi)],
                         _info=info)
    assert res == [None]
    assert info["noops"] == 1
    assert os.stat(p).st_mtime_ns == st0.st_mtime_ns


# ---------------------------------------------------------------------------
# WAL replay
# ---------------------------------------------------------------------------

def _wal_with(d, recs, t_ns=None):
    path = gc_mod.wal_file_path(d.root)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "ab") as f:
        f.write(gc_mod.encode_frame(recs, t_ns=t_ns))
    return path


def test_replay_repairs_torn_destination(tmp_path):
    d = mkdisk(tmp_path)
    fi = mkfi("rw", now_ns())
    assert d.commit_group([GroupOp.write_meta(BKT, "rw", fi)]) == [None]
    blob = read_xl(d, "rw")
    dest = os.path.join(d.root, BKT, "rw", "xl.meta")
    # Fabricate the power-cut state: WAL frame present, dest torn.
    _wal_with(d, [(BKT, "rw", blob)], t_ns=time.time_ns())
    with open(dest, "wb") as f:
        f.write(blob[: len(blob) // 2])
    rep = replay_wals(d)
    assert rep["repaired"] == 1
    assert read_xl(d, "rw") == blob
    assert os.listdir(os.path.join(d.root, SYS_VOL,
                                   gc_mod.GC_DIR)) == []


def test_replay_installs_when_rename_lost(tmp_path):
    """Destination older than the frame (or absent, dir present): the
    rename never landed — the acked journal installs from the WAL."""
    d = mkdisk(tmp_path)
    old_fi = mkfi("rl", now_ns())
    d.write_metadata(BKT, "rl", old_fi)
    old_blob = read_xl(d, "rl")
    xl = XLMeta.load(old_blob)
    xl.add_version(mkfi("rl", now_ns() + 10, data=b"new" * 8))
    new_blob = xl.dump()
    _wal_with(d, [(BKT, "rl", new_blob)], t_ns=time.time_ns() + 10_000)
    assert replay_wals(d)["repaired"] == 1
    assert read_xl(d, "rl") == new_blob


def test_replay_leaves_newer_destination_alone(tmp_path):
    """A destination newer than the frame is a later committed write
    — replay must not roll it back."""
    d = mkdisk(tmp_path)
    stale = XLMeta()
    stale.add_version(mkfi("nw", now_ns() - 50))
    _wal_with(d, [(BKT, "nw", stale.dump())],
              t_ns=time.time_ns() - 10 ** 9)
    d.write_metadata(BKT, "nw", mkfi("nw", now_ns()))
    newer = read_xl(d, "nw")
    assert replay_wals(d)["repaired"] == 0
    assert read_xl(d, "nw") == newer


def test_replay_never_resurrects_deleted_object(tmp_path):
    """Object dir pruned by a post-batch delete: the WAL frame must
    not bring the object back."""
    d = mkdisk(tmp_path)
    fi = mkfi("dz", now_ns())
    d.write_metadata(BKT, "dz", fi)
    blob = read_xl(d, "dz")
    _wal_with(d, [(BKT, "dz", blob)], t_ns=time.time_ns())
    d.delete_version(BKT, "dz", "")
    assert not os.path.exists(os.path.join(d.root, BKT, "dz"))
    assert replay_wals(d)["repaired"] == 0
    assert not os.path.exists(os.path.join(d.root, BKT, "dz"))


def test_replay_discards_torn_tail_frame(tmp_path):
    """A torn tail frame (power cut mid-append) is discarded; intact
    frames before it still replay."""
    d = mkdisk(tmp_path)
    good = XLMeta()
    good.add_version(mkfi("tg", now_ns()))
    blob = good.dump()
    os.makedirs(os.path.join(d.root, BKT, "tg"))
    path = _wal_with(d, [(BKT, "tg", blob)], t_ns=time.time_ns())
    torn = gc_mod.encode_frame([(BKT, "zz", b"XTP1garbage")])
    with open(path, "ab") as f:
        f.write(torn[: len(torn) // 2])
    rep = replay_wals(d)
    assert rep["replayed"] == 1 and rep["discarded"] == 1
    assert read_xl(d, "tg") == blob


def test_checkpoint_truncates_wal(tmp_path):
    d = mkdisk(tmp_path)
    d._gc_auto = False
    for i in range(3):
        assert d.commit_group([GroupOp.write_meta(
            BKT, f"ck-{i}", mkfi(f"ck-{i}"))]) == [None] * 1
    assert d.gc_pending() == 3
    wal = gc_mod.wal_file_path(d.root)
    assert os.path.getsize(wal) > 0
    assert d.gc_checkpoint() == 3
    assert os.path.getsize(wal) == 0
    assert d.gc_pending() == 0
    d.gc_close()


def test_recovery_sweep_replays_first(tmp_path):
    """recovery_sweep replays WAL frames BEFORE the dangling-data-dir
    scan, so data dirs claimed only by WAL-recorded journals are not
    reaped as orphans."""
    from minio_tpu.storage.local import recovery_sweep
    d = mkdisk(tmp_path)
    ddir = "11111111-2222-3333-4444-555555555555"
    obj = os.path.join(d.root, BKT, "rs")
    os.makedirs(os.path.join(obj, ddir))
    with open(os.path.join(obj, ddir, "part.1"), "wb") as f:
        f.write(b"shard")
    xl = XLMeta()
    fi = mkfi("rs", now_ns(), ddir=ddir, data=b"")
    fi.inline_data = None
    xl.add_version(fi)
    _wal_with(d, [(BKT, "rs", xl.dump())], t_ns=time.time_ns())
    rep = recovery_sweep(d, min_age=0)
    assert rep["wal_repaired"] == 1
    assert os.path.isfile(os.path.join(obj, ddir, "part.1")), \
        "replayed journal's data dir was reaped as dangling"
    assert rep["dangling"] == 0


# ---------------------------------------------------------------------------
# the coalescer (GroupCommit lanes)
# ---------------------------------------------------------------------------

def _mkset(tmp_path, n=4, name="es"):
    disks = [LocalStorage(str(tmp_path / f"{name}{i}")) for i in range(n)]
    es = ErasureSet(disks)
    es.make_bucket(BKT)
    return es


def test_concurrent_inline_puts_coalesce_and_roundtrip(tmp_path):
    es = _mkset(tmp_path)
    assert es.group_commit is not None
    body = os.urandom(2048)
    ex = ThreadPoolExecutor(max_workers=12)

    def put(t):
        for i in range(15):
            es.put_object(BKT, f"k-{t}-{i}", body)

    list(ex.map(put, range(12)))
    st = es.group_commit.stats()
    assert st["members"] > 0, "no commit ever rode the lanes"
    assert st["batches"] < st["members"], "no coalescing happened"
    for t in (0, 5, 11):
        for i in (0, 14):
            _, data = es.get_object(BKT, f"k-{t}-{i}")
            assert data == body
    # Listing sees every key (the coalesced bump invalidated walks).
    res = es.list_objects(BKT, prefix="k-")
    assert len(res.objects) == 12 * 15
    es.close()
    ex.shutdown(wait=False)
    # Graceful close checkpoints: no WAL frames survive for replay.
    for d in es.disks:
        gdir = os.path.join(d.root, SYS_VOL, gc_mod.GC_DIR)
        for name in (os.listdir(gdir) if os.path.isdir(gdir) else []):
            assert os.path.getsize(os.path.join(gdir, name)) == 0


def test_solo_request_bypasses_lanes(tmp_path):
    """A lone PUT (no concurrency) takes the solo fan-out — identical
    behavior and no window wait."""
    es = _mkset(tmp_path)
    es.put_object(BKT, "solo", b"x" * 512)
    st = es.group_commit.stats()
    assert st["members"] == 0
    assert st["solo_bypass"] >= 1
    _, data = es.get_object(BKT, "solo")
    assert data == b"x" * 512
    es.close()


def test_deadline_cull_without_poisoning(tmp_path):
    """A member whose budget is spent at dispatch is culled alone with
    DeadlineExceeded; batch-mates commit."""
    from minio_tpu.utils.deadline import DeadlineExceeded
    d = mkdisk(tmp_path)
    gc = GroupCommit([d], _FakeEngine())

    class _DL:
        expires_at = time.monotonic() - 1.0

    live = gc_mod._Latch(1)
    dead = gc_mod._Latch(1)
    m_ok = gc_mod._Member(GroupOp.write_meta(BKT, "dc-ok", mkfi("dc-ok")),
                          None, live)
    m_dead = gc_mod._Member(GroupOp.write_meta(BKT, "dc-no",
                                               mkfi("dc-no")),
                            _DL(), dead)
    gc._run_batch(gc._lanes[0], [m_ok, m_dead])
    assert m_ok.exc is None
    assert isinstance(m_dead.exc, DeadlineExceeded)
    assert XLMeta.load(read_xl(d, "dc-ok")).versions
    assert not os.path.exists(os.path.join(d.root, BKT, "dc-no"))
    assert gc.stats()["deadline_culls"] == 1


class _FakeEngine:
    def submit_nowait(self, idx, fn):
        fn()


def test_solo_demotion_on_batch_fault(tmp_path):
    """A wholesale commit_group fault demotes every member to the solo
    path — the batch fault is invisible to callers when solo
    succeeds."""
    d = mkdisk(tmp_path)

    class Flaky:
        root = d.root
        endpoint = "flaky"

        def commit_group(self, ops, _info=None):
            raise OSError("batch machinery exploded")

        def write_metadata(self, vol, path, fi):
            return d.write_metadata(vol, path, fi)

    gc = GroupCommit([Flaky()], _FakeEngine())
    latch = gc_mod._Latch(2)
    ms = [gc_mod._Member(GroupOp.write_meta(BKT, f"sd-{i}",
                                            mkfi(f"sd-{i}")), None, latch)
          for i in range(2)]
    gc._run_batch(gc._lanes[0], ms)
    assert all(m.exc is None for m in ms)
    assert gc.stats()["solo_demotions"] == 2
    for i in range(2):
        assert XLMeta.load(read_xl(d, f"sd-{i}")).versions


def test_coalesced_bump_fires_before_ack(tmp_path):
    """The batch's metacache bump happens BEFORE members are acked:
    a reader observing the PUT's return can never hit a stale cached
    listing/fileinfo."""
    d = mkdisk(tmp_path)
    order = []

    class Latch(gc_mod._Latch):
        def dec(self):
            order.append("ack")
            super().dec()

    gc = GroupCommit([d], _FakeEngine())
    gc.bump = lambda bucket: order.append(f"bump:{bucket}")
    latch = Latch(1)
    m = gc_mod._Member(GroupOp.write_meta(BKT, "bf", mkfi("bf")),
                       None, latch)
    gc._run_batch(gc._lanes[0], [m])
    assert order == [f"bump:{BKT}", "ack"]


def test_delete_marker_storm_coalesces(tmp_path):
    """Versioned delete markers ride the same lanes as inline PUTs."""
    es = _mkset(tmp_path)
    body = b"v" * 256
    keys = [f"dm-{i}" for i in range(24)]
    for k in keys:
        es.put_object(BKT, k, body)
    from minio_tpu.object.types import DeleteOptions
    before = es.group_commit.stats()["members"]
    ex = ThreadPoolExecutor(max_workers=8)

    def rm(k):
        es.delete_object(BKT, k, DeleteOptions(versioned=True))

    list(ex.map(rm, keys))
    after = es.group_commit.stats()["members"]
    assert after > before, "delete markers never rode the lanes"
    for k in keys[:3]:
        from minio_tpu.object.types import ObjectNotFound
        with pytest.raises(ObjectNotFound):
            es.get_object(BKT, k)
    es.close()
    ex.shutdown(wait=False)


def test_group_commit_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("MTPU_GROUP_COMMIT", "off")
    es = _mkset(tmp_path)
    assert es.group_commit is None
    es.put_object(BKT, "off", b"y" * 128)
    _, data = es.get_object(BKT, "off")
    assert data == b"y" * 128
    es.close()


# ---------------------------------------------------------------------------
# cross-process coherence of the coalesced bump (2 pre-forked workers)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gc_worker_server(tmp_path_factory):
    """A 2-worker pre-forked fleet on shared drives (subprocess — the
    pytest process has JAX loaded and fork-after-JAX is unsafe)."""
    import signal
    import socket
    import subprocess
    import sys
    root = tmp_path_factory.mktemp("gcworkers")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2",
               MTPU_GROUP_COMMIT="on")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "minio_tpu.server",
         "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
         f"{root}/d{{1...4}}"],
        env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    from tests.s3client import S3Client
    address = f"127.0.0.1:{port}"
    deadline = time.time() + 90
    ready = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        try:
            st, _, _ = S3Client(address).request(
                "GET", "/minio/health/live", sign=False)
            if st == 200:
                ready = True
                break
        except OSError:
            time.sleep(0.4)
    if not ready:
        out = proc.stdout.read().decode(errors="replace") \
            if proc.stdout else ""
        proc.kill()
        pytest.skip(f"worker fleet failed to boot: {out[-800:]}")
    yield address
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=25)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_workers_coalesced_bump_coherence(gc_worker_server):
    """Concurrent small-object PUT storms through BOTH pre-forked
    workers (group-commit lanes engaged), then overwrites: no
    connection anywhere may serve stale bytes — the coalesced bump
    must invalidate sibling workers' caches exactly like per-request
    bumps did."""
    from tests.s3client import S3Client
    addr = gc_worker_server
    assert S3Client(addr).request("PUT", "/gcb")[0] == 200
    body1 = b"one" * 1000
    body2 = b"two" * 1100

    def storm(body, tag):
        def put(t):
            cli = S3Client(addr)
            for i in range(6):
                st, _, _ = cli.request("PUT", f"/gcb/k{t}-{i}",
                                       body=body)
                assert st == 200
            st, _, _ = cli.request("PUT", "/gcb/hot", body=body)
            assert st == 200
        ex = ThreadPoolExecutor(max_workers=8)
        list(ex.map(put, range(8)))
        ex.shutdown(wait=False)

    storm(body1, "a")
    for _ in range(8):       # fresh connections: both workers cache it
        st, _, got = S3Client(addr).request("GET", "/gcb/hot")
        assert st == 200 and got == body1
    storm(body2, "b")
    for _ in range(8):
        st, _, got = S3Client(addr).request("GET", "/gcb/hot")
        assert st == 200 and got == body2, \
            "stale bytes served across workers after group-commit " \
            "overwrite storm"
    # And listings converge on the full keyspace.
    st, _, page = S3Client(addr).request(
        "GET", "/gcb", query={"prefix": "k", "max-keys": "1000"})
    assert st == 200
    assert page.count(b"<Key>") == 48


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------

def test_replay_survives_16_byte_torn_tail(tmp_path):
    """A torn tail of 16-19 bytes (magic+crc+partial body head) must
    be treated as torn, not raise out of replay (and through it, out
    of recovery_sweep)."""
    d = mkdisk(tmp_path)
    good = XLMeta()
    good.add_version(mkfi("tt", now_ns()))
    os.makedirs(os.path.join(d.root, BKT, "tt"))
    path = _wal_with(d, [(BKT, "tt", good.dump())], t_ns=time.time_ns())
    frame = gc_mod.encode_frame([(BKT, "zz", b"x")])
    with open(path, "ab") as f:
        f.write(frame[:17])
    rep = replay_wals(d)
    assert rep["replayed"] == 1 and rep["discarded"] == 1
    assert read_xl(d, "tt") == good.dump()


def test_commit_fanout_all_none_returns(tmp_path):
    """Every drive slot None (staging failed everywhere) must return
    immediately, not park on an un-signalled latch inside the ns
    lock."""
    d = mkdisk(tmp_path)
    gc = GroupCommit([d], _FakeEngine())
    t0 = time.monotonic()
    errors = gc.commit_fanout([None])
    assert time.monotonic() - t0 < 1.0
    assert errors == [None]
    gc.close()


def test_truncate_guard_skips_on_concurrent_append(tmp_path):
    """Frames appended between a checkpoint's sync and its truncate
    were not covered by that sync: the guarded truncate must skip
    (retire next round), never erase a live durability point."""
    d = mkdisk(tmp_path)
    d._gc_auto = False
    d.commit_group([GroupOp.write_meta(BKT, "tr-0", mkfi("tr-0"))])
    pre = d.gc_pending()
    assert pre == 1
    # A batch lands AFTER the (simulated) sync, BEFORE the truncate:
    d.commit_group([GroupOp.write_meta(BKT, "tr-1", mkfi("tr-1"))])
    assert d.gc_truncate_wal(expect=pre) == 0, \
        "truncate erased frames the sync never covered"
    assert d.gc_pending() == 2
    # Next round sees a stable count and retires both.
    assert d.gc_truncate_wal(expect=2) == 2
    d.gc_close()


def test_replay_mtime_lie_does_not_roll_back_overwrite(tmp_path):
    """Even when the destination's mtime reads OLDER than the frame
    (coarse-granularity fs, clock step), a destination whose journal
    already supersedes every frame version must not be rolled back."""
    d = mkdisk(tmp_path)
    t0 = now_ns()
    old = XLMeta()
    old.add_version(mkfi("cl", t0))
    # Destination holds a NEWER overwrite of the same null version.
    d.write_metadata(BKT, "cl", mkfi("cl", t0 + 1000,
                                     data=b"newer" * 8))
    newer = read_xl(d, "cl")
    # Frame stamped in the FUTURE: the mtime comparison alone would
    # say "destination is pre-batch, install".
    _wal_with(d, [(BKT, "cl", old.dump())],
              t_ns=time.time_ns() + 10 ** 12)
    assert replay_wals(d)["repaired"] == 0
    assert read_xl(d, "cl") == newer
