"""Device (XLA / Pallas-interpret) RS paths must match the host backend bit
for bit — and therefore the reference's golden digests."""

import numpy as np
import pytest

from minio_tpu.erasure.codec import Erasure, HostBackend
from minio_tpu.erasure.selftest import erasure_self_test
from minio_tpu.ops import gf256
from minio_tpu.ops.rs_device import DeviceBackend

CONFIGS = [(2, 2), (4, 2), (8, 4), (5, 3), (12, 4), (16, 4)]

# Pallas runs in (slow) interpret mode off-TPU, so CI keeps a reduced sweep
# for it; the full sweep runs on the XLA path, which lowers the exact same
# bit-matrix math. On real TPU hardware bench.py exercises the compiled
# Pallas kernel and cross-checks bytes against the host backend.
_ON_TPU = False
try:  # pragma: no cover - conftest pins CPU; real chip in bench runs
    import jax
    _ON_TPU = jax.default_backend() == "tpu"
except Exception:
    pass


@pytest.fixture(scope="module", params=["xla", "pallas"])
def backend(request):
    # host_cutover=0: these tests exist to exercise the DEVICE kernels;
    # the production small-input host reroute would make them vacuous.
    return DeviceBackend(mode=request.param, host_cutover=0)


def _skip_slow_interpret(backend, heavy: bool):
    if heavy and backend.mode == "pallas" and not _ON_TPU:
        pytest.skip("pallas interpret mode: reduced sweep off-TPU")


@pytest.mark.parametrize("k,m", CONFIGS)
@pytest.mark.parametrize("length", [1, 77, 128, 1024, 5000])
def test_apply_matrix_matches_host(backend, k, m, length):
    _skip_slow_interpret(backend, heavy=(k, m) != (4, 2) or length not in (77, 1024))
    rng = np.random.default_rng(k * 1000 + m * 10 + length)
    shards = rng.integers(0, 256, size=(k, length), dtype=np.uint8)
    pm = gf256.parity_matrix(k, m)
    want = HostBackend().apply_matrix(pm, shards)
    got = backend.apply_matrix(pm, shards)
    np.testing.assert_array_equal(want, got)


@pytest.mark.parametrize("k,m", [(4, 2), (8, 4)])
def test_batched_apply(backend, k, m):
    _skip_slow_interpret(backend, heavy=(k, m) != (4, 2))
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    batch = rng.integers(0, 256, size=(3, k, 2000), dtype=np.uint8)
    pm = gf256.parity_matrix(k, m)
    got = np.asarray(backend.apply_matrix_device(pm, jnp.asarray(batch)))
    for b in range(3):
        want = HostBackend().apply_matrix(pm, batch[b])
        np.testing.assert_array_equal(want, got[b])


def test_device_backend_passes_reference_selftest(backend):
    # The reference's boot gate (cmd/erasure-coding.go:152-209) run with the
    # device backend: byte-identical golden xxhash64 digests.
    _skip_slow_interpret(backend, heavy=True)
    erasure_self_test(backend=backend)


@pytest.mark.parametrize("k,m", [(8, 4)])
def test_encode_reconstruct_roundtrip_device(backend, k, m):
    _skip_slow_interpret(backend, heavy=True)
    e = Erasure(k, m, 1 << 20, backend=backend)
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    shards = e.encode_data(data)
    # Drop m shards (mixed data+parity) and reconstruct.
    shards[1] = np.zeros(0, dtype=np.uint8)
    shards[k + 1] = None
    lost2 = min(k - 1, 3)
    shards[lost2] = None
    e.decode_data_and_parity_blocks(shards)
    assert e.join(shards, len(data)) == data
