"""Topology: ellipses expansion, set sizing, SipHash routing, format.json
boot (quorum verify, drive reorder, fresh-drive heal, foreign refusal),
multi-set distribution and multi-pool federation."""

import os
import random

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.types import BucketNotEmpty, ObjectNotFound
from minio_tpu.storage.local import LocalStorage, OfflineDisk
from minio_tpu.topology import ellipses
from minio_tpu.topology import format as fmt_mod
from minio_tpu.utils.siphash import siphash24, sip_hash_mod


# ---------------------------------------------------------------------------
# ellipses
# ---------------------------------------------------------------------------

def test_expand_basic():
    assert ellipses.expand("/data/d{1...4}") == [
        "/data/d1", "/data/d2", "/data/d3", "/data/d4"]


def test_expand_zero_padded_and_nested():
    assert ellipses.expand("/p{01...03}") == ["/p01", "/p02", "/p03"]
    assert ellipses.expand("/r{1...2}/d{1...2}") == [
        "/r1/d1", "/r1/d2", "/r2/d1", "/r2/d2"]


def test_choose_set_size():
    assert ellipses.choose_set_size(1) == 1
    assert ellipses.choose_set_size(4) == 4
    assert ellipses.choose_set_size(16) == 16
    assert ellipses.choose_set_size(32) == 16
    assert ellipses.choose_set_size(18) == 9
    with pytest.raises(ValueError):
        ellipses.choose_set_size(17)   # prime > 16


def test_parse_pools():
    pools = ellipses.parse_pools(["/a/d{1...4}", "/b/d{1...4}"])
    assert len(pools) == 2 and len(pools[0]) == 4
    pools = ellipses.parse_pools(["/x", "/y", "/z", "/w"])
    assert pools == [["/x", "/y", "/z", "/w"]]


# ---------------------------------------------------------------------------
# siphash (reference vectors from the SipHash-2-4 specification)
# ---------------------------------------------------------------------------

def test_siphash24_reference_vectors():
    key = bytes(range(16))
    # vectors[i] = SipHash-2-4(key, bytes(range(i))) from the spec's
    # published test vector table.
    vectors = {
        0: 0x726FDB47DD0E0E31,
        1: 0x74F839C593DC67FD,
        2: 0x0D6C8009D9A94F5A,
        7: 0xAB0200F58B01D137,
        8: 0x93F5F5799A932462,
        15: 0xA129CA6149BE45E5,
    }
    for n, want in vectors.items():
        assert siphash24(key, bytes(range(n))) == want, n


def test_sip_hash_mod_distributes():
    id_ = os.urandom(16)
    counts = [0] * 8
    for i in range(4000):
        counts[sip_hash_mod(f"obj-{i}", 8, id_)] += 1
    assert min(counts) > 300   # roughly uniform


# ---------------------------------------------------------------------------
# format.json boot
# ---------------------------------------------------------------------------

def _mkdisks(tmp_path, n, prefix="d"):
    return [LocalStorage(str(tmp_path / f"{prefix}{i}")) for i in range(n)]


def test_format_fresh_init_and_reload(tmp_path):
    disks = _mkdisks(tmp_path, 4)
    ordered, fmt = fmt_mod.boot(disks, 4)
    assert len(fmt.sets) == 1 and len(fmt.sets[0]) == 4
    for d, u in zip(ordered, fmt.sets[0]):
        assert d.read_format()["xl"]["this"] == u
    # Reload with SHUFFLED drive objects: order restored from format.
    shuffled = list(disks)
    random.Random(7).shuffle(shuffled)
    ordered2, fmt2 = fmt_mod.boot(shuffled, 4)
    assert fmt2.deployment_id == fmt.deployment_id
    assert [d.root for d in ordered2] == [d.root for d in ordered]


def test_format_fresh_drive_healed_into_position(tmp_path):
    import shutil
    disks = _mkdisks(tmp_path, 4)
    _, fmt = fmt_mod.boot(disks, 4)
    # Drive 2 is replaced with a blank one.
    shutil.rmtree(tmp_path / "d2")
    disks2 = _mkdisks(tmp_path, 4)
    ordered, fmt2 = fmt_mod.boot(disks2, 4)
    assert all(d is not None for d in ordered)
    healed = ordered[2]
    assert healed.read_format()["xl"]["this"] == fmt.sets[0][2]


def test_format_foreign_drive_refused(tmp_path):
    disks = _mkdisks(tmp_path, 4)
    fmt_mod.boot(disks, 4)
    foreign = _mkdisks(tmp_path, 4, prefix="f")
    fmt_mod.boot(foreign, 4)   # a different deployment
    # Swap one drive from the foreign deployment in.
    mixed = disks[:3] + [foreign[0]]
    ordered, _ = fmt_mod.boot(mixed, 4)
    # The foreign drive must NOT occupy the missing position...
    assert ordered.count(None) == 1
    assert foreign[0] not in ordered
    # ...and its own identity was never overwritten.
    assert fmt_mod.FormatInfo.from_json(
        foreign[0].read_format()).deployment_id != \
        fmt_mod.FormatInfo.from_json(disks[0].read_format()).deployment_id


def test_format_no_quorum_fails(tmp_path):
    disks = _mkdisks(tmp_path, 4)
    fmt_mod.boot(disks, 4)
    # Wipe 3 of 4 formats -> only 1 vote, below quorum.
    for i in (0, 1, 2):
        os.remove(tmp_path / f"d{i}" / ".mtpu.sys" / "format.json")
    with pytest.raises(fmt_mod.FormatError):
        fmt_mod.boot(_mkdisks(tmp_path, 4), 4)


# ---------------------------------------------------------------------------
# multi-set layer
# ---------------------------------------------------------------------------

def make_sets_layer(tmp_path, n_sets=2, width=4):
    sets = []
    for s in range(n_sets):
        disks = [LocalStorage(str(tmp_path / f"s{s}d{i}"))
                 for i in range(width)]
        sets.append(ErasureSet(disks))
    layer = ErasureSets(sets)
    layer.make_bucket("bkt")
    return layer


def test_sets_round_trip_and_distribution(tmp_path):
    layer = make_sets_layer(tmp_path)
    hits = [0, 0]
    for i in range(40):
        key = f"obj-{i}"
        layer.put_object("bkt", key, f"payload-{i}".encode())
        hits[layer.set_index(key)] += 1
    assert all(h > 0 for h in hits)   # both sets used
    for i in range(40):
        _, got = layer.get_object("bkt", f"obj-{i}")
        assert got == f"payload-{i}".encode()
    # Objects live ONLY in their routed set.
    for i in range(40):
        key = f"obj-{i}"
        other = layer.sets[1 - layer.set_index(key)]
        with pytest.raises(Exception):
            other.get_object_info("bkt", key)


def test_sets_listing_merges(tmp_path):
    layer = make_sets_layer(tmp_path)
    keys = sorted(f"k/{i:03d}" for i in range(30))
    for k in keys:
        layer.put_object("bkt", k, b"x")
    info = layer.list_objects("bkt", prefix="k/", max_keys=1000)
    assert [o.name for o in info.objects] == keys
    # Pagination across sets.
    page1 = layer.list_objects("bkt", prefix="k/", max_keys=10)
    assert len(page1.objects) == 10 and page1.is_truncated
    page2 = layer.list_objects("bkt", prefix="k/",
                               marker=page1.next_marker, max_keys=1000)
    assert [o.name for o in page1.objects] + \
        [o.name for o in page2.objects] == keys


def test_sets_delete_and_bucket_lifecycle(tmp_path):
    layer = make_sets_layer(tmp_path)
    layer.put_object("bkt", "a", b"1")
    with pytest.raises(BucketNotEmpty):
        layer.delete_bucket("bkt")
    layer.delete_object("bkt", "a")
    layer.delete_bucket("bkt")
    with pytest.raises(Exception):
        layer.get_bucket_info("bkt")


def test_sets_survive_parity_failures_per_set(tmp_path):
    import shutil
    layer = make_sets_layer(tmp_path)   # 2 sets x 4 drives, parity 2
    for i in range(20):
        layer.put_object("bkt", f"o{i}", os.urandom(10_000))
    # Kill 2 drives in EACH set (= parity width per set).
    for s in range(2):
        for d in range(2):
            shutil.rmtree(tmp_path / f"s{s}d{d}")
            os.makedirs(tmp_path / f"s{s}d{d}" / ".mtpu.sys" / "tmp")
    for i in range(20):
        _, got = layer.get_object("bkt", f"o{i}")
        assert len(got) == 10_000


def test_sets_multipart_routes(tmp_path):
    from minio_tpu.object import multipart as mp
    layer = make_sets_layer(tmp_path)
    uid = layer.new_multipart_upload("bkt", "big")
    p1 = os.urandom(mp.MIN_PART_SIZE)
    e1 = layer.put_object_part("bkt", "big", uid, 1, p1)
    e2 = layer.put_object_part("bkt", "big", uid, 2, b"tail")
    layer.complete_multipart_upload("bkt", "big", uid,
                                    [(1, e1.etag), (2, e2.etag)])
    _, got = layer.get_object("bkt", "big")
    assert got == p1 + b"tail"


# ---------------------------------------------------------------------------
# pools
# ---------------------------------------------------------------------------

def make_pools_layer(tmp_path, n_pools=2, width=4):
    pools = []
    for p in range(n_pools):
        disks = [LocalStorage(str(tmp_path / f"p{p}d{i}"))
                 for i in range(width)]
        pools.append(ErasureSets([ErasureSet(disks)]))
    layer = ServerPools(pools)
    layer.make_bucket("bkt")
    return layer


def test_pools_put_get_delete(tmp_path):
    layer = make_pools_layer(tmp_path)
    layer.put_object("bkt", "x", b"data")
    _, got = layer.get_object("bkt", "x")
    assert got == b"data"
    # Overwrite stays in the pool that holds the key.
    holder = next(i for i, p in enumerate(layer.pools)
                  if _has(p, "bkt", "x"))
    layer.put_object("bkt", "x", b"data2")
    assert _has(layer.pools[holder], "bkt", "x")
    assert not _has(layer.pools[1 - holder], "bkt", "x")
    layer.delete_object("bkt", "x")
    with pytest.raises(ObjectNotFound):
        layer.get_object("bkt", "x")


def _has(pool, bucket, key) -> bool:
    try:
        pool.get_object_info(bucket, key)
        return True
    except Exception:  # noqa: BLE001
        return False


def test_pools_listing_merges(tmp_path):
    layer = make_pools_layer(tmp_path)
    # Force keys into specific pools by writing directly.
    layer.pools[0].put_object("bkt", "a", b"1")
    layer.pools[1].put_object("bkt", "b", b"2")
    info = layer.list_objects("bkt")
    assert [o.name for o in info.objects] == ["a", "b"]


def test_offline_disk_positions_tolerated(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    disks[3] = OfflineDisk("gone")
    es = ErasureSet(disks)
    es.make_bucket("bkt")
    es.put_object("bkt", "k", b"v" * 1000)
    _, got = es.get_object("bkt", "k")
    assert got == b"v" * 1000


def test_server_main_boots_pools(tmp_path):
    """End-to-end: ellipses arg -> pools/sets/format boot -> S3 serves."""
    import threading
    from minio_tpu import server as srv_mod
    from minio_tpu.object.pools import ServerPools as SP

    # Build the layer exactly as main() does, without the HTTP loop.
    from minio_tpu.topology import ellipses as el
    spec = str(tmp_path / "d{1...8}")
    drives = el.expand(spec)
    assert len(drives) == 8
    disks = [LocalStorage(p) for p in drives]
    size = el.choose_set_size(len(disks))
    assert size == 8
    ordered, fmt = fmt_mod.boot(disks, size)
    layer = SP([ErasureSets([ErasureSet(ordered)], fmt.deployment_id)])
    layer.make_bucket("b1")
    layer.put_object("b1", "k", b"v")
    _, got = layer.get_object("b1", "k")
    assert got == b"v"
