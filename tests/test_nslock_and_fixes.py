"""Namespace locking + round-2 hardening fixes.

Covers: the nsLock map (reference cmd/namespace-lock.go) under a
many-writers-one-key storm, parity-range validation (reference
storage-class validation), UUID-named user keys in listings (walk_dir
data-dir disambiguation), atomic multipart part commits, raw-path SigV4
verification, and the stricter dangling-purge criteria.
"""

import os
import threading
import uuid

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.nslock import NSLockMap, LockTimeout
from minio_tpu.object.types import DeleteOptions, ObjectNotFound, PutOptions
from minio_tpu.storage.local import LocalStorage


def make_set(tmp_path, n=4, parity=None):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(n)]
    es = ErasureSet(disks, parity=parity)
    es.make_bucket("bkt")
    return es


# ---------------------------------------------------------------------------
# nslock primitives
# ---------------------------------------------------------------------------

def test_nslock_write_excludes_write():
    ns = NSLockMap()
    with ns.write("b", "o"):
        # Second writer must time out while the first holds the lock.
        with pytest.raises(LockTimeout):
            with ns.write("b", "o", timeout=0.1):
                pass
    # Released: a new writer acquires immediately.
    with ns.write("b", "o", timeout=1):
        pass


def test_nslock_readers_share_writers_exclude():
    ns = NSLockMap()
    with ns.read("b", "o"):
        with ns.read("b", "o"):   # second reader enters fine
            with pytest.raises(LockTimeout):
                with ns.write("b", "o", timeout=0.1):
                    pass
    # After release the writer proceeds and the map is empty again.
    with ns.write("b", "o", timeout=1):
        pass
    assert not ns._locks


def test_nslock_keys_independent():
    ns = NSLockMap()
    with ns.write("b", "o1"):
        with ns.write("b", "o2", timeout=0.5):
            pass


# ---------------------------------------------------------------------------
# many writers, one key: no mixed-version states (VERDICT missing #4)
# ---------------------------------------------------------------------------

def test_one_key_write_storm_stays_consistent(tmp_path):
    es = make_set(tmp_path)
    n_threads, n_rounds = 8, 6
    payloads = [f"writer-{t}".encode() * 4096 for t in range(n_threads)]
    errs = []

    def writer(t):
        try:
            for r in range(n_rounds):
                if t % 3 == 2 and r % 2 == 1:
                    try:
                        es.delete_object("bkt", "hot")
                    except ObjectNotFound:
                        pass
                else:
                    es.put_object("bkt", "hot", payloads[t])
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    # Final state must be coherent: either a clean 404 or a quorum read
    # returning exactly one writer's payload — never a torn mix.
    es.mrf.drain()
    try:
        _, got = es.get_object("bkt", "hot")
    except ObjectNotFound:
        return
    assert got in payloads
    # Every drive that has the key agrees on the quorum version.
    fi, fis, _ = es._get_object_fileinfo("bkt", "hot")
    mods = {f.mod_time for f in fis if f is not None}
    assert fi.mod_time in mods


# ---------------------------------------------------------------------------
# parity validation (ADVICE medium #1)
# ---------------------------------------------------------------------------

def test_parity_out_of_range_rejected(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(8)]
    with pytest.raises(ValueError):
        ErasureSet(disks, parity=6)     # 6 > 8//2
    with pytest.raises(ValueError):
        ErasureSet(disks, parity=-1)
    ErasureSet(disks, parity=4)         # boundary OK


def test_server_boot_rejects_bad_parity(tmp_path):
    from minio_tpu.server import main
    with pytest.raises(SystemExit):
        main(["--parity", "3", str(tmp_path / "a"), str(tmp_path / "b"),
              str(tmp_path / "c"), str(tmp_path / "d")])


# ---------------------------------------------------------------------------
# UUID-named user keys stay listable (ADVICE medium #2)
# ---------------------------------------------------------------------------

def test_uuid_named_nested_key_is_listed(tmp_path):
    es = make_set(tmp_path)
    uuid_key = f"a/{uuid.UUID(int=0x1234)}"
    es.put_object("bkt", "a", b"parent")
    es.put_object("bkt", uuid_key, b"child")
    keys = {o.name for o in es.list_objects("bkt").objects}
    assert keys == {"a", uuid_key}
    # And the real data dirs are still not listed as keys.
    big = os.urandom(600 << 10)          # non-inline -> has a data dir
    es.put_object("bkt", "b", big)
    keys = {o.name for o in es.list_objects("bkt").objects}
    assert keys == {"a", uuid_key, "b"}


def test_uuid_key_directly_under_object(tmp_path):
    es = make_set(tmp_path)
    es.put_object("bkt", "o", os.urandom(600 << 10))  # non-inline
    child = f"o/{uuid.UUID(int=7)}"
    es.put_object("bkt", child, b"x")
    keys = {o.name for o in es.list_objects("bkt").objects}
    assert keys == {"o", child}


# ---------------------------------------------------------------------------
# multipart: torn part files cannot pair with a valid .meta (ADVICE low #3)
# ---------------------------------------------------------------------------

def test_part_reupload_is_atomic(tmp_path):
    from minio_tpu.object import multipart as mp
    es = make_set(tmp_path)
    uid = es.new_multipart_upload("bkt", "m")
    first = os.urandom(mp.MIN_PART_SIZE)
    second = os.urandom(mp.MIN_PART_SIZE)
    es.put_object_part("bkt", "m", uid, 1, first)
    e2 = es.put_object_part("bkt", "m", uid, 1, second)  # re-upload
    tail = es.put_object_part("bkt", "m", uid, 2, b"tail")
    es.complete_multipart_upload("bkt", "m", uid,
                                 [(1, e2.etag), (2, tail.etag)])
    _, got = es.get_object("bkt", "m")
    assert got == second + b"tail"


# ---------------------------------------------------------------------------
# SigV4 raw-path verification (ADVICE low #4)
# ---------------------------------------------------------------------------

def test_sigv4_differently_encoded_path_verifies():
    """A client that percent-encodes more characters than urllib's safe
    set must still verify: the wire path is signed verbatim."""
    import datetime
    import hashlib
    import hmac
    from minio_tpu.s3 import sigv4

    secret = "sk"
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    scope = f"{amz_date[:8]}/us-east-1/s3/aws4_request"
    # Client encodes '~' (allowed unencoded by RFC3986) as %7E.
    raw_path = "/bkt/weird%7Ekey"
    headers = {"host": "h", "x-amz-date": amz_date,
               "x-amz-content-sha256": sigv4.EMPTY_SHA256}
    signed = sorted(headers)
    canon = sigv4.canonical_request("GET", "", {}, headers, signed,
                                    sigv4.EMPTY_SHA256, raw_path=raw_path)
    sts = sigv4.string_to_sign(amz_date, scope, canon)
    key = sigv4.signing_key(secret, amz_date[:8], "us-east-1")
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"{sigv4.ALGORITHM} Credential=ak/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    auth = sigv4.verify_request("GET", raw_path, {}, headers,
                                lambda ak: secret if ak == "ak" else None)
    assert auth.credential.access_key == "ak"


def test_sigv4_rfc1123_date_header_accepted():
    """Clients signing with only a Date header (RFC1123) must pass the
    skew check instead of being rejected by the %Y%m%dT%H%M%SZ parse."""
    import datetime
    import hashlib
    import hmac
    from minio_tpu.s3 import sigv4

    secret = "sk"
    now = datetime.datetime.now(datetime.timezone.utc)
    date_hdr = now.strftime("%a, %d %b %Y %H:%M:%S GMT")
    scope = f"{now.strftime('%Y%m%d')}/us-east-1/s3/aws4_request"
    headers = {"host": "h", "date": date_hdr,
               "x-amz-content-sha256": sigv4.EMPTY_SHA256}
    signed = sorted(headers)
    canon = sigv4.canonical_request("GET", "", {}, headers, signed,
                                    sigv4.EMPTY_SHA256, raw_path="/b/k")
    # Spec-compliant clients put the ISO8601 rendering of the Date
    # header's instant in the string-to-sign, not the RFC1123 string.
    sts = sigv4.string_to_sign(now.strftime("%Y%m%dT%H%M%SZ"), scope, canon)
    key = sigv4.signing_key(secret, now.strftime("%Y%m%d"), "us-east-1")
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"{sigv4.ALGORITHM} Credential=ak/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    auth = sigv4.verify_request("GET", "/b/k", {}, headers,
                                lambda ak: secret if ak == "ak" else None)
    assert auth.credential.access_key == "ak"


# ---------------------------------------------------------------------------
# stricter dangling purge (ADVICE low #5)
# ---------------------------------------------------------------------------

def test_quorum_thin_write_not_purged(tmp_path):
    """A copy surviving on exactly k drives is below the majority but can
    still satisfy read quorum: heal must repair, never purge."""
    import shutil
    es = make_set(tmp_path, n=4)       # k=2, m=2
    es.put_object("bkt", "thin", os.urandom(1 << 20))
    # Remove from 2 of 4 drives: not_found == n//2 == 2 is NOT a majority.
    for i in (0, 1):
        shutil.rmtree(tmp_path / f"d{i}" / "bkt" / "thin")
    res = es.heal_object("bkt", "thin")
    assert res.healed == 2
    _, got = es.get_object("bkt", "thin")
    assert len(got) == 1 << 20
