"""Versioning SUSPENSION semantics over the wire: Suspended is a real
state (reference: internal/bucket/versioning/versioning.go:36,76), not
versioning-off — suspended writes stamp the null versionId replacing
the previous null version, Enabled-era versions survive, and simple
deletes insert a null delete marker. The enable -> suspend -> write ->
re-enable matrix AWS documents."""

import xml.etree.ElementTree as ET

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

B = "suspbkt"


@pytest.fixture(scope="module")
def cli(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("suspdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    server = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    server.start()
    c = S3Client(server.address)
    assert c.request("PUT", f"/{B}")[0] == 200
    yield c
    server.stop()


def _set_versioning(cli, status):
    body = (f'<VersioningConfiguration><Status>{status}</Status>'
            f'</VersioningConfiguration>').encode()
    st, _, b = cli.request("PUT", f"/{B}", query={"versioning": ""},
                           body=body)
    assert st == 200, b


def _versions(cli, key):
    """[(versionId, isLatest, isMarker)] newest-first for one key."""
    st, _, body = cli.request("GET", f"/{B}", query={"versions": "",
                                                     "prefix": key})
    assert st == 200
    root = ET.fromstring(body)
    ns = root.tag.split("}")[0] + "}"
    out = []
    for el in root:
        if el.tag in (f"{ns}Version", f"{ns}DeleteMarker"):
            out.append((el.findtext(f"{ns}VersionId"),
                        el.findtext(f"{ns}IsLatest") == "true",
                        el.tag == f"{ns}DeleteMarker"))
    return out


def test_enable_suspend_write_reenable_matrix(cli):
    key = "doc"
    # 1. Pre-versioning write: the null version.
    assert cli.request("PUT", f"/{B}/{key}", body=b"null-v0")[0] == 200
    # 2. Enable; two real versions stack above it.
    _set_versioning(cli, "Enabled")
    st, h, _ = cli.request("PUT", f"/{B}/{key}", body=b"real-v1")
    vid1 = h.get("x-amz-version-id")
    st, h, _ = cli.request("PUT", f"/{B}/{key}", body=b"real-v2")
    vid2 = h.get("x-amz-version-id")
    assert vid1 and vid2 and vid1 != vid2
    vs = _versions(cli, key)
    assert [v[0] for v in vs] == [vid2, vid1, "null"]
    # 3. Suspend: reported as a distinct state, and writes now REPLACE
    #    the null version while vid1/vid2 survive.
    _set_versioning(cli, "Suspended")
    st, _, body = cli.request("GET", f"/{B}", query={"versioning": ""})
    assert b"Suspended" in body
    st, h, _ = cli.request("PUT", f"/{B}/{key}", body=b"null-v1")
    assert st == 200 and not h.get("x-amz-version-id")
    vs = _versions(cli, key)
    assert [v[0] for v in vs] == ["null", vid2, vid1]
    assert vs[0][1]                      # the new null is latest
    assert cli.request("GET", f"/{B}/{key}")[2] == b"null-v1"
    # Enabled-era versions still readable by id.
    st, _, got = cli.request("GET", f"/{B}/{key}",
                             query={"versionId": vid1})
    assert st == 200 and got == b"real-v1"
    # 4. Suspended simple DELETE: a NULL delete marker replaces the
    #    null version; real versions survive.
    st, h, _ = cli.request("DELETE", f"/{B}/{key}")
    assert st == 204
    assert h.get("x-amz-delete-marker") == "true"
    assert h.get("x-amz-version-id") in (None, "null")
    vs = _versions(cli, key)
    assert [(v[0], v[2]) for v in vs] == [("null", True),
                                          (vid2, False), (vid1, False)]
    assert cli.request("GET", f"/{B}/{key}")[0] == 404
    st, _, got = cli.request("GET", f"/{B}/{key}",
                             query={"versionId": vid2})
    assert st == 200 and got == b"real-v2"
    # A second suspended DELETE is idempotent: still ONE null marker.
    assert cli.request("DELETE", f"/{B}/{key}")[0] == 204
    assert len(_versions(cli, key)) == 3
    # 5. Re-enable: new writes get real ids again; the null marker and
    #    old versions are preserved beneath.
    _set_versioning(cli, "Enabled")
    st, h, _ = cli.request("PUT", f"/{B}/{key}", body=b"real-v3")
    vid3 = h.get("x-amz-version-id")
    assert vid3
    vs = _versions(cli, key)
    assert [v[0] for v in vs] == [vid3, "null", vid2, vid1]
    assert cli.request("GET", f"/{B}/{key}")[2] == b"real-v3"
    # 6. Deleting the null marker by explicit versionId removes it.
    st, _, _ = cli.request("DELETE", f"/{B}/{key}",
                           query={"versionId": "null"})
    assert st == 204
    assert [v[0] for v in _versions(cli, key)] == [vid3, vid2, vid1]


def test_suspended_overwrite_reclaims_only_null(cli):
    key = "cycle"
    _set_versioning(cli, "Enabled")
    st, h, _ = cli.request("PUT", f"/{B}/{key}", body=b"keeper")
    vid = h.get("x-amz-version-id")
    _set_versioning(cli, "Suspended")
    for i in range(3):
        assert cli.request("PUT", f"/{B}/{key}",
                           body=f"null-{i}".encode())[0] == 200
    vs = _versions(cli, key)
    # Three suspended overwrites collapse into ONE null version.
    assert [v[0] for v in vs] == ["null", vid]
    assert cli.request("GET", f"/{B}/{key}")[2] == b"null-2"
    _set_versioning(cli, "Enabled")


def test_invalid_status_rejected(cli):
    st, _, body = cli.request(
        "PUT", f"/{B}", query={"versioning": ""},
        body=b"<VersioningConfiguration><Status>Paused</Status>"
             b"</VersioningConfiguration>")
    assert st == 400
