"""Drive lifecycle: hot replacement + checkpointed bulk heal, plus the
satellite hardening (MRF overflow spill, sweep safety, readiness
honesty, heal-vs-overwrite under NSLock, fi_cache invalidation after
heal). Reference patterns: cmd/background-newdisks-heal-ops.go,
cmd/global-heal.go, cmd/mrf.go."""

import json
import os
import shutil
import threading
import time

import pytest

from minio_tpu.object.drive_heal import (DriveHealManager, admission_pressure,
                                         bulk_heal_drive, new_tracker)
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.healing import DRIVE_STATE_OK, MRFQueue
from minio_tpu.storage.local import (SYS_VOL, LocalStorage, clear_healing,
                                     read_healing, sweep_stale_tmp,
                                     write_healing)

BKT = "bkt"


def make_set(tmp_path, n=4):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(n)]
    es = ErasureSet(disks)
    es.make_bucket(BKT)
    return es


def _replace_drive(tmp_path, i):
    """Swap drive i for a factory-fresh one (empty dir, no format)."""
    shutil.rmtree(tmp_path / f"d{i}")
    os.makedirs(tmp_path / f"d{i}")


def _init_formats(es):
    from minio_tpu.topology.format import init_formats
    init_formats(es.disks, len(es.disks))


# ---------------------------------------------------------------------------
# hot replacement e2e
# ---------------------------------------------------------------------------

def test_hot_replacement_converges_under_load(tmp_path):
    es = make_set(tmp_path)
    _init_formats(es)
    objs = {f"pre-{i:03d}": os.urandom(40_000 + i) for i in range(12)}
    for k, v in objs.items():
        es.put_object(BKT, k, v)

    _replace_drive(tmp_path, 1)
    # Concurrent traffic while the manager detects + bulk-heals: PUTs
    # land new data on the replaced drive immediately, GETs reconstruct
    # around the hole — both at quorum throughout.
    stop = threading.Event()
    failures: list = []

    def writer(tid):
        # Read-your-writes traffic: GETs stay off the pre-swap keys so
        # the degraded-read MRF hook cannot race the bulk heal to them
        # (the heal-count assertions below need the bulk sweep to be
        # the thing that repairs `objs`).
        i = 0
        while not stop.is_set():
            key = f"live-{tid}-{i:03d}"
            try:
                body = os.urandom(20_000)
                es.put_object(BKT, key, body)
                _, got = es.get_object(BKT, key)
                assert got == body
            except Exception as e:  # noqa: BLE001 - collected for assert
                failures.append((key, e))
            i += 1

    threads = [threading.Thread(target=writer, args=(t,), daemon=True)
               for t in range(3)]
    for t in threads:
        t.start()
    try:
        mgr = DriveHealManager([es], throttle=0.0, checkpoint_every=4)
        started = mgr.poll_once()
        assert started == 1 and mgr.formats_restored == 1
        # The replaced drive got its slot identity back immediately.
        assert es.disks[1].read_format() is not None
        st = mgr.status()
        assert st["drives"] and st["drives"][0]["state"] in ("healing",
                                                            "done")
        assert mgr.wait(60)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not failures, f"traffic failed during heal: {failures[:3]}"

    # Marker cleared, tracker finished with real progress counted.
    assert read_healing(es.disks[1]) is None
    st = mgr.status()["drives"][0]
    assert st["state"] == "done" and st["finished"]
    assert st["objects_healed"] >= len(objs)
    assert st["bytes_healed"] >= sum(len(v) for v in objs.values())

    # Convergence: zero missing/stale shards for the pre-swap objects
    # on the replaced drive — a re-heal finds nothing to do.
    for k, v in objs.items():
        r = es.heal_object(BKT, k)
        assert r.healed == 0 and r.before[1] == DRIVE_STATE_OK
        _, got = es.get_object(BKT, k)
        assert got == v
    mgr.stop()
    es.close()


def test_bulk_heal_checkpoint_resumes_across_restart(tmp_path):
    es = make_set(tmp_path)
    _init_formats(es)
    for i in range(30):
        es.put_object(BKT, f"o-{i:04d}", os.urandom(30_000))
    _replace_drive(tmp_path, 2)

    mgr = DriveHealManager([es], throttle=0.0, checkpoint_every=3)
    assert mgr.poll_once() == 1

    # "Crash" the process mid-heal: stop the manager once a checkpoint
    # landed on the drive, before the sweep finishes.
    deadline = time.time() + 30
    while time.time() < deadline:
        t = read_healing(es.disks[2])
        if t and t.get("checkpoint_object"):
            break
        time.sleep(0.005)
    mgr._stop.set()
    mgr.wait(30)
    persisted = read_healing(es.disks[2])
    if persisted is None:
        # The heal outran the stop signal — nothing left to resume;
        # the run above still validated checkpoint persistence.
        pytest.skip("bulk heal finished before the simulated crash")
    assert persisted["checkpoint_object"] and not persisted["finished"]

    # "Restart": a fresh manager resumes FROM the checkpoint, not from
    # scratch — and converges.
    mgr2 = DriveHealManager([es], throttle=0.0, checkpoint_every=3)
    assert mgr2.poll_once() == 1
    assert mgr2.wait(60)
    assert read_healing(es.disks[2]) is None
    done = mgr2.status()["drives"][0]
    assert done["state"] == "done"
    # Resumed sweep scanned from the checkpoint forward: strictly fewer
    # walks than the full namespace plus the pre-crash progress.
    assert done["objects_scanned"] <= 30
    assert done["checkpoint_object"] >= persisted["checkpoint_object"]
    for i in range(30):
        r = es.heal_object(BKT, f"o-{i:04d}")
        assert r.healed == 0 and r.before[2] == DRIVE_STATE_OK
    mgr2.stop()
    es.close()


def test_bulk_heal_restores_every_version(tmp_path):
    from minio_tpu.object.types import DeleteOptions, PutOptions
    from minio_tpu.storage.meta import XLMeta
    es = make_set(tmp_path)
    _init_formats(es)
    v1 = es.put_object(BKT, "ver", os.urandom(200_000),
                       PutOptions(versioned=True))
    v2 = es.put_object(BKT, "ver", os.urandom(210_000),
                       PutOptions(versioned=True))
    es.delete_object(BKT, "ver", DeleteOptions(versioned=True))

    _replace_drive(tmp_path, 1)
    mgr = DriveHealManager([es], throttle=0.0)
    assert mgr.poll_once() == 1 and mgr.wait(60)

    # The replaced drive holds the FULL version stack again: both data
    # versions and the delete marker — not just the latest.
    xl = XLMeta.load(open(tmp_path / "d1" / BKT / "ver" / "xl.meta",
                          "rb").read())
    vids = {v.get("vid") for v in xl.versions}
    assert v1.version_id in vids and v2.version_id in vids
    assert len(xl.versions) == 3
    for vid in (v1.version_id, v2.version_id):
        r = es.heal_object(BKT, "ver", vid)
        assert r.healed == 0 and r.before[1] == DRIVE_STATE_OK
    mgr.stop()
    es.close()


def test_clean_shutdown_stamp(tmp_path):
    from minio_tpu.storage.local import (consume_clean_shutdown,
                                         mark_clean_shutdown)
    d = LocalStorage(str(tmp_path / "d0"))
    assert not consume_clean_shutdown(d), "no stamp after a cold start"
    mark_clean_shutdown(d)
    assert consume_clean_shutdown(d), "graceful stop leaves the stamp"
    assert not consume_clean_shutdown(d), "the stamp is single-use"


def test_boot_time_fresh_drive_gets_healing_marker(tmp_path):
    from minio_tpu.topology import format as fmt_mod
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    fmt_mod.init_formats(disks, 4)
    shutil.rmtree(tmp_path / "d3")
    os.makedirs(tmp_path / "d3")
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    ordered, _ = fmt_mod.load_and_order(disks, 4)
    assert all(d is not None for d in ordered)
    marked = [d for d in ordered if read_healing(d) is not None]
    assert len(marked) == 1
    t = read_healing(marked[0])
    assert t["disk_index"] == 3 and not t["finished"]
    # The marker surfaces on disk_info so readiness can see it.
    assert marked[0].disk_info().healing


def test_bulk_heal_sheds_under_admission_pressure(tmp_path):
    es = make_set(tmp_path)
    for i in range(4):
        es.put_object(BKT, f"o-{i}", os.urandom(10_000))
    _replace_drive(tmp_path, 1)
    es.disks[1].write_format({"xl": {"this": "x"}})  # slot restored

    pressured = {"on": True, "polls": 0}

    def pressure():
        pressured["polls"] += 1
        return pressured["on"]

    tracker = new_tracker(0, 1)
    stop = threading.Event()
    th = threading.Thread(
        target=bulk_heal_drive,
        args=(es, 1, tracker),
        kwargs={"stop": stop, "pressure": pressure}, daemon=True)
    th.start()
    deadline = time.time() + 10
    while pressured["polls"] == 0 and time.time() < deadline:
        time.sleep(0.005)
    assert pressured["polls"] > 0
    # While shedding, no object progress happens.
    time.sleep(0.1)
    assert tracker["objects_scanned"] <= 1
    pressured["on"] = False       # pressure clears -> heal proceeds
    th.join(timeout=30)
    assert tracker["finished"]
    es.close()


def test_admission_pressure_reads_snapshot():
    class FakeAdm:
        def __init__(self, waiting, in_flight=0, limit=0):
            self._v = {"object": {"waiting": waiting,
                                  "in_flight": in_flight,
                                  "limit": limit}}

        def snapshot(self):
            return dict(self._v, deadline_exceeded_total=0)

    assert not admission_pressure(None)
    assert not admission_pressure(FakeAdm(0))
    assert admission_pressure(FakeAdm(3))
    assert admission_pressure(FakeAdm(0, in_flight=8, limit=8))


# ---------------------------------------------------------------------------
# readiness honesty + admin/metrics surfacing
# ---------------------------------------------------------------------------

def _raw_get(address, path):
    import http.client
    host, port = address.split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def test_readiness_names_healing_sets(tmp_path):
    from minio_tpu.s3.server import S3Server
    es = make_set(tmp_path)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    try:
        st, body = _raw_get(server.address, "/minio/health/ready")
        assert st == 200 and json.loads(body)["ready"] is True

        write_healing(es.disks[2], new_tracker(0, 2))
        st, body = _raw_get(server.address, "/minio/health/ready")
        assert st == 503
        payload = json.loads(body)
        assert payload["ready"] is False
        assert payload["degraded_sets"][0]["set"] == 0
        assert payload["degraded_sets"][0]["healing_drives"] == 1

        clear_healing(es.disks[2])
        st, _ = _raw_get(server.address, "/minio/health/ready")
        assert st == 200
    finally:
        server.stop()


def test_heal_endpoint_and_metrics_surface_drive_progress(tmp_path):
    from minio_tpu.s3.server import S3Server
    from tests.s3client import S3Client
    es = make_set(tmp_path)
    es.put_object(BKT, "o", os.urandom(10_000))
    server = S3Server(es, address="127.0.0.1:0")
    mgr = DriveHealManager([es])
    tracker = dict(new_tracker(0, 1), objects_scanned=7,
                   objects_healed=5, bytes_healed=12345, finished=True)
    mgr._done[(0, 1)] = tracker
    server.drive_heal = mgr
    server.start()
    try:
        cli = S3Client(server.address)
        st, _, body = cli.request("GET", "/minio/admin/v3/heal")
        assert st == 200
        payload = json.loads(body)
        drives = payload["drive_heal"]["drives"]
        assert drives[0]["objects_healed"] == 5
        assert drives[0]["state"] == "done"

        st, body = _raw_get(server.address, "/minio/v2/metrics/cluster")
        text = body.decode()
        assert "minio_tpu_drive_heal_objects_healed" in text
        assert 'set="0",drive="1"} 5' in text
        assert "minio_tpu_mrf_dropped_total" in text
        assert "minio_tpu_drives_healing 0" in text
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# MRF overflow spill (satellite)
# ---------------------------------------------------------------------------

def test_mrf_overflow_spills_to_pending_and_replays(tmp_path):
    es = make_set(tmp_path)
    keys = []
    for i in range(3):
        k = f"mrf-{i}"
        es.put_object(BKT, k, os.urandom(5_000))
        keys.append(k)

    q = MRFQueue(es, max_items=1, persist=True)
    q._stop.set()
    q._worker.join(timeout=5)
    for k in keys:
        q.enqueue(BKT, k, "")
    st = q.stats()
    assert st["pending"] == 3, "overflow entries must stay pending"
    assert st["spilled"] == 2, "overflow must be visible as spills"
    assert st["dropped"] == 0, "a spill is not a loss"
    q.save_now()

    # Next boot: the persisted spill replays in full, draining through
    # the bounded queue as it frees up (the _refill_one path).
    q2 = MRFQueue(es, max_items=1, persist=True)
    deadline = time.time() + 30
    while time.time() < deadline and q2.stats()["pending"]:
        time.sleep(0.02)
    assert q2.stats()["pending"] == 0
    assert q2.healed == 3
    q2.stop()
    es.close()


# ---------------------------------------------------------------------------
# sweep safety (satellite)
# ---------------------------------------------------------------------------

def test_sweep_skips_live_workers_and_young_entries(tmp_path):
    d = LocalStorage(str(tmp_path / "d0"))
    staging = os.path.join(d.root, SYS_VOL, "staging")
    os.makedirs(staging)
    # A live sibling worker's in-flight PUT (pid 1 is always alive).
    os.makedirs(os.path.join(staging, "p1-aaaa-bbbb"))
    # A dead worker's leftover (pid far beyond pid_max growth in tests).
    os.makedirs(os.path.join(staging, "p999999999-cccc"))
    # Untagged legacy entry.
    os.makedirs(os.path.join(staging, "dddd-eeee"))

    removed = sweep_stale_tmp(d, min_age=3600)
    assert removed == 1, "age gate must protect young untagged entries"
    assert not os.path.isdir(os.path.join(staging, "p999999999-cccc"))
    assert os.path.isdir(os.path.join(staging, "p1-aaaa-bbbb"))
    assert os.path.isdir(os.path.join(staging, "dddd-eeee"))

    removed = sweep_stale_tmp(d, min_age=0)
    assert removed == 1
    assert os.path.isdir(os.path.join(staging, "p1-aaaa-bbbb")), \
        "a live sibling's staging must survive any sweep"
    assert not os.path.isdir(os.path.join(staging, "dddd-eeee"))


def test_recovery_sweep_classification(tmp_path):
    import uuid
    from minio_tpu.storage.local import recovery_sweep
    es = make_set(tmp_path)
    # Large enough that shards exceed the inline threshold: the
    # journal must reference an on-disk data dir.
    es.put_object(BKT, "whole", os.urandom(300_000))
    es.put_object(BKT, "lost-data", os.urandom(300_000))
    es.put_object(BKT, "torn-journal", os.urandom(300_000))
    d0 = tmp_path / "d0"

    # Lost directory entry: journal references a vanished data dir.
    obj = d0 / BKT / "lost-data"
    for child in os.listdir(obj):
        if child != "xl.meta":
            shutil.rmtree(obj / child)
    # Interrupted rename_data: an unreferenced part-files-only UUID dir.
    dangling = d0 / BKT / "whole" / str(uuid.uuid4())
    os.makedirs(dangling)
    (dangling / "part.1").write_bytes(b"half-written junk")
    # Torn journal (never possible at a dest under the protocol, but
    # the sweep must still recover a hand-broken drive).
    (d0 / BKT / "torn-journal" / "xl.meta").write_bytes(b"\x85garbage")
    # A UUID-named USER KEY prefix must never be reaped.
    key_prefix = str(uuid.uuid4())
    es.put_object(BKT, f"{key_prefix}/nested", os.urandom(9_000))

    rep = recovery_sweep(LocalStorage(str(d0)), min_age=0)
    # Two orphans reaped: the hand-made dangling dir, plus
    # torn-journal's own data dir (once its journal is quarantined
    # nothing references the data; heal rebuilds both from peers).
    assert rep["dangling"] == 2 and not os.path.isdir(dangling)
    assert (BKT, "lost-data") in rep["heal"]
    assert (BKT, "torn-journal") in rep["heal"]
    assert os.path.isdir(d0 / BKT / key_prefix)

    # MRF-style repair of the findings restores full health.
    for vol, path in rep["heal"]:
        es.heal_object(vol, path, deep=True)
    for key in ("whole", "lost-data", "torn-journal",
                f"{key_prefix}/nested"):
        r = es.heal_object(BKT, key)
        assert r.healed == 0 and all(s == DRIVE_STATE_OK
                                     for s in r.after), (key, r.after)
    rep2 = recovery_sweep(LocalStorage(str(d0)), min_age=0)
    assert rep2["dangling"] == 0 and rep2["heal"] == []
    es.close()


def test_staging_paths_are_pid_tagged():
    from minio_tpu.object.erasure_object import new_staging
    s = new_staging()
    assert s.startswith(f"staging/p{os.getpid()}-")


# ---------------------------------------------------------------------------
# heal vs concurrent overwrite under NSLock; fi_cache invalidation
# ---------------------------------------------------------------------------

def test_heal_never_resurrects_old_version_under_overwrite(tmp_path):
    es = make_set(tmp_path)
    old = os.urandom(60_000)
    es.put_object(BKT, "hot", old)
    # Knock out one copy so the heal has real work racing the PUT.
    shutil.rmtree(tmp_path / "d1" / BKT / "hot")
    new = os.urandom(61_000)

    results = {}

    def healer():
        try:
            results["heal"] = es.heal_object(BKT, "hot", deep=True)
        except Exception as e:  # noqa: BLE001 - asserted below
            results["heal_err"] = e

    t = threading.Thread(target=healer, daemon=True)
    t.start()
    es.put_object(BKT, "hot", new)       # races the heal under NSLock
    t.join(timeout=30)
    assert "heal_err" not in results, results.get("heal_err")

    # Whatever interleaving won, the committed overwrite is what every
    # read serves — the healed holder map never resurrects `old`.
    _, got = es.get_object(BKT, "hot")
    assert got == new
    r = es.heal_object(BKT, "hot", deep=True)
    assert r.healed == 0
    _, got = es.get_object(BKT, "hot")
    assert got == new
    es.close()


def test_fi_cache_invalidated_by_heal(tmp_path):
    es = make_set(tmp_path)
    data = os.urandom(50_000)
    es.put_object(BKT, "c", data)
    es.get_object(BKT, "c")
    es.get_object(BKT, "c")
    st0 = es.fi_cache.stats()
    assert st0["hits"] >= 1 and st0["entries"] >= 1

    # Stale drive repaired by heal -> the bump funnel must flush the
    # cached holder map (a stale map would keep routing reads at the
    # pre-heal shard layout).
    shutil.rmtree(tmp_path / "d1" / BKT / "c")
    r = es.heal_object(BKT, "c")
    assert r.healed == 1
    st1 = es.fi_cache.stats()
    assert st1["invalidations"] > st0["invalidations"]
    assert es.fi_cache.get(BKT, "c", "", need_data=False) is None
    _, got = es.get_object(BKT, "c")
    assert got == data
    es.close()
