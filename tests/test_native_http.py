"""Serve hot loop: native HTTP head framer + pooled aws-chunked decode.

Three layers of proof, mirroring the conformance story in ISSUE 7:

  * native framer unit tests — mtpu_http_head / mtpu_chunk_head golden
    vectors straight through ctypes (lowercasing, rejection codes);
  * aws-chunked streaming SigV4 golden vectors — every body decoded by
    BOTH ChunkedPayloadReader (pure Python) and PooledChunkedReader
    (native scan over one pooled lease), asserted byte-identical,
    including chunk boundaries straddling socket reads, signed
    trailing-checksum trailers, and tampered chunk/trailer signatures
    rejected with the same SigError either way;
  * end-to-end — a real server with the framer ON and a second with
    MTPU_HTTP_NATIVE=off serving identical responses; tampered chunk
    signatures answered 403; keep-alive reuse / parse-fallback /
    connection gauges moving in s3/metrics.
"""

import ctypes
import hashlib
import hmac
import http.client
import os

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3 import hotloop, sigv4
from minio_tpu.s3.server import S3Server
from minio_tpu.s3.sigv4 import (Credential, ParsedAuth,
                                ChunkedPayloadReader, PooledChunkedReader,
                                SigError)
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

LIB = hotloop.lib()
pytestmark = pytest.mark.skipif(LIB is None, reason="native lib unavailable")

SECRET = "minioadmin"
AMZ_DATE = "20260803T120000Z"
DATE = AMZ_DATE[:8]
REGION = "us-east-1"
SCOPE = f"{DATE}/{REGION}/s3/aws4_request"
SEED_SIG = "a" * 64


def _auth(payload_hash=sigv4.STREAMING_PAYLOAD) -> ParsedAuth:
    return ParsedAuth(
        credential=Credential(access_key="minioadmin", date=DATE,
                              region=REGION, service="s3"),
        signed_headers=["host"], signature=SEED_SIG, amz_date=AMZ_DATE,
        payload_hash=payload_hash)


def _chunk_body(body: bytes, chunk=64 * 1024, trailers=None,
                tamper_chunk=False, tamper_trailer=False) -> bytes:
    """aws-chunked encoding of `body` chained off SEED_SIG — the wire
    shape tests/s3client.py produces, standalone so vectors can be
    tampered mid-chain."""
    key = sigv4.signing_key(SECRET, DATE, REGION)
    out = bytearray()
    prev = SEED_SIG
    chunks = [body[i:i + chunk] for i in range(0, len(body), chunk)]
    for j, data in enumerate(chunks + [b""]):
        sts = "\n".join(["AWS4-HMAC-SHA256-PAYLOAD", AMZ_DATE, SCOPE,
                         prev, sigv4.EMPTY_SHA256,
                         hashlib.sha256(data).hexdigest()])
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        prev = sig
        if tamper_chunk and j == len(chunks) // 2:
            sig = ("f" if sig[0] != "f" else "0") + sig[1:]
        out += f"{len(data):x};chunk-signature={sig}\r\n".encode()
        out += data + b"\r\n"
    if trailers is not None:
        out = out[:-2]
        raw = bytearray()
        for name, value in trailers.items():
            out += f"{name}:{value}\r\n".encode()
            raw += f"{name}:{value}\n".encode()
        sts = "\n".join(["AWS4-HMAC-SHA256-TRAILER", AMZ_DATE, SCOPE,
                         prev, hashlib.sha256(bytes(raw)).hexdigest()])
        tsig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if tamper_trailer:
            tsig = ("f" if tsig[0] != "f" else "0") + tsig[1:]
        out += f"x-amz-trailer-signature:{tsig}\r\n\r\n".encode()
    return bytes(out)


class Dribble:
    """Raw source that returns at most `step` bytes per read — chunk
    headers, data, delimiters and trailers straddle 'socket reads'."""

    def __init__(self, data: bytes, step: int, with_readinto=True):
        self._data = data
        self._pos = 0
        self._step = step
        if not with_readinto:
            self.readinto = None  # PooledChunkedReader probes getattr

    def read(self, n: int) -> bytes:
        take = min(n, self._step, len(self._data) - self._pos)
        out = self._data[self._pos:self._pos + take]
        self._pos += take
        return out

    def readinto(self, mv) -> int:
        take = min(len(mv), self._step, len(self._data) - self._pos)
        mv[:take] = self._data[self._pos:self._pos + take]
        self._pos += take
        return take


def _drain(reader, n=8192) -> bytes:
    out = bytearray()
    while True:
        c = reader.read(n)
        if not c:
            break
        out += c
    return bytes(out)


def _decode_both(wire, auth=None, step=977, trailers_expected=None,
                 with_readinto=True):
    """Decode one wire vector through BOTH readers; assert identical
    bytes + trailers; return the decoded body."""
    auth = auth or _auth()
    py = ChunkedPayloadReader(Dribble(wire, step), auth, SECRET)
    got_py = _drain(py)
    py.finalize()
    nat = PooledChunkedReader(
        Dribble(wire, step, with_readinto=with_readinto), auth, SECRET,
        lib=LIB)
    try:
        got_nat = _drain(nat)
        nat.finalize()
        assert got_nat == got_py
        assert nat.trailers == py.trailers
        if trailers_expected is not None:
            assert nat.trailers == trailers_expected
    finally:
        nat.close()
    return got_py


# ---------------------------------------------------------------------------
# native framer unit vectors
# ---------------------------------------------------------------------------

def _head(raw: bytes, max_headers=100):
    buf = bytearray(raw)
    arr = (ctypes.c_uint8 * len(buf)).from_buffer(buf)
    out = (ctypes.c_int32 * (6 + 4 * max_headers))()
    n = LIB.mtpu_http_head(arr, len(buf), out, max_headers)
    return int(n), out, buf


def test_head_golden():
    n, out, buf = _head(b"PUT /b/k?uploads= HTTP/1.1\r\n"
                        b"Host: h:9000\r\n"
                        b"X-Amz-Content-SHA256:  abc \r\n\r\nBODY")
    assert n == len(b"PUT /b/k?uploads= HTTP/1.1\r\n"
                    b"Host: h:9000\r\n"
                    b"X-Amz-Content-SHA256:  abc \r\n\r\n")
    assert bytes(buf[out[0]:out[0] + out[1]]) == b"PUT"
    assert bytes(buf[out[2]:out[2] + out[3]]) == b"/b/k?uploads="
    assert out[4] == 11 and out[5] == 2
    names = [bytes(buf[out[6 + 4 * i]:out[6 + 4 * i] + out[7 + 4 * i]])
             for i in range(out[5])]
    vals = [bytes(buf[out[8 + 4 * i]:out[8 + 4 * i] + out[9 + 4 * i]])
            for i in range(out[5])]
    assert names == [b"host", b"x-amz-content-sha256"]   # lowercased
    assert vals == [b"h:9000", b"abc"]                   # OWS trimmed


def test_head_incomplete_malformed_toomany():
    assert _head(b"GET / HTTP/1.1\r\nHost: h\r\n")[0] == 0   # no CRLFCRLF
    assert _head(b"GET / HTTP/2.0\r\n\r\n")[0] == -1
    assert _head(b"GET /\r\n\r\n")[0] == -1                  # no version
    assert _head(b"GET / HTTP/1.1\r\nA: 1\r\n folded\r\n\r\n")[0] == -1
    assert _head(b"GET / HTTP/1.1\r\nBad Name: 1\r\n\r\n")[0] == -1
    many = b"GET / HTTP/1.1\r\n" + b"".join(
        b"h%d: v\r\n" % i for i in range(5)) + b"\r\n"
    assert _head(many, max_headers=3)[0] == -2


def test_head_bare_lf_rejected():
    # A bare LF inside a field value or the request target is a
    # request-smuggling primitive (line-based parsers see two headers
    # where the scan saw one): the framer must refuse, handing the
    # bytes to the stock parser's line discipline.
    assert _head(b"GET / HTTP/1.1\r\nx-a: a\nx-evil: b\r\n\r\n")[0] == -1
    assert _head(b"GET /x\ny HTTP/1.1\r\nHost: h\r\n\r\n")[0] == -1
    assert _head(b"GET / HTTP/1.1\nHost: h\r\n\r\n")[0] == -1


def test_head_duplicate_headers_comma_join():
    # Native path folds repeats with a comma (SigV4 canonicalization);
    # server._headers_lower does the same for the stock parse so the
    # two paths verify identically.
    n, out, buf = _head(b"GET / HTTP/1.1\r\n"
                        b"Cache-Control: a\r\nCache-Control: b\r\n\r\n")
    assert n > 0 and out[5] == 2
    import socket as _socket
    from minio_tpu.s3 import hotloop
    a, b = _socket.socketpair()
    try:
        a.sendall(b"GET / HTTP/1.1\r\n"
                  b"Cache-Control: a\r\nCache-Control: b\r\n\r\n")
        r = hotloop.ConnReader(b)
        try:
            d, method, target, _version, http11 = r.parse_head(LIB)
            assert method == "GET" and http11
            assert d["cache-control"] == "a,b"
        finally:
            r.close()
    finally:
        a.close()
        b.close()


def test_send_gathered_annotates_progress_on_dead_peer():
    # The GET stream path decides clean-error vs cut-connection off
    # e.mtpu_sent: a send that dies before any byte hit the wire must
    # report 0 so the handler can still emit a proper S3 error.
    import socket as _socket
    from minio_tpu.s3 import hotloop
    a, b = _socket.socketpair()
    b.close()
    try:
        with pytest.raises(OSError) as ei:
            hotloop.send_gathered(a, [b"HTTP/1.1 200 OK\r\n\r\n", b"body"])
        assert getattr(ei.value, "mtpu_sent", None) == 0
    finally:
        a.close()


def test_chunk_head_bounds():
    out = (ctypes.c_int64 * 4)()
    big = bytearray(b"x" * 5000)                 # no CRLF within 4 KiB
    arr = (ctypes.c_uint8 * len(big)).from_buffer(big)
    assert LIB.mtpu_chunk_head(arr, len(big), 0, out) == -1
    over = bytearray(b"1000001\r\n")             # 16 MiB + 1
    arr = (ctypes.c_uint8 * len(over)).from_buffer(over)
    assert LIB.mtpu_chunk_head(arr, len(over), 0, out) == -1
    ok = bytearray(b"0\r\n")
    arr = (ctypes.c_uint8 * len(ok)).from_buffer(ok)
    assert LIB.mtpu_chunk_head(arr, len(ok), 0, out) == 1
    assert out[0] == 3 and out[1] == 0 and out[2] == 0


# ---------------------------------------------------------------------------
# aws-chunked golden vectors: native vs Python byte-identity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("size", [0, 1, 100, 64 * 1024, 64 * 1024 + 1,
                                  300_003])
@pytest.mark.parametrize("step", [1, 7, 977, 1 << 20])
def test_chunked_identity_across_read_boundaries(size, step):
    if size > 100_000 and step < 7:
        pytest.skip("1-byte dribble over large bodies is O(n^2) wall time")
    body = os.urandom(size)
    assert _decode_both(_chunk_body(body), step=step) == body


def test_chunked_small_chunks_straddle_headers():
    # 13-byte chunks: every frame header, delimiter and signature ext
    # straddles the 7-byte reads.
    body = os.urandom(997)
    wire = _chunk_body(body, chunk=13)
    assert _decode_both(wire, step=7) == body


def test_chunked_no_readinto_source():
    body = os.urandom(50_000)
    assert _decode_both(_chunk_body(body), with_readinto=False) == body


def test_chunked_signed_trailers_roundtrip():
    body = os.urandom(123_456)
    trailers = {"x-amz-checksum-crc32c": "wdBjLg=="}
    wire = _chunk_body(body, trailers=trailers)
    auth = _auth(sigv4.STREAMING_PAYLOAD_TRAILER)
    got = _decode_both(wire, auth=auth, step=311,
                       trailers_expected=trailers)
    assert got == body


@pytest.mark.parametrize("native", [True, False])
def test_tampered_chunk_signature_rejected(native):
    wire = _chunk_body(os.urandom(200_000), tamper_chunk=True)
    reader = (PooledChunkedReader(Dribble(wire, 977), _auth(), SECRET,
                                  lib=LIB) if native else
              ChunkedPayloadReader(Dribble(wire, 977), _auth(), SECRET))
    try:
        with pytest.raises(SigError) as ei:
            _drain(reader)
            reader.finalize()
        assert ei.value.code == "SignatureDoesNotMatch"
    finally:
        if native:
            reader.close()


@pytest.mark.parametrize("native", [True, False])
def test_tampered_trailer_signature_rejected(native):
    auth = _auth(sigv4.STREAMING_PAYLOAD_TRAILER)
    wire = _chunk_body(os.urandom(10_000),
                       trailers={"x-amz-checksum-crc32": "AAAAAA=="},
                       tamper_trailer=True)
    reader = (PooledChunkedReader(Dribble(wire, 311), auth, SECRET,
                                  lib=LIB) if native else
              ChunkedPayloadReader(Dribble(wire, 311), auth, SECRET))
    try:
        _drain(reader)
        with pytest.raises(SigError) as ei:
            reader.finalize()
        assert ei.value.code == "SignatureDoesNotMatch"
    finally:
        if native:
            reader.close()


@pytest.mark.parametrize("native", [True, False])
def test_truncated_body_rejected(native):
    wire = _chunk_body(os.urandom(100_000))[:-40]
    reader = (PooledChunkedReader(Dribble(wire, 977), _auth(), SECRET,
                                  lib=LIB) if native else
              ChunkedPayloadReader(Dribble(wire, 977), _auth(), SECRET))
    try:
        with pytest.raises(SigError) as ei:
            _drain(reader)
            reader.finalize()
        assert ei.value.code == "IncompleteBody"
    finally:
        if native:
            reader.close()


def test_pooled_reader_returns_lease():
    from minio_tpu.io.bufpool import global_pool
    pool = global_pool()
    before = pool.stats()["outstanding"]
    body = os.urandom(100_000)
    r = PooledChunkedReader(Dribble(_chunk_body(body), 977), _auth(),
                            SECRET, lib=LIB)
    assert _drain(r) == body
    r.finalize()
    assert pool.stats()["outstanding"] == before + 1
    r.close()
    r.close()                                   # idempotent
    assert pool.stats()["outstanding"] == before


def test_pooled_reader_grows_for_oversized_chunk():
    # One 1 MiB chunk > the 256 KiB initial lease: the reader swaps to
    # a larger lease mid-frame and stays byte-identical.
    body = os.urandom((1 << 20) + 17)
    wire = _chunk_body(body, chunk=1 << 20)
    assert _decode_both(wire, step=1 << 16) == body


# ---------------------------------------------------------------------------
# end to end: framer on vs off, 403s, connection-plane metrics
# ---------------------------------------------------------------------------

class _TamperingClient(S3Client):
    """Signs correctly, then corrupts the first chunk signature on the
    wire — the server must answer 403 SignatureDoesNotMatch."""

    def _chunk_body(self, body, seed_sig, amz_date, scope, trailers=None,
                    corrupt_trailer_sig=False):
        out = super()._chunk_body(body, seed_sig, amz_date, scope,
                                  trailers, corrupt_trailer_sig)
        i = out.find(b"chunk-signature=") + len(b"chunk-signature=")
        flip = b"f" if out[i:i + 1] != b"f" else b"0"
        return out[:i] + flip + out[i + 1:]


@pytest.fixture(scope="module", params=["native", "python"])
def srv(request, tmp_path_factory):
    """One real server per parser: the native hot loop and the
    MTPU_HTTP_NATIVE=off stock path must be observably identical."""
    old = os.environ.get("MTPU_HTTP_NATIVE")
    if request.param == "python":
        os.environ["MTPU_HTTP_NATIVE"] = "off"
    else:
        os.environ.pop("MTPU_HTTP_NATIVE", None)
    try:
        tmp = tmp_path_factory.mktemp(f"nhttp-{request.param}")
        disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
        server = S3Server(ErasureSet(disks), address="127.0.0.1:0")
        server.start()
        server._parser = request.param
        yield server
        server.stop()
    finally:
        if old is None:
            os.environ.pop("MTPU_HTTP_NATIVE", None)
        else:
            os.environ["MTPU_HTTP_NATIVE"] = old


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv.address)
    assert c.request("PUT", "/nhttp")[0] == 200
    return c


def test_e2e_roundtrip_both_parsers(srv, cli):
    body = os.urandom(300_000)
    st, h, _ = cli.request("PUT", "/nhttp/obj", body=body,
                           headers={"x-amz-meta-k": "v"})
    assert st == 200
    st, h, got = cli.request("GET", "/nhttp/obj")
    assert st == 200 and got == body and h.get("x-amz-meta-k") == "v"
    st, h, got = cli.request("GET", "/nhttp/obj",
                             headers={"Range": "bytes=1000-2999"})
    assert st == 206 and got == body[1000:3000]
    assert h["Content-Range"] == f"bytes 1000-2999/{len(body)}"


def test_e2e_streaming_put_both_parsers(srv, cli):
    body = os.urandom(200_000)
    st, _, _ = cli.request("PUT", "/nhttp/chunked", body=body, chunked=True)
    assert st == 200
    st, _, got = cli.request("GET", "/nhttp/chunked")
    assert st == 200 and got == body
    st, _, _ = cli.request("PUT", "/nhttp/trailed", body=body, chunked=True,
                           trailers={"x-amz-checksum-crc32": "AAAAAA=="})
    # Declared trailing checksum is validated server-side; the point
    # here is both parsers agree on the verdict for the same wire.
    st2, _, got = cli.request("GET", "/nhttp/trailed")
    assert (st, st2) in ((200, 200), (400, 404))


def test_e2e_tampered_chunk_sig_403(srv):
    bad = _TamperingClient(srv.address)
    st, _, body = bad.request("PUT", "/nhttp/tampered",
                              body=os.urandom(150_000), chunked=True)
    assert st == 403, body
    assert b"SignatureDoesNotMatch" in body
    st, _, _ = S3Client(srv.address).request("GET", "/nhttp/tampered")
    assert st == 404


def test_e2e_tampered_trailer_sig_403(srv, cli):
    st, _, body = cli.request("PUT", "/nhttp/ttrail",
                              body=os.urandom(50_000), chunked=True,
                              trailers={"x-amz-meta-ignored": "x"},
                              corrupt_trailer_sig=True)
    assert st == 403, body


def test_e2e_keepalive_and_fallback_metrics(srv):
    if srv._parser != "native":
        pytest.skip("connection-plane fast-path counters are native-mode")
    m = srv.metrics
    base = m.http_conn_stats()
    conn = http.client.HTTPConnection(srv.address, timeout=10)
    try:
        for _ in range(3):
            conn.request("GET", "/minio/health/live")
            r = conn.getresponse()
            r.read()
            assert r.status == 200
        mid = m.http_conn_stats()
        # 3 requests on ONE connection: >= 2 keep-alive reuses, and the
        # connection still open and counted.
        assert mid["keepalive_reuses"] >= base["keepalive_reuses"] + 2
        assert mid["connections_active"] >= 1
        assert mid["parse_fallbacks"] == base["parse_fallbacks"]
        # Obs-folded header: the native framer declines, the Python
        # parser takes the SAME buffered bytes (stock semantics).
        conn2 = http.client.HTTPConnection(srv.address, timeout=10)
        conn2.sock = None
        import socket as _s
        conn2.sock = _s.create_connection(
            (srv.address.split(":")[0], int(srv.address.split(":")[1])))
        conn2.sock.sendall(b"GET /minio/health/live HTTP/1.1\r\n"
                           b"Host: x\r\nA: 1\r\n folded\r\n"
                           b"Connection: close\r\n\r\n")
        resp = http.client.HTTPResponse(conn2.sock)
        resp.begin()
        resp.read()
        assert resp.status == 200
        conn2.sock.close()
        after = m.http_conn_stats()
        assert after["parse_fallbacks"] >= base["parse_fallbacks"] + 1
    finally:
        conn.close()
    # Prometheus names exported (metrics_lint guards hygiene; this
    # guards presence).
    text = m.render()
    for name in ("minio_tpu_http_connections_active",
                 "minio_tpu_http_keepalive_reuses_total",
                 "minio_tpu_http_parse_fallbacks_total"):
        assert name in text


def test_e2e_pipelined_requests(srv):
    """Two requests in one TCP segment: the second head is already
    buffered when the first response goes out — the hot loop must not
    lose it."""
    import socket as _s
    host, port = srv.address.split(":")
    sock = _s.create_connection((host, int(port)))
    try:
        sock.sendall(b"GET /minio/health/live HTTP/1.1\r\nHost: x\r\n\r\n"
                     b"GET /minio/health/live HTTP/1.1\r\nHost: x\r\n"
                     b"Connection: close\r\n\r\n")
        # Raw byte stream (one HTTPResponse per read would buffer past
        # its own response): both statuses must come back, in order,
        # then the server honors Connection: close.
        sock.settimeout(10)
        raw = bytearray()
        while True:
            try:
                got = sock.recv(65536)
            except OSError:
                break
            if not got:
                break
            raw += got
        assert raw.count(b"HTTP/1.1 200") == 2, raw[:200]
    finally:
        sock.close()


def test_e2e_inline_small_get(srv, cli):
    # Inline object (< inline threshold): served through the one-window
    # short-circuit + single gathered write.
    body = os.urandom(1024)
    assert cli.request("PUT", "/nhttp/tiny", body=body)[0] == 200
    st, h, got = cli.request("GET", "/nhttp/tiny")
    assert st == 200 and got == body
    st, _, got = cli.request("GET", "/nhttp/tiny",
                             headers={"Range": "bytes=100-199"})
    assert st == 206 and got == body[100:200]


def test_e2e_get_into_fast_client(srv, cli):
    """The raw-socket bench client path (S3Client.get_into): signed
    GETs over a persistent connection, bodies received straight into a
    reusable buffer — byte-identical to the stock client, connection
    reused across requests AND across an intervening error status."""
    body = os.urandom(257_000)
    assert cli.request("PUT", "/nhttp/fastget", body=body)[0] == 200
    fast = S3Client(srv.address, keepalive=True)
    buf = bytearray(len(body))
    try:
        for _ in range(3):
            st, n = fast.get_into("/nhttp/fastget", buf)
            assert st == 200 and n == len(body)
            assert bytes(buf) == body
        # An error response (XML body larger than 0, smaller than buf)
        # must drain cleanly and leave the connection usable.
        st, _n = fast.get_into("/nhttp/no-such-object-xyz", buf)
        assert st == 404
        st, n = fast.get_into("/nhttp/fastget", buf)
        assert st == 200 and n == len(body) and bytes(buf) == body
    finally:
        fast.close()
