"""Site replication: active-active mirroring across clusters
(reference: cmd/site-replication.go)."""

import json
import os
import time

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.replication.site import SiteError, SiteReplicator
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


def test_validate_config():
    with pytest.raises(SiteError):
        SiteReplicator.validate({"peers": []})
    with pytest.raises(SiteError):
        SiteReplicator.validate({"peers": [{"name": "b",
                                            "endpoint": "h:1"}]})
    with pytest.raises(SiteError):
        SiteReplicator.validate({"peers": [
            {"name": "x", "endpoint": "h:1", "accessKey": "a",
             "secretKey": "s"},
            {"name": "x", "endpoint": "h:2", "accessKey": "a",
             "secretKey": "s"}]})


@pytest.fixture
def two_sites(tmp_path):
    servers = []
    for name in ("east", "west"):
        disks = [LocalStorage(str(tmp_path / name / f"d{i}"))
                 for i in range(4)]
        srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
        srv.start()
        servers.append(srv)
    yield servers
    for s in servers:
        if s.site is not None:
            s.site.stop()
        s.stop()


def _link(a, b):
    """Register each server as the other's peer (active-active)."""
    for srv, peer, pname in ((a, b, "west"), (b, a, "east")):
        cli = S3Client(srv.address)
        st, _, body = cli.request(
            "POST", "/minio/admin/v3/site-replication-add",
            body=json.dumps({"name": pname + "-local", "peers": [
                {"name": pname, "endpoint": peer.address,
                 "accessKey": "minioadmin",
                 "secretKey": "minioadmin"}]}).encode())
        assert st == 200, body


def _wait(cond, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return False


def test_active_active_buckets_objects_metadata(two_sites):
    east, west = two_sites
    ec, wc = S3Client(east.address), S3Client(west.address)
    _link(east, west)

    # Bucket created on east appears on west.
    assert ec.request("PUT", "/mirror")[0] == 200
    assert _wait(lambda: wc.request("HEAD", "/mirror")[0] == 200)
    # Object PUT on east reads on west, metadata and tags intact.
    body = os.urandom(50_000)
    assert ec.request("PUT", "/mirror/doc", body=body, headers={
        "x-amz-meta-origin": "east",
        "x-amz-tagging": "zone=a"})[0] == 200
    assert _wait(lambda: wc.request("GET", "/mirror/doc")[0] == 200)
    st, h, got = wc.request("GET", "/mirror/doc")
    assert got == body
    assert h.get("x-amz-meta-origin") == "east"
    # ...and the reverse direction (active-active, no ping-pong: the
    # replica marker stops the copy from bouncing back).
    body2 = os.urandom(10_000)
    assert wc.request("PUT", "/mirror/back", body=body2)[0] == 200
    assert _wait(lambda: ec.request("GET", "/mirror/back")[0] == 200)
    st, _, got = ec.request("GET", "/mirror/back")
    assert got == body2
    east.site.drain()
    west.site.drain()
    assert east.site.info()["failed"] == 0
    assert west.site.info()["failed"] == 0

    # Bucket POLICY mirrors (whole metadata document).
    pol = {"Statement": [{"Effect": "Allow", "Principal": "*",
                          "Action": ["s3:GetObject"],
                          "Resource": ["arn:aws:s3:::mirror/*"]}]}
    assert ec.request("PUT", "/mirror", query={"policy": ""},
                      body=json.dumps(pol).encode())[0] == 200
    assert _wait(lambda: wc.request(
        "GET", "/mirror", query={"policy": ""})[0] == 200)
    # Versioning toggle mirrors too.
    assert ec.request(
        "PUT", "/mirror", query={"versioning": ""},
        body=b"<VersioningConfiguration><Status>Enabled</Status>"
             b"</VersioningConfiguration>")[0] == 200
    assert _wait(lambda: b"Enabled" in wc.request(
        "GET", "/mirror", query={"versioning": ""})[2])

    # Deletes mirror (marker semantics on the far side).
    assert ec.request("DELETE", "/mirror/doc")[0] == 204
    assert _wait(lambda: wc.request("GET", "/mirror/doc")[0] == 404)
    # No delete ping-pong: after the queues quiesce, each side holds
    # exactly ONE delete marker for the key (a missing replica marker
    # on deletes once bounced markers between sites forever).
    east.site.drain()
    west.site.drain()
    time.sleep(0.5)
    east.site.drain()
    west.site.drain()
    for cli in (ec, wc):
        st, _, listing = cli.request("GET", "/mirror",
                                     query={"versions": "",
                                            "prefix": "doc"})
        assert st == 200
        assert listing.count(b"<DeleteMarker>") == 1, listing


def test_bootstrap_syncs_existing_buckets(two_sites):
    east, west = two_sites
    ec, wc = S3Client(east.address), S3Client(west.address)
    # Buckets that existed BEFORE registration flow at bootstrap.
    assert ec.request("PUT", "/oldbkt")[0] == 200
    assert ec.request("PUT", "/oldbkt", query={"tagging": ""},
                      body=b"<Tagging><TagSet><Tag><Key>team</Key>"
                           b"<Value>sre</Value></Tag></TagSet></Tagging>"
                      )[0] == 200
    _link(east, west)
    assert _wait(lambda: wc.request("HEAD", "/oldbkt")[0] == 200)
    assert _wait(lambda: b"sre" in wc.request(
        "GET", "/oldbkt", query={"tagging": ""})[2])
    # Info reports peers without secrets.
    st, _, b = ec.request("GET", "/minio/admin/v3/site-replication-info")
    assert st == 200 and b"west" in b and b"secretKey" not in b
    # Remove tears it down.
    assert ec.request("POST",
                      "/minio/admin/v3/site-replication-remove")[0] == 200
    st, _, b = ec.request("GET", "/minio/admin/v3/site-replication-info")
    assert st == 200 and b in (b"", b"null")


@pytest.fixture
def two_iam_sites(tmp_path):
    """Two clusters WITH IAM stores (the default fixture has none)."""
    from minio_tpu.iam import IAMSys
    from minio_tpu.s3.server import Credentials
    servers = []
    for name in ("east", "west"):
        disks = [LocalStorage(str(tmp_path / name / f"d{i}"))
                 for i in range(4)]
        es = ErasureSet(disks)
        creds = Credentials("minioadmin", "minioadmin")
        creds.iam = IAMSys([es], "minioadmin", "minioadmin")
        srv = S3Server(es, address="127.0.0.1:0", credentials=creds)
        srv.start()
        servers.append(srv)
    yield servers
    for s in servers:
        if s.site is not None:
            s.site.stop()
        s.stop()


def test_iam_mirrors_across_sites(two_iam_sites):
    """A user + policy created on east signs requests on west
    (reference: cmd/site-replication.go mirrors IAM), and the applied
    import never ping-pongs back."""
    east, west = two_iam_sites
    ec = S3Client(east.address)
    _link(east, west)

    # Create a policy, a user, and the attachment on EAST only.
    st, _, b = ec.request(
        "PUT", "/minio/admin/v3/add-canned-policy",
        query={"name": "mirror-rw"},
        body=json.dumps({"Version": "2012-10-17", "Statement": [{
            "Effect": "Allow",
            "Action": ["s3:GetObject", "s3:PutObject", "s3:CreateBucket",
                        "s3:ListBucket"],
            "Resource": ["arn:aws:s3:::shared*"]}]}).encode())
    assert st == 200, b
    assert ec.request("PUT", "/minio/admin/v3/add-user",
                      query={"accessKey": "alice"},
                      body=json.dumps({"secretKey":
                                       "alicesecret99"}).encode())[0] == 200
    assert ec.request("PUT", "/minio/admin/v3/set-user-or-group-policy",
                      query={"userOrGroup": "alice",
                             "policyName": "mirror-rw"})[0] == 200
    assert east.site.drain(30)

    # Alice's credential works on WEST, inside her mirrored policy...
    west.credentials.iam.invalidate()
    walice = S3Client(west.address, access_key="alice",
                      secret_key="alicesecret99")
    assert walice.request("PUT", "/sharedbkt")[0] == 200
    assert walice.request("PUT", "/sharedbkt/doc", body=b"hi")[0] == 200
    assert walice.request("GET", "/sharedbkt/doc")[2] == b"hi"
    # ...and not outside it.
    assert walice.request("DELETE", "/sharedbkt/doc")[0] == 403

    # Loop prevention: west's import must not re-enqueue an IAM push
    # back toward east. Let the queues settle and compare counters.
    assert west.site.drain(10)
    failed_before = east.site.failed + west.site.failed
    time.sleep(0.5)
    assert east.site.failed + west.site.failed == failed_before
    # A user REMOVED on east disappears on west too.
    assert ec.request("DELETE", "/minio/admin/v3/remove-user",
                      query={"accessKey": "alice"})[0] == 200
    assert east.site.drain(30)
    west.credentials.iam.invalidate()
    assert _wait(lambda: walice.request("GET", "/sharedbkt/doc")[0] == 403)
