"""KMS key management admin API (reference: cmd/kms-handlers.go):
named keys created/listed/probed, persisted sealed under the master
key, usable by SSE after a restart."""

import base64
import json
import os

import pytest

from minio_tpu.crypto.kms import aesgcm_impl

if aesgcm_impl() is None:
    pytest.skip("SSE/KMS needs an AES-GCM backend (the optional "
                "'cryptography' wheel or the native kernel library)",
                allow_module_level=True)

from minio_tpu.crypto.kms import KMS, KeyStore, KMSError
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

MASTER = "root-key:" + base64.b64encode(b"\x11" * 32).decode()


@pytest.fixture
def env(tmp_path, monkeypatch):
    monkeypatch.setenv("MTPU_KMS_SECRET_KEY", MASTER)
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    srv.start()
    yield srv, S3Client(srv.address), disks
    srv.stop()


def test_kms_key_lifecycle(env):
    srv, cli, disks = env
    st, _, body = cli.request("GET", "/minio/admin/v3/kms-key-list")
    assert st == 200
    assert json.loads(body) == [{"name": "root-key", "default": True}]
    st, _, b = cli.request("POST", "/minio/admin/v3/kms-key-create",
                           query={"key-id": "tenant-a"})
    assert st == 200, b
    # Duplicate create is refused; bad names too.
    assert cli.request("POST", "/minio/admin/v3/kms-key-create",
                       query={"key-id": "tenant-a"})[0] == 400
    assert cli.request("POST", "/minio/admin/v3/kms-key-create",
                       query={"key-id": "a/b"})[0] == 400
    st, _, body = cli.request("GET", "/minio/admin/v3/kms-key-list")
    names = [k["name"] for k in json.loads(body)]
    assert names == ["root-key", "tenant-a"]
    st, _, body = cli.request("GET", "/minio/admin/v3/kms-key-status",
                              query={"key-id": "tenant-a"})
    doc = json.loads(body)
    assert doc["encrypt_ok"] and doc["decrypt_ok"]
    assert cli.request("GET", "/minio/admin/v3/kms-key-status",
                       query={"key-id": "ghost"})[0] == 400


def test_keys_survive_restart_and_unseal(env, tmp_path):
    srv, cli, disks = env
    assert cli.request("POST", "/minio/admin/v3/kms-key-create",
                       query={"key-id": "persist-me"})[0] == 200
    secret = srv.kms._keys["persist-me"]
    # "Restart": a fresh KMS from env + a fresh KeyStore over the
    # same drives recovers the same key material.
    kms2 = KMS.from_env()
    ks2 = KeyStore(kms2, disks)
    assert kms2._keys["persist-me"] == secret
    # Sealed blobs from before the restart unseal after it.
    data_key, sealed = srv.kms.generate_key({"bucket": "b"})
    assert kms2.unseal(sealed, {"bucket": "b"}) == data_key


def test_keystore_requires_master_key(tmp_path, monkeypatch):
    monkeypatch.delenv("MTPU_KMS_SECRET_KEY", raising=False)
    with pytest.raises(KMSError):
        KeyStore(KMS.from_env(), [])
