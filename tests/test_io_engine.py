"""I/O engine subsystem: bufpool invariants, per-drive queues, fused
native framing byte-identity, and the pre-forked SO_REUSEPORT worker
front-end (conformance subset + divided admission + aggregation).

The pool invariants the ISSUE pins down:
  * no buffer aliasing across concurrent requests (two live leases
    never share memory; recycled buffers only after the last release);
  * a dropped lease is returned and counted, never lost;
  * hot PUT paths allocate zero fresh window buffers at steady state
    (pool hit rate ~100 % after warmup).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from minio_tpu.io.bufpool import BufferPool
from minio_tpu.io.engine import DriveQueue, EngineSaturated, IOEngine
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


# ---------------------------------------------------------------------------
# bufpool
# ---------------------------------------------------------------------------

def test_lease_recycles_after_release():
    pool = BufferPool(max_per_class=4)
    a = pool.lease(100_000)
    buf_id = id(a.raw)
    a.release()
    b = pool.lease(100_000)
    assert id(b.raw) == buf_id, "released buffer should be recycled"
    st = pool.stats()
    assert st["hits"] == 1 and st["misses"] == 1
    b.release()
    assert pool.stats()["outstanding"] == 0


def test_no_aliasing_between_live_leases():
    """Two live leases never share memory, under concurrency: every
    worker writes its own pattern and re-reads it intact."""
    pool = BufferPool(max_per_class=4)
    errors: list = []

    def worker(tag: int):
        rng = np.random.default_rng(tag)
        for i in range(40):
            lease = pool.lease(65_536)
            view = lease.view(65_536)
            pattern = bytes([tag]) * 65_536
            view[:] = pattern
            time.sleep(rng.uniform(0, 0.002))
            if bytes(view) != pattern:
                errors.append(f"worker {tag} iter {i}: torn buffer")
            lease.release()

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:3]
    assert pool.stats()["outstanding"] == 0
    assert pool.stats()["leaks"] == 0


def test_retained_lease_survives_first_release():
    """The recycled-under-a-live-reader hazard: a retained holder keeps
    the buffer out of the pool until ITS release."""
    pool = BufferPool(max_per_class=4)
    a = pool.lease(70_000)
    marker = b"held-by-writer"
    a.view(len(marker))[:] = marker
    a.retain()
    a.release()                       # original holder done
    b = pool.lease(70_000)            # must NOT alias a's buffer
    assert b.raw is not a.raw
    assert bytes(a.view(len(marker))) == marker
    a.release()                       # retained holder done -> recycled
    c = pool.lease(70_000)
    assert c.raw is a.raw
    b.release()
    c.release()


def test_dropped_lease_returned_and_counted():
    pool = BufferPool(max_per_class=4)
    lease = pool.lease(80_000)
    raw = lease.raw
    del lease                         # dropped without release()
    import gc
    gc.collect()
    st = pool.stats()
    assert st["leaks"] == 1, st
    assert st["outstanding"] == 0
    back = pool.lease(80_000)
    assert back.raw is raw, "leaked buffer should be back in the pool"
    back.release()


def test_double_release_counted_not_corrupting():
    pool = BufferPool(max_per_class=4)
    a = pool.lease(90_000)
    a.release()
    a.release()
    assert pool.stats()["double_releases"] == 1
    b = pool.lease(90_000)
    c = pool.lease(90_000)
    assert b.raw is not c.raw, "double release must not alias leases"
    b.release()
    c.release()


def test_oversized_lease_served_unpooled():
    pool = BufferPool(max_per_class=2)
    big = pool.lease((1 << 26) + 1)
    assert big.size == (1 << 26) + 1
    big.view(64)[:] = b"x" * 64
    big.release()
    assert pool.stats()["oversized"] == 1
    assert pool.stats()["outstanding"] == 0


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def test_drive_queue_runs_and_bounds_depth():
    q = DriveQueue("t0", workers=1, depth=2)
    gate = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        gate.wait(10)
        return "done"

    f1 = q.submit(blocker)
    assert started.wait(5)
    # Worker busy; fill the queue past depth.
    f2 = q.submit(lambda: 2)
    f3 = q.submit(lambda: 3)
    from minio_tpu.utils import deadline as deadline_mod
    with deadline_mod.bind(deadline_mod.Deadline(0.2)):
        with pytest.raises(EngineSaturated):
            q.submit(lambda: 4)
    assert q.stats()["rejected_total"] == 1
    gate.set()
    assert f1.result(10) == "done"
    assert f2.result(10) == 2 and f3.result(10) == 3
    q.close()


def test_engine_per_drive_isolation():
    """A backlog on one drive must not delay another drive's ops."""
    eng = IOEngine(["a", "b"], workers=1, depth=16)
    gate = threading.Event()
    eng.submit(0, lambda: gate.wait(10))       # drive 0 wedged
    t0 = time.monotonic()
    assert eng.submit(1, lambda: "fast").result(5) == "fast"
    assert time.monotonic() - t0 < 2.0
    gate.set()
    eng.close()


def test_fanout_via_engine_preserves_quorum_semantics(tmp_path):
    """End-to-end through ErasureSet: per-disk faults stay per-disk."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("engb")
    es.put_object("engb", "k", b"v" * 50_000)
    _, got = es.get_object("engb", "k")
    assert got == b"v" * 50_000
    results, errors = es._fanout(
        [lambda d=d: d.stat_vol("engb") for d in es.disks])
    assert all(e is None for e in errors)
    # Subset fan-outs (cleanup shapes) run too, via the shared pool.
    results, errors = es._fanout(
        [lambda d=d: d.stat_vol("engb") for d in es.disks[:2]])
    assert all(e is None for e in errors)
    es.close()


# ---------------------------------------------------------------------------
# fused framing + steady-state allocation
# ---------------------------------------------------------------------------

def test_frame_windows_byte_identical_to_reference_path(tmp_path):
    """The pooled fused native framing must produce exactly the bytes
    of the numpy encode+frame path, tails included."""
    from minio_tpu import native
    from minio_tpu.storage import bitrot
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(6)]
    es = ErasureSet(disks, parity=2)
    k, m = 4, 2
    rng = np.random.default_rng(7)
    for size in ((1 << 20), (1 << 20) + 12345, 3 * (1 << 20),
                 (1 << 20) - 1, 777):
        data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
        chunks, lease = es._frame_windows(data, k, m)
        got = [b"".join(bytes(c) for c in row) for row in chunks]
        if lease is not None:
            lease.release()
        shards = es._encode_object(data, k, m)
        want = bitrot.frame_shards_batch(
            shards, es._erasure(k, m).shard_size())
        assert got == [bytes(w) for w in want], f"mismatch at size {size}"
    es.close()

    # k = 5 does not divide the 1 MiB block: the pooled native path is
    # ineligible and the fallback (split full blocks + separate tail
    # framing) must still be byte-identical to whole-object framing.
    disks7 = [LocalStorage(str(tmp_path / f"e{i}")) for i in range(7)]
    es7 = ErasureSet(disks7, parity=2)
    data = rng.integers(0, 256, size=(1 << 20) + 999,
                        dtype=np.uint8).tobytes()
    chunks, lease = es7._frame_windows(data, 5, 2)
    got = [b"".join(bytes(c) for c in row) for row in chunks]
    if lease is not None:
        lease.release()
    want = bitrot.frame_shards_batch(
        es7._encode_object(data, 5, 2), es7._erasure(5, 2).shard_size())
    assert got == [bytes(w) for w in want]
    es7.close()


def test_put_path_pool_hit_rate_steady_state(tmp_path):
    """Acceptance: hot PUT paths allocate zero fresh window buffers at
    steady state — pool hit rate ~100 % after warmup."""
    from minio_tpu import native
    if native.load() is None:
        pytest.skip("native library unavailable; pooled framing off")
    from minio_tpu.io.bufpool import global_pool
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(6)]
    es = ErasureSet(disks, parity=2)
    es.make_bucket("steady")
    body = os.urandom(1 << 20)
    for i in range(4):                      # warmup
        es.put_object("steady", f"warm-{i}", body)
    pool = global_pool()
    before = pool.stats()
    for i in range(12):                     # steady state
        es.put_object("steady", f"hot-{i}", body)
    after = pool.stats()
    assert after["misses"] == before["misses"], \
        "steady-state PUTs allocated fresh window buffers"
    assert after["hits"] >= before["hits"] + 12
    assert after["leaks"] == before["leaks"]
    es.close()


# ---------------------------------------------------------------------------
# pre-forked worker front-end
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def worker_server(tmp_path_factory):
    """A 2-worker pre-forked server on shared drives (subprocess: the
    pytest process has JAX loaded, and fork-after-JAX is unsafe)."""
    root = tmp_path_factory.mktemp("workers")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2",
               MTPU_API_REQUESTS_MAX="4",
               MTPU_API_REQUESTS_DEADLINE="100ms")
    proc = subprocess.Popen(
        [sys.executable, "-m", "minio_tpu.server",
         "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
         f"{root}/d{{1...4}}"],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    address = f"127.0.0.1:{port}"
    deadline = time.time() + 90
    ready = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        try:
            st, _, _ = S3Client(address).request(
                "GET", "/minio/health/live", sign=False)
            if st == 200:
                ready = True
                break
        except OSError:
            time.sleep(0.4)
    if not ready:
        out = proc.stdout.read().decode(errors="replace") \
            if proc.stdout else ""
        proc.kill()
        pytest.skip(f"worker fleet failed to boot: {out[-800:]}")
    yield address
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=25)
    except subprocess.TimeoutExpired:
        proc.kill()


def _cli(address):
    return S3Client(address)


def test_workers_conformance_subset(worker_server):
    """The S3 surface behaves across worker processes: bucket + object
    CRUD, listings (fresh after cross-worker writes), ranged GET,
    multipart, delete — each request on a FRESH connection so the
    kernel spreads them over both workers."""
    addr = worker_server
    assert _cli(addr).request("PUT", "/confb")[0] == 200
    body = os.urandom(300_000)
    assert _cli(addr).request("PUT", "/confb/obj1", body=body)[0] == 200
    st, _, got = _cli(addr).request("GET", "/confb/obj1")
    assert st == 200 and got == body
    st, _, part = _cli(addr).request(
        "GET", "/confb/obj1", headers={"Range": "bytes=100-299"})
    assert st == 206 and part == body[100:300]
    for i in range(6):
        st, _, lst = _cli(addr).request("GET", "/confb")
        assert st == 200 and b"obj1" in lst
    # Multipart through whichever workers the kernel picks.
    st, _, resp = _cli(addr).request("POST", "/confb/mp",
                                     query={"uploads": ""})
    assert st == 200
    upload_id = resp.decode().split("<UploadId>")[1].split("<")[0]
    part1 = os.urandom(5 << 20)
    part2 = os.urandom(1 << 20)
    etags = []
    for num, data in ((1, part1), (2, part2)):
        st, hdr, _ = _cli(addr).request(
            "PUT", "/confb/mp",
            query={"partNumber": str(num), "uploadId": upload_id},
            body=data)
        assert st == 200
        etags.append(hdr.get("ETag", hdr.get("etag", '""')))
    complete = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
        for n, e in zip((1, 2), etags)) + "</CompleteMultipartUpload>"
    st, _, _ = _cli(addr).request("POST", "/confb/mp",
                                  query={"uploadId": upload_id},
                                  body=complete.encode())
    assert st == 200
    st, _, got = _cli(addr).request("GET", "/confb/mp")
    assert st == 200 and got == part1 + part2
    assert _cli(addr).request("DELETE", "/confb/mp")[0] == 204
    assert _cli(addr).request("DELETE", "/confb/obj1")[0] == 204
    for i in range(4):
        st, _, lst = _cli(addr).request("GET", "/confb")
        assert b"obj1" not in lst, "cross-worker stale listing"


def test_workers_admission_divided_and_shedding(worker_server):
    """MTPU_API_REQUESTS_MAX=4 over 2 workers -> 2 slots per worker;
    a burst of slow-ish requests must shed with 503 + Retry-After
    while in-quorum traffic still succeeds."""
    addr = worker_server
    st, _, info = _cli(addr).request("GET", "/minio/admin/v3/info")
    assert st == 200
    j = json.loads(info)
    assert j["admission"]["s3"]["limit"] == 2, \
        "admission budget not divided across workers"
    assert len(j.get("workers", [])) == 2
    body = os.urandom(1 << 20)
    _cli(addr).request("PUT", "/admb")
    results: list = []
    mu = threading.Lock()

    def put_one(i):
        try:
            st, hdr, _ = _cli(addr).request("PUT", f"/admb/o{i}",
                                            body=body)
            with mu:
                results.append((st, hdr))
        except Exception as e:  # noqa: BLE001 - recorded
            with mu:
                results.append((0, {"error": str(e)}))

    threads = [threading.Thread(target=put_one, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    statuses = [s for s, _ in results]
    assert statuses.count(200) >= 4, statuses
    shed = [(s, h) for s, h in results if s == 503]
    for s, h in shed:
        retry = {k.lower(): v for k, v in h.items()}.get("retry-after")
        assert retry is not None, "503 without Retry-After"


def test_workers_metrics_aggregate(worker_server):
    """A /metrics scrape served by EITHER worker reports the whole
    fleet: per-worker in-flight gauges and fleet-total counters."""
    addr = worker_server
    _cli(addr).request("PUT", "/aggb")
    for i in range(4):
        _cli(addr).request("PUT", f"/aggb/m{i}", body=b"x" * 1000)
    st, _, met = _cli(addr).request("GET", "/minio/v2/metrics/cluster")
    assert st == 200
    text = met.decode()
    assert 'minio_tpu_worker_in_flight{worker="0"}' in text
    assert 'minio_tpu_worker_in_flight{worker="1"}' in text
    assert "minio_tpu_workers_total 2" in text
    assert "minio_tpu_bufpool_hits_total" in text
    assert "minio_tpu_drive_queue_depth" in text
    # Fleet-total request counters: the PUTs above must be visible in
    # a scrape no matter which worker serves it.
    total = 0
    for line in text.splitlines():
        if line.startswith("minio_tpu_http_requests_total{") \
                and 'api="PUT:object"' in line:
            total += int(float(line.rsplit(" ", 1)[1]))
    assert total >= 4, text[:1000]


def test_shared_gen_poll_interval(tmp_path):
    """Rate-limited SharedGen (the bucket-meta generation): calls
    inside the window reuse the last verdict, a sibling's bump is
    observed once the window expires, and our OWN bump resets the
    window so bump+check in one process never misses itself."""
    from minio_tpu.io.workers import SharedGen

    path = str(tmp_path / "meta.gen")
    writer = SharedGen(path)
    observer = SharedGen(path, poll_interval=3600.0)
    assert observer.changed() is True        # first look always syncs
    writer.bump()
    assert observer.changed() is False, \
        "inside the poll window the cached verdict must be reused"
    observer._polled_at = 0.0                # window expiry
    assert observer.changed() is True
    assert observer.changed() is False       # re-armed, no new bump
    observer.bump()                          # own bump resets window
    assert observer.changed() is True
    # The un-rate-limited writer still observes every change.
    observer.bump()
    assert writer.changed() is True
