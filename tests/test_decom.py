"""Pool decommission: drain, checkpointed resume, reads-during-drain
(reference: cmd/erasure-server-pool-decom.go:1269)."""

import os
import threading
import time

import pytest

from minio_tpu.crypto.kms import AESGCM as _AESGCM

requires_crypto = pytest.mark.skipif(
    _AESGCM is None,
    reason="SSE needs the optional 'cryptography' wheel")

from minio_tpu.object import decom
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.types import (GetOptions, ObjectNotFound, PutOptions)
from minio_tpu.storage.local import LocalStorage


def _pool(tmp_path, name, n=4, deployment_id=""):
    disks = [LocalStorage(str(tmp_path / name / f"d{i}")) for i in range(n)]
    kw = {"deployment_id": deployment_id} if deployment_id else {}
    return ErasureSets([ErasureSet(disks)], **kw)


DEP = "00000000-0000-0000-0000-00000000dec0"


@pytest.fixture
def layer(tmp_path):
    p0 = _pool(tmp_path, "p0", deployment_id=DEP)
    p1 = _pool(tmp_path, "p1", deployment_id=DEP)
    lay = ServerPools([p0, p1])
    lay.make_bucket("db")
    return lay


def _pool_is_empty(pool, bucket) -> bool:
    page = pool.list_objects(bucket, max_keys=10, include_versions=True)
    return not page.objects


def test_decommission_drains_pool_preserving_everything(layer):
    # Seed pool 0 with a mix: plain objects, a versioned stack with a
    # delete marker, metadata + tags. Force placement into pool 0 by
    # writing through the pool directly.
    src = layer.pools[0]
    bodies = {f"obj{i}": os.urandom(10_000 + i) for i in range(8)}
    for k, b in bodies.items():
        src.put_object("db", k, b, PutOptions(
            user_metadata={"color": "red"}, content_type="text/x-test",
            tags="team=a"))
    src.put_object("db", "ver", b"v1", PutOptions(versioned=True))
    src.put_object("db", "ver", b"v2", PutOptions(versioned=True))
    from minio_tpu.object.types import DeleteOptions
    src.delete_object("db", "marked", DeleteOptions(versioned=True))

    d = layer.start_decommission(0)
    assert d.wait(60)
    st = layer.decommission_status()
    assert st["status"] == "complete", st
    assert st["migrated"] >= 9 and st["failed"] == 0

    # Pool 0 is empty; everything reads back identically through the
    # pools layer (now out of pool 1).
    assert _pool_is_empty(layer.pools[0], "db")
    for k, b in bodies.items():
        info, got = layer.get_object("db", k)
        assert got == b
        assert info.user_metadata.get("color") == "red"
        assert info.content_type == "text/x-test"
        assert info.user_tags == "team=a"
    versions = layer.list_versions_all("db", "ver")
    assert len(versions) == 2
    _, got = layer.get_object("db", "ver")
    assert got == b"v2"
    # The delete-marker stack moved too.
    mv = layer.list_versions_all("db", "marked")
    assert len(mv) == 1 and mv[0].deleted
    # New writes land in surviving pools only.
    layer.put_object("db", "after", b"post-drain")
    assert _pool_is_empty(layer.pools[0], "db")
    # A fresh layer over the same drives (restart / peer node) learns
    # the completed drain from persisted state and keeps the pool
    # excluded; nothing resumes.
    layer3 = ServerPools(list(layer.pools))
    assert layer3.resume_decommission() is None
    assert 0 in layer3.decommissioning
    # The peer-sync entry point alone also suffices.
    layer4 = ServerPools(list(layer.pools))
    layer4.sync_decommission_markers()
    assert 0 in layer4.decommissioning


def test_decommission_preserves_multipart_parts_and_etag(layer):
    """A multipart object keeps its part boundaries and composite etag
    through the drain (part-aware SSE decryption depends on them)."""
    src = layer.pools[0]
    uid = src.new_multipart_upload("db", "mp", PutOptions())
    p1 = os.urandom(5 << 20)
    p2 = os.urandom(1234)
    e1 = src.put_object_part("db", "mp", uid, 1, p1).etag
    e2 = src.put_object_part("db", "mp", uid, 2, p2).etag
    info = src.complete_multipart_upload("db", "mp", uid,
                                         [(1, e1), (2, e2)])
    assert info.etag.endswith("-2")

    d = layer.start_decommission(0)
    assert d.wait(60)
    assert layer.decommission_status()["status"] == "complete"
    got_info, got = layer.get_object("db", "mp")
    assert got == p1 + p2
    assert got_info.etag == info.etag
    assert [p.number for p in got_info.parts] == [1, 2]
    assert [p.size for p in got_info.parts] == [len(p1), len(p2)]


def test_decommission_kill_and_resume(layer):
    src = layer.pools[0]
    bodies = {f"k{i:03d}": os.urandom(4000) for i in range(120)}
    for k, b in bodies.items():
        src.put_object("db", k, b)

    # Checkpoint every 4 objects; stop the drain partway through.
    d = layer.start_decommission(0, checkpoint_every=4)
    deadline = time.time() + 60
    while d.state["migrated"] < 10 and time.time() < deadline:
        time.sleep(0.005)
    d.stop()
    st = decom.load_state(layer)
    assert st["migrated"] >= 10
    if st["status"] == "draining":
        # The interesting path: the kill landed mid-drain; a fresh
        # layer over the same drives resumes from the checkpoint.
        assert not _pool_is_empty(layer.pools[0], "db")
        layer2 = ServerPools(list(layer.pools))
        d2 = layer2.resume_decommission()
        assert d2 is not None
        assert d2.wait(120)
        final = layer2
    else:
        # On a fast/unloaded box the drain can outrun the stop signal;
        # the resume path has nothing to do — fall through to the
        # invariant checks rather than flaking.
        assert st["status"] == "complete", st
        final = layer
    assert decom.load_state(final)["status"] == "complete"
    assert _pool_is_empty(final.pools[0], "db")
    for k, b in bodies.items():
        _, got = final.get_object("db", k)
        assert got == b


def test_reads_never_fail_during_drain(layer):
    src = layer.pools[0]
    bodies = {f"r{i:03d}": os.urandom(3000) for i in range(30)}
    for k, b in bodies.items():
        src.put_object("db", k, b)

    failures = []
    stop = threading.Event()

    def reader():
        keys = list(bodies)
        i = 0
        while not stop.is_set():
            k = keys[i % len(keys)]
            try:
                _, got = layer.get_object("db", k)
                if got != bodies[k]:
                    failures.append(f"{k}: wrong bytes")
            except Exception as e:  # noqa: BLE001 - recorded
                failures.append(f"{k}: {e}")
            i += 1

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    d = layer.start_decommission(0, checkpoint_every=4)
    assert d.wait(60)
    stop.set()
    for t in threads:
        t.join()
    assert not failures, failures[:5]
    assert layer.decommission_status()["status"] == "complete"


def test_decommission_guards(layer):
    with pytest.raises(decom.DecomError):
        decom.Decommission(layer, 7)
    single = ServerPools([layer.pools[0]])
    with pytest.raises(decom.DecomError):
        decom.Decommission(single, 0)


@requires_crypto
def test_decommission_preserves_sse_multipart(tmp_path):
    """The riskiest cross-feature seam this round: an SSE-S3 MULTIPART
    object (per-part DARE streams, per-part nonces in ObjectPartInfo)
    must decrypt byte-identically after its pool is drained — the
    restore re-encodes the stored ciphertext into the destination's
    geometry, and the part boundaries + nonces ride the metadata."""
    import base64
    from minio_tpu.s3.server import S3Server
    from tests.s3client import S3Client

    os.environ["MTPU_KMS_SECRET_KEY"] = \
        "dk:" + base64.b64encode(os.urandom(32)).decode()
    try:
        p0 = _pool(tmp_path, "p0", deployment_id=DEP)
        p1 = _pool(tmp_path, "p1", deployment_id=DEP)
        lay = ServerPools([p0, p1])
        srv = S3Server(lay, address="127.0.0.1:0")
        srv.start()
        try:
            cli = S3Client(srv.address)
            assert cli.request("PUT", "/ssedecom")[0] == 200
            st, _, body = cli.request(
                "POST", "/ssedecom/enc", query={"uploads": ""},
                headers={"x-amz-server-side-encryption": "AES256"})
            assert st == 200, body
            uid = body.split(b"<UploadId>")[1].split(
                b"</UploadId>")[0].decode()
            parts = [os.urandom(5 << 20), os.urandom(2222)]
            etags = []
            for i, p in enumerate(parts, 1):
                st, h, b = cli.request(
                    "PUT", "/ssedecom/enc",
                    query={"partNumber": str(i), "uploadId": uid},
                    body=p)
                assert st == 200, b
                etags.append(h.get("etag") or h.get("ETag"))
            xml = "<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag>"
                f"</Part>" for i, e in enumerate(etags, 1)) + \
                "</CompleteMultipartUpload>"
            st, _, b = cli.request("POST", "/ssedecom/enc",
                                   query={"uploadId": uid},
                                   body=xml.encode())
            assert st == 200, b

            whole = b"".join(parts)
            st, _, got = cli.request("GET", "/ssedecom/enc")
            assert st == 200 and got == whole

            # Drain whichever pool actually holds the object, so the
            # migration path is exercised regardless of free-space
            # placement.
            holder = 0 if not _pool_is_empty(lay.pools[0], "ssedecom") \
                else 1
            d = lay.start_decommission(holder)
            assert d.wait(60)
            assert lay.decommission_status()["status"] == "complete"
            assert _pool_is_empty(lay.pools[holder], "ssedecom")
            # Full and part-boundary-straddling reads decrypt after
            # the move.
            st, _, got = cli.request("GET", "/ssedecom/enc")
            assert st == 200 and got == whole
            lo, hi = (5 << 20) - 100, (5 << 20) + 99
            st, _, got = cli.request(
                "GET", "/ssedecom/enc",
                headers={"Range": f"bytes={lo}-{hi}"})
            assert st == 206 and got == whole[lo:hi + 1]
        finally:
            srv.stop()
    finally:
        os.environ.pop("MTPU_KMS_SECRET_KEY", None)
