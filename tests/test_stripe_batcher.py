"""Cross-request stripe batching (ops/batcher.py): coalescing,
demultiplexing, calibration routing, and the solo-bypass guarantee —
the submission-queue half of the blueprint's "a full erasure set's
stripes encode in one pmap" (BASELINE.json north star)."""

import threading
import time

import numpy as np
import pytest

from minio_tpu.object.erasure_object import _host_rows
from minio_tpu.ops.batcher import StripeBatcher

K, M, SHARD = 8, 4, 4096


def _mk_window(b, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(b, K, SHARD), dtype=np.uint8)


def _rows_equal(a, b):
    assert len(a) == len(b)
    for da, db in zip(a, b):
        assert len(da) == len(db)
        for (ha, blka), (hb, blkb) in zip(da, db):
            assert np.array_equal(np.asarray(ha), np.asarray(hb))
            assert np.array_equal(np.asarray(blka), np.asarray(blkb))


class _RecordingDevice:
    """Fake device framer: host math, records every dispatched batch."""

    def __init__(self):
        self.batches = []

    def __call__(self, stacked):
        self.batches.append(stacked.shape[0])
        return _host_rows(K, M, stacked)


def test_concurrent_windows_coalesce_into_one_device_batch():
    dev = _RecordingDevice()
    sb = StripeBatcher(dev, lambda s: _host_rows(K, M, s),
                       probe_fn=lambda: True, min_device_blocks=8)
    sb._device_ok = True               # skip async probe latency
    sb._probe_started = True
    n_req = 6
    windows = [_mk_window(3, i) for i in range(n_req)]
    results = [None] * n_req
    barrier = threading.Barrier(n_req)

    def worker(i):
        barrier.wait()
        results[i] = sb.frame(windows[i])

    # Pre-register inflight so no thread sees itself solo: the barrier
    # releases all at once, but the first to grab the lock would
    # otherwise bypass. Simulate a busy system with a dummy inflight.
    with sb._mu:
        sb._inflight += 1
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    with sb._mu:
        sb._inflight -= 1
    # Every request got exactly its own blocks back, byte-identical to
    # the host codec.
    for i in range(n_req):
        assert results[i] is not None
        _rows_equal(results[i], _host_rows(K, M, windows[i]))
    # Coalescing happened: fewer device dispatches than requests, and
    # at least one batch bigger than any single request.
    assert dev.batches, "device never dispatched"
    assert len(dev.batches) < n_req
    assert max(dev.batches) > 3
    # Batch dims are padded to fixed buckets (bounded compile cache).
    assert all(b in (8, 16, 32, 64, 128, 256) for b in dev.batches)


def test_solo_request_bypasses_queue_with_no_wait():
    dev = _RecordingDevice()
    sb = StripeBatcher(dev, lambda s: _host_rows(K, M, s),
                       probe_fn=lambda: True)
    sb._device_ok = True
    sb._probe_started = True
    w = _mk_window(2, 99)
    t0 = time.perf_counter()
    rows = sb.frame(w)
    elapsed = time.perf_counter() - t0
    _rows_equal(rows, _host_rows(K, M, w))
    assert dev.batches == []           # host path, no device dispatch
    assert elapsed < 0.2               # and no batching wait


def test_negative_calibration_routes_everything_host():
    dev = _RecordingDevice()
    sb = StripeBatcher(dev, lambda s: _host_rows(K, M, s),
                       probe_fn=lambda: False)
    sb._device_ok = False              # probe said: device link loses
    sb._probe_started = True
    with sb._mu:
        sb._inflight += 1              # simulate concurrency
    try:
        rows = sb.frame(_mk_window(4, 5))
    finally:
        with sb._mu:
            sb._inflight -= 1
    _rows_equal(rows, _host_rows(K, M, _mk_window(4, 5)))
    assert dev.batches == []


def test_device_failure_delivered_to_all_waiters():
    def boom(stacked):
        raise RuntimeError("device fell over")

    sb = StripeBatcher(boom, lambda s: _host_rows(K, M, s),
                       probe_fn=lambda: True, min_device_blocks=2)
    sb._device_ok = True
    sb._probe_started = True
    with sb._mu:
        sb._inflight += 1
    errs = []

    def worker(i):
        try:
            sb.frame(_mk_window(2, i))
        except RuntimeError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    with sb._mu:
        sb._inflight -= 1
    assert len(errs) == 3


def test_oversized_burst_splits_into_bucketed_batches():
    """Pending blocks beyond the largest pad bucket (256) must split
    across dispatches, not blow up the pad math (review r5 finding)."""
    dev = _RecordingDevice()
    sb = StripeBatcher(dev, lambda s: _host_rows(K, M, s),
                       probe_fn=lambda: True, min_device_blocks=8)
    sb._device_ok = True
    sb._probe_started = True
    n_req = 10                      # 10 x 32 blocks = 320 > 256
    windows = [_mk_window(32, i) for i in range(n_req)]
    results = [None] * n_req
    with sb._mu:
        sb._inflight += 1
    threads = [threading.Thread(
        target=lambda i=i: results.__setitem__(i, sb.frame(windows[i])))
        for i in range(n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    with sb._mu:
        sb._inflight -= 1
    for i in range(n_req):
        assert results[i] is not None, f"request {i} hung"
        _rows_equal(results[i], _host_rows(K, M, windows[i]))
    assert all(b <= 256 for b in dev.batches)


def test_solo_device_sized_window_dispatches_directly():
    """A lone streaming window at or above min_device_blocks skips the
    queue but still rides the device when calibration approves — a
    single-stream large PUT must not regress to the host codec
    (review r5 finding)."""
    dev = _RecordingDevice()
    sb = StripeBatcher(dev, lambda s: _host_rows(K, M, s),
                       probe_fn=lambda: True, min_device_blocks=8)
    sb._device_ok = True
    sb._probe_started = True
    w = _mk_window(32, 42)
    rows = sb.frame(w)              # solo, but device-sized
    _rows_equal(rows, _host_rows(K, M, w))
    assert dev.batches == [32]


def test_host_rows_matches_framer_format():
    """_host_rows output is byte-identical to the fused framer's run()
    (the portable path) for the same window."""
    from minio_tpu.object.erasure_object import _framer_for
    w = _mk_window(3, 7)
    _rows_equal(_host_rows(K, M, w), _framer_for(K, M)(w))
