"""Test env: force JAX onto a virtual 8-device CPU mesh.

Multi-chip sharding tests run against this mesh (real TPU hardware is
exercised by the driver's dryrun and bench.py; the axon TPU tunnel adds
~150 ms per host round-trip, which would dominate the suite).

The axon sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon already latched into jax.config, so mutating
os.environ here is too late — update the live config instead.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

# The pytest process has JAX loaded, and the pre-forked worker
# front-end's os.fork() is unsafe after that: any in-process
# minio_tpu.server.main() call must take the single-process path.
# Worker-mode tests boot the fleet in a clean subprocess and override
# this explicitly (tests/test_io_engine.py).
os.environ.setdefault("MTPU_HTTP_WORKERS", "1")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long multi-process cluster tests")
