"""Elastic fleet under fire: live pool expansion, chaos-proof online
rebalance/decommission, zero-downtime drain.

Single-process tests cover the new migration machinery directly —
merge-dedup listings during a migration, the coherence bump ordering
inside migrate_key, the admission governor (yield-to-foreground +
parallel workers), the coordinator lease, and the elastic janitor's
crashed-vs-paused distinction. The cluster tests (tests/cluster.py
harness, real server processes) then prove the fleet-wide story: a
remote node's cache never serves a migrated-away copy, a SIGKILLed
rebalance coordinator is replaced by a surviving node resuming from
the checkpoint, a drain converges through a network partition, and a
live node drains out with zero failed foreground requests before its
removal from the topology.
"""

import json
import os
import threading
import time

import pytest

from minio_tpu.grid.dsync import LocalLocker, LockServer
from minio_tpu.object import decom, rebalance
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.types import PutOptions
from minio_tpu.storage.local import LocalStorage
from minio_tpu.topology.ellipses import parse_pools

DEP = "00000000-0000-0000-0000-0000e1a50000"


def _pool(tmp_path, name, n=4):
    disks = [LocalStorage(str(tmp_path / name / f"d{i}")) for i in range(n)]
    return ErasureSets([ErasureSet(disks)], deployment_id=DEP)


@pytest.fixture
def layer(tmp_path):
    lay = ServerPools([_pool(tmp_path, "p0"), _pool(tmp_path, "p1")])
    lay.make_bucket("db")
    return lay


def _pool_is_empty(pool, bucket) -> bool:
    page = pool.list_objects(bucket, max_keys=10, include_versions=True)
    return not page.objects


# -- pool expansion CLI (topology/ellipses comma form) ----------------------

def test_parse_pools_comma_forms():
    # A comma-separated argument is its OWN pool of exactly those
    # endpoints (ports and drives advancing together can't be written
    # as one cartesian ellipses pattern).
    assert parse_pools(["a,b", "c", "d"]) == [["a", "b"], ["c", "d"]]
    # Commas compose with ellipses: each segment expands in place.
    assert parse_pools(
        ["http://h:9000/d{1...2},http://h:9001/d{1...2}"]) == [
        ["http://h:9000/d1", "http://h:9000/d2",
         "http://h:9001/d1", "http://h:9001/d2"]]
    # Trailing comma keeps a single-endpoint pool separate from the
    # plain-argument pool.
    assert parse_pools(["solo,", "x", "y"]) == [["solo"], ["x", "y"]]
    with pytest.raises(ValueError):
        parse_pools([","])


# -- merge-dedup listings during a migration --------------------------------

def test_listing_never_doubly_visible_mid_migration(layer):
    """The mid-migration window where BOTH pools hold the same version
    stack (restore landed, source cleanup not yet): plain and
    versioned listings show each (key, version) exactly once."""
    body = os.urandom(9_000)
    layer.pools[0].put_object("db", "dup", body,
                              PutOptions(versioned=True))
    src_set = layer.pools[0].set_for("dup")
    dst_set = layer.pools[1].set_for("dup")
    for fi in src_set.list_versions_all("db", "dup"):
        from minio_tpu.object.types import GetOptions
        _, data = src_set.get_object(
            "db", "dup", GetOptions(version_id=fi.version_id))
        dst_set.restore_version("db", "dup", fi, data)
    layer.decommissioning.add(0)        # drain in progress: dst-first

    page = layer.list_objects("db", max_keys=10)
    assert [o.name for o in page.objects] == ["dup"]
    vpage = layer.list_objects("db", max_keys=10, include_versions=True)
    vkeys = [(o.name, o.version_id) for o in vpage.objects]
    assert len(vkeys) == len(set(vkeys)) == 1, vkeys
    _, got = layer.get_object("db", "dup")
    assert got == body


# -- coherence bump ordering in migrate_key ---------------------------------

def test_migrate_key_bumps_coherence_before_source_cleanup(layer):
    """The bucket-generation bump (the funnel that invalidates every
    node's fi_cache/metacache) must fire while the SOURCE copy still
    exists — a peer re-filling its cache in the gap resolves
    destination-first and is already correct; bumping after the
    cleanup would leave a window serving the deleted copy."""
    body = os.urandom(12_345)
    layer.pools[0].put_object("db", "bump", body)
    src_set = layer.pools[0].set_for("bump")
    calls = []
    orig = src_set.metacache.bump

    def spy(bucket, *a, **kw):
        try:
            src_has = bool(src_set.list_versions_all("db", "bump"))
        except Exception:  # noqa: BLE001 - absent == cleaned up
            src_has = False
        calls.append((bucket, src_has))
        return orig(bucket, *a, **kw)

    src_set.metacache.bump = spy
    moved = decom.migrate_key(layer, 0, "db", "bump", lambda: 1)
    assert moved == len(body)
    mig = [c for c in calls if c[0] == "db"]
    assert mig, "migrate_key never bumped the bucket generation"
    assert mig[0][1], "first bump fired AFTER the source cleanup"
    assert _pool_is_empty(layer.pools[0], "db")
    _, got = layer.get_object("db", "bump")
    assert got == body


# -- admission governor: migration yields to foreground ---------------------

def test_drain_yields_to_foreground_pressure(layer, monkeypatch):
    monkeypatch.setenv("MTPU_REBALANCE_YIELD_MS", "5")
    bodies = {f"y{i}": os.urandom(4_000) for i in range(6)}
    for k, b in bodies.items():
        layer.pools[0].put_object("db", k, b)
    busy = threading.Event()
    busy.set()
    layer.migration_pressure = busy.is_set

    d = layer.start_decommission(0)
    time.sleep(0.3)
    # Gated: nothing migrates while the front end queues, and the
    # pause is accounted.
    assert d.state["migrated"] == 0
    assert d.state["yields"] >= 1
    busy.clear()
    assert d.wait(60)
    st = layer.decommission_status()
    assert st["status"] == "complete", st
    assert st["migrated"] == len(bodies)
    assert st["bytes_moved"] == sum(len(b) for b in bodies.values())
    for k, b in bodies.items():
        _, got = layer.get_object("db", k)
        assert got == b


def test_parallel_drain_workers(layer, monkeypatch):
    monkeypatch.setenv("MTPU_REBALANCE_WORKERS", "4")
    bodies = {f"w{i:02d}": os.urandom(5_000 + i) for i in range(12)}
    for k, b in bodies.items():
        layer.pools[0].put_object("db", k, b)
    d = layer.start_decommission(0)
    assert d.wait(60)
    st = layer.decommission_status()
    assert st["status"] == "complete", st
    assert st["migrated"] == len(bodies) and st["failed"] == 0
    assert _pool_is_empty(layer.pools[0], "db")
    for k, b in bodies.items():
        _, got = layer.get_object("db", k)
        assert got == b


# -- coordinator lease ------------------------------------------------------

def test_coordinator_lease_admits_single_driver(layer):
    layer.lockers = [LocalLocker(LockServer(ttl=60))]
    held = decom.coordinator_lease(layer, "decom")
    assert held is not None and held.lock(write=True, timeout=2)
    try:
        layer.pools[0].put_object("db", "lease", b"x" * 2048)
        # Another would-be coordinator (same layer = same lockers)
        # cannot start the drain while the lease is held...
        with pytest.raises(decom.LeaseHeld):
            layer.start_decommission(0)
        assert 0 not in layer.decommissioning   # no half-started state
    finally:
        held.unlock()
    # ...and proceeds normally once it lapses.
    d = layer.start_decommission(0)
    assert d.wait(60)
    assert layer.decommission_status()["status"] == "complete"


def test_coordinator_lease_none_without_lockers(layer):
    assert decom.coordinator_lease(layer, "decom") is None


# -- elastic janitor: crashed resumes, paused stays paused ------------------

def _seed(layer, n=40, size=4_000):
    bodies = {f"j{i:03d}": os.urandom(size) for i in range(n)}
    for k, b in bodies.items():
        layer.pools[0].put_object("db", k, b)
    return bodies


def test_janitor_resumes_crashed_drain(layer):
    bodies = _seed(layer)
    d = layer.start_decommission(0, checkpoint_every=4)
    deadline = time.time() + 60
    while d.state["migrated"] < 6 and time.time() < deadline:
        time.sleep(0.005)
    d.stop()
    st = decom.load_state(layer)
    if st["status"] == "draining":
        # Model the CRASH (a SIGKILLed coordinator never writes the
        # explicit-pause flag a clean stop leaves behind).
        st.pop("paused", None)
        decom._save_state(layer, st)
        lay2 = ServerPools(list(layer.pools))
        assert lay2.elastic_janitor_tick() == ["decom"]
        assert lay2._decom.wait(120)
        final = lay2
    else:
        final = layer                   # drain outran the stop signal
    assert decom.load_state(final)["status"] == "complete"
    assert _pool_is_empty(final.pools[0], "db")
    for k, b in bodies.items():
        _, got = final.get_object("db", k)
        assert got == b


def test_janitor_skips_operator_paused_walks(layer):
    _seed(layer, n=30)
    d = layer.start_decommission(0, checkpoint_every=4)
    deadline = time.time() + 60
    while d.state["migrated"] < 4 and time.time() < deadline:
        time.sleep(0.005)
    d.stop()                            # explicit pause
    st = decom.load_state(layer)
    if st["status"] != "draining":
        pytest.skip("drain outran the stop signal on this box")
    assert st.get("paused") is True
    lay2 = ServerPools(list(layer.pools))
    assert lay2.elastic_janitor_tick() == []
    assert lay2._decom is None
    # The explicit resume path (operator/boot) still works on a
    # paused record — and clears the flag.
    d2 = lay2.resume_decommission()
    assert d2 is not None and d2.wait(120)
    assert decom.load_state(lay2)["status"] == "complete"


def test_janitor_resumes_crashed_rebalance(tmp_path):
    lay = ServerPools([_pool(tmp_path, "p0"), _pool(tmp_path, "p1")])
    lay.make_bucket("db")
    bodies = {f"r{i:03d}": os.urandom(6_000) for i in range(40)}
    for k, b in bodies.items():
        lay.pools[0].put_object("db", k, b)
    rb = lay.start_rebalance(checkpoint_every=4)
    deadline = time.time() + 60
    while time.time() < deadline:
        recs = rb.state.get("pools", {})
        if sum(r.get("migrated", 0) for r in recs.values()) >= 4:
            break
        time.sleep(0.005)
    rb.stop()
    st = rebalance.load_state(lay)
    if st["status"] == "rebalancing":
        st.pop("paused", None)
        st["rev"] = st.get("rev", 0) + 1
        blob = json.dumps(st, sort_keys=True).encode()
        from minio_tpu.storage.local import SYS_VOL
        for s in lay.pools[0].sets:
            for dsk in s.disks:
                dsk.write_all(SYS_VOL, rebalance.REBAL_PATH, blob)
        lay2 = ServerPools(list(lay.pools))
        assert lay2.elastic_janitor_tick() == ["rebalance"]
        assert lay2._rebalance.wait(120)
        final = lay2
    else:
        final = lay
    assert rebalance.load_state(final)["status"] == "complete"
    for k, b in bodies.items():
        _, got = final.get_object("db", k)
        assert got == b
    vpage = final.list_objects("db", max_keys=100, include_versions=True)
    vkeys = [(o.name, o.version_id) for o in vpage.objects]
    assert len(vkeys) == len(set(vkeys)) == len(bodies)


# -- observability: rebalance/decom metrics + admin info --------------------

def test_rebalance_metrics_and_admin_info(tmp_path):
    from minio_tpu.s3.server import S3Server
    from tests.s3client import S3Client

    lay = ServerPools([_pool(tmp_path, "p0"), _pool(tmp_path, "p1")])
    lay.make_bucket("db")
    for i in range(20):
        lay.pools[0].put_object("db", f"m{i:02d}", os.urandom(6_000))
    srv = S3Server(lay, address="127.0.0.1:0")
    srv.start()
    try:
        rb = lay.start_rebalance()
        assert rb.wait(60)
        assert lay.rebalance_status()["status"] == "complete"
        d = lay.start_decommission(0)
        assert d.wait(60)

        cli = S3Client(srv.address)
        st, _, body = cli.request("GET", "/minio/v2/metrics/cluster")
        assert st == 200
        text = body.decode()
        for name in ("minio_tpu_rebalance_migrated_total",
                     "minio_tpu_rebalance_bytes_moved_total",
                     "minio_tpu_rebalance_failed_total",
                     "minio_tpu_rebalance_pool_fill_fraction",
                     "minio_tpu_rebalance_yields_total",
                     "minio_tpu_rebalance_checkpoint_age_seconds",
                     "minio_tpu_rebalance_active",
                     "minio_tpu_decom_bytes_moved_total",
                     "minio_tpu_decom_yields_total",
                     "minio_tpu_decom_checkpoint_age_seconds",
                     "minio_tpu_decommission_migrated_total"):
            assert f"\n{name}" in text or text.startswith(name), name
        # Something actually moved and the gauges read sane.
        moved = sum(
            float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
            if ln.startswith("minio_tpu_rebalance_bytes_moved_total{"))
        assert moved > 0
        assert "minio_tpu_rebalance_active 0" in text

        st, _, body = cli.request("GET", "/minio/admin/v3/info")
        assert st == 200
        info = json.loads(body)
        node = info["nodes"][0] if "nodes" in info else info
        assert node["rebalance"]["status"] == "complete"
        assert node["decommission"]["status"] == "complete"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# multi-process cluster tests (tests/cluster.py harness)
# ---------------------------------------------------------------------------

from tests.cluster import Cluster  # noqa: E402


def _put_retry(cli, path, body, deadline_s=45):
    deadline = time.time() + deadline_s
    while True:
        try:
            st, _, b = cli.request("PUT", path, body=body)
        except Exception as e:  # noqa: BLE001 - conn reset mid-failover
            st, b = 0, str(e).encode()
        if st == 200:
            return
        assert time.time() < deadline, f"PUT {path}: {st} {b[:300]}"
        time.sleep(1)


def _admin(cli, verb, method="GET", query=None):
    st, _, body = cli.request(method, f"/minio/admin/v3/{verb}",
                              query=query or {})
    return st, body


def _wait_status(cli, verb, want, deadline_s, key="status"):
    """Poll an elastic status admin verb until the persisted/live state
    reaches one of `want`; returns the final doc."""
    deadline = time.time() + deadline_s
    doc = None
    while time.time() < deadline:
        try:
            st, body = _admin(cli, verb)
            if st == 200 and body and body != b"null":
                doc = json.loads(body)
                if doc and doc.get(key) in want:
                    return doc
        except Exception:  # noqa: BLE001 - node mid-failover
            pass
        time.sleep(0.5)
    raise AssertionError(f"{verb} never reached {want}: {doc}")


def _disk_holds(cluster, node, pool, key) -> bool:
    """True when any drive dir of (node, pool) holds `key`'s xl.meta —
    ground-truth placement, independent of any server's view."""
    for d in range(64):
        p = cluster.pool_drive_dir(node, pool, d)
        if not os.path.isdir(p):
            break
        for dirpath, _dirs, files in os.walk(p):
            if key in dirpath.split(os.sep) and "xl.meta" in files:
                return True
    return False


def test_cluster_migrated_key_never_served_from_stale_cache(tmp_path):
    """Satellite 1, fleet-wide: nodes 1 and 2 warm their fi_cache /
    metacache against the SOURCE copy of a key; pool 0 then drains.
    migrate_key's coherence bump broadcasts BEFORE the source copy is
    destroyed, so the remote nodes' cached GET/HEAD must keep serving
    the (now migrated) bytes — never a 404, never the deleted copy —
    and listings show the key exactly once."""
    body = os.urandom(64 * 1024)
    with Cluster(tmp_path, nodes=3, pools=[2, 2]) as c:
        c0, c1, c2 = c.client(0), c.client(1), c.client(2)
        assert c0.request("PUT", "/ebkt")[0] == 200
        _put_retry(c0, "/ebkt/mig", body)
        holder = 0 if any(_disk_holds(c, n, 0, "mig")
                          for n in range(3)) else 1
        # Warm every node's caches against the source copy.
        for cli in (c1, c2):
            st, _, got = cli.request("GET", "/ebkt/mig")
            assert st == 200 and got == body
            assert cli.request("HEAD", "/ebkt/mig")[0] == 200

        st, b = _admin(c0, "decommission", "POST",
                       {"pool": str(holder)})
        assert st == 200, b
        # Any-node status: poll node 1, not the starting node.
        doc = _wait_status(c1, "decommission-status", ("complete",), 90)
        assert doc["failed"] == 0, doc

        for cli in (c1, c2):
            st, _, got = cli.request("GET", "/ebkt/mig")
            assert st == 200, "stale cache served the migrated-away copy"
            assert got == body
            assert cli.request("HEAD", "/ebkt/mig")[0] == 200
        st, _, lst = c2.request("GET", "/ebkt")
        assert st == 200 and lst.count(b"<Key>mig</Key>") == 1
        # Ground truth: the drained pool's drives are empty of the key.
        assert not any(_disk_holds(c, n, holder, "mig") for n in range(3))


@pytest.mark.slow
def test_cluster_sigkill_coordinator_rebalance_resumes(tmp_path):
    """The tentpole chaos acceptance: SIGKILL the node driving a
    rebalance mid-walk. Its dsync lease stops refreshing, expires
    after MTPU_GRID_LOCK_TTL, and a surviving node's elastic janitor
    wins the lock and resumes from the persisted checkpoint — no
    object lost, none doubly visible."""
    env = {"MTPU_GRID_LOCK_TTL": "4", "MTPU_ELASTIC_JANITOR_S": "1",
           "MTPU_REBALANCE_PACE_MS": "250"}
    bodies = {f"k{i:03d}": os.urandom(6_000 + i) for i in range(48)}
    with Cluster(tmp_path, nodes=4, pools=[2, 2], env=env) as c:
        c0, c1 = c.client(0), c.client(1)
        assert c0.request("PUT", "/rbkt")[0] == 200
        for k, b in bodies.items():
            _put_retry(c0, f"/rbkt/{k}", b)

        st, b = _admin(c0, "rebalance-start", "POST")
        assert st == 200, b
        # Let the walk make real progress, then crash the coordinator.
        deadline = time.time() + 60
        while time.time() < deadline:
            st, body = _admin(c0, "rebalance-status")
            doc = json.loads(body) if st == 200 and body else None
            moved = sum(r.get("migrated", 0)
                        for r in (doc or {}).get("pools", {}).values())
            if moved >= 2:
                break
            time.sleep(0.2)
        assert moved >= 2, f"rebalance made no progress: {doc}"
        c.kill(0)

        # A survivor resumes from the checkpoint and completes.
        doc = _wait_status(c1, "rebalance-status", ("complete",), 120)
        recs = doc.get("pools", {})
        assert sum(r.get("failed", 0) for r in recs.values()) == 0, doc
        assert sum(r.get("migrated", 0) for r in recs.values()) >= 2

        # Post-chaos byte identity + single-visibility for EVERY key.
        for k, b in bodies.items():
            st, _, got = c1.request("GET", f"/rbkt/{k}")
            assert st == 200 and got == b, f"{k}: lost or torn"
        st, _, lst = c1.request("GET", "/rbkt",
                                query={"max-keys": "1000"})
        assert st == 200
        for k in bodies:
            assert lst.count(f"<Key>{k}</Key>".encode()) == 1, k


@pytest.mark.slow
def test_cluster_partition_during_decommission_converges(tmp_path):
    """Partition a non-coordinator node mid-drain: the walk keeps
    going on remaining quorum (EC 4+4 tolerates 2 of 8 drives dark),
    completes, and after the node rejoins every key reads back
    byte-identical from every node — including the rejoined one."""
    env = {"MTPU_REBALANCE_PACE_MS": "150"}
    bodies = {f"p{i:03d}": os.urandom(5_000) for i in range(24)}
    with Cluster(tmp_path, nodes=4, pools=[2, 2], env=env) as c:
        c0, c2 = c.client(0), c.client(2)
        assert c0.request("PUT", "/pbkt")[0] == 200
        for k, b in bodies.items():
            _put_retry(c0, f"/pbkt/{k}", b)
        holder = 0 if any(_disk_holds(c, n, 0, "p000")
                          for n in range(4)) else 1

        st, b = _admin(c0, "decommission", "POST",
                       {"pool": str(holder)})
        assert st == 200, b
        deadline = time.time() + 60
        doc = None
        while time.time() < deadline:
            st, body = _admin(c0, "decommission-status")
            doc = json.loads(body) if st == 200 and body else None
            if doc and doc.get("migrated", 0) >= 2:
                break
            time.sleep(0.2)
        assert doc and doc.get("migrated", 0) >= 2, doc
        c.partition(1)
        try:
            doc = _wait_status(c0, "decommission-status",
                               ("complete", "failed"), 120)
        finally:
            c.rejoin(1)
        if doc.get("status") == "failed":
            # Keys that landed on the partitioned node's drives below
            # read quorum fail their migrate and are retried once the
            # partition heals — kick the resume and re-converge.
            st, b = _admin(c0, "decommission", "POST",
                           {"pool": str(holder)})
            assert st == 200, b
            doc = _wait_status(c0, "decommission-status",
                               ("complete",), 120)
        assert doc["status"] == "complete", doc

        for k, b in bodies.items():
            st, _, got = c2.request("GET", f"/pbkt/{k}")
            assert st == 200 and got == b, f"{k}: lost or torn"
        # The rejoined node converges too (its caches invalidate or
        # expire; never the deleted source copy).
        c1 = c.client(1)
        deadline = time.time() + 30
        for k, b in bodies.items():
            while True:
                st, _, got = c1.request("GET", f"/pbkt/{k}")
                if st == 200 and got == b:
                    break
                assert time.time() < deadline, f"{k} via rejoined node"
                time.sleep(0.5)


@pytest.mark.slow
def test_cluster_drain_and_remove_live_node(tmp_path):
    """Zero-downtime node removal: node 3 exclusively hosts pool 1;
    drain it while foreground PUT/GET traffic runs (zero failures
    allowed), then SHRINK the topology — reboot as a 3-node cluster
    without node 3 or its pool — and prove byte identity of every
    object through the new fleet."""
    bodies = {f"d{i:03d}": os.urandom(8_000) for i in range(16)}
    ports = None
    fg_bodies = {}
    failures = []
    with Cluster(tmp_path, nodes=4,
                 pools=[([0, 1, 2], 2), ([3], 12)]) as c:
        ports = list(c.ports)
        c0, c1 = c.client(0), c.client(1)
        assert c0.request("PUT", "/dbkt")[0] == 200
        for k, b in bodies.items():
            _put_retry(c0, f"/dbkt/{k}", b)
        # Pool 1 (12 drives, most free space) took the writes — the
        # shape under test: the node-to-remove holds the data.
        assert _disk_holds(c, 3, 1, "d000")

        st, b = _admin(c0, "decommission", "POST", {"pool": "1"})
        assert st == 200, b
        # Placement now excludes pool 1 cluster-wide; foreground
        # traffic through ANOTHER node must see zero failures for the
        # whole drain window.
        stop = threading.Event()

        def foreground():
            i = 0
            while not stop.is_set():
                k, body = f"fg{i:03d}", os.urandom(2_000)
                try:
                    st, _, b = c1.request("PUT", f"/dbkt/{k}", body=body)
                    if st != 200:
                        failures.append(f"PUT {k}: {st} {b[:200]}")
                    else:
                        fg_bodies[k] = body
                        st, _, got = c1.request("GET", f"/dbkt/{k}")
                        if st != 200 or got != body:
                            failures.append(f"GET {k}: {st}")
                except Exception as e:  # noqa: BLE001 - recorded
                    failures.append(f"{k}: {e}")
                i += 1
                time.sleep(0.05)

        t = threading.Thread(target=foreground)
        t.start()
        try:
            doc = _wait_status(c0, "decommission-status",
                               ("complete",), 120)
        finally:
            stop.set()
            t.join()
        assert doc["failed"] == 0, doc
        assert not failures, failures[:5]
        assert fg_bodies, "foreground loop never completed a PUT"
        # Ground truth: node 3's pool-1 drives hold nothing anymore.
        assert not any(_disk_holds(c, 3, 1, k) for k in bodies)

    # The operator removes the node: same drives, topology without
    # pool 1 or node 3. The persisted decom record names the drained
    # pool by SIGNATURE, so the shrunk boot ignores it cleanly.
    with Cluster(tmp_path, nodes=3, ports=ports[:3], pools=[2]) as c:
        cli = c.client(1)
        for k, b in {**bodies, **fg_bodies}.items():
            st, _, got = cli.request("GET", f"/dbkt/{k}")
            assert st == 200 and got == b, f"{k}: lost after removal"
        st, _, lst = cli.request("GET", "/dbkt",
                                 query={"max-keys": "1000"})
        assert st == 200
        for k in bodies:
            assert lst.count(f"<Key>{k}</Key>".encode()) == 1, k
