"""External-SDK conformance: drive a live server with boto3 (the Mint
analogue, SURVEY §4.5). Wire-level behaviors a hand-rolled client can't
catch — SDK header casing, XML namespace strictness, 100-continue,
checksum/retry behavior — surface here.

The whole module SKIPS when boto3 is absent (this CI image has no
network and no bundled SDK); any environment with boto3 runs it as-is.
"""

import os

import pytest

boto3 = pytest.importorskip("boto3")
from botocore.client import Config  # noqa: E402
from botocore.exceptions import ClientError  # noqa: E402

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("botodrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    server = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


@pytest.fixture(scope="module")
def s3(srv):
    return boto3.client(
        "s3", endpoint_url=f"http://{srv.address}",
        aws_access_key_id="minioadmin",
        aws_secret_access_key="minioadmin",
        region_name="us-east-1",
        config=Config(s3={"addressing_style": "path"},
                      retries={"max_attempts": 2}))


BUCKET = "sdkbkt"


def test_bucket_and_object_crud(s3):
    s3.create_bucket(Bucket=BUCKET)
    body = os.urandom(128_000)
    put = s3.put_object(Bucket=BUCKET, Key="crud/obj", Body=body,
                        ContentType="application/x-test",
                        Metadata={"owner": "sdk"})
    assert put["ResponseMetadata"]["HTTPStatusCode"] == 200
    got = s3.get_object(Bucket=BUCKET, Key="crud/obj")
    assert got["Body"].read() == body
    assert got["ContentType"] == "application/x-test"
    assert got["Metadata"].get("owner") == "sdk"
    head = s3.head_object(Bucket=BUCKET, Key="crud/obj")
    assert head["ContentLength"] == len(body)
    assert head["ETag"] == put["ETag"]
    s3.delete_object(Bucket=BUCKET, Key="crud/obj")
    with pytest.raises(ClientError) as ei:
        s3.head_object(Bucket=BUCKET, Key="crud/obj")
    assert ei.value.response["ResponseMetadata"]["HTTPStatusCode"] == 404


def test_ranged_get_and_conditional(s3):
    body = os.urandom(64_000)
    put = s3.put_object(Bucket=BUCKET, Key="ranged", Body=body)
    got = s3.get_object(Bucket=BUCKET, Key="ranged",
                        Range="bytes=1000-1999")
    assert got["Body"].read() == body[1000:2000]
    assert got["ResponseMetadata"]["HTTPStatusCode"] == 206
    got = s3.get_object(Bucket=BUCKET, Key="ranged",
                        IfMatch=put["ETag"])
    assert got["Body"].read() == body
    with pytest.raises(ClientError) as ei:
        s3.get_object(Bucket=BUCKET, Key="ranged", IfMatch='"nope"')
    assert ei.value.response["ResponseMetadata"]["HTTPStatusCode"] == 412


def test_multipart_upload(s3):
    mp = s3.create_multipart_upload(Bucket=BUCKET, Key="mp/big")
    uid = mp["UploadId"]
    parts = []
    chunks = [os.urandom(5 << 20), os.urandom(5 << 20), os.urandom(3000)]
    for i, c in enumerate(chunks, start=1):
        r = s3.upload_part(Bucket=BUCKET, Key="mp/big", UploadId=uid,
                           PartNumber=i, Body=c)
        parts.append({"PartNumber": i, "ETag": r["ETag"]})
    done = s3.complete_multipart_upload(
        Bucket=BUCKET, Key="mp/big", UploadId=uid,
        MultipartUpload={"Parts": parts})
    assert done["ETag"].strip('"').endswith("-3")
    got = s3.get_object(Bucket=BUCKET, Key="mp/big")
    assert got["Body"].read() == b"".join(chunks)


def test_presigned_urls(s3):
    import urllib.request
    body = b"presigned payload"
    s3.put_object(Bucket=BUCKET, Key="pres", Body=body)
    url = s3.generate_presigned_url(
        "get_object", Params={"Bucket": BUCKET, "Key": "pres"},
        ExpiresIn=300)
    with urllib.request.urlopen(url) as r:
        assert r.read() == body


def test_tagging_and_copy(s3):
    s3.put_object(Bucket=BUCKET, Key="src", Body=b"copy me",
                  Tagging="team=eng&tier=gold")
    tags = s3.get_object_tagging(Bucket=BUCKET, Key="src")
    assert {t["Key"]: t["Value"] for t in tags["TagSet"]} == \
        {"team": "eng", "tier": "gold"}
    s3.copy_object(Bucket=BUCKET, Key="dst",
                   CopySource={"Bucket": BUCKET, "Key": "src"})
    assert s3.get_object(Bucket=BUCKET,
                         Key="dst")["Body"].read() == b"copy me"


def test_listing_pagination(s3):
    for i in range(12):
        s3.put_object(Bucket=BUCKET, Key=f"page/{i:03d}", Body=b"x")
    keys = []
    token = None
    while True:
        kw = {"Bucket": BUCKET, "Prefix": "page/", "MaxKeys": 5}
        if token:
            kw["ContinuationToken"] = token
        page = s3.list_objects_v2(**kw)
        keys.extend(o["Key"] for o in page.get("Contents", []))
        if not page.get("IsTruncated"):
            break
        token = page["NextContinuationToken"]
    assert keys == [f"page/{i:03d}" for i in range(12)]


def test_versioning_and_batch_delete(s3):
    s3.put_bucket_versioning(
        Bucket=BUCKET,
        VersioningConfiguration={"Status": "Enabled"})
    v1 = s3.put_object(Bucket=BUCKET, Key="ver", Body=b"one")
    v2 = s3.put_object(Bucket=BUCKET, Key="ver", Body=b"two")
    assert v1["VersionId"] != v2["VersionId"]
    got = s3.get_object(Bucket=BUCKET, Key="ver",
                        VersionId=v1["VersionId"])
    assert got["Body"].read() == b"one"
    listing = s3.list_object_versions(Bucket=BUCKET, Prefix="ver")
    assert len(listing.get("Versions", [])) == 2
    out = s3.delete_objects(Bucket=BUCKET, Delete={"Objects": [
        {"Key": "ver", "VersionId": v1["VersionId"]},
        {"Key": "ver", "VersionId": v2["VersionId"]}]})
    assert len(out.get("Deleted", [])) == 2 and not out.get("Errors")
