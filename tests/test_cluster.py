"""Fleet-scale robustness: grid chaos, per-peer breakers, cross-node
cache coherence, remote walk_scan listings, dsync lock liveness, and
the multi-node cluster chaos matrix (tests/cluster.py harness).

Fast tests run in-process (grid pairs, two-"node" coherence stacks) or
on small 3-node clusters; the 8-node matrix is @slow."""

import os
import threading
import time

import pytest

from minio_tpu.grid import GridClient, GridError, GridServer
from minio_tpu.grid import chaos as chaos_mod
from minio_tpu.grid.coherence import CLASS_LISTING, PeerCoherence
from minio_tpu.storage.local import LocalStorage
from minio_tpu.storage.meta import FileNotFoundErr
from minio_tpu.storage.remote import RemoteStorage, StorageRPCService
from tests.cluster import Cluster


# ---------------------------------------------------------------------------
# grid chaos injection (the harness's partition/delay/hang primitives)
# ---------------------------------------------------------------------------

@pytest.fixture
def chaos_file(tmp_path):
    """Arm MTPU_GRID_CHAOS for this process, yield the file path, and
    fully disarm afterwards (the module gate is process-global)."""
    path = tmp_path / "chaos.json"
    old = os.environ.get(chaos_mod.ENV)
    os.environ[chaos_mod.ENV] = str(path)
    chaos_mod._reset_for_tests()
    try:
        yield path
    finally:
        if old is None:
            os.environ.pop(chaos_mod.ENV, None)
        else:
            os.environ[chaos_mod.ENV] = old
        chaos_mod._reset_for_tests()


def _wait_chaos():
    time.sleep(chaos_mod._POLL_S + 0.02)


def test_grid_chaos_modes(chaos_file):
    srv = GridServer(0, host="127.0.0.1")
    srv.register("echo", lambda p: p)
    srv.start()
    c = GridClient("127.0.0.1", srv.port, send_retries=0, trip_after=1000)
    try:
        assert c.call("echo", 1) == 1
        # Blackhole: connects/sends/accepts fail -> fast GridError.
        chaos_file.write_text('{"mode": "blackhole"}')
        _wait_chaos()
        with pytest.raises(GridError):
            c.call("echo", 2, timeout=1.0)
        # Drop: request frames vanish silently -> caller times out.
        chaos_file.write_text('{"mode": "drop"}')
        _wait_chaos()
        t0 = time.monotonic()
        with pytest.raises(GridError):
            c.call("echo", 3, timeout=0.5)
        assert time.monotonic() - t0 >= 0.4   # timed out, not refused
        # Delay: frames pay the configured jitter.
        chaos_file.write_text('{"mode": "delay", "seconds": 0.15}')
        _wait_chaos()
        t0 = time.monotonic()
        assert c.call("echo", 4, timeout=5.0) == 4
        assert time.monotonic() - t0 >= 0.15
        # Cleared: back to healthy.
        chaos_file.write_text("{}")
        _wait_chaos()
        assert c.call("echo", 5) == 5
    finally:
        c.close()
        srv.stop()


def test_chaos_drive_delay_hangs_remote_rpc(chaos_file, tmp_path):
    local = LocalStorage(str(tmp_path / "drv"))
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({local.root: local}).register_into(srv)
    srv.start()
    rem = RemoteStorage("127.0.0.1", srv.port, local.root)
    try:
        rem.make_vol("v")
        chaos_file.write_text('{"drive_delay": 0.3}')
        _wait_chaos()
        t0 = time.monotonic()
        rem.write_all("v", "k", b"x")
        assert time.monotonic() - t0 >= 0.3
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# per-peer circuit breaker
# ---------------------------------------------------------------------------

def test_grid_breaker_opens_and_fails_fast():
    """A dead peer costs one fast failure per call once the breaker
    opens, instead of a connect attempt per call; a returning peer
    closes it via the half-open probe."""
    # Nothing listens here; connects fail (refused) immediately.
    probe = GridServer(0, host="127.0.0.1")
    probe.start()
    port = probe.port
    probe.stop()
    time.sleep(0.05)
    c = GridClient("127.0.0.1", port, send_retries=2,
                   trip_after=3, cooldown=0.2, cooldown_max=1.0)
    with pytest.raises(GridError):
        c.call("echo", 1, timeout=1.0)      # 3 attempts = 3 faults
    assert c.breaker_state() == "open"
    t0 = time.monotonic()
    with pytest.raises(GridError) as ei:
        c.call("echo", 2, timeout=1.0)
    assert time.monotonic() - t0 < 0.05     # no connect attempt at all
    assert "circuit open" in str(ei.value)
    st = c.stats()
    assert st["state"] == "open" and st["rpc_errors"] >= 3
    assert st["breaker_opens"] == 1
    # Peer returns: after the cooldown one probe call reconnects.
    srv = GridServer(port, host="127.0.0.1")
    srv.register("echo", lambda p: p)
    srv.start()
    try:
        deadline = time.monotonic() + 10
        while True:
            try:
                assert c.call("echo", 3, timeout=2.0) == 3
                break
            except GridError:
                assert time.monotonic() < deadline
                time.sleep(0.1)
        assert c.breaker_state() == "closed"
        assert c.stats()["reconnects"] >= 1
    finally:
        c.close()
        srv.stop()


def test_remote_handler_errors_never_trip_breaker():
    srv = GridServer(0, host="127.0.0.1")

    def boom(p):
        raise FileNotFoundErr("nope")
    srv.register("boom", boom)
    srv.start()
    c = GridClient("127.0.0.1", srv.port, trip_after=2)
    try:
        from minio_tpu.grid import RemoteCallError
        for _ in range(6):
            with pytest.raises(RemoteCallError):
                c.call("boom")
        assert c.breaker_state() == "closed"
    finally:
        c.close()
        srv.stop()


# ---------------------------------------------------------------------------
# peer-notify observability (satellite: no more invisible swallows)
# ---------------------------------------------------------------------------

def test_peer_notifier_counts_and_logs_failures():
    from minio_tpu.grid import peers as peers_mod
    from minio_tpu.grid.peers import PeerNotifier, RELOAD_HANDLER, \
        make_reload_handler
    from minio_tpu.utils import tracing

    srv = GridServer(0, host="127.0.0.1")
    srv.register(RELOAD_HANDLER, make_reload_handler())
    srv.start()
    try:
        before = peers_mod.notify_stats()
        live = GridClient("127.0.0.1", srv.port)
        dead = GridClient("127.0.0.1", 1, send_retries=0)
        n = PeerNotifier([live, dead], timeout=1.0)
        n.broadcast("iam")
        after = peers_mod.notify_stats()
        assert after["sent"] == before["sent"] + 1
        assert after["failed"] == before["failed"] + 1
        recent = [r for r in tracing.slow_ops()
                  if r.get("name") == "peer.notify-failed"]
        assert recent and recent[-1]["tags"]["peer"].endswith(":1")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# coherence protocol: generation-validated cross-node invalidation
# ---------------------------------------------------------------------------

def _coherence_pair():
    """Two nodes' coherence stacks wired over real grid sockets."""
    srv_a = GridServer(0, host="127.0.0.1")
    srv_b = GridServer(0, host="127.0.0.1")
    srv_a.start()
    srv_b.start()
    c_ab = GridClient("127.0.0.1", srv_b.port, send_retries=0)
    c_ba = GridClient("127.0.0.1", srv_a.port, send_retries=0)
    inv_a, inv_b = [], []
    coh_a = PeerCoherence("A", {"B": c_ab},
                          on_invalidate=lambda b, c: inv_a.append((b, c)))
    coh_b = PeerCoherence("B", {"A": c_ba},
                          on_invalidate=lambda b, c: inv_b.append((b, c)))
    coh_a.register_into(srv_a)
    coh_b.register_into(srv_b)
    return (srv_a, srv_b, c_ab, c_ba, coh_a, coh_b, inv_a, inv_b)


def test_coherence_push_resync_and_rearm():
    srv_a, srv_b, c_ab, c_ba, coh_a, coh_b, inv_a, inv_b = \
        _coherence_pair()
    try:
        # Disarmed until the first resync proves generation state.
        assert not coh_a.coherent() and not coh_b.coherent()
        assert coh_a.resync("B") and coh_b.resync("A")
        assert coh_a.coherent() and coh_b.coherent()

        # Push: a mutation on A reaches B acked, B applies it.
        coh_a.broadcast("bkt", CLASS_LISTING)
        assert ("bkt", CLASS_LISTING) in inv_b
        assert coh_a.stats()["inv_sent"] == 1
        assert coh_a.stats()["inv_failed"] == 0

        # Missed-push recovery: A mutates while B cannot be reached;
        # B's resync finds the advanced generation and re-invalidates.
        real_call = c_ab.call
        c_ab.call = lambda *a, **kw: (_ for _ in ()).throw(
            GridError("partitioned"))
        coh_a.broadcast("bkt", CLASS_LISTING)     # escalates
        assert coh_a.stats()["inv_failed"] == 1
        assert coh_a.stats()["escalations"] == 1
        n_before = len(inv_b)
        coh_b._disarm("A")
        assert not coh_b.coherent()
        assert coh_b.resync("A")                  # pull recovers the gap
        assert len(inv_b) == n_before + 1
        assert coh_b.coherent()
        c_ab.call = real_call

        # No change -> resync invalidates nothing.
        n_before = len(inv_b)
        assert coh_b.resync("A")
        assert len(inv_b) == n_before
    finally:
        c_ab.close()
        c_ba.close()
        srv_a.stop()
        srv_b.stop()


def test_coherence_conn_loss_disarms():
    srv_a, srv_b, c_ab, c_ba, coh_a, coh_b, inv_a, inv_b = \
        _coherence_pair()
    try:
        assert coh_b.resync("A")
        assert coh_b.coherent()
        # A live connection to the peer dying disarms immediately.
        assert c_ba.ping()
        srv_a.stop()
        deadline = time.monotonic() + 5
        while coh_b.coherent() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not coh_b.coherent()
    finally:
        c_ab.close()
        c_ba.close()
        srv_b.stop()


def test_fi_cache_remote_gate_blocks_serving():
    from minio_tpu.object.fi_cache import FileInfoCache

    class FI:
        erasure = type("E", (), {"data_blocks": 0})()
        inline_data = b""

    cache = FileInfoCache(enabled=True)
    tok = cache.token("b")
    cache.put("b", "o", "", FI(), [], read_data=True, token=tok)
    assert cache.get("b", "o", "", need_data=False) is not None
    gate_up = [False]
    cache.remote_gate = lambda: gate_up[0]
    assert cache.get("b", "o", "", need_data=False) is None
    assert cache.get_stat("b", "o", "") is None
    gate_up[0] = True
    assert cache.get("b", "o", "", need_data=False) is not None


def test_metacache_remote_gate_bypasses_cached_walks(tmp_path):
    from minio_tpu.object.erasure_object import ErasureSet
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    try:
        es.make_bucket("b")
        es.put_object("b", "k1", b"x" * 1024)
        mc = es.metacache
        assert [o.name for o in es.list_objects("b").objects] == ["k1"]
        hits0 = mc.stats()["hits"]
        es.list_objects("b")
        assert mc.stats()["hits"] == hits0 + 1      # cached walk reused
        gate_up = [False]
        mc.remote_gate = lambda: gate_up[0]
        started0 = mc.stats()["walks_started"]
        es.list_objects("b")                        # incoherent: re-walk
        assert mc.stats()["walks_started"] == started0 + 1
        gate_up[0] = True
        es.list_objects("b")
        es.list_objects("b")                        # coherent: cached again
        assert mc.stats()["hits"] > hits0 + 1
    finally:
        es.close()


# ---------------------------------------------------------------------------
# two-node in-process stack: remote sets with COHERENT caches ON
# ---------------------------------------------------------------------------

def _two_node_stack(tmp_path):
    """Two 'nodes' sharing one 4-drive erasure layout: each node sees
    its own 2 drives locally and the sibling's 2 over the grid, each
    runs its own metacache/fi_cache wired into a PeerCoherence pair —
    the in-process twin of a 2-node cluster's cache plane."""
    from minio_tpu.object.erasure_object import ErasureSet

    drives = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    srv_a = GridServer(0, host="127.0.0.1")   # node A serves d0, d1
    srv_b = GridServer(0, host="127.0.0.1")   # node B serves d2, d3
    StorageRPCService({d.root: d for d in drives[:2]}).register_into(srv_a)
    StorageRPCService({d.root: d for d in drives[2:]}).register_into(srv_b)
    srv_a.start()
    srv_b.start()

    es_a = ErasureSet([drives[0], drives[1],
                       RemoteStorage("127.0.0.1", srv_b.port,
                                     drives[2].root),
                       RemoteStorage("127.0.0.1", srv_b.port,
                                     drives[3].root)])
    es_b = ErasureSet([RemoteStorage("127.0.0.1", srv_a.port,
                                     drives[0].root),
                       RemoteStorage("127.0.0.1", srv_a.port,
                                     drives[1].root),
                       drives[2], drives[3]])
    # Remote sets start with the deny-all gate (no protocol): the old
    # `enabled = False` branch is gone, replaced by the gate.
    assert es_a.fi_cache.enabled and es_b.fi_cache.enabled
    assert es_a.fi_cache.remote_gate() is False

    c_ab = GridClient("127.0.0.1", srv_b.port, send_retries=0)
    c_ba = GridClient("127.0.0.1", srv_a.port, send_retries=0)

    from minio_tpu.grid.coherence import make_set_invalidator
    coh_a = PeerCoherence("A", {"B": c_ab},
                          on_invalidate=make_set_invalidator([es_a]))
    coh_b = PeerCoherence("B", {"A": c_ba},
                          on_invalidate=make_set_invalidator([es_b]))
    coh_a.register_into(srv_a)
    coh_b.register_into(srv_b)
    for es, coh in ((es_a, coh_a), (es_b, coh_b)):
        es.metacache.on_bump = \
            lambda bucket, coh=coh: coh.broadcast(bucket, CLASS_LISTING)
        es.metacache.bump_coalesce = 0.0     # synchronous acked pushes
        es.fi_cache.remote_gate = coh.coherent
        es.metacache.remote_gate = coh.coherent
    assert coh_a.resync("B") and coh_b.resync("A")
    return {"drives": drives, "servers": (srv_a, srv_b),
            "clients": (c_ab, c_ba), "sets": (es_a, es_b),
            "coh": (coh_a, coh_b)}


def _teardown_stack(stack):
    for es in stack["sets"]:
        es.close()
    for c in stack["clients"]:
        c.close()
    for s in stack["servers"]:
        s.stop()


def test_cross_node_overwrite_invalidates_sibling_fi_cache(tmp_path):
    """THE remote-set coherence claim: fi_cache is ON on both nodes'
    remote sets, repeat GETs hit, and an overwrite through node A
    invalidates node B's cached entry before A's PUT returns."""
    stack = _two_node_stack(tmp_path)
    es_a, es_b = stack["sets"]
    try:
        es_a.make_bucket("bkt")
        v1 = os.urandom(256 << 10)
        es_a.put_object("bkt", "obj", v1)

        _, got = es_b.get_object("bkt", "obj")
        assert got == v1
        hits0 = es_b.fi_cache.stats()["hits"]
        _, got = es_b.get_object("bkt", "obj")
        assert got == v1
        assert es_b.fi_cache.stats()["hits"] > hits0, \
            "repeat GET on a coherent remote set must be a cache hit"

        # Cross-node overwrite: A's PUT broadcasts the acked
        # invalidation inside the PUT, so by return B holds nothing.
        v2 = os.urandom(256 << 10)
        es_a.put_object("bkt", "obj", v2)
        assert es_b.fi_cache.get("bkt", "obj", "", need_data=False) is None
        _, got = es_b.get_object("bkt", "obj")
        assert got == v2

        # Listings too: B's walk streams were orphaned by the same
        # bump; a new key through A is visible on B immediately.
        assert [o.name for o in es_b.list_objects("bkt").objects] == ["obj"]
        es_a.put_object("bkt", "obj2", b"x" * 2048)
        names = [o.name for o in es_b.list_objects("bkt").objects]
        assert names == ["obj", "obj2"]
    finally:
        _teardown_stack(stack)


def test_partitioned_then_rejoined_node_serves_zero_stale(tmp_path):
    """The staleness probe: B caches an entry, the coherence plane
    partitions, A overwrites (push escalates), and B must answer
    misses — never the stale hit — until its rejoin resync re-arms."""
    stack = _two_node_stack(tmp_path)
    es_a, es_b = stack["sets"]
    c_ab, c_ba = stack["clients"]
    coh_a, coh_b = stack["coh"]
    try:
        es_a.make_bucket("bkt")
        v1 = os.urandom(128 << 10)
        es_a.put_object("bkt", "obj", v1)
        _, got = es_b.get_object("bkt", "obj")
        assert got == v1
        assert es_b.fi_cache.get("bkt", "obj", "", need_data=False) \
            is not None

        # Partition the coherence plane both ways (the data-plane
        # drive clients stay up: we are probing CACHE staleness, so B
        # must be able to read the truth yet must not serve the cache).
        def dead(*a, **kw):
            raise GridError("partitioned")
        real_ab, real_ba = c_ab.call, c_ba.call
        c_ab.call = dead
        c_ba.call = dead
        assert not coh_b.resync("A")          # B notices: disarmed
        assert not coh_b.coherent()

        v2 = os.urandom(128 << 10)
        es_a.put_object("bkt", "obj", v2)     # push to B escalates
        assert coh_a.stats()["inv_failed"] >= 1

        # B's cached (now stale) entry exists physically but the gate
        # refuses to serve it; the read comes from the drives = v2.
        assert es_b.fi_cache.get("bkt", "obj", "", need_data=False) is None
        _, got = es_b.get_object("bkt", "obj")
        assert got == v2

        # Rejoin: resync sees A's advanced generation, invalidates,
        # re-arms — and the caches work again (fresh entries, hits).
        c_ab.call, c_ba.call = real_ab, real_ba
        assert coh_b.resync("A")
        assert coh_b.coherent()
        assert es_b.fi_cache.get("bkt", "obj", "", need_data=False) is None
        _, got = es_b.get_object("bkt", "obj")
        assert got == v2
        hits0 = es_b.fi_cache.stats()["hits"]
        _, got = es_b.get_object("bkt", "obj")
        assert got == v2 and es_b.fi_cache.stats()["hits"] > hits0
    finally:
        _teardown_stack(stack)


# ---------------------------------------------------------------------------
# remote walk_scan: trimmed summaries over the grid
# ---------------------------------------------------------------------------

def _fixture_set(tmp_path, n=4):
    from minio_tpu.object.erasure_object import ErasureSet
    drives = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(n)]
    es = ErasureSet(drives)
    es.make_bucket("wb")
    keys = ["a/x", "a/y/deep", "b", "c/1", "c/2", "zz"]
    for i, k in enumerate(keys):
        es.put_object("wb", k, bytes([i]) * (1024 + i))
    es.put_object("wb", "a/x", b"overwritten" * 100)   # newer version
    return drives, es, sorted(keys)


def test_remote_walk_scan_identical_to_local(tmp_path):
    drives, es, keys = _fixture_set(tmp_path)
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({d.root: d for d in drives}).register_into(srv)
    srv.start()
    try:
        rem = RemoteStorage("127.0.0.1", srv.port, drives[0].root)
        local_walk = list(drives[0].walk_scan("wb"))
        remote_walk = list(rem.walk_scan("wb"))
        assert [(p, v, b) for p, v, b in local_walk] == \
            [(p, v, b) for p, v, b in remote_walk]
        # Shallow (delimiter) walks round-trip the PREFIX_MARK sentinel
        # by IDENTITY (the resolver tests `is PREFIX_MARK`).
        from minio_tpu.storage.meta_scan import PREFIX_MARK
        local_sh = list(drives[0].walk_scan("wb", shallow=True))
        remote_sh = list(rem.walk_scan("wb", shallow=True))
        assert local_sh == remote_sh
        assert any(v is PREFIX_MARK for _, v, _ in remote_sh)
    finally:
        es.close()
        srv.stop()


def test_distributed_listing_byte_identical(tmp_path, monkeypatch):
    """A remote-drive set's listing — riding walk_scan trimmed
    summaries over the grid — is identical to (a) the same namespace
    listed over local drives and (b) the full-journal walk_dir path."""
    from minio_tpu.object.erasure_object import ErasureSet
    drives, es, keys = _fixture_set(tmp_path)
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({d.root: d for d in drives}).register_into(srv)
    srv.start()
    es_r = ErasureSet([RemoteStorage("127.0.0.1", srv.port, d.root)
                       for d in drives])

    def snap(listing):
        return [(o.name, o.etag, o.size, o.version_id, o.delete_marker)
                for o in listing.objects], sorted(listing.prefixes)

    shapes = ({}, {"prefix": "a/"}, {"delimiter": "/"},
              {"prefix": "c/", "delimiter": "/"},
              {"include_versions": True}, {"max_keys": 3})
    es_j = ErasureSet([RemoteStorage("127.0.0.1", srv.port, d.root)
                       for d in drives])
    try:
        trimmed = {}
        for i, kwargs in enumerate(shapes):
            local = snap(es.list_objects("wb", **kwargs))
            trimmed[i] = snap(es_r.list_objects("wb", **kwargs))
            assert trimmed[i] == local, f"listing differs for {kwargs}"
        # And against the legacy full-journal stream: hide walk_scan so
        # remote drives fall back to walk_dir's raw xl.meta journals.
        monkeypatch.delattr(RemoteStorage, "walk_scan")
        for i, kwargs in enumerate(shapes):
            journal = snap(es_j.list_objects("wb", **kwargs))
            assert trimmed[i] == journal, \
                f"trimmed vs full-journal differs for {kwargs}"
    finally:
        es.close()
        es_r.close()
        es_j.close()
        srv.stop()


# ---------------------------------------------------------------------------
# dsync: lock-holder liveness
# ---------------------------------------------------------------------------

def test_leaked_write_lock_unblocks_within_ttl():
    """The namespace-wedge regression: a holder that dies without
    unlocking (SIGKILL shape: refresh stops, entries linger) no longer
    wedges writers — they proceed within the TTL window."""
    from minio_tpu.grid.dsync import DRWMutex, LocalLocker, LockServer

    servers = [LockServer(ttl=0.4) for _ in range(3)]
    lks = [LocalLocker(s) for s in servers]
    holder = DRWMutex(lks, "bkt/obj")
    assert holder.lock(write=True, timeout=2)
    holder._stop_refresh.set()           # the crash: no refresh, no unlock

    blocked = DRWMutex(lks, "bkt/obj")
    t0 = time.monotonic()
    assert blocked.lock(write=True, timeout=5)
    waited = time.monotonic() - t0
    blocked.unlock()
    assert waited < 2.0, f"writer waited {waited:.2f}s, TTL is 0.4s"
    assert sum(s.stats()["expired_total"] for s in servers) >= 1


def test_lock_ttl_env_knobs(monkeypatch):
    import importlib

    from minio_tpu.grid import dsync as dsync_mod
    monkeypatch.setenv("MTPU_GRID_LOCK_TTL", "9.0")
    monkeypatch.setenv("MTPU_GRID_LOCK_REFRESH", "100.0")
    mod = importlib.reload(dsync_mod)
    try:
        assert mod.LOCK_TTL == 9.0
        assert mod.REFRESH_INTERVAL == 3.0     # clamped to TTL/3
        assert mod.LockServer().ttl == 9.0
    finally:
        monkeypatch.delenv("MTPU_GRID_LOCK_TTL")
        monkeypatch.delenv("MTPU_GRID_LOCK_REFRESH")
        importlib.reload(mod)


# ---------------------------------------------------------------------------
# multi-process cluster chaos matrix (tests/cluster.py harness)
# ---------------------------------------------------------------------------

def _put_retry(cli, path, body, deadline_s=45):
    deadline = time.time() + deadline_s
    while True:
        try:
            st, _, b = cli.request("PUT", path, body=body)
        except Exception as e:  # noqa: BLE001 - conn reset mid-failover
            st, b = 0, str(e).encode()
        if st == 200:
            return
        assert time.time() < deadline, f"PUT {path}: {st} {b[:300]}"
        time.sleep(1)


def test_cluster_kill_in_and_out_of_write_quorum(tmp_path):
    """3 nodes x 2 drives (EC 3+3, write quorum 4): one node down is
    IN write quorum (PUTs succeed), two nodes down is OUT (honest 503
    SlowDownWrite, and fast — the peer breaker fails the dead nodes'
    drives in microseconds, not a connect timeout per shard)."""
    with Cluster(tmp_path, nodes=3, drives_per_node=2) as cluster:
        c0 = cluster.client(0)
        assert c0.request("PUT", "/qbkt")[0] == 200
        v1 = os.urandom(1 << 20)
        _put_retry(c0, "/qbkt/obj1", v1)

        cluster.kill(2)
        v2 = os.urandom(1 << 20)
        _put_retry(c0, "/qbkt/obj2", v2)         # in quorum: succeeds
        st, _, got = cluster.client(1).request("GET", "/qbkt/obj2")
        assert st == 200 and got == v2

        cluster.kill(1)                          # 2 of 6 drives left
        deadline = time.time() + 45
        while True:
            st, _, b = c0.request("PUT", "/qbkt/obj3", body=b"x" * 4096)
            if st == 503:
                break
            assert time.time() < deadline, f"want 503, got {st}"
            time.sleep(1)
        assert b"reduce your request rate" in b or b"SlowDown" in b, b
        # Fail FAST: breakers are open by now.
        t0 = time.time()
        st, _, _ = c0.request("PUT", "/qbkt/obj3", body=b"x" * 4096)
        assert st == 503
        assert time.time() - t0 < 10.0
        # Reads of quorum-readable data still work (obj1 has 2 shards
        # on node0 + reconstruct is impossible at 2/6 — honest 503 too).
        st, _, _ = c0.request("GET", "/qbkt/obj1")
        assert st in (200, 503)


def test_cluster_partition_rejoin_no_stale_reads(tmp_path):
    """Partition a node's grid plane, overwrite through the healthy
    side, rejoin: the rejoined node must never answer the old bytes,
    and must see keys written during the partition."""
    with Cluster(tmp_path, nodes=3, drives_per_node=2) as cluster:
        c0 = cluster.client(0)
        c2 = cluster.client(2)
        assert c0.request("PUT", "/pbkt")[0] == 200
        v1 = os.urandom(512 << 10)
        _put_retry(c0, "/pbkt/obj", v1)
        # Warm node2's caches (repeat GET = fi_cache hit path).
        for _ in range(2):
            st, _, got = c2.request("GET", "/pbkt/obj")
            assert st == 200 and got == v1
        st, _, _ = c2.request("GET", "/pbkt")
        assert st == 200

        cluster.partition(2)
        time.sleep(1.0)          # > chaos poll + sync interval (0.5 s)
        v2 = os.urandom(512 << 10)
        _put_retry(c0, "/pbkt/obj", v2)          # 4/6 drives: quorum
        _put_retry(c0, "/pbkt/during", b"y" * 4096)

        # The partitioned node must not serve the stale cache: its
        # coherence gate is down, so either an honest error (no read
        # quorum from its 2 local drives) or — never — v1.
        st, _, got = c2.request("GET", "/pbkt/obj")
        assert not (st == 200 and got == v1), "stale read served"

        cluster.rejoin(2)
        deadline = time.time() + 45
        while True:
            st, _, got = c2.request("GET", "/pbkt/obj")
            if st == 200 and got == v2:
                break
            assert not (st == 200 and got == v1), "stale read after rejoin"
            assert time.time() < deadline, f"rejoin GET: {st}"
            time.sleep(1)
        # Listing on the rejoined node sees the partition-era write.
        deadline = time.time() + 30
        while True:
            st, _, body = c2.request("GET", "/pbkt")
            if st == 200 and b"during" in body:
                break
            assert time.time() < deadline, f"listing stale: {st} {body[:200]}"
            time.sleep(1)


@pytest.mark.slow
def test_cluster_dsync_lock_expires_after_holder_sigkill(tmp_path):
    """A SIGKILLed node's in-flight PUT leaks its dsync write lock on
    the surviving lock servers; a writer of the same key proceeds
    within the TTL window instead of wedging."""
    with Cluster(tmp_path, nodes=3, drives_per_node=2,
                 env={"MTPU_GRID_LOCK_TTL": "4"}) as cluster:
        c0 = cluster.client(0)
        assert c0.request("PUT", "/lbkt")[0] == 200
        _put_retry(c0, "/lbkt/obj", b"seed" * 1024)

        # Node2's PUT hangs mid-write (peers' drives answer slowly),
        # holding the distributed write lock; SIGKILL leaks it.
        cluster.hang_drives(0, 6.0)
        cluster.hang_drives(1, 6.0)
        time.sleep(0.2)

        def doomed():
            try:
                cluster.client(2, timeout=30).request(
                    "PUT", "/lbkt/obj", body=os.urandom(1 << 20))
            except Exception:  # noqa: BLE001 - node dies mid-request
                pass
        t = threading.Thread(target=doomed, daemon=True)
        t.start()
        time.sleep(1.5)                  # lock acquired, writes hanging
        cluster.kill(2)
        cluster.rejoin(0)
        cluster.rejoin(1)

        t0 = time.time()
        _put_retry(c0, "/lbkt/obj", b"after" * 1024, deadline_s=40)
        waited = time.time() - t0
        assert waited < 30, f"writer waited {waited:.1f}s past the leak"
        st, _, got = cluster.client(1).request("GET", "/lbkt/obj")
        assert st == 200 and got == b"after" * 1024


@pytest.mark.slow
def test_cluster_8_node_chaos_matrix(tmp_path):
    """The acceptance matrix at 8 nodes x 8 drives (EC 4+4): single
    node killed -> writes succeed; listing via a sibling is complete;
    partition-then-rejoin serves no stale bytes; 4 nodes dead -> out
    of write quorum -> honest fast 503s."""
    with Cluster(tmp_path, nodes=8, drives_per_node=1) as cluster:
        c0 = cluster.client(0)
        assert c0.request("PUT", "/mbkt")[0] == 200
        keys = {}
        for i in range(6):
            keys[f"k{i}"] = os.urandom(128 << 10)
            _put_retry(c0, f"/mbkt/k{i}", keys[f"k{i}"])

        # Cross-node reads + complete listing through a sibling.
        c3 = cluster.client(3)
        st, _, got = c3.request("GET", "/mbkt/k0")
        assert st == 200 and got == keys["k0"]
        st, _, body = c3.request("GET", "/mbkt")
        assert st == 200
        for k in keys:
            assert k.encode() in body

        # Kill one node: still in write quorum (7 >= 5).
        cluster.kill(7)
        v = os.urandom(128 << 10)
        _put_retry(c0, "/mbkt/k0", v)
        keys["k0"] = v
        st, _, got = c3.request("GET", "/mbkt/k0")
        assert st == 200 and got == v

        # Partition node 6, overwrite through node 0, rejoin: no stale.
        c6 = cluster.client(6)
        for _ in range(2):
            st, _, got = c6.request("GET", "/mbkt/k1")
            assert st == 200 and got == keys["k1"]
        cluster.partition(6)
        time.sleep(1.0)
        v = os.urandom(128 << 10)
        _put_retry(c0, "/mbkt/k1", v)
        st, _, got = c6.request("GET", "/mbkt/k1")
        assert not (st == 200 and got == keys["k1"]), "stale read"
        keys["k1"] = v
        cluster.rejoin(6)
        deadline = time.time() + 45
        while True:
            st, _, got = c6.request("GET", "/mbkt/k1")
            if st == 200 and got == v:
                break
            assert not (st == 200 and got != v), "stale read after rejoin"
            assert time.time() < deadline
            time.sleep(1)

        # Out of write quorum: 4 alive < 5 -> honest, fast 503s.
        for i in (4, 5, 6):
            cluster.kill(i)
        deadline = time.time() + 45
        while True:
            st, _, b = c0.request("PUT", "/mbkt/kx", body=b"x" * 4096)
            if st == 503:
                break
            assert time.time() < deadline, f"want 503, got {st}"
            time.sleep(1)
        t0 = time.time()
        st, _, _ = c0.request("PUT", "/mbkt/kx", body=b"x" * 4096)
        assert st == 503 and time.time() - t0 < 10.0


# ---------------------------------------------------------------------------
# N x M topology: distributed nodes x pre-forked workers
# ---------------------------------------------------------------------------

def _get_retry(cli, path, want, deadline_s=45):
    deadline = time.time() + deadline_s
    while True:
        try:
            st, _, got = cli.request("GET", path)
        except Exception as e:  # noqa: BLE001 - conn reset mid-failover
            st, got = 0, str(e).encode()
        if st == 200 and got == want:
            return
        assert time.time() < deadline, f"GET {path}: {st}"
        time.sleep(0.5)


def test_cluster_workers_topology_e2e(tmp_path):
    """2 nodes x 2 drives x 2 workers: worker 0 owns each node's grid
    plane, siblings reach it over loopback. Cross-node reads, the
    published coherence state file, sibling-worker respawn and
    grid-owner (worker 0) respawn all keep serving."""
    with Cluster(tmp_path, nodes=2, drives_per_node=2,
                 workers=2) as cluster:
        # Both workers forked per node.
        for i in range(2):
            assert len(cluster.worker_pids(i)) == 2, cluster.logs(i)[-1500:]
        c0 = cluster.client(0)
        assert c0.request("PUT", "/wbkt")[0] == 200
        data = os.urandom(2 << 20)
        _put_retry(c0, "/wbkt/obj", data)
        # Cross-node read: node 1 pulls node 0's shards over the grid.
        st, _, got = cluster.client(1).request("GET", "/wbkt/obj")
        assert st == 200 and got == data
        st, _, body = cluster.client(1).request("GET", "/wbkt")
        assert st == 200 and b"<Key>obj</Key>" in body

        # Worker 0 publishes the coherence gate state file siblings
        # poll (FileGate) under a drive's system area.
        states = [os.path.join(cluster.drive_dir(i, d), ".mtpu.sys",
                               "workers", "coherence.state")
                  for i in range(2) for d in range(2)]
        assert any(os.path.exists(p) for p in states), states

        # SIGKILL a sibling worker: the pool respawns it; service
        # never needs the restart (the other worker keeps accepting).
        kids = cluster.worker_pids(0)
        os.kill(kids[1], 9)
        deadline = time.time() + 30
        while len(cluster.worker_pids(0)) < 2:
            assert time.time() < deadline, "sibling worker not respawned"
            time.sleep(0.5)
        _get_retry(c0, "/wbkt/obj", data)

        # SIGKILL worker 0 (the GRID OWNER) on node 1: the respawned
        # worker re-binds the node's grid port with a fresh boot
        # instance id; cross-node reads recover (peers resync).
        kids = cluster.worker_pids(1)
        os.kill(kids[0], 9)
        deadline = time.time() + 30
        while len(cluster.worker_pids(1)) < 2:
            assert time.time() < deadline, "worker 0 not respawned"
            time.sleep(0.5)
        _get_retry(c0, "/wbkt/obj", data)
        _get_retry(cluster.client(1), "/wbkt/obj", data)


def test_cluster_workers_sibling_no_stale_reads(tmp_path):
    """Overwrite through node 0, then hammer node 1 with fresh
    connections (SO_REUSEPORT sprays them across BOTH workers): no
    request — whichever worker serves it — may answer the old bytes.
    Sibling workers learn of the remote write via the worker-0 relay
    (gen.relay + shared generation files), not their own grid plane."""
    with Cluster(tmp_path, nodes=2, drives_per_node=2,
                 workers=2) as cluster:
        c0 = cluster.client(0)
        assert c0.request("PUT", "/sbkt")[0] == 200
        v1 = os.urandom(256 << 10)
        _put_retry(c0, "/sbkt/obj", v1)
        # Warm every worker's caches on node 1 (fresh conn each time).
        for _ in range(8):
            st, _, got = cluster.client(1).request("GET", "/sbkt/obj")
            assert st == 200 and got == v1
        v2 = os.urandom(256 << 10)
        _put_retry(c0, "/sbkt/obj", v2)
        # Give the push-invalidation one sync tick (0.5 s in harness).
        time.sleep(1.5)
        for _ in range(12):
            st, _, got = cluster.client(1).request("GET", "/sbkt/obj")
            assert st == 200, st
            assert got != v1, "stale read from a sibling worker"
            assert got == v2


@pytest.mark.slow
def test_cluster_workers_chaos_matrix(tmp_path):
    """N x M chaos: (a) grid-owner worker respawn while cross-node
    GETs are in flight — the client-facing answer is always correct
    bytes or an honest error, never torn data; (b) partition during a
    bulk sendfile transfer — same guarantee, and after rejoin the
    object reads back byte-identical; (c) small RPCs stay live while
    a node's drives hang mid-bulk (mux fairness end to end)."""
    with Cluster(tmp_path, nodes=3, drives_per_node=2,
                 workers=2) as cluster:
        c0 = cluster.client(0, timeout=60)
        assert c0.request("PUT", "/xbkt")[0] == 200
        big = os.urandom(8 << 20)
        _put_retry(c0, "/xbkt/big", big)
        small = os.urandom(16 << 10)
        _put_retry(c0, "/xbkt/small", small)

        # (a) kill node 1's grid owner mid-stream, repeatedly GETting
        # through node 0 (whose erasure set spans node 1's drives).
        stop = threading.Event()
        errs: list = []

        def hammer():
            while not stop.is_set():
                try:
                    st, _, got = cluster.client(0, timeout=60).request(
                        "GET", "/xbkt/big")
                except Exception:  # noqa: BLE001 - conn reset is honest
                    continue
                if st == 200 and got != big:
                    errs.append(f"torn read: {len(got)} bytes")
                    return

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        time.sleep(0.5)
        kids = cluster.worker_pids(1)
        if kids:
            os.kill(kids[0], 9)          # grid owner, mid-transfer
        time.sleep(3.0)
        stop.set()
        t.join(timeout=90)
        assert not errs, errs
        deadline = time.time() + 30
        while len(cluster.worker_pids(1)) < 2:
            assert time.time() < deadline, "worker 0 not respawned"
            time.sleep(0.5)
        _get_retry(c0, "/xbkt/big", big, deadline_s=60)

        # (b) partition node 2 mid-bulk: in-flight GETs reconstruct
        # from the surviving shards or fail honestly; after rejoin the
        # bytes are identical.
        stop2 = threading.Event()
        errs2: list = []

        def hammer2():
            while not stop2.is_set():
                try:
                    st, _, got = cluster.client(0, timeout=60).request(
                        "GET", "/xbkt/big")
                except Exception:  # noqa: BLE001
                    continue
                if st == 200 and got != big:
                    errs2.append(f"torn read: {len(got)} bytes")
                    return

        t2 = threading.Thread(target=hammer2, daemon=True)
        t2.start()
        time.sleep(0.3)
        cluster.partition(2)
        time.sleep(3.0)
        stop2.set()
        t2.join(timeout=90)
        assert not errs2, errs2
        cluster.rejoin(2)
        _get_retry(c0, "/xbkt/big", big, deadline_s=60)

        # (c) hang node 2's remote-drive RPCs: bulk reads touching it
        # stall, but small unary traffic through node 0 keeps flowing
        # (the grid connection is multiplexed, not head-of-line
        # blocked behind the hung bulk stream).
        cluster.hang_drives(2, 20.0)
        time.sleep(1.0)
        bulk_done = threading.Event()

        def slow_bulk():
            try:
                cluster.client(1, timeout=60).request("GET", "/xbkt/big")
            except Exception:  # noqa: BLE001
                pass
            finally:
                bulk_done.set()

        tb = threading.Thread(target=slow_bulk, daemon=True)
        tb.start()
        time.sleep(0.5)
        lat = []
        for _ in range(5):
            t0 = time.time()
            st, _, got = c0.request("GET", "/xbkt/small")
            lat.append(time.time() - t0)
            assert st == 200 and got == small
        lat.sort()
        assert lat[len(lat) // 2] < 5.0, f"small GETs starved: {lat}"
        cluster.rejoin(2)
        bulk_done.wait(60)
