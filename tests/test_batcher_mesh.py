"""Device-resident cross-request stripe batching (ops/batcher.py +
ops/hh_device.make_mesh_framer): byte-identity of batched vs
solo-framed output across ragged tails and every padding bucket,
donation safety of the pooled staging lease, deadline-exhausted members
failing without poisoning batch-mates, the kernel span fanned into each
member's trace, the MTPU_BATCH_FORCE knob, and the mesh framer on a
virtual 8-device mesh."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from minio_tpu.io.bufpool import BufferPool
from minio_tpu.object.erasure_object import _host_rows
from minio_tpu.ops.batcher import _BUCKETS, StripeBatcher
from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import Deadline, DeadlineExceeded

K, M, SHARD = 8, 4, 4096


def _mk_window(b, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(b, K, SHARD), dtype=np.uint8)


def _rows_equal(a, b):
    assert len(a) == len(b)
    for da, db in zip(a, b):
        assert len(da) == len(db)
        for (ha, blka), (hb, blkb) in zip(da, db):
            assert np.array_equal(np.asarray(ha), np.asarray(hb))
            assert np.array_equal(np.asarray(blka), np.asarray(blkb))


class _RecordingDevice:
    """Fake device framer: host math, records every dispatched batch."""

    def __init__(self, mesh_devices=1, delay=0.0):
        self.batches = []
        self.mesh_devices = mesh_devices
        self.delay = delay
        self.in_flight_hook = None

    def __call__(self, stacked):
        self.batches.append(stacked.shape[0])
        if self.in_flight_hook is not None:
            self.in_flight_hook(stacked)
        if self.delay:
            time.sleep(self.delay)
        return _host_rows(K, M, stacked)


def _pinned(device_fn, pool=None, **kw):
    sb = StripeBatcher(device_fn, lambda s: _host_rows(K, M, s),
                       probe_fn=lambda: True, pool=pool, **kw)
    sb.force(True)
    return sb


def _coalesce(sb, windows, timeout=30):
    """Run the windows through sb.frame concurrently (with a dummy
    inflight so nobody sees itself solo); returns the results list."""
    results = [None] * len(windows)
    errors = [None] * len(windows)

    def worker(i):
        try:
            results[i] = sb.frame(windows[i])
        except BaseException as e:  # noqa: BLE001 - asserted by tests
            errors[i] = e

    with sb._mu:
        sb._inflight += 1
    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(windows))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)
    finally:
        with sb._mu:
            sb._inflight -= 1
    return results, errors


def test_batched_output_byte_identical_across_ragged_members():
    """Coalesced windows of UNEVEN sizes (ragged tails riding full
    windows) demultiplex to exactly the bytes each member would get
    solo-framed — incl. the data-drive views re-pointed at each
    member's own window after the staging lease returns."""
    dev = _RecordingDevice()
    pool = BufferPool(max_per_class=4)
    # Wide window: all five threads must enqueue into ONE batch even on
    # a loaded CI box (a split batch would route a sub-minimum tail to
    # the host codec, which is not what this test asserts).
    sb = _pinned(dev, pool=pool, min_device_blocks=8, max_wait_s=0.1)
    sizes = [1, 2, 3, 5, 7]              # 18 blocks, ragged mix
    windows = [_mk_window(b, i) for i, b in enumerate(sizes)]
    results, errors = _coalesce(sb, windows)
    assert all(e is None for e in errors)
    for i, w in enumerate(windows):
        assert results[i] is not None
        _rows_equal(results[i], _host_rows(K, M, w))
        # Data-drive blocks are views of the MEMBER's own window, not
        # of the (already recycled) staging buffer.
        for drive in range(K):
            for bi, (_dig, blk) in enumerate(results[i][drive]):
                assert np.shares_memory(np.asarray(blk), w)
    assert dev.batches and all(b in _BUCKETS for b in dev.batches)
    st = sb.stats()
    assert st["dispatches"]["device"] >= 1
    assert st["batched_blocks"] <= st["capacity_blocks"]
    assert pool.stats()["outstanding"] == 0      # staging lease returned


@pytest.mark.parametrize("bucket", _BUCKETS)
def test_every_padding_bucket_byte_identity(bucket):
    """Solo device-sized windows at every bucket size (full and
    one-under, exercising the zero-pad tail) frame byte-identically
    to the host codec."""
    dev = _RecordingDevice()
    pool = BufferPool(max_per_class=2)
    sb = _pinned(dev, pool=pool, min_device_blocks=4)
    for b in (bucket, bucket - 1):
        w = _mk_window(b, b)
        rows = sb.frame(w)
        _rows_equal(rows, _host_rows(K, M, w))
    assert dev.batches == [bucket, bucket]
    assert pool.stats()["outstanding"] == 0


def test_oversized_window_chunks_through_device_route():
    """A window larger than the biggest padding bucket (whole-part
    framing of a huge multipart part) must dispatch in bucket-sized
    chunks — never reach the staging buffer as one >256-row copy —
    and splice back byte-identical to the solo host framing."""
    dev = _RecordingDevice(mesh_devices=4)
    sb = _pinned(dev)
    w = _mk_window(300, seed=77)
    rows = sb.frame(w)
    _rows_equal(rows, _host_rows(K, M, w))
    # Both chunks rode the device route, each within the bucket cap.
    assert len(dev.batches) == 2
    assert all(b <= 256 for b in dev.batches)
    assert sum(dev.batches) >= 300


def test_donation_safety_staging_lease_held_across_dispatch():
    """While a dispatch is in flight, the pooled staging buffer backing
    the device input is NOT recyclable: a concurrent lease of the same
    size class must get different memory, and the lease returns to the
    pool only after the dispatch completes."""
    dev = _RecordingDevice()
    pool = BufferPool(max_per_class=4)
    sb = _pinned(dev, pool=pool, min_device_blocks=8, max_wait_s=0.1)
    seen = {}

    def hook(stacked):
        addr = stacked.__array_interface__["data"][0]
        size = stacked.nbytes
        assert pool.stats()["outstanding"] >= 1
        rival = pool.lease(size)
        try:
            raddr = rival.ndarray((size,)).__array_interface__["data"][0]
            # The staging mapping must never be handed out again while
            # the device is still reading it.
            assert raddr != addr
        finally:
            rival.release()
        seen["addr"] = addr

    dev.in_flight_hook = hook
    windows = [_mk_window(5, i) for i in range(3)]   # forces staging
    results, errors = _coalesce(sb, windows)
    assert all(e is None for e in errors)
    assert seen, "staged dispatch never ran"
    for i, w in enumerate(windows):
        _rows_equal(results[i], _host_rows(K, M, w))
    st = pool.stats()
    assert st["outstanding"] == 0 and st["leaks"] == 0


def test_deadline_exhausted_member_fails_without_poisoning_mates():
    """A member whose budget is spent by dispatch time is culled with
    DeadlineExceeded; batch-mates still get byte-correct rows. Driven
    through _run_batch directly (the dispatcher's entry point for every
    accumulated batch): the wall-clock race of arranging a mid-window
    expiry with live threads made the end-to-end variant flaky under
    parallel-suite load, while the cull contract itself is exactly
    what this exercises."""
    from minio_tpu.ops.batcher import _Pending
    dev = _RecordingDevice()
    sb = _pinned(dev, min_device_blocks=8)
    good = [_mk_window(4, 1), _mk_window(4, 2)]
    doomed = _mk_window(4, 3)
    pgood = [_Pending(w, None) for w in good]
    pdead = _Pending(doomed, Deadline(-1.0))    # spent before dispatch
    sb._run_batch([pgood[0], pdead, pgood[1]])
    assert isinstance(pdead.exc, DeadlineExceeded)
    assert pdead.event.is_set() and pdead.rows is None
    for i, p in enumerate(pgood):
        assert p.exc is None and p.event.is_set()
        _rows_equal(p.rows, _host_rows(K, M, good[i]))
    # The surviving pair still dispatched on the device route.
    assert dev.batches == [8]
    assert sb.stats()["deadline_failures"] == 1


def test_already_expired_deadline_fails_fast_without_device():
    dev = _RecordingDevice()
    sb = _pinned(dev, min_device_blocks=2)
    with deadline_mod.bind(Deadline(-1.0)):
        with pytest.raises(DeadlineExceeded):
            sb.frame(_mk_window(4, 9))
    assert dev.batches == []


def test_kernel_span_fans_into_each_member_trace():
    """One coalesced dispatch records ONE kernel span into EVERY
    member request's span tree, tagged with the shared batch shape and
    the member's own block count."""
    dev = _RecordingDevice()
    sb = _pinned(dev, min_device_blocks=8, max_wait_s=0.1)
    tracing.arm("test-batcher")
    try:
        ctxs = [tracing.TraceContext() for _ in range(3)]
        windows = [_mk_window(4, i) for i in range(3)]
        results = [None] * 3

        def worker(i):
            with tracing.bind(ctxs[i]):
                results[i] = sb.frame(windows[i])

        with sb._mu:
            sb._inflight += 1
        try:
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=20)
        finally:
            with sb._mu:
                sb._inflight -= 1
        for i, ctx in enumerate(ctxs):
            assert results[i] is not None
            spans = [s for s in ctx.spans
                     if s["type"] == "kernel"
                     and s["name"] == "batcher.dispatch"]
            assert len(spans) == 1, f"member {i} got {len(spans)} spans"
            tags = spans[0]["tags"]
            assert tags["blocks"] == 4
            assert tags["route"] == "device"
            assert tags["bucket"] in _BUCKETS
    finally:
        tracing.disarm("test-batcher")


def test_batch_force_env_knob(monkeypatch):
    host_calls = []

    def host(s):
        host_calls.append(s.shape[0])
        return _host_rows(K, M, s)

    monkeypatch.setenv("MTPU_BATCH_FORCE", "host")
    dev = _RecordingDevice()
    sb = StripeBatcher(dev, host, probe_fn=lambda: True)
    assert sb._device_ok is False and not sb.wants_device()
    sb.frame(_mk_window(16, 1))
    assert dev.batches == [] and host_calls == [16]
    sb.reset_calibration()                     # re-pins under the env
    assert sb._device_ok is False

    monkeypatch.setenv("MTPU_BATCH_FORCE", "device")
    sb2 = StripeBatcher(dev, host, probe_fn=lambda: False)
    assert sb2._device_ok is True
    rows = sb2.frame(_mk_window(16, 2))        # solo big -> device
    _rows_equal(rows, _host_rows(K, M, _mk_window(16, 2)))
    assert dev.batches == [16]

    monkeypatch.setenv("MTPU_BATCH_FORCE", "auto")
    sb3 = StripeBatcher(dev, host, probe_fn=lambda: True)
    assert sb3._device_ok is None and not sb3._probe_started


def test_adaptive_window_tracks_fill():
    dev = _RecordingDevice()
    sb = _pinned(dev, min_device_blocks=8, max_wait_s=0.002)
    w0 = sb._cur_wait
    sb._adapt_window(1.0)                      # full buckets: stretch
    assert sb._cur_wait >= w0
    for _ in range(8):
        sb._adapt_window(0.1)                  # sparse: shrink
    assert sb._cur_wait < w0


def test_fill_target_scales_with_mesh():
    dev1 = _RecordingDevice(mesh_devices=1)
    dev8 = _RecordingDevice(mesh_devices=8)
    sb1 = _pinned(dev1, min_device_blocks=8)
    sb8 = _pinned(dev8, min_device_blocks=8)
    assert sb1._fill_target() < sb8._fill_target()
    assert sb8._fill_target() <= 256
    assert sb8.mesh_devices == 8


def test_batcher_metrics_render():
    """The occupancy satellites surface in Prometheus text."""
    dev = _RecordingDevice()
    sb = _pinned(dev, min_device_blocks=4)
    sb.frame(_mk_window(8, 0))
    from minio_tpu.s3.metrics import Metrics
    text = Metrics().render()
    for name in ("minio_tpu_batcher_dispatches_total",
                 "minio_tpu_batcher_requests_total",
                 "minio_tpu_batcher_fill_ratio",
                 "minio_tpu_batcher_wait_seconds_bucket",
                 "minio_tpu_batcher_deadline_failures_total",
                 "minio_tpu_kernel_lane_dispatches_total"):
        assert name in text, name


def test_force_device_engages_batcher_off_tpu(monkeypatch, tmp_path):
    """MTPU_BATCH_FORCE=device reaches the REAL batched device route
    even off-TPU: the erasure layer's platform gate yields to the knob,
    so a device-window-sized PUT through a device-capable backend
    records a batcher device dispatch and still round-trips
    byte-identically. (Without the gate honoring the knob, a non-TPU
    host silently measured the host codec no matter what the batcher
    was forced to — the exact invisible degradation the knob exists to
    rule out in CI/bench runs.)"""
    monkeypatch.setenv("MTPU_BATCH_FORCE", "device")
    from minio_tpu.object.erasure_object import ErasureSet, _batcher_for
    from minio_tpu.ops.rs_device import DeviceBackend
    from minio_tpu.storage.local import LocalStorage
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    for d in disks:
        d.make_vol("bkt")
    es = ErasureSet(disks, parity=2, backend=DeviceBackend("auto"))
    sb = _batcher_for(2, 2)
    sb.reset_calibration()              # re-pin the cached batcher
    try:
        before = sb.stats()["dispatches"]["device"]
        # 8 full blocks = one device-sized window (>= min_device_blocks):
        # a solo PUT this big dispatches straight through the batch path.
        body = np.random.default_rng(11).integers(
            0, 256, size=8 << 20, dtype=np.uint8).tobytes()
        es.put_object("bkt", "o", body)
        assert sb.stats()["dispatches"]["device"] == before + 1
        _, got = es.get_object("bkt", "o")
        assert got == body
    finally:
        es.close()
        monkeypatch.delenv("MTPU_BATCH_FORCE", raising=False)
        sb.reset_calibration()          # un-pin for suite-mates
    assert sb._device_ok is None


_MESH_BODY = r"""
import numpy as np
from minio_tpu.object.erasure_object import _host_rows
from minio_tpu.ops import gf256
from minio_tpu.ops.hh_device import make_mesh_framer, mesh_batch_devices
import jax

K, M, SHARD = 8, 4, 256
assert len(jax.devices()) == 8, jax.devices()
framer = make_mesh_framer(gf256.parity_matrix(K, M))
assert framer.mesh_devices == 8, framer.mesh_devices
rng = np.random.default_rng(0)
for b in (8, 16, 32):
    w = rng.integers(0, 256, size=(b, K, SHARD), dtype=np.uint8)
    rows = framer(w)
    want = _host_rows(K, M, w)
    assert len(rows) == K + M
    for d in range(K + M):
        for (hg, bg), (hw, bw) in zip(rows[d], want[d]):
            assert np.array_equal(np.asarray(hg), np.asarray(hw)), d
            assert np.array_equal(np.asarray(bg), np.asarray(bw)), d
# The batcher over the real mesh framer coalesces into mesh-divisible
# buckets and stays byte-identical.
from minio_tpu.ops.batcher import StripeBatcher
import threading
sb = StripeBatcher(framer, lambda s: _host_rows(K, M, s),
                   probe_fn=lambda: True, min_device_blocks=8)
sb.force(True)
windows = [rng.integers(0, 256, size=(3, K, SHARD), dtype=np.uint8)
           for _ in range(4)]
results = [None] * 4
with sb._mu:
    sb._inflight += 1
ts = [threading.Thread(target=lambda i=i: results.__setitem__(
    i, sb.frame(windows[i]))) for i in range(4)]
[t.start() for t in ts]
[t.join(timeout=60) for t in ts]
with sb._mu:
    sb._inflight -= 1
for i in range(4):
    want = _host_rows(K, M, windows[i])
    for d in range(K + M):
        for (hg, bg), (hw, bw) in zip(results[i][d], want[d]):
            assert np.array_equal(np.asarray(hg), np.asarray(hw))
            assert np.array_equal(np.asarray(bg), np.asarray(bw))
print("MESH_OK")
"""


def test_mesh_framer_byte_identity_on_virtual_8_device_mesh():
    """The sharded dispatch on a real 8-device mesh (virtual CPU
    devices — the platform must be chosen before JAX initializes, so a
    fresh subprocess) produces bytes identical to the host codec, solo
    and through the batcher."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("MTPU_MESH_DEVICES", None)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", "")
        + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c", _MESH_BODY], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, timeout=420)
    assert proc.returncode == 0, proc.stderr.decode()[-4000:]
    assert b"MESH_OK" in proc.stdout
