"""Multipart upload lifecycle + CopyObject, over HTTP and the object layer
(reference patterns: cmd/erasure-multipart.go, multipart-quorum-test.sh)."""

import os
import xml.etree.ElementTree as ET

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"
PART = 5 * (1 << 20)


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("drives")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    server = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv.address)
    c.request("PUT", "/mpb")
    return c


def _initiate(cli, key, headers=None):
    status, _, body = cli.request("POST", f"/mpb/{key}", query={"uploads": ""},
                                  headers=headers or {})
    assert status == 200, body
    return ET.fromstring(body).findtext(f"{NS}UploadId")


def test_full_multipart_flow(cli):
    uid = _initiate(cli, "big", headers={"x-amz-meta-kind": "multi",
                                         "content-type": "app/z"})
    data = [os.urandom(PART), os.urandom(PART), os.urandom(1234)]
    etags = []
    for i, d in enumerate(data):
        status, h, body = cli.request(
            "PUT", "/mpb/big",
            query={"partNumber": str(i + 1), "uploadId": uid}, body=d)
        assert status == 200, body
        etags.append(h["ETag"])

    # list parts
    status, _, body = cli.request("GET", "/mpb/big", query={"uploadId": uid})
    root = ET.fromstring(body)
    nums = [int(e.text) for e in root.iter(f"{NS}PartNumber")]
    assert nums == [1, 2, 3]

    # complete
    xml = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{i+1}</PartNumber><ETag>{etags[i]}</ETag></Part>"
        for i in range(3)) + "</CompleteMultipartUpload>"
    status, _, body = cli.request("POST", "/mpb/big", query={"uploadId": uid},
                                  body=xml.encode())
    assert status == 200, body
    etag = ET.fromstring(body).findtext(f"{NS}ETag").strip('"')
    assert etag.endswith("-3")

    full = b"".join(data)
    status, h, got = cli.request("GET", "/mpb/big")
    assert got == full
    assert h["ETag"] == f'"{etag}"'
    assert h.get("x-amz-meta-kind") == "multi"
    assert h["Content-Type"] == "app/z"

    # ranged read across the part-2/part-3 boundary
    off = 2 * PART - 100
    status, _, got = cli.request(
        "GET", "/mpb/big", headers={"Range": f"bytes={off}-{off + 199}"})
    assert got == full[off:off + 200]


def test_complete_validations(cli):
    uid = _initiate(cli, "val")
    d = os.urandom(1000)
    _, h, _ = cli.request("PUT", "/mpb/val",
                          query={"partNumber": "1", "uploadId": uid}, body=d)
    etag = h["ETag"]
    # wrong etag
    xml = (f"<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
           f"<ETag>\"{'0'*32}\"</ETag></Part></CompleteMultipartUpload>")
    status, _, body = cli.request("POST", "/mpb/val", query={"uploadId": uid},
                                  body=xml.encode())
    assert status == 400 and b"InvalidPart" in body
    # out-of-order
    _, h2, _ = cli.request("PUT", "/mpb/val",
                           query={"partNumber": "2", "uploadId": uid}, body=d)
    xml = ("<CompleteMultipartUpload>"
           f"<Part><PartNumber>2</PartNumber><ETag>{h2['ETag']}</ETag></Part>"
           f"<Part><PartNumber>1</PartNumber><ETag>{etag}</ETag></Part>"
           "</CompleteMultipartUpload>")
    status, _, body = cli.request("POST", "/mpb/val", query={"uploadId": uid},
                                  body=xml.encode())
    assert status == 400 and b"InvalidPartOrder" in body
    # too-small non-last part
    xml = ("<CompleteMultipartUpload>"
           f"<Part><PartNumber>1</PartNumber><ETag>{etag}</ETag></Part>"
           f"<Part><PartNumber>2</PartNumber><ETag>{h2['ETag']}</ETag></Part>"
           "</CompleteMultipartUpload>")
    status, _, body = cli.request("POST", "/mpb/val", query={"uploadId": uid},
                                  body=xml.encode())
    assert status == 400 and b"EntityTooSmall" in body


def test_abort_and_list_uploads(cli):
    uid = _initiate(cli, "gone")
    status, _, body = cli.request("GET", "/mpb", query={"uploads": ""})
    assert uid in body.decode()
    status, _, _ = cli.request("DELETE", "/mpb/gone", query={"uploadId": uid})
    assert status == 204
    status, _, body = cli.request("GET", "/mpb", query={"uploads": ""})
    assert uid not in body.decode()
    # operations on the aborted upload 404
    status, _, body = cli.request("GET", "/mpb/gone", query={"uploadId": uid})
    assert status == 404 and b"NoSuchUpload" in body


def test_part_overwrite_last_wins(cli):
    uid = _initiate(cli, "ow")
    cli.request("PUT", "/mpb/ow", query={"partNumber": "1", "uploadId": uid},
                body=b"A" * 1000)
    _, h, _ = cli.request("PUT", "/mpb/ow",
                          query={"partNumber": "1", "uploadId": uid},
                          body=b"B" * 1000)
    xml = (f"<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
           f"<ETag>{h['ETag']}</ETag></Part></CompleteMultipartUpload>")
    status, _, body = cli.request("POST", "/mpb/ow", query={"uploadId": uid},
                                  body=xml.encode())
    assert status == 200, body
    _, _, got = cli.request("GET", "/mpb/ow")
    assert got == b"B" * 1000


def test_copy_object(cli):
    payload = os.urandom(600_000)
    cli.request("PUT", "/mpb/src", body=payload,
                headers={"x-amz-meta-tag": "orig", "content-type": "a/b"})
    status, _, body = cli.request(
        "PUT", "/mpb/dst", headers={"x-amz-copy-source": "/mpb/src"})
    assert status == 200 and b"CopyObjectResult" in body
    status, h, got = cli.request("GET", "/mpb/dst")
    assert got == payload and h.get("x-amz-meta-tag") == "orig" \
        and h["Content-Type"] == "a/b"
    # REPLACE directive
    status, _, _ = cli.request(
        "PUT", "/mpb/dst2",
        headers={"x-amz-copy-source": "/mpb/src",
                 "x-amz-metadata-directive": "REPLACE",
                 "x-amz-meta-tag": "new"})
    _, h, got = cli.request("GET", "/mpb/dst2")
    assert got == payload and h.get("x-amz-meta-tag") == "new"
