"""Minimal SigV4 S3 client for tests — signs real HTTP requests the way
aws-sdk clients do, so the server-side verification is exercised for real
(the shape of the reference's test-signing helpers in cmd/test-utils_test.go)."""

from __future__ import annotations

import bisect
import datetime
import hashlib
import hmac
import http.client
import random
import socket
import threading
import time
import urllib.parse

from minio_tpu.s3 import sigv4


class S3Client:
    def __init__(self, address: str, access_key="minioadmin",
                 secret_key="minioadmin", region="us-east-1", timeout=30,
                 session_token: str = "", keepalive: bool = False):
        """keepalive=True reuses ONE HTTP connection across request()
        calls (reopened transparently if the server closes it) — the
        SDK connection-pool shape, exercising the serve hot loop's
        persistent-connection fast path instead of a fresh handshake
        per request."""
        self.address = address
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = timeout
        self.session_token = session_token
        self.keepalive = keepalive
        self._conn: http.client.HTTPConnection | None = None
        self._sock: socket.socket | None = None   # get_into fast path
        self._spare = b""       # bytes read past the previous response

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        self._spare = b""

    def get_into(self, path: str, buf) -> tuple[int, int]:
        """Signed GET over a persistent raw socket, body received
        straight into `buf` via recv_into — the thinnest client read
        path there is (no http.client response machinery, no
        per-request bytes join). For bench probes and throughput tests
        where CLIENT-side Python costs must not pollute the measured
        server number. Returns (status, body_len); body_len may exceed
        len(buf) only on error statuses (the XML body is drained, not
        stored)."""
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = amz_date[:8]
        scope = f"{date}/{self.region}/s3/aws4_request"
        lower = {"host": self.address, "x-amz-date": amz_date,
                 "x-amz-content-sha256": sigv4.EMPTY_SHA256}
        if self.session_token:
            lower["x-amz-security-token"] = self.session_token
        signed = sorted(lower)
        canon = sigv4.canonical_request("GET", path, {}, lower, signed,
                                        sigv4.EMPTY_SHA256)
        sts = sigv4.string_to_sign(amz_date, scope, canon)
        key = sigv4.signing_key(self.secret_key, date, self.region)
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        url = sigv4.uri_encode(path, encode_slash=False)
        req = (f"GET {url} HTTP/1.1\r\nHost: {self.address}\r\n"
               f"x-amz-date: {amz_date}\r\n"
               f"x-amz-content-sha256: {sigv4.EMPTY_SHA256}\r\n"
               + (f"x-amz-security-token: {self.session_token}\r\n"
                  if self.session_token else "")
               + f"Authorization: {sigv4.ALGORITHM} "
               f"Credential={self.access_key}/{scope}, "
               f"SignedHeaders={';'.join(signed)}, Signature={sig}\r\n"
               "\r\n").encode("latin-1")
        for attempt in (0, 1):
            if self._sock is None:
                host, _, port = self.address.rpartition(":")
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=self.timeout)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
            try:
                self._sock.sendall(req)
                return self._read_response_into(buf)
            except OSError:
                if self._sock is not None:
                    self._sock.close()
                    self._sock = None
                self._spare = b""
                if attempt:
                    raise
        raise OSError("unreachable")

    def _read_response_into(self, buf) -> tuple[int, int]:
        sock = self._sock
        head = self._spare
        while True:
            end = head.find(b"\r\n\r\n")
            if end >= 0:
                break
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("EOF in response head")
            head += chunk
        status = int(head[9:12])
        clen = 0
        for line in head[:end].split(b"\r\n")[1:]:
            if line[:15].lower() == b"content-length:":
                clen = int(line[15:])
        rest = head[end + 4:]
        body_mv = memoryview(buf)
        got = min(len(rest), clen, len(buf))
        body_mv[:got] = rest[:got]
        drained = len(rest)
        self._spare = rest[clen:] if clen <= len(rest) else b""
        filled = got
        while drained < clen:
            if filled < min(clen, len(buf)):
                n = sock.recv_into(body_mv[filled:],
                                   min(clen - drained, len(buf) - filled))
                filled += n
            else:
                n = len(sock.recv(min(clen - drained, 1 << 20)))
            if not n:
                raise ConnectionError("EOF in response body")
            drained += n
        return status, clen

    def request(self, method: str, path: str, query: dict | None = None,
                body: bytes = b"", headers: dict | None = None,
                sign: bool = True, chunked: bool = False,
                te_chunked: bool = False, trailers: dict | None = None,
                corrupt_trailer_sig: bool = False):
        """te_chunked: send the (aws-chunked) body with HTTP
        Transfer-Encoding: chunked instead of Content-Length — the SDK
        pattern for unknown-length streaming uploads. trailers (with
        chunked=True): signed-trailer mode — append the trailer lines
        and an x-amz-trailer-signature over them."""
        query = {k: [v] if isinstance(v, str) else v
                 for k, v in (query or {}).items()}
        headers = dict(headers or {})
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = amz_date[:8]
        scope = f"{date}/{self.region}/s3/aws4_request"

        send_headers = {"Host": self.address, "x-amz-date": amz_date}
        if chunked:
            payload_hash = sigv4.STREAMING_PAYLOAD if trailers is None \
                else sigv4.STREAMING_PAYLOAD_TRAILER
            send_headers["content-encoding"] = "aws-chunked"
            send_headers["x-amz-decoded-content-length"] = str(len(body))
            if trailers is not None:
                send_headers["x-amz-trailer"] = ",".join(trailers)
        else:
            payload_hash = hashlib.sha256(body).hexdigest()
        send_headers["x-amz-content-sha256"] = payload_hash
        if self.session_token:
            send_headers["x-amz-security-token"] = self.session_token
        send_headers.update(headers)

        if sign:
            lower = {k.lower(): v for k, v in send_headers.items()}
            signed = sorted(lower)
            canon = sigv4.canonical_request(method, path, query, lower,
                                            signed, payload_hash)
            sts = sigv4.string_to_sign(amz_date, scope, canon)
            key = sigv4.signing_key(self.secret_key, date, self.region)
            sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
            send_headers["Authorization"] = (
                f"{sigv4.ALGORITHM} Credential={self.access_key}/{scope}, "
                f"SignedHeaders={';'.join(signed)}, Signature={sig}")
            if chunked:
                body = self._chunk_body(body, sig, amz_date, scope,
                                        trailers, corrupt_trailer_sig)

        qs = urllib.parse.urlencode(
            [(k, v) for k, vs in query.items() for v in vs])
        # Send exactly the URI that was signed (raw-path verification).
        url = sigv4.uri_encode(path, encode_slash=False) + ("?" + qs if qs else "")
        if te_chunked:
            # An iterable body with no Content-Length makes http.client
            # use Transfer-Encoding: chunked.
            step = 256 * 1024
            body = iter([body[i:i + step]
                         for i in range(0, len(body), step)] or [b""])
        if not self.keepalive:
            conn = http.client.HTTPConnection(self.address,
                                              timeout=self.timeout)
            try:
                conn.request(method, url, body=body, headers=send_headers)
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.getheaders()), data
            finally:
                conn.close()
        for attempt in (0, 1):
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.address, timeout=self.timeout)
            try:
                self._conn.request(method, url, body=body,
                                   headers=send_headers)
                resp = self._conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.getheaders()), data
            except (http.client.HTTPException, OSError):
                # Server closed the idle connection (keep-alive timeout
                # or drain): reopen once. Iterable bodies can't be
                # replayed — surface those.
                self.close()
                if attempt or te_chunked:
                    raise

    def _chunk_body(self, body: bytes, seed_sig: str, amz_date: str,
                    scope: str, trailers: dict | None = None,
                    corrupt_trailer_sig: bool = False) -> bytes:
        key = sigv4.signing_key(self.secret_key, scope.split("/")[0],
                                self.region)
        out = bytearray()
        prev = seed_sig
        chunks = [body[i:i + 64 * 1024] for i in range(0, len(body), 64 * 1024)]
        for data in chunks + [b""]:
            sts = "\n".join(["AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope,
                             prev, sigv4.EMPTY_SHA256,
                             hashlib.sha256(data).hexdigest()])
            sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
            out += f"{len(data):x};chunk-signature={sig}\r\n".encode()
            out += data + b"\r\n"
            prev = sig
        if trailers is not None:
            # AWS signed-trailer shape: trailer lines, then a signature
            # over their '\n'-terminated forms chained off the final
            # (0-byte) chunk's signature.
            out = out[:-2]      # the 0-chunk has no trailing CRLF pair
            raw = bytearray()
            for name, value in trailers.items():
                out += f"{name}:{value}\r\n".encode()
                raw += f"{name}:{value}\n".encode()
            sts = "\n".join(["AWS4-HMAC-SHA256-TRAILER", amz_date, scope,
                             prev, hashlib.sha256(bytes(raw)).hexdigest()])
            tsig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
            if corrupt_trailer_sig:
                tsig = ("0" * 63) + ("1" if tsig[63] != "1" else "2")
            out += f"x-amz-trailer-signature:{tsig}\r\n\r\n".encode()
        return bytes(out)

    def presign(self, method: str, path: str, expires: int = 300) -> str:
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = amz_date[:8]
        scope = f"{date}/{self.region}/s3/aws4_request"
        query = {
            "X-Amz-Algorithm": [sigv4.ALGORITHM],
            "X-Amz-Credential": [f"{self.access_key}/{scope}"],
            "X-Amz-Date": [amz_date],
            "X-Amz-Expires": [str(expires)],
            "X-Amz-SignedHeaders": ["host"],
        }
        headers = {"host": self.address}
        canon = sigv4.canonical_request(method, path, query, headers,
                                        ["host"], sigv4.UNSIGNED_PAYLOAD)
        sts = sigv4.string_to_sign(amz_date, scope, canon)
        key = sigv4.signing_key(self.secret_key, date, self.region)
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        query["X-Amz-Signature"] = [sig]
        qs = urllib.parse.urlencode(
            [(k, v) for k, vs in query.items() for v in vs])
        return sigv4.uri_encode(path, encode_slash=False) + "?" + qs


def ramp_get(address: str, path: str, body_len: int, connections: int,
             duration_s: float = 2.0, access_key: str = "minioadmin",
             secret_key: str = "minioadmin",
             region: str = "us-east-1",
             paths: list[str] | None = None, alpha: float = 1.0,
             hot_frac: float = 0.1) -> dict:
    """Multi-connection GET fan-in driver: `connections` client threads,
    each with its OWN persistent raw socket (S3Client.get_into — signed
    head out, recv_into straight into a reusable buffer), all released
    together and looping the same object for `duration_s`. This is the
    measurement r10 could not make: the served GET aggregate against a
    growing client connection count, instead of one hot socket whose
    single client thread was the bottleneck. Returns {connections, ops,
    bytes, secs, agg_gibps, errors}; the aggregate counts only
    responses that completed inside the window.

    paths: optional zipfian hot-set mode — instead of hammering `path`,
    every request picks from `paths` (ALL must serve `body_len` bytes)
    with rank-frequency P(rank i) ∝ 1/(i+1)**alpha, the skew real
    object workloads show and the distribution the hot read tier's
    tinyLFU admission is built for. Each thread uses its own seeded
    RNG so runs are reproducible. hot_frac only adds accounting: the
    first max(1, round(hot_frac*len(paths))) ranks are the "hot set"
    and the result gains {hot_set, hot_ops} so callers can relate the
    served aggregate to expected cache residency."""
    if paths:
        weights = [1.0 / (i + 1) ** alpha for i in range(len(paths))]
        total_w = sum(weights)
        cum, acc = [], 0.0
        for w in weights:
            acc += w
            cum.append(acc / total_w)
        hot_set = max(1, round(hot_frac * len(paths)))
    else:
        cum = None
        hot_set = 0
    results: list = [None] * connections
    deadline_box = [0.0]
    # The barrier action runs in exactly one thread at the release
    # moment, so every worker reads a deadline anchored to the instant
    # the whole ramp went hot — not to when the driver started priming.
    barrier = threading.Barrier(
        connections + 1, action=lambda: deadline_box.__setitem__(
            0, time.monotonic() + duration_s))

    def worker(t: int) -> None:
        cli = S3Client(address, access_key=access_key,
                       secret_key=secret_key, region=region)
        rng = random.Random(0xC0FFEE + t)

        def pick() -> tuple[str, int]:
            if cum is None:
                return path, 0
            i = bisect.bisect_left(cum, rng.random())
            return paths[i], i

        buf = bytearray(body_len)
        ops = got = errs = hot_ops = 0
        primed = False
        try:
            # Prime the connection OUTSIDE the measured window (TCP +
            # first-request warmup is setup, not serving).
            st, n = cli.get_into(pick()[0], buf)
            assert st == 200 and n == body_len, (st, n)
            primed = True
            barrier.wait()
            deadline = deadline_box[0]
            while time.monotonic() < deadline:
                p, rank = pick()
                try:
                    st, n = cli.get_into(p, buf)
                except OSError:
                    errs += 1
                    continue
                if st == 200 and n == body_len:
                    ops += 1
                    got += n
                    if rank < hot_set:
                        hot_ops += 1
                else:
                    errs += 1
        except Exception:  # noqa: BLE001 - surface via the error count
            errs += 1
            if not primed:
                try:
                    barrier.wait(timeout=60)
                except threading.BrokenBarrierError:
                    pass
        finally:
            results[t] = (ops, got, errs, hot_ops)
            cli.close()

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(connections)]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.monotonic()
    for th in threads:
        th.join(timeout=duration_s + 120)
    secs = max(time.monotonic() - t0, 1e-9)
    ops = sum(r[0] for r in results if r)
    nbytes = sum(r[1] for r in results if r)
    errors = sum(r[2] for r in results if r)
    out = {"connections": connections, "ops": ops, "bytes": nbytes,
           "secs": round(secs, 3), "errors": errors,
           "agg_gibps": round(nbytes / secs / (1 << 30), 4)}
    if cum is not None:
        out["hot_set"] = hot_set
        out["hot_ops"] = sum(r[3] for r in results if r)
    return out
