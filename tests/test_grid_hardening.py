"""Grid hardening: frame-granular write lock (lock RPC latency unaffected
by a concurrent bulk transfer) and deterministic naughty-disk fault
schedules driving quorum paths (reference: internal/grid/README.md
credit/frame scheduling, cmd/naughty-disk_test.go)."""

import os
import threading
import time

import numpy as np
import pytest

from minio_tpu.grid.server import GridServer
from minio_tpu.grid.client import GridClient
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import WriteQuorumError
from minio_tpu.storage.local import LocalStorage
from minio_tpu.storage.naughty import NaughtyDisk
from minio_tpu.storage.remote import RemoteStorage, StorageRPCService


# ---------------------------------------------------------------------------
# frame-granular interleaving
# ---------------------------------------------------------------------------

@pytest.fixture
def grid_env(tmp_path):
    roots = [str(tmp_path / f"d{i}") for i in range(2)]
    locals_ = [LocalStorage(r) for r in roots]
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({d.root: d for d in locals_}).register_into(srv)
    srv.start()
    yield srv, roots
    srv.stop()


def test_lock_rpc_latency_under_concurrent_bulk_write(grid_env):
    """A large remote create_file must not head-of-line-block small
    RPCs: p99 of pings issued DURING the transfer stays bounded."""
    srv, roots = grid_env
    port = srv.port
    remote = RemoteStorage("127.0.0.1", port, roots[0])
    remote.make_vol_if_missing("bulkvol")
    blob = np.random.default_rng(0).integers(
        0, 256, size=64 << 20, dtype=np.uint8).tobytes()   # 64 MiB

    done = threading.Event()
    err: list = []

    def bulk():
        try:
            remote.create_file("bulkvol", "big.bin", blob)
        except Exception as e:  # noqa: BLE001
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=bulk, daemon=True)
    small = GridClient("127.0.0.1", port)
    small.ping()          # warm connection before the bulk starts
    t.start()
    lat = []
    while not done.is_set() and len(lat) < 500:
        t0 = time.perf_counter()
        assert small.ping(timeout=5.0)
        lat.append(time.perf_counter() - t0)
    t.join(timeout=30)
    assert not err, err
    assert remote.read_file("bulkvol", "big.bin", 0, 16) == blob[:16]
    assert len(lat) >= 5, "bulk finished before any concurrent pings"
    lat.sort()
    p99 = lat[int(len(lat) * 0.99) - 1]
    # One 1 MiB frame transfer on loopback is well under 50 ms; a 64 MiB
    # head-of-line block would show up as multi-hundred-ms pings.
    assert p99 < 0.25, f"p99 ping latency {p99 * 1000:.1f} ms"


# ---------------------------------------------------------------------------
# naughty-disk quorum schedules
# ---------------------------------------------------------------------------

@pytest.fixture
def naughty_set(tmp_path):
    reals = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    naughties = [NaughtyDisk(d) for d in reals]
    es = ErasureSet(naughties)
    es.make_bucket("nb")
    return es, naughties


def test_put_succeeds_with_programmed_minority_failures(naughty_set):
    es, naughties = naughty_set
    naughties[0].fail_ops = {"create_file": OSError("programmed fault"),
                             "write_metadata": OSError("programmed fault"),
                             "rename_data": OSError("programmed fault")}
    body = os.urandom(300_000)
    info = es.put_object("nb", "obj", body)
    assert info.size == len(body)
    # The failed drive got repair queued (write-path MRF hook).
    es.mrf.drain()
    _, got = es.get_object("nb", "obj")
    assert got == body


def test_put_fails_below_write_quorum_with_programmed_faults(naughty_set):
    es, naughties = naughty_set
    for nd in naughties[:3]:
        nd.fail_ops = {"create_file": OSError("programmed fault"),
                       "write_metadata": OSError("programmed fault"),
                       "rename_data": OSError("programmed fault")}
    with pytest.raises(WriteQuorumError):
        es.put_object("nb", "doomed", os.urandom(300_000))


def test_degraded_read_with_scheduled_read_faults(naughty_set):
    es, naughties = naughty_set
    body = os.urandom(300_000)
    es.put_object("nb", "robj", body)
    # Parity-count (2) drives refuse all reads from now on.
    for nd in naughties[:2]:
        nd.fail_ops = {"read_file": OSError("programmed fault"),
                       "read_version": OSError("programmed fault")}
    _, got = es.get_object("nb", "robj")
    assert got == body


def test_nth_call_schedule_and_accounting(tmp_path):
    real = LocalStorage(str(tmp_path / "d0"))
    nd = NaughtyDisk(real, fail_calls={2: OSError("second call dies")})
    nd.make_vol_if_missing("v")                 # call 1: passes
    with pytest.raises(OSError):
        nd.write_all("v", "x", b"data")         # call 2: programmed fault
    nd.write_all("v", "x", b"data")             # call 3: passes
    assert nd.read_all("v", "x") == b"data"
    assert nd.call_count == 4
    assert [op for op, _ in nd.calls] == [
        "make_vol_if_missing", "write_all", "write_all", "read_all"]
