"""Legacy SigV2 authentication and the persisted config subsystem
(reference: cmd/signature-v2.go, internal/config + admin SetConfigKV)."""

import base64
import hashlib
import hmac
import http.client
import json
import time
import urllib.parse

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("v2drv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


def _v2_request(addr, method, path, body=b"", headers=None,
                access="minioadmin", secret="minioadmin"):
    headers = dict(headers or {})
    date = time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())
    headers["Date"] = date
    amz = sorted(f"{k.lower()}:{v.strip()}" for k, v in headers.items()
                 if k.lower().startswith("x-amz-") and
                 k.lower() != "x-amz-date")
    sts = "\n".join([method, headers.get("Content-MD5", ""),
                     headers.get("Content-Type", ""), date] + amz + [path])
    sig = base64.b64encode(hmac.new(secret.encode(), sts.encode(),
                                    hashlib.sha1).digest()).decode()
    headers["Authorization"] = f"AWS {access}:{sig}"
    conn = http.client.HTTPConnection(addr, timeout=30)
    try:
        conn.request(method, path, body=body, headers=headers)
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def test_sigv2_header_roundtrip(srv):
    st, _, b = _v2_request(srv.address, "PUT", "/v2bkt")
    assert st == 200, b
    st, _, _ = _v2_request(srv.address, "PUT", "/v2bkt/obj",
                           body=b"v2 data",
                           headers={"Content-Type": "text/plain"})
    assert st == 200
    st, _, got = _v2_request(srv.address, "GET", "/v2bkt/obj")
    assert st == 200 and got == b"v2 data"


def test_sigv2_bad_signature_rejected(srv):
    st, _, _ = _v2_request(srv.address, "GET", "/v2bkt/obj",
                           secret="wrongsecret")
    assert st == 403
    st, _, _ = _v2_request(srv.address, "GET", "/v2bkt/obj",
                           access="ghost")
    assert st == 403


def test_sigv2_presigned(srv):
    _v2_request(srv.address, "PUT", "/v2bkt")
    _v2_request(srv.address, "PUT", "/v2bkt/obj", body=b"v2 data")
    expires = str(int(time.time()) + 120)
    path = "/v2bkt/obj"
    sts = f"GET\n\n\n{expires}\n{path}"
    sig = base64.b64encode(hmac.new(b"minioadmin", sts.encode(),
                                    hashlib.sha1).digest()).decode()
    qs = urllib.parse.urlencode({"AWSAccessKeyId": "minioadmin",
                                 "Expires": expires, "Signature": sig})
    conn = http.client.HTTPConnection(srv.address, timeout=30)
    conn.request("GET", f"{path}?{qs}")
    r = conn.getresponse()
    body = r.read()
    conn.close()
    assert r.status == 200 and body == b"v2 data"
    # Expired link: denied.
    old = str(int(time.time()) - 10)
    sts = f"GET\n\n\n{old}\n{path}"
    sig = base64.b64encode(hmac.new(b"minioadmin", sts.encode(),
                                    hashlib.sha1).digest()).decode()
    qs = urllib.parse.urlencode({"AWSAccessKeyId": "minioadmin",
                                 "Expires": old, "Signature": sig})
    conn = http.client.HTTPConnection(srv.address, timeout=30)
    conn.request("GET", f"{path}?{qs}")
    r = conn.getresponse()
    r.read()
    conn.close()
    assert r.status == 403


# ---------------------------------------------------------------------------
# config subsystem
# ---------------------------------------------------------------------------

def test_config_set_get_apply_persist(srv):
    cli = S3Client(srv.address)
    assert srv.compression is False
    st, _, b = cli.request("PUT", "/minio/admin/v3/set-config",
                           body=json.dumps({
                               "compression": "on",
                               "scanner_deep_every": 64}).encode())
    assert st == 200, b
    assert json.loads(b)["applied"] == ["compression"]   # no scanner wired
    assert srv.compression is True
    st, _, b = cli.request("GET", "/minio/admin/v3/get-config")
    cfg = json.loads(b)
    assert cfg["compression"] == "on"
    assert cfg["scanner_deep_every"] == 64
    # Invalid values rejected, state unchanged.
    st, _, _ = cli.request("PUT", "/minio/admin/v3/set-config",
                           body=json.dumps({"compression": "maybe"}
                                           ).encode())
    assert st == 400
    assert srv.compression is True
    # Reset for other tests.
    cli.request("PUT", "/minio/admin/v3/set-config",
                body=json.dumps({"compression": "off"}).encode())
    assert srv.compression is False


def test_config_applies_to_scanner(tmp_path):
    import types

    from minio_tpu.object.scanner import Scanner
    from minio_tpu.s3 import config as cfg_mod
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.scanner = Scanner([es], throttle=0.5, deep_every=1024)
    server = types.SimpleNamespace(object_layer=es, compression=False)
    applied = cfg_mod.apply_config(server, {
        "scanner_interval": 5, "scanner_deep_every": 10,
        "scanner_throttle": 0})
    assert set(applied) == {"scanner_interval", "scanner_deep_every",
                            "scanner_throttle"}
    assert es.scanner.interval == 5.0
    assert es.scanner.deep_every == 10
    assert es.scanner.throttle == 0.0
