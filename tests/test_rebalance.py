"""Pool rebalance: overfilled pools shed toward the cluster average
with checkpointed resume; every object readable throughout (reference:
cmd/erasure-server-pool-rebalance.go:100)."""

import os
import threading

import pytest

from minio_tpu.object import rebalance
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.types import DeleteOptions, PutOptions
from minio_tpu.storage.local import LocalStorage


def _pool(tmp_path, name, n=4):
    disks = [LocalStorage(str(tmp_path / name / f"d{i}")) for i in range(n)]
    return ErasureSets([ErasureSet(disks)])


@pytest.fixture
def layer(tmp_path):
    lay = ServerPools([_pool(tmp_path, "p0"), _pool(tmp_path, "p1")])
    lay.make_bucket("rb")
    return lay


def _used(pool, bucket="rb") -> int:
    return rebalance.pool_usage(pool)[0]


def _seed_imbalance(layer, n=20, size=50_000):
    """All objects into pool 0; pool 1 empty. Test pools share one
    filesystem, so equal capacities make 'fill fraction' degenerate to
    'used bytes' — the rebalance target is then the byte average."""
    bodies = {}
    for i in range(n):
        body = os.urandom(size + i)
        bodies[f"o{i:03d}"] = body
        layer.pools[0].put_object("rb", f"o{i:03d}", body)
    return bodies


def test_rebalance_converges_and_preserves_objects(layer):
    bodies = _seed_imbalance(layer)
    # Versioned stack + delete marker also migrate intact.
    layer.pools[0].put_object("rb", "ver", b"v1", PutOptions(versioned=True))
    layer.pools[0].put_object("rb", "ver", b"v2", PutOptions(versioned=True))
    layer.pools[0].delete_object("rb", "marked",
                                 DeleteOptions(versioned=True))
    before = _used(layer.pools[0])
    rb = layer.start_rebalance()
    assert rb.wait(120)
    st = layer.rebalance_status()
    assert st["status"] == "complete", st
    rec0 = st["pools"]["0"]
    assert rec0["participating"] and rec0["migrated"] > 0
    assert rec0["failed"] == 0
    # Pool 0 shed roughly half its bytes (to the average of 2 pools);
    # pool 1 gained them. Tolerate per-key granularity slack.
    u0, u1 = _used(layer.pools[0]), _used(layer.pools[1])
    assert u1 > 0
    assert u0 < before * 0.75
    assert abs(u0 - u1) < before * 0.35
    # Everything still reads correctly through the layer.
    for k, b in bodies.items():
        _, got = layer.get_object("rb", k)
        assert got == b
    from minio_tpu.object.types import GetOptions, ObjectNotFound
    with pytest.raises(ObjectNotFound):
        layer.get_object("rb", "marked", GetOptions())
    # ...but the marker itself migrated (it lives in SOME pool).
    def marker_in(p):
        try:
            return any(v.deleted for v in p.set_for("marked")
                       .list_versions_all("rb", "marked"))
        except ObjectNotFound:
            return False
    assert any(marker_in(p) for p in layer.pools)


def test_balanced_cluster_is_a_noop(layer):
    # Same bytes in both pools: nobody participates.
    for i in range(4):
        layer.pools[0].put_object("rb", f"a{i}", os.urandom(10_000))
        layer.pools[1].put_object("rb", f"b{i}", os.urandom(10_000))
    rb = layer.start_rebalance()
    assert rb.wait(60)
    st = layer.rebalance_status()
    assert st["status"] == "complete"
    assert all(not r["participating"] for r in st["pools"].values())
    assert all(r["migrated"] == 0 for r in st["pools"].values())


def test_rebalance_kill_midway_then_resume(layer, tmp_path):
    bodies = _seed_imbalance(layer, n=30)
    # Checkpoint every key; stop the run as soon as a few keys moved.
    rb = rebalance.Rebalance(layer, checkpoint_every=1)
    layer._rebalance = rb

    moved = threading.Event()
    orig = rebalance.migrate_key

    def spy(lay, src, bucket, key, pick):
        orig(lay, src, bucket, key, pick)
        if lay.pools and rb.state["pools"]["0"]["migrated"] >= 4:
            moved.set()

    rebalance.migrate_key = spy
    try:
        rb.start()
        assert moved.wait(60)
        rb.stop()                       # simulate a clean kill
    finally:
        rebalance.migrate_key = orig
    st = rebalance.load_state(layer)
    assert st is not None and st["status"] == "rebalancing"
    partial = st["pools"]["0"]["migrated"]
    assert partial >= 4
    # Every object readable in the interrupted state.
    for k, b in bodies.items():
        _, got = layer.get_object("rb", k)
        assert got == b
    # Resume (the boot path) finishes the job.
    rb2 = layer.resume_rebalance()
    assert rb2 is not None
    assert rb2.wait(120)
    st = layer.rebalance_status()
    assert st["status"] == "complete", st
    u0, u1 = _used(layer.pools[0]), _used(layer.pools[1])
    assert u1 > 0 and abs(u0 - u1) < (u0 + u1) * 0.4
    for k, b in bodies.items():
        _, got = layer.get_object("rb", k)
        assert got == b


def test_rebalance_admin_api(tmp_path):
    from minio_tpu.s3.server import S3Server
    from tests.s3client import S3Client
    lay = ServerPools([_pool(tmp_path, "p0"), _pool(tmp_path, "p1")])
    srv = S3Server(lay, address="127.0.0.1:0")
    srv.start()
    try:
        cli = S3Client(srv.address)
        assert cli.request("PUT", "/rbb")[0] == 200
        for i in range(10):
            lay.pools[0].put_object("rbb", f"x{i}", os.urandom(30_000))
        st, _, body = cli.request(
            "POST", "/minio/admin/v3/rebalance-start")
        assert st == 200, body
        import json
        for _ in range(200):
            st, _, body = cli.request(
                "GET", "/minio/admin/v3/rebalance-status")
            assert st == 200
            doc = json.loads(body)
            if doc and doc.get("status") in ("complete", "failed"):
                break
            import time
            time.sleep(0.1)
        assert doc["status"] == "complete", doc
        assert cli.request(
            "POST", "/minio/admin/v3/rebalance-stop")[0] == 200
    finally:
        srv.stop()
        lay.close()
