"""Distributed runtime: grid RPC mesh, remote StorageAPI, dsync quorum
locks, and a verify-healing-style multi-process cluster test
(reference: internal/grid, cmd/storage-rest-*, internal/dsync,
buildscripts/verify-healing.sh)."""

import os
import signal
import subprocess
import sys
import time

import pytest

from minio_tpu.grid import GridClient, GridError, GridServer, RemoteCallError
from minio_tpu.grid.dsync import (DRWMutex, DistNSLock, LocalLocker,
                                  LockServer, RemoteLocker)
from minio_tpu.storage.local import LocalStorage
from minio_tpu.storage.meta import (ErasureInfo, FileInfo, FileNotFoundErr,
                                    ObjectPartInfo)
from minio_tpu.storage.remote import RemoteStorage, StorageRPCService


# ---------------------------------------------------------------------------
# grid core
# ---------------------------------------------------------------------------

@pytest.fixture
def grid_pair():
    srv = GridServer(0, host="127.0.0.1")
    srv.start()
    client = GridClient("127.0.0.1", srv.port)
    yield srv, client
    client.close()
    srv.stop()


def test_grid_unary_and_concurrent(grid_pair):
    srv, client = grid_pair
    srv.register("echo", lambda p: p)
    srv.register("double", lambda p: p * 2)
    assert client.call("echo", {"a": [1, 2], "b": b"raw"}) == \
        {"a": [1, 2], "b": b"raw"}
    import threading
    results = []

    def worker(i):
        results.append(client.call("double", i))
    ts = [threading.Thread(target=worker, args=(i,)) for i in range(20)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sorted(results) == [i * 2 for i in range(20)]


def test_grid_stream(grid_pair):
    srv, client = grid_pair
    srv.register_stream("gen", lambda p: (i for i in range(p)))
    assert list(client.stream("gen", 5)) == [0, 1, 2, 3, 4]


def test_grid_error_mapping(grid_pair):
    srv, client = grid_pair

    def boom(p):
        raise FileNotFoundErr("nope")
    srv.register("boom", boom)
    with pytest.raises(RemoteCallError) as ei:
        client.call("boom")
    assert ei.value.code == "FileNotFound"
    with pytest.raises(RemoteCallError) as ei:
        client.call("no-such-handler")
    assert ei.value.code == "NoSuchHandler"


def test_grid_reconnect_after_server_restart():
    srv = GridServer(0, host="127.0.0.1")
    srv.start()
    port = srv.port
    srv.register("echo", lambda p: p)
    client = GridClient("127.0.0.1", port)
    assert client.call("echo", 1) == 1
    srv.stop()
    time.sleep(0.1)
    with pytest.raises(GridError):
        client.call("echo", 2, timeout=2.0)
    srv2 = GridServer(port, host="127.0.0.1")
    srv2.register("echo", lambda p: p)
    srv2.start()
    try:
        # Next call reconnects transparently.
        deadline = time.time() + 5
        while True:
            try:
                assert client.call("echo", 3) == 3
                break
            except GridError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
    finally:
        client.close()
        srv2.stop()


# ---------------------------------------------------------------------------
# remote StorageAPI
# ---------------------------------------------------------------------------

@pytest.fixture
def remote_drive(tmp_path):
    local = LocalStorage(str(tmp_path / "drv"))
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({local.root: local}).register_into(srv)
    srv.start()
    rem = RemoteStorage("127.0.0.1", srv.port, local.root)
    yield local, rem
    srv.stop()


def test_remote_storage_round_trip(remote_drive):
    local, rem = remote_drive
    rem.make_vol("vol")
    assert rem.stat_vol("vol").name == "vol"
    rem.write_all("vol", "cfg/x.json", b"{}")
    assert rem.read_all("vol", "cfg/x.json") == b"{}"
    big = os.urandom(9 << 20)        # > one chunk: chunked create path
    rem.create_file("vol", "obj/data/part.1", big)
    assert rem.read_file("vol", "obj/data/part.1") == big
    assert rem.read_file("vol", "obj/data/part.1", offset=100,
                         length=50) == big[100:150]
    assert rem.stat_info_file("vol", "obj/data/part.1").st_size == len(big)
    with pytest.raises(FileNotFoundErr):
        rem.read_all("vol", "missing")


def test_remote_storage_versions_and_walk(remote_drive):
    local, rem = remote_drive
    rem.make_vol("b")
    fi = FileInfo(volume="b", name="k", version_id="", mod_time=123,
                  size=3, metadata={"etag": "abc"},
                  parts=[ObjectPartInfo(number=1, size=3, actual_size=3)],
                  erasure=ErasureInfo(data_blocks=2, parity_blocks=1,
                                      block_size=1 << 20, index=1,
                                      distribution=(1, 2, 3)),
                  inline_data=b"xyz")
    rem.write_metadata("b", "k", fi)
    got = rem.read_version("b", "k", read_data=True)
    assert got.size == 3 and got.inline_data == b"xyz"
    assert got.erasure.distribution == (1, 2, 3)
    assert [v.name for v in rem.list_versions("b", "k")] == ["k"]
    walked = list(rem.walk_dir("b"))
    assert walked and walked[0][0] == "k"
    # Same journal bytes the local drive sees.
    assert walked[0][1] == local.read_all("b", os.path.join("k", "xl.meta"))
    rem.delete_version("b", "k")
    with pytest.raises(FileNotFoundErr):
        rem.read_version("b", "k")


def test_remote_rename_data_commit(remote_drive):
    local, rem = remote_drive
    rem.make_vol("b")
    rem.make_vol_if_missing(".mtpu.sys")
    fi = FileInfo(volume="b", name="obj", data_dir="dd-1", mod_time=5,
                  size=4, erasure=ErasureInfo(data_blocks=1, parity_blocks=0,
                                              block_size=1 << 20, index=1,
                                              distribution=(1,)))
    rem.create_file(".mtpu.sys", "staging/u1/dd-1/part.1", b"data")
    rem.rename_data(".mtpu.sys", "staging/u1", fi, "b", "obj")
    got = rem.read_version("b", "obj")
    assert got.data_dir == "dd-1" and got.size == 4
    assert rem.read_file("b", "obj/dd-1/part.1") == b"data"


def test_erasure_set_over_remote_drives(tmp_path):
    """A full ErasureSet where half the drives are remote."""
    locals_ = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    srv = GridServer(0, host="127.0.0.1")
    StorageRPCService({d.root: d for d in locals_}).register_into(srv)
    srv.start()
    try:
        from minio_tpu.object.erasure_object import ErasureSet
        disks = [locals_[0], locals_[1],
                 RemoteStorage("127.0.0.1", srv.port, locals_[2].root),
                 RemoteStorage("127.0.0.1", srv.port, locals_[3].root)]
        es = ErasureSet(disks)
        es.make_bucket("bkt")
        data = os.urandom(3 << 20)
        es.put_object("bkt", "obj", data)
        _, got = es.get_object("bkt", "obj")
        assert got == data
        # All 4 drives hold shards (2 written over RPC).
        for d in locals_:
            assert d.read_version("bkt", "obj").size == len(data)
        info = es.list_objects("bkt")
        assert [o.name for o in info.objects] == ["obj"]
        es.delete_object("bkt", "obj")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# dsync
# ---------------------------------------------------------------------------

def _lockers(n=3, remote=False):
    servers = [LockServer() for _ in range(n)]
    if not remote:
        return servers, [LocalLocker(s) for s in servers]
    grids, lks = [], []
    for s in servers:
        g = GridServer(0, host="127.0.0.1")
        s.register_into(g)
        g.start()
        grids.append(g)
        lks.append(RemoteLocker(GridClient("127.0.0.1", g.port)))
    return servers, lks, grids


def test_dsync_mutual_exclusion():
    _, lks = _lockers(3)
    m1 = DRWMutex(lks, "b/o")
    m2 = DRWMutex(lks, "b/o")
    assert m1.lock(write=True, timeout=1)
    assert not m2.lock(write=True, timeout=0.3)
    m1.unlock()
    assert m2.lock(write=True, timeout=1)
    m2.unlock()


def test_dsync_readers_share():
    _, lks = _lockers(3)
    r1 = DRWMutex(lks, "b/o")
    r2 = DRWMutex(lks, "b/o")
    w = DRWMutex(lks, "b/o")
    assert r1.lock(write=False, timeout=1)
    assert r2.lock(write=False, timeout=1)
    assert not w.lock(write=True, timeout=0.3)
    r1.unlock()
    r2.unlock()
    assert w.lock(write=True, timeout=1)
    w.unlock()


def test_dsync_quorum_with_one_locker_down():
    servers, lks, grids = _lockers(3, remote=True)
    grids[2].stop()          # one lock server dies
    time.sleep(0.1)
    m = DRWMutex(lks, "b/o")
    assert m.lock(write=True, timeout=3)   # 2/3 still a quorum
    m2 = DRWMutex(lks, "b/o")
    assert not m2.lock(write=True, timeout=0.3)
    m.unlock()
    for g in grids[:2]:
        g.stop()


def test_dsync_expiry_frees_crashed_holder():
    servers = [LockServer(ttl=0.2) for _ in range(3)]
    lks = [LocalLocker(s) for s in servers]
    m1 = DRWMutex(lks, "b/o")
    assert m1.lock(write=True, timeout=1)
    # Simulate holder crash: no unlock, no refresh; TTL frees it.
    m1._stop_refresh.set()
    time.sleep(0.35)
    m2 = DRWMutex(lks, "b/o")
    assert m2.lock(write=True, timeout=1)
    m2.unlock()


def test_dist_nslock_interface():
    _, lks = _lockers(3)
    ns = DistNSLock(lks)
    with ns.write("b", "o"):
        from minio_tpu.object.nslock import LockTimeout
        with pytest.raises(LockTimeout):
            with ns.write("b", "o", timeout=0.3):
                pass
    with ns.read("b", "o"):
        with ns.read("b", "o"):
            pass


# ---------------------------------------------------------------------------
# peer control plane
# ---------------------------------------------------------------------------

def test_peer_notifier_reload_handler():
    """PeerNotifier fan-out reaches a registered reload handler and
    drops the right caches (reference: cmd/notification.go)."""
    from minio_tpu.grid.peers import (PeerNotifier, RELOAD_HANDLER,
                                      make_reload_handler)

    class FakeIAM:
        invalidated = 0

        def invalidate(self):
            self.invalidated += 1

    class FakeLayer:
        dropped = None

        def invalidate_bucket_meta(self, bucket=""):
            self.dropped = bucket

    applied = []
    iam, layer = FakeIAM(), FakeLayer()
    srv = GridServer(0, host="127.0.0.1")
    srv.register(RELOAD_HANDLER, make_reload_handler(
        iam=iam, object_layer=layer,
        apply_config=lambda: applied.append(1)))
    srv.start()
    try:
        n = PeerNotifier([GridClient("127.0.0.1", srv.port)])
        n.broadcast("iam")
        n.broadcast("bucket-meta", bucket="bkt")
        n.broadcast("config")
        assert iam.invalidated == 1
        assert layer.dropped == "bkt"
        assert applied == [1]
        # Unknown kinds and dead peers are silently tolerated.
        n.broadcast("future-kind")
        dead = PeerNotifier([GridClient("127.0.0.1", 1)], timeout=0.5)
        dead.broadcast("iam")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# multi-process cluster (verify-healing style)
# ---------------------------------------------------------------------------

BASE = 9480


def _node_cmd(idx: int, endpoints: list[str], base: int = BASE,
              extra: tuple = ()) -> list[str]:
    return [sys.executable, "-m", "minio_tpu.server",
            "--address", f"127.0.0.1:{base + idx}",
            "--ec-backend", "host", "--boot-timeout", "60",
            *extra, *endpoints]


def _spawn(idx, endpoints, tmp_path, base: int = BASE, extra: tuple = ()):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    log = open(tmp_path / f"node{idx}.log", "wb")
    return subprocess.Popen(_node_cmd(idx, endpoints, base, extra),
                            stdout=log, stderr=subprocess.STDOUT, env=env)


def _wait_ready(tmp_path, idx, timeout=90):
    deadline = time.time() + timeout
    path = tmp_path / f"node{idx}.log"
    while time.time() < deadline:
        if path.exists() and b"serving S3" in path.read_bytes():
            return
        time.sleep(0.5)
    raise TimeoutError(
        f"node {idx} not ready:\n{path.read_bytes().decode()[-2000:]}")


def test_two_node_change_propagation(tmp_path):
    """Bucket-metadata and IAM changes made on one node take effect on
    the other IMMEDIATELY via the peer control plane — no TTL sleeps
    anywhere in this test (reference: cmd/peer-rest-client.go:304
    fan-out on every shared-state write)."""
    import json as _json
    base = 9484
    endpoints = []
    for n in range(2):
        for d in range(2):
            os.makedirs(tmp_path / f"n{n}" / f"d{d}")
            endpoints.append(
                f"http://127.0.0.1:{base + n}{tmp_path}/n{n}/d{d}")

    procs = []
    try:
        for n in range(2):
            procs.append(_spawn(n, endpoints, tmp_path, base=base,
                                extra=("--scanner-interval", "0")))
        for n in range(2):
            _wait_ready(tmp_path, n)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from s3client import S3Client
        c0 = S3Client(f"127.0.0.1:{base}")
        c1 = S3Client(f"127.0.0.1:{base + 1}")

        # --- bucket metadata: versioning toggle ------------------------
        assert c0.request("PUT", "/propbkt")[0] == 200
        # Warm node1's bucket-meta cache with versioning OFF.
        assert c1.request("PUT", "/propbkt/obj", body=b"v1")[0] == 200
        # Toggle versioning via node0; node1 must see it on the very
        # next write (stale cache would overwrite without a version).
        vxml = (b'<VersioningConfiguration><Status>Enabled</Status>'
                b'</VersioningConfiguration>')
        st, _, b = c0.request("PUT", "/propbkt", query={"versioning": ""},
                              body=vxml)
        assert st == 200, b
        assert c1.request("PUT", "/propbkt/obj", body=b"v2")[0] == 200
        st, _, listing = c1.request("GET", "/propbkt",
                                    query={"versions": ""})
        assert st == 200
        assert listing.count(b"<Version>") == 2, listing

        # --- IAM: credential revocation --------------------------------
        st, _, b = c0.request("PUT", "/minio/admin/v3/add-user",
                              query={"accessKey": "tempu"},
                              body=_json.dumps(
                                  {"secretKey": "tempsecret1"}).encode())
        assert st == 200, b
        st, _, b = c0.request(
            "PUT", "/minio/admin/v3/set-user-or-group-policy",
            query={"userOrGroup": "tempu", "policyName": "readwrite"})
        assert st == 200, b
        u1 = S3Client(f"127.0.0.1:{base + 1}", access_key="tempu",
                      secret_key="tempsecret1")
        # Warm node1's IAM cache: the user works there.
        st, _, got = u1.request("GET", "/propbkt/obj")
        assert st == 200 and got == b"v2"
        # Revoke via node0; node1 must refuse the NEXT request.
        st, _, b = c0.request("DELETE", "/minio/admin/v3/remove-user",
                              query={"accessKey": "tempu"})
        assert st == 200, b
        assert u1.request("GET", "/propbkt/obj")[0] == 403
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGKILL)
            except OSError:
                pass


@pytest.mark.slow
def test_three_node_cluster_kill_and_heal(tmp_path):
    """3 nodes x 2 drives (EC 3+3): write via node0, read via node1, kill
    node2 mid-workload, keep serving, restart, verify heal repairs its
    drives — the shape of buildscripts/verify-healing.sh."""
    sys_path = tmp_path
    endpoints = []
    for n in range(3):
        for d in range(2):
            os.makedirs(tmp_path / f"n{n}" / f"d{d}")
            endpoints.append(
                f"http://127.0.0.1:{BASE + n}{tmp_path}/n{n}/d{d}")
    procs = {}
    try:
        for n in range(3):
            procs[n] = _spawn(n, endpoints, tmp_path)
        for n in range(3):
            _wait_ready(tmp_path, n)

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from s3client import S3Client
        c0 = S3Client(f"127.0.0.1:{BASE}")
        c1 = S3Client(f"127.0.0.1:{BASE + 1}")

        st, _, b = c0.request("PUT", "/dbkt")
        assert st == 200, b
        payload = os.urandom(2 << 20)
        st, _, b = c0.request("PUT", "/dbkt/obj1", body=payload)
        assert st == 200, b
        # Cross-node read: node1 reads shards from node0/node2 drives.
        st, _, got = c1.request("GET", "/dbkt/obj1")
        assert st == 200 and got == payload

        # Kill node2; cluster keeps serving (EC 3+3, write quorum 4 of
        # the 4 remaining drives).
        procs[2].send_signal(signal.SIGKILL)
        procs[2].wait(timeout=10)
        payload2 = os.urandom(1 << 20)
        deadline = time.time() + 30
        while True:
            st, _, b = c0.request("PUT", "/dbkt/obj2", body=payload2)
            if st == 200:
                break
            assert time.time() < deadline, b
            time.sleep(1)
        st, _, got = c1.request("GET", "/dbkt/obj2")
        assert st == 200 and got == payload2
        st, _, got = c1.request("GET", "/dbkt/obj1")
        assert st == 200 and got == payload

        # Restart node2: its drives missed obj2; a read through node0
        # sees the gap and MRF-heals it in the background.
        procs[2] = _spawn(2, endpoints, tmp_path)
        _wait_ready(tmp_path, 2)
        st, _, got = c0.request("GET", "/dbkt/obj2")
        assert st == 200 and got == payload2
        deadline = time.time() + 30
        healed = False
        while time.time() < deadline and not healed:
            healed = all(
                os.path.exists(tmp_path / "n2" / f"d{d}" / "dbkt" / "obj2" /
                               "xl.meta") for d in range(2))
            if not healed:
                c0.request("GET", "/dbkt/obj2")
                time.sleep(1)
        assert healed, "node2 drives were not healed after restart"
        # And node2 itself serves the object.
        c2 = S3Client(f"127.0.0.1:{BASE + 2}")
        st, _, got = c2.request("GET", "/dbkt/obj2")
        assert st == 200 and got == payload2
    finally:
        for p in procs.values():
            try:
                p.send_signal(signal.SIGKILL)
            except OSError:
                pass


def test_remote_bulk_windowed_chunks(remote_drive):
    """Windowed (credit-limited) chunk uploads reassemble byte-identical
    regardless of arrival order, including odd sizes straddling chunk
    boundaries."""
    local, rem = remote_drive
    rem.make_vol("wv")
    for size in (4 * (1 << 20) + 17, 12 * (1 << 20) + 3):
        blob = os.urandom(size)
        rem.create_file("wv", f"big-{size}", blob)
        assert rem.read_file("wv", f"big-{size}") == blob
        assert local.read_file("wv", f"big-{size}") == blob
