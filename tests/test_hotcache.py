"""Hot-object read tier (object/hotcache.py): tinyLFU admission unit
tests and zero-stale-read chaos at every process topology.

  * admission sketch — doorkeeper absorbs one-hit wonders (scan
    resistance), repeated access raises the estimate, aging decays it;
  * residency — free-room warm-up admits, byte-cap eviction drains
    probation first, contested admission requires beating the victim's
    frequency, token protocol refuses puts that raced a mutation;
  * eligibility — ranged, versioned and SSE GETs never populate the
    cache; the kill switch disables it wholesale with byte-identical
    responses;
  * zero stale reads — concurrent overwrite/delete chaos in one
    process, across a 2-worker pre-forked fleet (shared-generation
    flush), and on a 3-node cluster through a partition/rejoin cycle
    (coherence gate refuses hits while partitioned).
"""

import os
import socket
import threading
import time
import types

import pytest

from minio_tpu.object import hotcache
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.cluster import Cluster
from tests.s3client import S3Client


def _wait(cond, timeout=30, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _info(etag="e1", version_id=""):
    return types.SimpleNamespace(etag=etag, version_id=version_id)


def _cache(monkeypatch, max_entries=8, max_bytes=1 << 20,
           obj_max=1 << 19):
    monkeypatch.setenv("MTPU_HOT_CACHE_MAX", str(max_entries))
    monkeypatch.setenv("MTPU_HOT_CACHE_BYTES", str(max_bytes))
    monkeypatch.setenv("MTPU_HOT_CACHE_OBJ_MAX", str(obj_max))
    monkeypatch.delenv("MTPU_HOT_CACHE", raising=False)
    hc = hotcache.HotObjectCache()
    # Anchor the (empty) topology walk so the first get() does not
    # register as a topology change and flush the cache under test.
    hc.attach_layer(None)
    return hc


# ---------------------------------------------------------------------------
# admission sketch
# ---------------------------------------------------------------------------

def test_sketch_doorkeeper_absorbs_first_access():
    sk = hotcache.FrequencySketch(64)
    sk.record("k")
    # First occurrence only set doorkeeper bits: sketch counters are 0.
    assert sk.estimate("k") == 1
    sk.record("k")
    assert sk.estimate("k") >= 2


def test_sketch_scan_resistance():
    """A scan of one-hit wonders never outranks a genuinely hot key:
    single occurrences stop at the doorkeeper (estimate 1) while the
    hot key's counters keep climbing."""
    sk = hotcache.FrequencySketch(64)
    for i in range(500):
        sk.record(f"scan-{i}")
    for _ in range(8):
        sk.record("hot")
    hot = sk.estimate("hot")
    assert hot >= 6
    assert all(sk.estimate(f"scan-{i}") < hot for i in range(0, 500, 50))


def test_sketch_aging_decays_estimates():
    sk = hotcache.FrequencySketch(16)
    for _ in range(30):
        sk.record("k")
    before = sk.estimate("k")
    sk._age()
    after = sk.estimate("k")
    assert after < before
    # Doorkeeper was reset too: a post-aging single hit is absorbed.
    sk.record("fresh")
    assert sk.estimate("fresh") == 1


# ---------------------------------------------------------------------------
# residency: admission, eviction, token protocol
# ---------------------------------------------------------------------------

def test_free_room_admits_and_roundtrips(monkeypatch):
    hc = _cache(monkeypatch)
    assert hc.admit("b", "o", 100)
    tok = hc.token("b")
    assert hc.put("b", "o", _info(), b"x" * 100, None, tok)
    entry = hc.get("b", "o")
    assert entry is not None and entry.body == b"x" * 100
    st = hc.stats()
    assert st["entries"] == 1 and st["bytes"] == 100 and st["hits"] == 1


def test_byte_cap_eviction_drains_probation_first(monkeypatch):
    hc = _cache(monkeypatch, max_entries=64, max_bytes=10_000,
                obj_max=5_000)
    tok = hc.token("b")
    for i in range(3):
        assert hc.put("b", f"o{i}", _info(), b"x" * 4_000, None, tok)
    st = hc.stats()
    assert st["bytes"] <= 10_000
    assert st["entries"] == 2 and st["evictions"] == 1
    # The LRU probation entry (o0) was the victim.
    assert hc.get("b", "o0") is None
    assert hc.get("b", "o2") is not None


def test_contested_admission_requires_frequency(monkeypatch):
    hc = _cache(monkeypatch, max_entries=4)
    tok = hc.token("b")
    for i in range(4):
        assert hc.put("b", f"r{i}", _info(), b"x" * 10, None, tok)
    # Cold candidate: estimate 0 does not beat the victim — rejected.
    assert not hc.admit("b", "cold", 10)
    assert hc.stats()["rejects"] == 1
    # A key that keeps missing accumulates frequency (get() records the
    # sketch on miss too) and eventually wins the contest.
    for _ in range(4):
        assert hc.get("b", "hot") is None
    assert hc.admit("b", "hot", 10)


def test_oversized_object_never_admitted(monkeypatch):
    hc = _cache(monkeypatch, obj_max=1_000)
    assert not hc.admit("b", "big", 1_001)
    tok = hc.token("b")
    assert not hc.put("b", "big", _info(), b"x" * 1_001, None, tok)
    assert hc.stats()["entries"] == 0


def test_token_put_refused_after_bucket_invalidation(monkeypatch):
    hc = _cache(monkeypatch)
    tok = hc.token("b")
    hc.invalidate_bucket("b")          # a mutation raced the read
    assert not hc.put("b", "o", _info(), b"data", None, tok)
    assert hc.get("b", "o") is None
    # A fresh token works again.
    tok = hc.token("b")
    assert hc.put("b", "o", _info(), b"data", None, tok)


def test_invalidate_bucket_is_exact(monkeypatch):
    hc = _cache(monkeypatch)
    ta, tb = hc.token("a"), hc.token("b")
    assert hc.put("a", "o", _info(), b"aa", None, ta)
    assert hc.put("b", "o", _info(), b"bb", None, tb)
    hc.invalidate_bucket("a")
    assert hc.get("a", "o") is None
    assert hc.get("b", "o") is not None


def test_probation_hit_promotes_to_protected(monkeypatch):
    hc = _cache(monkeypatch, max_entries=10)
    tok = hc.token("b")
    assert hc.put("b", "o", _info(), b"x", None, tok)
    assert ("b", "o") in hc._probation
    assert hc.get("b", "o") is not None
    assert ("b", "o") in hc._protected and ("b", "o") not in hc._probation


def test_partial_coherence_gates_per_owning_set(monkeypatch):
    """Per-owning-set coherence: a key's hit gates on ITS sets only —
    an unrelated set's downed gate doesn't blank the tier — and a
    recovered set gets its own entries selectively flushed before its
    hits resume."""
    hc = _cache(monkeypatch)
    gates = {0: True, 1: True}

    class FakeSet:
        def __init__(self, i):
            self.fi_cache = types.SimpleNamespace(
                remote_gate=lambda i=i: gates[i])
            self.metacache = types.SimpleNamespace(listeners=[])

    class FakePool:
        def __init__(self):
            self.sets = [FakeSet(0), FakeSet(1)]

        def set_index(self, key):
            return 0 if key.startswith("a") else 1

    hc.attach_layer(types.SimpleNamespace(pools=[FakePool()]))
    tok = hc.token("b")
    assert hc.put("b", "a-obj", _info(), b"A" * 100, None, tok)
    assert hc.put("b", "z-obj", _info(), b"Z" * 100, None, tok)
    assert hc.get("b", "a-obj") is not None
    assert hc.get("b", "z-obj") is not None

    gates[1] = False
    assert hc.get("b", "a-obj") is not None, \
        "unrelated set's partition blanked the tier"
    assert hc.get("b", "z-obj") is None, "served through a down gate"

    gates[1] = True
    # Recovery flush is selective: set 1's entry is gone (bumps during
    # the gap never reached us), set 0's stays hot.
    assert hc.get("b", "z-obj") is None
    assert hc.get("b", "a-obj") is not None
    # The flushed key re-admits and serves normally afterwards.
    tok = hc.token("b")
    assert hc.put("b", "z-obj", _info(), b"Z2" * 50, None, tok)
    assert hc.get("b", "z-obj") is not None


def test_kill_switch_disables_cache(monkeypatch):
    monkeypatch.setenv("MTPU_HOT_CACHE", "off")
    hc = hotcache.HotObjectCache()
    assert not hc.enabled
    assert not hc.admit("b", "o", 10)
    assert not hc.put("b", "o", _info(), b"x", None, hc.token("b"))
    assert hc.get("b", "o") is None


def test_split_head_roundtrip():
    head = (b"HTTP/1.1 200 OK\r\nServer: MinIO-TPU\r\n"
            b"Date: Thu, 01 Jan 1970 00:00:00 GMT\r\n"
            b"ETag: \"abc\"\r\nContent-Length: 3\r\n\r\n")
    prefix, suffix = hotcache.split_head(head)
    stamped = prefix + hotcache.date_bytes() + suffix
    assert stamped.startswith(b"HTTP/1.1 200 OK\r\nServer: MinIO-TPU\r\n"
                              b"Date: ")
    assert stamped.endswith(b"ETag: \"abc\"\r\nContent-Length: 3\r\n\r\n")
    assert hotcache.split_head(b"HTTP/1.1 200 OK\r\n\r\n") is None


# ---------------------------------------------------------------------------
# served-path behavior (in-process server, both front ends)
# ---------------------------------------------------------------------------

def _make_server(tmp_path, name, env=None, drives=4):
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        disks = [LocalStorage(str(tmp_path / name / f"d{i}"))
                 for i in range(drives)]
        srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
        srv.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return srv


@pytest.fixture(scope="module", params=["loop", "threads"])
def srv(request, tmp_path_factory):
    env = {"MTPU_HOT_CACHE": None}
    if request.param == "threads":
        env["MTPU_HTTP_EVENTLOOP"] = "off"
    server = _make_server(tmp_path_factory.mktemp(f"hc-{request.param}"),
                          request.param, env)
    server._front = request.param
    yield server
    server.stop()


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv.address)
    assert c.request("PUT", "/hcb")[0] == 200
    return c


def _resident(srv, bucket, key):
    hc = srv.hot_cache
    return (bucket, key) in hc._probation or (bucket, key) in hc._protected


def test_hit_response_identical_and_path_stamped(srv, cli):
    body = os.urandom(200_000)
    assert cli.request("PUT", "/hcb/hot", body=body)[0] == 200
    st, h_miss, got = cli.request("GET", "/hcb/hot")
    assert st == 200 and got == body
    # put() runs after the response's final write — wait for residency.
    assert _wait(lambda: _resident(srv, "hcb", "hot"))
    st, h_hit, got = cli.request("GET", "/hcb/hot")
    assert st == 200 and got == body
    strip = lambda h: {k: v for k, v in h.items() if k != "Date"}  # noqa: E731
    assert strip(h_hit) == strip(h_miss)
    # The thread front end stamps response_path AFTER the final send
    # returns — the client can finish reading (and this test scrape the
    # counters) a hair before the server thread runs the stamp line.
    assert _wait(lambda: srv.metrics.http_conn_stats()
                 ["response_path"].get("hotcache", 0) >= 1, timeout=5), \
        srv.metrics.http_conn_stats()["response_path"]
    assert srv.hot_cache.stats()["hits"] >= 1


def test_overwrite_and_delete_never_serve_stale(srv, cli):
    v1 = os.urandom(64_000)
    assert cli.request("PUT", "/hcb/mut", body=v1)[0] == 200
    st, _, got = cli.request("GET", "/hcb/mut")
    assert st == 200 and got == v1
    _wait(lambda: _resident(srv, "hcb", "mut"))
    v2 = os.urandom(64_000)
    assert cli.request("PUT", "/hcb/mut", body=v2)[0] == 200
    # The bump listener dropped the entry before the PUT acked: the
    # very next GET must be the new bytes.
    st, _, got = cli.request("GET", "/hcb/mut")
    assert st == 200 and got == v2
    _wait(lambda: _resident(srv, "hcb", "mut"))
    assert cli.request("DELETE", "/hcb/mut")[0] == 204
    st, _, _ = cli.request("GET", "/hcb/mut")
    assert st == 404


def test_concurrent_overwrite_chaos_zero_stale(srv):
    """Reader threads hammer GET over keep-alive sockets while the
    writer overwrites through 8 generations: every 200 must be a
    complete generation body (no torn reads), and after each acked PUT
    the next synchronous GET must serve the new generation."""
    size = 32_768
    gens = [bytes([g]) * size for g in range(8)]
    assert S3Client(srv.address).request("PUT", "/hcb/chaos",
                                         body=gens[0])[0] == 200
    stop = threading.Event()
    errors: list = []

    def reader():
        c = S3Client(srv.address, keepalive=True)
        try:
            while not stop.is_set():
                st, _, got = c.request("GET", "/hcb/chaos")
                if st == 200 and got not in gens:
                    errors.append(f"torn body len={len(got)}")
                    return
        except Exception as e:  # noqa: BLE001 - surfaced via errors
            if not stop.is_set():
                errors.append(repr(e))
        finally:
            c.close()

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    w = S3Client(srv.address, keepalive=True)
    try:
        for g in range(1, 8):
            assert w.request("PUT", "/hcb/chaos", body=gens[g])[0] == 200
            st, _, got = w.request("GET", "/hcb/chaos")
            assert st == 200 and got == gens[g], f"stale gen after PUT {g}"
    finally:
        stop.set()
        w.close()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert S3Client(srv.address).request("DELETE", "/hcb/chaos")[0] == 204
    assert S3Client(srv.address).request("GET", "/hcb/chaos")[0] == 404


def test_ranged_versioned_sse_gets_never_populate(srv, cli):
    body = os.urandom(50_000)
    assert cli.request("PUT", "/hcb/rng", body=body)[0] == 200
    st, _, got = cli.request("GET", "/hcb/rng",
                             headers={"Range": "bytes=10-99"})
    assert st == 206 and got == body[10:100]
    assert not _resident(srv, "hcb", "rng")
    # versionId GETs bypass the cache entirely.
    st, _, _ = cli.request("GET", "/hcb/rng", query={"versionId": "null"})
    assert not _resident(srv, "hcb", "rng")
    # SSE-C objects decrypt per-request and are never pinned.
    import base64
    import hashlib
    key = os.urandom(32)
    hdr = {"x-amz-server-side-encryption-customer-algorithm": "AES256",
           "x-amz-server-side-encryption-customer-key":
           base64.b64encode(key).decode(),
           "x-amz-server-side-encryption-customer-key-md5":
           base64.b64encode(hashlib.md5(key).digest()).decode()}
    assert cli.request("PUT", "/hcb/enc", body=body,
                       headers=hdr)[0] == 200
    for _ in range(2):
        st, _, got = cli.request("GET", "/hcb/enc", headers=hdr)
        assert st == 200 and got == body
    time.sleep(0.2)
    assert not _resident(srv, "hcb", "enc")


@pytest.fixture(scope="module")
def srv_off(tmp_path_factory):
    server = _make_server(tmp_path_factory.mktemp("hc-off"), "off",
                          {"MTPU_HOT_CACHE": "off"})
    yield server
    server.stop()


def test_kill_switch_server_byte_identical(srv_off):
    """MTPU_HOT_CACHE=off: no admission, no hotcache response path, and
    repeat GETs stay byte-identical modulo the Date stamp (the miss
    path is deterministic — proving the knob changes nothing visible)."""
    assert not srv_off.hot_cache.enabled
    cli = S3Client(srv_off.address, keepalive=True)
    assert cli.request("PUT", "/offb")[0] == 200
    body = os.urandom(100_000)
    assert cli.request("PUT", "/offb/obj", body=body)[0] == 200
    st, h1, g1 = cli.request("GET", "/offb/obj")
    st2, h2, g2 = cli.request("GET", "/offb/obj")
    assert st == st2 == 200 and g1 == g2 == body
    strip = lambda h: {k: v for k, v in h.items() if k != "Date"}  # noqa: E731
    assert strip(h1) == strip(h2)
    assert srv_off.hot_cache.stats()["entries"] == 0
    rp = srv_off.metrics.http_conn_stats()["response_path"]
    assert rp.get("hotcache", 0) == 0, rp
    cli.close()


# ---------------------------------------------------------------------------
# 2-worker pre-forked fleet: shared-generation flush
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """2 pre-forked workers (subprocess: pytest has JAX loaded and
    fork-after-JAX is unsafe), each with its own private hot cache
    coupled only through the shared list.gen bump file."""
    import signal
    import subprocess
    import sys

    root = tmp_path_factory.mktemp("hc-fleet")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2")
    env.pop("MTPU_HOT_CACHE", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "minio_tpu.server",
         "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
         f"{root}/d{{1...4}}"],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    address = f"127.0.0.1:{port}"
    deadline = time.time() + 90
    ready = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        try:
            if S3Client(address).request(
                    "GET", "/minio/health/live", sign=False)[0] == 200:
                ready = True
                break
        except OSError:
            time.sleep(0.4)
    if not ready:
        out = proc.stdout.read().decode(errors="replace") \
            if proc.stdout else ""
        proc.kill()
        pytest.skip(f"worker fleet failed to boot: {out[-800:]}")
    yield address
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=25)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_fleet_overwrite_flushes_sibling_caches(fleet):
    """Warm BOTH workers' hot caches (fresh connections spread accepts
    across listeners), overwrite through one worker, then every
    subsequent GET — whichever worker lands it — must serve the new
    bytes: the sibling observed the shared generation bump and
    flushed."""
    addr = fleet
    assert S3Client(addr).request("PUT", "/flh")[0] == 200
    v1 = os.urandom(48_000)
    assert S3Client(addr).request("PUT", "/flh/obj", body=v1)[0] == 200
    for _ in range(8):        # warm whichever workers take the accepts
        st, _, got = S3Client(addr).request("GET", "/flh/obj")
        assert st == 200 and got == v1
    v2 = os.urandom(48_000)
    assert S3Client(addr).request("PUT", "/flh/obj", body=v2)[0] == 200
    for i in range(8):
        st, _, got = S3Client(addr).request("GET", "/flh/obj")
        assert st == 200 and got == v2, f"stale read on GET {i}"
    assert S3Client(addr).request("DELETE", "/flh/obj")[0] == 204
    for _ in range(4):
        assert S3Client(addr).request("GET", "/flh/obj")[0] == 404


# ---------------------------------------------------------------------------
# 3-node cluster: partition/rejoin, gate-down refusal
# ---------------------------------------------------------------------------

def test_cluster_partition_rejoin_hot_cache_zero_stale(tmp_path):
    """Warm a node's hot cache with repeat GETs, partition its grid
    plane, overwrite through the healthy side: the partitioned node's
    coherence gate is down so the RAM copy must NOT be served; after
    rejoin the node serves the new bytes and never the old."""
    with Cluster(tmp_path, nodes=3, drives_per_node=2) as cluster:
        c0 = cluster.client(0)
        c2 = cluster.client(2, keepalive=True)
        assert c0.request("PUT", "/hcl")[0] == 200
        v1 = os.urandom(200_000)
        deadline = time.time() + 45
        while True:
            st, _, b = c0.request("PUT", "/hcl/obj", body=v1)
            if st == 200:
                break
            assert time.time() < deadline, f"PUT: {st} {b[:200]}"
            time.sleep(1)
        # Repeat GETs on node2: miss + admit, then hot hits.
        for _ in range(3):
            st, _, got = c2.request("GET", "/hcl/obj")
            assert st == 200 and got == v1

        cluster.partition(2)
        time.sleep(1.0)          # > chaos poll + grid sync interval
        v2 = os.urandom(200_000)
        deadline = time.time() + 45
        while True:
            st, _, b = c0.request("PUT", "/hcl/obj", body=v2)
            if st == 200:
                break
            assert time.time() < deadline, f"PUT: {st} {b[:200]}"
            time.sleep(1)
        # The partitioned node holds v1 in RAM, but its gate is down:
        # an honest error is fine, v1 is never.
        st, _, got = c2.request("GET", "/hcl/obj")
        assert not (st == 200 and got == v1), "stale hot-cache hit"

        cluster.rejoin(2)
        deadline = time.time() + 45
        while True:
            st, _, got = c2.request("GET", "/hcl/obj")
            if st == 200 and got == v2:
                break
            assert not (st == 200 and got == v1), "stale read after rejoin"
            assert time.time() < deadline, f"rejoin GET: {st}"
            time.sleep(1)
        # And the fresh bytes are served (hot again) repeatably.
        for _ in range(2):
            st, _, got = c2.request("GET", "/hcl/obj")
            assert st == 200 and got == v2
        c2.close()
