"""Event-loop connection plane (s3/eventloop.py): adversarial
connection behavior the epoll front end must absorb.

  * kill-switch — MTPU_HTTP_EVENTLOOP=off reverts wholesale to the
    thread-per-connection path; the same e2e surface runs green both
    ways (parametrized fixture);
  * slowloris — partial request heads never occupy an executor thread
    and are reaped by the idle deadline while parked;
  * mid-body client death — a 1k-connection churn storm of partial
    heads, half-sent bodies, and instant disconnects leaves bufpool
    leases net zero and the connection table empty;
  * pipelining — back-to-back requests buffered in one segment are
    served on one dispatch;
  * idle-timeout parity — MTPU_HTTP_KEEPALIVE_S closes idle keep-alive
    connections under the loop exactly as under the thread path;
  * connection-level backpressure — accepts past MTPU_MAX_CONNS are
    answered 503 + Retry-After before any byte is read;
  * EAGAIN tail offload — a response's final write against a slow
    reader parks on the loop's EPOLLOUT drain instead of pinning the
    executor thread;
  * parked-idle memory model — idle keep-alive connections hold ZERO
    pooled recv buffers (hibernated leases);
  * sendfile short-circuit — whole-object plaintext GETs of a
    tier-resident version go file->socket in-kernel and stamp the
    response-path split.
"""

import os
import select
import socket
import time

import pytest

from minio_tpu.io.bufpool import global_pool
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3 import eventloop
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client, ramp_get

pytestmark = pytest.mark.skipif(not hasattr(select, "epoll"),
                                reason="epoll front end is Linux-only")


def _make_server(tmp_path, name, env=None, drives=4):
    """S3Server over fresh local drives with `env` latched for the
    construction window (the front-end class and its knobs are read
    once, at bind time)."""
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        disks = [LocalStorage(str(tmp_path / name / f"d{i}"))
                 for i in range(drives)]
        srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
        srv.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return srv


def _raw_conn(srv, timeout=10):
    host, _, port = srv.address.rpartition(":")
    s = socket.create_connection((host, int(port)), timeout=timeout)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _wait(cond, timeout=30, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# kill-switch + both-ways e2e surface
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module", params=["loop", "threads"])
def srv(request, tmp_path_factory):
    """One server per front end: the epoll loop and the
    MTPU_HTTP_EVENTLOOP=off thread path must be observably identical."""
    env = {} if request.param == "loop" else {"MTPU_HTTP_EVENTLOOP": "off"}
    server = _make_server(tmp_path_factory.mktemp(f"el-{request.param}"),
                          request.param, env)
    server._front = request.param
    yield server
    server.stop()


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv.address)
    assert c.request("PUT", "/evloop")[0] == 200
    return c


def test_front_end_selection(srv):
    cls = type(srv.httpd).__name__
    if srv._front == "loop":
        assert cls == "EventLoopServer"
        assert srv.eventloop_stats()["enabled"] is True
    else:
        assert cls != "EventLoopServer"
        assert srv.eventloop_stats() is None


def test_e2e_roundtrip_both_front_ends(srv, cli):
    body = os.urandom(300_000)
    st, _, _ = cli.request("PUT", "/evloop/obj", body=body,
                           headers={"x-amz-meta-k": "v"})
    assert st == 200
    st, h, got = cli.request("GET", "/evloop/obj")
    assert st == 200 and got == body and h.get("x-amz-meta-k") == "v"
    st, h, got = cli.request("GET", "/evloop/obj",
                             headers={"Range": "bytes=1000-2999"})
    assert st == 206 and got == body[1000:3000]
    st, _, _ = cli.request("PUT", "/evloop/chunked", body=body,
                           chunked=True)
    assert st == 200
    st, _, got = cli.request("GET", "/evloop/chunked")
    assert st == 200 and got == body
    st, _, got = cli.request("GET", "/evloop/missing-key")
    assert st == 404


def test_e2e_keepalive_reuse_both_front_ends(srv):
    ka = S3Client(srv.address, keepalive=True)
    base = srv.metrics.http_conn_stats()["keepalive_reuses"]
    for _ in range(4):
        assert ka.request("GET", "/minio/health/live", sign=False)[0] == 200
    assert srv.metrics.http_conn_stats()["keepalive_reuses"] >= base + 3
    ka.close()


def test_e2e_ramp_driver_both_front_ends(srv, cli):
    body = os.urandom(64 << 10)
    assert cli.request("PUT", "/evloop/ramp", body=body)[0] == 200
    r = ramp_get(srv.address, "/evloop/ramp", len(body), connections=8,
                 duration_s=0.5)
    assert r["errors"] == 0 and r["ops"] >= 8, r
    assert r["bytes"] == r["ops"] * len(body)


def test_pipelined_requests(srv):
    """Two requests in one TCP segment: under the loop the second head
    is already buffered at dispatch and must be served back-to-back on
    the same executor turn; under threads the hot loop handles it."""
    sock = _raw_conn(srv)
    try:
        sock.sendall(b"GET /minio/health/live HTTP/1.1\r\nHost: x\r\n\r\n"
                     b"GET /minio/health/live HTTP/1.1\r\nHost: x\r\n"
                     b"Connection: close\r\n\r\n")
        raw = bytearray()
        while True:
            try:
                got = sock.recv(65536)
            except OSError:
                break
            if not got:
                break
            raw += got
        assert raw.count(b"HTTP/1.1 200") == 2, raw[:200]
    finally:
        sock.close()


# ---------------------------------------------------------------------------
# idle deadline: slowloris + keep-alive parity
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module", params=["loop", "threads"])
def reap_srv(request, tmp_path_factory):
    env = {"MTPU_HTTP_KEEPALIVE_S": "1"}
    if request.param == "threads":
        env["MTPU_HTTP_EVENTLOOP"] = "off"
    server = _make_server(tmp_path_factory.mktemp(f"reap-{request.param}"),
                          request.param, env)
    server._front = request.param
    yield server
    server.stop()


def _closed_within(sock, seconds) -> bool:
    sock.settimeout(seconds)
    try:
        return sock.recv(4096) == b""
    except socket.timeout:
        return False
    except OSError:
        return True


def test_slowloris_partial_head_reaped(reap_srv):
    """A drip-fed request head must never graduate to an executor
    thread and must die on the idle deadline (same MTPU_HTTP_KEEPALIVE_S
    budget the thread path applies via settimeout)."""
    stats0 = reap_srv.eventloop_stats()
    sock = _raw_conn(reap_srv)
    try:
        sock.sendall(b"GET /minio/health/live HTTP/1.1\r\nHo")
        assert _closed_within(sock, 8), \
            "slowloris connection survived the idle deadline"
    finally:
        sock.close()
    if reap_srv._front == "loop":
        assert _wait(lambda: reap_srv.eventloop_stats()["reaped_idle_total"]
                     > stats0["reaped_idle_total"], timeout=5)
        # The partial head was parked, not dispatched.
        assert reap_srv.eventloop_stats()["dispatch_total"] == \
            stats0["dispatch_total"]


def test_idle_keepalive_timeout_parity(reap_srv):
    """An idle keep-alive connection (one complete request served) is
    closed by the same deadline either way."""
    sock = _raw_conn(reap_srv)
    try:
        sock.sendall(b"GET /minio/health/live HTTP/1.1\r\nHost: x\r\n\r\n")
        sock.settimeout(10)
        head = sock.recv(65536)
        assert head.startswith(b"HTTP/1.1 200"), head[:64]
        t0 = time.monotonic()
        assert _closed_within(sock, 8), \
            "idle keep-alive connection survived the deadline"
        # The deadline is ~1s; anything past a few seconds means a
        # different (wrong) timer closed it.
        assert time.monotonic() - t0 < 6
    finally:
        sock.close()


# ---------------------------------------------------------------------------
# churn storm: leases net zero, table empty
# ---------------------------------------------------------------------------

def _signed_put_head(address, path, clen) -> bytes:
    """A correctly signed PUT head declaring `clen` body bytes (body
    signed UNSIGNED-PAYLOAD so partial delivery is the only sin)."""
    import datetime
    import hashlib
    import hmac

    from minio_tpu.s3 import sigv4
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"
    lower = {"host": address, "x-amz-date": amz_date,
             "x-amz-content-sha256": sigv4.UNSIGNED_PAYLOAD,
             "content-length": str(clen)}
    signed = sorted(lower)
    canon = sigv4.canonical_request("PUT", path, {}, lower, signed,
                                    sigv4.UNSIGNED_PAYLOAD)
    sts = sigv4.string_to_sign(amz_date, scope, canon)
    key = sigv4.signing_key("minioadmin", date, "us-east-1")
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return (f"PUT {path} HTTP/1.1\r\nHost: {address}\r\n"
            f"x-amz-date: {amz_date}\r\n"
            f"x-amz-content-sha256: {sigv4.UNSIGNED_PAYLOAD}\r\n"
            f"Content-Length: {clen}\r\n"
            f"Authorization: {sigv4.ALGORITHM} "
            f"Credential=minioadmin/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}\r\n"
            "\r\n").encode()


def test_churn_storm_leases_net_zero(srv, cli):
    """1k-connection churn storm of adversarial disconnects: instant
    close, partial head then close, and signed PUT dying mid-body.
    Afterwards the connection table drains to the fixture's own clients
    and the bufpool holds not one more outstanding lease than before —
    the leak the recv-buffer/body-lease plumbing must never have."""
    pool = global_pool()
    # Settle: let any prior test's connections finish dying first.
    time.sleep(0.5)
    base_outstanding = pool.stats()["outstanding"]
    put_head = _signed_put_head(srv.address, "/evloop/churn-victim",
                                64 << 10)
    n = 0
    for round_ in range(100):
        socks = []
        try:
            for kind in range(10):
                s = _raw_conn(srv, timeout=5)
                if kind % 3 == 1:
                    s.sendall(b"GET /x HTTP/1.1\r\nHo")       # partial head
                elif kind % 3 == 2:
                    s.sendall(put_head + b"\x00" * 1024)      # mid-body die
                socks.append(s)
                n += 1
        finally:
            for s in socks:
                # Abortive close (RST where the stack allows): the
                # nastiest client exit there is.
                try:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                 b"\x01\x00\x00\x00\x00\x00\x00\x00")
                except OSError:
                    pass
                s.close()
    assert n == 1000
    if srv._front == "loop":
        assert _wait(lambda: srv.eventloop_stats()["parked"]
                     + srv.eventloop_stats()["active"] <= 1,
                     timeout=60), srv.eventloop_stats()
    assert _wait(lambda: pool.stats()["outstanding"] <= base_outstanding,
                 timeout=60), \
        (base_outstanding, pool.stats())
    # The server still serves.
    assert cli.request("GET", "/minio/health/live", sign=False)[0] == 200


# ---------------------------------------------------------------------------
# connection-level backpressure
# ---------------------------------------------------------------------------

def test_accept_shed_503(tmp_path):
    server = _make_server(tmp_path, "shed", {"MTPU_MAX_CONNS": "8"})
    try:
        assert server.eventloop_stats()["max_conns"] == 8
        parked = []
        try:
            for _ in range(8):
                parked.append(_raw_conn(server))
            assert _wait(lambda: server.eventloop_stats()["parked"] == 8,
                         timeout=10), server.eventloop_stats()
            extra = _raw_conn(server)
            extra.settimeout(10)
            got = extra.recv(4096)
            assert got.startswith(b"HTTP/1.1 503"), got[:80]
            assert b"Retry-After" in got
            assert extra.recv(4096) == b""          # closed after shed
            extra.close()
            assert server.eventloop_stats()["shed_total"] >= 1
            # Freeing one slot re-opens admission.
            parked.pop().close()
            assert _wait(lambda: server.eventloop_stats()["parked"] == 7,
                         timeout=10)
            ok = _raw_conn(server)
            ok.sendall(b"GET /minio/health/live HTTP/1.1\r\n"
                       b"Host: x\r\n\r\n")
            ok.settimeout(10)
            assert ok.recv(4096).startswith(b"HTTP/1.1 200")
            ok.close()
        finally:
            for s in parked:
                s.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# EAGAIN tail offload + parked-idle memory model
# ---------------------------------------------------------------------------

def test_final_write_offloads_to_loop(srv, cli):
    """A slow reader on a response's final write must park the tail on
    the loop's EPOLLOUT drain, not pin the executor thread — and the
    bytes must still arrive intact."""
    if srv._front != "loop":
        pytest.skip("loop-owned response tails are event-loop machinery")
    body = os.urandom(512 << 10)
    assert cli.request("PUT", "/evloop/slowread", body=body)[0] == 200
    # Accepted sockets inherit the listener's buffers: shrink the send
    # side so a 512 KiB single-window response can never fit inline.
    srv.httpd.socket.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                65536)
    host, _, port = srv.address.rpartition(":")
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    # A tiny receive window guarantees the server's final gathered
    # write cannot complete inline.
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8192)
    sock.connect((host, int(port)))
    url = cli.presign("GET", "/evloop/slowread")
    try:
        sock.sendall(f"GET {url} HTTP/1.1\r\nHost: {srv.address}\r\n"
                     "Connection: close\r\n\r\n".encode())
        # Don't read: the tail must be parked in _WRITING state.
        assert _wait(lambda: srv.eventloop_stats()["writing"] >= 1,
                     timeout=15), srv.eventloop_stats()
        sock.settimeout(60)
        raw = bytearray()
        while True:
            got = sock.recv(65536)
            if not got:
                break
            raw += got
            time.sleep(0.001)           # stay slow; the loop drains
        head_end = raw.find(b"\r\n\r\n")
        assert raw.startswith(b"HTTP/1.1 200"), raw[:64]
        assert bytes(raw[head_end + 4:]) == body
    finally:
        sock.close()


def test_parked_idle_connections_hold_no_leases(tmp_path):
    """The idle-connection memory model the tentpole charters: parked
    keep-alive connections hibernate their pooled recv buffer, so N
    idle connections hold ZERO leases (fds + small objects only)."""
    server = _make_server(tmp_path, "park", {})
    pool = global_pool()
    conns = []
    try:
        time.sleep(0.3)
        base = pool.stats()["outstanding"]
        for _ in range(100):
            s = _raw_conn(server)
            s.sendall(b"GET /minio/health/live HTTP/1.1\r\n"
                      b"Host: x\r\n\r\n")
            conns.append(s)
        for s in conns:
            s.settimeout(10)
            assert s.recv(65536).startswith(b"HTTP/1.1 200")
        assert _wait(lambda: server.eventloop_stats()["parked"] == 100,
                     timeout=15), server.eventloop_stats()
        assert _wait(lambda: pool.stats()["outstanding"] <= base,
                     timeout=10), (base, pool.stats())
    finally:
        for s in conns:
            s.close()
        server.stop()


# ---------------------------------------------------------------------------
# sendfile short-circuit + connection-plane observability
# ---------------------------------------------------------------------------

@pytest.fixture
def tiered_srv(tmp_path):
    """A live server whose object layer has one FS-warm tier and one
    transitioned 3 MiB object (tb/logs/app)."""
    from minio_tpu.object.lifecycle import make_scanner_hook
    from minio_tpu.object.scanner import Scanner
    from minio_tpu.object.tier import TierRegistry
    from minio_tpu.object.types import PutOptions

    disks = [LocalStorage(str(tmp_path / "t" / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("tb")
    reg = TierRegistry([es])
    reg.add("COLD", {"type": "fs", "path": str(tmp_path / "cold")})
    es.tiers = reg
    meta = es.get_bucket_meta("tb")
    meta["config:lifecycle"] = (
        '<LifecycleConfiguration><Rule><ID>t</ID>'
        '<Status>Enabled</Status><Filter><Prefix></Prefix></Filter>'
        '<Transition><Days>1</Days><StorageClass>COLD</StorageClass>'
        '</Transition></Rule></LifecycleConfiguration>')
    es.set_bucket_meta("tb", meta)
    body = os.urandom(3 << 20)
    es.put_object("tb", "logs/app", body, PutOptions())
    sc = Scanner([es], throttle=0)
    sc.on_object.append(
        make_scanner_hook(now_fn=lambda: time.time() + 2 * 86400))
    sc.scan_cycle()
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server, body
    server.stop()


def test_sendfile_short_circuit_tier_get(tiered_srv):
    server, body = tiered_srv
    cli = S3Client(server.address)
    st, _, got = cli.request("GET", "/tb/logs/app")
    assert st == 200 and got == body
    rp = server.metrics.http_conn_stats()["response_path"]
    assert rp.get("sendfile", 0) == 1, rp
    # Ranged reads leave the sendfile fast path; since the first GET
    # admitted the object to the hot read tier, the range is sliced
    # from the RAM copy (falls back to pooled windows when it isn't).
    st, _, got = cli.request("GET", "/tb/logs/app",
                             headers={"Range": "bytes=100-199"})
    assert st == 206 and got == body[100:200]
    rp2 = server.metrics.http_conn_stats()["response_path"]
    assert rp2["sendfile"] == 1, rp2
    assert rp2.get("hotcache", 0) + rp2.get("pooled", 0) >= 1, rp2
    # The split is exported.
    text = server.metrics.render()
    assert 'minio_tpu_http_response_path_total{path="sendfile"} 1' in text


def test_connection_plane_metrics_exported(srv, cli):
    text = srv.metrics.render(server=srv)
    for name in ("minio_tpu_http_eventloop_enabled",
                 "minio_tpu_http_parked_connections",
                 "minio_tpu_http_dispatched_connections",
                 "minio_tpu_http_conns_accepted_total",
                 "minio_tpu_http_conns_shed_total",
                 "minio_tpu_http_conn_reparks_total",
                 "minio_tpu_http_idle_reaped_total",
                 "minio_tpu_http_response_path_total"):
        assert name in text, name
    if srv._front == "loop":
        assert "minio_tpu_http_eventloop_enabled 1" in text
        assert "minio_tpu_http_loop_lag_seconds" in text
    else:
        assert "minio_tpu_http_eventloop_enabled 0" in text


def test_admin_info_connections_section(srv):
    from minio_tpu.s3 import metrics as metrics_mod
    info = metrics_mod.node_info(srv)
    if srv._front == "loop":
        conns = info["connections"]
        for k in ("parked", "active", "max_conns", "accepted_total",
                  "shed_total", "reparks_total", "reaped_idle_total"):
            assert k in conns, k
        assert "loop_lag_ms" in conns
    else:
        assert "connections" not in info


# ---------------------------------------------------------------------------
# 2-worker pre-forked fleet, both front ends
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", params=["loop", "threads"])
def fleet(request, tmp_path_factory):
    """A 2-worker pre-forked fleet per front end (subprocess: the
    pytest process has JAX loaded and fork-after-JAX is unsafe) — the
    ISSUE's 2-worker conformance subset, green both ways."""
    import signal
    import subprocess
    import sys

    root = tmp_path_factory.mktemp(f"fleet-{request.param}")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2")
    if request.param == "threads":
        env["MTPU_HTTP_EVENTLOOP"] = "off"
    else:
        env.pop("MTPU_HTTP_EVENTLOOP", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "minio_tpu.server",
         "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
         f"{root}/d{{1...4}}"],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    address = f"127.0.0.1:{port}"
    deadline = time.time() + 90
    ready = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        try:
            if S3Client(address).request(
                    "GET", "/minio/health/live", sign=False)[0] == 200:
                ready = True
                break
        except OSError:
            time.sleep(0.4)
    if not ready:
        out = proc.stdout.read().decode(errors="replace") \
            if proc.stdout else ""
        proc.kill()
        pytest.skip(f"worker fleet failed to boot: {out[-800:]}")
    yield address, request.param
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=25)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_fleet_conformance_subset_both_front_ends(fleet):
    """Object CRUD + listings + ranged GET across 2 pre-forked workers,
    each request on a FRESH connection so the kernel spreads accepts
    over both workers' listeners."""
    addr, _front = fleet
    assert S3Client(addr).request("PUT", "/flb")[0] == 200
    body = os.urandom(300_000)
    assert S3Client(addr).request("PUT", "/flb/obj", body=body)[0] == 200
    st, _, got = S3Client(addr).request("GET", "/flb/obj")
    assert st == 200 and got == body
    st, _, part = S3Client(addr).request(
        "GET", "/flb/obj", headers={"Range": "bytes=100-299"})
    assert st == 206 and part == body[100:300]
    for _ in range(4):
        st, _, lst = S3Client(addr).request("GET", "/flb")
        assert st == 200 and b"obj" in lst
    ka = S3Client(addr, keepalive=True)
    for i in range(4):
        assert ka.request("PUT", f"/flb/ka-{i}", body=b"x" * 1024)[0] \
            == 200
    ka.close()
    assert S3Client(addr).request("DELETE", "/flb/obj")[0] == 204
    st, _, lst = S3Client(addr).request("GET", "/flb")
    assert b"<Key>obj</Key>" not in lst


def test_fleet_connections_admin_and_metrics(fleet):
    """Any worker's admin-info/metrics scrape reports the FLEET's
    connection plane (io/workers.py carries each worker's loop snapshot
    in its control-plane stat)."""
    import json

    addr, front = fleet
    st, _, raw = S3Client(addr).request("GET", "/minio/admin/v3/info")
    assert st == 200
    info = json.loads(raw)
    assert len(info.get("workers", [])) == 2
    if front == "loop":
        conns = info.get("connections")
        assert conns, "fleet admin info missing connections section"
        assert conns["accepted_total"] >= 1
        assert conns["max_conns"] > 0
        assert "loop_lag_ms" in conns
    else:
        assert "connections" not in info
    st, _, text = S3Client(addr).request(
        "GET", "/minio/v2/metrics/cluster")
    assert st == 200
    text = text.decode()
    want = "minio_tpu_http_eventloop_enabled 1" if front == "loop" \
        else "minio_tpu_http_eventloop_enabled 0"
    assert want in text


def test_merge_loop_stats_fleet_view():
    from minio_tpu.s3.metrics import merge_loop_stats
    a = eventloop.EventLoopServer(("127.0.0.1", 0), _DummyHandler,
                                  workers=1)
    b = eventloop.EventLoopServer(("127.0.0.1", 0), _DummyHandler,
                                  workers=1)
    a.accepted_total, b.accepted_total = 3, 4
    a.loop_lag.observe(0.001)
    b.loop_lag.observe(0.002)
    merged = merge_loop_stats([a.stats(), b.stats(), None, "junk"])
    assert merged["enabled"] and merged["accepted_total"] == 7
    assert merged["loop_lag"]["count"] == 2
    a.server_close()
    b.server_close()
    for fd in (a._wr, a._ww, b._wr, b._ww):
        os.close(fd)
    a._epoll.close()
    b._epoll.close()


class _DummyHandler:
    loop_native_lib = None
    loop_keepalive_s = 75.0
