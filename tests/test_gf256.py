import numpy as np

from minio_tpu.ops import gf256


def test_field_basics():
    assert gf256.gf_mul(0, 77) == 0
    assert gf256.gf_mul(1, 77) == 77
    # 2*142 wraps the reducing polynomial 0x11D
    assert gf256.gf_mul(2, 0x8E) == ((0x8E << 1) ^ 0x11D) & 0xFF
    for a in (1, 2, 3, 0x53, 0xCA, 255):
        inv = gf256.gf_div(1, a)
        assert gf256.gf_mul(a, inv) == 1


def test_exp_matches_repeated_mul():
    for a in (0, 1, 2, 5, 0x1D, 0xFF):
        acc = 1
        for n in range(10):
            assert gf256.gf_exp(a, n) == acc
            acc = gf256.gf_mul(acc, a)


def test_coding_matrix_systematic():
    for k, m in [(2, 2), (4, 2), (8, 4), (12, 3)]:
        mat = gf256.coding_matrix(k, m)
        assert mat.shape == (k + m, k)
        assert np.array_equal(mat[:k], np.eye(k, dtype=np.uint8))


def test_inverse_roundtrip():
    rng = np.random.default_rng(0)
    for n in (2, 5, 8):
        # random invertible matrix (retry until invertible)
        while True:
            m = rng.integers(0, 256, size=(n, n), dtype=np.uint8)
            try:
                inv = gf256.gf_inverse(m)
                break
            except ValueError:
                continue
        assert np.array_equal(gf256.gf_matmul(m, inv), np.eye(n, dtype=np.uint8))


def test_bit_matrix_equivalence():
    rng = np.random.default_rng(1)
    mat = rng.integers(0, 256, size=(3, 5), dtype=np.uint8)
    data = rng.integers(0, 256, size=(5, 64), dtype=np.uint8)
    want = gf256.gf_matvec_bytes(mat, data)
    bm = gf256.bit_matrix(mat)  # [24, 40]
    bits = np.unpackbits(data[:, None, :], axis=1, bitorder="little").reshape(40, 64)
    out_bits = (bm.astype(np.int32) @ bits.astype(np.int32)) & 1
    got = np.packbits(out_bits.reshape(3, 8, 64).astype(np.uint8), axis=1,
                      bitorder="little").reshape(3, 64)
    assert np.array_equal(want, got)
