"""STS AssumeRole (temporary credentials, session-policy intersection,
expiry, session tokens) and IAM groups (membership-resolved policies)
(reference: cmd/sts-handlers.go:61, cmd/iam.go group handling)."""

import json
import time
import urllib.parse

import pytest

from minio_tpu.iam import IAMError, IAMSys
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import Credentials, S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


# ---------------------------------------------------------------------------
# store-level semantics
# ---------------------------------------------------------------------------

@pytest.fixture
def iam(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    return IAMSys([ErasureSet(disks)], "root", "rootsecret")


def test_group_policies_grant_members(iam):
    iam.add_user("gina", "ginasecret")
    iam.update_group_members("readers", ["gina"])
    iam.attach_policy("readers", ["readonly"])
    assert iam.is_allowed("gina", "s3:GetObject", "b/k")
    assert not iam.is_allowed("gina", "s3:PutObject", "b/k")
    # Removal revokes the group grant.
    iam.update_group_members("readers", ["gina"], remove=True)
    assert not iam.is_allowed("gina", "s3:GetObject", "b/k")
    # Unknown members are rejected; groups persist across reloads.
    with pytest.raises(IAMError):
        iam.update_group_members("readers", ["ghost"])
    iam.update_group_members("readers", ["gina"])
    iam2 = IAMSys(iam._sets, "root", "rootsecret")
    assert iam2.is_allowed("gina", "s3:GetObject", "b/k")
    assert iam2.list_groups()["readers"]["members"] == ["gina"]


def test_assume_role_inherits_and_intersects(iam):
    iam.add_user("carol", "carolsecret")
    iam.attach_policy("carol", ["readwrite"])
    rec = iam.assume_role("carol")
    ak = rec["access_key"]
    assert iam.secret_for(ak) == rec["secret_key"]
    assert iam.session_token_for(ak) == rec["session_token"]
    # Inherits the parent's permissions...
    assert iam.is_allowed(ak, "s3:PutObject", "b/k")
    # ...but never root's short-circuit.
    assert not iam.is_root(ak)
    # Session policy INTERSECTS: parent allows rw, session only read.
    rec2 = iam.assume_role("carol", session_policy={"Statement": [
        {"Effect": "Allow", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::b/*"]}]})
    ak2 = rec2["access_key"]
    assert iam.is_allowed(ak2, "s3:GetObject", "b/k")
    assert not iam.is_allowed(ak2, "s3:PutObject", "b/k")
    # A session policy can never EXPAND beyond the parent.
    iam.attach_policy("carol", ["readonly"])
    rec3 = iam.assume_role("carol", session_policy={"Statement": [
        {"Effect": "Allow", "Action": ["s3:*"], "Resource": ["*"]}]})
    assert not iam.is_allowed(rec3["access_key"], "s3:PutObject", "b/k")


def test_assume_role_expiry_and_bounds(iam):
    iam.add_user("dave", "davesecret1")
    with pytest.raises(IAMError):
        iam.assume_role("dave", duration_s=10)          # below AWS minimum
    with pytest.raises(IAMError):
        iam.assume_role("dave", duration_s=13 * 3600)   # above maximum
    rec = iam.assume_role("dave")
    ak = rec["access_key"]
    assert iam.secret_for(ak) is not None
    # Force expiry: the key must die everywhere at once.
    iam._state["sts"][ak]["expiry_ns"] = time.time_ns() - 1
    assert iam.secret_for(ak) is None
    assert iam.session_token_for(ak) is None
    assert not iam.is_allowed(ak, "s3:GetObject", "b/k")
    # Service accounts cannot chain AssumeRole.
    iam.add_service_account("dave", "svcdave", "svcdavesecret")
    with pytest.raises(IAMError):
        iam.assume_role("svcdave")


def test_sts_dies_with_parent(iam):
    """Disabling or deleting a user revokes its STS keys immediately."""
    iam.add_user("hank", "hanksecret")
    iam.attach_policy("hank", ["readwrite"])
    rec = iam.assume_role("hank")
    ak = rec["access_key"]
    assert iam.secret_for(ak) is not None
    iam.set_user_status("hank", enabled=False)
    assert iam.secret_for(ak) is None
    assert iam.session_token_for(ak) is None
    assert not iam.is_allowed(ak, "s3:GetObject", "b/k")
    iam.set_user_status("hank", enabled=True)
    assert iam.secret_for(ak) is not None     # re-enable restores
    iam.remove_user("hank")
    assert iam.secret_for(ak) is None
    assert ak not in iam._state["sts"]        # purged, not just dead


def test_user_group_namespace_and_membership_hygiene(iam):
    iam.add_user("iris", "irissecret")
    # A group may not shadow a user and vice versa.
    with pytest.raises(IAMError):
        iam.update_group_members("iris", [])
    iam.update_group_members("team", ["iris"])
    with pytest.raises(IAMError):
        iam.add_user("team", "teamsecret1")
    # remove=True on an unknown group is an error, not a phantom group.
    with pytest.raises(IAMError):
        iam.update_group_members("nope", ["iris"], remove=True)
    assert "nope" not in iam.list_groups()
    # Deleting a user scrubs its memberships: a recreated same-name
    # user must not inherit the old group grants.
    iam.attach_policy("team", ["readwrite"])
    iam.remove_user("iris")
    assert iam.list_groups()["team"]["members"] == []
    iam.add_user("iris", "irissecret2")
    assert not iam.is_allowed("iris", "s3:PutObject", "b/k")


# ---------------------------------------------------------------------------
# end-to-end over HTTP
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("stsdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    creds = Credentials("minioadmin", "minioadmin")
    creds.iam = IAMSys([es], "minioadmin", "minioadmin")
    server = S3Server(es, address="127.0.0.1:0", credentials=creds)
    server.start()
    yield server
    server.stop()


def _assume_role(cli, **form):
    body = urllib.parse.urlencode(
        {"Action": "AssumeRole", "Version": "2011-06-15", **form}).encode()
    st, _, resp = cli.request(
        "POST", "/", body=body,
        headers={"Content-Type": "application/x-www-form-urlencoded"})
    return st, resp


def _field(xml: bytes, tag: str) -> str:
    return xml.split(f"<{tag}>".encode())[1].split(
        f"</{tag}>".encode())[0].decode()


def test_e2e_assume_role_and_session_token(srv):
    root = S3Client(srv.address)
    assert root.request("PUT", "/stsbkt")[0] == 200
    assert root.request("PUT", "/stsbkt/obj", body=b"sts data")[0] == 200
    st, _, b = root.request("PUT", "/minio/admin/v3/add-user",
                            query={"accessKey": "erin"},
                            body=json.dumps(
                                {"secretKey": "erinsecret"}).encode())
    assert st == 200, b
    st, _, b = root.request("PUT",
                            "/minio/admin/v3/set-user-or-group-policy",
                            query={"userOrGroup": "erin",
                                   "policyName": "readonly"})
    assert st == 200, b
    erin = S3Client(srv.address, access_key="erin", secret_key="erinsecret")
    st, resp = _assume_role(erin, DurationSeconds="900")
    assert st == 200, resp
    ak = _field(resp, "AccessKeyId")
    sk = _field(resp, "SecretAccessKey")
    tok = _field(resp, "SessionToken")
    assert _field(resp, "Expiration")
    temp = S3Client(srv.address, access_key=ak, secret_key=sk,
                    session_token=tok)
    st, _, got = temp.request("GET", "/stsbkt/obj")
    assert st == 200 and got == b"sts data"
    # Parent is readonly: writes refused for the temp key too.
    assert temp.request("PUT", "/stsbkt/obj2", body=b"x")[0] == 403
    # Requests WITHOUT the session token are refused outright.
    no_tok = S3Client(srv.address, access_key=ak, secret_key=sk)
    assert no_tok.request("GET", "/stsbkt/obj")[0] == 403
    wrong = S3Client(srv.address, access_key=ak, secret_key=sk,
                     session_token="forged")
    assert wrong.request("GET", "/stsbkt/obj")[0] == 403
    # Admin surface stays closed to temp credentials.
    assert temp.request("GET", "/minio/admin/v3/list-users")[0] == 403


def test_e2e_expired_sts_key_fails_auth(srv):
    root = S3Client(srv.address)
    erin = S3Client(srv.address, access_key="erin", secret_key="erinsecret")
    st, resp = _assume_role(erin)
    assert st == 200
    ak, sk = _field(resp, "AccessKeyId"), _field(resp, "SecretAccessKey")
    tok = _field(resp, "SessionToken")
    # Expire it in place (the store is shared within this process).
    srv.credentials.iam._state["sts"][ak]["expiry_ns"] = \
        time.time_ns() - 1
    temp = S3Client(srv.address, access_key=ak, secret_key=sk,
                    session_token=tok)
    st, _, body = temp.request("GET", "/stsbkt/obj")
    assert st == 403 and b"InvalidAccessKeyId" in body


def test_e2e_session_policy_restricts(srv):
    erin = S3Client(srv.address, access_key="erin", secret_key="erinsecret")
    pol = {"Statement": [{"Effect": "Allow", "Action": ["s3:GetObject"],
                          "Resource": ["arn:aws:s3:::stsbkt/obj"]}]}
    st, resp = _assume_role(erin, Policy=json.dumps(pol))
    assert st == 200, resp
    temp = S3Client(srv.address,
                    access_key=_field(resp, "AccessKeyId"),
                    secret_key=_field(resp, "SecretAccessKey"),
                    session_token=_field(resp, "SessionToken"))
    assert temp.request("GET", "/stsbkt/obj")[0] == 200
    # readonly parent allows ListBucket; the session policy does not.
    assert temp.request("GET", "/stsbkt")[0] == 403
    # Anonymous AssumeRole is refused.
    import http.client
    host, _, port = srv.address.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=10)
    body = b"Action=AssumeRole&Version=2011-06-15"
    conn.request("POST", "/", body=body,
                 headers={"Content-Type":
                          "application/x-www-form-urlencoded",
                          "Content-Length": str(len(body))})
    r = conn.getresponse()
    assert r.status == 403
    conn.close()


def test_e2e_groups_grant_access(srv):
    root = S3Client(srv.address)
    st, _, b = root.request("PUT", "/minio/admin/v3/add-user",
                            query={"accessKey": "frank"},
                            body=json.dumps(
                                {"secretKey": "franksecret"}).encode())
    assert st == 200, b
    st, _, b = root.request("PUT", "/minio/admin/v3/update-group-members",
                            body=json.dumps(
                                {"group": "ops",
                                 "members": ["frank"]}).encode())
    assert st == 200, b
    st, _, b = root.request("PUT",
                            "/minio/admin/v3/set-user-or-group-policy",
                            query={"userOrGroup": "ops",
                                   "policyName": "readwrite"})
    assert st == 200, b
    frank = S3Client(srv.address, access_key="frank",
                     secret_key="franksecret")
    assert frank.request("PUT", "/stsbkt/frankobj", body=b"f")[0] == 200
    st, _, b = root.request("GET", "/minio/admin/v3/list-groups")
    assert st == 200 and b"frank" in b
    # Removing the member revokes the grant.
    st, _, b = root.request("PUT", "/minio/admin/v3/update-group-members",
                            body=json.dumps(
                                {"group": "ops", "members": ["frank"],
                                 "remove": True}).encode())
    assert st == 200, b
    assert frank.request("PUT", "/stsbkt/frankobj2", body=b"f")[0] == 403
