"""MQTT/NATS/Redis event targets (reference: internal/event/target/
mqtt.go, nats.go, redis.go): wire-protocol framing validated against
in-process brokers that PARSE per spec (not just byte-compare), plus
store-and-forward retry across a broker outage."""

import json
import socket
import socketserver
import threading
import time

import pytest

from minio_tpu.events.targets import (MQTTTarget, NATSTarget, RedisTarget,
                                      TargetError)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        c = sock.recv(n - len(buf))
        if not c:
            raise AssertionError("short read")
        buf += c
    return buf


class _Broker:
    """TCP fake broker base: collects published payloads."""

    def __init__(self, handler):
        self.published = []
        broker = self

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    handler(broker, self.request)
                except Exception:  # noqa: BLE001 - test sees no publish
                    pass

        self.srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
        self.srv.daemon_threads = True
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()

    @property
    def addr(self):
        h, p = self.srv.server_address
        return f"{h}:{p}"

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


# -- spec-parsing handlers --------------------------------------------------

def _mqtt_handler(broker, sock):
    def read_packet():
        first = _recv_exact(sock, 1)[0]
        n = shift = 0
        while True:
            b = _recv_exact(sock, 1)[0]
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return first, _recv_exact(sock, n) if n else b""

    first, body = read_packet()
    assert first >> 4 == 1                       # CONNECT
    # Variable header: protocol name "MQTT", level 4.
    plen = int.from_bytes(body[0:2], "big")
    assert body[2:2 + plen] == b"MQTT" and body[2 + plen] == 4
    sock.sendall(b"\x20\x02\x00\x00")            # CONNACK accepted
    first, body = read_packet()
    assert first >> 4 == 3                       # PUBLISH
    qos = (first >> 1) & 3
    tlen = int.from_bytes(body[0:2], "big")
    topic = body[2:2 + tlen].decode()
    off = 2 + tlen
    if qos:
        pid = body[off:off + 2]
        off += 2
    # Record BEFORE acking: the client returns on PUBACK, and the test
    # asserts immediately — appending after the ack is a lost race
    # under load.
    broker.published.append((topic, body[off:]))
    if qos:
        sock.sendall(b"\x40\x02" + pid)          # PUBACK


def _nats_handler(broker, sock):
    sock.sendall(b'INFO {"server_id":"fake","max_payload":1048576}\r\n')
    f = sock.makefile("rb")
    line = f.readline()
    assert line.startswith(b"CONNECT ")
    json.loads(line[8:])                         # must be valid JSON
    sock.sendall(b"+OK\r\n")
    line = f.readline()
    parts = line.split()
    assert parts[0] == b"PUB"
    subject, nbytes = parts[1].decode(), int(parts[2])
    payload = f.read(nbytes)                     # buffered source only
    f.read(2)                                    # trailing CRLF
    broker.published.append((subject, payload))
    sock.sendall(b"+OK\r\n")


def _redis_handler(broker, sock):
    f = sock.makefile("rb")
    line = f.readline()
    assert line[:1] == b"*"
    nargs = int(line[1:])
    args = []
    for _ in range(nargs):
        hdr = f.readline()
        assert hdr[:1] == b"$"
        n = int(hdr[1:])
        args.append(f.read(n))                   # buffered source only
        f.read(2)                                # arg CRLF
    assert args[0].upper() == b"RPUSH"
    broker.published.append((args[1].decode(), args[2]))
    sock.sendall(b":1\r\n")


RECORD = {"eventName": "s3:ObjectCreated:Put",
          "s3": {"bucket": {"name": "b"}, "object": {"key": "k"}}}


@pytest.mark.parametrize("handler,mk", [
    (_mqtt_handler, lambda a: MQTTTarget("mqtt", a, "minio/events",
                                     timeout=30)),
    (_nats_handler, lambda a: NATSTarget("nats", a, "minio.events",
                                     timeout=30)),
    (_redis_handler, lambda a: RedisTarget("redis", a, "minio:events",
                                       timeout=30)),
])
def test_target_speaks_its_protocol(handler, mk):
    broker = _Broker(handler)
    try:
        mk(broker.addr).send(RECORD)
        assert len(broker.published) == 1
        chan, payload = broker.published[0]
        assert chan in ("minio/events", "minio.events", "minio:events")
        doc = json.loads(payload)
        assert doc["Records"][0]["eventName"] == "s3:ObjectCreated:Put"
    finally:
        broker.close()


def test_send_fails_loudly_when_broker_down():
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    port = dead.getsockname()[1]
    dead.close()                                 # nothing listening
    for t in (MQTTTarget("m", f"127.0.0.1:{port}", "t", timeout=0.5),
              NATSTarget("n", f"127.0.0.1:{port}", "s", timeout=0.5),
              RedisTarget("r", f"127.0.0.1:{port}", "k", timeout=0.5)):
        with pytest.raises((TargetError, OSError)):
            t.send(RECORD)


def test_store_and_forward_retries_after_broker_recovery(tmp_path):
    """EventNotifier + MQTT target: events queued while the broker is
    DOWN deliver after it comes back — the reference's queue-store
    guarantee, on the new target type."""
    from minio_tpu.events import EventNotifier
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.storage.local import LocalStorage
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("evb")
    meta = es.get_bucket_meta("evb")
    meta["config:notification"] = (
        '<NotificationConfiguration><QueueConfiguration>'
        '<Queue>arn:minio:sqs:us-east-1:1:mqtt</Queue>'
        '<Event>s3:ObjectCreated:*</Event>'
        '</QueueConfiguration></NotificationConfiguration>')
    es.set_bucket_meta("evb", meta)

    # Reserve a port, but leave the broker DOWN for now.
    placeholder = socket.socket()
    placeholder.bind(("127.0.0.1", 0))
    port = placeholder.getsockname()[1]
    placeholder.close()
    target = MQTTTarget("mqtt", f"127.0.0.1:{port}", "minio/events",
                        timeout=0.5)
    notifier = EventNotifier(es, str(tmp_path / "queue"),
                             targets=[target])
    notifier._RETRY_BASE = 0.05
    try:
        notifier.notify("s3:ObjectCreated:Put", "evb", "hello.txt",
                        size=5)
        time.sleep(0.3)                          # worker fails against
        assert notifier._pending_files()          # the dead broker
        # Broker comes up on the SAME port: the queue drains into it.
        broker = _Broker(_mqtt_handler)
        real_addr = broker.addr
        target._addr = ("127.0.0.1", int(real_addr.rsplit(":", 1)[1]))
        assert notifier.drain(20)
        assert len(broker.published) == 1
        doc = json.loads(broker.published[0][1])
        assert doc["Records"][0]["s3"]["object"]["key"] == "hello.txt"
        broker.close()
    finally:
        notifier.stop()
        es.close()
