"""Federated STS: AssumeRoleWithWebIdentity against a LOCAL OIDC token
issuer (reference: cmd/sts-handlers.go:61-65 + the identity_openid
provider). A real RSA keypair signs RS256 JWTs; the JWKS document is
served over HTTP by an in-process issuer, and the minted credentials
perform signed S3 operations end-to-end."""

import base64
import http.server
import json
import threading
import time

import pytest

pytest.importorskip(
    "cryptography",
    reason="OIDC tests sign real RS256 JWTs; the optional "
           "'cryptography' wheel is not installed")

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import padding, rsa

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


class _Issuer:
    """Minimal OIDC issuer: one RSA key, JWKS over HTTP, RS256 mint."""

    def __init__(self):
        self.key = rsa.generate_private_key(public_exponent=65537,
                                            key_size=2048)
        pub = self.key.public_key().public_numbers()
        self.jwks = {"keys": [{
            "kty": "RSA", "alg": "RS256", "use": "sig", "kid": "tk1",
            "n": _b64url(pub.n.to_bytes((pub.n.bit_length() + 7) // 8,
                                        "big")),
            "e": _b64url(pub.e.to_bytes(3, "big").lstrip(b"\x00")),
        }]}
        issuer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = json.dumps(issuer.jwks).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = http.server.HTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def jwks_url(self):
        h, p = self.httpd.server_address
        return f"http://{h}:{p}/jwks"

    def mint(self, claims: dict, kid="tk1", alg="RS256") -> str:
        header = {"alg": alg, "typ": "JWT", "kid": kid}
        signed = (_b64url(json.dumps(header).encode()) + "." +
                  _b64url(json.dumps(claims).encode()))
        sig = self.key.sign(signed.encode(), padding.PKCS1v15(),
                            hashes.SHA256())
        return signed + "." + _b64url(sig)


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("oidcdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    from minio_tpu.iam import IAMSys
    from minio_tpu.s3.server import Credentials
    creds = Credentials("minioadmin", "minioadmin")
    creds.iam = IAMSys([es], "minioadmin", "minioadmin")
    server = S3Server(es, address="127.0.0.1:0", credentials=creds)
    server.start()
    issuer = _Issuer()
    root = S3Client(server.address)
    assert root.request("PUT", "/oidcbkt")[0] == 200
    # Provider config via the admin config subsystem + a named policy
    # the claim maps to.
    st, _, b = root.request(
        "PUT", "/minio/admin/v3/set-config",
        body=json.dumps({
            "identity_openid_jwks_url": issuer.jwks_url,
            "identity_openid_client_id": "mtpu-app",
            "identity_openid_claim_name": "policy",
            "identity_openid_issuer": "https://idp.test",
        }).encode())
    assert st == 200, b
    st, _, b = root.request(
        "PUT", "/minio/admin/v3/add-canned-policy",
        query={"name": "webrw"},
        body=json.dumps({"Version": "2012-10-17", "Statement": [{
            "Effect": "Allow", "Action": ["s3:GetObject", "s3:PutObject"],
            "Resource": ["arn:aws:s3:::oidcbkt/*"]}]}).encode())
    assert st == 200, b
    yield server, issuer, root
    server.stop()
    issuer.httpd.shutdown()


def _claims(issuer, **over):
    c = {"sub": "user-7", "iss": "https://idp.test", "aud": "mtpu-app",
         "exp": time.time() + 600, "policy": "webrw"}
    c.update(over)
    return c


def _assume(cli_addr, token, duration=None):
    import urllib.parse
    form = {"Action": "AssumeRoleWithWebIdentity",
            "Version": "2011-06-15", "WebIdentityToken": token}
    if duration:
        form["DurationSeconds"] = str(duration)
    import http.client
    conn = http.client.HTTPConnection(cli_addr, timeout=15)
    body = urllib.parse.urlencode(form)
    conn.request("POST", "/", body=body,
                 headers={"Content-Type":
                          "application/x-www-form-urlencoded"})
    r = conn.getresponse()
    data = r.read()
    conn.close()
    return r.status, data


def test_web_identity_end_to_end(env):
    server, issuer, root = env
    st, body = _assume(server.address, issuer.mint(_claims(issuer)))
    assert st == 200, body
    import xml.etree.ElementTree as ET
    doc = ET.fromstring(body)
    ns = doc.tag.split("}")[0] + "}"
    res = doc.find(f"{ns}AssumeRoleWithWebIdentityResult")
    assert res.findtext(f"{ns}SubjectFromWebIdentityToken") == "user-7"
    c = res.find(f"{ns}Credentials")
    ak = c.findtext(f"{ns}AccessKeyId")
    sk = c.findtext(f"{ns}SecretAccessKey")
    tok = c.findtext(f"{ns}SessionToken")
    assert ak.startswith("STS")
    # The minted credential performs SIGNED S3 ops within its policy...
    cli = S3Client(server.address, access_key=ak, secret_key=sk,
                   session_token=tok)
    assert cli.request("PUT", "/oidcbkt/doc", body=b"web hello")[0] == 200
    assert cli.request("GET", "/oidcbkt/doc")[2] == b"web hello"
    # ...and NOTHING outside it.
    assert cli.request("DELETE", "/oidcbkt/doc")[0] == 403
    assert cli.request("PUT", "/otherbkt")[0] == 403


def test_tampered_and_bad_tokens_rejected(env):
    server, issuer, _ = env
    good = issuer.mint(_claims(issuer))
    # Flip a payload byte: signature check must fail.
    h, p, s = good.split(".")
    bad_payload = json.loads(base64.urlsafe_b64decode(p + "==="))
    bad_payload["policy"] = "consoleAdmin"
    forged = h + "." + _b64url(json.dumps(bad_payload).encode()) + "." + s
    assert _assume(server.address, forged)[0] == 403
    # Expired.
    assert _assume(server.address,
                   issuer.mint(_claims(issuer,
                                       exp=time.time() - 5)))[0] == 403
    # Wrong audience / issuer.
    assert _assume(server.address,
                   issuer.mint(_claims(issuer, aud="other")))[0] == 403
    assert _assume(server.address,
                   issuer.mint(_claims(issuer,
                                       iss="https://evil")))[0] == 403
    # Missing policy claim: no mapping, no credentials.
    claims = _claims(issuer)
    claims.pop("policy")
    assert _assume(server.address, issuer.mint(claims))[0] == 403
    # Unknown signer (fresh key, same kid).
    rogue = _Issuer()
    try:
        assert _assume(server.address,
                       rogue.mint(_claims(rogue)))[0] == 403
    finally:
        rogue.httpd.shutdown()


def test_duration_bounds(env):
    server, issuer, _ = env
    assert _assume(server.address, issuer.mint(_claims(issuer)),
                   duration=60)[0] == 403         # below the 900s floor
    assert _assume(server.address, issuer.mint(_claims(issuer)),
                   duration=3600)[0] == 200
