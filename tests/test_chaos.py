"""Chaos suite: admission control, deadlines, and graceful degradation.

The end-to-end proof of the robustness seam (tests/chaos.py is the
harness): under composed faults — hung drives that trip the health
breaker, NaughtyDisk error schedules, a killed grid peer, saturating
concurrent load — the stack must degrade GRACEFULLY:

  * in-quorum reads/writes keep succeeding;
  * out-of-quorum requests fail FAST with correct S3 errors
    (503 SlowDown{Read,Write}), never by hanging;
  * shed requests get 503 + Retry-After, never unbounded queueing;
  * no request outlives its deadline budget by more than the slop
    bound, and deadline exhaustion answers 408 RequestTimeout;
  * shed/queue/deadline counters surface in metrics and admin info.
"""

import json
import os
import time

import pytest

from minio_tpu.grid import GridClient, GridError, GridServer
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.admission import (AdmissionController, AdmissionShed,
                                    parse_duration)
from minio_tpu.storage.local import LocalStorage
from minio_tpu.storage.naughty import NaughtyDisk
from minio_tpu.storage.remote import RemoteStorage, StorageRPCService
from minio_tpu.utils import deadline as deadline_mod
from tests.chaos import (HungDisk, boot_server, build_set, run_load,
                         statuses)
from tests.s3client import S3Client

SLOP = 1.0          # scheduler/teardown grace over a deadline, seconds


# ---------------------------------------------------------------------------
# admission controller unit behavior
# ---------------------------------------------------------------------------

def test_parse_duration():
    assert parse_duration("10s", 1.0) == 10.0
    assert parse_duration("500ms", 1.0) == 0.5
    assert parse_duration("2m", 1.0) == 120.0
    assert parse_duration("3", 1.0) == 3.0
    assert parse_duration("", 7.0) == 7.0
    assert parse_duration("junk", 7.0) == 7.0


def test_gate_queue_full_sheds_immediately():
    adm = AdmissionController(max_requests=1, wait_deadline=5.0)
    g1 = adm.enter("s3")                      # occupies the only slot
    # Fill the wait queue (bound == limit == 1) with a parked waiter.
    import threading
    parked = threading.Thread(
        target=lambda: adm.enter("s3").leave(), daemon=True)
    parked.start()
    for _ in range(100):
        if adm.gates["s3"].waiting:
            break
        time.sleep(0.01)
    t0 = time.monotonic()
    with pytest.raises(AdmissionShed) as ei:
        adm.enter("s3")
    assert time.monotonic() - t0 < 1.0        # immediate, not deadline
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after >= 1
    g1.leave()                                # admits the parked waiter
    parked.join(timeout=5)
    snap = adm.snapshot()
    assert snap["s3"]["shed_queue_full_total"] == 1
    assert snap["s3"]["admitted_total"] == 2
    assert snap["s3"]["in_flight"] == 0


def test_gate_deadline_shed_and_admin_isolation():
    adm = AdmissionController(max_requests=1, wait_deadline=0.1)
    g = adm.enter("s3")
    t0 = time.monotonic()
    with pytest.raises(AdmissionShed) as ei:
        adm.enter("s3")
    assert ei.value.reason == "deadline"
    assert 0.05 <= time.monotonic() - t0 < 2.0
    # The admin class has its own gate: saturated data traffic must
    # never starve operator endpoints.
    adm.enter("admin").leave()
    g.leave()


def test_classify_admin_paths():
    adm = AdmissionController()
    assert adm.classify("/minio/admin/v3/info") == "admin"
    assert adm.classify("/minio/admin") == "admin"
    assert adm.classify("/minio/health/live") == "admin"
    assert adm.classify("/minio/health/ready") == "admin"
    assert adm.classify("/minio/v2/metrics/cluster") == "admin"
    assert adm.classify("/bucket/key") == "s3"
    # Data traffic in a bucket named "minio" must never ride the
    # unlimited admin gate: only paths the ROUTER dispatches to
    # admin/health/metrics handlers classify as admin.
    assert adm.classify("/minio/admindata/x") == "s3"
    assert adm.classify("/minio/healthfiles/y") == "s3"
    assert adm.classify("/minio/health/other") == "s3"


def test_from_env_reads_limits(monkeypatch):
    monkeypatch.setenv("MTPU_API_REQUESTS_MAX", "7")
    monkeypatch.setenv("MTPU_API_REQUESTS_DEADLINE", "250ms")
    monkeypatch.setenv("MTPU_API_REQUEST_TIMEOUT", "2s")
    adm = AdmissionController.from_env()
    assert adm.gates["s3"].limit == 7
    assert adm.gates["s3"].wait_deadline == 0.25
    assert adm.request_timeout == 2.0
    assert adm.gates["admin"].limit == 0      # unlimited by default


def test_deadline_shield_unbinds_budget():
    with deadline_mod.bind(deadline_mod.Deadline(0.0)):
        assert deadline_mod.current() is not None
        with deadline_mod.shield():
            assert deadline_mod.current() is None
        assert deadline_mod.current() is not None


def test_quorum_triage_408_only_when_deadline_decisive():
    """DeadlineExceeded surfaces only when the budget was DECISIVE:
    genuine drive faults that alone preclude quorum stay an honest
    503 quorum error (operators must see unhealth, not timeout noise)."""
    from minio_tpu.object.erasure_object import _raise_for_quorum
    from minio_tpu.object.types import ReadQuorumError
    DE = deadline_mod.DeadlineExceeded
    # Cut drives could have met quorum: the budget is to blame -> 408.
    with pytest.raises(DE):
        _raise_for_quorum([DE("t"), DE("t"), None, OSError("io")],
                          ReadQuorumError("b", "o"), quorum=3)
    # Infra faults alone doom quorum: 503, even with one cut drive.
    with pytest.raises(ReadQuorumError):
        _raise_for_quorum([OSError("io")] * 3 + [DE("t")],
                          ReadQuorumError("b", "o"), quorum=3)
    # No deadline involvement at all: plain quorum error.
    with pytest.raises(ReadQuorumError):
        _raise_for_quorum([OSError("io")] * 4,
                          ReadQuorumError("b", "o"), quorum=3)


# ---------------------------------------------------------------------------
# end-to-end: shed with 503 + Retry-After under saturation
# ---------------------------------------------------------------------------

@pytest.fixture
def slow_read_server(tmp_path, monkeypatch):
    """4-drive set whose read_version hangs 1s on every drive (no
    health wrapper: the slowness is WITHIN op deadlines — this models
    a server that is merely saturated, not broken), gated at 2
    in-flight data requests. Env-configured so the acceptance path
    (MTPU_API_REQUESTS_MAX set low) is the one under test."""
    monkeypatch.setenv("MTPU_API_REQUESTS_MAX", "2")
    monkeypatch.setenv("MTPU_API_REQUESTS_DEADLINE", "150ms")
    hung = []

    def chaos(i, d):
        h = HungDisk(d, 1.0, ops={"read_version"})
        hung.append(h)
        return h

    es = build_set(tmp_path, 4, chaos=chaos, health=False)
    server = boot_server(es)    # admission comes from env
    cli = S3Client(server.address)
    assert cli.request("PUT", "/bkt")[0] == 200
    for h in hung:
        h.release()             # seed object without the delay
    assert cli.request("PUT", "/bkt/k", body=b"x" * 1024)[0] == 200
    for h in hung:
        h._released.clear()
    yield server
    for h in hung:
        h.release()
    server.stop()


def test_saturation_sheds_503_with_retry_after(slow_read_server):
    server = slow_read_server
    out = run_load(server.address,
                   lambda cli: cli.request("GET", "/bkt/k"), threads=8)
    hist = statuses(out)
    # 2 slots busy ~1 s each; the burst's overflow sheds either
    # instantly (queue full) or at the 150 ms wait deadline. Exact
    # counts jitter with client-side scheduling (a late arrival can be
    # admitted once a slot frees), but the invariants hold: every
    # outcome is a 200 or a prompt 503 — nothing queues unboundedly.
    assert hist.get(200, 0) >= 2, hist
    assert hist.get(503, 0) >= 2, hist
    assert hist.get(200, 0) + hist.get(503, 0) == 8, hist
    for o in out:
        if o.status == 503:
            assert o.headers.get("Retry-After") == "1"
            assert o.seconds < 2.0          # shed, never served nor hung
    snap = server.admission.snapshot()
    shed = snap["s3"]["shed_queue_full_total"] + \
        snap["s3"]["shed_deadline_total"]
    assert shed == hist.get(503, 0)
    # Counters surface in Prometheus metrics and admin info.
    cli = S3Client(server.address)
    _, _, text = cli.request("GET", "/minio/v2/metrics/cluster")
    assert b"minio_tpu_api_requests_shed_total" in text
    assert b'class="s3"' in text
    _, _, body = cli.request("GET", "/minio/admin/v3/info")
    info = json.loads(body)
    assert info["admission"]["s3"]["shed_queue_full_total"] \
        + info["admission"]["s3"]["shed_deadline_total"] == shed


def test_admin_class_not_starved_by_saturation(slow_read_server):
    """While data traffic saturates its gate, health stays served."""
    import threading
    server = slow_read_server
    done = threading.Event()
    results = []

    def saturate():
        results.extend(run_load(
            server.address, lambda cli: cli.request("GET", "/bkt/k"),
            threads=6))
        done.set()

    t = threading.Thread(target=saturate, daemon=True)
    t.start()
    time.sleep(0.25)            # gate is now full
    cli = S3Client(server.address)
    t0 = time.monotonic()
    status, _, _ = cli.request("GET", "/minio/health/live")
    assert status == 200
    assert time.monotonic() - t0 < 1.0
    done.wait(timeout=30)


# ---------------------------------------------------------------------------
# end-to-end: per-request deadline budget bounds hung drives
# ---------------------------------------------------------------------------

def test_deadline_bounds_request_to_408(tmp_path):
    """Every drive hangs far past the request budget: the request must
    answer 408 RequestTimeout within deadline + slop — not hang, and
    not claim a (bogus) quorum loss."""
    hung = []

    def chaos(i, d):
        h = HungDisk(d, 10.0, ops={"read_version"})
        hung.append(h)
        return h

    es = build_set(tmp_path, 4, chaos=chaos, health=True, op_timeout=30.0)
    adm = AdmissionController(request_timeout=0.5)
    server = boot_server(es, admission=adm)
    try:
        cli = S3Client(server.address)
        assert cli.request("PUT", "/bkt")[0] == 200
        for h in hung:
            h.release()
        assert cli.request("PUT", "/bkt/k", body=b"y" * 1024)[0] == 200
        for h in hung:
            h._released.clear()
        t0 = time.monotonic()
        status, _, body = cli.request("GET", "/bkt/k")
        elapsed = time.monotonic() - t0
        assert status == 408, (status, body)
        assert b"RequestTimeout" in body
        assert elapsed <= 0.5 + SLOP, elapsed
        assert server.admission.snapshot()["deadline_exceeded_total"] >= 1
        _, _, text = cli.request("GET", "/minio/v2/metrics/cluster")
        assert b"minio_tpu_api_request_deadline_exceeded_total" in text
    finally:
        for h in hung:
            h.release()
        server.stop()


# ---------------------------------------------------------------------------
# end-to-end: quorum invariants under drive faults
# ---------------------------------------------------------------------------

def test_in_quorum_succeeds_while_drive_hangs(tmp_path):
    """One hung drive out of 8: the breaker eats its op timeout once
    or twice, trips, and every request keeps succeeding fast."""
    hung = []

    def chaos(i, d):
        if i == 0:
            h = HungDisk(d, 5.0)
            hung.append(h)
            return h
        return d

    # Op timeout sized for a loaded 1-core CI box: a healthy-but-GIL-
    # contended drive must never trip; the 5 s hang still does.
    es = build_set(tmp_path, 8, chaos=chaos, health=True,
                   op_timeout=1.0, bulk_timeout=1.0, trip_after=2,
                   cooldown=300.0)
    server = boot_server(es)
    try:
        cli = S3Client(server.address)
        # Bucket creation pays the hung drive's first timeouts.
        assert cli.request("PUT", "/bkt")[0] == 200
        out = run_load(
            server.address,
            lambda c: c.request("PUT", f"/bkt/k-{os.urandom(4).hex()}",
                                body=os.urandom(2048)),
            threads=4, per_thread=2)
        hist = statuses(out)
        assert hist == {200: 8}, hist
        # After the burst the hung drive's breaker is open (fail-fast)
        # and the worst request paid at most a couple of op timeouts.
        assert not es.disks[0].is_online()
        assert max(o.seconds for o in out) < 1.0 * 2 + SLOP
        # Reads also hold quorum with the drive still hung.
        status, _, _ = cli.request("GET", "/bkt/k-" + "0" * 8)
        assert status == 404        # fast, correct NoSuchKey — not a hang
    finally:
        for h in hung:
            h.release()
        server.stop()


def test_out_of_quorum_fails_fast_with_s3_errors(tmp_path):
    """3 of 4 drives erroring: writes and reads answer 503
    SlowDownWrite/SlowDownRead quickly — correct S3 verdicts, never
    timeouts."""
    es = build_set(tmp_path, 4, health=False)
    server = boot_server(es)
    try:
        cli = S3Client(server.address)
        assert cli.request("PUT", "/bkt")[0] == 200
        assert cli.request("PUT", "/bkt/pre", body=b"z" * 512)[0] == 200
        # Break 3 drives AFTER seeding (deterministic: the wrappers
        # replace the live disk list).
        for i in range(3):
            es.disks[i] = NaughtyDisk(es.disks[i],
                                      default_err=OSError("chaos: io"))
        t0 = time.monotonic()
        status, _, body = cli.request("PUT", "/bkt/new", body=b"w" * 512)
        assert status == 503 and b"SlowDownWrite" in body, (status, body)
        status, _, body = cli.request("GET", "/bkt/pre")
        assert status == 503 and b"SlowDownRead" in body, (status, body)
        assert time.monotonic() - t0 < 5.0
    finally:
        server.stop()


def test_streaming_writer_timeout_neither_hangs_nor_leaks(tmp_path):
    """When a health-wrapped create_file times out MID-ITERATION of
    the chunk generator, the abandoned pool worker and the writer's
    drain loop both consume the same queue: the sticky sentinel must
    terminate BOTH — the old single-consume sentinel either parked the
    orphaned worker forever (leaking one pool worker per timeout until
    the drive's pool ran dry) or parked the drain loop (hanging the
    whole PUT in join)."""
    from minio_tpu.storage.health import DiskHealthWrapper
    from minio_tpu.storage.local import SYS_VOL
    from minio_tpu.utils.streams import Payload

    class SlowWriteDisk:
        endpoint = "sloww"

        def create_file(self, vol, path, data):
            for _piece in data:
                time.sleep(0.4)      # slower than the bulk timeout

        def ping(self):
            return "pong"

    hd = DiskHealthWrapper(SlowWriteDisk(), op_timeout=1.0,
                           bulk_timeout=0.2, trip_after=1000,
                           cooldown=0.0)
    goods = [LocalStorage(str(tmp_path / f"g{i}")) for i in range(3)]
    disks = [hd] + goods
    es = ErasureSet(disks)
    try:
        data = os.urandom(300_000)
        for r in range(10):
            t0 = time.monotonic()
            _, errors = es._stream_framed_writes(
                Payload.wrap(data), 2, 2, [1, 2, 3, 4],
                lambda i, r=r: (disks[i], SYS_VOL,
                                f"staging/sw{r}-{i}/part.1"))
            assert time.monotonic() - t0 < 10    # join never wedges
            assert errors[0] is not None         # slow writer timed out
            assert all(e is None for e in errors[1:])
        time.sleep(0.6)          # let unblocked orphans finish their op
        t0 = time.monotonic()
        for _ in range(8):       # pool has 8 workers: all must be free
            assert hd.ping() == "pong"
        assert time.monotonic() - t0 < 2.0       # pool not leaked dry
    finally:
        es.close()


# ---------------------------------------------------------------------------
# grid: retry on transient connect errors, deadline stops retries
# ---------------------------------------------------------------------------

def test_grid_client_survives_peer_restart(tmp_path):
    srv = GridServer(0, host="127.0.0.1")
    srv.start()
    port = srv.port
    c = GridClient("127.0.0.1", port, connect_timeout=1.0,
                   call_timeout=5.0, cooldown=1.0)
    assert c.call("grid.ping") == "pong"
    srv.stop()
    with pytest.raises(GridError):
        c.call("grid.ping")
    # The failed call's send retries opened the per-peer breaker:
    # while it is open (cooldown pinned to 1 s so this call cannot
    # race into a half-open probe) further calls fail fast with no
    # connect attempt. The tight-window fail-fast bound lives in
    # tests/test_cluster.py::test_grid_breaker_opens_and_fails_fast.
    t0 = time.monotonic()
    with pytest.raises(GridError) as ei:
        c.call("grid.ping")
    assert time.monotonic() - t0 < 0.5
    assert "circuit open" in str(ei.value)
    # Peer comes back on the same port: the half-open probe reconnects
    # within the (jittered, bounded) cooldown window.
    srv2 = GridServer(port, host="127.0.0.1")
    srv2.start()
    try:
        deadline = time.monotonic() + 10
        while True:
            try:
                assert c.call("grid.ping") == "pong"
                break
            except GridError:
                assert time.monotonic() < deadline
                time.sleep(0.05)
    finally:
        srv2.stop()
        c.close()


def test_grid_retry_never_runs_against_exhausted_deadline():
    # Nothing listens on this port: without a deadline the client pays
    # its backoff schedule; with an expired budget it fails instantly.
    c = GridClient("127.0.0.1", 1, connect_timeout=0.2,
                   send_retries=2, retry_backoff=0.05)
    t0 = time.monotonic()
    with pytest.raises(GridError):
        c.call("grid.ping")
    assert time.monotonic() - t0 >= 0.05      # at least one backoff
    with deadline_mod.bind(deadline_mod.Deadline(0.0)):
        t0 = time.monotonic()
        with pytest.raises(deadline_mod.DeadlineExceeded):
            c.call("grid.ping")
        assert time.monotonic() - t0 < 0.2    # no connect, no backoff
    c.close()


# ---------------------------------------------------------------------------
# chaos stress: composed faults under sustained concurrent load
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_composed_faults_invariants(tmp_path):
    """The full composition: a hung drive (breaker food), a NaughtyDisk
    erroring intermittently, a KILLED grid peer, admission gating, and
    deadline budgets — under sustained concurrent load. Invariants:
    every outcome is a 200, a 503 shed (with Retry-After), or a 408;
    nothing hangs past its deadline by more than slop; the set stays
    writable (quorum holds: 5 healthy drives of 8, write quorum 5)."""
    # Grid peer serving 2 remote drives, killed mid-test.
    peer_roots = [str(tmp_path / f"r{i}") for i in range(2)]
    peer_disks = [LocalStorage(r) for r in peer_roots]
    gsrv = GridServer(0, host="127.0.0.1")
    StorageRPCService({d.root: d for d in peer_disks}).register_into(gsrv)
    gsrv.start()

    hung = []

    def chaos(i, d):
        if i == 0:
            h = HungDisk(d, 5.0)
            hung.append(h)
            return h
        if i == 1:
            # Sparse intermittent infra faults: exercises MRF/quorum
            # paths without ever producing two CONSECUTIVE faults (two
            # faulting calls completing back-to-back under concurrency
            # would trip this drive's breaker and, with the peer also
            # dead, push the set below write quorum — a different
            # scenario than the one under test).
            return NaughtyDisk(d, fail_calls={
                n: OSError("chaos: intermittent")
                for n in range(25, 5000, 150)})
        return d

    local = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(6)]
    remote = [RemoteStorage("127.0.0.1", gsrv.port, r)
              for r in peer_roots]
    disks = [chaos(i, d) or d for i, d in enumerate(local)] + remote
    from minio_tpu.storage.health import wrap_disks
    # Op deadlines sized for burst GIL contention (8 HTTP handlers +
    # pools + grid threads): tight enough to catch the 5 s hang, loose
    # enough that a healthy-but-contended drive never trips.
    disks = wrap_disks(disks, op_timeout=1.0, bulk_timeout=2.0,
                       trip_after=2, cooldown=300.0)
    es = ErasureSet(disks)          # 8 drives: parity 4, write quorum 5
    adm = AdmissionController(max_requests=8, wait_deadline=0.2,
                              request_timeout=3.0)
    server = boot_server(es, admission=adm)
    try:
        cli = S3Client(server.address)
        assert cli.request("PUT", "/bkt")[0] == 200

        def work(c: S3Client):
            key = f"/bkt/o-{os.urandom(4).hex()}"
            status, headers, body = c.request("PUT", key,
                                              body=os.urandom(4096))
            if status != 200:
                return status, headers, body
            return c.request("GET", key)

        # Phase 1: peer alive (7 usable drives, write quorum 5).
        out1 = run_load(server.address, work, threads=8, per_thread=2)
        # Phase 2: kill the peer mid-life, keep loading (5 usable —
        # exactly at write quorum, so transient faults may shed).
        gsrv.stop()
        out2 = run_load(server.address, work, threads=8, per_thread=2)

        for o in out1 + out2:
            assert o.error is None, o.error
            assert o.status in (200, 503, 408), (o.status, o.headers)
            assert o.seconds <= 3.0 + SLOP, o.seconds
            if o.status == 503 and "Retry-After" in o.headers:
                assert int(o.headers["Retry-After"]) >= 1
        h1, h2 = statuses(out1), statuses(out2)
        # Quorum held: phase 1 has two drives of margin (mostly 200s),
        # phase 2 sits exactly at quorum (most traffic still lands).
        assert h1.get(200, 0) >= 3 * len(out1) // 4, (h1, h2)
        assert h2.get(200, 0) >= len(out2) // 2, (h1, h2)
        # And the set is still writable after all faults.
        assert cli.request("PUT", "/bkt/final", body=b"ok")[0] == 200
        status, _, body = cli.request("GET", "/bkt/final")
        assert status == 200 and body == b"ok"
    finally:
        for h in hung:
            h.release()
        server.stop()
        gsrv.stop()


@pytest.mark.slow
def test_chaos_sustained_saturation_no_unbounded_queue(tmp_path):
    """Sustained oversubscription: the wait queue stays bounded (never
    more than limit waiters), every shed is prompt, and throughput
    continues — the front-end can be benchmarked honestly at
    saturation because it says no instead of queueing."""
    hung = []

    def chaos(i, d):
        h = HungDisk(d, 0.15, ops={"read_version"})
        hung.append(h)
        return h

    es = build_set(tmp_path, 4, chaos=chaos, health=False)
    adm = AdmissionController(max_requests=3, wait_deadline=0.3)
    server = boot_server(es, admission=adm)
    try:
        cli = S3Client(server.address)
        assert cli.request("PUT", "/bkt")[0] == 200
        for h in hung:
            h.release()
        assert cli.request("PUT", "/bkt/k", body=b"q" * 1024)[0] == 200
        for h in hung:
            h._released.clear()
        peak_wait = [0]

        def sample():
            for _ in range(200):
                snap = server.admission.snapshot()
                peak_wait[0] = max(peak_wait[0], snap["s3"]["waiting"])
                time.sleep(0.01)

        import threading
        sampler = threading.Thread(target=sample, daemon=True)
        sampler.start()
        out = run_load(server.address,
                       lambda c: c.request("GET", "/bkt/k"),
                       threads=12, per_thread=4)
        sampler.join(timeout=10)
        hist = statuses(out)
        assert hist.get(200, 0) >= 12, hist           # progress under load
        assert peak_wait[0] <= 3                      # queue bound == limit
        for o in out:
            if o.status == 503:
                # Prompt: bounded by the wait deadline plus client-
                # side scheduling jitter, never a full service time
                # behind an unbounded queue.
                assert o.seconds < 2.0, o.seconds
    finally:
        for h in hung:
            h.release()
        server.stop()
