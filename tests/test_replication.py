"""Bucket replication: rules, async replication to a second live
cluster, status lifecycle, delete-marker replication, scanner resync
(reference: cmd/bucket-replication.go, internal/bucket/replication)."""

import json
import time

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.scanner import Scanner
from minio_tpu.replication import (ReplicationEngine, ReplicationError,
                                   parse_replication_xml)
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

REPL_XML = b"""<ReplicationConfiguration>
  <Role>arn:minio:replication::r1:role</Role>
  <Rule><ID>r1</ID><Status>Enabled</Status><Priority>1</Priority>
    <DeleteMarkerReplication><Status>Enabled</Status>
    </DeleteMarkerReplication>
    <Destination><Bucket>arn:aws:s3:::dstb</Bucket></Destination>
  </Rule>
</ReplicationConfiguration>"""


def test_parse_replication_rules():
    rules = parse_replication_xml(REPL_XML)
    assert len(rules) == 1
    assert rules[0].enabled and rules[0].delete_markers
    assert rules[0].matches("any/key")
    with pytest.raises(ReplicationError):
        parse_replication_xml(b"<ReplicationConfiguration/>")
    with pytest.raises(ReplicationError):
        parse_replication_xml(
            b"<ReplicationConfiguration><Rule><ID>x</ID></Rule>"
            b"</ReplicationConfiguration>")


@pytest.fixture
def clusters(tmp_path):
    """Source (with replication engine) and target clusters."""
    src_disks = [LocalStorage(str(tmp_path / f"s{i}")) for i in range(4)]
    dst_disks = [LocalStorage(str(tmp_path / f"t{i}")) for i in range(4)]
    src_es, dst_es = ErasureSet(src_disks), ErasureSet(dst_disks)
    src = S3Server(src_es, address="127.0.0.1:0")
    dst = S3Server(dst_es, address="127.0.0.1:0")
    src.replicator = ReplicationEngine(src_es)
    src.start()
    dst.start()
    sc = S3Client(src.address)
    dc = S3Client(dst.address)
    assert sc.request("PUT", "/srcb")[0] == 200
    assert dc.request("PUT", "/dstb")[0] == 200
    # Register the remote target + rules on the source bucket.
    st, _, b = sc.request("PUT", "/minio/admin/v3/set-remote-target",
                          query={"bucket": "srcb"},
                          body=json.dumps({
                              "endpoint": dst.address,
                              "accessKey": "minioadmin",
                              "secretKey": "minioadmin",
                              "bucket": "dstb"}).encode())
    assert st == 200, b
    st, _, b = sc.request("PUT", "/srcb", query={"replication": ""},
                          body=REPL_XML)
    assert st == 200, b
    yield src, dst, sc, dc, src_es
    src.replicator.stop()
    src.stop()
    dst.stop()


def test_put_replicates_and_status_completes(clusters):
    src, dst, sc, dc, src_es = clusters
    body = b"replicate me" * 1000
    st, _, _ = sc.request("PUT", "/srcb/doc.txt", body=body,
                          headers={"x-amz-meta-team": "infra",
                                   "x-amz-tagging": "env=prod"})
    assert st == 200
    assert src.replicator.drain(15)
    # Replica landed with metadata and tags.
    st, hh, got = dc.request("GET", "/dstb/doc.txt")
    assert st == 200 and got == body
    assert hh.get("x-amz-meta-team") == "infra"
    assert hh.get("x-amz-meta-mtpu-replica") == "true"
    # Source status header reaches COMPLETED.
    for _ in range(50):
        st, hh, _ = sc.request("HEAD", "/srcb/doc.txt")
        if hh.get("x-amz-replication-status") == "COMPLETED":
            break
        time.sleep(0.1)
    assert hh.get("x-amz-replication-status") == "COMPLETED"


def test_delete_replicates(clusters):
    src, dst, sc, dc, src_es = clusters
    sc.request("PUT", "/srcb/gone.txt", body=b"x")
    assert src.replicator.drain(15)
    assert dc.request("GET", "/dstb/gone.txt")[0] == 200
    sc.request("DELETE", "/srcb/gone.txt")
    assert src.replicator.drain(15)
    assert dc.request("GET", "/dstb/gone.txt")[0] == 404


def test_get_remote_target_hides_secret(clusters):
    src, dst, sc, dc, src_es = clusters
    st, _, b = sc.request("GET", "/minio/admin/v3/get-remote-target",
                          query={"bucket": "srcb"})
    assert st == 200
    rec = json.loads(b)
    assert rec["endpoint"] == dst.address
    assert "secretKey" not in rec


def test_scanner_resyncs_failed_replication(tmp_path):
    """Target down at PUT time: status FAILED; once the target is back,
    the scanner hook re-queues and completes."""
    src_disks = [LocalStorage(str(tmp_path / f"s{i}")) for i in range(4)]
    dst_disks = [LocalStorage(str(tmp_path / f"t{i}")) for i in range(4)]
    src_es, dst_es = ErasureSet(src_disks), ErasureSet(dst_disks)
    src = S3Server(src_es, address="127.0.0.1:0")
    engine = ReplicationEngine(src_es)
    engine._RETRIES = 1          # fail fast for the test
    src.replicator = engine
    src.start()
    sc = S3Client(src.address)
    assert sc.request("PUT", "/srcb")[0] == 200
    # Point at a dead endpoint for now.
    sc.request("PUT", "/minio/admin/v3/set-remote-target",
               query={"bucket": "srcb"},
               body=json.dumps({"endpoint": "127.0.0.1:1",
                                "accessKey": "minioadmin",
                                "secretKey": "minioadmin",
                                "bucket": "dstb"}).encode())
    sc.request("PUT", "/srcb", query={"replication": ""}, body=REPL_XML)
    sc.request("PUT", "/srcb/lost.txt", body=b"data")
    assert engine.drain(15)
    st, hh, _ = sc.request("HEAD", "/srcb/lost.txt")
    assert hh.get("x-amz-replication-status") == "FAILED"

    # Target comes up; fix the remote-target record.
    dst = S3Server(dst_es, address="127.0.0.1:0")
    dst.start()
    dc = S3Client(dst.address)
    assert dc.request("PUT", "/dstb")[0] == 200
    sc.request("PUT", "/minio/admin/v3/set-remote-target",
               query={"bucket": "srcb"},
               body=json.dumps({"endpoint": dst.address,
                                "accessKey": "minioadmin",
                                "secretKey": "minioadmin",
                                "bucket": "dstb"}).encode())
    scanner = Scanner([src_es], throttle=0)
    scanner.on_object.append(engine.scanner_hook)
    scanner.scan_cycle()
    assert engine.drain(15)
    assert dc.request("GET", "/dstb/lost.txt")[2] == b"data"
    engine.stop()
    src.stop()
    dst.stop()
