"""Bucket replication: rules, async replication to a second live
cluster, status lifecycle, delete-marker replication, scanner resync,
durable WAL + replay, per-target breaker lanes, ordering, two-cluster
chaos convergence (reference: cmd/bucket-replication.go,
internal/bucket/replication)."""

import json
import os
import re
import time

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.scanner import Scanner
from minio_tpu.replication import (ReplicationEngine, ReplicationError,
                                   parse_replication_xml)
from minio_tpu.replication.engine import (BreakerOpen, LaneBreaker,
                                          ReplWAL)
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

REPL_XML = b"""<ReplicationConfiguration>
  <Role>arn:minio:replication::r1:role</Role>
  <Rule><ID>r1</ID><Status>Enabled</Status><Priority>1</Priority>
    <DeleteMarkerReplication><Status>Enabled</Status>
    </DeleteMarkerReplication>
    <Destination><Bucket>arn:aws:s3:::dstb</Bucket></Destination>
  </Rule>
</ReplicationConfiguration>"""


def test_parse_replication_rules():
    rules = parse_replication_xml(REPL_XML)
    assert len(rules) == 1
    assert rules[0].enabled and rules[0].delete_markers
    assert rules[0].matches("any/key")
    with pytest.raises(ReplicationError):
        parse_replication_xml(b"<ReplicationConfiguration/>")
    with pytest.raises(ReplicationError):
        parse_replication_xml(
            b"<ReplicationConfiguration><Rule><ID>x</ID></Rule>"
            b"</ReplicationConfiguration>")


@pytest.fixture
def clusters(tmp_path):
    """Source (with replication engine) and target clusters."""
    src_disks = [LocalStorage(str(tmp_path / f"s{i}")) for i in range(4)]
    dst_disks = [LocalStorage(str(tmp_path / f"t{i}")) for i in range(4)]
    src_es, dst_es = ErasureSet(src_disks), ErasureSet(dst_disks)
    src = S3Server(src_es, address="127.0.0.1:0")
    dst = S3Server(dst_es, address="127.0.0.1:0")
    src.replicator = ReplicationEngine(src_es)
    src.start()
    dst.start()
    sc = S3Client(src.address)
    dc = S3Client(dst.address)
    assert sc.request("PUT", "/srcb")[0] == 200
    assert dc.request("PUT", "/dstb")[0] == 200
    # Register the remote target + rules on the source bucket.
    st, _, b = sc.request("PUT", "/minio/admin/v3/set-remote-target",
                          query={"bucket": "srcb"},
                          body=json.dumps({
                              "endpoint": dst.address,
                              "accessKey": "minioadmin",
                              "secretKey": "minioadmin",
                              "bucket": "dstb"}).encode())
    assert st == 200, b
    st, _, b = sc.request("PUT", "/srcb", query={"replication": ""},
                          body=REPL_XML)
    assert st == 200, b
    yield src, dst, sc, dc, src_es
    src.replicator.stop()
    src.stop()
    dst.stop()


def test_put_replicates_and_status_completes(clusters):
    src, dst, sc, dc, src_es = clusters
    body = b"replicate me" * 1000
    st, _, _ = sc.request("PUT", "/srcb/doc.txt", body=body,
                          headers={"x-amz-meta-team": "infra",
                                   "x-amz-tagging": "env=prod"})
    assert st == 200
    assert src.replicator.drain(15)
    # Replica landed with metadata and tags.
    st, hh, got = dc.request("GET", "/dstb/doc.txt")
    assert st == 200 and got == body
    assert hh.get("x-amz-meta-team") == "infra"
    assert hh.get("x-amz-meta-mtpu-replica") == "true"
    # Source status header reaches COMPLETED.
    for _ in range(50):
        st, hh, _ = sc.request("HEAD", "/srcb/doc.txt")
        if hh.get("x-amz-replication-status") == "COMPLETED":
            break
        time.sleep(0.1)
    assert hh.get("x-amz-replication-status") == "COMPLETED"


def test_delete_replicates(clusters):
    src, dst, sc, dc, src_es = clusters
    sc.request("PUT", "/srcb/gone.txt", body=b"x")
    assert src.replicator.drain(15)
    assert dc.request("GET", "/dstb/gone.txt")[0] == 200
    sc.request("DELETE", "/srcb/gone.txt")
    assert src.replicator.drain(15)
    assert dc.request("GET", "/dstb/gone.txt")[0] == 404


def test_get_remote_target_hides_secret(clusters):
    src, dst, sc, dc, src_es = clusters
    st, _, b = sc.request("GET", "/minio/admin/v3/get-remote-target",
                          query={"bucket": "srcb"})
    assert st == 200
    rec = json.loads(b)
    assert rec["endpoint"] == dst.address
    assert "secretKey" not in rec


def test_scanner_resyncs_failed_replication(tmp_path):
    """Target down at PUT time: status FAILED; once the target is back,
    the scanner hook re-queues and completes."""
    src_disks = [LocalStorage(str(tmp_path / f"s{i}")) for i in range(4)]
    dst_disks = [LocalStorage(str(tmp_path / f"t{i}")) for i in range(4)]
    src_es, dst_es = ErasureSet(src_disks), ErasureSet(dst_disks)
    src = S3Server(src_es, address="127.0.0.1:0")
    engine = ReplicationEngine(src_es)
    engine._RETRIES = 1          # fail fast for the test
    src.replicator = engine
    src.start()
    sc = S3Client(src.address)
    assert sc.request("PUT", "/srcb")[0] == 200
    # Point at a dead endpoint for now.
    sc.request("PUT", "/minio/admin/v3/set-remote-target",
               query={"bucket": "srcb"},
               body=json.dumps({"endpoint": "127.0.0.1:1",
                                "accessKey": "minioadmin",
                                "secretKey": "minioadmin",
                                "bucket": "dstb"}).encode())
    sc.request("PUT", "/srcb", query={"replication": ""}, body=REPL_XML)
    sc.request("PUT", "/srcb/lost.txt", body=b"data")
    assert engine.drain(15)
    st, hh, _ = sc.request("HEAD", "/srcb/lost.txt")
    assert hh.get("x-amz-replication-status") == "FAILED"

    # Target comes up; fix the remote-target record.
    dst = S3Server(dst_es, address="127.0.0.1:0")
    dst.start()
    dc = S3Client(dst.address)
    assert dc.request("PUT", "/dstb")[0] == 200
    sc.request("PUT", "/minio/admin/v3/set-remote-target",
               query={"bucket": "srcb"},
               body=json.dumps({"endpoint": dst.address,
                                "accessKey": "minioadmin",
                                "secretKey": "minioadmin",
                                "bucket": "dstb"}).encode())
    scanner = Scanner([src_es], throttle=0)
    scanner.on_object.append(engine.scanner_hook)
    scanner.scan_cycle()
    assert engine.drain(15)
    assert dc.request("GET", "/dstb/lost.txt")[2] == b"data"
    engine.stop()
    src.stop()
    dst.stop()

# ---------------------------------------------------------------------------
# v2 durable plane: breaker, WAL, spill, ordering
# ---------------------------------------------------------------------------


def test_breaker_trip_probe_recover():
    """Trip after N consecutive transport faults, admit exactly one
    half-open probe per cooldown window, double the cooldown on a
    failed probe, reset fully on success."""
    br = LaneBreaker(trip_after=3, cooldown=0.05, cooldown_max=0.4)
    for _ in range(3):
        br.admit()
        br.fault()
    assert br.state() == "open"
    with pytest.raises(BreakerOpen):
        br.admit()
    time.sleep(0.08)           # > cooldown * 1.25 (max jitter)
    assert br.state() == "half-open"
    br.admit()                 # takes the single probe slot
    with pytest.raises(BreakerOpen):
        br.admit()             # concurrent probe denied
    br.fault()                 # probe failed: cooldown doubles
    assert br.state() == "open"
    with pytest.raises(BreakerOpen):
        br.admit()
    time.sleep(0.15)           # > 2 * cooldown * 1.25
    br.admit()                 # next probe
    br.ok()                    # probe succeeded: fully closed
    assert br.state() == "closed"
    br.admit()


def test_wal_replay_and_torn_tail(tmp_path):
    """Incomplete intents replay from a dead instance's WAL; done
    intents and torn tail bytes do not; retired files are not replayed
    twice (idempotence)."""
    w1 = ReplWAL(str(tmp_path), fsync=False)
    w1.append_intent({"seq": 1, "b": "b", "k": "k1", "v": "",
                      "op": "put", "mt": 1})
    w1.append_intent({"seq": 2, "b": "b", "k": "k2", "v": "",
                      "op": "put", "mt": 2})
    w1.append_intent({"seq": 3, "b": "b", "k": "k2", "v": "",
                      "op": "put", "mt": 2})     # dup of k2 intent
    w1.mark_done(1)
    with open(w1.path, "ab") as fh:
        fh.write(b"RPW1torn-frame-garbage")      # simulated torn append
    w2 = ReplWAL(str(tmp_path), fsync=False)
    recs = w2.replay_others()
    # k1 completed, k2 deduped to one intent, garbage discarded.
    assert [r["k"] for r in recs] == ["k2"]
    assert w2.discarded >= 1
    w2.retire_replayed()
    assert not os.path.exists(w1.path)
    w3 = ReplWAL(str(tmp_path), fsync=False)
    assert w3.replay_others() == []
    for w in (w2, w3):
        w.close()


def _solo_engine(tmp_path, endpoint="127.0.0.1:1", workers=0, **kw):
    """Engine over a real ErasureSet with replication config planted
    directly in bucket meta — no HTTP server, workers=0 leaves intents
    queued for introspection."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("srcb")
    meta = es.get_bucket_meta("srcb")
    meta["config:replication"] = REPL_XML.decode()
    meta["config:remote-target"] = json.dumps(
        {"endpoint": endpoint, "accessKey": "a", "secretKey": "s",
         "bucket": "dstb"})
    es.set_bucket_meta("srcb", meta)
    return es, ReplicationEngine(es, workers=workers, **kw)


def test_chain_orders_by_source_version(tmp_path):
    """Intents for one key queue in source-version order regardless of
    arrival order — the target's latest is the source's latest."""
    es, eng = _solo_engine(tmp_path)
    try:
        eng.enqueue("srcb", "k", "v-new", "put", mod_time=300)
        eng.enqueue("srcb", "k", "v-old", "put", mod_time=100)
        eng.enqueue("srcb", "k", "v-mid", "put", mod_time=200)
        lane = eng._lanes["127.0.0.1:1"]
        chain = lane.chains[("srcb", "k")]
        assert [i.version_id for i in chain] == ["v-old", "v-mid",
                                                "v-new"]
        # Duplicate intents dedup instead of stacking.
        eng.enqueue("srcb", "k", "v-mid", "put", mod_time=200)
        assert len(lane.chains[("srcb", "k")]) == 3
    finally:
        eng.stop()


def test_overflow_spills_never_drops(tmp_path):
    """queue.Full used to count as `failed` and LOSE the intent; now it
    spills to the persisted pending set and replays on the next boot."""
    es, eng = _solo_engine(tmp_path)
    try:
        eng._q_max = 2
        for i in range(5):
            eng.enqueue("srcb", f"k{i}", f"v{i}", "put", mod_time=i)
        assert eng.spilled == 3
        assert eng.dropped == 0
        assert eng.stats()["spill_backlog"] == 3
        assert eng.stats()["pending"] == 5
    finally:
        eng.stop()          # persists the spill set
    pending = tmp_path / "d0" / ".mtpu.sys" / "repl" / "pending.json"
    assert pending.exists()
    items = json.loads(pending.read_text())["items"]
    assert {r["k"] for r in items} == {"k2", "k3", "k4"}


def test_spill_drain_clears_pending_file(tmp_path):
    """Draining the spill set must not leave a stale pending.json
    behind: a stale file would re-enqueue already-delivered intents at
    the next boot (an old PUT replayed after a completed DELETE
    regresses the target's latest)."""
    es, eng = _solo_engine(tmp_path)
    pending = tmp_path / "d0" / ".mtpu.sys" / "repl" / "pending.json"
    try:
        eng._q_max = 1
        for i in range(3):
            eng.enqueue("srcb", f"k{i}", f"v{i}", "put", mod_time=i)
        with eng._mu:
            eng._maybe_save_spill_locked(force=True)
        assert pending.exists()
        # Room frees up (deliveries would drive this via _finish).
        eng._q_max = 100
        eng._refill_one()
        eng._refill_one()
        assert eng.stats()["spill_backlog"] == 0
        # The drain-to-empty refill removed the file immediately.
        assert not pending.exists()
    finally:
        eng.stop()
    assert not pending.exists()
    eng2 = ReplicationEngine(es, workers=0)
    try:
        assert eng2.stats()["spill_backlog"] == 0
    finally:
        eng2.stop()


def test_stop_unlinks_stale_pending_file(tmp_path):
    """stop() persists the spill state UNCONDITIONALLY: an engine whose
    spill drained between throttled saves removes the on-disk file at
    shutdown instead of leaving delivered intents listed."""
    es, eng = _solo_engine(tmp_path)
    pending = tmp_path / "d0" / ".mtpu.sys" / "repl" / "pending.json"
    eng._q_max = 1
    for i in range(2):
        eng.enqueue("srcb", f"k{i}", f"v{i}", "put", mod_time=i)
    with eng._mu:
        eng._maybe_save_spill_locked(force=True)
        # Simulate deliveries draining the spill with every throttled
        # save window missed.
        eng._spill.clear()
    assert pending.exists()
    eng.stop()
    assert not pending.exists()


def test_engine_restart_replays_wal_and_spill(tmp_path):
    """SIGKILL simulation: engine 1 dies (no stop()) with queued +
    spilled intents; engine 2 on the same node root replays every
    incomplete intent exactly once."""
    es, eng1 = _solo_engine(tmp_path)
    eng1._q_max = 2
    for i in range(4):
        eng1.enqueue("srcb", f"k{i}", f"v{i}", "put", mod_time=i)
    # Persist the spill set the way the throttled saver eventually
    # would, then abandon eng1 WITHOUT stop() — a crash, not a drain.
    with eng1._mu:
        eng1._maybe_save_spill_locked(force=True)
    eng2 = ReplicationEngine(es, workers=0)
    try:
        st = eng2.stats()
        # 2 chained intents replay from eng1's WAL; 2 more load from
        # pending.json; the idk dedup keeps each exactly once.
        assert st["pending"] == 4
        assert eng2.replayed >= 2
        lane = eng2._lanes["127.0.0.1:1"]
        keys = set(lane.chains) | {(r["b"], r["k"])
                                   for r in eng2._spill.values()}
        assert keys == {("srcb", f"k{i}") for i in range(4)}
    finally:
        eng2.stop()


def test_sse_versions_skip_with_accounting(tmp_path):
    """SSE objects never replicate: delivery is terminal on the first
    attempt, counted in sse_skipped (not retried, not a lane fault)."""
    from minio_tpu.object.types import PutOptions
    es, eng = _solo_engine(tmp_path, workers=2)
    try:
        info = es.put_object(
            "srcb", "enc", b"cipherbytes",
            PutOptions(internal_metadata={"x-internal-sse-alg":
                                          "AES256"}))
        eng.enqueue("srcb", "enc", info.version_id, "put",
                    mod_time=info.mod_time)
        assert eng.drain(10)
        assert eng.sse_skipped == 1
        assert eng.completed == 0
    finally:
        eng.stop()


def test_replica_delete_does_not_ping_pong(clusters):
    """A DELETE carrying the replica marker header (i.e. arriving FROM
    a peer) must not re-enqueue — active-active pairs would bounce
    delete markers forever."""
    src, dst, sc, dc, src_es = clusters
    sc.request("PUT", "/srcb/pp.txt", body=b"x")
    assert src.replicator.drain(15)
    before = src.replicator.queued
    st, _, _ = sc.request(
        "DELETE", "/srcb/pp.txt",
        headers={"x-amz-meta-mtpu-replica": "true"})
    assert st == 204
    assert src.replicator.queued == before


def test_versioned_delete_marker_replicates_with_status(tmp_path):
    """Versioned buckets: the marker replicates as a versioned marker
    (object 404s on the target), and the SOURCE marker itself carries
    PENDING -> COMPLETED status so the scanner can resync it."""
    from minio_tpu.replication import REPL_STATUS_KEY
    src_disks = [LocalStorage(str(tmp_path / f"s{i}")) for i in range(4)]
    dst_disks = [LocalStorage(str(tmp_path / f"t{i}")) for i in range(4)]
    src_es, dst_es = ErasureSet(src_disks), ErasureSet(dst_disks)
    src = S3Server(src_es, address="127.0.0.1:0")
    dst = S3Server(dst_es, address="127.0.0.1:0")
    src.replicator = ReplicationEngine(src_es)
    src.start()
    dst.start()
    sc, dc = S3Client(src.address), S3Client(dst.address)
    try:
        assert sc.request("PUT", "/srcb")[0] == 200
        assert dc.request("PUT", "/dstb")[0] == 200
        ver_xml = (b"<VersioningConfiguration><Status>Enabled</Status>"
                   b"</VersioningConfiguration>")
        assert sc.request("PUT", "/srcb", query={"versioning": ""},
                          body=ver_xml)[0] == 200
        assert dc.request("PUT", "/dstb", query={"versioning": ""},
                          body=ver_xml)[0] == 200
        sc.request("PUT", "/minio/admin/v3/set-remote-target",
                   query={"bucket": "srcb"},
                   body=json.dumps({"endpoint": dst.address,
                                    "accessKey": "minioadmin",
                                    "secretKey": "minioadmin",
                                    "bucket": "dstb"}).encode())
        sc.request("PUT", "/srcb", query={"replication": ""},
                   body=REPL_XML)
        sc.request("PUT", "/srcb/vk", body=b"v1")
        sc.request("PUT", "/srcb/vk", body=b"v2")
        assert src.replicator.drain(15)
        assert dc.request("GET", "/dstb/vk")[2] == b"v2"
        st, _, _ = sc.request("DELETE", "/srcb/vk")
        assert st == 204
        assert src.replicator.drain(15)
        # Marker replicated: latest on the target is a delete marker.
        assert dc.request("GET", "/dstb/vk")[0] == 404
        # The source marker carries COMPLETED status metadata.
        versions = src_es.list_versions_all("srcb", "vk")
        marker = next(v for v in versions if v.deleted)
        assert marker.metadata.get(REPL_STATUS_KEY) == "COMPLETED"
        # The target minted its marker WITH the source marker's version
        # id (the x-mtpu-replica-dm-version header, consumed by the
        # delete handler) — active-active peers hold the SAME marker.
        dst_versions = dst_es.list_versions_all("dstb", "vk")
        dst_marker = next(v for v in dst_versions if v.deleted)
        assert dst_marker.version_id == marker.version_id
    finally:
        src.replicator.stop()
        src.stop()
        dst.stop()


def test_retry_backoff_rides_timer_not_worker(tmp_path):
    """During an outage the delivery workers stay free (backoff parks
    on the timer heap): a healthy lane enqueued later still completes
    while the dead lane's retries wait."""
    es, eng = _solo_engine(tmp_path, workers=1)
    dst_disks = [LocalStorage(str(tmp_path / f"h{i}")) for i in range(4)]
    dst_es = ErasureSet(dst_disks)
    dst = S3Server(dst_es, address="127.0.0.1:0")
    dst.start()
    dc = S3Client(dst.address)
    try:
        assert dc.request("PUT", "/dstb")[0] == 200
        es.make_bucket("okb")
        meta = es.get_bucket_meta("okb")
        meta["config:replication"] = REPL_XML.decode()
        meta["config:remote-target"] = json.dumps(
            {"endpoint": dst.address, "accessKey": "minioadmin",
             "secretKey": "minioadmin", "bucket": "dstb"})
        es.set_bucket_meta("okb", meta)
        info = es.put_object("okb", "alive", b"healthy lane")
        # Dead-lane intent FIRST: under v1 its worker-thread backoff
        # (0.2 + 0.4 + ... ≈ 3s+) head-of-line blocked this worker.
        eng.enqueue("srcb", "stuck", "v1", "put", mod_time=1)
        eng.enqueue("okb", "alive", info.version_id, "put",
                    mod_time=info.mod_time)
        t0 = time.monotonic()
        deadline = t0 + 10
        while time.monotonic() < deadline:
            if eng.completed >= 1:
                break
            time.sleep(0.02)
        assert eng.completed == 1, "healthy lane blocked by dead lane"
        assert dc.request("GET", "/dstb/alive")[2] == b"healthy lane"
        # The healthy delivery finished while the dead lane was still
        # inside its retry schedule.
        assert eng.failed == 0 or eng.stats()["pending"] >= 1
    finally:
        eng.stop()
        dst.stop()


def test_kill_switch_reverts_to_memory_plane(tmp_path, monkeypatch):
    """MTPU_REPLICATION_DURABLE=off: no WAL on disk, no breaker lanes —
    but replication itself still converges (v1 semantics + the
    satellite fixes)."""
    monkeypatch.setenv("MTPU_REPLICATION_DURABLE", "off")
    src_disks = [LocalStorage(str(tmp_path / f"s{i}")) for i in range(4)]
    dst_disks = [LocalStorage(str(tmp_path / f"t{i}")) for i in range(4)]
    src_es, dst_es = ErasureSet(src_disks), ErasureSet(dst_disks)
    src = S3Server(src_es, address="127.0.0.1:0")
    dst = S3Server(dst_es, address="127.0.0.1:0")
    src.replicator = ReplicationEngine(src_es)
    src.start()
    dst.start()
    sc, dc = S3Client(src.address), S3Client(dst.address)
    try:
        assert src.replicator.durable is False
        assert src.replicator.wal is None
        assert sc.request("PUT", "/srcb")[0] == 200
        assert dc.request("PUT", "/dstb")[0] == 200
        sc.request("PUT", "/minio/admin/v3/set-remote-target",
                   query={"bucket": "srcb"},
                   body=json.dumps({"endpoint": dst.address,
                                    "accessKey": "minioadmin",
                                    "secretKey": "minioadmin",
                                    "bucket": "dstb"}).encode())
        sc.request("PUT", "/srcb", query={"replication": ""},
                   body=REPL_XML)
        sc.request("PUT", "/srcb/mem.txt", body=b"volatile plane")
        assert src.replicator.drain(15)
        assert dc.request("GET", "/dstb/mem.txt")[2] == b"volatile plane"
        wal_dir = tmp_path / "s0" / ".mtpu.sys" / "repl"
        assert not any(p.name.startswith("wal-")
                       for p in wal_dir.iterdir()) \
            if wal_dir.exists() else True
    finally:
        src.replicator.stop()
        src.stop()
        dst.stop()


def test_admin_replication_status_and_resync(clusters):
    """replication-status exposes the full v2 stats doc (v1 keys kept);
    replication-resync kicks a checkpointed sweep that re-queues
    unreplicated versions."""
    src, dst, sc, dc, src_es = clusters
    sc.request("PUT", "/srcb/adm.txt", body=b"x")
    assert src.replicator.drain(15)
    st, _, b = sc.request("GET", "/minio/admin/v3/replication-status")
    assert st == 200
    doc = json.loads(b)
    for k in ("queued", "completed", "failed", "spilled", "dropped",
              "pending", "lanes", "durable"):
        assert k in doc
    assert doc["completed"] >= 1
    # Plant an object that predates the replication config by wiping
    # its status, then prove resync picks it up.
    from minio_tpu.replication import REPL_STATUS_KEY
    src_es.update_version_metadata(
        "srcb", "adm.txt", "",
        lambda m: m.pop(REPL_STATUS_KEY, None))
    st, _, b = sc.request("POST", "/minio/admin/v3/replication-resync",
                          query={"bucket": "srcb"})
    assert st == 200
    doc = json.loads(b)
    assert doc["bucket"] == "srcb" and doc["state"] == "running"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st, _, b = sc.request("GET",
                              "/minio/admin/v3/replication-resync",
                              query={"bucket": "srcb"})
        doc = json.loads(b)
        if doc and doc.get("state") == "done":
            break
        time.sleep(0.1)
    assert doc["state"] == "done"
    assert doc["queued"] >= 1
    assert src.replicator.drain(15)
    st, hh, _ = sc.request("HEAD", "/srcb/adm.txt")
    assert hh.get("x-amz-replication-status") == "COMPLETED"

def _multiset_engine(tmp_path, n_keys=40):
    """Engine over a TWO-set pool with n_keys hash-distributed,
    unstamped (pre-config) objects — the shape where a shared resync
    checkpoint across sets silently skips keys."""
    from minio_tpu.object.sets import ErasureSets
    sets = [ErasureSet([LocalStorage(str(tmp_path / f"p{s}d{i}"))
                        for i in range(4)]) for s in range(2)]
    ess = ErasureSets(
        sets, deployment_id="8d7a41f2-9b33-4c55-a0ef-3c1d2e4f5a6b")
    ess.make_bucket("srcb")
    meta = ess.get_bucket_meta("srcb")
    meta["config:replication"] = REPL_XML.decode()
    meta["config:remote-target"] = json.dumps(
        {"endpoint": "127.0.0.1:1", "accessKey": "a", "secretKey": "s",
         "bucket": "dstb"})
    ess.set_bucket_meta("srcb", meta)
    keys = [f"k{i:03d}" for i in range(n_keys)]
    for k in keys:
        ess.put_object("srcb", k, b"x")
    by_set = {0: [], 1: []}
    for k in keys:
        by_set[ess.set_index(k)].append(k)
    # Both sets populated, and set 1 holds keys sorting BEFORE set 0's
    # last key — the exact layout a shared checkpoint would skip.
    assert by_set[0] and by_set[1]
    assert min(by_set[1]) < max(by_set[0])
    return ess, ReplicationEngine(ess, workers=0), keys


def _wait_resync(eng, bucket, timeout=15):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = eng.resync_status(bucket)
        if doc and doc.get("state") not in (None, "running"):
            return doc
        time.sleep(0.05)
    return eng.resync_status(bucket)


def test_resync_covers_all_sets(tmp_path):
    """Full-bucket resync walks EVERY erasure set from its own key
    cursor: set 1's walk must not start at set 0's (lexically late)
    final checkpoint, or hash-distributed keys in later sets are
    silently skipped."""
    ess, eng, keys = _multiset_engine(tmp_path)
    try:
        eng.start_resync("srcb")
        doc = _wait_resync(eng, "srcb")
        assert doc["state"] == "done"
        assert doc["queued"] == len(keys)
        assert doc["scanned"] == len(keys)
        assert eng.stats()["pending"] == len(keys)
    finally:
        eng.stop()


def test_resync_failed_sweep_resumes_checkpoint(tmp_path):
    """Re-kicking a FAILED sweep resumes at its persisted (set,
    checkpoint) instead of restarting at set 0 / '' — and a done sweep
    re-kicks from scratch."""
    ess, eng, keys = _multiset_engine(tmp_path)
    try:
        # Prior sweep failed after finishing set 0 and walking set 1
        # past every key: the resumed sweep has nothing left to queue.
        eng._resyncs["srcb"] = {
            "bucket": "srcb", "state": "failed", "set": 1,
            "checkpoint": "zzz", "scanned": 0, "queued": 0,
            "started": 0.0, "finished": 0.0}
        eng.start_resync("srcb")
        doc = _wait_resync(eng, "srcb")
        assert doc["state"] == "done"
        assert doc["queued"] == 0
        # A fresh kick over the now-done sweep starts over and queues
        # the whole bucket.
        eng.start_resync("srcb")
        doc = _wait_resync(eng, "srcb")
        assert doc["state"] == "done"
        assert doc["queued"] == len(keys)
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Two-cluster chaos convergence matrix (real server processes)
# ---------------------------------------------------------------------------


def _pair_up(tmp_path, scanner_interval=0.5, env=None):
    """Two single-node real-process clusters: source replicating to
    target.  Returns (src_cluster, dst_cluster, src_client,
    dst_client)."""
    from tests.cluster import Cluster
    src = Cluster(tmp_path / "src", nodes=1, drives_per_node=4,
                  scanner_interval=scanner_interval, env=env).start()
    dst = Cluster(tmp_path / "dst", nodes=1, drives_per_node=4,
                  scanner_interval=0).start()
    sc, dc = src.client(0), dst.client(0)
    assert sc.request("PUT", "/srcb")[0] == 200
    assert dc.request("PUT", "/dstb")[0] == 200
    st, _, b = sc.request("PUT", "/minio/admin/v3/set-remote-target",
                          query={"bucket": "srcb"},
                          body=json.dumps({
                              "endpoint": dst.address(0),
                              "accessKey": "minioadmin",
                              "secretKey": "minioadmin",
                              "bucket": "dstb"}).encode())
    assert st == 200, b
    st, _, b = sc.request("PUT", "/srcb", query={"replication": ""},
                          body=REPL_XML)
    assert st == 200, b
    return src, dst, sc, dc


def _list_keys(client, bucket):
    st, _, body = client.request("GET", f"/{bucket}")
    assert st == 200, body
    return set(re.findall(rb"<Key>([^<]+)</Key>", body))


def _assert_converged(sc, dc, expect: dict, timeout=60):
    """Eventual byte-identity: every expected key's latest bytes match
    on both sides (None = deleted on both), and the target has ZERO
    divergent (extra) objects."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        diverged = []
        for key, want in expect.items():
            ss, _, sb = sc.request("GET", f"/srcb/{key}")
            ds, _, db = dc.request("GET", f"/dstb/{key}")
            if want is None:
                if not (ss == 404 and ds == 404):
                    diverged.append((key, ss, ds))
            elif not (ss == 200 and ds == 200 and sb == db == want):
                diverged.append((key, ss, ds))
        if not diverged:
            extra = _list_keys(dc, "dstb") - \
                {k.encode() for k, v in expect.items() if v is not None}
            if not extra:
                return
            diverged = [("extra-on-target", sorted(extra))]
        last = diverged
        time.sleep(0.5)
    raise AssertionError(f"divergent objects after chaos: {last}")


def test_chaos_target_kill_restart_converges(tmp_path):
    """Kill the target mid-replication; keep writing; restart it: the
    scanner resync + breaker-parked lanes converge to byte-identity
    with zero divergent objects."""
    src, dst, sc, dc = _pair_up(tmp_path)
    expect = {}
    try:
        for i in range(6):
            body = f"pre-kill-{i}".encode() * 50
            assert sc.request("PUT", f"/srcb/k{i}",
                              body=body)[0] == 200
            expect[f"k{i}"] = body
        dst.kill(0)                      # crash mid-replication
        for i in range(6, 12):
            body = f"during-outage-{i}".encode() * 50
            assert sc.request("PUT", f"/srcb/k{i}",
                              body=body)[0] == 200
            expect[f"k{i}"] = body
        # A delete during the outage must also converge.
        assert sc.request("DELETE", "/srcb/k0")[0] == 204
        expect["k0"] = None
        time.sleep(1.0)                  # let retries burn into FAILED
        dst.restart(0)
        dc = dst.client(0)
        _assert_converged(sc, dc, expect, timeout=90)
    finally:
        src.stop()
        dst.stop()


def test_chaos_source_sigkill_wal_replays(tmp_path):
    """SIGKILL the source with a loaded WAL (target down, intents
    queued): the restarted source replays its WAL / resyncs stamped
    versions and converges — v1 lost every queued intent here."""
    src, dst, sc, dc = _pair_up(tmp_path)
    expect = {}
    try:
        dst.kill(0)                      # target down: intents pile up
        for i in range(8):
            body = f"wal-loaded-{i}".encode() * 40
            assert sc.request("PUT", f"/srcb/w{i}",
                              body=body)[0] == 200
            expect[f"w{i}"] = body
        src.kill(0)                      # SIGKILL with the WAL loaded
        dst.restart(0)
        src.restart(0)
        sc, dc = src.client(0), dst.client(0)
        _assert_converged(sc, dc, expect, timeout=90)
    finally:
        src.stop()
        dst.stop()


@pytest.mark.slow
def test_chaos_matrix_full(tmp_path):
    """The full matrix: foreground writes + deletes churning while the
    target flaps twice and the source crashes once — eventual
    byte-identity, zero divergent objects."""
    src, dst, sc, dc = _pair_up(tmp_path)
    expect = {}
    try:
        def put(i, tag):
            body = f"{tag}-{i}".encode() * 64
            assert sc.request("PUT", f"/srcb/m{i}", body=body)[0] == 200
            expect[f"m{i}"] = body

        for i in range(5):
            put(i, "phase0")
        dst.kill(0)
        for i in range(5, 10):
            put(i, "outage1")
        sc.request("DELETE", "/srcb/m1")
        expect["m1"] = None
        dst.restart(0)
        dc = dst.client(0)
        _assert_converged(sc, dc, expect, timeout=90)
        # Second flap + source crash while loaded.
        dst.kill(0)
        for i in range(10, 15):
            put(i, "outage2")
        src.kill(0)
        dst.restart(0)
        src.restart(0)
        sc, dc = src.client(0), dst.client(0)
        for i in range(15, 18):
            put(i, "post-restart")
        _assert_converged(sc, dc, expect, timeout=120)
    finally:
        src.stop()
        dst.stop()
