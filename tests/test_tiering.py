"""ILM transitions + warm tiers (reference: cmd/warm-backend.go,
cmd/tier.go, lifecycle Transition in cmd/bucket-lifecycle.go)."""

import json
import os
import time

import pytest

from minio_tpu.object import tier as tier_mod
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.lifecycle import make_scanner_hook, parse_lifecycle
from minio_tpu.object.scanner import Scanner
from minio_tpu.object.tier import (FSWarmBackend, S3WarmBackend, TierError,
                                   TierRegistry)
from minio_tpu.object.types import GetOptions, PutOptions
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client


def _es(tmp_path, name="es"):
    disks = [LocalStorage(str(tmp_path / name / f"d{i}")) for i in range(4)]
    return ErasureSet(disks)


# ---------------------------------------------------------------------------
# backends + registry
# ---------------------------------------------------------------------------

def test_fs_backend_round_trip(tmp_path):
    b = FSWarmBackend(str(tmp_path / "cold"))
    b.put("a/b/obj", b"tiered bytes")
    assert b.get("a/b/obj") == b"tiered bytes"
    assert b.get("a/b/obj", offset=2, length=4) == b"ered"
    b.remove("a/b/obj")
    with pytest.raises(TierError):
        b.get("a/b/obj")
    b.remove("a/b/obj")                     # idempotent


def test_registry_persistence_and_secrets(tmp_path):
    es = _es(tmp_path)
    reg = TierRegistry([es])
    reg.add("COLD", {"type": "fs", "path": str(tmp_path / "cold")})
    with pytest.raises(TierError):
        reg.add("bad name!", {"type": "fs", "path": "/x"})
    with pytest.raises(TierError):
        reg.add("NOPE", {"type": "warp"})
    # A second registry over the same drives sees the tier.
    reg2 = TierRegistry([es])
    assert "COLD" in reg2.list()
    assert reg2.get("COLD") is not None
    # S3 tier secrets never echo in listings.
    reg.add("REMOTE", {"type": "s3", "endpoint": "127.0.0.1:1",
                       "accessKey": "ak", "secretKey": "SECRET",
                       "bucket": "cold"})
    assert "secretKey" not in reg.list()["REMOTE"]
    reg.remove("REMOTE")
    with pytest.raises(TierError):
        reg.remove("REMOTE")


def test_lifecycle_parses_transitions():
    rules = parse_lifecycle(
        b"<LifecycleConfiguration><Rule><ID>t</ID>"
        b"<Status>Enabled</Status><Filter><Prefix>logs/</Prefix></Filter>"
        b"<Transition><Days>30</Days><StorageClass>COLD</StorageClass>"
        b"</Transition>"
        b"<NoncurrentVersionTransition><NoncurrentDays>7</NoncurrentDays>"
        b"<StorageClass>COLD</StorageClass>"
        b"</NoncurrentVersionTransition>"
        b"</Rule></LifecycleConfiguration>")
    assert rules[0].transition_days == 30
    assert rules[0].transition_tier == "COLD"
    assert rules[0].noncurrent_transition_days == 7


# ---------------------------------------------------------------------------
# object-layer transition + read-through
# ---------------------------------------------------------------------------

LC_TRANSITION = (b'<LifecycleConfiguration><Rule><ID>t</ID>'
                 b'<Status>Enabled</Status>'
                 b'<Filter><Prefix></Prefix></Filter>'
                 b'<Transition><Days>1</Days>'
                 b'<StorageClass>COLD</StorageClass></Transition>'
                 b'</Rule></LifecycleConfiguration>')


@pytest.fixture
def tiered_es(tmp_path):
    es = _es(tmp_path)
    es.make_bucket("tb")
    reg = TierRegistry([es])
    reg.add("COLD", {"type": "fs", "path": str(tmp_path / "cold")})
    es.tiers = reg
    meta = es.get_bucket_meta("tb")
    meta["config:lifecycle"] = LC_TRANSITION.decode()
    es.set_bucket_meta("tb", meta)
    return es


def test_scanner_transitions_and_reads_through(tiered_es, tmp_path):
    es = tiered_es
    body = os.urandom(3 << 20)       # multi-block, non-inline
    es.put_object("tb", "logs/app", body,
                  PutOptions(user_metadata={"app": "x"}, tags="env=prod"))
    info0 = es.get_object_info("tb", "logs/app")

    future = time.time() + 2 * 86400
    sc = Scanner([es], throttle=0)
    sc.on_object.append(make_scanner_hook(now_fn=lambda: future))
    sc.scan_cycle()

    # Metadata stays local, carries the pointer; data left the drives.
    info = es.get_object_info("tb", "logs/app")
    assert info.internal_metadata.get(tier_mod.META_TIER) == "COLD"
    assert info.etag == info0.etag
    assert info.user_metadata.get("app") == "x"
    for d in es.disks:
        fi = d.read_version("tb", "logs/app")
        assert not d.exists("tb", f"logs/app/{fi.data_dir}") \
            if hasattr(d, "exists") else True
    # The tier holds the stored stream.
    cold_files = []
    for root, _, files in os.walk(tmp_path / "cold"):
        cold_files += [os.path.join(root, f) for f in files]
    assert len(cold_files) == 1
    # Reads are byte-identical, full and ranged.
    _, got = es.get_object("tb", "logs/app")
    assert got == body
    _, got = es.get_object("tb", "logs/app",
                           GetOptions(offset=1 << 20, length=4096))
    assert got == body[1 << 20:(1 << 20) + 4096]
    info2, chunks = es.get_object_stream("tb", "logs/app", GetOptions())
    assert b"".join(chunks) == body
    # A second scan cycle must NOT re-transition (idempotent).
    sc.scan_cycle()
    assert len([f for r, _, fs in os.walk(tmp_path / "cold")
                for f in fs]) == 1


def test_deleting_transitioned_version_removes_tier_copy(tiered_es,
                                                         tmp_path):
    es = tiered_es
    es.put_object("tb", "gone", os.urandom(100_000))
    future = time.time() + 2 * 86400
    sc = Scanner([es], throttle=0)
    sc.on_object.append(make_scanner_hook(now_fn=lambda: future))
    # First cycle expires nothing (no Expiration rule) but transitions.
    sc.scan_cycle()
    info = es.get_object_info("tb", "gone")
    assert info.internal_metadata.get(tier_mod.META_TIER) == "COLD"
    from minio_tpu.object.types import DeleteOptions
    es.delete_object("tb", "gone", DeleteOptions())
    # The tier copy is gone too (no orphans).
    leftovers = [f for r, _, fs in os.walk(tmp_path / "cold") for f in fs
                 if "gone" in r or "gone" in f]
    assert not leftovers


def test_transition_to_s3_backend_via_live_server(tmp_path):
    """Dogfood: one cluster's COLD tier is ANOTHER minio_tpu server
    reached over S3 — the reference's warm-backend-minio shape."""
    from minio_tpu.s3.server import S3Server
    cold_disks = [LocalStorage(str(tmp_path / "colddrv" / f"d{i}"))
                  for i in range(4)]
    cold_srv = S3Server(ErasureSet(cold_disks), address="127.0.0.1:0")
    cold_srv.start()
    try:
        cold_cli = S3Client(cold_srv.address)
        assert cold_cli.request("PUT", "/coldbkt")[0] == 200

        es = _es(tmp_path, "hot")
        es.make_bucket("tb")
        reg = TierRegistry([es])
        reg.add("COLD", {"type": "s3",
                         "endpoint": cold_srv.address,
                         "accessKey": "minioadmin",
                         "secretKey": "minioadmin",
                         "bucket": "coldbkt", "prefix": "tiered"})
        es.tiers = reg
        meta = es.get_bucket_meta("tb")
        meta["config:lifecycle"] = LC_TRANSITION.decode()
        es.set_bucket_meta("tb", meta)

        body = os.urandom(300_000)
        es.put_object("tb", "doc", body)
        future = time.time() + 2 * 86400
        sc = Scanner([es], throttle=0)
        sc.on_object.append(make_scanner_hook(now_fn=lambda: future))
        sc.scan_cycle()

        info = es.get_object_info("tb", "doc")
        assert info.internal_metadata.get(tier_mod.META_TIER) == "COLD"
        _, got = es.get_object("tb", "doc")
        assert got == body
        _, got = es.get_object("tb", "doc",
                               GetOptions(offset=1000, length=2000))
        assert got == body[1000:3000]
        # The cold cluster physically holds it.
        st, _, listing = cold_cli.request("GET", "/coldbkt",
                                          query={"prefix": "tiered/"})
        assert st == 200 and b"doc" in listing
    finally:
        cold_srv.stop()


def test_drop_marker_never_fires_on_live_version():
    """Regression: a rule with ExpiredObjectDeleteMarker must not emit
    drop_marker for a LIVE lone version (an elif once rebound to the
    wrong if during the transition-rule insert, destroying live data)."""
    import dataclasses as dc
    from minio_tpu.object.lifecycle import Rule, evaluate

    @dc.dataclass
    class V:
        mod_time: int
        deleted: bool
        version_id: str
        metadata: dict = dc.field(default_factory=dict)

    r = Rule(rule_id="m", expire_delete_marker=True,
             noncurrent_transition_days=1,
             noncurrent_transition_tier="COLD")
    live = [V(mod_time=time.time_ns(), deleted=False, version_id="v1")]
    assert evaluate([r], "k", live) == []
    # And it still fires on an actual lone marker.
    marker = [V(mod_time=1, deleted=True, version_id="m1")]
    acts = evaluate([r], "k", marker)
    assert [a.kind for a in acts] == ["drop_marker"]


def test_decommission_migrates_tier_pointer_not_blob(tmp_path):
    """Draining a pool with transitioned versions moves the POINTER;
    the warm-tier blob survives and the migrated copy reads through."""
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets

    def pool(name):
        disks = [LocalStorage(str(tmp_path / name / f"d{i}"))
                 for i in range(4)]
        return ErasureSets(
            [ErasureSet(disks)],
            deployment_id="00000000-0000-0000-0000-00000000dec1")

    p0, p1 = pool("p0"), pool("p1")
    layer = ServerPools([p0, p1])
    layer.make_bucket("tb")
    reg = TierRegistry(p0.sets)
    for p in (p0, p1):
        for s in p.sets:
            s.tiers = reg
    reg.add("COLD", {"type": "fs", "path": str(tmp_path / "cold")})
    meta = layer.get_bucket_meta("tb")
    meta["config:lifecycle"] = LC_TRANSITION.decode()
    layer.set_bucket_meta("tb", meta)

    body = os.urandom(200_000)
    p0.put_object("tb", "doc", body)
    future = time.time() + 2 * 86400
    sc = Scanner(p0.sets, throttle=0)
    sc.on_object.append(make_scanner_hook(now_fn=lambda: future))
    sc.scan_cycle()
    assert p0.get_object_info("tb", "doc").internal_metadata.get(
        tier_mod.META_TIER) == "COLD"

    d = layer.start_decommission(0)
    assert d.wait(60)
    assert layer.decommission_status()["status"] == "complete"
    info, got = layer.get_object("tb", "doc")
    assert got == body
    assert info.internal_metadata.get(tier_mod.META_TIER) == "COLD"
    # The blob is still in the tier (pointer migrated, data did not).
    blobs = [f for r, _, fs in os.walk(tmp_path / "cold") for f in fs]
    assert len(blobs) == 1
    # Deleting the migrated copy reclaims the blob.
    from minio_tpu.object.types import DeleteOptions
    layer.delete_object("tb", "doc", DeleteOptions())
    blobs = [f for r, _, fs in os.walk(tmp_path / "cold") for f in fs]
    assert not blobs


# ---------------------------------------------------------------------------
# admin API
# ---------------------------------------------------------------------------

def test_admin_tier_management(tmp_path):
    from minio_tpu.s3.server import S3Server
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    srv = S3Server(ErasureSet(disks), address="127.0.0.1:0")
    srv.start()
    try:
        cli = S3Client(srv.address)
        st, _, b = cli.request("PUT", "/minio/admin/v3/add-tier",
                               body=json.dumps({
                                   "name": "GLACIER",
                                   "config": {"type": "fs",
                                              "path": str(tmp_path /
                                                          "glacier")}
                               }).encode())
        assert st == 200, b
        st, _, b = cli.request("GET", "/minio/admin/v3/list-tiers")
        assert st == 200 and b"GLACIER" in b
        st, _, b = cli.request("PUT", "/minio/admin/v3/add-tier",
                               body=json.dumps({
                                   "name": "BAD",
                                   "config": {"type": "nope"}}).encode())
        assert st == 400
        st, _, b = cli.request("DELETE", "/minio/admin/v3/remove-tier",
                               query={"name": "GLACIER"})
        assert st == 200, b
        st, _, b = cli.request("GET", "/minio/admin/v3/list-tiers")
        assert st == 200 and b"GLACIER" not in b
    finally:
        srv.stop()
