"""S3 conformance: conditional requests, ListObjectVersions, tagging,
bucket config persistence, POST-policy upload (reference:
cmd/object-handlers.go, cmd/bucket-handlers.go, cmd/post-policy.go)."""

import base64
import datetime
import hashlib
import hmac
import http.client
import json
import os
import xml.etree.ElementTree as ET

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3 import sigv4
from minio_tpu.s3.server import Credentials, S3Server
from minio_tpu.storage.local import LocalStorage
from tests.s3client import S3Client

NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("confdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv.address)
    assert c.request("PUT", "/conf")[0] == 200
    return c


# ---------------------------------------------------------------------------
# conditional requests
# ---------------------------------------------------------------------------

def test_conditional_get(cli):
    body = b"conditional-data"
    st, h, _ = cli.request("PUT", "/conf/cond", body=body)
    etag = h["ETag"]
    st, _, _ = cli.request("GET", "/conf/cond",
                           headers={"If-None-Match": etag})
    assert st == 304
    st, _, got = cli.request("GET", "/conf/cond",
                             headers={"If-None-Match": '"other"'})
    assert st == 200 and got == body
    st, _, got = cli.request("GET", "/conf/cond",
                             headers={"If-Match": etag})
    assert st == 200 and got == body
    st, _, _ = cli.request("GET", "/conf/cond",
                           headers={"If-Match": '"bogus"'})
    assert st == 412
    future = "Fri, 01 Jan 2100 00:00:00 GMT"
    past = "Mon, 01 Jan 2001 00:00:00 GMT"
    st, _, _ = cli.request("GET", "/conf/cond",
                           headers={"If-Modified-Since": future})
    assert st == 304
    st, _, _ = cli.request("GET", "/conf/cond",
                           headers={"If-Modified-Since": past})
    assert st == 200
    st, _, _ = cli.request("GET", "/conf/cond",
                           headers={"If-Unmodified-Since": past})
    assert st == 412


def test_conditional_put_create_only(cli):
    st, _, _ = cli.request("PUT", "/conf/newobj", body=b"first",
                           headers={"If-None-Match": "*"})
    assert st == 200
    st, _, _ = cli.request("PUT", "/conf/newobj", body=b"second",
                           headers={"If-None-Match": "*"})
    assert st == 412
    _, _, got = cli.request("GET", "/conf/newobj")
    assert got == b"first"


def test_conditional_put_if_match(cli):
    st, h, _ = cli.request("PUT", "/conf/casobj", body=b"v1")
    etag = h["ETag"]
    st, _, _ = cli.request("PUT", "/conf/casobj", body=b"v2",
                           headers={"If-Match": etag})
    assert st == 200
    st, _, _ = cli.request("PUT", "/conf/casobj", body=b"v3",
                           headers={"If-Match": etag})
    assert st == 412
    _, _, got = cli.request("GET", "/conf/casobj")
    assert got == b"v2"


def test_copy_source_conditions(cli):
    st, h, _ = cli.request("PUT", "/conf/copysrc", body=b"src")
    etag = h["ETag"]
    st, _, _ = cli.request("PUT", "/conf/copydst", headers={
        "x-amz-copy-source": "/conf/copysrc",
        "x-amz-copy-source-if-match": etag})
    assert st == 200
    st, _, _ = cli.request("PUT", "/conf/copydst2", headers={
        "x-amz-copy-source": "/conf/copysrc",
        "x-amz-copy-source-if-match": '"wrong"'})
    assert st == 412
    st, _, _ = cli.request("PUT", "/conf/copydst3", headers={
        "x-amz-copy-source": "/conf/copysrc",
        "x-amz-copy-source-if-none-match": etag})
    assert st == 412


# ---------------------------------------------------------------------------
# ListObjectVersions
# ---------------------------------------------------------------------------

def test_list_object_versions(cli):
    assert cli.request("PUT", "/verb")[0] == 200
    body = ET.tostring(ET.fromstring(
        '<VersioningConfiguration><Status>Enabled</Status>'
        '</VersioningConfiguration>'))
    assert cli.request("PUT", "/verb", query={"versioning": ""},
                       body=body)[0] == 200
    cli.request("PUT", "/verb/doc", body=b"one")
    cli.request("PUT", "/verb/doc", body=b"two")
    cli.request("DELETE", "/verb/doc")
    st, _, xml = cli.request("GET", "/verb", query={"versions": ""})
    assert st == 200
    root = ET.fromstring(xml)
    versions = root.findall(f"{NS}Version")
    markers = root.findall(f"{NS}DeleteMarker")
    assert len(versions) == 2
    assert len(markers) == 1
    assert markers[0].findtext(f"{NS}IsLatest") == "true"
    assert {v.findtext(f"{NS}Key") for v in versions} == {"doc"}
    assert all(v.findtext(f"{NS}VersionId") for v in versions)


# ---------------------------------------------------------------------------
# tagging
# ---------------------------------------------------------------------------

def test_object_tagging_roundtrip(cli):
    cli.request("PUT", "/conf/tagged", body=b"x",
                headers={"x-amz-tagging": "env=prod&team=infra"})
    st, _, xml = cli.request("GET", "/conf/tagged", query={"tagging": ""})
    assert st == 200
    root = ET.fromstring(xml)
    tags = {t.findtext(f"{NS}Key"): t.findtext(f"{NS}Value")
            for t in root.iter(f"{NS}Tag")}
    assert tags == {"env": "prod", "team": "infra"}
    # Replace via PUT ?tagging
    body = (b'<Tagging><TagSet><Tag><Key>env</Key><Value>dev</Value>'
            b'</Tag></TagSet></Tagging>')
    st, _, b = cli.request("PUT", "/conf/tagged", query={"tagging": ""},
                           body=body)
    assert st == 200, b
    _, _, xml = cli.request("GET", "/conf/tagged", query={"tagging": ""})
    tags = {t.findtext(f"{NS}Key"): t.findtext(f"{NS}Value")
            for t in ET.fromstring(xml).iter(f"{NS}Tag")}
    assert tags == {"env": "dev"}
    # DELETE clears
    st, _, _ = cli.request("DELETE", "/conf/tagged", query={"tagging": ""})
    assert st == 204
    _, _, xml = cli.request("GET", "/conf/tagged", query={"tagging": ""})
    assert not list(ET.fromstring(xml).iter(f"{NS}Tag"))


def test_bucket_tagging_and_configs_persist(cli):
    body = (b'<Tagging><TagSet><Tag><Key>owner</Key><Value>me</Value>'
            b'</Tag></TagSet></Tagging>')
    assert cli.request("PUT", "/conf", query={"tagging": ""},
                       body=body)[0] == 200
    st, _, xml = cli.request("GET", "/conf", query={"tagging": ""})
    assert st == 200 and b"owner" in xml
    assert cli.request("DELETE", "/conf", query={"tagging": ""})[0] == 204
    assert cli.request("GET", "/conf", query={"tagging": ""})[0] == 404

    pol = json.dumps({"Version": "2012-10-17", "Statement": [
        {"Effect": "Allow", "Principal": "*",
         "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::conf/*"]}]}).encode()
    assert cli.request("PUT", "/conf", query={"policy": ""},
                       body=pol)[0] == 200
    st, _, got = cli.request("GET", "/conf", query={"policy": ""})
    assert st == 200 and json.loads(got)["Statement"]
    assert cli.request("DELETE", "/conf", query={"policy": ""})[0] == 204
    assert cli.request("GET", "/conf", query={"policy": ""})[0] == 404

    lc = (b'<LifecycleConfiguration><Rule><ID>r1</ID>'
          b'<Status>Enabled</Status><Expiration><Days>1</Days>'
          b'</Expiration></Rule></LifecycleConfiguration>')
    assert cli.request("PUT", "/conf", query={"lifecycle": ""},
                       body=lc)[0] == 200
    st, _, got = cli.request("GET", "/conf", query={"lifecycle": ""})
    assert st == 200 and b"<ID>r1</ID>" in got


def test_malformed_bucket_configs_rejected(cli):
    assert cli.request("PUT", "/conf", query={"policy": ""},
                       body=b"{not json")[0] == 400
    assert cli.request("PUT", "/conf", query={"lifecycle": ""},
                       body=b"<unclosed")[0] == 400


# ---------------------------------------------------------------------------
# POST policy upload
# ---------------------------------------------------------------------------

def _post_form(srv_addr, bucket, fields, file_data,
               filename="upload.bin"):
    boundary = "----testboundary42"
    parts = []
    for k, v in fields.items():
        parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                     f'name="{k}"\r\n\r\n{v}\r\n'.encode())
    parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                 f'name="file"; filename="{filename}"\r\n'
                 f"Content-Type: application/octet-stream\r\n\r\n".encode()
                 + file_data + b"\r\n")
    parts.append(f"--{boundary}--\r\n".encode())
    body = b"".join(parts)
    conn = http.client.HTTPConnection(srv_addr, timeout=30)
    try:
        conn.request("POST", f"/{bucket}", body=body, headers={
            "Content-Type": f"multipart/form-data; boundary={boundary}"})
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _signed_policy_fields(key_prefix, bucket, access="minioadmin",
                          secret="minioadmin", expire_mins=10):
    now = datetime.datetime.now(datetime.timezone.utc)
    exp = now + datetime.timedelta(minutes=expire_mins)
    date = now.strftime("%Y%m%d")
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    cred = f"{access}/{date}/us-east-1/s3/aws4_request"
    policy = {
        "expiration": exp.strftime("%Y-%m-%dT%H:%M:%S.000Z"),
        "conditions": [
            {"bucket": bucket},
            ["starts-with", "$key", key_prefix],
            ["content-length-range", 0, 10 << 20],
        ],
    }
    pol_b64 = base64.b64encode(json.dumps(policy).encode()).decode()
    skey = sigv4.signing_key(secret, date, "us-east-1")
    sig = hmac.new(skey, pol_b64.encode(), hashlib.sha256).hexdigest()
    return {
        "key": key_prefix + "${filename}",
        "policy": pol_b64,
        "x-amz-algorithm": "AWS4-HMAC-SHA256",
        "x-amz-credential": cred,
        "x-amz-date": amz_date,
        "x-amz-signature": sig,
    }


def test_post_policy_upload(srv, cli):
    data = os.urandom(10_000)
    fields = _signed_policy_fields("uploads/", "conf")
    st, body = _post_form(srv.address, "conf", fields, data,
                          filename="file1.bin")
    assert st == 204, body
    st, _, got = cli.request("GET", "/conf/uploads/file1.bin")
    assert st == 200 and got == data


def test_post_policy_bad_signature_rejected(srv):
    fields = _signed_policy_fields("uploads/", "conf")
    fields["x-amz-signature"] = "0" * 64
    st, body = _post_form(srv.address, "conf", fields, b"data")
    assert st == 403, body


def test_post_policy_condition_violation_rejected(srv):
    fields = _signed_policy_fields("uploads/", "conf")
    fields["key"] = "elsewhere/escape.bin"   # violates starts-with
    st, body = _post_form(srv.address, "conf", fields, b"data")
    assert st == 403, body


def test_post_policy_expired_rejected(srv):
    fields = _signed_policy_fields("uploads/", "conf", expire_mins=-10)
    st, body = _post_form(srv.address, "conf", fields, b"data")
    assert st == 403, body


# ---------------------------------------------------------------------------
# SDK wire behaviors (what boto3/aws-sdk actually put on the socket)
# ---------------------------------------------------------------------------

def test_expect_100_continue_put(srv, cli):
    """AWS SDKs send `Expect: 100-continue` on PUTs and wait for the
    interim response before the body; the server must answer it and
    then accept the payload (reference: Go's net/http does this
    transparently; BaseHTTPRequestHandler must too)."""
    assert cli.request("PUT", "/conf100")[0] == 200
    body = os.urandom(50_000)
    st, _, b = cli.request("PUT", "/conf100/exp", body=body,
                           headers={"Expect": "100-continue"})
    assert st == 200, b
    st, _, got = cli.request("GET", "/conf100/exp")
    assert st == 200 and got == body


def test_keep_alive_connection_reuse(srv):
    """SDKs pipeline many requests over one pooled connection; each
    response's framing must leave the socket clean for the next
    request (Content-Length exact, bodies fully drained)."""
    cli = S3Client(srv.address)
    assert cli.request("PUT", "/confka")[0] == 200
    conn = http.client.HTTPConnection(*srv.address.rsplit(":", 1),
                                      timeout=15)
    try:
        for i in range(6):
            body = f"ka-{i}".encode() * 100
            # Sign each request independently but send on ONE socket.
            import urllib.parse as _up
            now = datetime.datetime.now(datetime.timezone.utc)
            amz_date = now.strftime("%Y%m%dT%H%M%SZ")
            scope = f"{amz_date[:8]}/us-east-1/s3/aws4_request"
            path = f"/confka/k{i}"
            ph = hashlib.sha256(body).hexdigest()
            hdrs = {"host": srv.address, "x-amz-date": amz_date,
                    "x-amz-content-sha256": ph}
            signed = sorted(hdrs)
            canon = sigv4.canonical_request("PUT", path, {}, hdrs,
                                            signed, ph)
            sts = sigv4.string_to_sign(amz_date, scope, canon)
            key = sigv4.signing_key("minioadmin", amz_date[:8],
                                    "us-east-1")
            sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
            hdrs["Authorization"] = (
                f"{sigv4.ALGORITHM} Credential=minioadmin/{scope}, "
                f"SignedHeaders={';'.join(signed)}, Signature={sig}")
            conn.request("PUT", path, body=body, headers=hdrs)
            r = conn.getresponse()
            r.read()
            assert r.status == 200
        # All six landed through the one connection.
        c2 = S3Client(srv.address)
        for i in range(6):
            st, _, got = c2.request("GET", f"/confka/k{i}")
            assert st == 200 and got == f"ka-{i}".encode() * 100
    finally:
        conn.close()


def test_acl_surface(cli):
    """MinIO-parity ACLs (reference: cmd/acl-handlers.go): GET always
    answers the owner's FULL_CONTROL; only 'private' can be PUT;
    everything else points at bucket policies."""
    assert cli.request("PUT", "/aclbkt")[0] == 200
    assert cli.request("PUT", "/aclbkt/obj", body=b"a")[0] == 200
    for path in ("/aclbkt", "/aclbkt/obj"):
        st, _, b = cli.request("GET", path, query={"acl": ""})
        assert st == 200 and b"FULL_CONTROL" in b and b"Owner" in b
    # Canned private is accepted; anything else refused.
    assert cli.request("PUT", "/aclbkt", query={"acl": ""},
                       headers={"x-amz-acl": "private"})[0] == 200
    st, _, b = cli.request("PUT", "/aclbkt", query={"acl": ""},
                           headers={"x-amz-acl": "public-read"})
    assert st == 501, b
    st, _, b = cli.request("PUT", "/aclbkt/obj", query={"acl": ""},
                           headers={"x-amz-acl": "public-read"})
    assert st == 501, b
    # A grant body naming anything beyond FULL_CONTROL is refused.
    bad = (b'<AccessControlPolicy><AccessControlList><Grant>'
           b'<Permission>READ</Permission></Grant>'
           b'</AccessControlList></AccessControlPolicy>')
    st, _, b = cli.request("PUT", "/aclbkt", query={"acl": ""}, body=bad)
    assert st == 501, b
    # ACL of a missing object is a 404, not an empty grant set.
    st, _, _ = cli.request("GET", "/aclbkt/ghost", query={"acl": ""})
    assert st == 404
