"""Local storage engine: version journal, atomic commits, walk, bitrot framing."""

import os

import numpy as np
import pytest

from minio_tpu.storage import bitrot
from minio_tpu.storage.local import (LocalStorage, StorageError, VolumeExists,
                                     VolumeNotFound)
from minio_tpu.storage.meta import (ErasureInfo, FileInfo, FileNotFoundErr,
                                    ObjectPartInfo, VersionNotFoundErr,
                                    XLMeta, new_uuid, now_ns)


@pytest.fixture
def disk(tmp_path):
    return LocalStorage(str(tmp_path / "drive0"))


def _fi(name="obj", vid="", data_dir="", size=0, deleted=False, mod_time=None):
    return FileInfo(volume="bkt", name=name, version_id=vid,
                    data_dir=data_dir, size=size, deleted=deleted,
                    mod_time=mod_time if mod_time is not None else now_ns(),
                    erasure=ErasureInfo(data_blocks=2, parity_blocks=2,
                                        block_size=1 << 20, index=1,
                                        distribution=(1, 2, 3, 4)))


class TestVolumes:
    def test_make_list_stat_delete(self, disk):
        disk.make_vol("bkt")
        with pytest.raises(VolumeExists):
            disk.make_vol("bkt")
        assert [v.name for v in disk.list_vols()] == ["bkt"]
        assert disk.stat_vol("bkt").name == "bkt"
        disk.delete_vol("bkt")
        with pytest.raises(VolumeNotFound):
            disk.stat_vol("bkt")

    def test_sys_volume_hidden(self, disk):
        assert disk.list_vols() == []

    def test_invalid_names(self, disk):
        for bad in ("", ".", "..", "a/b"):
            with pytest.raises(StorageError):
                disk.make_vol(bad)


class TestMetaJournal:
    def test_roundtrip(self):
        xl = XLMeta()
        fi = _fi(vid=new_uuid(), data_dir=new_uuid(), size=123)
        fi.parts = [ObjectPartInfo(number=1, size=123, actual_size=123)]
        xl.add_version(fi)
        xl2 = XLMeta.load(xl.dump())
        got = xl2.to_fileinfo("bkt", "obj", fi.version_id)
        assert got.size == 123
        assert got.erasure.data_blocks == 2
        assert got.parts[0].number == 1
        assert got.is_latest

    def test_latest_ordering_and_delete_marker(self):
        xl = XLMeta()
        v1, v2 = new_uuid(), new_uuid()
        xl.add_version(_fi(vid=v1, mod_time=100))
        xl.add_version(_fi(vid=v2, mod_time=200))
        xl.add_version(_fi(vid="", deleted=True, mod_time=300))
        latest = xl.to_fileinfo("bkt", "obj")
        assert latest.deleted and latest.is_latest
        old = xl.to_fileinfo("bkt", "obj", v1)
        assert not old.deleted and not old.is_latest

    def test_inline_data(self):
        xl = XLMeta()
        fi = _fi(vid=new_uuid())
        fi.inline_data = b"shardbytes"
        xl.add_version(fi)
        xl2 = XLMeta.load(xl.dump())
        assert xl2.to_fileinfo("b", "o", fi.version_id, read_data=True).inline_data == b"shardbytes"
        # Without read_data the marker is an empty-bytes sentinel.
        assert xl2.to_fileinfo("b", "o", fi.version_id).inline_data == b""


class TestVersionedStorage:
    def test_write_read_delete_version(self, disk):
        disk.make_vol("bkt")
        vid = new_uuid()
        disk.write_metadata("bkt", "a/b/obj", _fi(vid=vid, size=7))
        got = disk.read_version("bkt", "a/b/obj")
        assert got.version_id == vid and got.size == 7
        disk.delete_version("bkt", "a/b/obj", vid)
        with pytest.raises(FileNotFoundErr):
            disk.read_version("bkt", "a/b/obj")
        # empty parents cleaned up
        assert not os.path.exists(os.path.join(disk.root, "bkt", "a"))

    def test_rename_data_commit(self, disk):
        disk.make_vol("bkt")
        ddir = new_uuid()
        staging = f"staging-{new_uuid()}"
        disk.create_file(".mtpu.sys", f"{staging}/{ddir}/part.1", b"SHARD")
        fi = _fi(vid=new_uuid(), data_dir=ddir, size=5)
        disk.rename_data(".mtpu.sys", staging, fi, "bkt", "obj")
        got = disk.read_version("bkt", "obj")
        assert got.data_dir == ddir
        assert disk.read_file("bkt", f"obj/{ddir}/part.1") == b"SHARD"
        # staging dir gone
        assert not os.path.exists(os.path.join(disk.root, ".mtpu.sys", staging))

    def test_nested_objects_coexist(self, disk):
        disk.make_vol("bkt")
        disk.write_metadata("bkt", "a", _fi(name="a", vid=new_uuid()))
        disk.write_metadata("bkt", "a/b", _fi(name="a/b", vid=new_uuid()))
        assert disk.read_version("bkt", "a").name == "a"
        assert disk.read_version("bkt", "a/b").name == "a/b"

    def test_walk_dir(self, disk):
        disk.make_vol("bkt")
        names = ["z", "a/1", "a/2", "m/x/deep"]
        for n in names:
            disk.write_metadata("bkt", n, _fi(name=n, vid=new_uuid()))
        # staged uuid data dir inside an object must not appear
        ddir = new_uuid()
        disk.create_file("bkt", f"a/1/{ddir}/part.1", b"x")
        walked = [p for p, _ in disk.walk_dir("bkt")]
        assert walked == sorted(names)

    def test_update_metadata_missing_version(self, disk):
        disk.make_vol("bkt")
        disk.write_metadata("bkt", "o", _fi(vid=new_uuid()))
        with pytest.raises(VersionNotFoundErr):
            disk.update_metadata("bkt", "o", _fi(vid=new_uuid()))


class TestBitrotFraming:
    def test_frame_and_read_roundtrip(self):
        rng = np.random.default_rng(3)
        shard = rng.integers(0, 256, size=10_000, dtype=np.uint8)
        blob = bitrot.frame_shard(shard, shard_size=4096)
        assert len(blob) == bitrot.shard_file_size(10_000, 4096)
        r = bitrot.FramedShardReader(blob, 4096, 10_000)
        got = np.concatenate([r.block(i) for i in range(3)])
        assert np.array_equal(got, shard)

    def test_batch_framing_matches_single(self):
        rng = np.random.default_rng(4)
        shards = rng.integers(0, 256, size=(6, 5000), dtype=np.uint8)
        batch = bitrot.frame_shards_batch(shards, shard_size=2048)
        for i in range(6):
            assert batch[i] == bitrot.frame_shard(shards[i], 2048)

    def test_corruption_detected(self):
        shard = np.arange(5000, dtype=np.int32).astype(np.uint8)
        blob = bytearray(bitrot.frame_shard(shard, shard_size=2048))
        blob[40] ^= 0xFF  # flip a data byte in block 0
        r = bitrot.FramedShardReader(bytes(blob), 2048, 5000)
        with pytest.raises(bitrot.BitrotError):
            r.block(0)
        r.block(1)  # other blocks still verify

    def test_whole_file_algorithms_unframed(self):
        assert bitrot.shard_file_size(100, 10, bitrot.SHA256) == 100


def test_create_file_odirect_roundtrip(tmp_path):
    """Streaming shard writes ride O_DIRECT with aligned bulk + ragged
    tail (reference: cmd/xl-storage.go:2147 writeAllDirect); bytes read
    back identical for aligned, unaligned and multi-chunk shapes."""
    from minio_tpu.storage import local as local_mod
    d = local_mod.LocalStorage(str(tmp_path / "od"))
    d.make_vol("v")
    cases = [
        [b"x" * 4096],                       # exactly one block
        [b"y" * (1 << 20), b"z" * 133],      # big + ragged tail
        [b"a" * 100],                        # tail-only
        [b"b" * 5000, b"c" * 7000, b"d" * 3],
        [],                                  # empty
    ]
    for i, chunks in enumerate(cases):
        d.create_file("v", f"f{i}", iter(chunks))
        want = b"".join(chunks)
        assert d.read_file("v", f"f{i}") == want, f"case {i}"


def test_create_file_falls_back_without_odirect(tmp_path, monkeypatch):
    from minio_tpu.storage import local as local_mod
    monkeypatch.setattr(local_mod, "O_DIRECT_ENABLED", False)
    d = local_mod.LocalStorage(str(tmp_path / "nod"))
    d.make_vol("v")
    d.create_file("v", "f", iter([b"q" * 9999]))
    assert d.read_file("v", "f") == b"q" * 9999


def test_read_file_odirect_matches_buffered(tmp_path, monkeypatch):
    """Bulk reads mirror the O_DIRECT write path: byte-identical to the
    buffered path across aligned/unaligned offsets and lengths, at EOF,
    and for whole-file reads (length=-1)."""
    import os as _os

    from minio_tpu.storage import local as local_mod
    d = local_mod.LocalStorage(str(tmp_path / "odr"))
    d.make_vol("v")
    blob = bytes(range(256)) * ((3 << 20) // 256) + b"tail" * 33
    d.create_file("v", "f", blob)
    # Force the direct path by dropping the size floor; every case
    # must match the buffered result exactly (including EOF clamps).
    monkeypatch.setattr(local_mod.LocalStorage, "_DIRECT_READ_MIN", 1)
    cases = [(0, len(blob)), (0, -1), (4096, 1 << 20),
             (4097, (1 << 20) + 13), (123, 456789),
             (len(blob) - 100, 100), (len(blob) - 7, 999),
             (0, len(blob) + 5000)]
    for off, ln in cases:
        got = d.read_file("v", "f", offset=off, length=ln)
        want = blob[off:] if ln < 0 else blob[off:off + ln]
        assert got == want, (off, ln, len(got), len(want))
    # The direct opener actually engaged (or cleanly fell back) —
    # either way behavior is identical; exercise fallback explicitly.
    monkeypatch.setattr(local_mod, "O_DIRECT_ENABLED", False)
    assert d.read_file("v", "f", offset=11, length=1 << 20) == \
        blob[11:11 + (1 << 20)]


def test_read_file_odirect_missing_file_raises(tmp_path):
    from minio_tpu.storage import local as local_mod
    from minio_tpu.storage.meta import FileNotFoundErr
    d = local_mod.LocalStorage(str(tmp_path / "odm"))
    d.make_vol("v")
    with pytest.raises(FileNotFoundErr):
        d.read_file("v", "nope", offset=0, length=4 << 20)
