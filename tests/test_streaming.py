"""O(block) streaming data path: windowed PUT, streamed GET, bounded
memory — the analogue of the reference's block-pipelined PutObject /
GetObject (cmd/erasure-object.go:1415-1428, cmd/erasure-encode.go:69).
"""

import hashlib
import json
import subprocess
import sys

import numpy as np
import pytest

from minio_tpu.object.erasure_object import (BLOCK_SIZE, STREAM_THRESHOLD,
                                             STREAM_WINDOW_BLOCKS, ErasureSet)
from minio_tpu.object.types import GetOptions, PutOptions, WriteQuorumError
from minio_tpu.storage.local import LocalStorage
from minio_tpu.utils.streams import (HashingReader, LimitedReader, Payload,
                                     StreamError)


# ---------------------------------------------------------------------------
# stream primitives
# ---------------------------------------------------------------------------

class _ChunkSource:
    """Deterministic pattern reader that never holds the full body."""

    def __init__(self, size, chunk=1 << 20, seed=7):
        self.size = size
        self._chunk = chunk
        self._made = 0
        self._rng = np.random.default_rng(seed)

    def read(self, n):
        n = min(n, self.size - self._made, self._chunk)
        if n <= 0:
            return b""
        out = self._rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        self._made += n
        return out


def _pattern_bytes(size, seed=7, chunk=1 << 20):
    src = _ChunkSource(size, chunk=chunk, seed=seed)
    return b"".join(iter(lambda: src.read(chunk), b""))


def test_payload_short_body_raises():
    p = Payload(_ChunkSource(100), 200)
    with pytest.raises(StreamError):
        p.read_exact(200)


def test_payload_finish_runs_once_before_last_byte_returns():
    calls = []
    p = Payload(_ChunkSource(64), 64, finish=lambda: calls.append(1))
    assert p.read_exact(64)
    assert calls == [1]
    assert p.read(10) == b""
    assert calls == [1]


def test_payload_finish_failure_propagates():
    def boom():
        raise ValueError("hash mismatch")
    p = Payload(_ChunkSource(32), 32, finish=boom)
    with pytest.raises(ValueError):
        p.read_exact(32)


def test_hashing_reader_matches():
    data = _pattern_bytes(100_000)
    src = Payload.wrap(data)
    hr = HashingReader(src)
    out = bytearray()
    while True:
        c = hr.read(8192)
        if not c:
            break
        out += c
    assert bytes(out) == data
    assert hr.hexdigest() == hashlib.sha256(data).hexdigest()


def test_limited_reader():
    class Endless:
        def read(self, n):
            return b"x" * n
    lr = LimitedReader(Endless(), 10)
    assert lr.read(6) == b"xxxxxx"
    assert lr.read(6) == b"xxxx"
    assert lr.read(6) == b""


# ---------------------------------------------------------------------------
# streamed PUT / GET through the erasure set
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def es(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sdrives")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(6)]
    s = ErasureSet(disks)
    s.make_bucket("sb")
    return s


SIZE = STREAM_THRESHOLD + 2 * BLOCK_SIZE + 12345   # 2 windows + tail


def test_streamed_put_roundtrip(es):
    body = _pattern_bytes(SIZE, seed=1)
    info = es.put_object("sb", "big", Payload(_ChunkSource(SIZE, seed=1),
                                              SIZE))
    assert info.size == SIZE
    assert info.etag == hashlib.md5(body).hexdigest()
    got_info, got = es.get_object("sb", "big")
    assert got == body
    # Streamed read matches, window-aligned chunks.
    sinfo, chunks = es.get_object_stream("sb", "big")
    assert sinfo.size == SIZE
    assert b"".join(chunks) == body


def test_streamed_get_range_across_windows(es):
    body = _pattern_bytes(SIZE, seed=1)
    lo = BLOCK_SIZE * (STREAM_WINDOW_BLOCKS - 1) + 11
    hi = STREAM_THRESHOLD + BLOCK_SIZE + 17   # crosses window boundary
    _, chunks = es.get_object_stream(
        "sb", "big", GetOptions(range_spec=(lo, hi)))
    assert b"".join(chunks) == body[lo:hi + 1]


def test_streamed_put_etag_matches_buffered(es):
    """Same bytes via buffered path produce the same etag/content."""
    small = _pattern_bytes(BLOCK_SIZE * 2 + 7, seed=3)
    es.put_object("sb", "small", small)
    _, got = es.get_object("sb", "small")
    assert got == small


def test_streamed_put_tolerates_minority_drive_failure(es):
    class Dead:
        def __getattr__(self, name):
            def fail(*a, **k):
                raise OSError("dead drive")
            return fail
    disks = list(es.disks)
    try:
        es.disks[5] = Dead()
        body_src = _ChunkSource(SIZE, seed=2)
        info = es.put_object("sb", "degraded", Payload(body_src, SIZE))
        body = _pattern_bytes(SIZE, seed=2)
        assert info.etag == hashlib.md5(body).hexdigest()
    finally:
        es.disks[:] = disks
    _, got = es.get_object("sb", "degraded")
    assert got == body


def test_streamed_put_quorum_failure_cleans_staging(es):
    class Dead:
        def __getattr__(self, name):
            def fail(*a, **k):
                raise OSError("dead drive")
            return fail
    disks = list(es.disks)
    try:
        for i in (2, 3, 4, 5):
            es.disks[i] = Dead()
        with pytest.raises(WriteQuorumError):
            es.put_object("sb", "failed",
                          Payload(_ChunkSource(SIZE, seed=4), SIZE))
    finally:
        es.disks[:] = disks
    # No staged leftovers on the healthy drives.
    import os
    for d in disks[:2]:
        staging = os.path.join(d.root, ".mtpu.sys", "staging")
        if os.path.isdir(staging):
            assert os.listdir(staging) == []


def test_streamed_payload_verification_aborts_before_commit(es):
    """A finish-hook failure (content-hash mismatch) must abort: object
    never becomes visible."""
    def boom():
        raise ValueError("sha mismatch")
    with pytest.raises(ValueError):
        es.put_object("sb", "tampered",
                      Payload(_ChunkSource(SIZE, seed=5), SIZE, finish=boom))
    from minio_tpu.object.types import ObjectNotFound
    with pytest.raises(ObjectNotFound):
        es.get_object("sb", "tampered")


def test_multipart_streamed_part(es):
    uid = es.new_multipart_upload("sb", "mpstream", PutOptions())
    psize = STREAM_THRESHOLD + BLOCK_SIZE + 99
    part = es.put_object_part("sb", "mpstream", uid, 1,
                              Payload(_ChunkSource(psize, seed=6), psize))
    body = _pattern_bytes(psize, seed=6)
    assert part.etag == hashlib.md5(body).hexdigest()
    es.complete_multipart_upload("sb", "mpstream", uid, [(1, part.etag)])
    _, got = es.get_object("sb", "mpstream")
    assert got == body


# ---------------------------------------------------------------------------
# bounded memory (subprocess, RSS high-water mark)
# ---------------------------------------------------------------------------

_MEM_SCRIPT = r"""
import json, resource, sys
sys.path.insert(0, {repo!r})
import numpy as np
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.storage.local import LocalStorage
from minio_tpu.utils.streams import Payload

SIZE = 512 << 20   # 512 MiB object
class Src:
    def __init__(self, size):
        self.left = size
        self.block = np.arange(1 << 20, dtype=np.uint8).tobytes()
    def read(self, n):
        n = min(n, self.left, len(self.block))
        self.left -= n
        return self.block[:n]

disks = [LocalStorage({tmp!r} + f"/d{{i}}") for i in range(4)]
es = ErasureSet(disks)
es.make_bucket("m")
# Warm every code path (compiles, pools, native lib) with a small
# streamed object, THEN measure: the delta for a 512 MiB object must be
# window-sized, not object-sized.
warm = 40 << 20
es.put_object("m", "warm", Payload(Src(warm), warm))
for c in es.get_object_stream("m", "warm")[1]:
    pass
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
es.put_object("m", "huge", Payload(Src(SIZE), SIZE))
info, chunks = es.get_object_stream("m", "huge")
total = 0
for c in chunks:
    total += len(c)
assert total == SIZE, total
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({{"base_kib": base, "rss_kib": rss}}))
"""


@pytest.mark.slow
def test_bounded_memory_large_object(tmp_path):
    """A 512 MiB object must stream through with only window-sized
    memory growth over a warmed baseline — O(window), not O(object)."""
    import pathlib
    repo = str(pathlib.Path(__file__).resolve().parents[1])
    script = _MEM_SCRIPT.format(repo=repo, tmp=str(tmp_path))
    r = subprocess.run([sys.executable, "-c", script], capture_output=True,
                       text=True, timeout=600,
                       env={"PATH": "/usr/bin:/bin", "HOME": "/root",
                            "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-2000:]
    stats = json.loads(r.stdout.strip().splitlines()[-1])
    grown = stats["rss_kib"] - stats["base_kib"]
    # A window is 32 MiB plaintext / 48 MiB framed; queues hold <= 2
    # windows per drive set. 512 MiB of payload must not show up.
    assert grown < 220 * 1024, stats
