"""Deep tracing: span trees through drives/engine/kernels, typed trace
streaming (incl. cross-worker over the pre-forked control pipes),
last-minute latency windows, per-drive histograms, and the slow-op log
(reference: TraceHandler internal trace types + pubsub,
cmd/last-minute.gen.go, metrics-v3 histograms)."""

import datetime
import hashlib
import hmac as hmac_mod
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import types as types_mod

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3 import sigv4
from minio_tpu.s3.server import S3Server
from minio_tpu.s3.trace import AuditLogger, TraceBroadcaster, make_entry
from minio_tpu.storage.health import wrap_disks
from minio_tpu.storage.local import LocalStorage
from minio_tpu.utils import latency, tracing
from tests.s3client import S3Client

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# broadcaster: typed subscriptions + slow-subscriber drop-oldest
# ---------------------------------------------------------------------------

def test_broadcaster_typed_subscription_filters():
    b = TraceBroadcaster()
    qs3 = b.subscribe()                       # default: s3 only
    qst = b.subscribe(types={"storage", "kernel"})
    assert tracing.ACTIVE, "internal subscriber must arm span collection"
    b.publish({"trace_type": "s3", "i": 1})
    b.publish({"trace_type": "storage", "i": 2})
    b.publish({"trace_type": "kernel", "i": 3})
    b.publish({"trace_type": "grid", "i": 4})  # nobody wants grid
    b.publish({"i": 5})                        # untyped = s3
    assert [qs3.get_nowait()["i"] for _ in range(2)] == [1, 5]
    assert qs3.empty()
    assert [qst.get_nowait()["i"] for _ in range(2)] == [2, 3]
    assert qst.empty()
    b.unsubscribe(qst)
    assert not tracing.ACTIVE or tracing.slow_ms() > 0, \
        "last internal subscriber gone must disarm"
    b.unsubscribe(qs3)
    assert not b.active


def test_broadcaster_slow_subscriber_drops_oldest():
    b = TraceBroadcaster()
    q = b.subscribe(types={"storage"})
    try:
        for i in range(1500):               # over queue depth of 1000
            b.publish({"trace_type": "storage", "i": i})
        got = []
        while not q.empty():
            got.append(q.get_nowait()["i"])
        assert len(got) == 1000
        assert got[-1] == 1499, "newest entry must survive"
        assert got[0] == 500, "oldest entries must be the ones dropped"
    finally:
        b.unsubscribe(q)


def test_broadcast_entries_bypass_type_filters():
    # The span-truncation marker (`broadcast`) must reach a
    # storage-only subscriber even though it is typed s3 — a filtered
    # stream still has to learn its span tree is incomplete.
    b = TraceBroadcaster()
    q = b.subscribe(types={"storage"})
    try:
        b.publish({"trace_type": "s3", "api": "trace.dropped",
                   "broadcast": True})
        b.publish({"trace_type": "s3", "api": "normal-root"})
        got = []
        while not q.empty():
            got.append(q.get_nowait()["api"])
        assert got == ["trace.dropped"]
    finally:
        b.unsubscribe(q)


def test_query_rpc_discards_stale_replies():
    # A reply landing AFTER its request timed out must not be served
    # as the answer to the next exchange on the same worker pipe.
    import socket as socket_mod
    from minio_tpu.io.workers import WorkerPool, _recv_msg, _send_msg
    pool = WorkerPool.__new__(WorkerPool)
    import itertools
    pool._rid = itertools.count(1)
    parent, child = socket_mod.socketpair()
    try:
        rec = {"worker": 0, "query": parent, "qmu": threading.Lock()}

        def responder():
            # Stale leftover from a timed-out earlier exchange...
            _send_msg(child, {"rid": 9999, "entries": ["stale"]})
            # ...then answer the real request properly.
            msg = _recv_msg(child, timeout=5.0)
            _send_msg(child, {"rid": msg["rid"], "stats": ["fresh"]})

        t = threading.Thread(target=responder, daemon=True)
        t.start()
        time.sleep(0.1)            # stale reply is already buffered
        reply = pool._query_rpc(rec, {"op": "stat"}, timeout=5.0)
        assert reply["stats"] == ["fresh"]
        t.join(timeout=5)
    finally:
        parent.close()
        child.close()


def test_broadcaster_remote_relay_arms_and_drains():
    b = TraceBroadcaster()
    b.arm_remote(["s3", "storage"])
    assert b.active and tracing.ACTIVE
    b.publish({"trace_type": "storage", "i": 1})
    b.publish({"trace_type": "kernel", "i": 2})   # not relayed
    b.publish({"trace_type": "s3", "i": 3})
    assert [e["i"] for e in b.drain_remote()] == [1, 3]
    assert b.drain_remote() == []
    b.disarm_remote()
    assert not b.active


def test_remote_relay_ttl_self_disarms():
    # A worker whose parent never delivered trace_stop (timeout,
    # respawn, parent death) must not stay armed forever: the relay
    # expires when no drain refreshes it within the TTL.
    b = TraceBroadcaster()
    b.arm_remote(["storage"])
    assert b.active and tracing.ACTIVE
    b._remote_deadline = time.monotonic() - 1     # simulate staleness
    b.publish({"trace_type": "storage", "i": 1})  # lazy expiry check
    assert not b.active
    assert b.drain_remote() == []
    assert not tracing.ACTIVE or tracing.slow_ms() > 0


# ---------------------------------------------------------------------------
# span tree over a real erasure PUT + GET
# ---------------------------------------------------------------------------

@pytest.fixture()
def traced_set(tmp_path):
    disks = wrap_disks([LocalStorage(str(tmp_path / f"d{i}"))
                        for i in range(4)])
    es = ErasureSet(disks)
    es.make_bucket("b")
    tracing.arm("test")
    yield es
    tracing.disarm("test")
    es.close()


def _span_index(ctx):
    return {s["span"]: s for s in ctx.spans}


def test_span_tree_linkage_put_get(traced_set):
    es = traced_set
    body = b"z" * (1 << 20)
    ctx_put = tracing.TraceContext()
    with tracing.bind(ctx_put):
        es.put_object("b", "k", body)
    ctx_get = tracing.TraceContext()
    with tracing.bind(ctx_get):
        _, got = es.get_object("b", "k")
    assert got == body

    from minio_tpu import native
    for ctx, kernel_name in ((ctx_put, "mtpu_put_frame"),
                             (ctx_get, "mtpu_get_frame")):
        by_id = _span_index(ctx)
        engine = [s for s in ctx.spans if s["name"] == "engine.op"]
        disk = [s for s in ctx.spans if s["name"].startswith("disk.")]
        assert engine and disk, ctx.spans
        # Engine spans hang off the root; every disk op is a child of
        # an engine span on the SAME drive queue, and carries the
        # queue-wait split in its parent's tags.
        for s in engine:
            assert s["parent"] == 0
            assert "queue_wait_ms" in s["tags"]
        for s in disk:
            parent = by_id[s["parent"]]
            assert parent["name"] == "engine.op", s
        if native.load() is not None:
            kernels = [s for s in ctx.spans if s["type"] == "kernel"]
            assert [s["name"] for s in kernels] == [kernel_name]
            assert kernels[0]["parent"] == 0
        # Span ids unique, parents resolve inside the same trace.
        assert len(by_id) == len(ctx.spans)
        for s in ctx.spans:
            assert s["parent"] == 0 or s["parent"] in by_id


def test_slow_op_log_names_ancestry(traced_set):
    es = traced_set
    before = tracing.slow_total
    tracing.set_slow_ms(0.0001)        # everything is "slow"
    try:
        with tracing.bind(tracing.TraceContext()):
            es.put_object("b", "slowk", b"s" * 200_000)
    finally:
        tracing.set_slow_ms(0.0)
    assert tracing.slow_total > before
    disk_ops = [o for o in tracing.slow_ops()
                if o["name"].startswith("disk.") and o.get("slow")]
    assert disk_ops, "per-drive slow records expected"
    rec = disk_ops[-1]
    assert rec["ancestry"] == ["<root>", "engine.op"], rec
    assert rec["threshold_ms"] == 0.0001
    assert rec["tags"]["drive"], "slow op must name its drive"


def test_grid_call_and_stream_spans(tmp_path):
    from minio_tpu.grid.client import GridClient
    from minio_tpu.grid.server import GridServer
    gs = GridServer(0, host="127.0.0.1")
    gs.register("echo", lambda p: p)
    gs.register_stream("count", lambda p: iter(range(p)))
    gs.start()
    tracing.arm("test-grid")
    try:
        cli = GridClient("127.0.0.1", gs.port)
        ctx = tracing.TraceContext()
        with tracing.bind(ctx):
            with tracing.span("storage", "disk.remote_op"):
                assert cli.call("echo", {"x": 1}) == {"x": 1}
            assert list(cli.stream("count", 3)) == [0, 1, 2]
        cli.close()
        grid = [s for s in ctx.spans if s["type"] == "grid"]
        # Armed grid calls now propagate the trace to the peer and
        # stitch its subtree back under an explicit wire span (one per
        # round-trip) carrying the serialize/transit/peer timing split.
        assert {s["name"] for s in grid} == {"grid.echo", "grid.count",
                                             "wire"}
        by_name = {s["name"]: s for s in grid}
        # The unary call nested under the storage span; the stream span
        # hangs off the root and counted its chunks.
        parent = [s for s in ctx.spans if s["name"] == "disk.remote_op"]
        assert by_name["grid.echo"]["parent"] == parent[0]["span"]
        assert by_name["grid.count"]["tags"]["chunks"] == 3
        wires = [s for s in grid if s["name"] == "wire"]
        assert len(wires) == 2
        assert {w["parent"] for w in wires} == {
            by_name["grid.echo"]["span"], by_name["grid.count"]["span"]}
    finally:
        tracing.disarm("test-grid")
        gs.stop()


# ---------------------------------------------------------------------------
# histograms + last-minute windows
# ---------------------------------------------------------------------------

def test_latency_histogram_and_percentiles():
    h = latency.Histogram()
    for ms in (1, 2, 30, 30, 30, 400):
        h.observe(ms / 1000.0)
    st = h.state()
    assert st["count"] == 6
    cum = dict(latency.Histogram.cumulative(st))
    assert cum["+Inf"] == 6
    assert cum["0.05"] == 5          # all but the 400 ms one
    merged = latency.Histogram.merge([st, st])
    assert merged["count"] == 12

    lm = latency.LastMinute()
    now = time.time()
    for _ in range(90):
        lm.observe(0.004, now=now)
    for _ in range(10):
        lm.observe(0.8, now=now)
    s = lm.stats(now=now)
    assert s["count"] == 100
    assert s["p50"] == 0.005         # bucket upper bound containing 4 ms
    assert s["p99"] >= 0.5           # rank 99 lands in the slow tail
    assert s["max"] == 0.8
    # Entries age out of the trailing minute.
    assert lm.stats(now=now + 120)["count"] == 0

    # Quantiles landing in the +Inf bucket report the tracked max,
    # not a silent cap — a 60 s stall must read as 60 s.
    stall = latency.LastMinute()
    for _ in range(10):
        stall.observe(60.0, now=now)
    s2 = stall.stats(now=now)
    assert s2["p50"] == 60.0 and s2["p99"] == 60.0 and s2["max"] == 60.0


def test_per_drive_histogram_and_last_minute_in_metrics(traced_set):
    es = traced_set
    for i in range(4):
        es.put_object("b", f"m-{i}", b"q" * 4096)
    from minio_tpu.s3.metrics import Metrics
    m = Metrics()
    m.record("PUT:object", 200, 0.004)
    m.record("PUT:object", 200, 0.004)
    text = m.render(object_layer=es)
    # Per-drive histogram buckets + last-minute p99 rendered per drive.
    assert re.search(r'minio_tpu_drive_op_duration_seconds_bucket'
                     r'\{set="0",drive="0",le="\+Inf"\} [1-9]', text)
    drive_p99 = re.findall(
        r'minio_tpu_drive_last_minute_seconds'
        r'\{set="0",drive="\d+",q="p99"\} ([0-9.]+)', text)
    assert len(drive_p99) == 4 and all(float(v) > 0 for v in drive_p99)
    assert re.search(r'minio_tpu_drive_queue_wait_last_minute_seconds'
                     r'\{set="0",drive="0",q="p99"\} [0-9.]+', text)
    # Per-API histogram + last-minute.
    assert re.search(r'minio_tpu_api_request_duration_seconds_bucket'
                     r'\{api="PUT:object",le="0.005"\} 2', text)
    assert re.search(r'minio_tpu_api_last_minute_seconds'
                     r'\{api="PUT:object",q="p99"\} 0\.005', text)
    assert 'minio_tpu_api_last_minute_requests{api="PUT:object"} 2' in text
    # Last-minute merging across (simulated) workers doubles counts —
    # per-API and PER-DRIVE (each worker ships labelled engine rows;
    # the scrape merges the fleet, not its own 1/N slice).
    st = m.state()
    engine_rows = []
    for si, s in enumerate([es]):
        for di, est in enumerate(s.io.stats()):
            engine_rows.append({"set": si, "drive": di, **est})
    peers = [{"metrics": st, "engine": engine_rows},
             {"metrics": st, "engine": engine_rows}]
    text2 = m.render(object_layer=es, peer_states=peers)
    assert 'minio_tpu_api_last_minute_requests{api="PUT:object"} 4' in text2
    assert re.search(r'minio_tpu_api_request_duration_seconds_bucket'
                     r'\{api="PUT:object",le="0.005"\} 4', text2)
    one = int(re.search(r'minio_tpu_drive_op_duration_seconds_count'
                        r'\{set="0",drive="0"\} (\d+)', text).group(1))
    two = int(re.search(r'minio_tpu_drive_op_duration_seconds_count'
                        r'\{set="0",drive="0"\} (\d+)', text2).group(1))
    assert two == 2 * one, (one, two)
    assert re.search(r'minio_tpu_drive_last_minute_seconds'
                     r'\{set="0",drive="0",q="p99"\} [0-9.]+', text2)


# ---------------------------------------------------------------------------
# make_entry precision + audit counters
# ---------------------------------------------------------------------------

def test_make_entry_millisecond_timestamps():
    e = make_entry("GET:object", "GET", "/b/k", "b", "k", 200, 0.01,
                   "127.0.0.1", "ak")
    assert re.match(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z$",
                    e["time"]), e["time"]
    # Two entries in one burst sort (strictly or equal, never coarser
    # than a millisecond apart when >= 1 ms elapsed).
    t0 = make_entry("a", "GET", "/", "", "", 200, 0, "", "")["time"]
    time.sleep(0.002)
    t1 = make_entry("a", "GET", "/", "", "", 200, 0, "", "")["time"]
    assert t1 > t0


def test_audit_drop_counters_surface():
    # Unreachable target: deliveries fail, retries exhaust, drops count.
    log = AuditLogger("http://127.0.0.1:1/audit", timeout=0.2)
    log._MAX_ATTEMPTS = 1
    try:
        log.submit(make_entry("PUT:object", "PUT", "/b/k", "b", "k", 200,
                              0.01, "127.0.0.1", "ak"))
        deadline = time.time() + 10
        while log.dropped == 0 and time.time() < deadline:
            time.sleep(0.05)
        st = log.stats()
        assert st["dropped"] >= 1 and st["sent"] == 0
        # Exported in Prometheus text via the server hook.
        from minio_tpu.s3.metrics import Metrics
        fake_server = types_mod.SimpleNamespace(audit=log)
        text = Metrics().render(server=fake_server)
        assert re.search(r"minio_tpu_audit_dropped_total [1-9]", text)
        assert "minio_tpu_audit_sent_total 0" in text
        assert "minio_tpu_audit_pending" in text
    finally:
        log.stop()


# ---------------------------------------------------------------------------
# admin trace over HTTP: typed internal spans, linkage, admin info
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("deeptr")
    disks = wrap_disks([LocalStorage(str(tmp / f"d{i}"))
                        for i in range(4)])
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()


def _stream_trace(address, query: dict, out: list):
    """One raw signed GET of /minio/admin/v3/trace, de-chunked, JSON
    lines appended to `out` (the S3Client can't stream)."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"
    payload_hash = hashlib.sha256(b"").hexdigest()
    hdrs = {"host": address, "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash}
    signed = sorted(hdrs)
    q = {k: [v] for k, v in query.items()}
    canon = sigv4.canonical_request("GET", "/minio/admin/v3/trace", q,
                                    hdrs, signed, payload_hash)
    sts = sigv4.string_to_sign(amz_date, scope, canon)
    skey = sigv4.signing_key("minioadmin", date, "us-east-1")
    sig = hmac_mod.new(skey, sts.encode(), hashlib.sha256).hexdigest()
    qs = "&".join(f"{k}={v}" for k, v in sorted(query.items()))
    conn = http.client.HTTPConnection(address, timeout=30)
    conn.request("GET", f"/minio/admin/v3/trace?{qs}", headers={
        **hdrs,
        "Authorization": f"{sigv4.ALGORITHM} "
        f"Credential=minioadmin/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}"})
    resp = conn.getresponse()
    body = resp.read()              # http.client de-chunks
    conn.close()
    for line in body.splitlines():
        if line.strip():
            out.append(json.loads(line))


def test_admin_trace_internal_types_and_linkage(srv):
    cli = S3Client(srv.address)
    assert cli.request("PUT", "/deep")[0] == 200
    entries: list = []
    t = threading.Thread(target=_stream_trace,
                         args=(srv.address, {"types": "all", "count": "60"},
                               entries),
                         daemon=True)
    t.start()
    deadline = time.time() + 5
    while not tracing.ACTIVE and time.time() < deadline:
        time.sleep(0.05)            # types=all subscriber arms spans
    assert tracing.ACTIVE
    body = os.urandom(300_000)
    assert cli.request("PUT", "/deep/one", body=body)[0] == 200
    st, _, got = cli.request("GET", "/deep/one")
    assert st == 200 and got == body
    # Pad with s3-only requests so the count limit is reached and the
    # stream closes regardless of per-request span counts.
    for _ in range(60):
        cli.request("GET", "/minio/health/live", sign=False)
        if not t.is_alive():
            break
        time.sleep(0.05)
    t.join(timeout=20)
    assert not t.is_alive() and entries

    puts = [e for e in entries
            if e.get("trace_type") == "s3" and e["api"] == "PUT:object"]
    gets = [e for e in entries
            if e.get("trace_type") == "s3" and e["api"] == "GET:object"]
    assert puts and gets, entries[:5]
    for root in (puts[0], gets[0]):
        tid = root["trace"]
        assert root["span"] == 0
        kids = [e for e in entries if e.get("trace") == tid
                and e is not root]
        storage = [e for e in kids if e["trace_type"] == "storage"]
        assert storage, f"no storage spans for {root['api']}"
        ids = {e["span"] for e in kids} | {0}
        for e in kids:
            assert e["parent"] in ids, e
        # Every span streams exactly once (slow-op marking must not
        # double-publish a span under the same trace/span id).
        assert len(ids) == len(kids) + 1
        engine_ids = {e["span"] for e in kids if e["api"] == "engine.op"}
        disk = [e for e in kids if e["api"].startswith("disk.")]
        assert disk and all(e["parent"] in engine_ids for e in disk)


def test_admin_trace_default_excludes_internal(srv):
    cli = S3Client(srv.address)
    entries: list = []
    t = threading.Thread(target=_stream_trace,
                         args=(srv.address, {"count": "3"}, entries),
                         daemon=True)
    t.start()
    time.sleep(0.4)
    cli.request("PUT", "/deft")
    cli.request("PUT", "/deft/o", body=b"1")
    cli.request("GET", "/deft/o")
    t.join(timeout=15)
    assert len(entries) == 3
    assert all(e.get("trace_type", "s3") == "s3" for e in entries)
    apis = [e["api"] for e in entries]
    assert apis == ["PUT:bucket", "PUT:object", "GET:object"]


def test_admin_info_surfaces_last_minute_and_slow_ops(srv):
    cli = S3Client(srv.address)
    cli.request("PUT", "/obsb")
    cli.request("PUT", "/obsb/k", body=b"x" * 1000)
    st, _, raw = cli.request("GET", "/minio/admin/v3/info")
    assert st == 200
    info = json.loads(raw)
    assert "PUT:object" in info["last_minute"]
    assert info["last_minute"]["PUT:object"]["count"] >= 1
    assert info["last_minute"]["PUT:object"]["p99"] > 0
    assert "slow_ops" in info and "total" in info["slow_ops"]


# ---------------------------------------------------------------------------
# cross-worker trace streaming (2 pre-forked workers)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def worker_server(tmp_path_factory):
    """A 2-worker pre-forked server on shared drives (subprocess: the
    pytest process has JAX loaded, and fork-after-JAX is unsafe)."""
    root = tmp_path_factory.mktemp("trworkers")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2")
    proc = subprocess.Popen(
        [sys.executable, "-m", "minio_tpu.server",
         "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
         f"{root}/d{{1...4}}"],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    address = f"127.0.0.1:{port}"
    deadline = time.time() + 90
    ready = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        try:
            st, _, _ = S3Client(address).request(
                "GET", "/minio/health/live", sign=False)
            if st == 200:
                ready = True
                break
        except OSError:
            time.sleep(0.4)
    if not ready:
        out = proc.stdout.read().decode(errors="replace") \
            if proc.stdout else ""
        proc.kill()
        pytest.skip(f"worker fleet failed to boot: {out[-800:]}")
    yield address
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=25)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_cross_worker_trace_stream(worker_server):
    """A trace stream served by ONE worker must carry entries for
    requests the kernel routed to EVERY worker (parent control-pipe
    relay, io/workers.py trace pump)."""
    addr = worker_server
    n_req = 14
    entries: list = []
    t = threading.Thread(
        target=_stream_trace,
        args=(addr, {"types": "all", "count": str(40 * n_req)}, entries),
        daemon=True)
    t.start()
    time.sleep(1.2)                 # subscription + fleet arming settle
    body = os.urandom(200_000)
    cli = S3Client(addr)
    assert cli.request("PUT", "/xwb")[0] == 200
    for i in range(n_req):
        # Fresh connection per request: the kernel spreads them.
        assert S3Client(addr).request("PUT", f"/xwb/o{i}",
                                      body=body)[0] == 200
    deadline = time.time() + 25
    j = 0
    while t.is_alive() and time.time() < deadline:
        # Keep traffic flowing until the count limit closes the stream.
        # Overwriting PUTs (not GETs): a repeat GET can be a hot-tier
        # hit tracing as a single root entry, while every PUT emits the
        # full storage/engine span fan-out the count budget assumes.
        S3Client(addr).request("PUT", f"/xwb/o{j % n_req}", body=body)
        j += 1
        time.sleep(0.1)
    roots = [e for e in entries if e.get("trace_type") == "s3"
             and e.get("api") in ("PUT:object", "GET:object")]
    assert roots, f"no s3 roots in {len(entries)} entries"
    workers_seen = {e.get("worker") for e in roots}
    assert len(workers_seen) >= 2, \
        f"entries only from workers {workers_seen}"
    # Internal spans relay cross-worker too, linked to their roots.
    tids = {e["trace"] for e in roots}
    storage = [e for e in entries if e.get("trace_type") == "storage"
               and e.get("trace") in tids]
    assert storage, "no storage spans relayed from the fleet"
    if t.is_alive():
        # Stream still open (count not reached): one last burst.
        for k in range(10):
            S3Client(addr).request("PUT", f"/xwb/o{k % n_req}",
                                   body=body)
        t.join(timeout=10)
