"""Metadata plane: native batched xl.meta scan, trimmed walk entries,
shallow delimiter walks, and the fileinfo cache's stat class.

The load-bearing guarantee: every listing surface is FIELD-IDENTICAL
with the native scanner on and off — the scanner is an accelerator, not
a second source of truth. Journals the scanner rejects must flow
through the Python parser and land in the fallback counter, never
change results.
"""

import os
import random

import pytest

from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.object.types import DeleteOptions, PutOptions
from minio_tpu.storage import meta_scan
from minio_tpu.storage.local import LocalStorage
from minio_tpu.storage.meta import (ErasureInfo, FileInfo, ObjectPartInfo,
                                    XLMeta)

RND = random.Random(1234)


def _fi(name, vid="", deleted=False, meta=None, inline=None, ddir="",
        mt=None):
    fi = FileInfo(
        volume="b", name=name, version_id=vid, deleted=deleted,
        data_dir=ddir, mod_time=mt or RND.randrange(1, 1 << 62),
        size=RND.randrange(0, 1 << 40),
        metadata=meta if meta is not None else
        {"etag": "e" * 32, "content-type": "text/plain"},
        inline_data=inline)
    if not deleted:
        fi.parts = [ObjectPartInfo(number=1, size=fi.size,
                                   actual_size=fi.size, etag="p" * 8)]
        fi.erasure = ErasureInfo(data_blocks=2, parity_blocks=1,
                                 block_size=1 << 20, index=1,
                                 distribution=(1, 2, 3))
    return fi


def _corpus():
    """(name, blob) journals covering every scanner decision path."""
    out = []
    x = XLMeta()
    x.add_version(_fi("a", inline=b"xyz"))
    out.append(("single-inline", x.dump()))

    x = XLMeta()
    x.add_version(_fi("a", ddir="11111111-1111-4111-8111-111111111111"))
    out.append(("single-ddir", x.dump()))

    x = XLMeta()
    x.add_version(_fi("a", vid="22222222-2222-4222-8222-222222222222",
                      mt=5))
    x.add_version(_fi("a", vid="33333333-3333-4333-8333-333333333333",
                      deleted=True, mt=9, meta={}))
    out.append(("delete-marker-latest", x.dump()))

    x = XLMeta()
    x.add_version(_fi("a", meta={"etag": "e", "x-amz-meta-user": "v",
                                 "content-type": "x"}))
    out.append(("user-meta", x.dump()))

    x = XLMeta()
    x.add_version(_fi("a", meta={"etag": "e",
                                 "x-internal-sse-size": "123"}))
    out.append(("internal-meta", x.dump()))

    x = XLMeta()
    x.add_version(_fi("日本/キー", vid="null",
                      meta={"etag": "é" * 40,
                            "x-amz-tagging": "k=v&a=b"}))
    out.append(("unicode", x.dump()))

    x = XLMeta()
    for v in range(5):
        x.add_version(_fi("a", vid=f"{v:08d}-0000-4000-8000-"
                                   "000000000000", mt=100 + v))
    out.append(("five-versions", x.dump()))

    x = XLMeta()
    for v in range(meta_scan.MAXV + 1):
        x.add_version(_fi("a", vid=f"{v:08d}-0000-4000-8000-"
                                   "000000000001", mt=200 + v))
    out.append(("over-maxv", x.dump()))

    x = XLMeta()
    x.add_version(_fi("a", meta={}, mt=1))
    out.append(("empty-meta", x.dump()))

    out.append(("bad-magic", b"NOPE" + b"\x00" * 16))
    out.append(("truncated", XLMeta().dump()[:-1] if XLMeta().dump()
                else b"XTP1"))
    out.append(("torn", b"XTP1\x81\xa8versions\xc1"))
    return out


def test_native_scan_matches_python_mirror():
    """scan_blob (native when built) and summarize_xl (pure Python)
    classify and summarize every corpus blob identically."""
    for name, blob in _corpus():
        got = meta_scan.scan_blob(blob)
        try:
            ref = meta_scan.summarize_xl(XLMeta.load(blob))
        except Exception:  # noqa: BLE001 - unreadable blob
            ref = None
        assert got == ref, (name, got, ref)


def test_native_scan_fuzz_random_journals():
    rnd = random.Random(99)
    for trial in range(60):
        x = XLMeta()
        for v in range(rnd.randrange(1, 6)):
            meta = {"etag": "%032x" % rnd.getrandbits(128)}
            if rnd.random() < 0.4:
                meta["content-type"] = "application/x-" + str(trial)
            if rnd.random() < 0.3:
                meta["x-amz-tagging"] = "a=b"
            if rnd.random() < 0.25:
                meta["x-amz-meta-k"] = "v" * rnd.randrange(1, 50)
            x.add_version(_fi(
                f"k{trial}", deleted=rnd.random() < 0.2,
                vid=f"{v:08d}-{trial:04d}-4000-8000-000000000000",
                meta=meta,
                inline=b"d" if rnd.random() < 0.5 else None,
                ddir="" if rnd.random() < 0.5 else
                "44444444-4444-4444-8444-444444444444"))
        blob = x.dump()
        assert meta_scan.scan_blob(blob) == \
            meta_scan.summarize_xl(XLMeta.load(blob)), trial


def test_scan_counters_move():
    before_n = meta_scan.counters["native"]
    before_f = meta_scan.counters["fallback"]
    good = _corpus()[0][1]
    meta_scan.scan_blob(good)
    meta_scan.scan_blob(b"XTP1\x81\xa8versions\xc1")
    moved = (meta_scan.counters["native"] - before_n) + \
        (meta_scan.counters["fallback"] - before_f)
    assert moved >= 2
    assert meta_scan.counters["fallback"] > before_f


def test_blob_scanner_batch_order_and_blob_policy(tmp_path):
    """BlobScanner returns results in add() order; rejected blobs and
    insufficient summaries carry bytes, clean summaries do not."""
    blobs = _corpus()
    paths = []
    for i, (name, blob) in enumerate(blobs):
        p = tmp_path / f"blob-{i:02d}"
        p.write_bytes(blob)
        paths.append((f"key-{i:02d}-{name}", str(p), blob))
    sc = meta_scan.BlobScanner(max_items=4)
    out = []
    for key, p, _ in paths:
        if sc.full():
            out.extend(sc.flush())
        fd = os.open(p, os.O_RDONLY)
        try:
            sc.add(key, fd)
        finally:
            os.close(fd)
    out.extend(sc.flush())
    sc.close()
    assert [o[0] for o in out] == [k for k, _, _ in paths]
    for (key, _, blob), (okey, vlist, oblob) in zip(paths, out):
        ref = meta_scan.scan_blob(blob)
        assert vlist == ref, key
        if vlist is None:
            assert oblob == blob, key      # fallback needs the bytes
        elif not meta_scan.summary_sufficient(vlist):
            assert oblob == blob, key      # full fidelity rides along
        else:
            assert oblob is None, key


# ---------------------------------------------------------------------------
# listing identity: scanner on vs off, shallow vs deep
# ---------------------------------------------------------------------------


@pytest.fixture
def es4(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    s = ErasureSet(disks)
    s.make_bucket("b")
    yield s
    s.close()


def _seed_namespace(es):
    es.put_object("b", "plain", b"p" * 100)
    es.put_object("b", "tagged", b"t" * 100,
                  PutOptions(tags="team=x&env=y"))
    es.put_object("b", "withmeta", b"m" * 100,
                  PutOptions(user_metadata={"x-amz-meta-k": "v"}))
    es.put_object("b", "a/nested/one", b"1")
    es.put_object("b", "a/nested/two", b"2")
    es.put_object("b", "a/other", b"3")
    es.put_object("b", "zz/deep/deeper/leaf", b"4")
    # An object that is also a prefix (nested keys under an object).
    es.put_object("b", "obj", b"o" * 100)
    es.put_object("b", "obj/child", b"c")
    # Versioned stack + delete marker.
    es.put_object("b", "ver/k", b"v1", PutOptions(versioned=True))
    es.put_object("b", "ver/k", b"v2", PutOptions(versioned=True))
    es.put_object("b", "ver/dead", b"x", PutOptions(versioned=True))
    es.delete_object("b", "ver/dead",
                     DeleteOptions(versioned=True))


def _snap_listing(es, **kw):
    info = es.list_objects("b", **kw)
    objs = [(o.name, o.version_id, o.is_latest, o.delete_marker,
             o.etag, o.size, o.mod_time, o.content_type, o.user_tags,
             dict(o.user_metadata), dict(o.internal_metadata))
            for o in info.objects]
    return objs, list(info.prefixes), info.is_truncated, info.next_marker


def _walk_all_pages(es, **kw):
    pages = []
    marker = ""
    for _ in range(100):
        objs, prefixes, trunc, nm = _snap_listing(es, marker=marker, **kw)
        pages.append((objs, prefixes))
        if not trunc:
            return pages
        marker = nm
    raise AssertionError("listing did not terminate")


LISTING_SHAPES = [
    {},
    {"prefix": "a/"},
    {"prefix": "a/nested/"},
    {"delimiter": "/"},
    {"prefix": "a/", "delimiter": "/"},
    {"prefix": "zz/", "delimiter": "/"},
    {"prefix": "obj", "delimiter": "/"},
    {"include_versions": True},
    {"prefix": "ver/", "include_versions": True},
    {"delimiter": "/", "max_keys": 2},
    {"max_keys": 3},
]


def test_listing_identity_scanner_on_off(es4, monkeypatch):
    """Every listing shape returns identical fields with the native
    scanner enabled and disabled (Python fallback)."""
    _seed_namespace(es4)
    snaps = {}
    for native_off in (False, True):
        monkeypatch.setattr(meta_scan, "_NATIVE_OFF", native_off)
        for i, shape in enumerate(LISTING_SHAPES):
            es4.metacache.bump("b")      # force a fresh walk each way
            snaps.setdefault(i, []).append(_walk_all_pages(es4, **shape))
    for i, (on, off) in snaps.items():
        assert on == off, (LISTING_SHAPES[i], on, off)


def test_listing_identity_shallow_vs_deep(es4, monkeypatch):
    """Delimiter pages via the shallow one-level walk match the deep
    recursive walk exactly, page by page."""
    _seed_namespace(es4)
    shapes = [s for s in LISTING_SHAPES if s.get("delimiter")]
    got = {}
    for mode in ("on", "off"):
        monkeypatch.setenv("MTPU_LIST_SHALLOW", mode)
        for i, shape in enumerate(shapes):
            es4.metacache.bump("b")
            got.setdefault(i, []).append(_walk_all_pages(es4, **shape))
    for i, (shallow, deep) in got.items():
        assert shallow == deep, (shapes[i], shallow, deep)


def test_shallow_marker_inside_collapsed_prefix(es4):
    """A marker strictly inside a collapsed subtree re-surfaces that
    subtree's common prefix (S3 semantics) on the shallow path."""
    _seed_namespace(es4)
    objs, prefixes, _, _ = _snap_listing(
        es4, delimiter="/", marker="a/nested/one")
    assert "a/" in prefixes


def test_walk_scan_matches_walk_dir(es4):
    _seed_namespace(es4)
    d = es4.disks[0]
    old = [p for p, _ in d.walk_dir("b")]
    new = [p for p, _, _ in d.walk_scan("b")]
    assert old == new
    mid = old[len(old) // 2]
    assert [p for p, _ in d.walk_dir("b", forward_from=mid)] == \
        [p for p, _, _ in d.walk_scan("b", forward_from=mid)]


# ---------------------------------------------------------------------------
# fileinfo cache: stat class under HEAD storms
# ---------------------------------------------------------------------------


def test_head_storm_does_not_evict_data_class(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    try:
        es.make_bucket("b")
        es.fi_cache.max_entries = 4      # tiny data class
        es.fi_cache.max_stat = 4096
        for i in range(40):
            es.put_object("b", f"k{i:03d}", b"x" * 64)
        # Hot GET entries for 3 keys (data class).
        for k in ("k000", "k001", "k002"):
            es.get_object("b", k)
            es.get_object("b", k)
        base_entries = es.fi_cache.stats()["entries"]
        assert base_entries >= 3
        # HEAD storm over every key: fills the stat class only.
        for i in range(40):
            es.get_object_info("b", f"k{i:03d}")
        st = es.fi_cache.stats()
        assert st["entries"] == base_entries, \
            "HEAD storm must not evict data-class entries"
        assert st["stat_entries"] >= 30
        # Second pass: storm is served from cache (no fan-out).
        misses_before = es.fi_cache.stats()["stat_misses"]
        for i in range(40):
            es.get_object_info("b", f"k{i:03d}")
        st = es.fi_cache.stats()
        assert st["stat_misses"] == misses_before
        assert st["stat_hits"] >= 40
    finally:
        es.close()


def test_stat_class_invalidated_by_writes(tmp_path):
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    try:
        es.make_bucket("b")
        es.put_object("b", "k", b"v1")
        info1 = es.get_object_info("b", "k")
        assert es.fi_cache.stats()["stat_entries"] >= 1
        es.put_object("b", "k", b"v2" * 10)
        info2 = es.get_object_info("b", "k")
        assert info2.size == 20
        assert info2.etag != info1.etag
    finally:
        es.close()
