"""Fused single-pass data plane: fused-vs-legacy byte identity across
the transform matrix (SSE-C, SSE-S3, compressed, compressed+encrypted,
ranged GETs across block/package boundaries, multipart, inline, ragged
tails), failure paths (wrong SSE-C key 403, tampered ciphertext),
native kernel goldens (NIST GCM vectors, hashlib digest identity, zlib
deflate byte identity), the MTPU_TRANSFORM_FUSED=off kill-switch, and
the path-split counters ("zero legacy requests with fusion on")."""

import base64
import contextlib
import ctypes
import hashlib
import os
import struct
import zlib

import pytest

from minio_tpu import native
from minio_tpu.crypto import compress as comp
from minio_tpu.crypto import dare
from minio_tpu.crypto.kms import aesgcm_impl
from minio_tpu.object import transform as tf
from minio_tpu.object.erasure_object import (BLOCK_SIZE, ErasureSet,
                                             STREAM_THRESHOLD)
from minio_tpu.object.types import PutOptions
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from minio_tpu.utils.streams import Payload
from tests.s3client import S3Client

LIB = native.load()
MASTER = os.urandom(32)

pytestmark = pytest.mark.skipif(
    LIB is None, reason="native kernel library unavailable")


def _u8(b):
    return (ctypes.c_uint8 * len(b)).from_buffer_copy(b)


@contextlib.contextmanager
def fused(on: bool):
    """Flip the fused-plane kill-switch for one block."""
    old = os.environ.get("MTPU_TRANSFORM_FUSED")
    os.environ["MTPU_TRANSFORM_FUSED"] = "on" if on else "off"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("MTPU_TRANSFORM_FUSED", None)
        else:
            os.environ["MTPU_TRANSFORM_FUSED"] = old


# ---------------------------------------------------------------------------
# native kernel goldens
# ---------------------------------------------------------------------------

def test_gcm_nist_vectors():
    """AES-256-GCM against the NIST SP 800-38D reference vectors."""
    out = (ctypes.c_uint8 * 16)()
    LIB.mtpu_gcm_seal(_u8(b"\0" * 32), _u8(b"\0" * 12), _u8(b""), 0,
                      _u8(b""), 0, out)
    assert bytes(out).hex() == "530f8afbc74536b9a963b4f1c4cb738b"
    out = (ctypes.c_uint8 * 32)()
    LIB.mtpu_gcm_seal(_u8(b"\0" * 32), _u8(b"\0" * 12), _u8(b""), 0,
                      _u8(b"\0" * 16), 16, out)
    assert bytes(out).hex() == ("cea7403d4d606b6e074ec5d3baf39d18"
                                "d0d1c8a799996bf0265b98b5d48ab919")
    key = bytes.fromhex("feffe9928665731c6d6a8f9467308308"
                        "feffe9928665731c6d6a8f9467308308")
    iv = bytes.fromhex("cafebabefacedbaddecaf888")
    pt = bytes.fromhex(
        "d9313225f88406e5a55909c5aff5269a86a7a9531534f7da2e4c303d8a318a72"
        "1c3c0c95956809532fcf0e2449a6b525b16aedf5aa0de657ba637b39")
    aad = bytes.fromhex("feedfacedeadbeeffeedfacedeadbeefabaddad2")
    out = (ctypes.c_uint8 * (len(pt) + 16))()
    LIB.mtpu_gcm_seal(_u8(key), _u8(iv), _u8(aad), len(aad), _u8(pt),
                      len(pt), out)
    assert bytes(out).hex() == (
        "522dc1f099567d07f47f37a32a84427d643a8cdcbfe5c0c97598a2bd2555d1aa"
        "8cb08e48590dbb3da7b08b1056828838c5f61e6393ba7a0abcc9f662"
        "76fc6ece0f4e1768cddf8853bb2d551b")
    dec = (ctypes.c_uint8 * len(pt))()
    assert LIB.mtpu_gcm_open(_u8(key), _u8(iv), _u8(aad), len(aad), out,
                             len(pt) + 16, dec) == len(pt)
    assert bytes(dec) == pt
    bad = bytearray(bytes(out))
    bad[3] ^= 1
    assert LIB.mtpu_gcm_open(_u8(key), _u8(iv), _u8(aad), len(aad),
                             _u8(bytes(bad)), len(pt) + 16, dec) == -1


def test_native_aesgcm_class_available():
    impl = aesgcm_impl()
    assert impl is not None
    key, nonce = os.urandom(32), os.urandom(12)
    a = impl(key)
    ct = a.encrypt(nonce, b"payload", b"aad")
    assert a.decrypt(nonce, ct, b"aad") == b"payload"
    with pytest.raises(Exception):
        a.decrypt(nonce, ct, b"other-aad")


@pytest.mark.parametrize("algo,name,dlen",
                         [(0, "md5", 16), (1, "sha256", 32),
                          (2, "sha1", 20)])
def test_native_digests_match_hashlib(algo, name, dlen):
    for size in (0, 1, 55, 64, 65, 1000, BLOCK_SIZE + 17):
        data = os.urandom(size)
        ctx = (ctypes.c_uint8 * 128)()
        LIB.mtpu_digest_init(algo, ctx)
        half = size // 3
        LIB.mtpu_digest_update(algo, ctx, _u8(data[:half]), half)
        LIB.mtpu_digest_update(algo, ctx, _u8(data[half:]), size - half)
        out = (ctypes.c_uint8 * dlen)()
        LIB.mtpu_digest_final(algo, ctx, out)
        assert bytes(out) == getattr(hashlib, name)(data).digest(), size


def test_native_crc32_matches_zlib():
    d1, d2 = os.urandom(1000), os.urandom(313)
    c = LIB.mtpu_crc32(0, _u8(d1), len(d1))
    assert c == zlib.crc32(d1)
    assert LIB.mtpu_crc32(c, _u8(d2), len(d2)) == zlib.crc32(d2, c)


def test_native_deflate_byte_identical_to_python_zlib():
    data = (b"log line %06d\n" * 120_000) % tuple(range(120_000))
    data = data[: 2 * comp.BLOCK + 54321]
    result = comp.deflate_blocks(data)
    assert result is not None
    stored, ends = result
    ref_blocks = [zlib.compress(data[o:o + comp.BLOCK], 6)
                  for o in range(0, len(data), comp.BLOCK)]
    assert stored == b"".join(ref_blocks)
    total, ref_ends = 0, []
    for b in ref_blocks:
        total += len(b)
        ref_ends.append(total)
    assert ends == ref_ends


def test_dare_native_matches_python_layout():
    """Native bulk seal == the per-package AEAD loop (same nonce/AAD
    schedule), and tampered packages fail with the package index."""
    key, nonce = os.urandom(32), os.urandom(12)
    plain = os.urandom(3 * dare.PACKAGE_SIZE + 777)
    sealed = dare.seal_bulk(key, nonce, 0, plain)
    assert sealed is not None
    impl = aesgcm_impl()
    ref = b"".join(
        impl(key).encrypt(dare._nonce(nonce, i),
                          plain[o:o + dare.PACKAGE_SIZE],
                          dare._aad(i))
        for i, o in enumerate(range(0, len(plain), dare.PACKAGE_SIZE)))
    assert sealed == ref
    assert dare.open_bulk(key, nonce, 0, sealed) == plain
    bad = bytearray(sealed)
    bad[2 * (dare.PACKAGE_SIZE + dare.TAG_SIZE) + 7] ^= 1
    with pytest.raises(dare.DareError, match="package 2"):
        dare.open_bulk(key, nonce, 0, bytes(bad))


# ---------------------------------------------------------------------------
# fused-vs-legacy matrix over the live S3 API
# ---------------------------------------------------------------------------

SSE_KEY = os.urandom(32)
SSE_HDRS = {
    "x-amz-server-side-encryption-customer-algorithm": "AES256",
    "x-amz-server-side-encryption-customer-key":
        base64.b64encode(SSE_KEY).decode(),
    "x-amz-server-side-encryption-customer-key-md5":
        base64.b64encode(hashlib.md5(SSE_KEY).digest()).decode(),
}

MODES = {
    "plain": ("bin-%s.dat", {}),
    "sse-c": ("bin-%s.dat", SSE_HDRS),
    "sse-s3": ("bin-%s.dat", {"x-amz-server-side-encryption": "AES256"}),
    # .log keys are compression-eligible on the fixture server.
    "comp": ("log-%s.log", {}),
    "comp+sse": ("log-%s.log", SSE_HDRS),
}


def _body(size: int) -> bytes:
    # Compressible but not trivially so (repeating numbered lines).
    line = b"".join(b"%09d fused transform plane\n" % i
                    for i in range(4000))
    out = (line * (size // len(line) + 1))[:size]
    return out


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    os.environ["MTPU_KMS_SECRET_KEY"] = \
        "tfkey:" + base64.b64encode(MASTER).decode()
    tmp = tmp_path_factory.mktemp("tfdrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.compression = True
    server.start()
    yield server, es
    server.stop()
    os.environ.pop("MTPU_KMS_SECRET_KEY", None)


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv[0].address)
    assert c.request("PUT", "/tfb")[0] == 200
    return c


@pytest.mark.parametrize("mode", sorted(MODES))
@pytest.mark.parametrize("size", [
    700,                        # inline
    200_000,                    # sub-block
    2 * BLOCK_SIZE + 4321,      # multi-block + ragged tail
])
def test_fused_vs_legacy_byte_identity(cli, mode, size):
    key_tpl, hdrs = MODES[mode]
    body = _body(size)
    etags = {}
    for path_on in (True, False):
        key = key_tpl % f"{mode}-{size}-{'f' if path_on else 'l'}"
        with fused(path_on):
            st, hh, _ = cli.request("PUT", f"/tfb/{key}", body=body,
                                    headers=dict(hdrs))
            assert st == 200, (mode, size, path_on)
            etags[path_on] = hh["ETag"]
        # Read the object back under BOTH planes: a fused write must
        # be byte-identical through the legacy read path and vice
        # versa, whole and ranged (block/package boundary crossers).
        for read_on in (True, False):
            with fused(read_on):
                st, hh, got = cli.request("GET", f"/tfb/{key}",
                                          headers=dict(hdrs))
                assert st == 200 and got == body, (mode, size, path_on,
                                                   read_on)
                assert hh["Content-Length"] == str(len(body))
                for lo, hi in ((0, 0), (1, 100),
                               (64 * 1024 - 3, 64 * 1024 + 7),
                               (BLOCK_SIZE - 5, BLOCK_SIZE + 999),
                               (len(body) - 17, len(body) - 1)):
                    hi = min(hi, len(body) - 1)
                    if lo > hi:
                        continue
                    st, _, got = cli.request(
                        "GET", f"/tfb/{key}",
                        headers={**hdrs, "Range": f"bytes={lo}-{hi}"})
                    assert st == 206 and got == body[lo:hi + 1], \
                        (mode, size, path_on, read_on, lo, hi)
    # The etag is path-invariant (md5 of the same source bytes) for
    # every unencrypted mode; SSE etags hash a freshly-keyed
    # ciphertext, so only shape can match there.
    if "sse" not in mode or mode == "comp+sse":
        assert etags[True] == etags[False], mode
    else:
        assert len(etags[True]) == len(etags[False])


def test_comp_sse_combined_stores_both_transforms(srv, cli):
    """A compressed+encrypted object carries BOTH metadata sets and its
    stored stream is DARE over the compressed blocks."""
    body = _body(3 * BLOCK_SIZE + 99)
    with fused(True):
        assert cli.request("PUT", "/tfb/combined.log", body=body,
                           headers=dict(SSE_HDRS))[0] == 200
    _, es = srv
    info = es.get_object_info("tfb", "combined.log")
    imeta = info.internal_metadata
    assert imeta.get(comp.META_SCHEME) == comp.SCHEME
    assert imeta.get("x-internal-sse-alg") == "SSE-C"
    assert info.size == len(body)
    comp_total = int(imeta[  # sse size = DARE plaintext = compressed
        "x-internal-sse-size"])
    assert comp_total == struct.unpack(
        ">I", base64.b64decode(imeta[comp.META_INDEX])[-4:])[0]
    assert comp_total < len(body)


def test_copy_source_combined_object(cli):
    """CopyObject whose SOURCE is compressed+encrypted must decrypt
    BEFORE inflating (the copy-source read path's dispatch order)."""
    body = _body(400_000)
    copy_hdrs = {
        "x-amz-copy-source": "/tfb/cpsrc.log",
        "x-amz-copy-source-server-side-encryption-customer-algorithm":
            "AES256",
        "x-amz-copy-source-server-side-encryption-customer-key":
            SSE_HDRS["x-amz-server-side-encryption-customer-key"],
        "x-amz-copy-source-server-side-encryption-customer-key-md5":
            SSE_HDRS["x-amz-server-side-encryption-customer-key-md5"],
    }
    with fused(True):
        assert cli.request("PUT", "/tfb/cpsrc.log", body=body,
                           headers=dict(SSE_HDRS))[0] == 200
        st, _, resp = cli.request("PUT", "/tfb/cpdst.bin",
                                  headers=copy_hdrs)
        assert st == 200, resp
        st, _, got = cli.request("GET", "/tfb/cpdst.bin")
        assert st == 200 and got == body


def test_wrong_sse_c_key_403_and_tamper_fails(cli, srv):
    body = _body(150_000)
    with fused(True):
        assert cli.request("PUT", "/tfb/locked", body=body,
                           headers=dict(SSE_HDRS))[0] == 200
        wrong = os.urandom(32)
        whdr = {
            "x-amz-server-side-encryption-customer-algorithm": "AES256",
            "x-amz-server-side-encryption-customer-key":
                base64.b64encode(wrong).decode(),
            "x-amz-server-side-encryption-customer-key-md5":
                base64.b64encode(hashlib.md5(wrong).digest()).decode(),
        }
        assert cli.request("GET", "/tfb/locked")[0] == 400
        assert cli.request("GET", "/tfb/locked",
                           headers=whdr)[0] == 403
        assert cli.request("HEAD", "/tfb/locked",
                           headers=whdr)[0] == 403
    # Tampered ciphertext: flip one stored package byte -> DareError.
    key, nonce = os.urandom(32), os.urandom(12)
    sealed = dare.seal_bulk(key, nonce, 0, body)
    bad = bytearray(sealed)
    bad[100] ^= 1
    with pytest.raises(dare.DareError):
        b"".join(dare.decrypt_packages(iter([bytes(bad)]), key, nonce,
                                       0, 0, len(body)))


def test_declared_checksum_verify_and_mismatch(cli):
    body = _body(90_000)
    want = base64.b64encode(hashlib.sha256(body).digest()).decode()
    for on in (True, False):
        with fused(on):
            st, hh, _ = cli.request(
                "PUT", f"/tfb/ck-{on}", body=body,
                headers={"x-amz-checksum-sha256": want})
            assert st == 200, on
            assert hh.get("x-amz-checksum-sha256") == want
            bad = base64.b64encode(b"\0" * 32).decode()
            st, _, resp = cli.request(
                "PUT", f"/tfb/ck-bad-{on}", body=body,
                headers={"x-amz-checksum-sha256": bad})
            assert st == 400 and b"Checksum" in resp, on
            assert cli.request("GET", f"/tfb/ck-bad-{on}")[0] == 404


def test_multipart_sse_roundtrip_both_planes(cli):
    part = _body(5 * 1024 * 1024)
    body = part + part[: 1024 * 1024]
    for on in (True, False):
        with fused(on):
            key = f"mp-{'f' if on else 'l'}"
            st, _, resp = cli.request("POST", f"/tfb/{key}",
                                      query={"uploads": ""},
                                      headers=dict(SSE_HDRS))
            assert st == 200
            uid = resp.split(b"<UploadId>")[1].split(b"</UploadId>")[0] \
                .decode()
            etags = []
            for i, data in enumerate((part, body[len(part):])):
                st, hh, _ = cli.request(
                    "PUT", f"/tfb/{key}",
                    query={"partNumber": str(i + 1), "uploadId": uid},
                    body=data, headers=dict(SSE_HDRS))
                assert st == 200
                etags.append(hh["ETag"])
            xml = "<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{i + 1}</PartNumber>"
                f"<ETag>{e}</ETag></Part>"
                for i, e in enumerate(etags)) + \
                "</CompleteMultipartUpload>"
            st, _, _ = cli.request("POST", f"/tfb/{key}",
                                   query={"uploadId": uid},
                                   body=xml.encode())
            assert st == 200
        for read_on in (True, False):
            with fused(read_on):
                st, _, got = cli.request("GET", f"/tfb/{key}",
                                         headers=dict(SSE_HDRS))
                assert st == 200 and got == body, (on, read_on)
                lo, hi = len(part) - 9, len(part) + 77
                st, _, got = cli.request(
                    "GET", f"/tfb/{key}",
                    headers={**SSE_HDRS, "Range": f"bytes={lo}-{hi}"})
                assert st == 206 and got == body[lo:hi + 1], (on, read_on)


def test_path_split_counters_zero_legacy_with_fusion_on(cli):
    tf.reset_stats()
    with fused(True):
        for i in range(4):
            assert cli.request("PUT", f"/tfb/ctr-{i}.log",
                               body=_body(100_000))[0] == 200
            assert cli.request("GET", f"/tfb/ctr-{i}.log")[0] == 200
    st = tf.stats()
    assert st["put_requests"]["fused"] >= 4
    assert st["put_requests"]["legacy"] == 0
    tf.reset_stats()
    with fused(False):
        assert cli.request("PUT", "/tfb/ctr-off.log",
                           body=_body(100_000))[0] == 200
    st = tf.stats()
    assert st["put_requests"]["legacy"] >= 1
    assert st["put_requests"]["fused"] == 0


def test_conformance_subset_with_kill_switch(cli):
    """The layered pipeline still serves the whole matrix with the
    fused plane off wholesale — the operational escape hatch."""
    with fused(False):
        for mode, (key_tpl, hdrs) in sorted(MODES.items()):
            key = key_tpl % f"ks-{mode}"
            body = _body(300_000)
            assert cli.request("PUT", f"/tfb/{key}", body=body,
                               headers=dict(hdrs))[0] == 200
            st, _, got = cli.request("GET", f"/tfb/{key}",
                                     headers=dict(hdrs))
            assert st == 200 and got == body, mode


# ---------------------------------------------------------------------------
# object-layer specifics
# ---------------------------------------------------------------------------

def test_streaming_put_native_md5_etag(tmp_path):
    """Streaming PUTs (> STREAM_THRESHOLD) fold the per-window etag
    md5 into the fused native frame call — etag must equal md5(body)."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("sb")
    body = os.urandom(STREAM_THRESHOLD + 3 * 1024 * 1024 + 12345)
    info = es.put_object("sb", "big", Payload.wrap(body), PutOptions())
    assert info.etag == hashlib.md5(body).hexdigest()
    _, got = es.get_object("sb", "big")
    assert got == body


def test_fused_spec_results_inline_and_tail(tmp_path):
    """Direct object-layer fused PUT: digests, stored size, comp index
    land on the spec; inline and ragged-tail shapes round-trip."""
    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("ob")
    for size in (100, 4000, BLOCK_SIZE + 7):
        body = _body(size)
        spec = tf.TransformSpec(compress=True)
        info = es.put_object("ob", f"o{size}", body,
                             PutOptions(transform=spec))
        assert info.etag == hashlib.md5(body).hexdigest()
        assert spec.plain_size == size
        if spec.comp_used:
            assert spec.stored_size == spec.comp_ends[-1]
        _, stored = es.get_object("ob", f"o{size}")
        if spec.comp_used:
            gi = es.get_object_info("ob", f"o{size}")
            assert comp.decompress_range(
                stored, gi.internal_metadata, 0, size) == body
        else:
            assert stored == body


def test_checksum_verify_failure_commits_nothing(tmp_path):
    """The spec's pre-commit verify hook aborts BEFORE any disk write
    (the layered path's finish-hook timing, preserved)."""
    from minio_tpu.object.types import ObjectNotFound

    disks = [LocalStorage(str(tmp_path / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    es.make_bucket("vb")

    def verify(sp):
        raise ValueError("checksum mismatch")

    spec = tf.TransformSpec(verify=verify)
    with pytest.raises(ValueError):
        es.put_object("vb", "nope", b"x" * 1000,
                      PutOptions(transform=spec))
    with pytest.raises(ObjectNotFound):
        es.get_object_info("vb", "nope")
