"""Server-side encryption: KMS sealing, DARE packages, SSE-S3/SSE-C over
the S3 API including ranged decrypting GETs (reference:
cmd/encryption-v1.go, internal/crypto/, internal/kms/)."""

import base64
import hashlib
import os

import pytest

from minio_tpu.crypto.kms import aesgcm_impl

if aesgcm_impl() is None:
    pytest.skip("SSE/KMS needs an AES-GCM backend (the optional "
                "'cryptography' wheel or the native kernel library)",
                allow_module_level=True)

from minio_tpu.crypto import (EncryptingPayload, KMS, KMSError,
                              encrypt_stream_size, decrypt_packages,
                              package_range, plaintext_size, PACKAGE_SIZE)
from minio_tpu.crypto.dare import DareError
from minio_tpu.object.erasure_object import ErasureSet
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.local import LocalStorage
from minio_tpu.utils.streams import Payload
from tests.s3client import S3Client

MASTER = os.urandom(32)


# ---------------------------------------------------------------------------
# KMS
# ---------------------------------------------------------------------------

def test_kms_seal_unseal_roundtrip():
    kms = KMS({"k1": MASTER}, "k1")
    ctx = {"bucket": "b", "object": "o"}
    key, sealed = kms.generate_key(ctx)
    assert kms.unseal(sealed, ctx) == key
    with pytest.raises(KMSError):
        kms.unseal(sealed, {"bucket": "b", "object": "OTHER"})
    other = KMS({"k1": os.urandom(32)}, "k1")
    with pytest.raises(KMSError):
        other.unseal(sealed, ctx)


def test_kms_from_env(monkeypatch):
    monkeypatch.setenv("MTPU_KMS_SECRET_KEY",
                       "mykey:" + base64.b64encode(MASTER).decode())
    kms = KMS.from_env()
    assert kms.default_key == "mykey"
    monkeypatch.delenv("MTPU_KMS_SECRET_KEY")
    assert KMS.from_env() is None


# ---------------------------------------------------------------------------
# DARE core
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("size", [0, 1, 100, PACKAGE_SIZE,
                                  PACKAGE_SIZE + 1, 3 * PACKAGE_SIZE + 777])
def test_dare_roundtrip_sizes(size):
    key, nonce = os.urandom(32), os.urandom(12)
    plain = os.urandom(size)
    enc = EncryptingPayload(Payload.wrap(plain), key, nonce)
    assert enc.size == encrypt_stream_size(size)
    ct = bytearray()
    while True:
        c = enc.read(50_000)
        if not c:
            break
        ct += c
    assert len(ct) == enc.size
    assert plaintext_size(len(ct)) == size
    if size:
        out = b"".join(decrypt_packages(iter([bytes(ct)]), key, nonce,
                                        0, 0, size))
        assert out == plain


def _read_all(reader):
    out = bytearray()
    while True:
        c = reader.read(1 << 20)
        if not c:
            return bytes(out)
        out += c


def test_dare_range_decrypt():
    key, nonce = os.urandom(32), os.urandom(12)
    plain = os.urandom(5 * PACKAGE_SIZE + 123)
    enc = EncryptingPayload(Payload.wrap(plain), key, nonce)
    ct = _read_all(enc)
    assert len(ct) == enc.size
    lo, ln = PACKAGE_SIZE + 17, 2 * PACKAGE_SIZE + 5
    first, c_off, c_len = package_range(lo, ln)
    c_len = min(c_len, len(ct) - c_off)
    out = b"".join(decrypt_packages(
        iter([ct[c_off:c_off + c_len]]), key, nonce, first,
        lo - first * PACKAGE_SIZE, ln))
    assert out == plain[lo:lo + ln]


def test_dare_detects_tamper_and_reorder():
    key, nonce = os.urandom(32), os.urandom(12)
    plain = os.urandom(2 * PACKAGE_SIZE)
    ct = bytearray(_read_all(EncryptingPayload(Payload.wrap(plain), key,
                                               nonce)))
    assert len(ct) == 2 * (PACKAGE_SIZE + 16)
    ct[100] ^= 1
    with pytest.raises(DareError):
        b"".join(decrypt_packages(iter([bytes(ct)]), key, nonce, 0, 0,
                                  len(plain)))
    # Swap the two packages: sequence-bound nonces reject it.
    pkg = PACKAGE_SIZE + 16
    good = _read_all(EncryptingPayload(Payload.wrap(plain), key, nonce))
    swapped = good[pkg:] + good[:pkg]
    with pytest.raises(DareError):
        b"".join(decrypt_packages(iter([swapped]), key, nonce, 0, 0,
                                  len(plain)))


# ---------------------------------------------------------------------------
# end-to-end over the S3 API
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    os.environ["MTPU_KMS_SECRET_KEY"] = \
        "testkey:" + base64.b64encode(MASTER).decode()
    tmp = tmp_path_factory.mktemp("ssedrv")
    disks = [LocalStorage(str(tmp / f"d{i}")) for i in range(4)]
    es = ErasureSet(disks)
    server = S3Server(es, address="127.0.0.1:0")
    server.start()
    yield server
    server.stop()
    os.environ.pop("MTPU_KMS_SECRET_KEY", None)


@pytest.fixture(scope="module")
def cli(srv):
    c = S3Client(srv.address)
    assert c.request("PUT", "/sseb")[0] == 200
    return c


def test_sse_s3_roundtrip(cli, srv):
    body = os.urandom(200_000)
    st, hh, _ = cli.request("PUT", "/sseb/enc1", body=body, headers={
        "x-amz-server-side-encryption": "AES256"})
    assert st == 200
    assert hh.get("x-amz-server-side-encryption") == "AES256"
    st, hh, got = cli.request("GET", "/sseb/enc1")
    assert st == 200 and got == body
    assert hh.get("x-amz-server-side-encryption") == "AES256"
    assert hh.get("Content-Length") == str(len(body))
    # Ciphertext (not plaintext) is what sits on the drives.
    st, _, head = cli.request("HEAD", "/sseb/enc1")
    assert st == 200


def test_sse_s3_ranged_get(cli):
    body = os.urandom(3 * PACKAGE_SIZE + 999)
    assert cli.request("PUT", "/sseb/encr", body=body, headers={
        "x-amz-server-side-encryption": "AES256"})[0] == 200
    lo, hi = PACKAGE_SIZE - 5, 2 * PACKAGE_SIZE + 10
    st, hh, got = cli.request("GET", "/sseb/encr",
                              headers={"Range": f"bytes={lo}-{hi}"})
    assert st == 206
    assert got == body[lo:hi + 1]
    assert hh["Content-Range"] == f"bytes {lo}-{hi}/{len(body)}"


def test_sse_c_requires_matching_key(cli):
    key = os.urandom(32)
    key_b64 = base64.b64encode(key).decode()
    md5_b64 = base64.b64encode(hashlib.md5(key).digest()).decode()
    body = os.urandom(50_000)
    hdr = {"x-amz-server-side-encryption-customer-algorithm": "AES256",
           "x-amz-server-side-encryption-customer-key": key_b64,
           "x-amz-server-side-encryption-customer-key-md5": md5_b64}
    assert cli.request("PUT", "/sseb/cobj", body=body,
                       headers=hdr)[0] == 200
    # Without the key: rejected.
    st, _, _ = cli.request("GET", "/sseb/cobj")
    assert st == 400
    # Wrong key: denied.
    wrong = os.urandom(32)
    whdr = {"x-amz-server-side-encryption-customer-algorithm": "AES256",
            "x-amz-server-side-encryption-customer-key":
            base64.b64encode(wrong).decode(),
            "x-amz-server-side-encryption-customer-key-md5":
            base64.b64encode(hashlib.md5(wrong).digest()).decode()}
    st, _, _ = cli.request("GET", "/sseb/cobj", headers=whdr)
    assert st == 403
    # Right key: plaintext.
    st, _, got = cli.request("GET", "/sseb/cobj", headers=hdr)
    assert st == 200 and got == body
    # HEAD enforces the key too.
    assert cli.request("HEAD", "/sseb/cobj")[0] == 400
    assert cli.request("HEAD", "/sseb/cobj", headers=hdr)[0] == 200


def test_bucket_default_encryption_applies(cli):
    enc_cfg = (b'<ServerSideEncryptionConfiguration><Rule>'
               b'<ApplyServerSideEncryptionByDefault>'
               b'<SSEAlgorithm>AES256</SSEAlgorithm>'
               b'</ApplyServerSideEncryptionByDefault></Rule>'
               b'</ServerSideEncryptionConfiguration>')
    assert cli.request("PUT", "/sseb", query={"encryption": ""},
                       body=enc_cfg)[0] == 200
    body = os.urandom(10_000)
    st, hh, _ = cli.request("PUT", "/sseb/auto", body=body)
    assert st == 200
    assert hh.get("x-amz-server-side-encryption") == "AES256"
    st, _, got = cli.request("GET", "/sseb/auto")
    assert st == 200 and got == body
    assert cli.request("DELETE", "/sseb", query={"encryption": ""})[0] == 204


def test_copy_encrypted_to_plaintext_and_back(cli):
    body = os.urandom(80_000)
    assert cli.request("PUT", "/sseb/src-enc", body=body, headers={
        "x-amz-server-side-encryption": "AES256"})[0] == 200
    # encrypted -> plaintext copy
    st, _, b = cli.request("PUT", "/sseb/dst-plain", headers={
        "x-amz-copy-source": "/sseb/src-enc"})
    assert st == 200, b
    st, hh, got = cli.request("GET", "/sseb/dst-plain")
    assert got == body and "x-amz-server-side-encryption" not in hh
    # plaintext -> encrypted copy
    st, _, b = cli.request("PUT", "/sseb/dst-enc", headers={
        "x-amz-copy-source": "/sseb/dst-plain",
        "x-amz-server-side-encryption": "AES256"})
    assert st == 200, b
    st, hh, got = cli.request("GET", "/sseb/dst-enc")
    assert got == body
    assert hh.get("x-amz-server-side-encryption") == "AES256"


def _mp_upload(cli, bucket, key, parts, init_headers=None,
               part_headers=None):
    """Initiate → upload parts → complete; returns (statuses, etags)."""
    st, _, body = cli.request("POST", f"/{bucket}/{key}",
                              query={"uploads": ""},
                              headers=init_headers or {})
    assert st == 200, body
    uid = body.split(b"<UploadId>")[1].split(b"</UploadId>")[0].decode()
    etags = []
    for i, data in enumerate(parts, start=1):
        st, hh, b2 = cli.request("PUT", f"/{bucket}/{key}",
                                 query={"partNumber": str(i),
                                        "uploadId": uid},
                                 body=data, headers=part_headers or {})
        assert st == 200, b2
        etags.append(hh.get("etag") or hh.get("ETag"))
    xml = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag></Part>"
        for i, e in enumerate(etags, start=1)) + \
        "</CompleteMultipartUpload>"
    st, _, b3 = cli.request("POST", f"/{bucket}/{key}",
                            query={"uploadId": uid}, body=xml.encode())
    assert st == 200, b3
    return uid, etags


def test_multipart_sse_s3_roundtrip_and_ranges(cli):
    """16 x 5 MiB-class encrypted multipart: full read, ranged reads
    across part boundaries, part-straddling and suffix ranges
    (reference: cmd/encryption-v1.go:643 part-boundary decryption)."""
    part_size = 5 << 20
    parts = [os.urandom(part_size) for _ in range(3)] + [os.urandom(1234)]
    whole = b"".join(parts)
    _mp_upload(cli, "sseb", "mpenc", parts,
               init_headers={"x-amz-server-side-encryption": "AES256"})
    st, hh, got = cli.request("GET", "/sseb/mpenc")
    assert st == 200 and got == whole
    assert hh.get("x-amz-server-side-encryption") == "AES256"
    # HEAD reports the plaintext size.
    st, hh, _ = cli.request("HEAD", "/sseb/mpenc")
    assert int(hh.get("content-length") or hh.get("Content-Length")) == \
        len(whole)
    # Range inside one part.
    st, _, got = cli.request("GET", "/sseb/mpenc",
                             headers={"Range": "bytes=1000-1999"})
    assert st == 206 and got == whole[1000:2000]
    # Range straddling the part-1/part-2 boundary.
    lo, hi = part_size - 500, part_size + 499
    st, _, got = cli.request("GET", "/sseb/mpenc",
                             headers={"Range": f"bytes={lo}-{hi}"})
    assert st == 206 and got == whole[lo:hi + 1]
    # Range spanning three parts.
    lo, hi = part_size - 10, 2 * part_size + 9
    st, _, got = cli.request("GET", "/sseb/mpenc",
                             headers={"Range": f"bytes={lo}-{hi}"})
    assert st == 206 and got == whole[lo:hi + 1]
    # Suffix range into the small final part.
    st, _, got = cli.request("GET", "/sseb/mpenc",
                             headers={"Range": "bytes=-2000"})
    assert st == 206 and got == whole[-2000:]


def test_multipart_sse_c_requires_key_on_parts_and_get(cli):
    key = os.urandom(32)
    key_b64 = base64.b64encode(key).decode()
    md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
    hdrs = {"x-amz-server-side-encryption-customer-algorithm": "AES256",
            "x-amz-server-side-encryption-customer-key": key_b64,
            "x-amz-server-side-encryption-customer-key-md5": md5}
    parts = [os.urandom(5 << 20), os.urandom(999)]
    _mp_upload(cli, "sseb", "mpssec", parts, init_headers=hdrs,
               part_headers=hdrs)
    # GET without the key: refused.
    st, _, _ = cli.request("GET", "/sseb/mpssec")
    assert st == 400
    # With the key: byte-identical.
    st, _, got = cli.request("GET", "/sseb/mpssec", headers=hdrs)
    assert st == 200 and got == b"".join(parts)
    # Wrong key on a part upload: refused.
    st, _, body = cli.request("POST", "/sseb/mpssec2",
                              query={"uploads": ""}, headers=hdrs)
    assert st == 200
    uid = body.split(b"<UploadId>")[1].split(b"</UploadId>")[0].decode()
    bad = dict(hdrs)
    bk = os.urandom(32)
    bad["x-amz-server-side-encryption-customer-key"] = \
        base64.b64encode(bk).decode()
    bad["x-amz-server-side-encryption-customer-key-md5"] = \
        base64.b64encode(hashlib.md5(bk).digest()).decode()
    st, _, _ = cli.request("PUT", "/sseb/mpssec2",
                           query={"partNumber": "1", "uploadId": uid},
                           body=b"x" * 100, headers=bad)
    assert st == 403


def test_multipart_sse_part_reupload_gets_fresh_nonce(cli):
    """Re-uploading a part must produce different ciphertext for the
    same plaintext (fresh DARE base nonce per attempt): AES-GCM
    (key, nonce) reuse across different plaintexts would be a
    confidentiality break, and the only observable of the fix is the
    ciphertext etag changing."""
    st, _, body = cli.request("POST", "/sseb/reup", query={"uploads": ""},
                              headers={"x-amz-server-side-encryption":
                                       "AES256"})
    assert st == 200
    uid = body.split(b"<UploadId>")[1].split(b"</UploadId>")[0].decode()
    data = os.urandom(100_000)
    st, h1, _ = cli.request("PUT", "/sseb/reup",
                            query={"partNumber": "1", "uploadId": uid},
                            body=data)
    assert st == 200
    st, h2, _ = cli.request("PUT", "/sseb/reup",
                            query={"partNumber": "1", "uploadId": uid},
                            body=data)
    assert st == 200
    e1 = h1.get("etag") or h1.get("ETag")
    e2 = h2.get("etag") or h2.get("ETag")
    assert e1 != e2, "same plaintext re-encrypted under the same nonce"
    # The LAST upload wins and decrypts correctly.
    xml = (f"<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
           f"<ETag>{e2}</ETag></Part></CompleteMultipartUpload>")
    st, _, b3 = cli.request("POST", "/sseb/reup", query={"uploadId": uid},
                            body=xml.encode())
    assert st == 200, b3
    st, _, got = cli.request("GET", "/sseb/reup")
    assert st == 200 and got == data


def test_multipart_sse_copy_to_plaintext(cli):
    """CopyObject out of an encrypted multipart source decrypts at part
    boundaries."""
    parts = [os.urandom(5 << 20), os.urandom(4321)]
    _mp_upload(cli, "sseb", "mpsrc", parts,
               init_headers={"x-amz-server-side-encryption": "AES256"})
    st, _, b = cli.request("PUT", "/sseb/mpcopy", headers={
        "x-amz-copy-source": "/sseb/mpsrc"})
    assert st == 200, b
    st, _, got = cli.request("GET", "/sseb/mpcopy")
    assert st == 200 and got == b"".join(parts)


def test_listing_reports_plaintext_size(cli):
    body = os.urandom(12_345)
    cli.request("PUT", "/sseb/sized", body=body, headers={
        "x-amz-server-side-encryption": "AES256"})
    st, _, xml = cli.request("GET", "/sseb", query={"prefix": "sized"})
    assert st == 200
    assert f"<Size>{len(body)}</Size>".encode() in xml
