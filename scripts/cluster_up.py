#!/usr/bin/env python3
"""Boot an N-node-in-one-container minio-tpu cluster and drive chaos
interactively.

    python scripts/cluster_up.py --nodes 4 --drives 2 /tmp/mtpu-cluster

Spawns N real server processes (real grid mesh, real dsync quorums)
over directory drives under the given root, prints the S3 endpoints,
then reads chaos commands from stdin until EOF/quit:

    kill N | restart N | partition N | drop N | rejoin N
    delay N SECONDS | hang N SECONDS | status | quit

The same primitives the chaos tests use (tests/cluster.py) — this is
the operator-facing wrapper for poking a live topology.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tests.cluster import Cluster  # noqa: E402


def _node_telemetry(cluster, i: int) -> str:
    """Per-node observability digest for `status`: slow-op count and
    replication lag pulled over the node's grid plane (`peer.metrics`,
    the same verb the federated scrape uses — no S3 auth needed)."""
    if not cluster.alive(i):
        return ""
    try:
        from minio_tpu.grid.client import client_for
        st = client_for("127.0.0.1",
                        cluster.ports[i] + 1000).call(
            "peer.metrics", {}, timeout=2.0)
    except Exception:  # noqa: BLE001 - grid plane not up yet
        return ""
    if not isinstance(st, dict):
        return ""
    out = f" slow_ops={st.get('slow_ops', 0)}"
    lag = (st.get("replication") or {}).get("lag_ms") or {}
    if lag.get("count"):
        out += (f" repl_lag_p50={lag.get('p50_ms', 0)}ms"
                f" p99={lag.get('p99_ms', 0)}ms")
    return out


def main() -> int:
    ap = argparse.ArgumentParser(prog="cluster_up")
    ap.add_argument("root", help="directory for drives/logs/chaos files")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--drives", type=int, default=2,
                    help="drives per node")
    ap.add_argument("--parity", type=int, default=None)
    ap.add_argument("--scanner-interval", type=float, default=60.0)
    args = ap.parse_args()

    os.makedirs(args.root, exist_ok=True)
    cluster = Cluster(args.root, nodes=args.nodes,
                      drives_per_node=args.drives, parity=args.parity,
                      scanner_interval=args.scanner_interval)
    print(f"booting {args.nodes} nodes x {args.drives} drives "
          f"under {args.root} ...", flush=True)
    try:
        cluster.start()
        for i in range(cluster.n):
            print(f"  node {i}: http://{cluster.address(i)}  "
                  f"(grid :{cluster.ports[i] + 1000}, "
                  f"log {cluster.log_path(i)})")
        print("cluster up. commands: kill/restart/partition/drop/rejoin N,"
              " delay N S, hang N S, status, quit", flush=True)
        for line in sys.stdin:
            parts = line.split()
            if not parts:
                continue
            cmd, rest = parts[0], parts[1:]
            try:
                if cmd in ("quit", "exit", "q"):
                    break
                elif cmd == "status":
                    for i in range(cluster.n):
                        chaos = "none"
                        if os.path.exists(cluster.chaos_path(i)):
                            with open(cluster.chaos_path(i)) as fh:
                                chaos = fh.read().strip() or "none"
                        print(f"  node {i}: "
                              f"{'up' if cluster.alive(i) else 'DOWN'} "
                              f"chaos={chaos}{_node_telemetry(cluster, i)}")
                elif cmd == "kill":
                    cluster.kill(int(rest[0]))
                elif cmd == "restart":
                    cluster.restart(int(rest[0]))
                elif cmd == "partition":
                    cluster.partition(int(rest[0]))
                elif cmd == "drop":
                    cluster.drop(int(rest[0]))
                elif cmd == "rejoin":
                    cluster.rejoin(int(rest[0]))
                elif cmd == "delay":
                    cluster.delay(int(rest[0]), float(rest[1]))
                elif cmd == "hang":
                    cluster.hang_drives(int(rest[0]), float(rest[1]))
                else:
                    print(f"unknown command: {cmd}")
                    continue
                print("ok", flush=True)
            except (IndexError, ValueError) as e:
                print(f"bad args: {e}", flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        print("stopping cluster", flush=True)
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
