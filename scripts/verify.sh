#!/usr/bin/env bash
# Tier-1 verification gate (ROADMAP.md): a fast whole-tree compile
# check, then the non-slow test suite under the same flags and timeout
# the driver uses. Chaos STRESS tests are marked `slow` and excluded
# here so tier-1 wall time stays inside the 870 s budget.
set -o pipefail
cd "$(dirname "$0")/.."

echo "== compileall gate =="
python -m compileall -q minio_tpu || exit 1

# Metric-name hygiene: every exported name minio_tpu_-prefixed
# snake_case and registered exactly once (scripts/metrics_lint.py).
echo "== metrics lint =="
python scripts/metrics_lint.py || exit 1

# Opt-in bench smoke (MTPU_BENCH_SMOKE=1): the concurrent-PUT
# aggregate at small budget, failing on >20% regression against the
# committed BENCH_r*.json. Off by default — tier-1 wall time stays
# inside budget and cross-machine numbers are not comparable.
if [ "${MTPU_BENCH_SMOKE:-}" = "1" ]; then
    echo "== bench smoke =="
    bash scripts/bench_smoke.sh || exit 1
fi

# Opt-in crash-consistency sweep (MTPU_CRASH_SWEEP=1): the full
# power-cut crash-point matrix (tests/test_crash_matrix.py, marked
# slow) — every injection point in the PUT/multipart/delete/heal
# commit paths, asserted old-or-new after remount + recovery sweep.
# Off by default: ~200 crash-point runs keep it out of the tier-1
# wall-time budget (a cheap smoke subset stays in tier-1).
if [ "${MTPU_CRASH_SWEEP:-}" = "1" ]; then
    echo "== crash-point matrix =="
    env JAX_PLATFORMS=cpu python -m pytest tests/test_crash_matrix.py \
        -q -p no:cacheprovider || exit 1
fi

# Hot-read-tier kill-switch conformance: the S3 conformance subset
# must be green with the hot cache ON (default) and OFF
# (MTPU_HOT_CACHE=off) — responses are chartered byte-identical either
# way, so any divergence is a hot-path bug, not a config choice. The
# hotcache suite itself (admission, zero-stale chaos, fleet/cluster
# coherence) runs inside tier-1 below; this up-front pass pins the
# kill switch specifically.
echo "== hot-cache kill-switch conformance (on/off) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_s3_conformance.py \
    -q -m 'not slow' -p no:cacheprovider || exit 1
env JAX_PLATFORMS=cpu MTPU_HOT_CACHE=off python -m pytest \
    tests/test_s3_conformance.py \
    -q -m 'not slow' -p no:cacheprovider || exit 1

# Fast cluster subset FIRST: the multi-node-in-one-container harness
# (tests/cluster.py) booting real server processes with real grid
# websockets and dsync quorums — kill/partition/walk_scan/coherence
# invariants. These also run inside tier-1 below (they are not marked
# slow); running them up front fails the distributed plane loudly in
# seconds instead of minutes into the full suite. The 8-node matrix
# and SIGKILL-mid-PUT lock-expiry e2e are @slow (run them with
# `pytest tests/test_cluster.py -m slow`).
echo "== cluster smoke (fast subset) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_cluster.py \
    -q -m 'not slow' -p no:cacheprovider || exit 1

# Fleet-trace smoke: 3-node harness, one ARMED distributed GET must
# yield a single stitched span tree containing remote disk.* spans
# under wire spans (cross-node trace propagation), plus a federated
# scrape reporting every node and the SLO burn-rate gauges.
echo "== fleet trace smoke =="
env JAX_PLATFORMS=cpu python scripts/fleet_trace_smoke.py || exit 1

echo "== tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
