#!/usr/bin/env bash
# Bench smoke gate (opt-in; see scripts/verify.sh): run the
# concurrent-PUT and concurrent-GET aggregates at a small budget
# (object-layer columns only) and fail when either measured host
# aggregate regresses more than 20% against the newest committed
# BENCH_r*.json. GET gating engages only when the committed artifact
# records the GET metric (older artifacts predate it).
# Meant to run on the host that produced the committed artifact —
# cross-machine comparisons measure the machines, not the code.
set -euo pipefail
cd "$(dirname "$0")/.."

latest=$(ls BENCH_r*.json 2>/dev/null | sort | tail -1 || true)
if [ -z "$latest" ]; then
    echo "bench_smoke: no committed BENCH_r*.json; nothing to compare"
    exit 0
fi

echo "== bench smoke (baseline: $latest) =="
out=$(JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
      MTPU_BENCH_ONLY=put_latency,put_concurrent,get_latency,get_concurrent,meta_listing,small_put,transform_put,distributed,cluster_get,connections,rebalance,hot_get,replication,trace_overhead \
      MTPU_BENCH_SMALL=1 \
      python bench.py)
echo "$out"

SMOKE_OUT="$out" BASELINE_FILE="$latest" python - <<'EOF'
import json
import os
import sys

# (metric, column, direction) triples gated at 20% regression.
# Throughput columns are the object-layer host-path numbers:
# comparable across runs on one host, unlike the served column
# (front-end boot noise) or the headline (which may switch sources).
# The p50 gate ("lower" direction) watches the PutObject latency the
# cross-request batcher is chartered to keep down (ROADMAP <= 8 ms on
# TPU hosts): measured p50 must stay within 20% of the committed
# small-budget reference ceiling.
# The served_ratio gates ("higher" direction) watch the front-end tax:
# served_gibps / object-layer like-for-like, computed inside ONE bench
# run (both sides share that run's scheduler weather, so the ratio is
# far more stable than either column). A regression here means the
# serve hot loop (native framer, keep-alive path, zero-copy writes)
# got slower relative to the object layer it fronts.
# The meta_listing gates ("lower") watch the metadata plane: cold-walk
# first-page LIST p50, and the HEAD cold (drive fan-out) p50 — the
# repeat/hot p50 is a few microseconds of dict hit and would gate on
# rounding noise. On hosts where the fixture cannot build (no /dev/shm
# capacity) the bench emits the metrics with value null and the gates
# skip cleanly.
# The hot-GET p50 gate ("lower") watches the read hot path the decode
# batcher PR must not regress: get_latency's headline is the repeat-GET
# p50 (fileinfo cache + verify kernel — native host or batched device
# per calibration). The bench emits an explicit null on hosts where the
# fixture cannot build, and the gate skips cleanly there.
# The small_put gate ("higher") watches the KV-scale write plane: the
# group-commit lanes' aggregate small-object ops/s through the object
# layer. The bench always measures it on local drives; the served
# column (nullable on 1-core hosts) is informational, not gated.
# The transform_put gates ("higher") watch the fused single-pass data
# plane: the SSE and compressed PUT aggregates relative to the
# plaintext aggregate measured in the SAME run (vs_plain — both sides
# share the run's scheduler weather, so the ratio is the stable
# signal; ROADMAP item 3 charters ~>= 0.9, i.e. within ~1.1x of
# plaintext). Skips via explicit null where the native transform
# kernel is unavailable.
# The connections gates watch the event-loop connection plane
# (ROADMAP item 6): idle keep-alive RSS per connection ("lower" — the
# parked-fd memory model must not regress back toward thread stacks)
# and the served GET aggregate at the top of the client connection
# ramp ("higher" — fan-in must not degrade the aggregate). Both emit
# explicit nulls on fd-limited hosts (RLIMIT_NOFILE below the
# connection target) and the gates skip cleanly there.
# The hot_get gates watch the hot read tier (ROADMAP item 4):
# hot_get_gibps ("higher") is the served GET aggregate of the
# frequency-admitted RAM cache at the top of a zipfian connection
# ramp, and vs_erasure ("higher") divides it by the MTPU_HOT_CACHE=off
# column measured back-to-back in the SAME bench run (the kill-switch
# fleet pays the full erasure fan-out per GET, so the ratio is the
# hit-path win and shares the run's scheduler weather). Both emit
# explicit nulls on fd-limited hosts and the gates skip cleanly there.
# The rebalance gates watch the elastic fleet plane (ROADMAP item 3):
# vs_quiescent ("lower") is the foreground PUT p50 during an online
# drain divided by the quiescent p50 measured in the SAME run — the
# background admission class must keep yielding to foreground SLOs, so
# the drain tax ratio is the stable cross-run signal, not either raw
# latency column. rebalance_identity ("higher") is the fraction of
# objects that survive the drain byte-identical with a unique listing
# entry (1.0 = no object lost, torn, or doubly visible). Both emit
# explicit nulls on hosts where the fixture cannot build and the gates
# skip cleanly there.
# The distributed listing gate ("lower") watches the cluster listing
# page: every measured page pays a real cross-node walk over the
# remote walk_scan trimmed-summary stream through REAL spawned server
# processes. A regression means the grid stream, the summary path, or
# the per-set fan-out got slower. Hosts that cannot boot the cluster
# emit an explicit null and the gate skips.
# The native-plane gates watch the cluster data plane (ROADMAP item
# 2) through in-run ratios — both columns of each ratio share ONE
# bench run's scheduler weather, so they are stable on a loaded box
# where the raw cluster aggregates measure the machine:
#   distributed_get vs_old_plane ("higher"): multi-node GET aggregate
#   divided by the same probe against a cluster booted under
#   MTPU_GRID_NATIVE=off. A regression means the raw-frame/sendfile
#   read path lost its edge over per-frame msgpack bulk bytes.
#   cluster_get value + vs_old_plane ("higher"): the isolated
#   inter-node shard fetch (RemoteStorage.read_file through a real
#   GridServer — drive fd → socket via os.sendfile into pooled
#   leases) and its ratio over the MTPU_GRID_NATIVE=off column
#   measured back-to-back in the same run. The bench fails outright
#   if the native column's bytes did not ride sendfile, so a green
#   gate is also a zero-copy-proof.
# Both emit explicit nulls where the fixture cannot boot and the
# gates skip cleanly.
# The replication gates watch the durable replication plane (ROADMAP
# item 5): replication_lag_p99_ms ("lower") is the enqueue-to-delivered
# p99 from the engine's own lag histogram under foreground PUT load
# through a real source->target server pair — WAL append + fsync sit on
# the ack path, so a regression here means the durability tax grew (the
# line carries an in-run MTPU_REPLICATION_DURABLE=off column for
# context). replication_convergence ("higher", healthy value 1.0, never
# 0 — column() treats 0.0 as unmeasured) is the fraction of the final
# namespace byte-identical on both sides after a target kill/restart
# mid-stream plus a post-heal delete, with divergent extra objects
# capping the score below 1. Both emit explicit nulls where the pair
# cannot boot and the gates skip cleanly.
GATES = [
    ("put_concurrent_aggregate_gibps", "host_gibps", "higher"),
    ("put_concurrent_aggregate_gibps", "served_ratio", "higher"),
    ("get_concurrent_aggregate_gibps", "object_layer_gibps", "higher"),
    ("get_concurrent_aggregate_gibps", "served_ratio", "higher"),
    ("put_object_p50_ec4_1mib_ms", "value", "lower"),
    ("get_object_p50_ec4_1mib_ms", "value", "lower"),
    ("meta_listing_list_cold_p50_ms", "value", "lower"),
    ("meta_listing_head_p50_ms", "cold_p50_ms", "lower"),
    ("small_put_ops_s", "value", "higher"),
    ("transform_put_sse_gibps", "vs_plain", "higher"),
    ("transform_put_comp_gibps", "vs_plain", "higher"),
    ("distributed_list_page_p50_ms", "value", "lower"),
    ("distributed_get_aggregate_gibps", "vs_old_plane", "higher"),
    ("cluster_get_shard_fetch_gibps", "value", "higher"),
    ("cluster_get_shard_fetch_gibps", "vs_old_plane", "higher"),
    ("connections_idle_rss_per_conn_kib", "value", "lower"),
    ("connections_get_ramp_gibps", "value", "higher"),
    ("hot_get_gibps", "value", "higher"),
    ("hot_get_gibps", "vs_erasure", "higher"),
    ("rebalance_fg_p50_during_ms", "vs_quiescent", "lower"),
    ("rebalance_identity", "value", "higher"),
    ("replication_lag_p99_ms", "value", "lower"),
    ("replication_convergence", "value", "higher"),
    # trace_overhead vs_baseline is min(armed/disarmed) across the
    # put/get throughput columns and the grid unary-latency column
    # (inverted): 1.0 = free, lower = more armed tax. "higher" fails
    # the smoke if the DISARMED-relative cost of watching regresses —
    # including the cross-node propagation path on the grid wire.
    ("tracing_overhead_armed_vs_disarmed_pct", "vs_baseline", "higher"),
]


def metric_lines(obj):
    """Every embedded metric dict in a BENCH artifact: the `parsed`
    field plus any JSON line inside `tail`."""
    out = []
    if isinstance(obj, dict):
        if obj.get("metric"):
            out.append(obj)
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and parsed.get("metric"):
            out.append(parsed)
        for line in str(obj.get("tail", "")).splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    j = json.loads(line)
                except ValueError:
                    continue
                if j.get("metric"):
                    out.append(j)
    return out


def column(lines, metric, col, direction="higher"):
    """The conservative bound of the column across matching reference
    lines: the floor (min) for higher-is-better metrics, the ceiling
    (max) for lower-is-better ones (latency) — several committed runs
    gate against their most forgiving member."""
    vals = [float(j[col]) for j in lines
            if j.get("metric") == metric and j.get(col)]
    if not vals:
        return None
    return min(vals) if direction == "higher" else max(vals)


with open(os.environ["BASELINE_FILE"]) as f:
    artifact = json.load(f)
# Like-for-like: an artifact carrying small-budget `smoke` reference
# runs is compared against THOSE; the full-budget headline columns
# (more reps, best-of passes) would set an unfairly high floor for the
# gate's own small-budget measurement.
baseline_lines = metric_lines(artifact.get("smoke")) \
    or metric_lines(artifact)
measured_lines = []
for line in os.environ["SMOKE_OUT"].splitlines():
    line = line.strip()
    if line.startswith("{"):
        measured_lines.append(json.loads(line))

failed = False
gated = 0
for metric, col, direction in GATES:
    base = column(baseline_lines, metric, col, direction)
    if base is None:
        print(f"bench_smoke: baseline has no {metric}.{col}; skip")
        continue
    got = column(measured_lines, metric, col, direction)
    if not got:
        # A metric line carrying an explicit null means the probe
        # legitimately did not run on this host (e.g. served columns
        # need cpu_count >= 2 to boot the fleet) — skip the gate.
        # A missing line/column is still a hard failure.
        if any(j.get("metric") == metric and col in j
               and j.get(col) is None for j in measured_lines):
            print(f"bench_smoke: {metric}.{col} not measured on this "
                  f"host (probe skipped); gate skipped")
            continue
        print(f"bench_smoke: FAILED to measure {metric}.{col}")
        failed = True
        continue
    if direction == "higher":
        bound = base * 0.8
        ok = got >= bound
        print(f"bench_smoke: {metric} {got:.3f} vs committed "
              f"{base:.3f} (floor {bound:.3f}) -> "
              f"{'OK' if ok else 'REGRESSION'}")
    else:
        bound = base * 1.2
        ok = got <= bound
        print(f"bench_smoke: {metric} {got:.3f} vs committed "
              f"{base:.3f} (ceiling {bound:.3f}) -> "
              f"{'OK' if ok else 'REGRESSION'}")
    gated += 1
    failed = failed or not ok
if gated == 0 and not failed:
    print("bench_smoke: baseline artifact has no gated metrics; skip")
sys.exit(1 if failed else 0)
EOF
