#!/usr/bin/env bash
# Bench smoke gate (opt-in; see scripts/verify.sh): run ONLY the
# concurrent-PUT aggregate at a small budget (8 clients x 2 puts,
# object-layer columns only) and fail when the measured host aggregate
# regresses more than 20% against the newest committed BENCH_r*.json.
# Meant to run on the host that produced the committed artifact —
# cross-machine comparisons measure the machines, not the code.
set -euo pipefail
cd "$(dirname "$0")/.."

latest=$(ls BENCH_r*.json 2>/dev/null | sort | tail -1 || true)
if [ -z "$latest" ]; then
    echo "bench_smoke: no committed BENCH_r*.json; nothing to compare"
    exit 0
fi

echo "== bench smoke (baseline: $latest) =="
out=$(JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
      MTPU_BENCH_ONLY=put_concurrent MTPU_BENCH_SMALL=1 \
      python bench.py)
echo "$out"

SMOKE_OUT="$out" BASELINE_FILE="$latest" python - <<'EOF'
import json
import os
import sys

def host_gibps_from(obj):
    """host_gibps of the put_concurrent metric inside a BENCH artifact
    (its `parsed` field when that is the aggregate metric, else any
    metric line embedded in `tail`)."""
    cands = []
    if isinstance(obj, dict):
        if obj.get("metric") == "put_concurrent_aggregate_gibps":
            cands.append(obj)
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and \
                parsed.get("metric") == "put_concurrent_aggregate_gibps":
            cands.append(parsed)
        for line in str(obj.get("tail", "")).splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    j = json.loads(line)
                except ValueError:
                    continue
                if j.get("metric") == "put_concurrent_aggregate_gibps":
                    cands.append(j)
    for c in cands:
        v = c.get("host_gibps")
        if v:
            return float(v)
    return None

with open(os.environ["BASELINE_FILE"]) as f:
    baseline = host_gibps_from(json.load(f))
measured = None
for line in os.environ["SMOKE_OUT"].splitlines():
    line = line.strip()
    if line.startswith("{"):
        j = json.loads(line)
        if j.get("metric") == "put_concurrent_aggregate_gibps":
            measured = float(j.get("host_gibps") or 0)
if baseline is None:
    print("bench_smoke: baseline artifact has no host aggregate; skip")
    sys.exit(0)
if not measured:
    print("bench_smoke: FAILED to measure the aggregate")
    sys.exit(1)
floor = baseline * 0.8
verdict = "OK" if measured >= floor else "REGRESSION"
print(f"bench_smoke: host aggregate {measured:.3f} GiB/s vs committed "
      f"{baseline:.3f} GiB/s (floor {floor:.3f}) -> {verdict}")
sys.exit(0 if measured >= floor else 1)
EOF
