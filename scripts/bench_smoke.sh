#!/usr/bin/env bash
# Bench smoke gate (opt-in; see scripts/verify.sh): run the
# concurrent-PUT and concurrent-GET aggregates at a small budget
# (object-layer columns only) and fail when either measured host
# aggregate regresses more than 20% against the newest committed
# BENCH_r*.json. GET gating engages only when the committed artifact
# records the GET metric (older artifacts predate it).
# Meant to run on the host that produced the committed artifact —
# cross-machine comparisons measure the machines, not the code.
set -euo pipefail
cd "$(dirname "$0")/.."

latest=$(ls BENCH_r*.json 2>/dev/null | sort | tail -1 || true)
if [ -z "$latest" ]; then
    echo "bench_smoke: no committed BENCH_r*.json; nothing to compare"
    exit 0
fi

echo "== bench smoke (baseline: $latest) =="
out=$(JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
      MTPU_BENCH_ONLY=put_concurrent,get_concurrent MTPU_BENCH_SMALL=1 \
      python bench.py)
echo "$out"

SMOKE_OUT="$out" BASELINE_FILE="$latest" python - <<'EOF'
import json
import os
import sys

# (metric, column) pairs gated at 20% regression. The column is the
# object-layer host-path number: comparable across runs on one host,
# unlike the served column (front-end boot noise) or the headline
# (which may switch sources).
GATES = [
    ("put_concurrent_aggregate_gibps", "host_gibps"),
    ("get_concurrent_aggregate_gibps", "object_layer_gibps"),
]


def metric_lines(obj):
    """Every embedded metric dict in a BENCH artifact: the `parsed`
    field plus any JSON line inside `tail`."""
    out = []
    if isinstance(obj, dict):
        if obj.get("metric"):
            out.append(obj)
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and parsed.get("metric"):
            out.append(parsed)
        for line in str(obj.get("tail", "")).splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    j = json.loads(line)
                except ValueError:
                    continue
                if j.get("metric"):
                    out.append(j)
    return out


def column(lines, metric, col):
    """Min of the column across matching lines — the conservative
    floor when the artifact records several reference runs."""
    vals = [float(j[col]) for j in lines
            if j.get("metric") == metric and j.get(col)]
    return min(vals) if vals else None


with open(os.environ["BASELINE_FILE"]) as f:
    artifact = json.load(f)
# Like-for-like: an artifact carrying small-budget `smoke` reference
# runs is compared against THOSE; the full-budget headline columns
# (more reps, best-of passes) would set an unfairly high floor for the
# gate's own small-budget measurement.
baseline_lines = metric_lines(artifact.get("smoke")) \
    or metric_lines(artifact)
measured_lines = []
for line in os.environ["SMOKE_OUT"].splitlines():
    line = line.strip()
    if line.startswith("{"):
        measured_lines.append(json.loads(line))

failed = False
gated = 0
for metric, col in GATES:
    base = column(baseline_lines, metric, col)
    if base is None:
        print(f"bench_smoke: baseline has no {metric}.{col}; skip")
        continue
    got = column(measured_lines, metric, col)
    if not got:
        print(f"bench_smoke: FAILED to measure {metric}.{col}")
        failed = True
        continue
    floor = base * 0.8
    verdict = "OK" if got >= floor else "REGRESSION"
    print(f"bench_smoke: {metric} {got:.3f} GiB/s vs committed "
          f"{base:.3f} GiB/s (floor {floor:.3f}) -> {verdict}")
    gated += 1
    failed = failed or got < floor
if gated == 0 and not failed:
    print("bench_smoke: baseline artifact has no gated metrics; skip")
sys.exit(1 if failed else 0)
EOF
