#!/usr/bin/env python3
"""Fleet-trace smoke (wired into scripts/verify.sh).

Boots the 3-node cluster harness, issues ONE armed distributed GET
while subscribed to node 0's admin trace (types=all), and asserts the
cross-node propagation contract end to end on real server processes:

  * the caller's span tree is stitched into ONE trace id containing at
    least one REMOTE `disk.*` span (a span whose `node` label names a
    peer, grafted under a `wire` span);
  * every `wire` span carries the timing split
    (peer_queue_ms / peer_service_ms / transit_ms / serialize_ms);
  * the federated scrape answers for the whole fleet: /metrics on
    node 0 reports minio_tpu_cluster_node_up for all three nodes, and
    the SLO engine exports burn-rate gauges.

Exit 0 on success, 1 with a reason otherwise.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tests.cluster import Cluster  # noqa: E402
from tests.test_fleet_obs import _stream_trace  # noqa: E402


def fail(msg: str) -> int:
    print(f"fleet-trace-smoke: FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="mtpu-fleet-smoke-")
    with Cluster(tmp, nodes=3, drives_per_node=2, parity=2) as cluster:
        cli = cluster.client(0)
        assert cli.request("PUT", "/smoke")[0] == 200
        body = os.urandom(150_000)
        assert cli.request("PUT", "/smoke/o", body=body)[0] == 200

        entries: list = []
        t = threading.Thread(
            target=_stream_trace,
            args=(cluster.address(0),
                  {"types": "all", "count": "120"}, entries),
            daemon=True)
        t.start()
        time.sleep(0.8)                       # subscription armed
        st, _, got = cli.request("GET", "/smoke/o")
        if st != 200 or got != body:
            return fail(f"distributed GET failed: {st}")
        for _ in range(150):
            cli.request("GET", "/minio/health/live", sign=False)
            if not t.is_alive():
                break
            time.sleep(0.05)
        t.join(timeout=30)
        if t.is_alive() or not entries:
            return fail("trace stream never closed / no entries")

        gets = [e for e in entries if e.get("trace_type") == "s3"
                and e.get("api") == "GET:object"]
        if not gets:
            return fail("no s3 GET root entry in trace")
        tid = gets[0]["trace"]
        tree = [e for e in entries if e.get("trace") == tid]
        wires = [e for e in tree if e.get("api") == "wire"]
        if not wires:
            return fail("no wire spans in the GET's tree")
        for w in wires:
            tags = w.get("tags") or {}
            if "fault" in tags:
                continue
            missing = [k for k in ("peer_queue_ms", "peer_service_ms",
                                   "transit_ms", "serialize_ms")
                       if k not in tags]
            if missing:
                return fail(f"wire span missing timing split {missing}")
        wire_ids = {e["span"] for e in wires}
        remote = [e for e in tree
                  if str(e.get("api", "")).startswith("disk.")
                  and e.get("node") != gets[0].get("node")
                  and e.get("parent") in wire_ids]
        if not remote:
            return fail("no remote disk.* span stitched under a wire "
                        "span (cross-node propagation broken)")

        # Federated telemetry: one scrape answers for the fleet.
        st, _, text = cli.request("GET", "/minio/v2/metrics/cluster")
        if st != 200:
            return fail(f"/minio/v2/metrics/cluster -> {st}")
        text = text.decode()
        up = [ln for ln in text.splitlines()
              if ln.startswith("minio_tpu_cluster_node_up{")]
        if len(up) < 3:
            return fail(f"scrape reports {len(up)} nodes, want 3")
        if "minio_tpu_slo_burn_rate{" not in text:
            return fail("no SLO burn-rate gauges in scrape")

        print(f"fleet-trace-smoke: OK — {len(tree)} spans in the GET "
              f"tree, {len(wires)} wire spans, {len(remote)} remote "
              f"disk.* spans, {len(up)} nodes in one scrape")
    return 0


if __name__ == "__main__":
    sys.exit(main())
