#!/usr/bin/env python3
"""Synthetic namespace generator: fabricate N-object buckets directly on
the drives (xl.meta journals written straight to disk, no PUT path) so a
10M-object namespace builds in minutes instead of hours.

The metadata-plane bench (bench.py meta_listing) and the high-cardinality
listing tests need namespaces far past what put_object can build in a
test budget: a PUT pays erasure encode + staging + rename + fsync per
object (~1 ms floor), while a fabricated object is one makedirs + one
unsynced write of a ~400-byte journal. The journals are REAL — built by
the same msgpack layout `storage/meta.py` writes (magic + versions +
inline map, bitrot-framed inline payload with a true HighwayHash
digest), so every fabricated object HEADs, GETs and lists exactly like
a PUT object; only mtimes/etags are synthetic.

Profile (``mixed``) — shaped like production namespaces, with each
shape's pathology represented:

  kv    70%   kv/<aa>/<bb>/o<idx>      two-level 256-way fanout (the
                                       "many medium dirs" shape)
  deep  20%   deep/<a>/<b>/.../o<idx>  6-deep chains (prefix-descend
                                       cost)
  flat   9%   flat/o<idx>              one huge directory (listdir+sort
                                       pathology)
  ver    1%   ver/o<idx>               versioned churn: 5 versions per
                                       object, latest-first journal

Layout decisions ride the object INDEX (deterministic, seeded), so any
worker count produces the identical namespace and tests can predict key
names.

Usage:
  python scripts/namespace_gen.py --root /dev/shm/ns --objects 1000000 \
      [--drives 1] [--bucket ns] [--workers N] [--profile mixed]

As a library: `generate(root, objects, drives=1, ...)` returns a summary
dict (also printed as one JSON line by the CLI).
"""

from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUCKET = "ns"
# Version-id / data-dir style UUIDs, deterministic per index.
_HEX = "0123456789abcdef"


def _uuid_at(i: int, salt: int) -> str:
    h = f"{(i * 0x9e3779b97f4a7c15 + salt) & ((1 << 128) - 1):032x}"
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:32]}"


def key_at(i: int, objects: int, profile: str = "mixed") -> str:
    """Deterministic key for object index i (shared with tests)."""
    if profile == "flat":
        return f"flat/o{i:08d}"
    r = i % 100
    if r < 70:
        j = i
        return f"kv/{_HEX[(j >> 4) & 15]}{_HEX[j & 15]}/" \
               f"{_HEX[(j >> 12) & 15]}{_HEX[(j >> 8) & 15]}/o{i:08d}"
    if r < 90:
        j = i
        parts = [_HEX[(j >> (4 * d + 8)) & 7] for d in range(6)]
        return "deep/" + "/".join(parts) + f"/o{i:08d}"
    if r < 99:
        return f"flat/o{i:08d}"
    return f"ver/o{i:08d}"


def is_versioned(i: int) -> bool:
    return i % 100 == 99


def _build_blobs(drives: int, versions_mixed: bool):
    """Per-drive xl.meta payload templates.

    Returns (single_tmpl, ver_tmpl): callables (i) -> list of per-drive
    blob bytes. The inline payload and its bitrot digest are shared
    across all objects (identical payload => identical digest/etag, the
    dedup-friendly shape bench data takes); per-object fields (vid,
    mod-time, data-less journal entries) are packed fresh — msgpack of a
    ~10-key map is ~3 us, the file write dominates.
    """
    import msgpack
    import numpy as np

    from minio_tpu.erasure.codec import Erasure
    from minio_tpu.object.erasure_object import hash_order
    from minio_tpu.storage.meta import MAGIC
    from minio_tpu.utils.highwayhash import MAGIC_KEY, highwayhash256

    payload = bytes(range(128))                      # 128 B inline body
    k, m = max(1, drives - drives // 2), drives // 2
    e = Erasure(k, m, 1 << 20)
    shards = e.encode_data(payload)                  # k data + m parity rows
    # Bitrot-framed shard per shard INDEX (one erasure block: the whole
    # payload fits in a single frame) — every object shares the payload,
    # so each drive's inline blob is one of these n precomputed frames.
    framed = [highwayhash256(MAGIC_KEY, bytes(s)) + bytes(np.asarray(s))
              for s in shards]
    etag = __import__("hashlib").md5(payload).hexdigest()
    base_ns = 1_700_000_000_000_000_000

    def ec_map(drive: int, dist) -> dict:
        return {"alg": "rs-vandermonde", "k": k, "m": m,
                "bs": 1 << 20, "idx": dist[drive], "dist": list(dist),
                "cks": []}

    def vmap(i: int, vid: str, mt: int, drive: int, dist) -> dict:
        return {
            "kind": 1, "vid": vid, "mt": mt, "ddir": "", "size": len(payload),
            "meta": {"etag": etag, "content-type": "application/octet-stream"},
            "parts": [{"n": 1, "s": len(payload), "as": len(payload),
                       "mt": 0, "etag": etag}],
            "ec": ec_map(drive, dist), "inline": True,
        }

    # 10M objects cannot afford a dict build + packb each (~100 us of
    # allocator churn per object): pre-pack one TEMPLATE blob per
    # (distribution rotation, drive) with sentinel mod-times/version-ids
    # whose msgpack encodings are fixed-width, record their byte
    # offsets, and emit each object as template-copy + struct patch.
    import struct

    SENT_MT = [(1 << 62) + 0x1234500 + v for v in range(5)]   # 0xcf + 8B
    SENT_VID = [f"ffffffff-ffff-4fff-8fff-fffffff1230{v}" for v in range(5)]

    def _mt_off(blob: bytes, v: int) -> int:
        off = blob.find(struct.pack(">BQ", 0xCF, SENT_MT[v]))
        assert off >= 0
        return off + 1

    def _vid_offs(blob: bytes, v: int) -> list[int]:
        # vid appears in the version map AND as the inline-map key.
        pat = SENT_VID[v].encode()
        offs, start = [], 0
        while True:
            off = blob.find(pat, start)
            if off < 0:
                return offs
            offs.append(off)
            start = off + 1

    # Templates are keyed by the EXACT distribution tuple hash_order
    # yields (one of `drives` rotations today) — the per-key lookup
    # calls hash_order itself, so the fabricated ec.dist/idx can never
    # drift from what the object layer computes for that key.
    single_tmpl: dict = {}   # dist -> [drive] -> (blob, mt_off)
    ver_tmpl: dict = {}      # dist -> [drive] -> (blob, mt_offs, vid_offs)
    for s in range(drives):
        # hash_order's contract: a rotation of [1..n]; enumerate every
        # start. _templates() looks rows up by hash_order's ACTUAL
        # output per key, so a changed spread fails loudly here
        # instead of fabricating mismatched layouts.
        dist = tuple(1 + (s + i) % drives for i in range(drives))
        srow, vrow = [], []
        for d in range(drives):
            blob = MAGIC + msgpack.packb(
                {"versions": [vmap(0, "null", SENT_MT[0], d, dist)],
                 "inline": {"null": framed[dist[d] - 1]}},
                use_bin_type=True)
            srow.append((blob, _mt_off(blob, 0)))
            versions = [vmap(0, SENT_VID[v], SENT_MT[v], d, dist)
                        for v in range(5)]
            vblob = MAGIC + msgpack.packb(
                {"versions": versions,
                 "inline": {SENT_VID[v]: framed[dist[d] - 1]
                            for v in range(5)}}, use_bin_type=True)
            vrow.append((vblob, [_mt_off(vblob, v) for v in range(5)],
                         [_vid_offs(vblob, v) for v in range(5)]))
        single_tmpl[dist] = srow
        ver_tmpl[dist] = vrow

    def _templates(kind: dict, key: str) -> list:
        dist = tuple(hash_order(f"{BUCKET}/{key}", drives))
        row = kind.get(dist)
        if row is None:      # hash_order spread changed: rebuild lazily
            raise KeyError(f"no template for distribution {dist}")
        return row

    def single(i: int, key: str) -> list[bytes]:
        row = _templates(single_tmpl, key)
        mt = base_ns + i * 1000
        out = []
        for d in range(drives):
            tmpl, off = row[d]
            b = bytearray(tmpl)
            struct.pack_into(">Q", b, off, mt)
            out.append(b)
        return out

    def ver(i: int, key: str) -> list[bytes]:
        row = _templates(ver_tmpl, key)
        vids = [_uuid_at(i, v).encode() for v in range(5)]
        out = []
        for d in range(drives):
            tmpl, mt_offs, vid_offs = row[d]
            b = bytearray(tmpl)
            for v in range(5):
                struct.pack_into(">Q", b, mt_offs[v],
                                 base_ns + i * 1000 + (4 - v))
                for off in vid_offs[v]:
                    b[off:off + 36] = vids[v]
            out.append(b)
        return out

    return single, ver


def _worker(root: str, drives: int, bucket: str, objects: int,
            profile: str, lo: int, hi: int, progress=None) -> int:
    single, ver = _build_blobs(drives, True)
    roots = [os.path.join(root, f"d{d}", bucket) for d in range(drives)]
    wrote = 0
    flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC
    for i in range(lo, hi):
        key = key_at(i, objects, profile)
        blobs = ver(i, key) if (profile == "mixed" and is_versioned(i)) \
            else single(i, key)
        for d in range(drives):
            # Syscall-lean commit: this loop runs tens of millions of
            # times, so probe nothing — mkdir optimistically, create
            # missing parents only on the miss.
            obj_dir = f"{roots[d]}/{key}"
            try:
                os.mkdir(obj_dir)
            except FileExistsError:
                pass
            except FileNotFoundError:
                os.makedirs(obj_dir, exist_ok=True)
            fd = os.open(f"{obj_dir}/xl.meta", flags, 0o644)
            os.write(fd, blobs[d])
            os.close(fd)
        wrote += 1
        if progress is not None and wrote % 200_000 == 0:
            progress(wrote)
    return wrote


def generate(root: str, objects: int, drives: int = 1, bucket: str = BUCKET,
             workers: int | None = None, profile: str = "mixed") -> dict:
    """Fabricate the namespace; idempotent over an existing root."""
    t0 = time.time()
    workers = workers or min(8, (os.cpu_count() or 1))
    for d in range(drives):
        os.makedirs(os.path.join(root, f"d{d}", ".mtpu.sys", "tmp"),
                    exist_ok=True)
        os.makedirs(os.path.join(root, f"d{d}", bucket), exist_ok=True)
    if workers <= 1 or objects < 50_000:
        _worker(root, drives, bucket, objects, profile, 0, objects)
    else:
        step = (objects + workers - 1) // workers
        procs = []
        for w in range(workers):
            lo, hi = w * step, min(objects, (w + 1) * step)
            if lo >= hi:
                continue
            p = multiprocessing.Process(
                target=_worker,
                args=(root, drives, bucket, objects, profile, lo, hi))
            p.start()
            procs.append(p)
        for p in procs:
            p.join()
            if p.exitcode:
                raise RuntimeError(f"namespace_gen worker rc={p.exitcode}")
    dt = time.time() - t0
    return {"root": root, "bucket": bucket, "objects": objects,
            "drives": drives, "profile": profile,
            "seconds": round(dt, 1),
            "objects_per_sec": round(objects / max(dt, 1e-9))}


def attach(root: str, drives: int = 1):
    """An ErasureSet over a generated root (1 drive => parity 0)."""
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.storage.local import LocalStorage
    return ErasureSet([LocalStorage(os.path.join(root, f"d{d}"))
                       for d in range(drives)])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    ap.add_argument("--objects", type=int, required=True)
    ap.add_argument("--drives", type=int, default=1)
    ap.add_argument("--bucket", default=BUCKET)
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--profile", default="mixed",
                    choices=("mixed", "flat"))
    ap.add_argument("--self-test", action="store_true",
                    help="HEAD+GET+LIST a few fabricated objects through "
                         "the real object layer before reporting")
    args = ap.parse_args()
    summary = generate(args.root, args.objects, drives=args.drives,
                       bucket=args.bucket, workers=args.workers,
                       profile=args.profile)
    if args.self_test:
        es = attach(args.root, args.drives)
        probe = [0, 1, args.objects - 1]
        for i in probe:
            key = key_at(i, args.objects, args.profile)
            info = es.get_object_info(args.bucket, key)
            assert info.size == 128, (key, info.size)
            _, got = es.get_object(args.bucket, key)
            assert len(got) == 128, key
        page = es.list_objects(args.bucket, max_keys=10)
        assert page.objects, "empty first page"
        es.close()
        summary["self_test"] = "ok"
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
