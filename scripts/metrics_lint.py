#!/usr/bin/env python3
"""Metrics-name lint (wired into scripts/verify.sh).

Walks the source tree's ASTs for every registration call — `metric(...)`
and `hist_metric(...)` in s3/metrics.py render paths — and asserts:

  * every exported metric name is a string LITERAL (a computed name
    can silently collide or escape this lint);
  * every name is `minio_tpu_`-prefixed snake_case
    (^minio_tpu_[a-z0-9]+(_[a-z0-9]+)*$);
  * every name is registered exactly once across the tree (double
    registration renders duplicate HELP/TYPE blocks, which Prometheus
    scrapers reject).

Additionally renders one synthetic FLEET exposition (multi-node
node_states, SLO engine attached, errors across several API classes)
and runs a label-cardinality guard over it: no family may expose more
than --cardinality-cap distinct label-sets unless its prefix is on the
allowlist of genuinely per-drive / per-peer / per-node families. A
label explosion (per-object key, per-client address, raw path) lands
here before it lands on a production Prometheus.

Exit 0 clean, 1 with one line per violation.
"""

from __future__ import annotations

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NAME_RE = re.compile(r"^minio_tpu_[a-z0-9]+(_[a-z0-9]+)*$")
REGISTRARS = {"metric", "hist_metric"}

# Label-cardinality guard: one sample line of the text exposition.
SERIES_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s")
CARDINALITY_CAP = 64
# Families whose label-set count legitimately scales with hardware or
# topology (drives, grid peers, cluster nodes, replication targets) —
# bounded by the deployment, not by traffic.
CARDINALITY_ALLOW = (
    "minio_tpu_drive_",
    "minio_tpu_grid_peer_",
    "minio_tpu_cluster_node_",
    "minio_tpu_replication_breaker_",
    "minio_tpu_replication_lane_",
)


def call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _loop_literal_names(tree: ast.AST) -> dict:
    """Names registered via the `for name, ... in ((LITERAL, ...), ...):
    metric(name, ...)` idiom: maps the id of each such Call node to the
    list of (lineno, literal) names its loop iterates."""
    out: dict[int, list] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.For):
            continue
        target = node.target
        if not (isinstance(target, ast.Tuple) and target.elts
                and isinstance(target.elts[0], ast.Name)):
            continue
        var = target.elts[0].id
        names = []
        if isinstance(node.iter, (ast.Tuple, ast.List)):
            for elt in node.iter.elts:
                if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str):
                    names.append((elt.elts[0].lineno, elt.elts[0].value))
                else:
                    names = None
                    break
        else:
            names = None
        if names is None:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and call_name(sub) in REGISTRARS and sub.args \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id == var:
                out[id(sub)] = names
    return out


def lint_file(path: str, seen: dict, problems: list) -> None:
    with open(path, encoding="utf-8") as fh:
        try:
            tree = ast.parse(fh.read(), filename=path)
        except SyntaxError as e:
            problems.append(f"{path}: syntax error: {e}")
            return
    rel = os.path.relpath(path, ROOT)
    loop_names = _loop_literal_names(tree)

    def check(name: str, loc: str) -> None:
        if not NAME_RE.match(name):
            problems.append(
                f"{loc}: metric name {name!r} is not minio_tpu_-prefixed "
                "snake_case")
        if name in seen:
            problems.append(
                f"{loc}: metric {name!r} already registered at "
                f"{seen[name]}")
        else:
            seen[name] = loc

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or call_name(node) not in REGISTRARS or not node.args:
            continue
        first = node.args[0]
        loc = f"{rel}:{node.lineno}"
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            check(first.value, loc)
        elif id(node) in loop_names:
            for lineno, name in loop_names[id(node)]:
                check(name, f"{rel}:{lineno}")
        else:
            problems.append(f"{loc}: metric name is not a string literal")


def check_exposition(text: str, cap: int = CARDINALITY_CAP,
                     allowlist=CARDINALITY_ALLOW,
                     problems: list | None = None) -> list:
    """Count distinct label-sets per metric FAMILY in a rendered text
    exposition; flag any family over `cap` whose name is not prefixed
    by an allowlist entry. Histogram series (_bucket/_sum/_count)
    collapse into their family."""
    if problems is None:
        problems = []
    fams: dict[str, set] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = SERIES_RE.match(line)
        if m is None:
            continue
        name = re.sub(r"_(bucket|sum|count)$", "", m.group(1))
        # `le` is the bucket-boundary pseudo-label — fixed per
        # histogram, not a cardinality dimension.
        labels = re.sub(r'(^|,)le="[^"]*"', "", m.group(2) or "")
        fams.setdefault(name, set()).add(labels.strip(","))
    for fam in sorted(fams):
        n = len(fams[fam])
        if n > cap and not any(fam.startswith(p) for p in allowlist):
            problems.append(
                f"cardinality: family {fam!r} exposes {n} label-sets "
                f"(cap {cap}); allowlist it ONLY if it genuinely "
                "scales with hardware/topology, never with traffic")
    return problems


def _synthetic_fleet_exposition() -> str:
    """Render the fullest exposition the lint can reach without a live
    server: a populated Metrics registry, the SLO engine, and a
    node_states fleet (one peer down) — exercising the request, SLO,
    and per-node family paths the cardinality guard watches."""
    sys.path.insert(0, ROOT)
    from types import SimpleNamespace

    from minio_tpu.s3.metrics import Metrics
    from minio_tpu.utils.slo import SLOEngine

    m = Metrics()
    for api in ("GET:object", "PUT:object", "HEAD:object", "GET:bucket",
                "DELETE:object", "GET:metrics"):
        for status in (200, 404, 500, 503):
            m.record(api, status, 0.012, rx=1024, tx=2048)
    slo = SLOEngine()
    slo.observe("GET:object", 200)
    slo.observe("PUT:object", 503)
    srv = SimpleNamespace(slo=slo)
    nodes = []
    for i in range(4):
        nodes.append({
            "node": f"host{i}:9000",
            "states": [Metrics().state(), m.state()],
            "slow_ops": i,
            "replication": {"lag_ms": {"count": 3, "mean_ms": 1.2,
                                       "p50_ms": 1.0, "p99_ms": 4.5}},
            **({"local": True} if i == 0 else {}),
        })
    nodes.append({"node": "down:9000", "states": [],
                  "unreachable": True})
    return m.render(server=srv, peer_states=[m.state()],
                    node_states=nodes)


def main() -> int:
    seen: dict = {}
    problems: list = []
    count = 0
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(ROOT, "minio_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                lint_file(os.path.join(dirpath, fn), seen, problems)
                count += 1
    try:
        text = _synthetic_fleet_exposition()
    except Exception as e:  # noqa: BLE001 - a broken render IS a finding
        problems.append(f"synthetic fleet render failed: {e!r}")
        text = ""
    families = 0
    if text:
        families = len({re.sub(r"_(bucket|sum|count)$", "",
                               SERIES_RE.match(ln).group(1))
                        for ln in text.splitlines()
                        if ln and not ln.startswith("#")
                        and SERIES_RE.match(ln)})
        check_exposition(text, problems=problems)
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}", file=sys.stderr)
        return 1
    print(f"metrics-lint: {len(seen)} metric names across {count} files, "
          "all minio_tpu_-prefixed snake_case, each registered once; "
          f"{families} families in the synthetic fleet exposition, "
          f"label cardinality within cap {CARDINALITY_CAP}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
