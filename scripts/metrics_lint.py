#!/usr/bin/env python3
"""Metrics-name lint (wired into scripts/verify.sh).

Walks the source tree's ASTs for every registration call — `metric(...)`
and `hist_metric(...)` in s3/metrics.py render paths — and asserts:

  * every exported metric name is a string LITERAL (a computed name
    can silently collide or escape this lint);
  * every name is `minio_tpu_`-prefixed snake_case
    (^minio_tpu_[a-z0-9]+(_[a-z0-9]+)*$);
  * every name is registered exactly once across the tree (double
    registration renders duplicate HELP/TYPE blocks, which Prometheus
    scrapers reject).

Exit 0 clean, 1 with one line per violation.
"""

from __future__ import annotations

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NAME_RE = re.compile(r"^minio_tpu_[a-z0-9]+(_[a-z0-9]+)*$")
REGISTRARS = {"metric", "hist_metric"}


def call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _loop_literal_names(tree: ast.AST) -> dict:
    """Names registered via the `for name, ... in ((LITERAL, ...), ...):
    metric(name, ...)` idiom: maps the id of each such Call node to the
    list of (lineno, literal) names its loop iterates."""
    out: dict[int, list] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.For):
            continue
        target = node.target
        if not (isinstance(target, ast.Tuple) and target.elts
                and isinstance(target.elts[0], ast.Name)):
            continue
        var = target.elts[0].id
        names = []
        if isinstance(node.iter, (ast.Tuple, ast.List)):
            for elt in node.iter.elts:
                if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str):
                    names.append((elt.elts[0].lineno, elt.elts[0].value))
                else:
                    names = None
                    break
        else:
            names = None
        if names is None:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and call_name(sub) in REGISTRARS and sub.args \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id == var:
                out[id(sub)] = names
    return out


def lint_file(path: str, seen: dict, problems: list) -> None:
    with open(path, encoding="utf-8") as fh:
        try:
            tree = ast.parse(fh.read(), filename=path)
        except SyntaxError as e:
            problems.append(f"{path}: syntax error: {e}")
            return
    rel = os.path.relpath(path, ROOT)
    loop_names = _loop_literal_names(tree)

    def check(name: str, loc: str) -> None:
        if not NAME_RE.match(name):
            problems.append(
                f"{loc}: metric name {name!r} is not minio_tpu_-prefixed "
                "snake_case")
        if name in seen:
            problems.append(
                f"{loc}: metric {name!r} already registered at "
                f"{seen[name]}")
        else:
            seen[name] = loc

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or call_name(node) not in REGISTRARS or not node.args:
            continue
        first = node.args[0]
        loc = f"{rel}:{node.lineno}"
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            check(first.value, loc)
        elif id(node) in loop_names:
            for lineno, name in loop_names[id(node)]:
                check(name, f"{rel}:{lineno}")
        else:
            problems.append(f"{loc}: metric name is not a string literal")


def main() -> int:
    seen: dict = {}
    problems: list = []
    count = 0
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(ROOT, "minio_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                lint_file(os.path.join(dirpath, fn), seen, problems)
                count += 1
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}", file=sys.stderr)
        return 1
    print(f"metrics-lint: {len(seen)} metric names across {count} files, "
          "all minio_tpu_-prefixed snake_case, each registered once")
    return 0


if __name__ == "__main__":
    sys.exit(main())
