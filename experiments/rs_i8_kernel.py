"""Experiment: RS kernel with i8-domain bit unpack via pltpu.bitcast.

Hypothesis: the u32 kernel's 64 shift+and ops/word dominate; extracting
bits in the u8 domain (4x denser vregs, 3 ops/bit-row via and/cmp/select)
plus a block-diagonal bit-matrix cuts the VPU unpack cost ~2.7x and
halves MXU lane-cycles.

Row conventions (from the measured pltpu.bitcast layout):
  u32 [k, T4] -> u8 [4k, T4], row = 4*shard + byte_slot
  bits i8 [32k, T4], row = bit*4k + 4*shard + slot   (concat of 8 planes)
  acc rows = c*4r + 4*jr + slot (plane-major over output u8 rows)
  out u8 [4r, T4] -> bitcast -> u32 [r, T4]
"""

from __future__ import annotations

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from minio_tpu.ops import gf256
from minio_tpu.ops.rs_device import _repack_weights


@functools.lru_cache(maxsize=64)
def _bm8_cached(key: bytes, r: int, k: int) -> np.ndarray:
    """Block-diagonal bit matrix [32r, 32k] int8 for the i8-row layout."""
    matrix = np.frombuffer(key, dtype=np.uint8).reshape(r, k)
    bm = gf256.bit_matrix(matrix)          # [r8, k8]: row jr*8+c, col i*8+b
    out = np.zeros((32 * r, 32 * k), dtype=np.int8)
    for c in range(8):
        for jr in range(r):
            for j in range(4):
                a = c * 4 * r + 4 * jr + j
                for b in range(8):
                    for i in range(k):
                        col = b * 4 * k + 4 * i + j
                        out[a, col] = bm[jr * 8 + c, i * 8 + b]
    return out


def _rs_kernel8(bmat_ref, wrep_ref, data_ref, out_ref):
    k = data_ref.shape[1]
    r = out_ref.shape[1]
    for i in range(data_ref.shape[0]):
        x = data_ref[i]                          # u32 [k, T4]
        xb = pltpu.bitcast(x, jnp.uint8)         # u8 [4k, T4]
        planes = [jnp.where((xb & jnp.uint8(1 << b)) != 0,
                            jnp.int8(1), jnp.int8(0)) for b in range(8)]
        bits = jnp.concatenate(planes, axis=0)   # i8 [32k, T4]
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)    # [32r, T4]
        accb = (acc & 1).astype(jnp.int8)
        packed = jax.lax.dot_general(
            wrep_ref[:], accb,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)    # [4r, T4] byte values
        ob = (packed & 0xFF).astype(jnp.uint8)   # u8 [4r, T4]
        out_ref[i] = pltpu.bitcast(ob, jnp.uint32)


@functools.partial(jax.jit, static_argnames=("tile4", "bb"))
def rs_apply8(bmat, wrep, data, tile4: int, bb: int):
    b, k, l4 = data.shape
    r4 = wrep.shape[0]
    r = r4 // 4
    grid = (b // bb, l4 // tile4)
    return pl.pallas_call(
        _rs_kernel8,
        grid=grid,
        in_specs=[
            pl.BlockSpec(bmat.shape, lambda ib, il: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(wrep.shape, lambda ib, il: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, k, tile4), lambda ib, il: (ib, 0, il),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bb, r, tile4), lambda ib, il: (ib, 0, il),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, r, l4), jnp.uint32),
    )(bmat, wrep, data)


def make_encoder8(matrix: np.ndarray, tile4: int = 8192, bb: int = 1):
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    r, k = matrix.shape
    bm8 = jnp.asarray(_bm8_cached(matrix.tobytes(), r, k))
    wrep = jnp.asarray(_repack_weights(4 * r))   # [4r, 32r]
    def run(data):
        return rs_apply8(bm8, wrep, data, tile4=tile4, bb=bb)
    return run


if __name__ == "__main__":
    import time

    from minio_tpu.ops.rs_device import make_encoder32

    K, M, BLOCK, BATCH = 8, 4, 1 << 20, 256
    shard_len = BLOCK // K
    l4 = shard_len // 4
    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 2 ** 31, size=(BATCH, K, l4), dtype=np.uint32)
    data = jnp.asarray(data_np)
    pm = gf256.parity_matrix(K, M)

    # correctness vs the current u32 kernel
    enc32 = make_encoder32(pm)
    want = np.asarray(enc32(data[:4]))
    for tile4, bb in [(8192, 1)]:
        enc8 = make_encoder8(pm, tile4=tile4, bb=bb)
        got = np.asarray(enc8(data[:4]))
        assert np.array_equal(want, got), f"mismatch tile4={tile4}"
    print("correctness OK")

    def chain_time(step, x0, iters=12):
        def chained(n):
            @jax.jit
            def f(x):
                return jax.lax.fori_loop(0, n, lambda _, x: step(x), x)[0, 0, 0]
            return f
        f1, fn = chained(1), chained(1 + iters)
        int(f1(x0)); int(fn(x0))
        def med(f):
            ts = []
            for _ in range(5):
                t0 = time.perf_counter(); int(f(x0))
                ts.append(time.perf_counter() - t0)
            ts.sort(); return ts[2]
        return max((med(fn) - med(f1)) / iters, 1e-9)

    nbytes = BATCH * K * shard_len
    def step32(x):
        p = enc32(x)
        return x.at[0, 0, 0].set(p[0, 0, 0])
    t = chain_time(step32, data)
    print(f"u32 kernel: {t*1e3:.3f} ms  {nbytes/t/2**30:.1f} GiB/s")

    for tile4 in (4096, 8192, 16384):
        for bb in (1, 2):
            try:
                enc8 = make_encoder8(pm, tile4=tile4, bb=bb)
                def step8(x, e=enc8):
                    p = e(x)
                    return x.at[0, 0, 0].set(p[0, 0, 0])
                t = chain_time(step8, data)
                print(f"i8 kernel tile4={tile4} bb={bb}: {t*1e3:.3f} ms  "
                      f"{nbytes/t/2**30:.1f} GiB/s")
            except Exception as e:
                print(f"i8 tile4={tile4} bb={bb}: FAIL {str(e)[:100]}")
